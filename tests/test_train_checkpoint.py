"""TrainCheckpointer: joint train-state + loader-position resume."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from petastorm_tpu.jax import TrainCheckpointer, make_jax_loader


def _state(seed=0):
    rng = np.random.RandomState(seed)
    return {
        'params': {'w': jnp.asarray(rng.randn(4, 4).astype(np.float32)),
                   'b': jnp.asarray(rng.randn(4).astype(np.float32))},
        'step_count': jnp.asarray(7, jnp.int32),
    }


def test_fresh_run_returns_template_and_step_zero(tmp_path, scalar_dataset):
    with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
        assert ckpt.latest_step is None
        template = _state()
        restored = ckpt.restore_state(template)
        assert restored is template
        with make_jax_loader(scalar_dataset.url, batch_size=16,
                             fields=['^id$']) as loader:
            assert ckpt.restore_loader(loader) == 0


def test_train_state_round_trips(tmp_path):
    want = _state(seed=3)
    with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
        ckpt.save(5, want)
        assert ckpt.latest_step == 5
    with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
        got = ckpt.restore_state(jax.tree_util.tree_map(jnp.zeros_like, want))
    for name in ('w', 'b'):
        np.testing.assert_array_equal(np.asarray(got['params'][name]),
                                      np.asarray(want['params'][name]))
    assert int(got['step_count']) == 7


def test_loader_resume_covers_remaining_rows(tmp_path, scalar_dataset):
    # consume part of an epoch, checkpoint, resume in a NEW loader: the
    # union of rows seen must cover the dataset (at-least-once semantics)
    seen_before = []
    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         num_epochs=1, shuffle_row_groups=True,
                         seed=11, last_batch='short') as loader:
        it = iter(loader)
        for _ in range(3):
            seen_before.extend(np.asarray(next(it)['id']).tolist())
        with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
            ckpt.save(3, _state(), loader)

    seen_after = []
    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         num_epochs=1, shuffle_row_groups=True,
                         seed=11, last_batch='short') as loader:
        with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
            assert ckpt.restore_loader(loader) == 3
        for batch in loader:
            seen_after.extend(np.asarray(batch['id']).tolist())

    assert set(seen_before) | set(seen_after) == set(range(100))
    # the resumed pass must NOT re-read everything: fully-consumed
    # row-groups are skipped
    assert len(seen_after) < 100


def test_loader_resume_with_shuffle_buffer(tmp_path, scalar_dataset):
    # the shuffling buffer holds rows long after the reader pulled their
    # row-group — the exact case the delivery-accurate provenance exists
    # for: rows still buffered at checkpoint time must be re-read
    seen_before = []
    with make_jax_loader(scalar_dataset.url, batch_size=8, fields=['^id$'],
                         num_epochs=1, shuffle_rows=True,
                         shuffling_queue_capacity=48, seed=5,
                         last_batch='short') as loader:
        it = iter(loader)
        for _ in range(4):
            seen_before.extend(np.asarray(next(it)['id']).tolist())
        with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
            ckpt.save(4, _state(), loader)

    seen_after = []
    with make_jax_loader(scalar_dataset.url, batch_size=8, fields=['^id$'],
                         num_epochs=1, shuffle_rows=True,
                         shuffling_queue_capacity=48, seed=5,
                         last_batch='short') as loader:
        with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
            ckpt.restore_loader(loader)
        for batch in loader:
            seen_after.extend(np.asarray(batch['id']).tolist())

    assert set(seen_before) | set(seen_after) == set(range(100))


def test_model_only_checkpoint_leaves_loader_fresh(tmp_path, scalar_dataset):
    with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
        ckpt.save(2, _state())  # no loader
    with make_jax_loader(scalar_dataset.url, batch_size=20, fields=['^id$'],
                         num_epochs=1, last_batch='short') as loader:
        with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
            assert ckpt.restore_loader(loader) == 2  # step, but fresh data
        rows = sum(len(np.asarray(b['id'])) for b in loader)
    assert rows == 100


def test_model_only_fallback_survives_any_orbax_exception_type(
        tmp_path, scalar_dataset):
    """ADVICE r2 #3 / VERDICT r3 #6: orbax does not contract the exception
    type for a missing composite item — a version that raises ValueError
    (with an inventory probe that is ALSO unsupported) must still hit the
    documented "data position starts fresh" fallback, not crash."""
    with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
        ckpt.save(2, _state())  # no loader state in the checkpoint
    with make_jax_loader(scalar_dataset.url, batch_size=20, fields=['^id$'],
                         num_epochs=1, last_batch='short') as loader:
        with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
            manager = ckpt._manager

            class _FutureOrbaxManager:
                """latest_step/close pass through; the probe is unsupported
                and a loader-state restore raises ValueError."""

                def latest_step(self):
                    return manager.latest_step()

                def item_metadata(self, step):
                    raise NotImplementedError('no item inventory')

                def restore(self, step, args=None):
                    raise ValueError(
                        'Item loader_state was not found in the checkpoint')

                def close(self):
                    manager.close()

            ckpt._manager = _FutureOrbaxManager()
            assert ckpt.restore_loader(loader) == 2  # fresh data, no crash
        rows = sum(len(np.asarray(b['id'])) for b in loader)
    assert rows == 100


def test_confirmed_present_loader_state_restore_failure_raises(
        tmp_path, scalar_dataset):
    """When the checkpoint inventory POSITIVELY lists loader state, a
    failing restore is corruption — it must surface, not be silently
    swallowed into a fresh data position."""
    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         num_epochs=1, last_batch='short') as loader:
        with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
            next(iter(loader))
            ckpt.save(1, _state(), loader)

    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         num_epochs=1, last_batch='short') as loader:
        with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
            manager = ckpt._manager

            class _CorruptRestoreManager:
                def latest_step(self):
                    return manager.latest_step()

                def item_metadata(self, step):
                    return manager.item_metadata(step)  # lists loader_state

                def restore(self, step, args=None):
                    raise ValueError('corrupt loader_state payload')

                def close(self):
                    manager.close()

            ckpt._manager = _CorruptRestoreManager()
            with pytest.raises(ValueError, match='corrupt'):
                ckpt.restore_loader(loader)


def test_resume_math_treats_absent_epoch_as_incomplete(scalar_dataset):
    # delivery-order records can contain epoch 1 while epoch 0 still has
    # undelivered row-groups (shuffle buffer pipelining across the epoch
    # boundary); resume must restart at the ABSENT epoch 0, not skip to 1
    from petastorm_tpu import make_batch_reader
    with make_batch_reader(scalar_dataset.url, num_epochs=3) as reader:
        all_items = set(range(reader._num_items))
        state = reader.resume_state_from({1: set(all_items)})
    assert state['epoch'] == 0
    assert state['consumed_items'] == []
    assert state['iterations_remaining'] == 3


def test_checkpoint_after_restore_does_not_rewind(scalar_dataset):
    # regression (r2 review): run into epoch 1, checkpoint, restore in a
    # fresh loader, consume a little, checkpoint AGAIN — the second
    # checkpoint must continue from the restored position, not rewind to
    # epoch 0 (restored loaders have no delivery record for the epochs
    # they skipped; the record is seeded from the restored state instead)
    def fresh_loader():
        return make_jax_loader(scalar_dataset.url, batch_size=10,
                               fields=['^id$'], num_epochs=3,
                               last_batch='short')

    with fresh_loader() as loader:
        it = iter(loader)
        for _ in range(13):  # 100 rows/epoch: 130 rows = into epoch 1
            next(it)
        state1 = loader.state_dict()
    assert state1['epoch'] == 1
    assert state1['iterations_remaining'] == 2

    with fresh_loader() as loader:
        loader.load_state_dict(state1)
        # checkpoint immediately after restore: identical position
        state_same = loader.state_dict()
        assert state_same['epoch'] == 1
        assert sorted(state_same['consumed_items']) == \
            sorted(state1['consumed_items'])
        it = iter(loader)
        rows = 0
        while rows < 60:  # finish epoch 1's remainder, start epoch 2
            rows += len(np.asarray(next(it)['id']))
        state2 = loader.state_dict()
    assert state2['epoch'] >= 1
    # progress is monotone: same-or-later epoch, and within the same epoch
    # at least as many row-groups consumed
    assert (state2['epoch'], len(state2['consumed_items'])) >= \
        (state1['epoch'], len(state1['consumed_items']))
    assert state2['iterations_remaining'] <= 2


def test_max_to_keep_prunes(tmp_path):
    with TrainCheckpointer(str(tmp_path / 'ckpt'), max_to_keep=2) as ckpt:
        for step in (1, 2, 3):
            ckpt.save(step, _state())
        assert ckpt.latest_step == 3
        steps = set(ckpt._manager.all_steps())
    assert steps == {2, 3}


def test_restore_specific_step(tmp_path):
    with TrainCheckpointer(str(tmp_path / 'ckpt'), max_to_keep=5) as ckpt:
        a, b = _state(seed=1), _state(seed=2)
        ckpt.save(1, a)
        ckpt.save(2, b)
        template = jax.tree_util.tree_map(jnp.zeros_like, a)
        got = ckpt.restore_state(template, step=1)
    np.testing.assert_array_equal(np.asarray(got['params']['w']),
                                  np.asarray(a['params']['w']))
