"""Spark surface tests: CLI flag plumbing (always), import gating (when
pyspark is absent), and real pyspark integration (``importorskip``-gated,
mirroring the reference's ``tests/test_spark_dataset_converter.py``).

This environment ships no pyspark, so the integration class skips here; the
gating class asserts the pyspark-requiring entry points fail loudly with
actionable guidance instead of deep inside a Spark call. The same code
paths these skipped tests cover DO execute in this environment against the
fake pyspark engine — see ``tests/test_fake_spark_execution.py``.
"""

import argparse

import numpy as np
import pytest

from petastorm_tpu.tools.spark_session_cli import (
    add_configure_spark_arguments, configure_spark, parse_session_config,
)

try:
    import pyspark  # noqa: F401
    HAS_PYSPARK = True
except ImportError:
    HAS_PYSPARK = False


class _StubBuilder:
    """Duck-typed SparkSession.Builder recording applied settings."""

    def __init__(self):
        self.configs = {}
        self.master_url = None

    def config(self, key, value):
        self.configs[key] = value
        return self

    def master(self, url):
        self.master_url = url
        return self


class TestSparkSessionCli:
    def _parse(self, argv):
        parser = argparse.ArgumentParser()
        add_configure_spark_arguments(parser)
        return parser.parse_args(argv)

    def test_flags_applied_to_builder(self):
        args = self._parse(['--master', 'local[2]',
                            '--spark-session-config',
                            'spark.executor.cores=2',
                            'spark.executor.memory=10g'])
        builder = configure_spark(_StubBuilder(), args)
        assert builder.master_url == 'local[2]'
        assert builder.configs == {'spark.executor.cores': '2',
                                   'spark.executor.memory': '10g'}

    def test_defaults_are_noop(self):
        builder = configure_spark(_StubBuilder(), self._parse([]))
        assert builder.master_url is None and builder.configs == {}

    def test_missing_arguments_rejected(self):
        with pytest.raises(RuntimeError, match='add_configure_spark_arguments'):
            configure_spark(_StubBuilder(), argparse.Namespace())

    @pytest.mark.parametrize('bad', ['noequals', '=value', 'key='])
    def test_malformed_config_pair_rejected(self, bad):
        with pytest.raises(ValueError, match='key=value'):
            parse_session_config([bad])

    def test_value_may_contain_equals(self):
        assert parse_session_config(['k=a=b']) == {'k': 'a=b'}


@pytest.mark.skipif(HAS_PYSPARK, reason='gating only observable sans pyspark')
class TestPysparkAbsenceGating:
    def test_make_spark_converter_guides_to_dataframe_converter(self):
        from petastorm_tpu.spark import make_spark_converter
        with pytest.raises(ImportError, match='make_dataframe_converter'):
            make_spark_converter(object())

    def test_dataset_as_rdd_requires_pyspark(self):
        from petastorm_tpu.spark_utils import dataset_as_rdd
        with pytest.raises(ImportError, match='pyspark'):
            dataset_as_rdd('file:///tmp/nope', None)


@pytest.mark.skipif(not HAS_PYSPARK, reason='pyspark not installed')
class TestPysparkIntegration:
    """Executes only where pyspark is installed (the reference's CI shape)."""

    @pytest.fixture(scope='class')
    def spark(self):
        from pyspark.sql import SparkSession
        session = (SparkSession.builder.master('local[2]')
                   .appName('petastorm_tpu-tests').getOrCreate())
        yield session
        session.stop()

    def test_materialize_with_spark_write(self, spark, tmp_path):
        from petastorm_tpu import make_batch_reader
        from petastorm_tpu.etl.dataset_metadata import materialize_dataset
        from petastorm_tpu.unischema import Unischema, UnischemaField
        import pyarrow as pa
        from petastorm_tpu.codecs import ScalarCodec

        schema = Unischema('S', [
            UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
        ])
        url = 'file://' + str(tmp_path / 'spark_ds')
        with materialize_dataset(url, schema, row_group_size_mb=1,
                                 spark=spark):
            spark.range(100).write.parquet(url[len('file://'):])
        with make_batch_reader(url) as reader:
            total = sum(len(b.id) for b in reader)
        assert total == 100

    def test_make_spark_converter_roundtrip(self, spark, tmp_path):
        from petastorm_tpu.spark import make_spark_converter
        df = spark.range(64).selectExpr('id', 'id * 2 as doubled')
        converter = make_spark_converter(
            df, parent_cache_dir_url='file://' + str(tmp_path / 'cache'))
        assert len(converter) == 64
        with converter.make_torch_dataloader(batch_size=16) as loader:
            batch = next(iter(loader))
        assert len(batch['id']) == 16
        converter.delete()

    def test_dataset_as_rdd(self, spark, synthetic_dataset):
        from petastorm_tpu.spark_utils import dataset_as_rdd
        rdd = dataset_as_rdd(synthetic_dataset.url, spark,
                             schema_fields=['^id$'])
        ids = sorted(row.id for row in rdd.collect())
        assert ids == sorted(d['id'] for d in synthetic_dataset.data)
