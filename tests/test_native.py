"""Native batched NPY decoder tests (C extension, with Python fallback
parity checks)."""

from io import BytesIO

import numpy as np
import pytest

from petastorm_tpu.codecs import NdarrayCodec
from petastorm_tpu.native import get_native_module
from petastorm_tpu.unischema import UnischemaField


def _npy(arr):
    buf = BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


def _shape_str(out):
    return "'shape': %r" % (out.shape[1:],)


@pytest.fixture(scope='module')
def native():
    module = get_native_module()
    if module is None:
        pytest.skip('native extension could not be built')
    return module


class TestNativeDecoder:
    def test_roundtrip_matches_source(self, native):
        rng = np.random.RandomState(0)
        arrs = [rng.rand(4, 6).astype(np.float32) for _ in range(20)]
        out = np.empty((20, 4, 6), np.float32)
        assert native.decode_npy_batch([_npy(a) for a in arrs], out, '<f4', _shape_str(out)) == 20
        for i in range(20):
            np.testing.assert_array_equal(out[i], arrs[i])

    def test_dtype_variants(self, native):
        for dtype in (np.int64, np.uint8, np.float64, np.bool_):
            arr = (np.arange(12) % 2).astype(dtype).reshape(3, 4)
            out = np.empty((1, 3, 4), dtype)
            assert native.decode_npy_batch([_npy(arr)], out,
                                           np.dtype(dtype).str,
                                           _shape_str(out)) == 1
            np.testing.assert_array_equal(out[0], arr)

    def test_stops_at_none(self, native):
        arr = np.ones((2, 2), np.float32)
        out = np.empty((3, 2, 2), np.float32)
        cells = [_npy(arr), None, _npy(arr)]
        assert native.decode_npy_batch(cells, out, '<f4', _shape_str(out)) == 1

    def test_stops_at_wrong_shape(self, native):
        good = np.ones((2, 2), np.float32)
        bad = np.ones((3, 3), np.float32)
        out = np.empty((2, 2, 2), np.float32)
        assert native.decode_npy_batch([_npy(good), _npy(bad)], out, '<f4', _shape_str(out)) == 1

    def test_rejects_wrong_dtype(self, native):
        arr = np.ones((2, 2), np.float64)
        out = np.empty((1, 2, 2), np.float32)
        assert native.decode_npy_batch([_npy(arr)], out, '<f4', _shape_str(out)) == 0

    def test_rejects_garbage(self, native):
        out = np.empty((1, 2, 2), np.float32)
        assert native.decode_npy_batch([b'not-an-npy'], out, '<f4', _shape_str(out)) == 0

    def test_rejects_fortran_order(self, native):
        arr = np.asfortranarray(np.arange(6, dtype=np.float32).reshape(2, 3))
        out = np.empty((1, 2, 3), np.float32)
        # np.save of a fortran array records fortran_order True
        assert native.decode_npy_batch([_npy(arr)], out, '<f4', _shape_str(out)) == 0

    def test_rejects_transposed_shape_same_bytes(self, native):
        # (3,2) and (2,3) have equal byte counts; memcpy'ing the former into
        # the latter would silently reinterpret the data (ADVICE r1, medium).
        arr = np.arange(6, dtype=np.float32).reshape(3, 2)
        out = np.empty((1, 2, 3), np.float32)
        assert native.decode_npy_batch([_npy(arr)], out, '<f4', _shape_str(out)) == 0

    def test_rejects_flat_vs_square_same_bytes(self, native):
        arr = np.arange(4, dtype=np.float32)  # (4,) vs declared (2, 2)
        out = np.empty((1, 2, 2), np.float32)
        assert native.decode_npy_batch([_npy(arr)], out, '<f4', _shape_str(out)) == 0


class TestCodecIntegration:
    def test_codec_batch_equals_per_cell(self):
        field = UnischemaField('m', np.float32, (5, 7), NdarrayCodec(), False)
        codec = field.codec
        rng = np.random.RandomState(1)
        arrs = [rng.rand(5, 7).astype(np.float32) for _ in range(10)]
        cells = [codec.encode(field, a) for a in arrs]
        batch = codec.decode_batch(field, cells)
        for got, expected in zip(batch, arrs):
            np.testing.assert_array_equal(got, expected)

    def test_codec_mixed_valid_cells_fall_back(self):
        field = UnischemaField('m', np.float32, (2, 2), NdarrayCodec(), False)
        codec = field.codec
        a = np.ones((2, 2), np.float32)
        # wildcard-free field but one cell is float64: full parity via fallback
        weird = BytesIO()
        np.save(weird, np.ones((2, 2), np.float64), allow_pickle=False)
        batch = codec.decode_batch(field, [codec.encode(field, a),
                                           weird.getvalue()])
        np.testing.assert_array_equal(batch[0], a)
        assert batch[1].dtype == np.float64

    def test_codec_transposed_cell_falls_back_with_true_shape(self):
        field = UnischemaField('m', np.float32, (2, 3), NdarrayCodec(), False)
        codec = field.codec
        good = np.arange(6, dtype=np.float32).reshape(2, 3)
        transposed = np.arange(6, dtype=np.float32).reshape(3, 2)
        batch = codec.decode_batch(field, [codec.encode(field, good),
                                           _npy(transposed)])
        np.testing.assert_array_equal(batch[0], good)
        # the mismatched cell must keep its true shape, not be reinterpreted
        assert batch[1].shape == (3, 2)
        np.testing.assert_array_equal(batch[1], transposed)

    def test_wildcard_shape_uses_python_path(self):
        field = UnischemaField('m', np.float32, (None, 3), NdarrayCodec(), False)
        codec = field.codec
        arrs = [np.ones((i + 1, 3), np.float32) for i in range(3)]
        batch = codec.decode_batch(field, [codec.encode(field, a) for a in arrs])
        assert [b.shape for b in batch] == [(1, 3), (2, 3), (3, 3)]
