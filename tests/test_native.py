"""Native batched NPY decoder tests (C extension, with Python fallback
parity checks)."""

from io import BytesIO

import numpy as np
import pytest

from petastorm_tpu.codecs import NdarrayCodec
from petastorm_tpu.native import get_native_module
from petastorm_tpu.unischema import UnischemaField


def _npy(arr):
    buf = BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


def _shape_str(out):
    return "'shape': %r" % (out.shape[1:],)


@pytest.fixture(scope='module')
def native():
    module = get_native_module()
    if module is None:
        pytest.skip('native extension could not be built')
    return module


class TestNativeDecoder:
    def test_roundtrip_matches_source(self, native):
        rng = np.random.RandomState(0)
        arrs = [rng.rand(4, 6).astype(np.float32) for _ in range(20)]
        out = np.empty((20, 4, 6), np.float32)
        assert native.decode_npy_batch([_npy(a) for a in arrs], out, '<f4', _shape_str(out)) == 20
        for i in range(20):
            np.testing.assert_array_equal(out[i], arrs[i])

    def test_dtype_variants(self, native):
        for dtype in (np.int64, np.uint8, np.float64, np.bool_):
            arr = (np.arange(12) % 2).astype(dtype).reshape(3, 4)
            out = np.empty((1, 3, 4), dtype)
            assert native.decode_npy_batch([_npy(arr)], out,
                                           np.dtype(dtype).str,
                                           _shape_str(out)) == 1
            np.testing.assert_array_equal(out[0], arr)

    def test_stops_at_none(self, native):
        arr = np.ones((2, 2), np.float32)
        out = np.empty((3, 2, 2), np.float32)
        cells = [_npy(arr), None, _npy(arr)]
        assert native.decode_npy_batch(cells, out, '<f4', _shape_str(out)) == 1

    def test_stops_at_wrong_shape(self, native):
        good = np.ones((2, 2), np.float32)
        bad = np.ones((3, 3), np.float32)
        out = np.empty((2, 2, 2), np.float32)
        assert native.decode_npy_batch([_npy(good), _npy(bad)], out, '<f4', _shape_str(out)) == 1

    def test_rejects_wrong_dtype(self, native):
        arr = np.ones((2, 2), np.float64)
        out = np.empty((1, 2, 2), np.float32)
        assert native.decode_npy_batch([_npy(arr)], out, '<f4', _shape_str(out)) == 0

    def test_rejects_garbage(self, native):
        out = np.empty((1, 2, 2), np.float32)
        assert native.decode_npy_batch([b'not-an-npy'], out, '<f4', _shape_str(out)) == 0

    def test_rejects_fortran_order(self, native):
        arr = np.asfortranarray(np.arange(6, dtype=np.float32).reshape(2, 3))
        out = np.empty((1, 2, 3), np.float32)
        # np.save of a fortran array records fortran_order True
        assert native.decode_npy_batch([_npy(arr)], out, '<f4', _shape_str(out)) == 0

    def test_rejects_transposed_shape_same_bytes(self, native):
        # (3,2) and (2,3) have equal byte counts; memcpy'ing the former into
        # the latter would silently reinterpret the data (ADVICE r1, medium).
        arr = np.arange(6, dtype=np.float32).reshape(3, 2)
        out = np.empty((1, 2, 3), np.float32)
        assert native.decode_npy_batch([_npy(arr)], out, '<f4', _shape_str(out)) == 0

    def test_rejects_flat_vs_square_same_bytes(self, native):
        arr = np.arange(4, dtype=np.float32)  # (4,) vs declared (2, 2)
        out = np.empty((1, 2, 2), np.float32)
        assert native.decode_npy_batch([_npy(arr)], out, '<f4', _shape_str(out)) == 0

    def test_threads_arg_parity_and_prefix(self, native):
        """The internal-pool spelling (trailing threads arg) decodes the
        same bytes to the same rows as the serial call, and a mid-batch
        oddball keeps the decoded-prefix contract."""
        rng = np.random.RandomState(3)
        arrs = [rng.rand(8, 16).astype(np.float32) for _ in range(24)]
        cells = [_npy(a) for a in arrs]
        serial = np.empty((24, 8, 16), np.float32)
        pooled = np.empty_like(serial)
        assert native.decode_npy_batch(cells, serial, '<f4',
                                       _shape_str(serial)) == 24
        assert native.decode_npy_batch(cells, pooled, '<f4',
                                       _shape_str(pooled), 4) == 24
        np.testing.assert_array_equal(serial, pooled)
        bad = list(cells)
        bad[5] = b'not-an-npy'
        prefix = np.empty_like(serial)
        assert native.decode_npy_batch(bad, prefix, '<f4',
                                       _shape_str(prefix), 4) == 5
        np.testing.assert_array_equal(prefix[:5], serial[:5])


class TestCodecIntegration:
    def test_codec_batch_equals_per_cell(self):
        field = UnischemaField('m', np.float32, (5, 7), NdarrayCodec(), False)
        codec = field.codec
        rng = np.random.RandomState(1)
        arrs = [rng.rand(5, 7).astype(np.float32) for _ in range(10)]
        cells = [codec.encode(field, a) for a in arrs]
        batch = codec.decode_batch(field, cells)
        for got, expected in zip(batch, arrs):
            np.testing.assert_array_equal(got, expected)

    def test_codec_mixed_valid_cells_fall_back(self):
        field = UnischemaField('m', np.float32, (2, 2), NdarrayCodec(), False)
        codec = field.codec
        a = np.ones((2, 2), np.float32)
        # wildcard-free field but one cell is float64: full parity via fallback
        weird = BytesIO()
        np.save(weird, np.ones((2, 2), np.float64), allow_pickle=False)
        batch = codec.decode_batch(field, [codec.encode(field, a),
                                           weird.getvalue()])
        np.testing.assert_array_equal(batch[0], a)
        assert batch[1].dtype == np.float64

    def test_codec_transposed_cell_falls_back_with_true_shape(self):
        field = UnischemaField('m', np.float32, (2, 3), NdarrayCodec(), False)
        codec = field.codec
        good = np.arange(6, dtype=np.float32).reshape(2, 3)
        transposed = np.arange(6, dtype=np.float32).reshape(3, 2)
        batch = codec.decode_batch(field, [codec.encode(field, good),
                                           _npy(transposed)])
        np.testing.assert_array_equal(batch[0], good)
        # the mismatched cell must keep its true shape, not be reinterpreted
        assert batch[1].shape == (3, 2)
        np.testing.assert_array_equal(batch[1], transposed)

    def test_wildcard_shape_uses_python_path(self):
        field = UnischemaField('m', np.float32, (None, 3), NdarrayCodec(), False)
        codec = field.codec
        arrs = [np.ones((i + 1, 3), np.float32) for i in range(3)]
        batch = codec.decode_batch(field, [codec.encode(field, a) for a in arrs])
        assert [b.shape for b in batch] == [(1, 3), (2, 3), (3, 3)]


class TestBuildStaleness:
    """The staleness probe covers the BUILD IDENTITY, not just .c mtime:
    a compiler/linker-flag change (e.g. adding -pthread) must trigger a
    rebuild instead of loading a stale extension (ISSUE 9 satellite)."""

    def test_current_build_is_found(self, native):
        import petastorm_tpu.native as nat
        assert nat._find_built_extension('_npy_batch') is not None

    def test_flag_identity_change_marks_stale(self, native, monkeypatch):
        import petastorm_tpu.native as nat
        monkeypatch.setattr(nat, '_build_identity',
                            lambda name: 'changed-flags')
        assert nat._find_built_extension('_npy_batch') is None

    def test_missing_identity_sidecar_marks_stale(self, native, monkeypatch,
                                                  tmp_path):
        # a .so that predates identity tracking has nothing vouching for
        # its flags: rebuild once rather than trust it
        import petastorm_tpu.native as nat
        monkeypatch.setattr(nat, '_identity_path',
                            lambda name: str(tmp_path / 'absent'))
        assert nat._find_built_extension('_npy_batch') is None

    def test_identity_covers_compile_flags(self):
        # the identity hashes the generated build script, which embeds
        # the flags — so the -pthread addition itself re-keys every build
        import petastorm_tpu.native as nat
        script = nat._build_script('_npy_batch')
        assert '-pthread' in script
        assert nat._build_identity('_npy_batch') \
            != __import__('hashlib').md5(b'other').hexdigest()


@pytest.fixture(scope='module')
def jpeg_native():
    from petastorm_tpu.native import get_jpeg_module
    module = get_jpeg_module()
    if module is None:
        pytest.skip('native jpeg extension could not be built '
                    '(no libjpeg dev files?)')
    return module


def _jpeg_cells(n, h=48, w=64, seed=0, quality=90):
    import cv2
    rng = np.random.RandomState(seed)
    cells, images = [], []
    for _ in range(n):
        base = cv2.resize((rng.rand(8, 8, 3) * 200).astype(np.uint8), (w, h),
                          interpolation=cv2.INTER_CUBIC)
        img = np.clip(base.astype(np.float64) + rng.rand(h, w, 3) * 40,
                      0, 255).astype(np.uint8)
        ok, enc = cv2.imencode('.jpeg',
                               cv2.cvtColor(img, cv2.COLOR_RGB2BGR),
                               [int(cv2.IMWRITE_JPEG_QUALITY), quality])
        assert ok
        cells.append(enc.tobytes())
        images.append(img)
    return cells, images


class TestNativeJpegDecoder:
    def test_bit_exact_with_cv2_under_fancy_env(self, jpeg_native,
                                                monkeypatch):
        """PETASTORM_TPU_JPEG_FANCY=1 restores libjpeg defaults, which are
        bit-identical to cv2's decode of the same bytes (both ride
        libjpeg-turbo) — the strict-compat escape hatch."""
        import cv2
        monkeypatch.setenv('PETASTORM_TPU_JPEG_FANCY', '1')
        cells, _ = _jpeg_cells(6)
        out = np.empty((6, 48, 64, 3), np.uint8)
        assert jpeg_native.decode_jpeg_batch(cells, out) == 6
        for i, cell in enumerate(cells):
            ref = cv2.imdecode(np.frombuffer(cell, np.uint8),
                               cv2.IMREAD_COLOR_RGB)
            np.testing.assert_array_equal(out[i], ref)

    def test_default_fast_path_close_to_cv2(self, jpeg_native, monkeypatch):
        """The default (merged-upsampling) decode differs from cv2 only in
        chroma interpolation: small mean deviation, never the luma-scale
        corruption a wrong-stride/wrong-colorspace bug would produce."""
        import cv2
        monkeypatch.delenv('PETASTORM_TPU_JPEG_FANCY', raising=False)
        cells, _ = _jpeg_cells(6)
        out = np.empty((6, 48, 64, 3), np.uint8)
        assert jpeg_native.decode_jpeg_batch(cells, out) == 6
        refs = np.stack([cv2.imdecode(np.frombuffer(c, np.uint8),
                                      cv2.IMREAD_COLOR_RGB) for c in cells])
        diff = np.abs(out.astype(int) - refs.astype(int))
        assert diff.mean() < 8.0, diff.mean()
        assert np.percentile(diff, 99) < 48, np.percentile(diff, 99)

    def test_ifast_dct_close_to_default(self, jpeg_native, monkeypatch):
        """PETASTORM_TPU_JPEG_DCT=ifast opts into turbo's fast integer DCT
        (for builds whose ISLOW has no SIMD path); output stays a faithful
        decode — tiny deviation from the default-path decode, no
        corruption."""
        monkeypatch.delenv('PETASTORM_TPU_JPEG_FANCY', raising=False)
        cells, _ = _jpeg_cells(4)
        default_out = np.empty((4, 48, 64, 3), np.uint8)
        monkeypatch.delenv('PETASTORM_TPU_JPEG_DCT', raising=False)
        assert jpeg_native.decode_jpeg_batch(cells, default_out) == 4
        ifast_out = np.empty((4, 48, 64, 3), np.uint8)
        monkeypatch.setenv('PETASTORM_TPU_JPEG_DCT', 'ifast')
        assert jpeg_native.decode_jpeg_batch(cells, ifast_out) == 4
        # the knob must actually take effect: IFAST and ISLOW provably
        # differ on q90 4:2:0 cells, so identical output means the env
        # parse is dead and both runs decoded ISLOW
        assert (ifast_out != default_out).any()
        diff = np.abs(ifast_out.astype(int) - default_out.astype(int))
        assert diff.mean() < 4.0, diff.mean()
        assert diff.max() < 64, diff.max()

    def test_corrupt_cell_stops_prefix(self, jpeg_native):
        cells, _ = _jpeg_cells(5)
        cells[2] = cells[2][:40]
        out = np.empty((5, 48, 64, 3), np.uint8)
        assert jpeg_native.decode_jpeg_batch(cells, out) == 2

    def test_wrong_size_stops(self, jpeg_native):
        cells, _ = _jpeg_cells(3)
        out = np.empty((3, 32, 32, 3), np.uint8)
        assert jpeg_native.decode_jpeg_batch(cells, out) == 0

    def test_grayscale_rejected_to_python_path(self, jpeg_native):
        import cv2
        gray = (np.arange(48 * 64, dtype=np.uint8).reshape(48, 64))
        ok, enc = cv2.imencode('.jpeg', gray)
        cells, _ = _jpeg_cells(2)
        out = np.empty((3, 48, 64, 3), np.uint8)
        assert jpeg_native.decode_jpeg_batch(
            [cells[0], enc.tobytes(), cells[1]], out) == 1

    def test_threads_arg_parity_and_prefix(self, jpeg_native, monkeypatch):
        """decode_jpeg_batch(cells, out, fancy, threads): the internal
        pthread pool decodes bit-identically to the serial loop (same
        mode, same libjpeg), and a corrupt mid-batch cell keeps the
        decoded-prefix contract across chunk boundaries."""
        monkeypatch.delenv('PETASTORM_TPU_JPEG_FANCY', raising=False)
        cells, _ = _jpeg_cells(11)
        serial = np.empty((11, 48, 64, 3), np.uint8)
        pooled = np.empty_like(serial)
        assert jpeg_native.decode_jpeg_batch(cells, serial, 1) == 11
        assert jpeg_native.decode_jpeg_batch(cells, pooled, 1, 4) == 11
        np.testing.assert_array_equal(serial, pooled)
        bad = list(cells)
        bad[3] = bad[3][:40]
        prefix = np.empty_like(serial)
        assert jpeg_native.decode_jpeg_batch(bad, prefix, 1, 4) == 3
        np.testing.assert_array_equal(prefix[:3], serial[:3])

    def test_arrow_buffer_cells(self, jpeg_native):
        import pyarrow as pa
        cells, _ = _jpeg_cells(4)
        arr = pa.array(cells, pa.binary())
        out = np.empty((4, 48, 64, 3), np.uint8)
        assert jpeg_native.decode_jpeg_batch(
            [v.as_buffer() for v in arr], out) == 4

    def test_bad_out_array_raises(self, jpeg_native):
        cells, _ = _jpeg_cells(1)
        with pytest.raises(ValueError, match='uint8'):
            jpeg_native.decode_jpeg_batch(cells,
                                          np.empty((1, 4, 4, 4), np.uint8))

    def test_explicit_mode_argument(self, jpeg_native, monkeypatch):
        """decode_jpeg_batch(cells, out, fancy) overrides the env parse:
        1 is bit-identical to cv2 (fancy), 0 (merged) provably differs on
        4:2:0 cells, and -1 defers to the env default."""
        import cv2
        monkeypatch.delenv('PETASTORM_TPU_JPEG_FANCY', raising=False)
        cells, _ = _jpeg_cells(4)
        fancy_out = np.empty((4, 48, 64, 3), np.uint8)
        merged_out = np.empty((4, 48, 64, 3), np.uint8)
        env_out = np.empty((4, 48, 64, 3), np.uint8)
        assert jpeg_native.decode_jpeg_batch(cells, fancy_out, 1) == 4
        assert jpeg_native.decode_jpeg_batch(cells, merged_out, 0) == 4
        assert jpeg_native.decode_jpeg_batch(cells, env_out, -1) == 4
        refs = np.stack([cv2.imdecode(np.frombuffer(c, np.uint8),
                                      cv2.IMREAD_COLOR_RGB) for c in cells])
        np.testing.assert_array_equal(fancy_out, refs)
        assert (merged_out != fancy_out).any()
        # env unset: -1 means the historical merged default
        np.testing.assert_array_equal(env_out, merged_out)
        # explicit mode wins over a set env var, in both directions
        monkeypatch.setenv('PETASTORM_TPU_JPEG_FANCY', '1')
        assert jpeg_native.decode_jpeg_batch(cells, merged_out, 0) == 4
        assert (merged_out != fancy_out).any()


class TestJpegCodecIntegration:
    def test_codec_batch_bit_exact_with_per_cell(self, monkeypatch):
        from petastorm_tpu.codecs import CompressedImageCodec
        monkeypatch.setenv('PETASTORM_TPU_JPEG_FANCY', '1')  # strict mode
        codec = CompressedImageCodec('jpeg', quality=92)
        field = UnischemaField('im', np.uint8, (48, 64, 3), codec, False)
        cells = [codec.encode(field, img)
                 for img in _jpeg_cells(8, seed=3)[1]]
        batch = codec.decode_batch(field, cells)
        assert isinstance(batch, np.ndarray) and batch.shape == (8, 48, 64, 3)
        for i, cell in enumerate(cells):
            np.testing.assert_array_equal(batch[i], codec.decode(field, cell))

    def test_codec_batch_with_mid_batch_oddball(self):
        # a grayscale cell mid-batch: native rejects it, _decode_into
        # raises on the shape mismatch, the codec falls back to the
        # per-cell list path preserving the odd cell's true shape
        import cv2
        from petastorm_tpu.codecs import CompressedImageCodec
        codec = CompressedImageCodec('jpeg')
        field = UnischemaField('im', np.uint8, (48, 64, 3), codec, False)
        cells = [codec.encode(field, img)
                 for img in _jpeg_cells(5, seed=4)[1]]
        gray = (np.arange(48 * 64, dtype=np.uint8).reshape(48, 64))
        ok, enc = cv2.imencode('.jpeg', gray)
        cells.insert(2, bytearray(enc.tobytes()))
        decoded = codec.decode_batch(field, cells)
        assert isinstance(decoded, list) and len(decoded) == 6
        assert decoded[2].shape == (48, 64)
        assert decoded[0].shape == (48, 64, 3)

    def test_upsampling_auto_calibration(self, jpeg_native, monkeypatch):
        """With the env unset, the first sizeable batch calibrates the
        chroma-upsampling mode (times both, caches the winner) and the
        decoded batch matches that mode's direct native decode exactly."""
        from petastorm_tpu import codecs
        from petastorm_tpu.codecs import CompressedImageCodec
        monkeypatch.delenv('PETASTORM_TPU_JPEG_FANCY', raising=False)
        monkeypatch.setattr(codecs, '_JPEG_FANCY_MODE', None)
        # hermetic: never read/write the real per-host mode cache
        monkeypatch.setattr(codecs, '_jpeg_mode_cache_path', lambda fn: None)
        codec = CompressedImageCodec('jpeg')
        field = UnischemaField('im', np.uint8, (48, 64, 3), codec, False)
        cells = [codec.encode(field, img)
                 for img in _jpeg_cells(8, seed=6)[1]]
        batch = codec.decode_batch(field, cells)
        assert isinstance(batch, np.ndarray) and batch.shape == (8, 48, 64, 3)
        assert codecs._JPEG_FANCY_MODE in (0, 1)
        ref = np.empty_like(batch)
        assert jpeg_native.decode_jpeg_batch(cells, ref,
                                             codecs._JPEG_FANCY_MODE) == 8
        np.testing.assert_array_equal(batch, ref)

    def test_calibration_host_cache_round_trip(self, jpeg_native,
                                               monkeypatch, tmp_path):
        """The calibrated winner persists to a per-host cache file keyed
        by the native build, and a later process (fresh module state)
        restores it without re-timing — run-to-run pixel stability on a
        host (advisor r4)."""
        from petastorm_tpu import codecs
        from petastorm_tpu.codecs import CompressedImageCodec
        monkeypatch.delenv('PETASTORM_TPU_JPEG_FANCY', raising=False)
        cache_file = str(tmp_path / 'jpeg_mode_cache')
        monkeypatch.setattr(codecs, '_jpeg_mode_cache_path',
                            lambda fn: cache_file)
        monkeypatch.setattr(codecs, '_JPEG_FANCY_MODE', None)
        codec = CompressedImageCodec('jpeg')
        field = UnischemaField('im', np.uint8, (48, 64, 3), codec, False)
        cells = [codec.encode(field, img)
                 for img in _jpeg_cells(8, seed=9)[1]]
        codec.decode_batch(field, cells)
        first = codecs._JPEG_FANCY_MODE
        assert first in (0, 1)
        with open(cache_file) as f:
            assert f.read().strip() == str(first)
        # a "new process": poison the cache with the OTHER mode and clear
        # module state — restore must adopt the cached pick, proving no
        # re-calibration happened (timing would likely re-pick `first`)
        with open(cache_file, 'w') as f:
            f.write(str(1 - first))
        monkeypatch.setattr(codecs, '_JPEG_FANCY_MODE', None)
        codec.decode_batch(field, cells)
        assert codecs._JPEG_FANCY_MODE == 1 - first

    def test_cache_path_keyed_by_native_build(self, jpeg_native):
        from petastorm_tpu import codecs
        path = codecs._jpeg_mode_cache_path(jpeg_native.decode_jpeg_batch)
        assert path is not None and 'petastorm_tpu_jpeg_fancy' in path
        # unidentifiable builds opt out of caching rather than colliding
        assert codecs._jpeg_mode_cache_path(len) is None

    def test_forced_env_skips_calibration(self, monkeypatch):
        """A set PETASTORM_TPU_JPEG_FANCY disables calibration entirely
        (the C env parse keeps authority) and =1 stays bit-identical to
        per-cell cv2 decode."""
        from petastorm_tpu import codecs
        from petastorm_tpu.codecs import CompressedImageCodec
        monkeypatch.setenv('PETASTORM_TPU_JPEG_FANCY', '1')
        monkeypatch.setattr(codecs, '_JPEG_FANCY_MODE', None)
        codec = CompressedImageCodec('jpeg')
        field = UnischemaField('im', np.uint8, (48, 64, 3), codec, False)
        cells = [codec.encode(field, img)
                 for img in _jpeg_cells(8, seed=7)[1]]
        batch = codec.decode_batch(field, cells)
        assert codecs._JPEG_FANCY_MODE is None  # calibration never ran
        for i, cell in enumerate(cells):
            np.testing.assert_array_equal(batch[i], codec.decode(field, cell))

    def test_mid_batch_png_cell_keeps_native_tail(self, monkeypatch):
        # a PNG cell in a jpeg-codec batch: native rejects it, cv2 decodes
        # it into its row, and the native loop RE-ENTERS for the tail (the
        # dense array comes back fully populated, not a list). Strict mode
        # so the jpeg rows compare exactly against per-cell decode.
        import cv2
        monkeypatch.setenv('PETASTORM_TPU_JPEG_FANCY', '1')
        from petastorm_tpu.codecs import CompressedImageCodec
        codec = CompressedImageCodec('jpeg')
        field = UnischemaField('im', np.uint8, (48, 64, 3), codec, False)
        images = _jpeg_cells(6, seed=5)[1]
        cells = [codec.encode(field, img) for img in images]
        ok, png = cv2.imencode('.png', cv2.cvtColor(images[3],
                                                    cv2.COLOR_RGB2BGR))
        cells[3] = bytearray(png.tobytes())
        batch = codec.decode_batch(field, cells)
        assert isinstance(batch, np.ndarray) and batch.shape == (6, 48, 64, 3)
        np.testing.assert_array_equal(batch[3], images[3])  # png lossless
        for i in (0, 1, 2, 4, 5):
            np.testing.assert_array_equal(batch[i],
                                          codec.decode(field, cells[i]))


@pytest.fixture(scope='module')
def png_native():
    from petastorm_tpu.native import get_png_module
    module = get_png_module()
    if module is None:
        pytest.skip('native png extension could not be built '
                    '(no libpng dev files?)')
    return module


class TestNativePngDecoder:
    def _png_cells(self, n, h=32, w=32, seed=0):
        import cv2
        rng = np.random.RandomState(seed)
        cells, images = [], []
        for _ in range(n):
            img = rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
            ok, enc = cv2.imencode('.png', cv2.cvtColor(img,
                                                        cv2.COLOR_RGB2BGR))
            assert ok
            cells.append(enc.tobytes())
            images.append(img)
        return cells, images

    def test_lossless_roundtrip(self, png_native):
        cells, images = self._png_cells(6)
        out = np.empty((6, 32, 32, 3), np.uint8)
        assert png_native.decode_png_batch(cells, out) == 6
        for i in range(6):
            np.testing.assert_array_equal(out[i], images[i])

    def test_corrupt_cell_stops_prefix(self, png_native):
        cells, _ = self._png_cells(4)
        cells[1] = cells[1][:30]
        out = np.empty((4, 32, 32, 3), np.uint8)
        assert png_native.decode_png_batch(cells, out) == 1

    def test_gray_or_wrong_size_rejected(self, png_native):
        import cv2
        cells, _ = self._png_cells(2)
        gray = np.arange(32 * 32, dtype=np.uint8).reshape(32, 32)
        ok, enc = cv2.imencode('.png', gray)
        out = np.empty((3, 32, 32, 3), np.uint8)
        assert png_native.decode_png_batch(
            [cells[0], enc.tobytes(), cells[1]], out) == 1
        small = np.empty((2, 16, 16, 3), np.uint8)
        assert png_native.decode_png_batch(cells, small) == 0

    def test_threads_arg_parity_and_prefix(self, png_native):
        cells, images = self._png_cells(9)
        serial = np.empty((9, 32, 32, 3), np.uint8)
        pooled = np.empty_like(serial)
        assert png_native.decode_png_batch(cells, serial) == 9
        assert png_native.decode_png_batch(cells, pooled, 4) == 9
        np.testing.assert_array_equal(serial, pooled)
        bad = list(cells)
        bad[2] = bad[2][:30]
        prefix = np.empty_like(serial)
        assert png_native.decode_png_batch(bad, prefix, 4) == 2
        np.testing.assert_array_equal(prefix[:2], serial[:2])

    def test_internal_pool_takes_one_native_call(self, png_native,
                                                 monkeypatch):
        """With PETASTORM_TPU_IMAGE_DECODER_THREADS > 1 and a current
        build, the codec issues ONE native call carrying the threads
        argument — the C pool fans out; the Python executor is never
        engaged for the batch (the knob must not multiply into
        threads x threads, docs/env_knobs.md)."""
        from petastorm_tpu import codecs
        from petastorm_tpu.codecs import CompressedImageCodec
        monkeypatch.setenv('PETASTORM_TPU_IMAGE_DECODER_THREADS', '3')
        # start from no cached executor so the assertion below really
        # proves the native path never consults one into existence
        monkeypatch.setattr(codecs, '_IMAGE_POOL', None)
        calls = []
        real = png_native.decode_png_batch

        def spy(cells, out, *args):
            calls.append((len(cells), args))
            return real(cells, out, *args)

        monkeypatch.setattr(png_native, 'decode_png_batch', spy)
        monkeypatch.setitem(codecs._NATIVE_THREADS_SUPPORT, spy, True)
        codec = CompressedImageCodec('png')
        field = UnischemaField('im', np.uint8, (32, 32, 3), codec, False)
        cells, images = self._png_cells(8, seed=11)
        batch = codec.decode_batch(field, cells)
        real_calls = [(n, args) for n, args in calls if n > 0]
        assert real_calls == [(8, (3,))], calls
        # the C pool took the batch, so the Python-side executor was
        # never even created (one pool per batch, docs/env_knobs.md)
        assert codecs._IMAGE_POOL is None
        for i in range(8):
            np.testing.assert_array_equal(batch[i], images[i])

    def test_codec_batch_uses_native_and_matches(self, png_native,
                                                 monkeypatch):
        from petastorm_tpu.codecs import CompressedImageCodec
        calls = []
        real = png_native.decode_png_batch
        monkeypatch.setattr(
            png_native, 'decode_png_batch',
            lambda cells, out: calls.append(len(cells)) or real(cells, out))
        codec = CompressedImageCodec('png')
        field = UnischemaField('im', np.uint8, (32, 32, 3), codec, False)
        images = self._png_cells(8, seed=9)[1]
        cells = [codec.encode(field, img) for img in images]
        batch = codec.decode_batch(field, cells)
        assert calls, 'native png path was not used'
        assert isinstance(batch, np.ndarray) and batch.shape == (8, 32, 32, 3)
        for i in range(8):
            np.testing.assert_array_equal(batch[i], images[i])
