"""Worker process for the REAL 2-process ``jax.distributed`` loader test.

Launched by ``tests/test_multihost.py`` (never run as a pytest module):
each worker joins a 2-process JAX distributed runtime over CPU devices,
builds a mesh spanning BOTH processes' devices, and drives
``make_jax_loader`` + ``iter_steps`` the documented multi-host way —
proving, with actual process boundaries (not monkeypatched
``_jax_process_info``):

* reader sharding defaults to (process_index, process_count) — disjoint
  row-group shards per host with zero configuration;
* global batch assembly via ``jax.make_array_from_process_local_data``
  (``jax/loader.py``): every step's array is GLOBAL (batch_size x
  process_count rows) while each host contributed only its shard;
* fixed-step epochs over an infinite loader keep collectives aligned
  across hosts whose shards are UNEVEN (the documented pod-hang hazard) —
  both workers run the same step count and every per-step ``psum``-style
  reduction agrees.

Results are written as JSON for the parent to assert on.
"""

import json
import os
import sys


def main():
    coordinator, process_id, num_processes, url, steps, batch, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
        int(sys.argv[5]), int(sys.argv[6]), sys.argv[7])

    os.environ['JAX_PLATFORMS'] = 'cpu'
    os.environ.setdefault(
        'XLA_FLAGS', '--xla_force_host_platform_device_count=4')
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax

    jax.config.update('jax_platforms', 'cpu')
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    assert jax.process_count() == num_processes

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from petastorm_tpu.jax import make_jax_loader

    devices = np.array(jax.devices())  # global: num_processes x 4
    mesh = Mesh(devices, ('data',))

    @jax.jit
    def global_sum(arr):
        return jnp.sum(arr)

    local_ids_per_step = []
    global_sums = []
    global_shapes = []
    with make_jax_loader(url, batch_size=batch, mesh=mesh,
                         fields=['^id$'], num_epochs=None,
                         shuffle_row_groups=False) as loader:
        for step_batch in loader.iter_steps(steps):
            arr = step_batch['id']
            global_shapes.append(list(arr.shape))
            # rows THIS host staged = its addressable shards
            local = np.concatenate(
                [np.asarray(s.data) for s in arr.addressable_shards])
            local_ids_per_step.append(sorted(int(x) for x in local))
            # a cross-host reduction over the global array: hangs (or
            # diverges) unless both hosts issue it the same number of times
            global_sums.append(int(global_sum(arr)))
        shard_info = {
            'cur_shard': loader.reader.cur_shard,
            'shard_count': loader.reader.shard_count,
        }

    with open(out_path, 'w') as f:
        json.dump({
            'process_id': process_id,
            'process_count': jax.process_count(),
            'global_shapes': global_shapes,
            'local_ids_per_step': local_ids_per_step,
            'global_sums': global_sums,
            **shard_info,
        }, f)


if __name__ == '__main__':
    main()
