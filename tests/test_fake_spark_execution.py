"""EXECUTE the pyspark-flavor parity surface against the fake pyspark.

VERDICT r2 weakness #4: ``make_spark_converter``, ``dataset_as_rdd`` and
``materialize_dataset(spark=...)`` were dead code in this environment
(pyspark needs a JVM). The fake (``test_util/fake_pyspark.py``) follows the
reference's mocked-subsystem strategy (mocked HDFS namenodes,
``hdfs/tests/test_hdfs_namenode.py``) so every pyspark-gated code path runs
for real here: vector flattening, precision unification, plan-fingerprint
dedupe, the Spark-side write, availability wait + size advisory, hadoop
conf save/restore, and the executor-side decode closure.
"""

import logging

import numpy as np
import pandas as pd
import pytest

from petastorm_tpu.test_util import fake_pyspark


@pytest.fixture()
def spark():
    displaced = fake_pyspark.install()
    try:
        yield fake_pyspark.SparkSession()
    finally:
        fake_pyspark.uninstall(displaced)


def _feature_df(session, n=32):
    rng = np.random.RandomState(0)
    return session.createDataFrame(pd.DataFrame({
        'id': np.arange(n, dtype=np.int64),
        'weight': rng.rand(n),                                  # float64
        'features': [fake_pyspark.DenseVector(rng.rand(3))      # VectorUDT
                     for _ in range(n)],
        'history': [rng.rand(4) for _ in range(n)],             # array<double>
    }))


class TestMakeSparkConverter:
    def test_roundtrip_vectors_precision_and_loaders(self, spark, tmp_path):
        from petastorm_tpu.spark import make_spark_converter
        df = _feature_df(spark)
        converter = make_spark_converter(
            df, parent_cache_dir_url='file://' + str(tmp_path / 'cache'))
        assert len(converter) == 32

        # the materialized copy must carry float32 (default dtype) arrays
        # where the input had float64 scalars / vectors / arrays
        import pyarrow.parquet as pq
        import glob as _glob
        parts = _glob.glob(str(tmp_path / 'cache' / 'ds-*' / '*.parquet'))
        assert len(parts) == 2, 'fake write emits two part files'
        table = pq.ParquetDataset(sorted(parts)).read()
        assert table.schema.field('weight').type == 'float'
        assert table.schema.field('features').type.value_type == 'float'
        assert table.schema.field('history').type.value_type == 'float'

        with converter.make_torch_dataloader(batch_size=8) as loader:
            batch = next(iter(loader))
        assert len(batch['id']) == 8
        assert batch['features'].shape == (8, 3)

        loader = converter.make_jax_loader(batch_size=8,
                                           fields=['^id$', '^features$'])
        with loader:
            jax_batch = next(iter(loader))
        assert jax_batch['features'].shape == (8, 3)
        converter.delete()

    def test_plan_fingerprint_dedupes(self, spark, tmp_path):
        from petastorm_tpu.spark import make_spark_converter
        url = 'file://' + str(tmp_path / 'cache')
        first = make_spark_converter(_feature_df(spark),
                                     parent_cache_dir_url=url)
        again = make_spark_converter(_feature_df(spark),
                                     parent_cache_dir_url=url)
        assert again is first, 'same content + parent dir must cache-hit'
        other = make_spark_converter(_feature_df(spark, n=16),
                                     parent_cache_dir_url=url)
        assert other is not first
        first.delete()
        other.delete()

    def test_parent_dir_from_spark_conf(self, spark, tmp_path):
        from petastorm_tpu.spark import make_spark_converter
        from petastorm_tpu.spark.spark_dataset_converter import (
            PARENT_CACHE_DIR_URL_CONF,
        )
        spark.conf.set(PARENT_CACHE_DIR_URL_CONF,
                       'file://' + str(tmp_path / 'conf_cache'))
        converter = make_spark_converter(spark.range(8))
        assert str(tmp_path / 'conf_cache') in converter.cache_dir_url
        converter.delete()

    def test_missing_parent_dir_raises(self, spark):
        from petastorm_tpu.spark import make_spark_converter
        with pytest.raises(ValueError, match='parent_cache_dir_url'):
            make_spark_converter(spark.range(4))

    def test_size_advisory_fires_on_small_files(self, spark, tmp_path,
                                                caplog):
        from petastorm_tpu.spark import make_spark_converter
        with caplog.at_level(logging.WARNING,
                             logger='petastorm_tpu.spark'
                                    '.spark_dataset_converter'):
            converter = make_spark_converter(
                _feature_df(spark),
                parent_cache_dir_url='file://' + str(tmp_path / 'cache'))
        assert any('median parquet file size' in m for m in caplog.messages)
        converter.delete()


class TestMaterializeWithSparkSession:
    def test_spark_write_and_hadoop_conf_restored(self, spark, tmp_path):
        from petastorm_tpu import make_batch_reader
        from petastorm_tpu.etl.dataset_metadata import materialize_dataset
        from petastorm_tpu.unischema import Unischema, UnischemaField
        import pyarrow as pa
        from petastorm_tpu.codecs import ScalarCodec

        schema = Unischema('S', [
            UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()),
                           False),
        ])
        url = 'file://' + str(tmp_path / 'spark_ds')
        hadoop_conf = spark.sparkContext._jsc.hadoopConfiguration()
        hadoop_conf.set('parquet.block.size', 'preexisting')
        with materialize_dataset(url, schema, row_group_size_mb=1,
                                 spark=spark):
            # conf is live inside the body (reference :135-178)
            assert hadoop_conf.get('parquet.block.size') == 1024 * 1024
            spark.range(100).write.parquet(url[len('file://'):])
        assert hadoop_conf.get('parquet.block.size') == 'preexisting'
        with make_batch_reader(url) as reader:
            total = sum(len(b.id) for b in reader)
        assert total == 100

    def test_conf_unset_when_absent_before(self, spark, tmp_path):
        from petastorm_tpu.etl.dataset_metadata import materialize_dataset
        from petastorm_tpu.unischema import Unischema, UnischemaField
        import pyarrow as pa
        from petastorm_tpu.codecs import ScalarCodec

        schema = Unischema('S', [
            UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()),
                           False),
        ])
        url = 'file://' + str(tmp_path / 'ds2')
        hadoop_conf = spark.sparkContext._jsc.hadoopConfiguration()
        with materialize_dataset(url, schema, row_group_size_mb=2,
                                 spark=spark):
            assert hadoop_conf.get('parquet.block.size') == 2 * 1024 * 1024
            spark.range(4).write.parquet(url[len('file://'):])
        assert hadoop_conf.get('parquet.block.size') is None


class TestDatasetAsRdd:
    def test_executor_side_decode(self, spark, synthetic_dataset):
        from petastorm_tpu.spark_utils import dataset_as_rdd
        rdd = dataset_as_rdd(synthetic_dataset.url, spark,
                             schema_fields=['^id$'])
        ids = sorted(row.id for row in rdd.collect())
        assert ids == sorted(d['id'] for d in synthetic_dataset.data)

    def test_full_schema_rows(self, spark, synthetic_dataset):
        from petastorm_tpu.spark_utils import dataset_as_rdd
        rows = dataset_as_rdd(synthetic_dataset.url, spark).collect()
        assert len(rows) == len(synthetic_dataset.data)
        by_id = {row.id: row for row in rows}
        want = synthetic_dataset.data[0]
        np.testing.assert_array_equal(by_id[want['id']].matrix,
                                      want['matrix'])


class TestFakeIsHonest:
    """The fake must not leak outside its fixture, and gating still works."""

    def test_import_gating_restored_after_uninstall(self):
        import sys
        assert not isinstance(sys.modules.get('pyspark'),
                              type(sys)) or 'fake' not in getattr(
            sys.modules.get('pyspark'), '__version__', '')
        from petastorm_tpu.spark import make_spark_converter
        try:
            import pyspark  # noqa: F401
            pytest.skip('real pyspark present: gating not applicable')
        except ImportError:
            pass
        with pytest.raises(ImportError, match='requires pyspark'):
            make_spark_converter(object())
