"""Live observability plane: windowed rollups, the anomaly detector, the
HTTP endpoint (port-0 smoke over thread/process/service pools), fleet
aggregation, and the structural zero-thread guard — the ISSUE 10
acceptance criteria.

All network traffic is loopback-only and every port is ephemeral
(``PETASTORM_TPU_OBS_PORT=0``); service tests are marked ``service``
like tests/test_service.py.
"""

import importlib
import json
import os
import sys
import threading
import time
import urllib.request

import pytest

from petastorm_tpu import telemetry as T
from petastorm_tpu.telemetry import obs_server, timeseries
from petastorm_tpu.telemetry.registry import metric_key
from petastorm_tpu.telemetry.spans import STAGE_CALLS, STAGE_SECONDS
from petastorm_tpu.telemetry.timeseries import (
    AnomalyDetector, HeartbeatSummarizer, WindowedRollup,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench_trend():
    tools_dir = os.path.join(_REPO, 'tools')
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    return importlib.import_module('bench_trend')


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    T.reset_for_tests()
    yield
    T.reset_for_tests()


def _arm(monkeypatch, window_sec='0.2', **extra):
    """Arm the observability plane with an ephemeral port and a fast
    test window; refresh so cached knobs notice."""
    monkeypatch.setenv('PETASTORM_TPU_OBS_PORT', '0')
    monkeypatch.setenv('PETASTORM_TPU_OBS_WINDOW_SEC', window_sec)
    for name, value in extra.items():
        monkeypatch.setenv(name, value)
    T.refresh()


def _get(route, port=None, timeout=10):
    port = port or obs_server.server_port()
    assert port, 'no obs server bound'
    return urllib.request.urlopen(
        'http://127.0.0.1:%d%s' % (port, route), timeout=timeout).read()


def _get_json(route, port=None):
    return json.loads(_get(route, port=port))


def _wait_for(predicate, timeout_s=20, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval_s)
    return predicate()


# -- WindowedRollup ----------------------------------------------------------


def test_rollup_rates_and_verdict():
    rollup = WindowedRollup(max_windows=4)
    reg = T.get_registry()
    assert rollup.sample(reg.snapshot(), now=0.0, wall=100.0) is None
    reg.counter(STAGE_CALLS, stage='queue_wait').inc(20)
    reg.counter(T.STALL_PRODUCER_WAIT).inc(1.8)
    reg.gauge('depth').set(7)
    window = rollup.sample(reg.snapshot(), now=2.0, wall=102.0)
    assert window['dur_s'] == pytest.approx(2.0)
    key = metric_key(STAGE_CALLS, {'stage': 'queue_wait'})
    assert window['rates'][key] == pytest.approx(10.0)
    assert window['throughput'] == pytest.approx(10.0)
    assert window['producer_wait_s'] == pytest.approx(1.8)
    # producer wait dominates 90% of the window -> consumer-bound
    assert window['verdict'] == T.CONSUMER_BOUND
    assert window['gauges']['depth'] == 7


def test_rollup_quantiles_from_bucket_deltas():
    rollup = WindowedRollup(max_windows=4)
    reg = T.get_registry()
    hist = reg.histogram('lat', buckets=(0.01, 0.1, 1.0))
    rollup.sample(reg.snapshot(), now=0.0)
    for _ in range(90):
        hist.observe(0.005)   # first bucket
    for _ in range(10):
        hist.observe(0.5)     # third bucket
    window = rollup.sample(reg.snapshot(), now=1.0)
    q = window['quantiles']['lat']
    assert q['p50'] == pytest.approx(0.01)
    assert q['p95'] == pytest.approx(1.0)
    assert q['p99'] == pytest.approx(1.0)
    # the NEXT window sees only new increments, not lifetime counts
    hist.observe(0.05)
    window = rollup.sample(reg.snapshot(), now=2.0)
    assert window['quantiles']['lat']['p50'] == pytest.approx(0.1)


def test_rollup_ring_is_bounded():
    rollup = WindowedRollup(max_windows=3)
    reg = T.get_registry()
    for i in range(10):
        rollup.sample(reg.snapshot(), now=float(i))
    assert len(rollup.windows()) == 3
    assert rollup.closed_total == 9


# -- AnomalyDetector (synthetic windows) -------------------------------------


def _window(dur=1.0, producer=0.0, consumer=0.0, rates=None, gauges=None,
            verdict=T.BALANCED, throughput=None, start=0.0):
    return {'start': start, 'dur_s': dur, 'rates': dict(rates or {}),
            'quantiles': {}, 'gauges': dict(gauges or {}),
            'producer_wait_s': producer, 'consumer_wait_s': consumer,
            'verdict': verdict, 'throughput': throughput}


def _detector():
    events = []

    def emit(kind, detail=None, window_start=None):
        event = {'kind': kind, 'detail': detail,
                 'window_start': window_start}
        events.append(event)
        return event

    return AnomalyDetector(emit=emit), events


def test_detector_queue_saturated_edge_and_rearm():
    detector, events = _detector()
    for _ in range(2):
        detector.observe(_window(producer=0.8))
    assert not events  # 3 consecutive windows required
    detector.observe(_window(producer=0.8))
    assert [e['kind'] for e in events] == ['queue_saturated']
    # persisting condition must NOT flood the ring (hysteresis)
    detector.observe(_window(producer=0.9))
    assert len(events) == 1
    # clears, then re-establishes -> exactly one more event
    detector.observe(_window(producer=0.0))
    for _ in range(3):
        detector.observe(_window(producer=0.8))
    assert [e['kind'] for e in events] == ['queue_saturated'] * 2


def test_detector_throughput_collapse_needs_waiting_consumer():
    detector, events = _detector()
    for _ in range(6):
        detector.observe(_window(throughput=100.0, consumer=0.1))
    # a stream that FINISHES (throughput gone, consumer no longer
    # waiting) is not a collapse
    for _ in range(3):
        detector.observe(_window(throughput=0.0, consumer=0.0))
    assert not events
    # rebuild the trailing mean, then collapse WITH the consumer starving
    for _ in range(6):
        detector.observe(_window(throughput=100.0, consumer=0.1))
    detector.observe(_window(throughput=5.0, consumer=0.4))
    assert not events  # one collapsed window is noise
    detector.observe(_window(throughput=5.0, consumer=0.4))
    assert [e['kind'] for e in events] == ['throughput_collapse']
    assert events[0]['detail']['trailing_mean'] == pytest.approx(100.0)


def test_detector_collapse_baseline_excludes_collapsed_windows():
    """A sustained collapse must not drag the trailing mean down to
    itself and self-clear while the pipeline is still stalled."""
    detector, events = _detector()
    for _ in range(6):
        detector.observe(_window(throughput=100.0, consumer=0.1))
    for _ in range(10):
        detector.observe(_window(throughput=5.0, consumer=0.4))
    assert len(events) == 1  # fired once, never cleared/re-fired


def test_detector_stall_flap():
    detector, events = _detector()
    verdicts = [T.PRODUCER_BOUND, T.CONSUMER_BOUND] * 3
    for verdict in verdicts:
        detector.observe(_window(verdict=verdict))
    assert [e['kind'] for e in events] == ['stall_flap']
    assert events[0]['detail']['flips'] >= 3


def test_detector_steady_verdicts_do_not_flap():
    detector, events = _detector()
    for _ in range(10):
        detector.observe(_window(verdict=T.PRODUCER_BOUND))
    assert not events


def test_detector_flap_rearms_after_calm_stretch():
    """A calm (balanced/idle) stretch ends the episode: the next genuine
    flap must fire a SECOND event instead of being swallowed by the
    frozen verdict history."""
    detector, events = _detector()
    for verdict in [T.PRODUCER_BOUND, T.CONSUMER_BOUND] * 3:
        detector.observe(_window(verdict=verdict))
    assert [e['kind'] for e in events] == ['stall_flap']
    for _ in range(AnomalyDetector._CALM_RESET):
        detector.observe(_window(verdict=T.BALANCED))
    for verdict in [T.PRODUCER_BOUND, T.CONSUMER_BOUND] * 3:
        detector.observe(_window(verdict=verdict))
    assert [e['kind'] for e in events] == ['stall_flap'] * 2


def test_detector_heartbeat_gap_from_gauges_and_reventilation():
    detector, events = _detector()
    detector.observe(_window(gauges={
        'petastorm_tpu_service_workers_alive': 2,
        'petastorm_tpu_service_workers_registered': 2}))
    assert not events
    detector.observe(_window(gauges={
        'petastorm_tpu_service_workers_alive': 1,
        'petastorm_tpu_service_workers_registered': 2}))
    assert [e['kind'] for e in events] == ['heartbeat_gap']
    # re-ventilation rate alone is also gap evidence (edge-triggered)
    detector2, events2 = _detector()
    detector2.observe(_window(rates={
        'petastorm_tpu_service_reventilated_total': 2.0}))
    assert [e['kind'] for e in events2] == ['heartbeat_gap']


def test_detector_h2d_starvation():
    detector, events = _detector()
    ready_key = metric_key(STAGE_SECONDS, {'stage': 'h2d_ready'})
    for _ in range(3):
        detector.observe(_window(rates={ready_key: 0.7}))
    assert [e['kind'] for e in events] == ['h2d_starvation']


def test_record_anomaly_rejects_unknown_kind():
    with pytest.raises(ValueError, match='ANOMALY_KINDS'):
        timeseries.record_anomaly('made_up_kind')


def test_record_anomaly_counts_and_runbook():
    event = timeseries.record_anomaly('queue_saturated', detail={'x': 1})
    assert 'troubleshoot.md' in event['runbook']
    assert T.get_registry().counter_value(
        timeseries.ANOMALY_EVENTS, kind='queue_saturated') == 1
    report = T.pipeline_report()
    assert report['anomalies']['by_kind'] == {'queue_saturated': 1}
    assert report['anomalies']['recent'][-1]['kind'] == 'queue_saturated'
    # the rendered report mentions them too
    assert 'anomalies: 1 event(s)' in T.format_pipeline_report(report)


def test_jsonl_snapshot_carries_anomalies():
    import io
    timeseries.record_anomaly('stall_flap')
    buf = io.StringIO()
    T.write_jsonl_snapshot(buf)
    record = json.loads(buf.getvalue())
    assert record['anomalies'][-1]['kind'] == 'stall_flap'
    assert 'runbook' in record['anomalies'][-1]


def test_report_has_no_anomaly_section_when_plane_idle():
    assert 'anomalies' not in T.pipeline_report()


# -- structural zero-cost guards ---------------------------------------------


def _obs_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith('petastorm-tpu-obs')]


def test_no_threads_or_server_without_port(small_scalar_dataset):
    """The acceptance gate's structural half: with the knob unset, a
    full reader pass creates NO observability thread, server or
    collector — mounts are the shared no-op."""
    from petastorm_tpu.reader import make_batch_reader
    assert not timeseries.obs_enabled()
    with make_batch_reader(small_scalar_dataset, num_epochs=1,
                           shuffle_row_groups=False) as reader:
        assert reader._obs_mount is obs_server._NOOP_MOUNT
        for _ in reader:
            pass
    assert obs_server._state.server is None
    assert obs_server._state.thread is None
    assert timeseries._collector is None
    assert not _obs_threads()


def test_no_threads_when_metrics_disabled(monkeypatch):
    monkeypatch.setenv('PETASTORM_TPU_METRICS', '0')
    monkeypatch.setenv('PETASTORM_TPU_OBS_PORT', '0')
    T.refresh()
    try:
        assert obs_server.mount('x') is obs_server._NOOP_MOUNT
        assert timeseries.ensure_collector() is None
        assert obs_server._state.server is None
        assert not _obs_threads()
    finally:
        monkeypatch.delenv('PETASTORM_TPU_METRICS')
        T.refresh()


# -- endpoint smoke: thread AND process AND service pools --------------------


@pytest.fixture
def small_scalar_dataset(tmp_path):
    from tests.test_common import create_test_scalar_dataset
    url = 'file://' + str(tmp_path / 'dataset')
    create_test_scalar_dataset(url, num_rows=80, num_files=8)
    return url


def _assert_routes_live(expect_component):
    metrics = _get('/metrics').decode()
    assert 'petastorm_tpu_stage_seconds_total' in metrics
    report = _get_json('/report')
    assert 'stages' in report and 'stall' in report
    assert 'rollup' in report  # the collector runs alongside the server
    health = _get_json('/health')
    assert health['status'] == 'ok'
    assert any(name.startswith(expect_component)
               for name in health['components'])
    trace = _get_json('/trace')
    assert 'traceEvents' in trace
    return report, health


def _consume(url, pool):
    from petastorm_tpu.reader import make_batch_reader
    with make_batch_reader(url, reader_pool_type=pool, workers_count=1,
                           num_epochs=1, shuffle_row_groups=False) as reader:
        for _ in reader:
            pass
        return _assert_routes_live('reader')


def test_endpoint_routes_thread_pool(small_scalar_dataset, monkeypatch):
    _arm(monkeypatch)
    report, health = _consume(small_scalar_dataset, 'thread')
    reader_health = next(v for k, v in health['components'].items()
                         if k.startswith('reader'))
    assert reader_health['started'] and not reader_health['stopped']
    assert 'items_processed' in reader_health


def test_endpoint_routes_process_pool(small_scalar_dataset, monkeypatch):
    _arm(monkeypatch)
    _consume(small_scalar_dataset, 'process')


def test_endpoint_routes_jax_loader(small_scalar_dataset, monkeypatch):
    """The acceptance shape: a running make_jax_loader job exposes all
    four routes; /health carries both the loader's and the reader's
    sections, /report the loader's live autotune verdict."""
    _arm(monkeypatch)
    from petastorm_tpu.jax import make_jax_loader
    with make_jax_loader(small_scalar_dataset, batch_size=8,
                         fields=['^id$'], num_epochs=1,
                         shuffle_row_groups=False) as loader:
        for _ in loader:
            pass
        report, health = _assert_routes_live('jax-loader')
        assert any(k.startswith('reader') for k in health['components'])
        assert 'autotune' in report
        assert report['autotune']['bottleneck'] in (
            'input', 'compute', 'balanced', 'undetermined')


@pytest.mark.service
def test_endpoint_fleet_view_service_pool(small_scalar_dataset,
                                          monkeypatch):
    """Fleet aggregation end to end: the dispatcher's endpoint serves a
    merged fleet view whose per-worker breakdown carries the
    heartbeat-piggybacked summaries — including each worker server's own
    obs port, which must itself answer /metrics."""
    _arm(monkeypatch)
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.service import ServicePool
    pool = ServicePool(spawn_local_workers=1, heartbeat_interval_s=0.2,
                       connect_timeout_s=60)
    with make_batch_reader(small_scalar_dataset, reader_pool_type=pool,
                           num_epochs=1, shuffle_row_groups=False) as reader:
        for _ in reader:
            pass

        def fleet_with_summary():
            fleet = _get_json('/report').get('fleet') or {}
            workers = fleet.get('workers') or {}
            if any('summary' in w for w in workers.values()):
                return fleet
            return None

        fleet = _wait_for(fleet_with_summary)
        assert fleet, 'no worker summary reached the dispatcher'
        assert fleet['workers_registered'] >= 1
        summary = next(w['summary'] for w in fleet['workers'].values()
                       if 'summary' in w)
        assert summary['pid'] != obs_server.build_health()['pid']
        assert summary['uptime_s'] >= 0
        # drill down into the worker server's OWN endpoint
        worker_port = summary.get('obs_port')
        assert worker_port, 'worker summary lacks its obs port'
        worker_metrics = _get('/metrics', port=worker_port).decode()
        assert 'petastorm_tpu_stage_seconds_total' in worker_metrics
        worker_health = _get_json('/health', port=worker_port)
        assert any(k.startswith('worker-server')
                   for k in worker_health['components'])
        # dispatcher /health: quiesce/backlog state
        health = _get_json('/health')
        dispatcher_health = next(
            v for k, v in health['components'].items()
            if k.startswith('service-dispatcher'))
        assert dispatcher_health['quiesced'] in (False, True)
        assert 'out_backlog' in dispatcher_health


# -- seeded anomaly fixtures (the acceptance criteria) -----------------------


def test_slow_consumer_fires_queue_saturated(small_scalar_dataset,
                                             monkeypatch):
    """Acceptance: a seeded slow consumer over a tiny results queue
    produces a `queue_saturated` event visible in BOTH the live /report
    and the final pipeline_report()."""
    _arm(monkeypatch, window_sec='0.2')
    from petastorm_tpu.reader import make_batch_reader
    with make_batch_reader(small_scalar_dataset, reader_pool_type='thread',
                           workers_count=2, results_queue_size=1,
                           num_epochs=4, shuffle_row_groups=False) as reader:
        saw_live = None
        for _ in reader:
            time.sleep(0.12)  # deliberately slow consumer
            if saw_live is None:
                live = _get_json('/report').get('anomalies') or {}
                if 'queue_saturated' in (live.get('by_kind') or {}):
                    saw_live = live
        # the stream may end before a poll caught it live; one more
        # scrape while the server still runs settles it
        if saw_live is None:
            saw_live = _get_json('/report').get('anomalies') or {}
        assert 'queue_saturated' in (saw_live.get('by_kind') or {}), \
            saw_live
    final = T.pipeline_report()['anomalies']
    assert final['by_kind'].get('queue_saturated', 0) >= 1
    kinds = {e['kind'] for e in final['recent']}
    assert 'queue_saturated' in kinds or final['by_kind'][
        'queue_saturated'] >= 1


@pytest.mark.service
def test_dead_worker_fires_heartbeat_gap(small_scalar_dataset,
                                         monkeypatch):
    """Acceptance: SIGKILLing a worker server mid-read must surface as a
    `heartbeat_gap` anomaly event (via the re-ventilation counter and
    the alive<registered gauge dip the dispatcher mirrors)."""
    import os
    import signal

    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.service import ServicePool
    from petastorm_tpu.transform import TransformSpec
    _arm(monkeypatch, window_sec='0.2')
    pool = ServicePool(spawn_local_workers=2, heartbeat_interval_s=0.2,
                       liveness_timeout_s=0.8, connect_timeout_s=60)
    with make_batch_reader(small_scalar_dataset, reader_pool_type=pool,
                           transform_spec=TransformSpec(_slow_identity),
                           num_epochs=2, shuffle_row_groups=False) as reader:
        first = True
        for _ in reader:
            if first:
                os.kill(pool._local_procs[0].pid, signal.SIGKILL)
                first = False
        event = _wait_for(lambda: [
            e for e in timeseries.recent_anomalies()
            if e['kind'] == 'heartbeat_gap'])
    assert event, 'no heartbeat_gap event after a worker SIGKILL'
    assert T.pipeline_report()['anomalies']['by_kind'][
        'heartbeat_gap'] >= 1


def _slow_identity(frame):
    time.sleep(0.05)
    return frame


# -- refresh / knobs ---------------------------------------------------------


def test_refresh_reconfigures_live_collector(monkeypatch):
    _arm(monkeypatch, window_sec='0.2')
    collector = timeseries.ensure_collector()
    assert collector is not None
    assert collector.window_s == pytest.approx(0.2)
    detector = collector.detector
    assert detector._saturated_share == pytest.approx(0.5)
    # refresh mid-condition must NOT reset hysteresis: an active anomaly
    # would otherwise re-fire its edge after every knob re-read
    detector._active.add('queue_saturated')
    detector._sat_streak = 3
    monkeypatch.setenv('PETASTORM_TPU_OBS_WINDOW_SEC', '0.7')
    monkeypatch.setenv('PETASTORM_TPU_OBS_SATURATED_SHARE', '0.25')
    T.refresh()  # the ONE knob re-read entry point covers obs knobs
    assert collector.window_s == pytest.approx(0.7)
    assert collector.detector is detector  # state survives in place
    assert detector._saturated_share == pytest.approx(0.25)
    assert 'queue_saturated' in detector._active
    assert detector._sat_streak == 3


def test_report_sections_never_clobber(monkeypatch):
    """Two mounted components returning the same report key (two loaders'
    'autotune') must BOTH appear — and no provider can overwrite a
    canonical pipeline_report section."""
    _arm(monkeypatch)
    obs_server.mount('a', report=lambda: {'autotune': {'who': 'a'},
                                          'stall': 'clobber-attempt'})
    obs_server.mount('b', report=lambda: {'autotune': {'who': 'b'}})
    report = obs_server.build_report()
    assert report['autotune'] == {'who': 'a'}
    assert report['autotune-2'] == {'who': 'b'}
    assert isinstance(report['stall'], dict)  # canonical section intact
    assert report['stall-2'] == 'clobber-attempt'


def test_sampler_thread_ticks_and_counts(monkeypatch):
    _arm(monkeypatch, window_sec='0.1')
    collector = timeseries.ensure_collector()
    assert _wait_for(lambda: collector.rollup.closed_total >= 2)
    assert T.get_registry().counter_value(timeseries.OBS_WINDOWS) >= 1
    section = timeseries.rollup_section()
    assert section['headline']['windows_sampled'] >= 2
    assert len(section['windows']) <= 12


# -- heartbeat summarizer / protocol -----------------------------------------


def test_heartbeat_summarizer_rates_and_caps():
    summarizer = HeartbeatSummarizer(worker_id=3)
    first = summarizer.summary(obs_port=1234)
    assert first['worker_id'] == 3 and first['obs_port'] == 1234
    assert 'rates' not in first  # first call primes the baseline
    T.get_registry().counter(STAGE_CALLS, stage='decode').inc(50)
    time.sleep(0.02)
    second = summarizer.summary()
    key = metric_key(STAGE_CALLS, {'stage': 'decode'})
    assert second['rates'][key] > 0
    assert len(second['rates']) <= HeartbeatSummarizer._MAX_RATES


def test_obs_summary_protocol_roundtrip_and_compat():
    from petastorm_tpu.service import protocol as proto
    summary = {'pid': 1, 'rates': {'x': 1.5}}
    assert proto.load_obs_summary(
        proto.dump_obs_summary(summary)) == summary
    assert proto.load_obs_summary(b'') is None
    assert proto.load_obs_summary(b'\x80garbage') is None
    assert proto.load_obs_summary(b'[1,2]') is None  # non-dict shapes
    # unserializable summaries degrade to the empty frame, never raise
    assert proto.dump_obs_summary({'bad': object()}) == b''


def test_dispatcher_heartbeat_summary_capture():
    """The dispatcher's _handle must capture the optional summary frame
    (and stay compatible with bare heartbeats) — unit-level, no fleet."""
    from petastorm_tpu.service import protocol as proto
    from petastorm_tpu.service.dispatcher import Dispatcher

    class _Sock:
        def send_multipart(self, frames, **kw):
            pass

    dispatcher = Dispatcher('tcp://127.0.0.1:0', b'', lambda e: True,
                            threading.Event())
    sock = _Sock()
    dispatcher._handle(sock, [b'w1', proto.MSG_REGISTER])
    dispatcher._handle(sock, [b'w1', proto.MSG_HEARTBEAT])  # bare: ok
    assert dispatcher.fleet_view()['workers']['w1'].get('summary') is None
    frame = proto.dump_obs_summary({'pid': 42, 'uptime_s': 1.0})
    dispatcher._handle(sock, [b'w1', proto.MSG_HEARTBEAT, frame])
    view = dispatcher.fleet_view()
    assert view['workers']['w1']['summary']['pid'] == 42
    dispatcher._handle(sock, [b'w1', proto.MSG_HEARTBEAT, b'garbage'])
    assert dispatcher.fleet_view()['workers']['w1']['summary'][
        'pid'] == 42  # bad frame never clobbers the last good one
    health = dispatcher.health()
    assert health['quiesced'] is False
    assert health['workers_registered'] == 1


# -- bench trend tool --------------------------------------------------------


def _bench_round(tmp_path, n, value, extra):
    headline = {'metric': 'hello_world_read_rate', 'value': value,
                'unit': 'samples/sec', 'vs_baseline': 1.0,
                'headline': True, 'extra': extra}
    record = {'n': n, 'cmd': 'python bench.py', 'rc': 0,
              'tail': 'noise line\n%s\n' % json.dumps(headline)}
    (tmp_path / ('BENCH_r%02d.json' % n)).write_text(json.dumps(record))


def test_bench_trend_fold_and_regression_flag(tmp_path):
    bench_trend = _bench_trend()
    _bench_round(tmp_path, 1, 1000.0, {'vs_tfdata': 1.0})
    _bench_round(tmp_path, 2, 2000.0, {'vs_tfdata': 1.2,
                                       'lm_train_mfu': 0.4})
    _bench_round(tmp_path, 3, 1500.0, {'vs_tfdata': 1.19})
    rounds = bench_trend.load_rounds(str(tmp_path))
    assert [n for n, _ in rounds] == [1, 2, 3]
    report = bench_trend.trend(rounds)
    assert report['metrics']['value']['series'] == [1000.0, 2000.0,
                                                    1500.0]
    # 1500 < 0.9 * 2000 -> the headline metric regressed
    assert 'value' in report['regressions']
    # within 10% of best -> not a regression
    assert 'vs_tfdata' not in report['regressions']
    # measured only once: no earlier baseline, can never flag
    assert not report['metrics']['lm_train_mfu']['regressed']
    table = bench_trend.format_table(report)
    assert 'REGRESSED' in table and 'r03' in table
    # CLI contract: exit 1 only under --fail-on-regression
    assert bench_trend.main(['--dir', str(tmp_path)]) == 0
    assert bench_trend.main(['--dir', str(tmp_path),
                             '--fail-on-regression', '--json']) == 1
    assert bench_trend.main(['--dir', str(tmp_path / 'empty')]) == 2


def test_bench_trend_stale_metrics_never_flag(tmp_path):
    """A metric the LATEST round did not record (skipped section, wedged
    chip) must not regress on stale data — only the latest round's own
    measurement can flag."""
    bench_trend = _bench_trend()
    _bench_round(tmp_path, 1, 1000.0, {'lm_train_mfu': 0.5})
    _bench_round(tmp_path, 2, 1000.0, {'lm_train_mfu': 0.2})
    _bench_round(tmp_path, 3, 1000.0, {})  # section skipped this round
    report = bench_trend.trend(bench_trend.load_rounds(str(tmp_path)))
    assert not report['metrics']['lm_train_mfu']['regressed']
    assert report['regressions'] == []


def test_bench_trend_skips_unparseable_tails(tmp_path):
    bench_trend = _bench_trend()
    (tmp_path / 'BENCH_r01.json').write_text(json.dumps(
        {'n': 1, 'rc': 124, 'tail': 'clipped {not json'}))
    _bench_round(tmp_path, 2, 500.0, {})
    rounds = bench_trend.load_rounds(str(tmp_path))
    assert [n for n, _ in rounds] == [2]


def _multichip_round(tmp_path, n, tail, ok=True):
    record = {'n': n, 'rc': 0 if ok else 1, 'ok': ok, 'tail': tail}
    (tmp_path / ('MULTICHIP_r%02d.json' % n)).write_text(
        json.dumps(record))


def test_bench_trend_folds_multichip_rounds(tmp_path):
    """MULTICHIP rounds join the same per-round table: the dryrun's
    self-counted METRICS line when present, the tail's checkpoint-line
    count for legacy rounds, and the mesh metrics are regression-gated
    like every tracked bench metric."""
    bench_trend = _bench_trend()
    _bench_round(tmp_path, 1, 1000.0, {})
    # legacy round: no METRICS line — checks counted from the tail
    _multichip_round(tmp_path, 1, 'dryrun_multichip: a\n'
                                  'dryrun_multichip: b\n')
    # modern round: the trailing self-counted metrics line wins (the
    # tail's visible line count may be clipped and must not matter)
    metrics = {'checks': 13, 'sharded_overlap_share': 1.0,
               'sharded_h2d_mb_per_sec': 120.5}
    _multichip_round(tmp_path, 2, 'dryrun_multichip: only-one-visible\n'
                     + 'MULTICHIP_METRICS ' + json.dumps(metrics) + '\n')
    rounds = bench_trend.load_rounds(str(tmp_path))
    assert [n for n, _ in rounds] == [1, 2]
    by_n = dict(rounds)
    assert by_n[1]['extra']['multichip_checks'] == 2
    # round 2 has no BENCH record: the MULTICHIP metrics still fold
    assert by_n[2]['value'] is None
    assert by_n[2]['extra']['multichip_checks'] == 13
    assert by_n[2]['extra']['multichip_sharded_overlap_share'] == 1.0
    report = bench_trend.trend(rounds)
    assert report['metrics']['multichip_checks']['series'] == [2, 13]
    assert report['regressions'] == []
    # a later round LOSING checkpoints is a gated regression
    _multichip_round(tmp_path, 3, 'MULTICHIP_METRICS '
                     + json.dumps({'checks': 4}) + '\n')
    report = bench_trend.trend(bench_trend.load_rounds(str(tmp_path)))
    assert 'multichip_checks' in report['regressions']


def test_bench_trend_failed_legacy_multichip_rounds_skip(tmp_path):
    """A failed legacy dryrun (ok=false, no metrics line) contributes
    nothing — absence of evidence is not a regression."""
    bench_trend = _bench_trend()
    _bench_round(tmp_path, 1, 1000.0, {'vs_tfdata': 1.0})
    _multichip_round(tmp_path, 1, 'dryrun_multichip: partial\n'
                                  'Traceback ...\n', ok=False)
    rounds = bench_trend.load_rounds(str(tmp_path))
    assert 'multichip_checks' not in rounds[0][1]['extra']


# -- overhead guard ----------------------------------------------------------


@pytest.mark.perf
def test_collector_overhead_budget(monkeypatch):
    """The sampler must not tax the span hot path: a tight span loop
    with the collector running stays within 4x of the loop without it
    (deliberately loose: shared-box noise must not flake this — it
    catches order-of-magnitude regressions like per-span locking)."""

    def rate():
        n = 20000
        t0 = time.perf_counter()
        for _ in range(n):
            with T.span('decode'):
                pass
        return n / (time.perf_counter() - t0)

    rate()  # warm
    baseline = rate()
    _arm(monkeypatch, window_sec='0.05')
    assert timeseries.ensure_collector() is not None
    armed = rate()
    assert armed >= 0.25 * baseline, (armed, baseline)
