"""Wire-speed I/O plane (ISSUE 15): coalesced column-chunk readahead.

The load-bearing contract is EXACT PARITY: an epoch served by the
readahead plane must deliver the identical row multiset (and identical
heavy-column bytes) as the ``PETASTORM_TPU_READAHEAD=0`` blocking-read
oracle, across pool flavors, with shuffle, pushdown pruning and late
materialization active — and every failure (fetch fault, pool
exhaustion, missing footer) must degrade to the blocking read, counted,
never to a wrong answer.
"""

import gc
import os
import tempfile

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu import readahead
from petastorm_tpu import telemetry as T
from petastorm_tpu.filters import FiltersPredicate


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    T.reset_for_tests()
    yield
    T.reset_for_tests()


def _with_env(env):
    """Apply env overrides + refresh the cached knobs; returns a restore
    callable (which refreshes again)."""
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    T.refresh()

    def restore():
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        T.refresh()

    return restore


def _read_ids(url, oracle=False, pool='thread', shuffle=True, **kwargs):
    restore = _with_env({'PETASTORM_TPU_READAHEAD': '0'} if oracle else {})
    try:
        with make_batch_reader(url, reader_pool_type=pool,
                               shuffle_row_groups=shuffle,
                               **kwargs) as reader:
            return sorted(int(i) for batch in reader for i in batch.id)
    finally:
        restore()


@pytest.fixture(scope='module')
def scalar_url(tmp_path_factory):
    """400 scalar rows over 4 files x 5 row-groups of 20 — enough
    row-groups that the depth-ahead window is exercised end to end."""
    import pyarrow as pa

    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('ReadaheadSchema', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('value', np.float64, (),
                       ScalarCodec(pa.float64()), False),
    ])
    url = 'file://' + str(tmp_path_factory.mktemp('readahead')) + '/ds'
    rows = [{'id': i, 'value': i * 0.5} for i in range(400)]
    write_dataset(url, schema, rows, rowgroup_size_rows=20, num_files=4)
    return url


# ---------------------------------------------------------------------------
# Units: coalescing, buffer pool, sequence arithmetic
# ---------------------------------------------------------------------------


class TestCoalesce:
    def test_adjacent_ranges_merge_through_the_gap(self):
        merged = readahead.coalesce_ranges(
            [(0, 100), (150, 100), (1000, 50)], gap=64, max_range=10000)
        # 100..150 gap (50 <= 64) merges; 250..1000 (750) does not
        assert merged == [(0, 250), (1000, 50)]

    def test_max_range_caps_a_merge(self):
        merged = readahead.coalesce_ranges(
            [(0, 100), (110, 100), (220, 100)], gap=64, max_range=250)
        assert merged == [(0, 210), (220, 100)]

    def test_single_oversized_chunk_is_never_split(self):
        merged = readahead.coalesce_ranges([(0, 5000)], gap=0,
                                           max_range=100)
        assert merged == [(0, 5000)]

    def test_unsorted_input_is_sorted_first(self):
        merged = readahead.coalesce_ranges([(500, 10), (0, 10)], gap=1000,
                                           max_range=10000)
        assert merged == [(0, 510)]


class TestBufferPool:
    def test_acquire_free_and_exhaustion(self):
        pool = readahead._BufferPool(100)
        assert pool.acquire(60)
        assert not pool.acquire(50)  # all-or-nothing, never evicts
        assert pool.acquire(40)
        pool.free(60)
        assert pool.used == 40
        pool.free(40)
        assert pool.used == 0


class TestSequenceMirror:
    """The manager's predicted order must be EXACTLY the ventilator's:
    same permutation arithmetic, same exclusions, same reset stride."""

    def _manager(self, n=10, randomize=True, seed=7, exclude=(),
                 iterations=None):
        plan = {'version': 1,
                'items': [('f%d' % (i % 2), i) for i in range(n)],
                'randomize': randomize, 'seed': seed,
                'iterations': iterations, 'exclude': sorted(exclude)}
        manager = readahead.ReadaheadManager(object(), plan)
        manager.close()  # arithmetic only; no fetch threads wanted
        return manager

    def _ventilator_order(self, n, seed, epoch, exclude=(), sweeps=0):
        from petastorm_tpu.workers.ventilator import (
            ConcurrentVentilator, _RESET_SEED_STRIDE,
        )
        vent = ConcurrentVentilator(lambda **kw: None,
                                    [{'i': i} for i in range(n)],
                                    randomize_item_order=True,
                                    random_seed=seed,
                                    always_exclude=frozenset(exclude))
        vent._seed = (seed + sweeps * _RESET_SEED_STRIDE) % (2 ** 32)
        order = vent._epoch_order(epoch)
        if exclude:
            order = [i for i in order if i not in frozenset(exclude)]
        return order

    @pytest.mark.parametrize('epoch', [0, 1, 5])
    def test_epoch_orders_match(self, epoch):
        manager = self._manager(n=17, seed=123)
        assert manager._epoch_order(0, epoch) == \
            self._ventilator_order(17, 123, epoch)

    def test_excluded_items_never_appear(self):
        manager = self._manager(n=12, seed=3, exclude={2, 7})
        order = manager._epoch_order(0, 0)
        assert 2 not in order and 7 not in order
        assert order == self._ventilator_order(12, 3, 0, exclude={2, 7})

    def test_sweep_advances_by_the_reset_stride(self):
        manager = self._manager(n=9, seed=55)
        assert manager._epoch_order(1, 0) == \
            self._ventilator_order(9, 55, 0, sweeps=1)

    def test_sweep_detected_from_repeated_epoch_items(self):
        manager = self._manager(n=4, randomize=False)
        assert manager._advance_sweep_locked(0, 0) == 0
        assert manager._advance_sweep_locked(1, 0) == 0
        # a reset REPLAYS the epoch: two consecutive repeats flip the
        # sweep (the first repeat alone is ambiguous — see below)
        assert manager._advance_sweep_locked(0, 0) == 0
        assert manager._advance_sweep_locked(1, 0) == 1

    def test_lone_retry_redelivery_does_not_desync(self):
        """A service re-ventilation/retry redelivers exactly ONE
        duplicate item; that must not read as a reset (it would advance
        the mirrored seed and kill the hit rate for the rest of the
        run)."""
        manager = self._manager(n=6, randomize=False)
        for item in (0, 1, 2):
            assert manager._advance_sweep_locked(item, 0) == 0
        assert manager._advance_sweep_locked(1, 0) == 0  # the retry
        for item in (3, 4, 5):
            assert manager._advance_sweep_locked(item, 0) == 0

    def test_sweep_detected_after_long_runs_by_epoch_regression(self):
        """A reset after MORE epochs than the bounded seen-sets retain
        (epoch 0's set evicted) must still be detected — via the
        epoch-regression rule — or a shuffled reader would mispredict
        forever after reset."""
        manager = self._manager(n=4, randomize=True)
        for epoch in range(8):  # > _SEEN_EPOCHS_MAX: epoch 0 set evicted
            for item in range(4):
                assert manager._advance_sweep_locked(item, epoch) == 0
        assert manager._advance_sweep_locked(0, 0) == 1
        # ...while ordinary cross-boundary pipelining (a late item from
        # the PREVIOUS epoch) never reads as a restart
        assert manager._advance_sweep_locked(1, 0) == 1
        manager._advance_sweep_locked(0, 1)
        assert manager._advance_sweep_locked(3, 0) == 1


# ---------------------------------------------------------------------------
# Exact parity vs the blocking-read oracle
# ---------------------------------------------------------------------------


class TestExactParity:
    @pytest.mark.parametrize('pool', ['thread', 'dummy', 'process',
                                      'service'])
    def test_row_multiset_parity_across_pools(self, scalar_url, pool):
        got = _read_ids(scalar_url, pool=pool, workers_count=2)
        oracle = _read_ids(scalar_url, oracle=True, pool=pool,
                           workers_count=2)
        assert got == oracle == list(range(400))

    def test_hits_recorded_and_pool_drains(self, scalar_url):
        got = _read_ids(scalar_url, num_epochs=2)
        assert got == sorted(list(range(400)) * 2)
        registry = T.get_registry()
        hits = registry.counter_value(readahead.READAHEAD_HITS)
        assert hits > 20  # 40 reads total; only cold-start misses allowed
        assert registry.counter_value(readahead.READAHEAD_BYTES) > 0
        assert registry.counter_value(
            readahead.READAHEAD_COALESCED_READS) > 0
        assert registry.counter_value('petastorm_tpu_stage_calls_total',
                                      stage='readahead_fetch') > 0
        gc.collect()
        used, _ = readahead.pool_status()
        assert used == 0

    def test_parity_with_pushdown_and_late_materialization(
            self, synthetic_dataset):
        """Shuffle + statistics pruning + the two-phase late-materialized
        read, served by the plane: row multiset AND heavy-column bytes
        must match the blocking oracle."""
        pred = FiltersPredicate([('id', 'in', (3, 31, 47, 99))])

        def rows(oracle):
            restore = _with_env({'PETASTORM_TPU_READAHEAD': '0'}
                                if oracle else {})
            try:
                with make_reader(synthetic_dataset.url,
                                 shuffle_row_groups=True,
                                 predicate=pred) as reader:
                    return sorted((r.id, r.image_png.tobytes(),
                                   r.matrix.tobytes()) for r in reader)
            finally:
                restore()

        got = rows(oracle=False)
        assert [g[0] for g in got] == [3, 31, 47, 99]
        assert got == rows(oracle=True)

    def test_reset_sweep_keeps_hitting(self, scalar_url):
        """reader.reset() advances the ventilator seed by the reset
        stride; the manager must detect the new sweep from the item
        stream and keep predicting (≥ one extra miss at the boundary is
        fine, going cold for the whole sweep is not)."""
        with make_batch_reader(scalar_url, reader_pool_type='thread',
                               shuffle_row_groups=True,
                               num_epochs=1) as reader:
            first = sorted(int(i) for b in reader for i in b.id)
            reader.reset()
            second = sorted(int(i) for b in reader for i in b.id)
        assert first == second == list(range(400))
        registry = T.get_registry()
        hits = registry.counter_value(readahead.READAHEAD_HITS)
        misses = registry.counter_value(readahead.READAHEAD_MISSES)
        assert hits + misses == 40
        assert hits >= 30


# ---------------------------------------------------------------------------
# Degrade: counted, never a wrong answer
# ---------------------------------------------------------------------------


class TestDegrade:
    def test_faulted_fetch_degrades_to_blocking(self, scalar_url):
        """Every prefetch read faulted (the io.read faultpoint's
        #readahead keys): the epoch must still deliver the exact
        multiset through the blocking path, with the degrade counted."""
        # match on the '#readahead' KEY SUFFIX, not the bare word — the
        # pytest tmp dir itself contains 'readahead', and a path match
        # would fault the worker's blocking reads too
        restore = _with_env(
            {'PETASTORM_TPU_FAULTS': 'io.read:error:1:match=#readahead'})
        try:
            got = _read_ids(scalar_url)
        finally:
            restore()
        assert got == list(range(400))
        registry = T.get_registry()
        assert registry.counter_value(readahead.READAHEAD_DEGRADED,
                                      reason='fetch-error') > 0
        assert registry.counter_value(readahead.READAHEAD_HITS) == 0

    def test_pool_exhaustion_degrades(self, scalar_url, monkeypatch):
        monkeypatch.setattr(readahead, 'pool_budget_bytes', lambda: 16)
        got = _read_ids(scalar_url)
        assert got == list(range(400))
        registry = T.get_registry()
        assert registry.counter_value(readahead.READAHEAD_DEGRADED,
                                      reason='pool-exhausted') > 0
        assert registry.counter_value(readahead.READAHEAD_HITS) == 0

    def test_caching_reader_ships_no_plan(self, scalar_url, tmp_path):
        """A caching reader must never prefetch (warm epochs read no
        storage); the decline is counted once, reader-side."""
        with make_batch_reader(scalar_url, reader_pool_type='thread',
                               shuffle_row_groups=False,
                               cache_type='decoded',
                               cache_location=str(tmp_path / 'cache'),
                               cache_size_limit=10 ** 8) as reader:
            delivered = sorted(int(i) for b in reader for i in b.id)
        assert delivered == list(range(400))
        registry = T.get_registry()
        assert registry.counter_value(readahead.READAHEAD_HITS) == 0
        assert registry.counter_value(readahead.READAHEAD_MISSES) == 0
        assert registry.counter_value(readahead.READAHEAD_DEGRADED,
                                      reason='cache') == 1

    def test_plan_decline_reasons_are_distinct(self, scalar_url):
        """A healthy footer with no prefetchable file columns (e.g. a
        partition-only predicate) must not read as 'no-footer' — the
        runbook sends those two cases down different paths."""
        from petastorm_tpu.etl.dataset_metadata import ParquetDatasetInfo
        info = ParquetDatasetInfo(scalar_url)
        plan = {'version': 1, 'items': [(info.file_paths[0], 0)],
                'randomize': False, 'seed': 0, 'iterations': 1,
                'exclude': [], 'workers': 1}
        manager = readahead.ReadaheadManager(info, plan)
        try:
            manager._columns = frozenset(['not_a_stored_column'])
            assert manager._plan_ranges(info.file_paths[0], 0) == \
                (None, 'no-columns')
            assert manager._plan_ranges('/nonexistent.parquet', 0) == \
                (None, 'no-footer')
            manager._columns = frozenset(['id'])
            planned, decline = manager._plan_ranges(info.file_paths[0], 0)
            assert decline is None and planned[1] == ['id']
        finally:
            manager.close()

    def test_oracle_knob_runs_zero_plane_state(self, scalar_url):
        got = _read_ids(scalar_url, oracle=True)
        assert got == list(range(400))
        registry = T.get_registry()
        assert registry.counter_value(readahead.READAHEAD_HITS) == 0
        assert registry.counter_value(readahead.READAHEAD_BYTES) == 0
        assert readahead.live_manager_count() == 0


# ---------------------------------------------------------------------------
# Satellites: parquet-file LRU, report section, health, ventilate seam
# ---------------------------------------------------------------------------


class TestParquetFileLru:
    def test_memo_is_bounded_and_reads_stay_exact(self, scalar_url,
                                                  monkeypatch):
        from petastorm_tpu import arrow_worker
        monkeypatch.setattr(arrow_worker, '_PARQUET_FILE_CACHE_MAX', 2)
        with make_batch_reader(scalar_url, reader_pool_type='thread',
                               workers_count=1,
                               shuffle_row_groups=True) as reader:
            delivered = sorted(int(i) for b in reader for i in b.id)
            workers = reader._pool._workers
            assert workers
            for worker in workers:
                assert len(worker._parquet_files) <= 2
        assert delivered == list(range(400))


class TestReportAndHealth:
    def test_report_section_and_rendering(self, scalar_url):
        _read_ids(scalar_url)
        report = T.pipeline_report()
        section = report['readahead']
        assert section['hits'] + section['misses'] > 0
        assert section['hit_share'] is not None
        assert section['coalesced_reads'] > 0
        assert section['mean_coalesced_bytes'] > 0
        assert section['pool_bytes'] == 0  # everything reclaimed
        text = T.format_pipeline_report(report)
        assert 'readahead:' in text

    def test_section_absent_without_activity(self):
        assert 'readahead' not in T.pipeline_report()

    def test_health_snapshot_shape(self, scalar_url):
        with make_batch_reader(scalar_url, reader_pool_type='thread',
                               shuffle_row_groups=False) as reader:
            next(iter(reader))
            health = reader._obs_health()
            assert health['ventilate_extra'] == 2
            snap = health['readahead']
            assert snap['enabled'] is True
            assert snap['managers'] == 1
            assert snap['depth'] >= 1


class TestVentilateExtraSeam:
    def test_live_bound_adjustment(self, scalar_url):
        with make_batch_reader(scalar_url, reader_pool_type='thread',
                               workers_count=2,
                               shuffle_row_groups=False) as reader:
            vent = reader._ventilator
            assert vent._current_max_queue_size() == 4
            assert reader.set_ventilate_extra(7) == 7
            assert reader.ventilate_extra == 7
            assert vent._current_max_queue_size() == 9
            # floor 1: the tuner can never strangle ventilation entirely
            assert reader.set_ventilate_extra(0) == 1


# ---------------------------------------------------------------------------
# The autotuner policies (readahead depth + ventilator in-flight)
# ---------------------------------------------------------------------------


class _FakeReader:
    def __init__(self, extra=2):
        self._extra = extra

    @property
    def ventilate_extra(self):
        return self._extra

    def set_ventilate_extra(self, extra):
        self._extra = max(1, int(extra))
        return self._extra


class _FakeLoader:
    def __init__(self, reader=None):
        self._stager = None
        self._prefetch = 2
        self._reader = reader or _FakeReader()

    @property
    def reader(self):
        return self._reader

    def _set_prefetch(self, depth):
        self._prefetch = depth
        return depth


def _window(verdict=None, io_rate=0.0):
    from petastorm_tpu.telemetry.timeseries import _IO_SECONDS_KEY
    return {'rates': {_IO_SECONDS_KEY: io_rate}, 'quantiles': {},
            'gauges': {}, 'producer_wait_s': 0.0, 'consumer_wait_s': 0.0,
            'verdict': verdict, 'dur_s': 1.0, 'throughput': None,
            'start': 0.0}


@pytest.fixture()
def live_manager(scalar_url):
    """One live manager so the depth policies have something to tune."""
    from petastorm_tpu.etl.dataset_metadata import ParquetDatasetInfo
    plan = {'version': 1, 'items': [('f', 0)], 'randomize': False,
            'seed': 0, 'iterations': 1, 'exclude': []}
    manager = readahead.ReadaheadManager(
        ParquetDatasetInfo(scalar_url), plan)
    yield manager
    manager.close()


class TestAutotunePolicies:
    def _tuner(self, loader=None):
        from petastorm_tpu.jax.autotune import StagingAutotuner
        return StagingAutotuner(loader or _FakeLoader(), window_s=10.0)

    def test_sustained_io_wait_deepens_readahead(self, live_manager):
        from petastorm_tpu.telemetry.stall import PRODUCER_BOUND
        tuner = self._tuner()
        base = readahead.current_depth()
        for _ in range(3):
            actions = tuner.observe(_window(verdict=PRODUCER_BOUND,
                                            io_rate=1.5))
        deepens = [a for a in actions
                   if a['action'] == 'deepen_readahead']
        assert deepens and deepens[0]['depth_to'] == base + 1
        assert readahead.current_depth() == base + 1
        tuner.close()
        assert readahead.current_depth() == base  # override died with it

    def test_io_wait_without_starving_consumer_does_nothing(
            self, live_manager):
        tuner = self._tuner()
        for _ in range(6):
            actions = tuner.observe(_window(verdict=None, io_rate=1.5))
        assert not any(a['action'] == 'deepen_readahead'
                       for a in actions)
        tuner.close()

    def test_pool_pressure_sheds_depth_to_the_knob_floor(
            self, live_manager, monkeypatch):
        from petastorm_tpu.telemetry.stall import PRODUCER_BOUND
        tuner = self._tuner()
        base = readahead.current_depth()
        for _ in range(3):  # deepen first: the knob width is the floor
            tuner.observe(_window(verdict=PRODUCER_BOUND, io_rate=1.5))
        assert readahead.current_depth() == base + 1
        monkeypatch.setattr(readahead, 'pool_status',
                            lambda: (95, 100))
        for _ in range(3):
            actions = tuner.observe(_window())
        sheds = [a for a in actions if a['action'] == 'shed_readahead']
        assert sheds and sheds[0]['depth_to'] == base
        # at the knob's own width the shed stops: the static
        # configuration is the floor, never tuned below
        for _ in range(6):
            actions = tuner.observe(_window())
        assert not any(a['action'] == 'shed_readahead' for a in actions)
        assert readahead.current_depth() == base
        tuner.close()

    def test_no_live_manager_means_no_depth_decisions(self):
        from petastorm_tpu.telemetry.stall import PRODUCER_BOUND
        tuner = self._tuner()
        for _ in range(3):
            actions = tuner.observe(_window(verdict=PRODUCER_BOUND,
                                            io_rate=1.5))
        assert not any(a['action'] == 'deepen_readahead'
                       for a in actions)
        tuner.close()

    def test_inflight_raises_and_lowers_with_the_verdict(self):
        from petastorm_tpu.telemetry.stall import (
            CONSUMER_BOUND, PRODUCER_BOUND,
        )
        reader = _FakeReader(extra=2)
        tuner = self._tuner(_FakeLoader(reader))
        for _ in range(3):
            actions = tuner.observe(_window(verdict=PRODUCER_BOUND))
        raises = [a for a in actions if a['action'] == 'raise_inflight']
        assert raises and reader.ventilate_extra == 3
        for _ in range(3):
            actions = tuner.observe(_window(verdict=CONSUMER_BOUND))
        lowers = [a for a in actions if a['action'] == 'lower_inflight']
        assert lowers and reader.ventilate_extra == 2
        # never below the construction-time baseline
        for _ in range(6):
            tuner.observe(_window(verdict=CONSUMER_BOUND))
        assert reader.ventilate_extra == 2
        tuner.close()

    def test_decisions_land_in_report_ring(self, live_manager):
        from petastorm_tpu.jax import autotune
        from petastorm_tpu.telemetry.stall import PRODUCER_BOUND
        tuner = self._tuner()
        for _ in range(3):
            tuner.observe(_window(verdict=PRODUCER_BOUND, io_rate=1.5))
        actions = {d['action'] for d in autotune.recent_decisions()}
        assert 'deepen_readahead' in actions
        assert 'raise_inflight' in actions
        report = T.pipeline_report()
        assert report['staging_autotune']['total'] >= 2
        tuner.close()


# ---------------------------------------------------------------------------
# Sanitizer compatibility
# ---------------------------------------------------------------------------


class TestSanitize:
    def test_parity_and_canaries_under_sanitize(self, scalar_url):
        restore = _with_env({'PETASTORM_TPU_SANITIZE': '1'})
        try:
            got = _read_ids(scalar_url)
        finally:
            restore()
        assert got == list(range(400))
        from petastorm_tpu import sanitizer
        assert not [v for v in sanitizer.violations()
                    if v['kind'] == 'readahead-canary']
        gc.collect()
        used, _ = readahead.pool_status()
        assert used == 0
