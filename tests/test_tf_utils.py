"""TF bridge tests (reference: ``tests/test_tf_utils.py``,
``test_tf_dataset.py``)."""

import numpy as np
import pytest

tf = pytest.importorskip('tensorflow')

from petastorm_tpu.ngram import NGram  # noqa: E402
from petastorm_tpu.reader import make_batch_reader, make_reader  # noqa: E402
from petastorm_tpu.tf_utils import make_petastorm_dataset, tf_tensors  # noqa: E402

_FIELDS = ['^id$', '^image_png$', '^decimal$', '^matrix_uint16$']


def test_row_dataset(synthetic_dataset):
    with make_reader(synthetic_dataset.url, schema_fields=_FIELDS,
                     shuffle_row_groups=False, num_epochs=1) as reader:
        dataset = make_petastorm_dataset(reader)
        rows = list(dataset.take(5))
    expected = {r['id']: r for r in synthetic_dataset.data}
    for row in rows:
        rid = int(row.id)
        np.testing.assert_array_equal(np.asarray(row.image_png),
                                      expected[rid]['image_png'])
        # uint16 promoted to int32, decimal to string
        assert row.matrix_uint16.dtype == tf.int32
        assert row.decimal.dtype == tf.string
        assert row.decimal.numpy().decode() == str(expected[rid]['decimal'])


def test_row_dataset_static_shapes(synthetic_dataset):
    with make_reader(synthetic_dataset.url, schema_fields=['^image_png$'],
                     num_epochs=1) as reader:
        dataset = make_petastorm_dataset(reader)
        spec = dataset.element_spec
    assert tuple(spec.image_png.shape) == (16, 32, 3)


def test_batch_dataset_scalar_store(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, shuffle_row_groups=False,
                           num_epochs=1) as reader:
        dataset = make_petastorm_dataset(reader)
        ids, strings, stamps = [], [], []
        for el in dataset:
            ids.extend(el.id.numpy().tolist())
            strings.extend(s.decode() for s in el.string.numpy())
            stamps.extend(el.timestamp.numpy().tolist())
    assert sorted(ids) == list(range(100))
    assert 'hello_0' in strings
    # datetimes land as int64 nanoseconds
    assert all(isinstance(s, int) for s in stamps)


def test_rebatching(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, shuffle_row_groups=False,
                           num_epochs=1) as reader:
        dataset = make_petastorm_dataset(reader).unbatch().batch(
            16, drop_remainder=True)
        sizes = [len(el.id) for el in dataset]
    assert sizes == [16] * 6


def test_ngram_dataset(synthetic_dataset):
    ngram = NGram(fields={0: ['^id$'], 1: ['^id$', '^sensor_name$']},
                  delta_threshold=1, timestamp_field='^id$')
    with make_reader(synthetic_dataset.url, ngram=ngram,
                     shuffle_row_groups=False, num_epochs=1) as reader:
        dataset = make_petastorm_dataset(reader)
        windows = list(dataset.take(4))
    for w in windows:
        assert set(w.keys()) == {0, 1}
        assert int(w[1].id) == int(w[0].id) + 1
        assert not hasattr(w[0], 'sensor_name')


def test_sanitize_tf_types_unit():
    # reference: test_sanitize_field_tf_types (:72) + decimal/datetime cases
    import datetime
    from decimal import Decimal
    from petastorm_tpu.tf_utils import _sanitize_field_tf_types
    assert _sanitize_field_tf_types(Decimal('1.25')) == '1.25'
    ns = _sanitize_field_tf_types(datetime.date(2020, 1, 2))
    assert ns == np.datetime64('2020-01-02').astype('datetime64[ns]').astype(np.int64)
    arr = np.array([Decimal('1.5'), Decimal('2.5')], dtype=object)
    assert _sanitize_field_tf_types(arr).tolist() == ['1.5', '2.5']
    dt64 = np.array(['2020-01-01', '2020-01-02'], dtype='datetime64[D]')
    out = _sanitize_field_tf_types(dt64)
    assert out.dtype == np.int64
    with pytest.raises(RuntimeError, match='Null'):
        _sanitize_field_tf_types(None)


def test_tf_dtype_map_promotions():
    # reference: test_uint16_promotion_to_int32 (:108) and the dtype map
    import tensorflow as tf
    from decimal import Decimal
    from petastorm_tpu.tf_utils import _tf_dtype
    from petastorm_tpu.unischema import UnischemaField

    def dtype_of(np_dtype):
        return _tf_dtype(tf, UnischemaField('f', np_dtype, (), None, False))

    assert dtype_of(np.uint16) == tf.int32
    assert dtype_of(np.uint32) == tf.int64
    assert dtype_of(np.str_) == tf.string
    assert dtype_of(Decimal) == tf.string
    assert dtype_of(np.dtype('datetime64[ns]')) == tf.int64
    assert dtype_of(np.float32) == tf.float32


def test_dataset_reiteration_guard(synthetic_dataset):
    # reference: the no-repeat guard (tf_utils.py:367-373)
    import tensorflow as tf
    with make_reader(synthetic_dataset.url, num_epochs=1,
                     schema_fields=['^id$']) as reader:
        dataset = make_petastorm_dataset(reader)
        assert sum(1 for _ in dataset) == 100
        with pytest.raises(tf.errors.OpError, match='Multiple iterations'):
            for _ in dataset:
                pass


def test_batch_dataset_decimal_column(tmp_path):
    # decimal columns must reach TF as strings through the batched bridge
    from decimal import Decimal
    import pyarrow as pa
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import write_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('Dec', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('price', Decimal, (),
                       ScalarCodec(pa.decimal128(10, 2)), False),
    ])
    url = 'file://' + str(tmp_path / 'dec')
    write_dataset(url, schema, [{'id': i, 'price': Decimal('3.14')}
                                for i in range(8)], rowgroup_size_rows=4)
    with make_reader(url, shuffle_row_groups=False) as reader:
        dataset = make_petastorm_dataset(reader)
        row = next(iter(dataset))
    assert row.price.numpy() == b'3.14'


def test_tf_tensors_shuffling_queue(synthetic_dataset):
    # reference: test_shuffling_queue (:210) — with a shuffle queue the rows
    # arrive decorrelated; the full multiset is preserved
    # dummy pool: the unshuffled baseline is strictly ordered, so only the
    # tf-side shuffle queue can decorrelate the stream
    with make_reader(synthetic_dataset.url, schema_fields=['^id$'],
                     shuffle_row_groups=False, reader_pool_type='dummy',
                     num_epochs=1) as reader:
        ids = [int(tf_tensors(reader, shuffling_queue_capacity=50).id)
               for _ in range(100)]
    assert sorted(ids) == list(range(100))
    assert ids != list(range(100))


def test_tf_tensors_capacity_change_rejected(synthetic_dataset):
    with make_reader(synthetic_dataset.url, schema_fields=['^id$'],
                     num_epochs=None) as reader:
        tf_tensors(reader, shuffling_queue_capacity=10)
        with pytest.raises(ValueError, match='cannot change'):
            tf_tensors(reader, shuffling_queue_capacity=20)


def test_tf_tensors_shim(synthetic_dataset):
    with make_reader(synthetic_dataset.url, schema_fields=['^id$'],
                     shuffle_row_groups=False, num_epochs=1) as reader:
        row = tf_tensors(reader)
    assert int(row.id) in range(100)


def test_training_loop_consumes_dataset(scalar_dataset):
    """A tiny keras regression fit over the bridge (smoke)."""
    with make_batch_reader(scalar_dataset.url, shuffle_row_groups=False,
                           num_epochs=1) as reader:
        dataset = (make_petastorm_dataset(reader)
                   .map(lambda el: (tf.cast(el.id, tf.float32)[:, None],
                                    tf.cast(el.float64, tf.float32)))
                   .unbatch().batch(25))
        model = tf.keras.Sequential([tf.keras.layers.Dense(1)])
        model.compile(optimizer='sgd', loss='mse')
        model.fit(dataset, epochs=1, verbose=0)


def test_shuffling_queue_size_tensor(synthetic_dataset):
    import tensorflow as tf

    from petastorm_tpu.reader import make_reader
    from petastorm_tpu.tf_utils import (
        RANDOM_SHUFFLING_QUEUE_SIZE, shuffling_queue_size_tensor,
    )
    assert RANDOM_SHUFFLING_QUEUE_SIZE == 'random_shuffling_queue_size'
    # wiring check on a stub with FIXED gauges (a live pool's queues move
    # between reads - racy asserts); the live-reader path is smoke-tested
    # for type/evaluability only
    class _StubReader:
        diagnostics = {'stage_queue_depth': 2, 'output_queue_size': 3}

    stub_size = shuffling_queue_size_tensor(_StubReader())
    assert int(stub_size.numpy()) == 5
    with make_reader(synthetic_dataset.url, schema_fields=['^id$'],
                     num_epochs=None) as reader:
        next(reader)
        size = shuffling_queue_size_tensor(reader)
        assert size.dtype == tf.int64
        assert int(size.numpy()) >= 0


def test_buffered_item_count_gauge_sources():
    from petastorm_tpu.tf_utils import _buffered_item_count
    # explicit queue depths win (thread pool / JaxLoader staging)
    assert _buffered_item_count({'stage_queue_depth': 2,
                                 'output_queue_size': 3}) == 5
    # process pool: in-flight = ventilated - processed
    assert _buffered_item_count({'items_ventilated': 7,
                                 'items_processed': 4}) == 3
    assert _buffered_item_count({}) == 0
