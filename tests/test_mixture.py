"""Streaming mixture engine: deterministic multi-dataset interleave +
token-budget sequence packing (``petastorm_tpu/mixture/``).

Covers the four subsystem layers plus the acceptance oracles:

* arithmetic interleave — source at position ``p`` is a pure function of
  ``(seed, weights, p)``, with a hard realized-ratio deviation bound;
* ``SequencePacker`` — token conservation, loss masks, segment ids,
  bounded open-bin set, split-tail carry, JSON checkpoint state;
* elastic checkpoint/resume — mid-stream resume parity across pool
  flavors, plus the N→M reshard oracle: N shard states merged and
  restored onto M consumers reproduce the uninterrupted global packed
  stream bit-identically;
* plane integration — identical streams with the readahead plane on and
  off (``PETASTORM_TPU_READAHEAD=0`` is the exact-parity oracle), and
  through the daemonized decode service.
"""

import json

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu import telemetry as T
from petastorm_tpu.mixture import (InterleaveSchedule, MixtureBatchReader,
                                   MixtureSource, MixtureSpec, MixtureStream,
                                   SequencePacker, build_source_readers,
                                   merge_mixture_states, realized_deviation)

ROW_COLS = ('tokens', 'loss_mask', 'segment_ids')


@pytest.fixture(scope='session')
def mix_datasets(tmp_path_factory):
    """Three plain-parquet token corpora of different sizes/lengths."""
    root = tmp_path_factory.mktemp('mixture')
    urls = {}
    for name, num_files, seed in [('a', 3, 1), ('b', 2, 2), ('c', 2, 3)]:
        d = root / name
        d.mkdir()
        rng = np.random.RandomState(seed)
        row = 0
        for f in range(num_files):
            tokens = [rng.randint(1, 1000, size=rng.randint(1, 50)).tolist()
                      for _ in range(10)]
            table = pa.table({'row_id': np.arange(row, row + 10),
                              'tokens': tokens})
            pq.write_table(table, str(d / ('part-%d.parquet' % f)),
                           row_group_size=5)
            row += 10
        urls[name] = 'file://' + str(d)
    return urls


def _spec(urls, sources=('a', 'b'), weights=(3, 1), seed=11, seq_len=64,
          **kw):
    return MixtureSpec([MixtureSource(n, w, url=urls[n])
                        for n, w in zip(sources, weights)],
                       seed=seed, seq_len=seq_len, **kw)


def _drain(stream):
    try:
        return list(stream)
    finally:
        stream.stop()
        stream.join()


def _rows_equal(a, b):
    return all(np.array_equal(a[k], b[k]) for k in ROW_COLS)


def _streams_equal(xs, ys):
    return len(xs) == len(ys) and all(_rows_equal(a, b)
                                      for a, b in zip(xs, ys))


# -- layer 1: arithmetic interleave ------------------------------------------


class TestInterleave:
    def test_position_is_pure_function(self):
        sched = InterleaveSchedule([3, 1, 1], seed=7)
        live = [sched.next() for _ in range(100)]
        assert live == InterleaveSchedule.order([3, 1, 1], seed=7, start=0,
                                                k=100)
        fresh = InterleaveSchedule([3, 1, 1], seed=7)
        assert [fresh.source_at(p) for p in (0, 5, 42, 99)] == \
            [live[p] for p in (0, 5, 42, 99)]

    def test_peek_does_not_advance(self):
        sched = InterleaveSchedule([2, 1], seed=0)
        ahead = sched.peek(5)
        assert [sched.next() for _ in range(5)] == ahead

    def test_windowed_order_matches_full_order(self):
        full = InterleaveSchedule.order([5, 2, 3], seed=3, start=0, k=60)
        assert InterleaveSchedule.order([5, 2, 3], seed=3, start=20,
                                        k=25) == full[20:45]

    @pytest.mark.parametrize('weights', [[3, 1], [1, 1, 1], [5, 2, 3],
                                         [0.7, 0.2, 0.1]])
    def test_realized_ratio_deviation_bound(self, weights):
        order = InterleaveSchedule.order(weights, seed=13, start=0, k=400)
        # the smooth round-robin guarantee: per-source realized counts
        # never stray more than one credit from the exact share
        assert realized_deviation(order, weights) <= 1.0 + 1e-9

    def test_seed_permutes_schedule(self):
        a = InterleaveSchedule.order([2, 1, 1], seed=0, start=0, k=50)
        others = [InterleaveSchedule.order([2, 1, 1], seed=s, start=0, k=50)
                  for s in range(1, 8)]
        assert any(o != a for o in others)
        # whatever the seed permutes, the smoothness bound still holds
        assert all(realized_deviation(o, [2, 1, 1]) <= 1.0 + 1e-9
                   for o in others)

    def test_state_json_roundtrip_continues_exactly(self):
        sched = InterleaveSchedule([3, 1, 2], seed=5)
        for _ in range(17):
            sched.next()
        state = json.loads(json.dumps(sched.state_dict()))
        restored = InterleaveSchedule.from_state([3, 1, 2], 5, state)
        assert [sched.next() for _ in range(40)] == \
            [restored.next() for _ in range(40)]

    def test_bad_weights_rejected(self):
        with pytest.raises(ValueError):
            InterleaveSchedule([])
        with pytest.raises(ValueError):
            InterleaveSchedule([1, -1])
        with pytest.raises(ValueError):
            InterleaveSchedule([0, 0])


# -- layer 2: token-budget packer --------------------------------------------


def _docs(n, seed=0, lo=1, hi=50):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 1000, size=rng.randint(lo, hi)).tolist()
            for _ in range(n)]


class TestSequencePacker:
    def test_rows_are_fixed_shape_with_masks_and_segments(self):
        packer = SequencePacker(seq_len=32)
        rows = []
        for doc in _docs(20, seed=1, hi=20):
            rows.extend(packer.feed(doc))
        rows.extend(packer.flush())
        for row in rows:
            assert row['tokens'].shape == (32,)
            assert row['loss_mask'].shape == (32,)
            assert row['segment_ids'].shape == (32,)
            # padding carries mask 0 / segment 0, real tokens mask 1
            pad = row['loss_mask'] == 0
            assert np.all(row['segment_ids'][pad] == 0)
            assert np.all(row['tokens'][pad] == 0)
            assert np.all(row['segment_ids'][~pad] >= 1)
            # segments are 1-based and non-decreasing within a row
            seg = row['segment_ids'][~pad]
            assert np.all(np.diff(seg) >= 0)

    def test_token_conservation(self):
        docs = _docs(30, seed=2)
        packer = SequencePacker(seq_len=48)
        rows = [r for d in docs for r in packer.feed(d)]
        rows.extend(packer.flush())
        total = sum(len(d) for d in docs)
        assert sum(int(r['loss_mask'].sum()) for r in rows) == total
        assert packer.stats['tokens'] == total
        assert packer.stats['rows'] == len(rows)
        assert packer.stats['docs'] == len(docs)

    def test_overlong_doc_splits_across_rows(self):
        packer = SequencePacker(seq_len=16)
        doc = list(range(1, 41))  # 40 tokens -> 2 full rows + carry of 8
        rows = packer.feed(doc)
        assert len(rows) == 2
        assert packer.stats['carried_tokens'] == 8
        rows.extend(packer.flush())
        got = np.concatenate([r['tokens'][r['loss_mask'] == 1]
                              for r in rows])
        assert got.tolist() == doc
        assert packer.stats['split_docs'] == 1

    def test_open_bin_bound_and_first_fit(self):
        packer = SequencePacker(seq_len=10, open_bins=2)
        for doc in _docs(50, seed=3, hi=10):
            packer.feed(doc)
            assert packer.stats['open_bins'] <= 2
        packer.flush()
        assert packer.stats['open_bins'] == 0

    def test_open_bins_knob_default(self, monkeypatch):
        monkeypatch.setenv('PETASTORM_TPU_MIXTURE_OPEN_BINS', '7')
        T.refresh()
        try:
            assert SequencePacker(seq_len=8)._open_bins == 7
        finally:
            monkeypatch.delenv('PETASTORM_TPU_MIXTURE_OPEN_BINS')
            T.refresh()

    def test_state_json_roundtrip_mid_stream(self):
        docs = _docs(40, seed=4)
        a = SequencePacker(seq_len=32)
        for d in docs[:25]:
            a.feed(d)
        state = json.loads(json.dumps(a.state_dict()))
        b = SequencePacker(seq_len=32)
        b.load_state_dict(state)
        rows_a = [r for d in docs[25:] for r in a.feed(d)] + a.flush()
        rows_b = [r for d in docs[25:] for r in b.feed(d)] + b.flush()
        assert _streams_equal(rows_a, rows_b)
        assert a.stats == b.stats

    def test_fill_ratio_reported(self):
        packer = SequencePacker(seq_len=64)
        for d in _docs(60, seed=5):
            packer.feed(d)
        packer.flush()
        stats = packer.stats
        assert 0.5 < stats['fill_ratio'] <= 1.0
        assert stats['padding_tokens'] == \
            stats['rows'] * 64 - stats['tokens']


# -- spec --------------------------------------------------------------------


class TestMixtureSpec:
    def test_source_requires_exactly_one_of_url_or_factory(self):
        with pytest.raises(ValueError):
            MixtureSource('x', 1)
        with pytest.raises(ValueError):
            MixtureSource('x', 1, url='file:///d',
                          reader_factory=lambda: None)

    def test_fingerprint_tracks_identity(self, mix_datasets):
        assert _spec(mix_datasets).fingerprint() == \
            _spec(mix_datasets).fingerprint()
        assert _spec(mix_datasets).fingerprint() != \
            _spec(mix_datasets, weights=(1, 1)).fingerprint()
        assert _spec(mix_datasets).fingerprint() != \
            _spec(mix_datasets, seed=12).fingerprint()


# -- layers 3+4: stream determinism, resume, reshard, plane parity -----------


class TestStreamDeterminism:
    def test_cross_pool_identical_streams(self, mix_datasets):
        oracle = _drain(MixtureStream(_spec(mix_datasets),
                                      reader_pool_type='dummy'))
        assert oracle, 'mixture produced no packed rows'
        for workers in (2, 4):
            got = _drain(MixtureStream(_spec(mix_datasets),
                                       reader_pool_type='thread',
                                       workers_count=workers))
            assert _streams_equal(oracle, got)

    def test_three_source_stream_and_ratio(self, mix_datasets):
        spec = _spec(mix_datasets, sources=('a', 'b', 'c'),
                     weights=(3, 1, 1), seed=2)
        stream = MixtureStream(spec, reader_pool_type='thread',
                               workers_count=3)
        rows = _drain(stream)
        assert rows
        docs = stream.source_doc_counts
        assert sum(docs) > 0
        # source a holds a 0.6 share; the interleave keeps the realized
        # ratio within one credit of exact until a source drains
        assert docs[0] > docs[1] and docs[0] > docs[2]

    def test_readahead_on_off_parity(self, mix_datasets):
        from tests.test_readahead import _with_env
        restore = _with_env({'PETASTORM_TPU_READAHEAD': '0'})
        try:
            oracle = _drain(MixtureStream(_spec(mix_datasets, seed=21),
                                          reader_pool_type='thread',
                                          workers_count=3))
        finally:
            restore()
        restore = _with_env({'PETASTORM_TPU_READAHEAD': '1'})
        try:
            live = _drain(MixtureStream(_spec(mix_datasets, seed=21),
                                        reader_pool_type='thread',
                                        workers_count=3))
        finally:
            restore()
        assert _streams_equal(oracle, live)

    @pytest.mark.slow
    def test_process_pool_identical_stream(self, mix_datasets):
        oracle = _drain(MixtureStream(_spec(mix_datasets),
                                      reader_pool_type='dummy'))
        got = _drain(MixtureStream(_spec(mix_datasets),
                                   reader_pool_type='process',
                                   workers_count=2))
        assert _streams_equal(oracle, got)


class TestResume:
    def test_resume_parity_across_pool_shapes(self, mix_datasets):
        oracle = _drain(MixtureStream(_spec(mix_datasets),
                                      reader_pool_type='dummy'))
        for cut in (1, len(oracle) // 2, len(oracle) - 2):
            first = MixtureStream(_spec(mix_datasets),
                                  reader_pool_type='thread',
                                  workers_count=4)
            head = [next(first) for _ in range(cut)]
            state = json.loads(json.dumps(first.state_dict()))
            first.stop()
            first.join()
            second = MixtureStream(_spec(mix_datasets),
                                   reader_pool_type='thread',
                                   workers_count=3)
            second.load_state_dict(state)
            tail = _drain(second)
            assert _streams_equal(head + tail, oracle), 'cut=%d' % cut

    @pytest.mark.parametrize('n_from,n_to,steps', [(2, 3, 3), (3, 2, 2),
                                                   (1, 2, 4)])
    def test_reshard_oracle_bit_identical(self, mix_datasets, n_from, n_to,
                                          steps):
        """The acceptance oracle: N shard states merged and restored on
        M consumers stitch back into the uninterrupted global stream."""
        spec_kw = dict(sources=('a', 'b'), weights=(3, 1), seed=11)
        oracle = _drain(MixtureStream(_spec(mix_datasets, **spec_kw),
                                      reader_pool_type='dummy'))
        states, pre = [], {}
        for r in range(n_from):
            s = MixtureStream(_spec(mix_datasets, **spec_kw),
                              reader_pool_type='thread', workers_count=2,
                              cur_shard=r, shard_count=n_from)
            pre[r] = [next(s) for _ in range(steps)]
            states.append(json.loads(json.dumps(s.state_dict())))
            s.stop()
            s.join()
        merged = merge_mixture_states(states)
        resume = merged['resume_ordinal']
        stitched = [None] * len(oracle)
        for r in range(n_from):
            for i, row in enumerate(pre[r]):
                stitched[r + i * n_from] = row
        for r in range(n_to):
            s = MixtureStream(_spec(mix_datasets, **spec_kw),
                              reader_pool_type='thread', workers_count=2,
                              cur_shard=r, shard_count=n_to)
            s.load_state_dict(json.loads(json.dumps(merged)))
            post = _drain(s)
            ordinals = [o for o in range(resume, len(oracle))
                        if o % n_to == r]
            assert len(ordinals) == len(post)
            for o, row in zip(ordinals, post):
                stitched[o] = row
        assert all(x is not None for x in stitched)
        assert _streams_equal(oracle, stitched)

    def test_merge_rejects_mismatched_families(self, mix_datasets):
        s = MixtureStream(_spec(mix_datasets), reader_pool_type='dummy',
                          cur_shard=0, shard_count=2)
        next(s)
        state = s.state_dict()
        s.stop()
        s.join()
        with pytest.raises(ValueError, match='mixture states'):
            merge_mixture_states([])
        other = dict(state, mixture='0' * 16)
        with pytest.raises(ValueError, match='different mixtures'):
            merge_mixture_states([state, other])
        with pytest.raises(ValueError, match='shard'):
            merge_mixture_states([state, dict(state, shard_count=3)])

    def test_fingerprint_guard_on_restore(self, mix_datasets):
        s = MixtureStream(_spec(mix_datasets), reader_pool_type='dummy')
        next(s)
        state = s.state_dict()
        s.stop()
        s.join()
        t = MixtureStream(_spec(mix_datasets, weights=(1, 1)),
                          reader_pool_type='dummy')
        try:
            with pytest.raises(ValueError, match='fingerprint'):
                t.load_state_dict(state)
        finally:
            t.stop()
            t.join()


# -- plane integration: jax loader + daemonized service ----------------------


class TestLoaderIntegration:
    def test_make_jax_loader_mixture_batches(self, mix_datasets):
        from petastorm_tpu.jax import make_jax_loader
        spec = _spec(mix_datasets, seq_len=48)
        loader = make_jax_loader(None, mixture=spec, batch_size=4,
                                 reader_pool_type='thread',
                                 workers_count=2)
        try:
            batch = next(iter(loader))
            for col in ROW_COLS:
                assert np.asarray(batch[col]).shape == (4, 48)
        finally:
            loader.stop()

    def test_mixture_rejects_conflicting_loader_args(self, mix_datasets):
        from petastorm_tpu.jax import make_jax_loader
        spec = _spec(mix_datasets)
        with pytest.raises(ValueError):
            make_jax_loader(mix_datasets['a'], mixture=spec, batch_size=2)
        with pytest.raises(ValueError):
            make_jax_loader(None, mixture=spec, batch_size=2,
                            inmemory_cache_all=True)

    def test_adapter_requires_seq_len(self, mix_datasets):
        spec = _spec(mix_datasets, seq_len=None)
        stream = MixtureStream(spec, reader_pool_type='dummy')
        try:
            with pytest.raises(ValueError, match='seq_len'):
                MixtureBatchReader(stream)
        finally:
            stream.stop()
            stream.join()


@pytest.mark.service
def test_daemon_service_path_bit_identical(mix_datasets, monkeypatch):
    """Acceptance: the mixture routed through a standing decode daemon
    (per-source QoS jobs) delivers the identical packed stream as the
    local thread pool — including across a mid-stream N→M reshard."""
    from petastorm_tpu.service.daemon import ServiceDaemon
    spec_kw = dict(sources=('a', 'b'), weights=(3, 1), seed=11)
    oracle = _drain(MixtureStream(_spec(mix_datasets, **spec_kw),
                                  reader_pool_type='thread',
                                  workers_count=2))
    daemon = ServiceDaemon('tcp://127.0.0.1:0', initial_workers=2)
    daemon.start()

    def daemon_stream(**stream_kw):
        readers = build_source_readers(_spec(mix_datasets, **spec_kw),
                                       reader_pool_type='service')
        return MixtureStream(_spec(mix_datasets, **spec_kw),
                             readers=readers, **stream_kw)

    try:
        monkeypatch.setenv('PETASTORM_TPU_SERVICE_DAEMON', daemon.endpoint)
        got = _drain(daemon_stream())
        assert _streams_equal(oracle, got)
        jobs = daemon.dispatcher.stats()['jobs_seen']
        assert jobs >= 2, 'each source should register its own QoS job'

        # N→M reshard through the daemon: 2 shard states cut at the same
        # step count, merged, restored onto 1 consumer — bit-identical
        steps = 3
        states, pre = [], {}
        for r in range(2):
            s = daemon_stream(cur_shard=r, shard_count=2)
            pre[r] = [next(s) for _ in range(steps)]
            states.append(json.loads(json.dumps(s.state_dict())))
            s.stop()
            s.join()
        merged = merge_mixture_states(states)
        resume = merged['resume_ordinal']
        assert resume == 2 * steps
        s = daemon_stream(cur_shard=0, shard_count=1)
        s.load_state_dict(json.loads(json.dumps(merged)))
        post = _drain(s)
        stitched = [None] * len(oracle)
        for r in range(2):
            for i, row in enumerate(pre[r]):
                stitched[r + i * 2] = row
        for o, row in zip(range(resume, len(oracle)), post):
            stitched[o] = row
        assert all(x is not None for x in stitched)
        assert _streams_equal(oracle, stitched)
    finally:
        monkeypatch.delenv('PETASTORM_TPU_SERVICE_DAEMON', raising=False)
        daemon.stop()


def test_stream_threads_per_source_trace_context(mix_datasets, monkeypatch):
    """PR 19 satellite: with tracing armed, every document pull joins
    its row-group's lifeline as a ``mixture_pull`` event on a
    per-source track — the critical-path engine sees the mixture side,
    not just the underlying readers."""
    monkeypatch.setenv('PETASTORM_TPU_TRACE', '1')
    monkeypatch.setenv('PETASTORM_TPU_TRACE_SAMPLE', '1')
    T.reset_for_tests()
    try:
        from petastorm_tpu.telemetry import recorder
        _drain(MixtureStream(_spec(mix_datasets),
                             reader_pool_type='thread', workers_count=1))
        pulls = [e for e in recorder.get_recorder().snapshot()
                 if e.get('name') == 'mixture_pull']
        assert pulls, 'no mixture_pull events reached the recorder'
        tracks = {e.get('tid') for e in pulls}
        # two sources in the spec -> two distinct mixture-side tracks
        assert {'mixture-src-0', 'mixture-src-1'} <= tracks, tracks
        assert all((e.get('args') or {}).get('trace_id') for e in pulls)
    finally:
        monkeypatch.delenv('PETASTORM_TPU_TRACE', raising=False)
        monkeypatch.delenv('PETASTORM_TPU_TRACE_SAMPLE', raising=False)
        T.reset_for_tests()
