"""End-to-end reader tests, parametrized over pool flavors and factories
(parity model: petastorm/tests/test_end_to_end.py, 872 LoC)."""

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.errors import NoDataAvailableError
from petastorm_tpu.predicates import in_lambda, in_pseudorandom_split, in_reduce, in_set
from petastorm_tpu.transform import TransformSpec
from tests.test_common import TestSchema

# Full matrix including the spawned-ZMQ process pool: the reference
# parametrizes everything over dummy/thread/process
# (petastorm/tests/test_end_to_end.py:42-58); the process pool crosses a
# dill/ZMQ serialization boundary, which is exactly where pickling bugs live.
POOLS = ['thread', 'dummy', 'process']


def _fields_by_id(rows):
    return {r['id']: r for r in rows}


def _check_simple_row(row, expected):
    np.testing.assert_array_equal(row.image_png, expected['image_png'])
    np.testing.assert_array_equal(row.matrix, expected['matrix'])
    np.testing.assert_array_equal(row.matrix_uint16, expected['matrix_uint16'])
    assert row.decimal == expected['decimal']
    assert row.partition_key == expected['partition_key']
    if expected['matrix_nullable'] is None:
        assert row.matrix_nullable is None
    else:
        np.testing.assert_array_equal(row.matrix_nullable, expected['matrix_nullable'])


@pytest.mark.parametrize('pool', POOLS)
def test_simple_read_all_fields(synthetic_dataset, pool):
    with make_reader(synthetic_dataset.url, reader_pool_type=pool,
                     workers_count=2) as reader:
        rows = list(reader)
    assert len(rows) == 100
    expected = _fields_by_id(synthetic_dataset.data)
    for row in rows[:20]:
        _check_simple_row(row, expected[row.id])


@pytest.mark.parametrize('pool', POOLS)
def test_column_projection_exact_and_regex(synthetic_dataset, pool):
    with make_reader(synthetic_dataset.url, reader_pool_type=pool,
                     schema_fields=[TestSchema.id, 'matrix.*']) as reader:
        row = next(reader)
    assert set(row._fields) == {'id', 'matrix', 'matrix_uint16', 'matrix_string',
                                'matrix_nullable'}


def test_unknown_field_in_projection_raises(synthetic_dataset):
    from petastorm_tpu.unischema import UnischemaField
    foreign = UnischemaField('not_there', np.int32, ())
    with pytest.raises(ValueError):
        make_reader(synthetic_dataset.url, schema_fields=[foreign])


@pytest.mark.parametrize('pool', POOLS)
def test_shuffle_changes_order_and_seed_reproduces(synthetic_dataset, pool):
    def read_ids(shuffle, seed):
        with make_reader(synthetic_dataset.url, reader_pool_type=pool,
                         workers_count=1, shuffle_row_groups=shuffle,
                         seed=seed) as reader:
            return [r.id for r in reader]

    unshuffled = read_ids(False, 0)
    assert unshuffled == read_ids(False, 0)
    shuffled = read_ids(True, 5)
    assert sorted(shuffled) == sorted(unshuffled)
    assert shuffled != unshuffled
    assert shuffled == read_ids(True, 5)  # deterministic given seed
    assert shuffled != read_ids(True, 6)


def test_shuffle_row_drop_partitions(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     shuffle_row_drop_partitions=3) as reader:
        ids = [r.id for r in reader]
    assert sorted(ids) == list(range(100))  # every row exactly once


@pytest.mark.parametrize('pool', POOLS)
def test_predicate_on_worker(synthetic_dataset, pool):
    with make_reader(synthetic_dataset.url, reader_pool_type=pool,
                     predicate=in_lambda(['id'], lambda v: v['id'] % 2 == 0)) as reader:
        ids = [r.id for r in reader]
    assert sorted(ids) == list(range(0, 100, 2))


def test_predicate_on_partition_key_pushdown(synthetic_dataset):
    # partition_key is a data column here (dataset not hive-partitioned), so
    # this exercises the worker predicate path with a multi-field reduce.
    pred = in_reduce([in_set({'p_2'}, 'partition_key'),
                      in_lambda(['id'], lambda v: v['id'] < 50)], all)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     predicate=pred) as reader:
        rows = list(reader)
    assert rows
    for r in rows:
        assert r.partition_key == 'p_2' and r.id < 50


def test_predicate_unknown_field_raises(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     predicate=in_set({1}, 'no_such_field')) as reader:
        with pytest.raises(ValueError):
            list(reader)


def test_pseudorandom_split_is_partition(synthetic_dataset):
    def split_ids(index):
        pred = in_pseudorandom_split([0.5, 0.5], index, 'id')
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         predicate=pred) as reader:
            return {r.id for r in reader}

    a, b = split_ids(0), split_ids(1)
    assert a | b == set(range(100))
    assert a.isdisjoint(b)
    assert a and b


@pytest.mark.parametrize('pool', POOLS)
def test_sharding_union_is_complete_and_disjoint(synthetic_dataset, pool):
    """The multi-node stand-in test (reference: test_partition_multi_node)."""
    shard_count = 4
    all_ids = []
    shard_sets = []
    for shard in range(shard_count):
        with make_reader(synthetic_dataset.url, reader_pool_type=pool,
                         workers_count=1, cur_shard=shard,
                         shard_count=shard_count,
                         shuffle_row_groups=False) as reader:
            ids = {r.id for r in reader}
        shard_sets.append(ids)
        all_ids.extend(ids)
    assert len(all_ids) == 100  # disjoint
    assert set(all_ids) == set(range(100))  # complete


def test_too_many_shards_raises(synthetic_dataset):
    with pytest.raises(NoDataAvailableError):
        make_reader(synthetic_dataset.url, cur_shard=0, shard_count=10000)


def test_partial_shard_args_raise(synthetic_dataset):
    with pytest.raises(ValueError):
        make_reader(synthetic_dataset.url, cur_shard=1, shard_count=None)


@pytest.mark.parametrize('pool', POOLS)
def test_num_epochs(synthetic_dataset, pool):
    with make_reader(synthetic_dataset.url, reader_pool_type=pool,
                     num_epochs=3, workers_count=2) as reader:
        ids = [r.id for r in reader]
    assert len(ids) == 300
    assert sorted(ids) == sorted(list(range(100)) * 3)


@pytest.mark.parametrize('pool', POOLS)
def test_reset_after_full_consumption(synthetic_dataset, pool):
    with make_reader(synthetic_dataset.url, reader_pool_type=pool,
                     workers_count=2) as reader:
        first = [r.id for r in reader]
        reader.reset()
        second = [r.id for r in reader]
    assert sorted(first) == sorted(second)


def test_reset_mid_epoch_raises(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy') as reader:
        next(reader)
        with pytest.raises(NotImplementedError):
            reader.reset()


def test_read_after_stop_raises(synthetic_dataset):
    reader = make_reader(synthetic_dataset.url, reader_pool_type='dummy')
    next(reader)
    reader.stop()
    reader.join()
    with pytest.raises(RuntimeError):
        next(reader)


@pytest.mark.parametrize('pool', POOLS)
def test_transform_spec_row_level(synthetic_dataset, pool):
    """TransformSpec on make_reader operates on a pandas frame of the rowgroup."""
    def double_id(frame):
        frame['id'] = frame['id'] * 2
        return frame

    spec = TransformSpec(double_id, selected_fields=['id'])
    with make_reader(synthetic_dataset.url, reader_pool_type=pool,
                     transform_spec=spec) as reader:
        rows = list(reader)
    assert set(rows[0]._fields) == {'id'}
    assert sorted(r.id for r in rows) == [2 * i for i in range(100)]


def test_transform_spec_new_field(synthetic_dataset):
    def add_field(frame):
        frame['id_plus_one'] = frame['id'] + 1
        return frame.drop(columns=['matrix'])

    spec = TransformSpec(add_field,
                         edit_fields=[('id_plus_one', np.int64, (), False)],
                         removed_fields=['matrix'])
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                     schema_fields=['id', 'matrix'], transform_spec=spec) as reader:
        row = next(reader)
    assert set(row._fields) == {'id', 'id_plus_one'}
    assert row.id_plus_one == row.id + 1


@pytest.mark.parametrize('pool', POOLS)
def test_local_disk_cache_round_trip(synthetic_dataset, tmp_path, pool):
    kwargs = dict(reader_pool_type=pool, workers_count=2,
                  cache_type='local-disk',
                  cache_location=str(tmp_path / 'cache'),
                  cache_size_limit=10 ** 9, shuffle_row_groups=False)
    with make_reader(synthetic_dataset.url, **kwargs) as reader:
        first = [r.id for r in reader]
    with make_reader(synthetic_dataset.url, **kwargs) as reader:
        second = [r.id for r in reader]
    # Multi-worker completion order is nondeterministic; cache correctness is
    # about content: both passes must yield the complete dataset.
    assert sorted(first) == sorted(second) == list(range(100))


def test_checkpoint_resume_round_trip(synthetic_dataset):
    """New capability vs the reference: stop mid-epoch, resume elsewhere."""
    reader = make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         shuffle_row_groups=True, seed=3)
    it = iter(reader)
    consumed = [next(it).id for _ in range(10)]
    state = reader.state_dict()
    reader.stop()
    reader.join()

    resumed = make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                          shuffle_row_groups=True, seed=3)
    resumed.load_state_dict(state)
    rest = [r.id for r in resumed]
    resumed.stop()
    resumed.join()
    # Resume starts at the next unventilated row-group: no loss beyond
    # re-reading in-flight groups; union must cover all ids.
    assert set(consumed) | set(rest) == set(range(100))


def test_checkpoint_resume_across_process_pool(synthetic_dataset):
    # the checkpoint must be portable across pool types: state captured
    # from a thread-pool reader resumes on a spawned process pool (the
    # ventilator cursor/seed crosses the dill/ZMQ boundary)
    reader = make_reader(synthetic_dataset.url, reader_pool_type='thread',
                         workers_count=2, shuffle_row_groups=True, seed=11,
                         schema_fields=['^id$'])
    try:
        it = iter(reader)
        consumed = [next(it).id for _ in range(25)]
        state = reader.state_dict()
    finally:
        reader.stop()
        reader.join()

    resumed = make_reader(synthetic_dataset.url, reader_pool_type='process',
                          workers_count=2, shuffle_row_groups=True, seed=11,
                          schema_fields=['^id$'])
    try:
        resumed.load_state_dict(state)
        rest = [r.id for r in resumed]
    finally:
        resumed.stop()
        resumed.join()
    assert set(consumed) | set(rest) == set(range(100))


def test_checkpoint_resume_preserves_remaining_epochs(synthetic_dataset):
    # resume in a 2-epoch sweep: the union over the rest must still cover
    # every id twice minus what the first reader already consumed
    reader = make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         shuffle_row_groups=False, num_epochs=2,
                         schema_fields=['^id$'])
    try:
        it = iter(reader)
        consumed = [next(it).id for _ in range(30)]
        state = reader.state_dict()
    finally:
        reader.stop()
        reader.join()

    resumed = make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                          shuffle_row_groups=False, num_epochs=2,
                          schema_fields=['^id$'])
    try:
        resumed.load_state_dict(state)
        rest = [r.id for r in resumed]
    finally:
        resumed.stop()
        resumed.join()
    from collections import Counter
    total = Counter(consumed) + Counter(rest)
    # at-least-once: every id appears at least twice overall and nothing
    # beyond the re-read of the in-flight row-group is duplicated
    assert all(total[i] >= 2 for i in range(100))
    assert len(consumed) + len(rest) <= 2 * 100 + 10  # ≤ one extra group


# ---------------------------------------------------------------------------
# make_batch_reader over plain parquet
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('pool', POOLS)
def test_batch_reader_scalar_dataset(scalar_dataset, pool):
    with make_batch_reader(scalar_dataset.url, reader_pool_type=pool) as reader:
        batches = list(reader)
    total = sum(len(b.id) for b in batches)
    assert total == 100
    ids = sorted(int(i) for b in batches for i in b.id)
    assert ids == list(range(100))
    b0 = batches[0]
    assert b0.int_fixed_size_list.ndim == 2 and b0.int_fixed_size_list.shape[1] == 3
    assert b0.string.dtype.kind == 'U'


def test_batch_reader_column_projection(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                           schema_fields=['id', 'float64']) as reader:
        b = next(reader)
    assert set(b._fields) == {'id', 'float64'}


def test_batch_reader_predicate(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, reader_pool_type='dummy',
                           predicate=in_lambda(['id'], lambda v: v['id'] < 10)) as reader:
        ids = sorted(int(i) for b in reader for i in b.id)
    assert ids == list(range(10))


def test_batch_reader_on_petastorm_dataset(synthetic_dataset):
    """make_batch_reader over a materialized dataset decodes codec columns too."""
    with make_batch_reader(synthetic_dataset.url, reader_pool_type='dummy',
                           schema_fields=['id', 'image_png']) as reader:
        batch = next(reader)
    assert batch.image_png[0].shape == (16, 32, 3)


def test_reader_iterable_protocol(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy') as reader:
        count = 0
        for _ in reader:
            count += 1
    assert count == 100


def test_diagnostics_property(synthetic_dataset):
    with make_reader(synthetic_dataset.url, reader_pool_type='thread') as reader:
        next(reader)
        assert 'items_ventilated' in reader.diagnostics


def test_url_with_added_slashes(synthetic_dataset):
    # reference: test_simple_read_with_added_slashes (:285)
    with make_reader(synthetic_dataset.url + '///',
                     reader_pool_type='dummy') as reader:
        assert len(list(reader)) == 100


def test_stable_pieces_order_without_shuffle(synthetic_dataset):
    # reference: test_stable_pieces_order (:495) — two unshuffled readers
    # emit identical row order
    orders = []
    for _ in range(2):
        with make_reader(synthetic_dataset.url, shuffle_row_groups=False,
                         reader_pool_type='dummy',
                         schema_fields=['^id$']) as reader:
            orders.append([r.id for r in reader])
    assert orders[0] == orders[1]


def test_persisted_codec_wins_over_user_instance(synthetic_dataset):
    # reference: test_use_persisted_codec_and_not_provided_by_user (:528) —
    # a schema_fields UnischemaField carrying a different codec is matched by
    # name; the dataset's stored codec decodes the data
    from petastorm_tpu.codecs import CompressedNdarrayCodec
    from petastorm_tpu.unischema import UnischemaField
    doctored = UnischemaField('matrix', np.float64, (32, 16, 3),
                              CompressedNdarrayCodec(), False)
    with make_reader(synthetic_dataset.url,
                     schema_fields=[doctored, '^id$'],
                     reader_pool_type='dummy') as reader:
        row = next(reader)
    expected = _fields_by_id(synthetic_dataset.data)
    np.testing.assert_array_equal(row.matrix, expected[row.id]['matrix'])


@pytest.mark.parametrize('pool', ['dummy', 'process'])
def test_transform_with_predicate(synthetic_dataset, pool):
    # reference: test_transform_function_with_predicate (:165) — predicate
    # narrows rows first, transform then edits the surviving frame
    def double_id2(frame):
        frame['id2'] = frame['id2'] * 2
        return frame

    with make_reader(synthetic_dataset.url,
                     predicate=in_lambda(['id'], lambda v: v['id'] % 2 == 0),
                     transform_spec=TransformSpec(double_id2),
                     schema_fields=['^id$', '^id2$'],
                     reader_pool_type=pool, workers_count=2) as reader:
        rows = list(reader)
    assert rows and all(r.id % 2 == 0 for r in rows)
    expected = _fields_by_id(synthetic_dataset.data)
    for r in rows:
        assert r.id2 == expected[r.id]['id2'] * 2


def test_multithreaded_consumers(synthetic_dataset):
    # reference: test_multithreaded_reads (:803) — several consumer threads
    # share one reader; union of consumed ids is exactly the dataset
    import threading
    seen = []
    lock = threading.Lock()
    with make_reader(synthetic_dataset.url, reader_pool_type='thread',
                     workers_count=2, schema_fields=['^id$']) as reader:
        def consume():
            while True:
                try:
                    row = next(reader)
                except StopIteration:
                    return
                with lock:
                    seen.append(row.id)

        threads = [threading.Thread(target=consume) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert sorted(seen) == list(range(100))


def test_invalid_num_epochs_rejected(synthetic_dataset):
    # reference: test_num_epochs_value_error (:609)
    for bad in (0, -1):
        with pytest.raises(ValueError):
            make_reader(synthetic_dataset.url, num_epochs=bad,
                        reader_pool_type='dummy')


def test_read_after_context_exit_raises(synthetic_dataset):
    # reference: test_should_fail_if_reading_out_of_context_manager (:815)
    with make_reader(synthetic_dataset.url, reader_pool_type='dummy') as reader:
        next(reader)
    with pytest.raises(RuntimeError):
        next(reader)


# -- process-pool-specific behaviors (beyond the POOLS matrix above) --------

def test_process_pool_worker_error_propagates(synthetic_dataset):
    from petastorm_tpu.transform import TransformSpec

    def _boom(frame):
        raise ValueError('decode exploded')

    with pytest.raises(ValueError, match='decode exploded'):
        with make_reader(synthetic_dataset.url, reader_pool_type='process',
                         workers_count=2,
                         transform_spec=TransformSpec(_boom)) as reader:
            list(reader)


def test_unlimited_epochs_stream(synthetic_dataset):
    # num_epochs=None: the reader streams forever (reference:
    # test_end_to_end.py test_unlimited_epochs); every dataset-size window
    # keeps covering all ids
    n = len(synthetic_dataset.data)
    with make_reader(synthetic_dataset.url, num_epochs=None,
                     shuffle_row_groups=True, workers_count=2) as reader:
        seen = [getattr(next(reader), 'id') for _ in range(3 * n)]
    from collections import Counter
    counts = Counter(seen)
    assert set(counts) == {r['id'] for r in synthetic_dataset.data}
    # ~3 appearances per id; the pool pipelines row-groups across epoch
    # boundaries (reader.py state_dict docstring), so the first 3n rows
    # may swap one epoch-k group for an epoch-k±1 one — exact-3 would flake
    assert sum(counts.values()) == 3 * n
    assert all(2 <= c <= 4 for c in counts.values())


def test_unlimited_epochs_batch_reader(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, num_epochs=None) as reader:
        seen = 0
        batches = 0
        while seen < 250:  # 2.5 epochs of 100 rows
            seen += len(next(reader).id)
            batches += 1
    assert seen >= 250


def test_epoch_boundaries_preserve_row_totals(scalar_dataset):
    # finite multi-epoch read delivers exactly epochs x rows
    with make_batch_reader(scalar_dataset.url, num_epochs=4) as reader:
        total = sum(len(b.id) for b in reader)
    assert total == 4 * 100
