"""SLO plane: spec parsing, multi-window burn-rate accounting, the
edge-triggered ``slo_breach`` anomaly, the on-disk flight recorder, and
the ISSUE 19 acceptance drill — a seeded slow consumer under a
``queue_wait_p99`` SLO whose breach must be visible in the live
``/health``, the final ``pipeline_report()`` AND the ``obs_replay``
rendering of the obs log directory.
"""

import importlib
import json
import os
import sys
import time
import urllib.request

import pytest

from petastorm_tpu import telemetry as T
from petastorm_tpu.telemetry import obs_server, obslog, slo, timeseries

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _obs_replay():
    tools_dir = os.path.join(_REPO, 'tools')
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    return importlib.import_module('obs_replay')


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    T.reset_for_tests()
    yield
    T.reset_for_tests()


def _win(start, throughput=None, p99=None, staleness=None, rates=None):
    """One synthetic closed rollup window in the shape SloPolicy reads."""
    window = {'start': start, 'throughput': throughput,
              'quantiles': {}, 'gauges': {}, 'rates': rates or {}}
    if p99 is not None:
        window['quantiles'][slo._QUEUE_WAIT_P99_KEY] = {'p99': p99}
    if staleness is not None:
        window['gauges'][slo._APPEND_STALENESS] = staleness
    return window


def _breach_events():
    return [e for e in timeseries.recent_anomalies()
            if e['kind'] == 'slo_breach']


# -- spec parsing ------------------------------------------------------------


def test_parse_spec_units_and_shapes():
    targets = slo.parse_spec(
        'rows_per_sec>=40000;queue_wait_p99<=50ms;'
        'append_staleness<=30s;h2d_overlap>=0.3')
    assert [(t['target'], t['op'], t['threshold']) for t in targets] == [
        ('rows_per_sec', '>=', 40000.0),
        ('queue_wait_p99', '<=', 0.05),
        ('append_staleness', '<=', 30.0),
        ('h2d_overlap', '>=', 0.3),
    ]


def test_parse_spec_drops_bad_clauses_not_the_plane():
    targets = slo.parse_spec(
        'frames_per_sec>=10;'      # unknown target
        'rows_per_sec=10;'          # no operator
        'queue_wait_p99<=fastms;'   # unparseable threshold
        ';rows_per_sec>=100')       # empty clause + one good one
    assert targets == [
        {'target': 'rows_per_sec', 'op': '>=', 'threshold': 100.0}]
    assert slo.parse_spec('') == []
    assert slo.parse_spec(None) == []


def test_h2d_overlap_resolver():
    rates = {slo._STAGE_FILL_KEY: 0.3, slo._H2D_DISPATCH_KEY: 0.1,
             slo._H2D_READY_KEY: 0.1}
    assert slo._resolve_h2d_overlap(
        _win(0.0, rates=rates)) == pytest.approx(0.8)
    assert slo._resolve_h2d_overlap(_win(0.0)) is None


# -- burn-rate state machine -------------------------------------------------


def test_observe_skips_unresolvable_windows():
    policy = slo.SloPolicy(slo.parse_spec('rows_per_sec>=100'))
    assert policy.observe(_win(0.0)) is None
    assert policy.section()['targets'][0]['windows_evaluated'] == 0


def test_breach_needs_warmup_then_fires_once():
    """A breach may not fire before ``_MIN_WINDOWS`` evaluated windows
    (one rough window must not page), fires exactly once on the rising
    edge, and re-arms only after the short horizon recovers."""
    policy = slo.SloPolicy(slo.parse_spec('rows_per_sec>=100'))
    start = 0.0
    for _ in range(slo._MIN_WINDOWS - 1):
        verdict = policy.observe(_win(start, throughput=10.0))
        start += 1.0
        assert not verdict['targets'][0]['breaching']
    assert _breach_events() == []
    # the _MIN_WINDOWS-th all-bad window crosses both horizons
    verdict = policy.observe(_win(start, throughput=10.0))
    assert verdict['targets'][0]['breaching']
    assert len(_breach_events()) == 1
    detail = _breach_events()[0]['detail']
    assert detail['target'] == 'rows_per_sec'
    assert detail['value'] == pytest.approx(10.0)
    # still breaching: edge-triggered, no second anomaly
    policy.observe(_win(start + 1, throughput=10.0))
    assert len(_breach_events()) == 1
    # a full short horizon of good windows clears the condition...
    for i in range(slo._SHORT_WINDOWS):
        verdict = policy.observe(_win(start + 2 + i, throughput=500.0))
    assert not verdict['targets'][0]['breaching']
    # ...so a fresh fast burn fires a SECOND anomaly (re-armed)
    for i in range(3):
        policy.observe(_win(start + 20 + i, throughput=10.0))
    assert len(_breach_events()) == 2


def test_budget_metrics_counter_and_gauge():
    policy = slo.SloPolicy(slo.parse_spec('rows_per_sec>=100'))
    policy.observe(_win(0.0, throughput=10.0))     # 1 bad window
    for i in range(19):
        policy.observe(_win(1.0 + i, throughput=500.0))
    reg = T.get_registry()
    assert reg.counter_value(slo.SLO_BREACH_WINDOWS,
                             target='rows_per_sec') == 1
    # 1 bad of 20 windows = 5% bad against a 10% budget: half remains
    assert reg.gauge_value(slo.SLO_BUDGET_REMAINING,
                           target='rows_per_sec') == pytest.approx(0.5)
    section = policy.section()['targets'][0]
    assert section['windows_evaluated'] == 20
    assert section['windows_bad'] == 1
    assert section['budget_remaining'] == pytest.approx(0.5)


def test_queue_wait_and_staleness_targets_resolve():
    policy = slo.SloPolicy(slo.parse_spec(
        'queue_wait_p99<=50ms;append_staleness<=30s'))
    verdict = policy.observe(_win(0.0, p99=0.2, staleness=5.0))
    by_target = {v['target']: v for v in verdict['targets']}
    assert by_target['queue_wait_p99']['bad']          # 0.2 > 0.05
    assert not by_target['append_staleness']['bad']    # 5 <= 30


# -- policy lifecycle --------------------------------------------------------


def test_get_policy_keeps_burn_state_across_refresh(monkeypatch):
    monkeypatch.setenv('PETASTORM_TPU_SLO', 'rows_per_sec>=100')
    policy = slo.get_policy()
    assert policy is not None
    policy.observe(_win(0.0, throughput=10.0))
    slo.refresh_slo()  # unchanged spec: same object, state intact
    assert slo.get_policy() is policy
    assert policy.section()['targets'][0]['windows_evaluated'] == 1
    # a CHANGED spec re-parses from scratch
    monkeypatch.setenv('PETASTORM_TPU_SLO', 'rows_per_sec>=200')
    fresh = slo.get_policy()
    assert fresh is not policy
    assert fresh.section()['targets'][0]['windows_evaluated'] == 0
    monkeypatch.delenv('PETASTORM_TPU_SLO')
    assert slo.get_policy() is None
    assert slo.slo_section() is None


# -- QoS weight advice -------------------------------------------------------


def test_qos_weight_advice_only_moves_weight_while_burning():
    entries = [
        {'job_id': 1, 'name': 'starved', 'worker_share': 0.2,
         'target_share': 0.5},
        {'job_id': 2, 'name': 'donor', 'worker_share': 0.6,
         'target_share': 0.3},
        {'job_id': 3, 'name': 'even', 'worker_share': 0.5,
         'target_share': 0.5},
    ]
    burning = {'targets': [{'breaching': True}]}
    calm = {'targets': [{'breaching': False}]}
    advice = slo.qos_weight_advice(entries, slo=burning)
    assert [a['advice'] for a in advice] == \
        ['raise_weight', 'lower_weight', 'ok']
    # with budgets intact weight churn is noise: everything is ok
    assert all(a['advice'] == 'ok'
               for a in slo.qos_weight_advice(entries, slo=calm))
    assert slo.qos_weight_advice([], slo=burning) == []


# -- the on-disk flight recorder ---------------------------------------------


def test_obslog_append_merges_kind_and_stamps_ts(tmp_path, monkeypatch):
    assert obslog.append('window', {'a': 1}) is False  # unarmed: no-op
    monkeypatch.setenv('PETASTORM_TPU_OBS_LOG_DIR', str(tmp_path))
    obslog.refresh_obslog()
    assert obslog.append('window', {'a': 1}) is True
    (record,) = obslog.read_log(str(tmp_path))
    assert record['kind'] == 'window'
    assert record['a'] == 1
    assert record['ts'] > 0


def test_obslog_two_slot_ring_rotates_at_cap(tmp_path):
    writer = obslog.ObsLogWriter(str(tmp_path), cap=300)
    for seq in range(40):
        assert writer.append('window', {'seq': seq})
    assert os.path.exists(writer.path + '.1')
    # disk use stays bounded near 2x the cap no matter the append count
    total = (os.path.getsize(writer.path)
             + os.path.getsize(writer.path + '.1'))
    assert total < 3 * 300
    seqs = [r['seq'] for r in obslog.read_log(str(tmp_path))]
    # oldest records fell off the ring, order survives, tail is intact
    assert seqs == sorted(seqs)
    assert seqs[-1] == 39
    assert len(seqs) < 40


def test_obslog_read_skips_torn_lines(tmp_path):
    path = os.path.join(str(tmp_path), 'obslog.jsonl')
    with open(path, 'w') as f:
        f.write(json.dumps({'kind': 'window', 'seq': 0}) + '\n')
        f.write('\n')
        f.write('{"kind": "window", "seq": 1')  # crash mid-write
    records = obslog.read_log(str(tmp_path))
    assert [r['seq'] for r in records] == [0]


# -- acceptance: seeded slow consumer breaches a queue_wait_p99 SLO ----------


def _get_json(route, port=None):
    port = port or obs_server.server_port()
    assert port, 'no obs server bound'
    return json.loads(urllib.request.urlopen(
        'http://127.0.0.1:%d%s' % (port, route), timeout=10).read())


def _wait_for(predicate, timeout_s=20, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval_s)
    return predicate()


def test_slow_consumer_breaches_queue_wait_slo(tmp_path, monkeypatch):
    """Acceptance (ISSUE 19): a seeded slow consumer under a
    ``queue_wait_p99`` SLO fires ``slo_breach`` visible in the live
    ``/health`` (status flips to ``slo-breach``), the final
    ``pipeline_report()``, and the ``obs_replay`` rendering of the
    flight-log directory.

    The threshold sits below the first duration-histogram bucket
    (0.1ms), so every window with any consumer pull is a bad window —
    the drill exercises the burn/breach machinery deterministically
    rather than depending on host timing.
    """
    from tests.test_common import create_test_scalar_dataset
    from petastorm_tpu.reader import make_batch_reader
    url = 'file://' + str(tmp_path / 'dataset')
    create_test_scalar_dataset(url, num_rows=80, num_files=8)
    log_dir = str(tmp_path / 'blackbox')

    monkeypatch.setenv('PETASTORM_TPU_OBS_PORT', '0')
    monkeypatch.setenv('PETASTORM_TPU_OBS_WINDOW_SEC', '0.2')
    monkeypatch.setenv('PETASTORM_TPU_SLO', 'queue_wait_p99<=0.05ms')
    monkeypatch.setenv('PETASTORM_TPU_OBS_LOG_DIR', log_dir)
    T.refresh()

    with make_batch_reader(url, reader_pool_type='thread',
                           workers_count=2, results_queue_size=1,
                           num_epochs=4, shuffle_row_groups=False) as reader:
        for _ in reader:
            time.sleep(0.12)  # deliberately slow consumer
        # the breach persists once fired (no good windows can follow a
        # sub-bucket threshold), so a post-loop poll settles it
        health = _wait_for(
            lambda: (lambda doc: doc
                     if doc.get('status') == 'slo-breach' else None)(
                         _get_json('/health')), timeout_s=10)
    assert health and health['status'] == 'slo-breach', health
    target = next(t for t in health['slo']['targets']
                  if t['target'] == 'queue_wait_p99')
    assert target['breaching']
    assert target['windows_bad'] >= slo._MIN_WINDOWS

    # final pipeline_report(): the SLO section and the anomaly ring
    report = T.pipeline_report()
    final = next(t for t in report['slo']['targets']
                 if t['target'] == 'queue_wait_p99')
    assert final['breaching']
    assert final['budget_remaining'] == pytest.approx(0.0)
    assert report['anomalies']['by_kind'].get('slo_breach', 0) >= 1
    reg = T.get_registry()
    assert reg.counter_value(slo.SLO_BREACH_WINDOWS,
                             target='queue_wait_p99') >= slo._MIN_WINDOWS

    # the flight recorder caught it all, and obs_replay folds it back
    records = obslog.read_log(log_dir)
    kinds = {r.get('kind') for r in records}
    assert {'window', 'slo', 'anomaly'} <= kinds, kinds
    breach_lines = [r for r in records if r.get('kind') == 'anomaly'
                    and r.get('anomaly') == 'slo_breach']
    assert breach_lines, 'no slo_breach anomaly reached the obs log'
    assert 'runbook' in breach_lines[0]

    replay = _obs_replay()
    summary = replay.fold_summary(records)
    assert summary['windows'] > 0
    assert summary['anomaly_kinds'].get('slo_breach', 0) >= 1
    folded = next(t for t in summary['slo']
                  if t['target'] == 'queue_wait_p99')
    assert folded['breaching_at_end']
    assert folded['breaches'] and folded['breaches'][0][1] is None
    assert folded['windows_bad'] >= slo._MIN_WINDOWS
    # the human renderings name the breach too
    lines = []
    replay.render_burn_report(summary['slo'], out=lines.append)
    assert any('BREACHING' in line for line in lines)
    lines = []
    replay.render_timeline(replay.split_records(records),
                           out=lines.append)
    assert any('!! slo_breach' in line for line in lines)
