"""Plain (non-petastorm) Parquet store reading
(reference: ``tests/test_parquet_reader.py``, 209 LoC)."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu import make_batch_reader


def test_many_columns_store(tmp_path):
    # reference: test_many_columns_non_petastorm_dataset (:83) — wide
    # schemas must survive inference, reading, and namedtuple creation
    n_cols = 300
    table = pa.table({'col_%03d' % i: np.arange(20) + i
                      for i in range(n_cols)})
    pq.write_table(table, str(tmp_path / 'wide.parquet'))
    url = 'file://' + str(tmp_path)
    with make_batch_reader(url, shuffle_row_groups=False) as reader:
        batch = next(reader)
    assert len(batch._fields) == n_cols
    np.testing.assert_array_equal(batch.col_299, np.arange(20) + 299)


def test_partitioned_field_is_not_queried(tmp_path):
    # reference: test_partitioned_field_is_not_queried (:93) — projecting
    # away the hive partition column must not break row-group discovery
    for part_dir, start in (('id_div=0', 0), ('id_div=1', 10)):
        (tmp_path / part_dir).mkdir()
        table = pa.table({'string': ['s_%d' % i
                                     for i in range(start, start + 10)]})
        pq.write_table(table, str(tmp_path / part_dir / 'part-0.parquet'))
    url = 'file://' + str(tmp_path)
    with make_batch_reader(url, schema_fields=['^string$'],
                           shuffle_row_groups=False) as reader:
        rows = [s for batch in reader for s in batch.string]
        fields = None
        with make_batch_reader(url, schema_fields=['^string$']) as r2:
            fields = next(r2)._fields
    assert sorted(rows) == sorted('s_%d' % i for i in range(20))
    assert fields == ('string',)


def test_asymmetric_parquet_pieces(tmp_path):
    # reference: test_asymetric_parquet_pieces (:105) — files with
    # DIFFERENT row-group counts must be enumerated and read completely
    sizes = [7, 40, 91]
    start = 0
    for file_idx, n in enumerate(sizes):
        table = pa.table({'id': np.arange(start, start + n)})
        pq.write_table(table, str(tmp_path / ('part-%d.parquet' % file_idx)),
                       row_group_size=13)
        start += n
    counts = {pq.ParquetFile(str(tmp_path / ('part-%d.parquet' % i)))
              .metadata.num_row_groups for i in range(len(sizes))}
    assert len(counts) > 1  # genuinely asymmetric
    url = 'file://' + str(tmp_path)
    with make_batch_reader(url, shuffle_row_groups=False) as reader:
        ids = [i for b in reader for i in b.id]
    assert sorted(ids) == list(range(sum(sizes)))


def test_out_of_int64_range_partition_never_overflows(tmp_path):
    # inference must never promise int64 for values the conversion would
    # overflow on; like Spark's discovery ladder (long → double → string),
    # a beyond-int64 integer lands on float64 instead of crashing the read
    huge = 99999999999999999999999
    for value in (1, huge):
        d = tmp_path / ('uid=%d' % value)
        d.mkdir()
        pq.write_table(pa.table({'x': np.arange(3)}),
                       str(d / 'part-0.parquet'))
    url = 'file://' + str(tmp_path)
    with make_batch_reader(url, shuffle_row_groups=False) as reader:
        values = {float(v) for b in reader for v in b.uid}
    assert values == {1.0, float(huge)}


def test_mixed_valid_and_invalid_column_names(scalar_dataset):
    # reference: test_invalid_and_valid_column_names (:141) — the unmatched
    # pattern is silently dropped, only the valid column comes back
    with make_batch_reader(scalar_dataset.url,
                           schema_fields=['^id$', '^no_such_column$'],
                           shuffle_row_groups=False) as reader:
        batch = next(reader)
    assert batch._fields == ('id',)


def test_all_invalid_column_names_raise(scalar_dataset):
    # reference: test_invalid_column_name (:129)
    with pytest.raises(ValueError, match='No fields matching'):
        make_batch_reader(scalar_dataset.url,
                          schema_fields=['^no_such_column$'])


def test_int_partition_values_are_typed(tmp_path):
    # reference: test_string_partition parametrization (:201) — integer
    # hive partition values come back typed, not as path strings
    for value in (0, 1):
        d = tmp_path / ('num=%d' % value)
        d.mkdir()
        pq.write_table(pa.table({'x': np.arange(5) + value * 5}),
                       str(d / 'part-0.parquet'))
    url = 'file://' + str(tmp_path)
    with make_batch_reader(url, shuffle_row_groups=False) as reader:
        batches = list(reader)
    nums = np.concatenate([np.asarray(b.num) for b in batches])
    assert set(nums.tolist()) == {0, 1}
    assert nums.dtype.kind in 'iu' or all(isinstance(v, (int, np.integer))
                                          for v in nums)
