"""On-disk interop with the reference implementation, both directions.

Direction 1 (reference-write → our-read): fixture datasets are materialized
with the REFERENCE's own ``petastorm/unischema.py`` + ``petastorm/codecs.py``
(imported from ``/root/reference`` via a path-only package so the reader
stack's dead dependencies stay out of it), and the reference's **real**
``Unischema`` instance is pickled into ``_common_metadata`` under its
``dataset-toolkit.unischema.v1`` key — byte-layout-faithful to what
``petastorm/etl/dataset_metadata.py:194-205`` writes. We then read the
dataset through ``make_reader``/``make_batch_reader`` and assert per-codec
value equality.

Direction 2 (our-write → reference-load): ``DatasetWriter`` datasets stamp a
reference-compatible pickled schema; unpickling that blob with the
reference's real classes importable must yield a genuine
``petastorm.unischema.Unischema`` (what a real petastorm+pyspark install's
``get_schema``, ``etl/dataset_metadata.py:356-386``, would see), and the
reference's codecs must decode our encoded cells to the original values.

pyspark itself is not installed here; minimal ``pyspark.sql.types`` stand-in
classes with the genuine module path play its part on both sides, exactly as
they appear inside real petastorm pickles.
"""

import json
import pickle
import sys
import types
from collections import OrderedDict
from decimal import Decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.codecs import (
    CompressedImageCodec, CompressedNdarrayCodec, NdarrayCodec, ScalarCodec,
)
from petastorm_tpu.etl.dataset_metadata import (
    LEGACY_ROW_GROUPS_PER_FILE_KEY, LEGACY_UNISCHEMA_KEY, ParquetDatasetInfo,
    get_schema_from_dataset_url, write_dataset,
)
from petastorm_tpu.unischema import Unischema, UnischemaField

REFERENCE_ROOT = '/root/reference/petastorm'

pytestmark = pytest.mark.skipif(
    not __import__('os').path.isdir(REFERENCE_ROOT),
    reason='reference petastorm checkout not present')


class _RefModules:
    """The reference's real unischema/codecs + pyspark.sql.types stand-ins."""

    def __init__(self, unischema, codecs, spark_types):
        self.unischema = unischema
        self.codecs = codecs
        self.spark_types = spark_types


def _make_spark_types_module():
    m = types.ModuleType('pyspark.sql.types')

    class DataType:
        def __eq__(self, other):
            return type(self) is type(other)

        def __hash__(self):
            return hash(type(self))

    names = ['BooleanType', 'ByteType', 'ShortType', 'IntegerType', 'LongType',
             'FloatType', 'DoubleType', 'StringType', 'BinaryType',
             'TimestampType', 'DateType', 'DecimalType']
    for name in names:
        cls = type(name, (DataType,), {})
        cls.__module__ = 'pyspark.sql.types'
        cls.__qualname__ = name
        setattr(m, name, cls)
    DataType.__module__ = 'pyspark.sql.types'
    m.DataType = DataType
    return m


@pytest.fixture(scope='module')
def ref():
    """Import the reference's real unischema/codecs via a path-only package.

    Registering a synthetic ``petastorm`` package whose ``__path__`` points at
    the reference tree lets ``petastorm.unischema``/``petastorm.codecs``
    import as their genuine selves (identical pickle paths) without executing
    the package ``__init__`` (whose reader imports need long-removed pyarrow
    APIs). ``pyspark.sql.types`` is a minimal stand-in under the real name.
    """
    saved = {k: sys.modules.get(k)
             for k in ('petastorm', 'petastorm.unischema', 'petastorm.codecs',
                       'pyspark', 'pyspark.sql', 'pyspark.sql.types')}
    pkg = types.ModuleType('petastorm')
    pkg.__path__ = [REFERENCE_ROOT]
    sys.modules['petastorm'] = pkg
    sys.modules.pop('petastorm.unischema', None)
    sys.modules.pop('petastorm.codecs', None)
    sys.modules['pyspark'] = types.ModuleType('pyspark')
    sys.modules['pyspark.sql'] = types.ModuleType('pyspark.sql')
    sys.modules['pyspark.sql.types'] = _make_spark_types_module()
    try:
        import petastorm.codecs as ref_codecs
        import petastorm.unischema as ref_unischema
        yield _RefModules(ref_unischema, ref_codecs,
                          sys.modules['pyspark.sql.types'])
    finally:
        for k, v in saved.items():
            if v is None:
                sys.modules.pop(k, None)
            else:
                sys.modules[k] = v


# ---------------------------------------------------------------------------
# Direction 1: reference-write → our-read
# ---------------------------------------------------------------------------

N_ROWS = 24
ROWS_PER_FILE = 12
ROWS_PER_GROUP = 6


def _ref_rows(rng):
    rows = []
    for i in range(N_ROWS):
        rows.append({
            'id': np.int32(i),
            'name': 'row_%d' % i,
            'weight': np.float64(i) / 3.0,
            'vec': rng.rand(8).astype(np.float32),
            'cvec': rng.rand(4).astype(np.float64),
            'img': rng.randint(0, 255, (16, 32, 3), np.uint8),
            'price': Decimal('%d.%02d' % (i, i)),
            'maybe': None if i % 3 == 0 else np.int32(i * 10),
        })
    return rows


@pytest.fixture(scope='module')
def reference_written_dataset(ref, tmp_path_factory):
    """A dataset laid out exactly as the reference writes it: parquet files
    whose binary columns hold the reference codecs' encoded bytes, plus a
    ``_common_metadata`` carrying the reference's real pickled Unischema."""
    u, c, st = ref.unischema, ref.codecs, ref.spark_types
    root = tmp_path_factory.mktemp('ref_ds')

    fields = [
        u.UnischemaField('id', np.int32, (), c.ScalarCodec(st.IntegerType()), False),
        u.UnischemaField('name', np.str_, (), c.ScalarCodec(st.StringType()), False),
        u.UnischemaField('weight', np.float64, (), c.ScalarCodec(st.DoubleType()), False),
        u.UnischemaField('vec', np.float32, (8,), c.NdarrayCodec(), False),
        u.UnischemaField('cvec', np.float64, (4,), c.CompressedNdarrayCodec(), False),
        u.UnischemaField('img', np.uint8, (16, 32, 3), c.CompressedImageCodec('png'), False),
        u.UnischemaField('price', Decimal, (), c.ScalarCodec(_decimal_type(st)), False),
        u.UnischemaField('maybe', np.int32, (), c.ScalarCodec(st.IntegerType()), True),
    ]
    schema = u.Unischema('RefSchema', fields)

    rng = np.random.RandomState(42)
    rows = _ref_rows(rng)
    encoded = []
    for row in rows:
        enc = {}
        for f in fields:
            value = row[f.name]
            enc[f.name] = (None if value is None
                           else f.codec.encode(f, value))
        encoded.append(enc)

    arrow_schema = pa.schema([
        pa.field('id', pa.int32()),
        pa.field('name', pa.string()),
        pa.field('weight', pa.float64()),
        pa.field('vec', pa.binary()),
        pa.field('cvec', pa.binary()),
        pa.field('img', pa.binary()),
        pa.field('price', pa.decimal128(10, 2)),
        pa.field('maybe', pa.int32()),
    ])

    counts = {}
    for file_idx in range(N_ROWS // ROWS_PER_FILE):
        chunk = encoded[file_idx * ROWS_PER_FILE:(file_idx + 1) * ROWS_PER_FILE]
        cols = {name: [r[name] for r in chunk] for name in arrow_schema.names}
        cols['price'] = [Decimal(str(v)) for v in cols['price']]
        table = pa.table(
            {n: pa.array(cols[n], type=arrow_schema.field(n).type)
             for n in arrow_schema.names}, schema=arrow_schema)
        fname = 'part-%05d.parquet' % file_idx
        pq.write_table(table, str(root / fname), row_group_size=ROWS_PER_GROUP)
        counts[fname] = ROWS_PER_FILE // ROWS_PER_GROUP

    # _common_metadata with the reference's REAL pickled schema, exactly the
    # keys petastorm/etl/dataset_metadata.py:194-241 stamps.
    blob = pickle.dumps(schema, protocol=2)
    meta_schema = arrow_schema.with_metadata({
        LEGACY_UNISCHEMA_KEY: blob,
        LEGACY_ROW_GROUPS_PER_FILE_KEY: json.dumps(counts).encode('utf-8'),
    })
    pq.write_metadata(meta_schema, str(root / '_common_metadata'))
    return 'file://' + str(root), rows


def _decimal_type(st):
    t = st.DecimalType()
    t.precision = 10
    t.scale = 2
    t.hasPrecisionInfo = True
    return t


class TestReferenceWrittenDataset:
    def test_schema_loads(self, reference_written_dataset):
        url, _ = reference_written_dataset
        schema = get_schema_from_dataset_url(url)
        assert list(schema.fields) == ['id', 'name', 'weight', 'vec', 'cvec',
                                       'img', 'price', 'maybe']
        assert schema.fields['vec'].shape == (8,)
        assert isinstance(schema.fields['vec'].codec, NdarrayCodec)
        assert isinstance(schema.fields['cvec'].codec, CompressedNdarrayCodec)
        assert isinstance(schema.fields['img'].codec, CompressedImageCodec)
        assert schema.fields['img'].codec.image_codec == 'png'
        assert schema.fields['maybe'].nullable

    @pytest.mark.parametrize('pool', ['thread', 'process'])
    def test_row_reader_values(self, reference_written_dataset, pool):
        url, rows = reference_written_dataset
        with make_reader(url, reader_pool_type=pool,
                         shuffle_row_groups=False) as reader:
            got = sorted(reader, key=lambda r: r.id)
        assert len(got) == N_ROWS
        for out, expected in zip(got, rows):
            assert out.id == expected['id']
            assert out.name == expected['name']
            assert out.weight == pytest.approx(expected['weight'])
            np.testing.assert_array_equal(out.vec, expected['vec'])
            np.testing.assert_array_equal(out.cvec, expected['cvec'])
            np.testing.assert_array_equal(out.img, expected['img'])
            assert out.price == expected['price']
            if expected['maybe'] is None:
                assert out.maybe is None
            else:
                assert out.maybe == expected['maybe']

    def test_batch_reader_values(self, reference_written_dataset):
        url, rows = reference_written_dataset
        with make_batch_reader(url, shuffle_row_groups=False) as reader:
            batches = list(reader)
        ids = np.concatenate([np.asarray(b.id) for b in batches])
        assert sorted(ids.tolist()) == list(range(N_ROWS))
        by_id = {}
        for b in batches:
            for i in range(len(b.id)):
                by_id[int(b.id[i])] = {'vec': b.vec[i], 'img': b.img[i]}
        for expected in rows:
            np.testing.assert_array_equal(by_id[int(expected['id'])]['vec'],
                                          expected['vec'])
            np.testing.assert_array_equal(by_id[int(expected['id'])]['img'],
                                          expected['img'])

    def test_rowgroup_counts_come_from_legacy_key(self, reference_written_dataset):
        from petastorm_tpu.etl.dataset_metadata import load_row_groups
        url, _ = reference_written_dataset
        rgs = load_row_groups(ParquetDatasetInfo(url))
        assert len(rgs) == N_ROWS // ROWS_PER_GROUP


# ---------------------------------------------------------------------------
# Direction 2: our-write → reference-load
# ---------------------------------------------------------------------------

def _our_schema():
    return Unischema('TpuSchema', [
        UnischemaField('id', np.int32, (), ScalarCodec(pa.int32()), False),
        UnischemaField('label', np.str_, (), ScalarCodec(pa.string()), False),
        UnischemaField('emb', np.float32, (6,), NdarrayCodec(), False),
        UnischemaField('zipped', np.float64, (3,), CompressedNdarrayCodec(), False),
        UnischemaField('thumb', np.uint8, (8, 8, 3), CompressedImageCodec('png'), False),
        UnischemaField('cost', Decimal, (), ScalarCodec(pa.decimal128(12, 3)), False),
    ])


@pytest.fixture(scope='module')
def our_written_dataset(tmp_path_factory):
    root = tmp_path_factory.mktemp('tpu_ds')
    url = 'file://' + str(root)
    schema = _our_schema()
    rng = np.random.RandomState(7)
    rows = [{'id': np.int32(i), 'label': 'L%d' % i,
             'emb': rng.rand(6).astype(np.float32),
             'zipped': rng.rand(3).astype(np.float64),
             'thumb': rng.randint(0, 255, (8, 8, 3), np.uint8),
             'cost': Decimal('%d.%03d' % (i * 2, i))} for i in range(10)]
    write_dataset(url, schema, rows, rowgroup_size_rows=5)
    return url, schema, rows


class TestOurDatasetLoadsInReference:
    def test_footer_carries_reference_pickle_keys(self, our_written_dataset):
        url, _, _ = our_written_dataset
        meta = dict(ParquetDatasetInfo(url).common_metadata.metadata)
        assert LEGACY_UNISCHEMA_KEY in meta
        counts = json.loads(meta[LEGACY_ROW_GROUPS_PER_FILE_KEY].decode())
        assert sum(counts.values()) == 2  # 10 rows / 5 per group

    def test_reference_unpickles_a_real_unischema(self, ref, our_written_dataset):
        url, schema, _ = our_written_dataset
        blob = dict(ParquetDatasetInfo(url).common_metadata.metadata)[
            LEGACY_UNISCHEMA_KEY]
        # With the reference's real modules importable, its get_schema
        # (etl/dataset_metadata.py:356-386) is a pickle.loads of this blob.
        loaded = pickle.loads(blob)
        assert type(loaded) is ref.unischema.Unischema
        assert loaded._name == 'TpuSchema'
        assert list(loaded._fields) == list(schema.fields)
        for name, field in loaded._fields.items():
            assert type(field) is ref.unischema.UnischemaField
            ours = schema.fields[name]
            assert field.shape == tuple(ours.shape)
            assert field.nullable == ours.nullable
        assert type(loaded._fields['emb'].codec) is ref.codecs.NdarrayCodec
        assert type(loaded._fields['zipped'].codec) is ref.codecs.CompressedNdarrayCodec
        img_codec = loaded._fields['thumb'].codec
        assert type(img_codec) is ref.codecs.CompressedImageCodec
        assert img_codec._image_codec == '.png'
        scalar = loaded._fields['id'].codec
        assert type(scalar) is ref.codecs.ScalarCodec
        assert type(scalar._spark_type).__name__ == 'IntegerType'
        cost = loaded._fields['cost'].codec._spark_type
        assert (type(cost).__name__, cost.precision, cost.scale) == ('DecimalType', 12, 3)

    def test_reference_codecs_decode_our_cells(self, ref, our_written_dataset):
        """Byte-level compat: the reference's decode on our stored payloads."""
        url, schema, rows = our_written_dataset
        info = ParquetDatasetInfo(url)
        table = pa.concat_tables([pq.read_table(info.open(p))
                                  for p in info.file_paths])
        ids = table.column('id').to_pylist()
        u, c = ref.unischema, ref.codecs
        ref_emb = u.UnischemaField('emb', np.float32, (6,), c.NdarrayCodec(), False)
        ref_zip = u.UnischemaField('zipped', np.float64, (3,), c.CompressedNdarrayCodec(), False)
        ref_img = u.UnischemaField('thumb', np.uint8, (8, 8, 3), c.CompressedImageCodec('png'), False)
        for pos, row_id in enumerate(ids):
            expected = rows[row_id]
            got_emb = c.NdarrayCodec().decode(ref_emb, table.column('emb')[pos].as_py())
            np.testing.assert_array_equal(got_emb, expected['emb'])
            got_zip = c.CompressedNdarrayCodec().decode(ref_zip, table.column('zipped')[pos].as_py())
            np.testing.assert_array_equal(got_zip, expected['zipped'])
            got_img = c.CompressedImageCodec('png').decode(ref_img, table.column('thumb')[pos].as_py())
            np.testing.assert_array_equal(got_img, expected['thumb'])

    def test_round_trip_through_both_schema_paths(self, our_written_dataset):
        """Our JSON key and the legacy pickle key must describe one schema."""
        url, schema, _ = our_written_dataset
        from petastorm_tpu.etl.legacy import depickle_legacy_unischema
        meta = dict(ParquetDatasetInfo(url).common_metadata.metadata)
        via_pickle = depickle_legacy_unischema(meta[LEGACY_UNISCHEMA_KEY])
        loaded = get_schema_from_dataset_url(url)
        assert list(via_pickle.fields) == list(loaded.fields)
        for name in loaded.fields:
            a, b = via_pickle.fields[name], loaded.fields[name]
            assert (a.shape, a.nullable) == (b.shape, b.nullable)
            assert type(a.codec).__name__ == type(b.codec).__name__
