"""pipesan runtime sanitizer (petastorm_tpu/sanitizer.py).

The dynamic half of the ISSUE's tentpole: ``PETASTORM_TPU_SANITIZE=1``
arms guards at the three zero-copy boundaries. Covered here: the seeded
use-after-recycle fixture (a deliberately-escaped staging-slot view trips
the weakref census and the recycle is aborted, not corrupted), red-zone
canary tramples, the decoded-cache read path arriving ``writeable=False``
on BOTH the mmap and pickle-fallback branches, pickle-5 wire views forced
read-only, the ``pipesan`` section of ``pipeline_report()``, knob
discipline through ``telemetry.refresh()``, and the ``perf``-marked
overhead guard (armed stays within a bounded factor; unarmed does zero
guard work)."""

import contextlib
import os
import time

import numpy as np
import pytest

from petastorm_tpu import sanitizer
from petastorm_tpu import telemetry as T
from petastorm_tpu.arrow_worker import ColumnBatch
from petastorm_tpu.jax import staging
from petastorm_tpu.materialized_cache import (
    MaterializedRowGroupCache, read_entry, write_entry,
)
from petastorm_tpu.serializers import PickleSerializer


@contextlib.contextmanager
def _sanitize_env(value):
    saved = os.environ.get('PETASTORM_TPU_SANITIZE')
    if value is None:
        os.environ.pop('PETASTORM_TPU_SANITIZE', None)
    else:
        os.environ['PETASTORM_TPU_SANITIZE'] = value
    sanitizer.refresh_sanitizer()
    try:
        yield
    finally:
        if saved is None:
            os.environ.pop('PETASTORM_TPU_SANITIZE', None)
        else:
            os.environ['PETASTORM_TPU_SANITIZE'] = saved
        sanitizer.refresh_sanitizer()


@pytest.fixture(autouse=True)
def _fresh():
    T.reset_for_tests()
    sanitizer.reset_for_tests()
    yield
    T.reset_for_tests()
    sanitizer.reset_for_tests()


class _AcceleratorLeaf:
    """Device-array stand-in that copies on construction and claims a
    non-host platform, pinning the staging engine's ring mode on the CPU
    test host (same idiom as tests/test_staging.py)."""

    def __init__(self, arr):
        self.value = np.array(arr, copy=True)

    def devices(self):
        class _Dev:
            platform = 'tpu'
        return (_Dev(),)

    def block_until_ready(self):
        return self


def _slab_root(arr):
    root = arr
    while isinstance(getattr(root, 'base', None), np.ndarray):
        root = root.base
    return root


# -- knob discipline ----------------------------------------------------------


def test_knob_off_by_default_and_covered_by_telemetry_refresh():
    assert not sanitizer.sanitize_enabled()
    os.environ['PETASTORM_TPU_SANITIZE'] = '1'
    try:
        # telemetry.refresh() is the documented one-stop knob re-read
        T.refresh()
        assert sanitizer.sanitize_enabled()
    finally:
        os.environ.pop('PETASTORM_TPU_SANITIZE', None)
        T.refresh()
    assert not sanitizer.sanitize_enabled()


# -- guarded slabs + census units --------------------------------------------


def test_allocate_guarded_round_trips_and_verifies_canaries():
    arr = sanitizer.allocate_guarded((4, 8), np.float32)
    assert arr.shape == (4, 8) and arr.dtype == np.float32
    arr[:] = np.arange(32, dtype=np.float32).reshape(4, 8)
    assert sanitizer.check_canaries(arr)
    np.testing.assert_array_equal(
        arr, np.arange(32, dtype=np.float32).reshape(4, 8))
    root = _slab_root(arr)
    assert root.dtype == np.uint8 and root.ndim == 1
    root[0] = 0                      # trample the front red zone
    assert not sanitizer.check_canaries(arr)
    # trampled zones are re-poisoned so the NEXT trample is caught too
    assert sanitizer.check_canaries(arr)


def test_plain_arrays_are_not_guarded_slabs():
    # np.empty allocations (unarmed engines) carry nothing to verify
    assert sanitizer.check_canaries(np.empty((4, 8), np.float32))


def test_view_census_counts_live_views():
    census = sanitizer.ViewCensus()
    a = np.arange(8)
    b = np.arange(8)
    census.register([a, b])
    assert census.escaped() == 2
    del a
    assert census.escaped() == 1
    view = b[:4]
    census.register([view])          # new dispatch replaces the old refs
    assert census.escaped() == 1
    del view
    assert census.escaped() == 0


# -- the seeded use-after-recycle fixture -------------------------------------


def test_escaped_staging_view_trips_the_census_and_quarantines():
    """The acceptance-gate fixture: a consumer deliberately keeps a
    dispatched host view; when its slot comes up for recycling the
    weakref census catches it, the recycle is ABORTED (fresh buffers for
    the slot, the escaped holder keeps the old memory — no corruption),
    and the violation is recorded + counted."""
    leaked = {}

    def put(tree):
        if not leaked:
            leaked.update(tree)      # the deliberate escape
        return {k: _AcceleratorLeaf(v) for k, v in tree.items()}

    with _sanitize_env('1'):
        eng = staging.StagingEngine(8, {'v': np.float32}, 'drop', put,
                                    num_slots=2)
        rng = np.random.RandomState(0)
        sources, held = [], []
        for i in range(6):
            cols = {'v': rng.rand(8, 4) + i}        # f64 → f32: ring path
            sources.append(cols['v'].astype(np.float32))
            held.append(eng.stage(cols, 8))
        assert eng._host_backed is False
    # batch 0's slot came up for recycling at batch 2 with the view alive
    assert eng.slabs_quarantined == 1
    kinds = [v['kind'] for v in sanitizer.violations()]
    assert kinds == ['staging-use-after-recycle']
    assert T.get_registry().counter_value(
        sanitizer.SANITIZER_VIOLATIONS,
        kind='staging-use-after-recycle') == 1
    # quarantine preserved the escaped holder's data: the old slab was
    # never refilled, and every delivered batch still carries its values
    np.testing.assert_array_equal(leaked['v'], sources[0])
    for src, batch in zip(sources, held):
        np.testing.assert_array_equal(batch['v'].value, src)


def test_canary_trample_detected_on_recycle():
    captured = {}

    def put(tree):
        if not captured:
            captured.update(tree)
        return {k: _AcceleratorLeaf(v) for k, v in tree.items()}

    with _sanitize_env('1'):
        eng = staging.StagingEngine(8, {'v': np.float32}, 'drop', put,
                                    num_slots=2)
        rng = np.random.RandomState(1)
        eng.stage({'v': rng.rand(8, 4)}, 8)
        root = _slab_root(captured['v'])
        assert root.dtype == np.uint8   # the guarded slab is reachable
        root[-1] = 0                    # wild write past the array bounds
        captured.clear()                # drop the ref: census stays clean
        eng.stage({'v': rng.rand(8, 4)}, 8)
        eng.stage({'v': rng.rand(8, 4)}, 8)   # slot 0 recycles: verify
    kinds = [v['kind'] for v in sanitizer.violations()]
    assert kinds == ['staging-canary-trampled']
    assert eng.slabs_quarantined == 0   # trample ≠ escape: slab reused
    assert T.get_registry().counter_value(
        sanitizer.SANITIZER_CANARY_CHECKS) > 0


def test_unarmed_engine_does_no_guard_work():
    """The ``=0`` half of the overhead claim, structurally: an unarmed
    engine allocates plain slabs, runs zero canary checks, keeps no
    census, and records nothing."""
    eng = staging.StagingEngine(8, {'v': np.float32}, 'drop',
                                lambda tree: {k: _AcceleratorLeaf(v)
                                              for k, v in tree.items()},
                                num_slots=2)
    rng = np.random.RandomState(2)
    for _ in range(5):
        eng.stage({'v': rng.rand(8, 4)}, 8)
    assert eng._sanitize is False
    for ring in eng._rings.values():
        for slot in ring.slots:
            assert slot.census is None
            assert _slab_root(slot.buffers['v']).dtype == np.float32
    assert T.get_registry().counter_value(
        sanitizer.SANITIZER_CANARY_CHECKS) == 0
    assert sanitizer.violations() == []


# -- decoded-cache boundary ---------------------------------------------------


def test_cached_columns_arrive_read_only_on_both_branches(tmp_path):
    """Satellite regression: EVERY column from ``read_entry`` is
    ``writeable=False`` — the mmap-backed raw branch AND the
    pickle-fallback branch (object/ragged columns) — knob-independent,
    because the entry is shared across processes either way."""
    path = str(tmp_path / 'entry.arrow')
    cols = {
        'ids': np.arange(6, dtype=np.int64),
        'ragged': np.array([np.arange(i + 1) for i in range(6)],
                           dtype=object),
    }
    write_entry(path, cols, 6)
    got, length, mmaped, copied = read_entry(path)
    assert length == 6 and mmaped >= 1 and copied >= 1
    assert not got['ids'].flags.writeable
    assert not got['ragged'].flags.writeable
    with pytest.raises(ValueError, match='read-only'):
        got['ids'][0] = 9
    with pytest.raises(ValueError, match='read-only'):
        got['ragged'][0] = None


def test_mem_tier_freezes_shared_columns_under_sanitize(tmp_path):
    """Armed mode: the memory tier shares its array objects with the
    batch just returned to the consumer — they are frozen at ``_mem_put``
    so an in-place consumer write raises at the write site."""
    with _sanitize_env('1'):
        cache = MaterializedRowGroupCache(str(tmp_path / 'dc'), 10 ** 8,
                                          mem_limit_bytes=8 * 2 ** 20)
        arr = np.arange(8, dtype=np.float32)
        batch = cache.get('k', lambda: ColumnBatch({'v': arr}, 8))
        assert not batch.columns['v'].flags.writeable
        with pytest.raises(ValueError, match='read-only'):
            batch.columns['v'][0] = 1.0
        assert T.get_registry().counter_value(
            sanitizer.SANITIZER_VIEWS_GUARDED) >= 1


def test_oversized_batch_never_stored_stays_writable_armed(tmp_path):
    """A batch the memory tier bails out on (nbytes > mem limit) is
    never shared — the consumer keeps its own writable memory even
    under SANITIZE=1."""
    with _sanitize_env('1'):
        cache = MaterializedRowGroupCache(str(tmp_path / 'dc'), 10 ** 8,
                                          mem_limit_bytes=1024)
        arr = np.zeros(4096, dtype=np.float32)     # 16 KB > 1 KB cap
        batch = cache.get('k', lambda: ColumnBatch({'v': arr}, 4096))
        assert batch.columns['v'].flags.writeable


def test_mem_tier_fill_batch_stays_writable_unarmed(tmp_path):
    cache = MaterializedRowGroupCache(str(tmp_path / 'dc'), 10 ** 8,
                                      mem_limit_bytes=8 * 2 ** 20)
    arr = np.arange(8, dtype=np.float32)
    batch = cache.get('k', lambda: ColumnBatch({'v': arr}, 8))
    assert batch.columns['v'].flags.writeable


# -- ZMQ wire boundary --------------------------------------------------------


def test_pickle5_wire_views_forced_read_only_under_sanitize():
    """Out-of-band arrays rebuilt over MUTABLE receive buffers come back
    writable by default; armed mode forces ``writeable=False`` so a
    consumer scribbling on a wire buffer raises."""
    serializer = PickleSerializer()
    value = {'v': np.arange(16, dtype=np.int32)}
    frames = [bytes(f) for f in serializer.serialize_frames(value)]
    plain = serializer.deserialize_frames(
        [bytearray(f) for f in frames])
    assert plain['v'].flags.writeable      # the unarmed contract
    with _sanitize_env('1'):
        guarded = serializer.deserialize_frames(
            [bytearray(f) for f in frames])
        assert not guarded['v'].flags.writeable
        with pytest.raises(ValueError, match='read-only'):
            guarded['v'][0] = 1
        np.testing.assert_array_equal(guarded['v'], value['v'])
        assert T.get_registry().counter_value(
            sanitizer.SANITIZER_VIEWS_GUARDED) >= 1


def test_guard_payload_walks_batch_shapes():
    inner = np.arange(4)
    batch = ColumnBatch({'a': inner}, 4)
    with _sanitize_env('1'):
        assert sanitizer.guard_payload([batch, {'b': np.arange(2)}]) == 2
    assert not inner.flags.writeable


# -- report surface -----------------------------------------------------------


def test_pipeline_report_grows_a_pipesan_section_when_armed():
    with _sanitize_env('1'):
        sanitizer.record_violation('staging-canary-trampled', 'seeded')
        report = T.pipeline_report()
        section = report['pipesan']
        assert section['enabled'] is True
        assert section['violations'] == 1
        assert section['by_kind'] == {'staging-canary-trampled': 1}
        assert section['recent'][-1]['detail'] == 'seeded'
        assert 'pipesan' in T.format_pipeline_report(report)


def test_pipeline_report_omits_pipesan_when_unarmed_and_clean():
    assert 'pipesan' not in T.pipeline_report()


def test_report_label_parsing_is_anchored():
    """`by_kind` binning must not let a label that merely ENDS in 'kind'
    (e.g. a future srckind=) satisfy the kind= lookup."""
    from petastorm_tpu.telemetry.export import _label_of
    assert _label_of('m{kind="a"}', 'kind') == 'a'
    assert _label_of('m{srckind="a"}', 'kind') is None
    assert _label_of('m{a="x",kind="b"}', 'kind') == 'b'
    assert _label_of('m', 'kind') is None


def test_violation_ring_is_bounded_and_keeps_the_newest():
    total = sanitizer._RING_LIMIT + 10
    for i in range(total):
        sanitizer.record_violation('staging-canary-trampled', 'v%d' % i)
    kept = sanitizer.violations()
    assert len(kept) == sanitizer._RING_LIMIT
    # oldest dropped off: the 'recent' report slice stays recent
    assert kept[-1]['detail'] == 'v%d' % (total - 1)
    assert kept[0]['detail'] == 'v10'


# -- perf marker: overhead guard ---------------------------------------------


def _staged_rows_per_sec(env_value):
    with _sanitize_env(env_value):
        eng = staging.StagingEngine(
            64, {'v': np.float32}, 'drop',
            lambda tree: {k: _AcceleratorLeaf(v)
                          for k, v in tree.items()},
            num_slots=2)
        rng = np.random.RandomState(0)
        cols = {'v': rng.rand(64, 64)}            # f64 → f32: ring path
        for _ in range(5):
            eng.stage(dict(cols), 64)
        n = 200
        start = time.monotonic()
        for _ in range(n):
            eng.stage(dict(cols), 64)
        return n * 64 / (time.monotonic() - start)


@pytest.mark.perf
def test_sanitizer_overhead_stays_within_a_bounded_factor():
    """Tier-1-safe budget, deliberately loose for shared-box noise: the
    armed staging path must hold ≥ 0.25x the unarmed throughput (canary
    verification + weakref census are O(fields), not O(bytes)). The
    unarmed side costing NOTHING is held structurally by
    test_unarmed_engine_does_no_guard_work."""
    for _ in range(2):
        off = _staged_rows_per_sec(None)
        on = _staged_rows_per_sec('1')
        if on >= 0.25 * off:
            return
    pytest.fail('sanitize on: %.0f rows/s vs off: %.0f rows/s '
                '(budget: >= 0.25x)' % (on, off))
