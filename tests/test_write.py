"""The distributed write plane (ISSUE 18): fleet-ETL writer,
read-optimized layout, compaction/re-shard, bounded-staleness append.

The load-bearing contracts:

* **Backend byte-parity** — local (pool=None), thread-pool and
  service-fleet writes of the same rows produce byte-identical part
  files AND byte-identical committed manifests.
* **Crash safety (the chaos drill)** — an injected ``io.write`` fault
  mid-distributed-write publishes zero partial files; the retried job
  commits a manifest byte-identical to a clean run's.
* **Torn-free compaction** — a reader opened before a compaction swap
  is multiset-exact; one opened after sees only folded files.
* **The write→read contract** — a dataset written with a declared sort
  key, read back through pushdown + readahead with a selective
  predicate, is multiset-exact with ``rowgroups_pruned > 0``, no
  ``no-statistics`` decline, and readahead hit share > 0.8.
"""

import glob
import hashlib
import json
import os
import threading
import time

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu import faults, pushdown, readahead
from petastorm_tpu import telemetry as T
from petastorm_tpu.codecs import ScalarCodec
from petastorm_tpu.errors import MetadataError
from petastorm_tpu.etl.dataset_metadata import (
    DatasetWriter, ParquetDatasetInfo, get_schema,
)
from petastorm_tpu.filters import FiltersPredicate
from petastorm_tpu.fs import get_filesystem_and_path_or_paths
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.unischema import Unischema, UnischemaField
from petastorm_tpu.workers.thread_pool import ThreadPool
from petastorm_tpu.write import (
    AppendFollower, CompactionDaemon, DistributedDatasetWriter, ManifestError,
    compact_dataset, gc_superseded, load_manifest, plan_compaction,
    self_check, write_dataset_distributed,
)
from petastorm_tpu.write import manifest as wmanifest

SCHEMA = Unischema('WriteTest', [
    UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
    UnischemaField('val', np.float64, (), ScalarCodec(pa.float64()), False),
])

_FAST = dict(heartbeat_interval_s=0.15, liveness_timeout_s=2.0,
             connect_timeout_s=60, no_workers_timeout_s=20)


def _rows(n, start=0):
    return [{'id': i, 'val': i * 0.5} for i in range(start, start + n)]


def _read_ids(url, **kwargs):
    with make_batch_reader(url, shuffle_row_groups=False, **kwargs) as r:
        return sorted(int(i) for b in r for i in b.id)


def _part_hashes(root):
    return [hashlib.sha1(open(p, 'rb').read()).hexdigest()
            for p in sorted(glob.glob(os.path.join(root, 'part-*')))]


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    T.reset_for_tests()
    yield
    os.environ.pop('PETASTORM_TPU_FAULTS', None)
    faults.refresh_faults()
    assert faults.ARMED is None
    T.reset_for_tests()


def _service_pool(workers=1, retries=3):
    from petastorm_tpu.service.service_pool import ServicePool
    return ServicePool(spawn_local_workers=workers, max_retries=retries,
                       **_FAST)


# ---------------------------------------------------------------------------
# The commit manifest
# ---------------------------------------------------------------------------


class TestManifest:
    def test_bytes_deterministic(self):
        entries = [wmanifest.file_entry('b.parquet', 10, 1, 100),
                   wmanifest.file_entry('a.parquet', 10, 1, 100)]
        m1 = wmanifest.build_manifest(entries, generation=3, sort_key='id')
        m2 = wmanifest.build_manifest(list(reversed(entries)), generation=3,
                                      sort_key='id')
        assert wmanifest.dumps(m1) == wmanifest.dumps(m2)
        # no wall-clock state anywhere in the committed bytes
        assert b'time' not in wmanifest.dumps(m1)

    def test_swap_must_be_monotonic(self, tmp_path):
        fs, root = get_filesystem_and_path_or_paths('file://' + str(tmp_path))
        wmanifest.publish(fs, root, wmanifest.build_manifest([], generation=2))
        with pytest.raises(ManifestError, match='not monotonic'):
            wmanifest.publish(fs, root,
                              wmanifest.build_manifest([], generation=2))
        assert load_manifest(fs, root)['generation'] == 2

    def test_missing_is_none_unparseable_raises(self, tmp_path):
        fs, root = get_filesystem_and_path_or_paths('file://' + str(tmp_path))
        assert load_manifest(fs, root) is None
        (tmp_path / '_manifest.json').write_text('{nope')
        with pytest.raises(ManifestError, match='Unparseable'):
            load_manifest(fs, root)

    def test_staleness_from_file_mtime(self, tmp_path):
        fs, root = get_filesystem_and_path_or_paths('file://' + str(tmp_path))
        assert wmanifest.staleness_s(fs, root) is None
        wmanifest.publish(fs, root, wmanifest.build_manifest([], generation=1))
        age = wmanifest.staleness_s(fs, root)
        assert age is not None and age < 30.0

    def test_publish_serializes_via_commit_lease(self, tmp_path):
        """A held commit lease blocks a second committer loudly; a lease
        orphaned by a dead committer is broken once stale."""
        fs, root = get_filesystem_and_path_or_paths('file://' + str(tmp_path))
        lock_file = tmp_path / '_manifest.lock'
        lock_file.write_bytes(b'held by a live committer')
        with pytest.raises(ManifestError, match='lease'):
            wmanifest.publish(fs, root,
                              wmanifest.build_manifest([], generation=1),
                              lock_timeout_s=0.3)
        old = time.time() - 3600
        os.utime(lock_file, (old, old))
        with wmanifest.CommitLock(fs, root, timeout_s=5.0, stale_s=60.0):
            pass  # stale lease broken, fresh one taken and released
        assert not lock_file.exists()
        wmanifest.publish(fs, root, wmanifest.build_manifest([], generation=1))
        assert load_manifest(fs, root)['generation'] == 1
        assert not lock_file.exists()  # publish releases its own lease

    def test_load_propagates_transient_io_errors(self, tmp_path):
        """A transiently unreadable manifest must NOT read as
        'manifest-less dataset' — that silently degrades discovery to
        the torn directory walk."""
        fs, root = get_filesystem_and_path_or_paths('file://' + str(tmp_path))
        wmanifest.publish(fs, root, wmanifest.build_manifest([], generation=1))

        class FlakyFS:
            def exists(self, path):
                return fs.exists(path)

            def open(self, *args, **kwargs):
                raise OSError('transient storage hiccup')

        with pytest.raises(OSError, match='transient'):
            wmanifest.load(FlakyFS(), root)
        assert wmanifest.load(fs, root)['generation'] == 1

    def test_purge_respects_age_gate(self, tmp_path):
        fs, root = get_filesystem_and_path_or_paths('file://' + str(tmp_path))
        fresh = tmp_path / '.tmp.part-live.parquet'
        fresh.write_bytes(b'x')
        assert wmanifest.purge_stale_tmp(fs, root) == 0  # too young
        assert fresh.exists()
        assert wmanifest.purge_stale_tmp(fs, root, max_age_s=0.0) == 1
        assert not fresh.exists()


# ---------------------------------------------------------------------------
# Local backend + round-trip
# ---------------------------------------------------------------------------


class TestLocalWrite:
    def test_write_commit_read_round_trip(self, tmp_path):
        url = 'file://' + str(tmp_path)
        w = write_dataset_distributed(url, SCHEMA, _rows(300), sort_by='id',
                                      shard_rows=100)
        assert w.manifest['generation'] == 1
        assert all(e['path'].startswith('part-g0001-s')
                   for e in w.manifest['files'])
        assert _read_ids(url) == list(range(300))
        # Unischema fidelity: the committed footer round-trips the schema
        assert {f.name for f in get_schema(ParquetDatasetInfo(url))} == \
            {'id', 'val'}

    def test_no_tmp_litter_after_commit(self, tmp_path):
        url = 'file://' + str(tmp_path)
        write_dataset_distributed(url, SCHEMA, _rows(100), shard_rows=40)
        assert glob.glob(str(tmp_path / '.tmp.*')) == []

    def test_zero_row_dataset_commits_cleanly(self, tmp_path):
        from petastorm_tpu.errors import NoDataAvailableError
        url = 'file://' + str(tmp_path)
        w = write_dataset_distributed(url, SCHEMA, [])
        assert w.manifest['generation'] == 1
        assert w.manifest['files'][0]['rows'] == 0
        # schema round-trips even with zero rows; the reader's existing
        # no-row-groups guard fires rather than anything torn
        assert {f.name for f in get_schema(ParquetDatasetInfo(url))} == \
            {'id', 'val'}
        with pytest.raises(NoDataAvailableError):
            _read_ids(url)

    def test_fresh_target_refuses_second_nonappend_write(self, tmp_path):
        url = 'file://' + str(tmp_path)
        write_dataset_distributed(url, SCHEMA, _rows(10))
        with pytest.raises(ValueError, match='append=True'):
            DistributedDatasetWriter(url, SCHEMA)

    def test_abort_on_exception_leaves_no_generation_litter(self, tmp_path):
        url = 'file://' + str(tmp_path)
        with pytest.raises(RuntimeError, match='boom'):
            with DistributedDatasetWriter(url, SCHEMA, shard_rows=20) as w:
                w.write_row_dicts(_rows(50))  # dispatches 2 shards inline
                raise RuntimeError('boom')
        assert glob.glob(str(tmp_path / 'part-*')) == []
        assert glob.glob(str(tmp_path / '.tmp.*')) == []
        assert load_manifest(*get_filesystem_and_path_or_paths(url)) is None

    def test_write_metrics_and_report_section(self, tmp_path):
        url = 'file://' + str(tmp_path)
        write_dataset_distributed(url, SCHEMA, _rows(120), shard_rows=60)
        registry = T.get_registry()
        assert registry.counter_value(
            'petastorm_tpu_write_rows_total') == 120
        assert registry.counter_value(
            'petastorm_tpu_write_files_total') == 2
        assert registry.counter_value(
            'petastorm_tpu_write_commits_total') == 1
        report = T.pipeline_report()
        assert report['write']['rows_written'] == 120
        assert report['write']['generation'] == 1
        assert any('write plane:' in line
                   for line in T.format_pipeline_report(report).splitlines())


# ---------------------------------------------------------------------------
# Satellite: DatasetWriter lifecycle + statistics hygiene
# ---------------------------------------------------------------------------


class TestDatasetWriterLifecycle:
    def test_exception_path_aborts_not_publishes(self, tmp_path):
        url = 'file://' + str(tmp_path / 'ds')
        with pytest.raises(RuntimeError, match='boom'):
            with DatasetWriter(url, SCHEMA, rowgroup_size_rows=10,
                               workers_count=2) as w:
                w.write_row_dicts(_rows(25))
                raise RuntimeError('boom')
        # no partial output, and the encode pool is gone
        assert glob.glob(str(tmp_path / 'ds' / '*.parquet')) == []
        assert w._encode_pool is None

    def test_success_path_still_publishes(self, tmp_path):
        url = 'file://' + str(tmp_path / 'ds')
        with DatasetWriter(url, SCHEMA, rowgroup_size_rows=10) as w:
            w.write_row_dicts(_rows(25))
        assert len(w.paths_written) == 1
        assert w._rows_written == 25

    def test_footer_statistics_always_written(self, tmp_path):
        import pyarrow.parquet as pq
        url = 'file://' + str(tmp_path / 'ds')
        with DatasetWriter(url, SCHEMA, rowgroup_size_rows=10,
                           sort_by='id') as w:
            w.write_row_dicts(_rows(30))
        meta = pq.read_metadata(w.paths_written[0])
        for rg in range(meta.num_row_groups):
            st = meta.row_group(rg).column(0).statistics
            assert st is not None and st.has_min_max
        assert meta.row_group(0).sorting_columns  # sort key stamped

    def test_sort_by_unknown_column_rejected(self, tmp_path):
        with pytest.raises(ValueError, match='not in the schema'):
            DatasetWriter('file://' + str(tmp_path), SCHEMA, sort_by='nope')

    def test_pushdown_never_declines_no_statistics_on_own_output(
            self, tmp_path):
        """Satellite 2: the whole point of write_statistics hygiene —
        a self-written dataset is never full-scan-priced for lack of
        footer statistics."""
        url = 'file://' + str(tmp_path)
        write_dataset_distributed(url, SCHEMA, _rows(200), sort_by='id',
                                  shard_rows=50)
        T.reset_for_tests()
        pred = FiltersPredicate([('id', '<', 20)])
        got = _read_ids(url, predicate=pred)
        assert got == list(range(20))
        summary = pushdown.planner_summary()
        assert summary['declines'].get('no-statistics', 0) == 0
        assert summary['rowgroups_pruned'] > 0


# ---------------------------------------------------------------------------
# Backend byte-parity (local / thread / service fleet)
# ---------------------------------------------------------------------------


class TestBackendParity:
    def test_thread_pool_matches_local_bytes(self, tmp_path):
        rows = _rows(300)
        w_local = write_dataset_distributed(
            'file://' + str(tmp_path / 'local'), SCHEMA, rows,
            sort_by='id', shard_rows=75)
        w_thread = write_dataset_distributed(
            'file://' + str(tmp_path / 'thread'), SCHEMA, rows,
            sort_by='id', shard_rows=75, pool=ThreadPool(3))
        assert wmanifest.dumps(w_local.manifest) == \
            wmanifest.dumps(w_thread.manifest)
        assert _part_hashes(str(tmp_path / 'local')) == \
            _part_hashes(str(tmp_path / 'thread'))

    def test_service_fleet_matches_local_bytes(self, tmp_path):
        rows = _rows(200)
        w_local = write_dataset_distributed(
            'file://' + str(tmp_path / 'local'), SCHEMA, rows,
            sort_by='id', shard_rows=50)
        w_fleet = write_dataset_distributed(
            'file://' + str(tmp_path / 'fleet'), SCHEMA, rows,
            sort_by='id', shard_rows=50, pool=_service_pool(workers=2))
        assert wmanifest.dumps(w_local.manifest) == \
            wmanifest.dumps(w_fleet.manifest)
        assert _part_hashes(str(tmp_path / 'local')) == \
            _part_hashes(str(tmp_path / 'fleet'))


# ---------------------------------------------------------------------------
# Crash safety: the chaos drill
# ---------------------------------------------------------------------------


def _arm(spec):
    os.environ['PETASTORM_TPU_FAULTS'] = spec
    faults.refresh_faults()


def _disarm():
    os.environ.pop('PETASTORM_TPU_FAULTS', None)
    faults.refresh_faults()


class TestCrashSafety:
    def test_faulted_rename_retries_to_byte_identical_manifest(
            self, tmp_path):
        """The acceptance drill: an io.write fault at the publication
        rename kills the first shard attempt; the fleet retries and the
        committed manifest + part files are byte-identical to a clean
        run. Zero partial files are ever visible under the final names.
        """
        rows = _rows(200)
        w_clean = write_dataset_distributed(
            'file://' + str(tmp_path / 'clean'), SCHEMA, rows,
            sort_by='id', shard_rows=50)
        _arm('io.write:error:1:times=1:match=#rename')
        try:
            w_chaos = write_dataset_distributed(
                'file://' + str(tmp_path / 'chaos'), SCHEMA, rows,
                sort_by='id', shard_rows=50,
                pool=_service_pool(workers=1, retries=3))
        finally:
            _disarm()
        assert wmanifest.dumps(w_clean.manifest) == \
            wmanifest.dumps(w_chaos.manifest)
        assert _part_hashes(str(tmp_path / 'clean')) == \
            _part_hashes(str(tmp_path / 'chaos'))
        assert glob.glob(str(tmp_path / 'chaos' / '.tmp.*')) == []

    def test_fault_before_part_write_publishes_nothing(self, tmp_path):
        """A fault before any data write (the #part seam) on EVERY
        attempt exhausts the retry budget: the write raises, no final
        part file and no manifest are ever published."""
        url = 'file://' + str(tmp_path)
        _arm('io.write:error:1:match=#part')
        try:
            with pytest.raises(Exception):
                write_dataset_distributed(url, SCHEMA, _rows(60),
                                          shard_rows=30)
        finally:
            _disarm()
        assert glob.glob(str(tmp_path / 'part-*')) == []
        assert load_manifest(*get_filesystem_and_path_or_paths(url)) is None

    def test_faulted_manifest_swap_keeps_previous_generation(self,
                                                             tmp_path):
        """A fault at the #manifest seam mid-append: the new generation
        never commits, and readers keep seeing generation 1 exactly."""
        url = 'file://' + str(tmp_path)
        write_dataset_distributed(url, SCHEMA, _rows(100), shard_rows=50)
        _arm('io.write:error:1:match=#manifest')
        try:
            with pytest.raises(Exception):
                write_dataset_distributed(url, SCHEMA, _rows(100, start=100),
                                          shard_rows=50, append=True)
        finally:
            _disarm()
        fs, root = get_filesystem_and_path_or_paths(url)
        assert load_manifest(fs, root)['generation'] == 1
        assert _read_ids(url) == list(range(100))


# ---------------------------------------------------------------------------
# Compaction
# ---------------------------------------------------------------------------


def _small_file_dataset(tmp_path, files=6, rows_per=30):
    url = 'file://' + str(tmp_path)
    w = None
    for i in range(files):
        w = write_dataset_distributed(
            url, SCHEMA, _rows(rows_per, start=i * rows_per),
            sort_by='id', shard_rows=rows_per, append=(i > 0))
    return url, files * rows_per, w


class TestCompaction:
    def test_fold_preserves_rows_schema_and_statistics(self, tmp_path):
        import pyarrow.parquet as pq
        url, total, _ = _small_file_dataset(tmp_path)
        m = compact_dataset(url, minimum=2)
        assert m is not None
        folded = [e for e in m['files'] if e['source'] == 'compact']
        assert folded and all(e['replaces'] for e in folded)
        assert _read_ids(url) == list(range(total))
        assert {f.name for f in get_schema(ParquetDatasetInfo(url))} == \
            {'id', 'val'}
        fs, root = get_filesystem_and_path_or_paths(url)
        for e in folded:
            with fs.open(os.path.join(root, e['path']), 'rb') as f:
                meta = pq.read_metadata(f)
            st = meta.row_group(0).column(0).statistics
            assert st is not None and st.has_min_max

    def test_concurrent_reader_is_multiset_exact_across_swap(self,
                                                             tmp_path):
        """A reader that resolved the pre-compaction manifest keeps its
        file set; the swap happens mid-iteration and the delivered
        multiset is exact — no torn mix, no loss, no duplication."""
        url, total, _ = _small_file_dataset(tmp_path)
        got = []
        with make_batch_reader(url, shuffle_row_groups=False) as reader:
            it = iter(reader)
            got.extend(int(i) for i in next(it).id)
            assert compact_dataset(url, minimum=2) is not None
            for batch in it:
                got.extend(int(i) for i in batch.id)
        assert sorted(got) == list(range(total))
        # a reader opened AFTER the swap sees only the folded layout
        assert _read_ids(url) == list(range(total))

    def test_gc_waits_out_the_grace_window(self, tmp_path):
        url, total, _ = _small_file_dataset(tmp_path)
        compact_dataset(url, minimum=2)
        fs, root = get_filesystem_and_path_or_paths(url)
        assert gc_superseded(fs, root, grace_s=3600) == []  # readers live
        removed = gc_superseded(fs, root, grace_s=0)
        assert removed
        assert _read_ids(url) == list(range(total))

    def test_gc_grace_measured_from_swap_not_file_age(self, tmp_path):
        """High-severity regression: hour-old source files must NOT be
        GC'd the instant a compaction supersedes them — the grace
        window runs from the manifest swap, so a reader that resolved
        the previous generation seconds before the swap keeps its
        files."""
        url, total, _ = _small_file_dataset(tmp_path)
        old = time.time() - 7200
        for p in glob.glob(str(tmp_path / 'part-*')):
            os.utime(p, (old, old))
        assert compact_dataset(url, minimum=2) is not None
        fs, root = get_filesystem_and_path_or_paths(url)
        assert gc_superseded(fs, root, grace_s=5.0) == []
        assert _read_ids(url) == list(range(total))

    def test_reader_holding_old_file_list_survives_restamp(self, tmp_path):
        """The footer restamp merges the previous generation's
        row-group counts: a reader that resolved the pre-swap file list
        (or opens between restamp and swap) still loads row-groups for
        the superseded files it holds."""
        from petastorm_tpu.etl.dataset_metadata import load_row_groups
        url, total, _ = _small_file_dataset(tmp_path)
        old_paths = list(ParquetDatasetInfo(url).file_paths)
        assert compact_dataset(url, minimum=2) is not None
        stale = ParquetDatasetInfo(url, validate=False)
        stale.file_paths = old_paths
        pieces = load_row_groups(stale)  # no MetadataError
        assert len(pieces) >= len(old_paths)

    def test_plan_respects_min_files_floor(self):
        committed = wmanifest.build_manifest(
            [wmanifest.file_entry('a.parquet', 10, 1, 100),
             wmanifest.file_entry('b.parquet', 10, 1, 100)],
            generation=1)
        assert plan_compaction(committed, minimum=3) == []
        assert plan_compaction(committed, minimum=2)

    def test_nothing_to_fold_returns_none(self, tmp_path):
        url = 'file://' + str(tmp_path)
        write_dataset_distributed(url, SCHEMA, _rows(50), shard_rows=50)
        assert compact_dataset(url, minimum=4) is None

    def test_restores_sort_after_interleaved_appends(self, tmp_path):
        """Appends interleave key ranges; the fold re-sorts, so the
        self-check's predicted prune share recovers."""
        url = 'file://' + str(tmp_path)
        write_dataset_distributed(url, SCHEMA,
                                  _rows(50) + _rows(50, start=100),
                                  sort_by='id', shard_rows=25)
        write_dataset_distributed(url, SCHEMA,
                                  _rows(50, start=50) + _rows(50, start=150),
                                  sort_by='id', shard_rows=25, append=True)
        compact_dataset(url, minimum=2,
                        target_bytes=16 * 1024)  # force multiple outputs
        report = self_check(url, sort_key='id')
        assert report['stats_coverage'] == 1.0
        assert _read_ids(url) == list(range(200))


# ---------------------------------------------------------------------------
# Bounded-staleness append
# ---------------------------------------------------------------------------


class TestAppend:
    def test_generations_are_monotonic_and_union(self, tmp_path):
        url = 'file://' + str(tmp_path)
        w1 = write_dataset_distributed(url, SCHEMA, _rows(50), shard_rows=50,
                                       sort_by='id')
        w2 = write_dataset_distributed(url, SCHEMA, _rows(50, start=50),
                                       shard_rows=50, append=True)
        assert (w1.manifest['generation'], w2.manifest['generation']) == (1, 2)
        assert w2.sort_by == 'id'  # inherited from the committed manifest
        assert len(w2.manifest['files']) == 2
        assert _read_ids(url) == list(range(100))

    def test_reader_staleness_opt_in(self, tmp_path):
        url = 'file://' + str(tmp_path)
        bare_url = 'file://' + str(tmp_path / 'bare')
        write_dataset_distributed(url, SCHEMA, _rows(30), shard_rows=30)
        assert _read_ids(url, max_staleness_s=5) == list(range(30))
        # a manifest-less dataset has no commit point to bound against
        with DatasetWriter(bare_url, SCHEMA) as w:
            w.write_row_dicts(_rows(10))
        with pytest.raises(ValueError, match='committed manifest'):
            make_batch_reader(bare_url, max_staleness_s=5)

    def test_follower_picks_up_rows_within_bound(self, tmp_path):
        url = 'file://' + str(tmp_path)
        write_dataset_distributed(url, SCHEMA, _rows(60), shard_rows=30)
        seen = []
        stamps = {}
        follower = AppendFollower(url, max_staleness_s=0.4,
                                  stop_after_idle_s=3.0)

        def consume():
            for batch in follower:
                seen.extend(int(i) for i in batch.id)
                stamps[len(seen)] = time.monotonic()

        thread = threading.Thread(target=consume)
        thread.start()
        time.sleep(1.0)
        committed_at = time.monotonic()
        write_dataset_distributed(url, SCHEMA, _rows(40, start=60),
                                  shard_rows=40, append=True)
        thread.join(timeout=30)
        assert not thread.is_alive()
        assert sorted(seen) == list(range(100))
        # the appended rows arrived within the staleness bound (+read)
        first_tail_stamp = min(t for n, t in stamps.items() if n > 60)
        assert first_tail_stamp - committed_at < 3.0

    def test_follower_skips_delivered_compaction_folds(self, tmp_path):
        url, total, _ = _small_file_dataset(tmp_path, files=4, rows_per=20)
        follower = AppendFollower(url, max_staleness_s=0.2,
                                  stop_after_idle_s=1.5)
        seen = []

        def consume():
            for batch in follower:
                seen.extend(int(i) for i in batch.id)

        thread = threading.Thread(target=consume)
        thread.start()
        time.sleep(0.8)  # let the initial generation drain
        compact_dataset(url, minimum=2)
        thread.join(timeout=30)
        assert not thread.is_alive()
        # the fold's rows already flowed through the source files:
        # exactly-once, no redelivery
        assert sorted(seen) == list(range(total))


    def test_partial_fold_delivers_only_undelivered_sources(self, tmp_path):
        """A fold that mixes delivered and undelivered sources must not
        be delivered whole (that redelivers consumed rows): the
        follower reads the still-on-disk undelivered source files
        directly, and the fold is settled afterwards."""
        url = 'file://' + str(tmp_path)
        write_dataset_distributed(url, SCHEMA, _rows(40), shard_rows=20,
                                  sort_by='id')
        follower = AppendFollower(url)
        first = follower._fresh_entries()
        assert len(first) == 2
        follower._mark_delivered(first)
        # a new generation lands, then compaction folds it together
        # with the already-delivered files
        write_dataset_distributed(url, SCHEMA, _rows(40, start=40),
                                  shard_rows=40, append=True)
        assert compact_dataset(url, minimum=2) is not None
        fresh = follower._fresh_entries()
        assert fresh and all(e.get('settles') for e in fresh)
        urls = [url.rstrip('/') + '/' + e['path'] for e in fresh]
        with make_batch_reader(urls, shuffle_row_groups=False) as reader:
            got = sorted(int(i) for b in reader for i in b.id)
        assert got == list(range(40, 80))  # ONLY the undelivered rows
        follower._mark_delivered(fresh)
        # the fold is settled: the next generation delivers only its
        # own new file, nothing from the fold
        write_dataset_distributed(url, SCHEMA, _rows(10, start=80),
                                  shard_rows=10, append=True)
        nxt = follower._fresh_entries()
        assert len(nxt) == 1 and not nxt[0].get('replaces')


# ---------------------------------------------------------------------------
# Concurrent committers: the commit lease
# ---------------------------------------------------------------------------


class TestCommitConcurrency:
    def test_append_commit_rebases_over_concurrent_compaction(self,
                                                              tmp_path):
        """Lost-update regression: an append writer whose base
        generation is compacted away mid-write rebases onto the latest
        manifest at commit — the fold keeps its files, the append
        stacks on top, nothing is dropped or resurrected."""
        url, total, _ = _small_file_dataset(tmp_path, files=4, rows_per=20)
        w = DistributedDatasetWriter(url, SCHEMA, shard_rows=40, append=True)
        w.write_row_dicts(_rows(40, start=total))
        compacted = compact_dataset(url, minimum=2)
        assert compacted is not None  # swapped a generation mid-write
        w.close()
        assert w.manifest['generation'] == compacted['generation'] + 1
        assert any(e['source'] == 'compact' for e in w.manifest['files'])
        assert _read_ids(url) == list(range(total + 40))

    def test_same_generation_part_collision_fails_loudly(self, tmp_path):
        """Two appenders racing the same generation collide on the
        deterministic part names: the second must fail loudly instead
        of silently replacing the first's committed bytes."""
        url = 'file://' + str(tmp_path)
        write_dataset_distributed(url, SCHEMA, _rows(50), shard_rows=50)
        wa = DistributedDatasetWriter(url, SCHEMA, shard_rows=50, append=True)
        wb = DistributedDatasetWriter(url, SCHEMA, shard_rows=50, append=True)
        wa.write_row_dicts(_rows(50, start=50))  # renamed into place inline
        with pytest.raises(RuntimeError, match='collision'):
            wb.write_row_dicts(_rows(50, start=100))
        wa.close()
        assert _read_ids(url) == list(range(100))


# ---------------------------------------------------------------------------
# Layout: targets + self-check
# ---------------------------------------------------------------------------


class TestLayout:
    def test_target_tracks_readahead_window(self, monkeypatch):
        from petastorm_tpu.write import layout
        monkeypatch.setenv('PETASTORM_TPU_READAHEAD_MAX_RANGE_MB', '8')
        assert layout.target_rowgroup_bytes() == 8 * 1024 * 1024
        monkeypatch.setenv('PETASTORM_TPU_WRITE_ROWGROUP_MB', '4')
        assert layout.target_rowgroup_bytes() == 4 * 1024 * 1024

    def test_sorted_dataset_reports_clean(self, tmp_path):
        url = 'file://' + str(tmp_path)
        w = write_dataset_distributed(url, SCHEMA, _rows(400), sort_by='id',
                                      shard_rows=100)
        report = w.last_self_check
        assert report is not None
        assert report['stats_coverage'] == 1.0
        assert report['predicted_prune_share'] > 0.5
        assert report['coalesce']['fits_window_share'] == 1.0
        assert report['warnings'] == []

    def test_scattered_sort_key_warns(self, tmp_path):
        url = 'file://' + str(tmp_path)
        rng = np.random.RandomState(7)
        ids = rng.permutation(400)
        rows = [{'id': int(i), 'val': float(i)} for i in ids]
        write_dataset_distributed(url, SCHEMA, rows, sort_by='id',
                                  shard_rows=100)
        report = self_check(url, sort_key='id')
        assert report['predicted_prune_share'] < 0.5
        assert any('prunes only' in warning for warning in report['warnings'])

    def test_self_check_knob_skips(self, tmp_path, monkeypatch):
        monkeypatch.setenv('PETASTORM_TPU_WRITE_SELF_CHECK', '0')
        url = 'file://' + str(tmp_path)
        w = write_dataset_distributed(url, SCHEMA, _rows(50), shard_rows=50)
        assert w.last_self_check is None


# ---------------------------------------------------------------------------
# Satellite 3: the write→read property test
# ---------------------------------------------------------------------------


class TestWriteReadContract:
    @pytest.mark.parametrize('backend', ['local', 'fleet'])
    def test_selective_read_is_index_priced_and_exact(self, tmp_path,
                                                      backend):
        """Write with the new plane (both backends), read back through
        pushdown + readahead with a selective predicate: exact row
        multiset, rowgroups pruned, readahead hit share > 0.8."""
        url = 'file://' + str(tmp_path)
        pool = ThreadPool(3) if backend == 'fleet' else None
        write_dataset_distributed(url, SCHEMA, _rows(400), sort_by='id',
                                  shard_rows=50, pool=pool)
        T.reset_for_tests()
        pred = FiltersPredicate([('id', '>=', 300)])
        got = _read_ids(url, predicate=pred, num_epochs=4)
        assert got == sorted(list(range(300, 400)) * 4)
        summary = pushdown.planner_summary()
        assert summary['rowgroups_pruned'] > 0
        assert summary['declines'].get('no-statistics', 0) == 0
        registry = T.get_registry()
        hits = registry.counter_value(readahead.READAHEAD_HITS)
        misses = registry.counter_value(readahead.READAHEAD_MISSES)
        assert hits + misses > 0
        assert hits / (hits + misses) > 0.8

    def test_full_multiset_parity_against_oracle(self, tmp_path):
        """Both planes off (the oracle) vs both on: identical rows."""
        url = 'file://' + str(tmp_path)
        write_dataset_distributed(url, SCHEMA, _rows(200), sort_by='id',
                                  shard_rows=40)
        pred = FiltersPredicate([('id', 'in', (3, 77, 150, 199))])
        saved = dict(os.environ)
        os.environ['PETASTORM_TPU_PUSHDOWN'] = '0'
        os.environ['PETASTORM_TPU_READAHEAD'] = '0'
        try:
            oracle = _read_ids(url, predicate=pred)
        finally:
            os.environ.clear()
            os.environ.update(saved)
        assert _read_ids(url, predicate=pred) == oracle == [3, 77, 150, 199]


# ---------------------------------------------------------------------------
# PR 19 satellites: trace threading, the staleness gauge, the daemon mount
# ---------------------------------------------------------------------------


class TestWriteObservability:
    def test_writer_threads_trace_and_dumps_chrome_json(self, tmp_path,
                                                        monkeypatch):
        """With tracing armed the writer mints per-shard contexts, the
        encode/write_flush stages land in the flight recorder, and
        ``dump_trace`` exports them as Chrome trace-event JSON — the
        write-plane sibling of ``Reader.dump_trace``."""
        monkeypatch.setenv('PETASTORM_TPU_TRACE', '1')
        monkeypatch.setenv('PETASTORM_TPU_TRACE_SAMPLE', '1')
        T.refresh()
        url = 'file://' + str(tmp_path / 'traced')
        w = write_dataset_distributed(url, SCHEMA, _rows(40), shard_rows=20)
        out = str(tmp_path / 'write-trace.json')
        assert w.dump_trace(out) > 0
        with open(out) as f:
            doc = json.load(f)
        names = {e.get('name') for e in doc['traceEvents']}
        assert {'encode', 'write_flush'} <= names, names
        # the shard lifelines carry minted contexts, so the critical-path
        # engine can reconstruct the write plane too
        assert any((e.get('args') or {}).get('trace_id')
                   for e in doc['traceEvents'])

    def test_append_follower_publishes_staleness_gauge(self, tmp_path):
        from petastorm_tpu.write import append as wappend
        url = 'file://' + str(tmp_path)
        write_dataset_distributed(url, SCHEMA, _rows(20), shard_rows=20)
        follower = AppendFollower(url, max_staleness_s=0.2)
        reg = T.get_registry()
        follower._note_staleness(True)   # undelivered rows pending
        lag = reg.gauge_value(wappend.APPEND_STALENESS)
        assert lag is not None and lag >= 0.0
        follower._note_staleness(False)  # caught up
        assert reg.gauge_value(wappend.APPEND_STALENESS) == 0.0

    def test_compaction_daemon_mounts_health_section(self, tmp_path,
                                                     monkeypatch):
        from petastorm_tpu.telemetry import obs_server
        monkeypatch.setenv('PETASTORM_TPU_OBS_PORT', '0')
        T.refresh()
        url, total, _ = _small_file_dataset(tmp_path, files=6, rows_per=30)
        daemon = CompactionDaemon(url, interval_s=0.1, gc_grace_s=600.0)
        daemon.start()
        try:
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and daemon.runs == 0:
                time.sleep(0.05)
            assert daemon.runs >= 1, 'daemon never folded the small files'
            health = obs_server.build_health()
            section = next(v for k, v in health['components'].items()
                           if k.startswith('compaction-daemon'))
            assert section['dataset_url'] == url
            assert section['runs'] >= 1
            assert section['generation'] >= 2  # the fold published
        finally:
            daemon.stop()
        # stop() unmounts: a dead daemon must not linger in /health
        assert not any(k.startswith('compaction-daemon')
                       for k in obs_server.build_health()['components'])
        assert _read_ids(url) == list(range(total))
