"""Predicate unit tests: columnar (do_include_batch) vs per-row parity, and
bit-parity of the pseudorandom split against the reference's md5 bucketing.

Reference: ``petastorm/predicates.py:26-183``, ``tests/test_predicates.py``.
"""

import os
import sys
import types

import numpy as np
import pytest

from petastorm_tpu.predicates import (
    in_intersection, in_lambda, in_negate, in_pseudorandom_split, in_reduce,
    in_set,
)

REFERENCE_ROOT = '/root/reference/petastorm'


def _row_loop(pred, columns):
    fields = sorted(pred.get_fields())
    n = len(columns[fields[0]])
    return np.array([pred.do_include({f: columns[f][i] for f in fields})
                     for i in range(n)], dtype=bool)


def _assert_batch_matches_rows(pred, columns):
    batch = pred.do_include_batch(columns)
    assert batch is not None
    np.testing.assert_array_equal(np.asarray(batch, bool),
                                  _row_loop(pred, columns))


class TestColumnarParity:
    def test_in_set_numeric(self):
        cols = {'id': np.arange(50)}
        _assert_batch_matches_rows(in_set({3, 7, 49, 1000}, 'id'), cols)

    def test_in_set_strings(self):
        cols = {'k': ['a_%d' % (i % 5) for i in range(30)]}
        _assert_batch_matches_rows(in_set({'a_1', 'a_4', 'zzz'}, 'k'), cols)

    def test_in_set_object_array(self):
        cols = {'k': np.array(['x', 'y', None, 'x'], dtype=object)}
        _assert_batch_matches_rows(in_set({'x'}, 'k'), cols)

    def test_in_intersection(self):
        cols = {'tags': [['a', 'b'], ['c'], [], ['b', 'd']]}
        _assert_batch_matches_rows(in_intersection({'b'}, 'tags'), cols)

    def test_in_negate(self):
        cols = {'id': np.arange(20)}
        _assert_batch_matches_rows(in_negate(in_set({1, 2}, 'id')), cols)

    def test_in_negate_of_lambda_falls_back(self):
        pred = in_negate(in_lambda(['id'], lambda v: v['id'] > 3))
        assert pred.do_include_batch({'id': np.arange(5)}) is None

    def test_in_reduce_all_any(self):
        cols = {'id': np.arange(40), 'k': ['s%d' % (i % 4) for i in range(40)]}
        for func in (all, any):
            pred = in_reduce([in_set(set(range(0, 40, 3)), 'id'),
                              in_set({'s1', 's2'}, 'k')], func)
            _assert_batch_matches_rows(pred, cols)

    def test_in_reduce_custom_func(self):
        cols = {'id': np.arange(30)}
        pred = in_reduce([in_set(set(range(10)), 'id'),
                          in_set(set(range(5, 15)), 'id'),
                          in_set(set(range(8, 40)), 'id')],
                         lambda votes: votes.count(True) >= 2)
        _assert_batch_matches_rows(pred, cols)

    def test_in_reduce_with_lambda_child_falls_back(self):
        pred = in_reduce([in_set({1}, 'id'),
                          in_lambda(['id'], lambda v: True)], all)
        assert pred.do_include_batch({'id': np.arange(3)}) is None

    def test_in_lambda_has_no_columnar_form(self):
        pred = in_lambda(['id'], lambda v: v['id'] % 2 == 0)
        assert pred.do_include_batch({'id': np.arange(4)}) is None

    def test_pseudorandom_split_batch(self):
        cols = {'id': np.arange(200)}
        _assert_batch_matches_rows(
            in_pseudorandom_split([0.3, 0.3, 0.4], 1, 'id'), cols)


@pytest.mark.skipif(not os.path.isdir(REFERENCE_ROOT),
                    reason='reference petastorm checkout not present')
class TestReferenceSplitParity:
    """in_pseudorandom_split must bucket values bit-identically to the
    reference's md5 math (``petastorm/predicates.py:144-183``) so existing
    train/val/test splits reproduce across frameworks."""

    @pytest.fixture(scope='class')
    def ref_predicates(self):
        saved = sys.modules.get('petastorm')
        pkg = types.ModuleType('petastorm')
        pkg.__path__ = [REFERENCE_ROOT]
        sys.modules['petastorm'] = pkg
        sys.modules.pop('petastorm.predicates', None)
        try:
            import petastorm.predicates as ref_preds
            yield ref_preds
        finally:
            sys.modules.pop('petastorm.predicates', None)
            if saved is None:
                sys.modules.pop('petastorm', None)
            else:
                sys.modules['petastorm'] = saved

    def test_bucket_assignment_matches(self, ref_predicates):
        fractions = [0.4, 0.3, 0.3]
        values = (['%d' % i for i in range(300)]
                  + ['key_%d' % i for i in range(300)]
                  + list(range(300)))
        for subset in range(3):
            ours = in_pseudorandom_split(fractions, subset, 'f')
            theirs = ref_predicates.in_pseudorandom_split(fractions, subset, 'f')
            our_mask = [ours.do_include({'f': v}) for v in values]
            their_mask = [theirs.do_include({'f': v}) for v in values]
            assert our_mask == their_mask

    def test_every_value_in_exactly_one_subset(self, ref_predicates):
        fractions = [0.25, 0.25, 0.5]
        values = ['row_%d' % i for i in range(500)]
        counts = np.zeros(len(values), dtype=int)
        for subset in range(3):
            pred = in_pseudorandom_split(fractions, subset, 'f')
            counts += np.array([pred.do_include({'f': v}) for v in values])
        assert (counts == 1).all()


def test_in_set_mixed_type_values_match_row_semantics():
    # numpy coerces [1, 'a'] to strings; the batch path must not use that
    cols = {'id': np.arange(5, dtype=np.int32)}
    pred = in_set({1, 'a'}, 'id')
    _assert_batch_matches_rows(pred, cols)
    assert pred.do_include({'id': 1})
