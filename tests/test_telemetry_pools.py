"""Telemetry across the pool flavors: gauge-name parity, worker-delta
aggregation over the result channels, and the stall attributor's
producer/consumer-bound verdicts — the ISSUE's acceptance criteria.

Service-pool tests spawn real localhost worker-server subprocesses and are
marked ``service`` like tests/test_service.py (tier-1, tight timeouts).
"""

import time

import pytest

from petastorm_tpu import telemetry as T
from petastorm_tpu.telemetry.spans import STAGE_SECONDS
from petastorm_tpu.workers import EmptyResultError, SHARED_POOL_GAUGES
from petastorm_tpu.workers.dummy_pool import DummyPool
from petastorm_tpu.workers.thread_pool import ThreadPool
from tests.stub_workers import (
    IdentityWorker, SleepyIdentityWorker, SpanningSleepyWorker,
)

_RESULT_TIMEOUT_S = 60


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    T.reset_for_tests()
    yield
    T.reset_for_tests()


@pytest.fixture
def small_scalar_dataset(tmp_path):
    """8 single-row-group files: enough ventilated items for pool gauges
    and stall scenarios without a session-scoped fixture dependency."""
    from tests.test_common import create_test_scalar_dataset
    url = 'file://' + str(tmp_path / 'dataset')
    create_test_scalar_dataset(url, num_rows=80, num_files=8)
    return url


def _drain(pool):
    out = []
    while True:
        try:
            out.append(pool.get_results(timeout=_RESULT_TIMEOUT_S))
        except EmptyResultError:
            return out


def _reader_diag_keys(url, pool):
    from petastorm_tpu.reader import make_batch_reader
    with make_batch_reader(url, reader_pool_type=pool, workers_count=1,
                           num_epochs=1, shuffle_row_groups=False) as reader:
        for _ in reader:
            pass
        diag = dict(reader.diagnostics)
    return diag


# -- gauge-name parity (satellite: hygiene test) -----------------------------


def test_pool_gauge_name_parity_local(small_scalar_dataset):
    """thread/dummy/process expose the IDENTICAL shared gauge set through
    Reader.diagnostics, so dashboard/autotune key names can never drift.
    (The service flavor is asserted in its own ``service``-marked test —
    it spawns a worker-server fleet.)"""
    for pool in ('thread', 'dummy', 'process'):
        diag = _reader_diag_keys(small_scalar_dataset, pool)
        missing = SHARED_POOL_GAUGES - set(diag)
        assert not missing, '%s pool lacks shared gauges %s' % (pool,
                                                                missing)


@pytest.mark.service
def test_pool_gauge_name_parity_service(small_scalar_dataset):
    diag = _reader_diag_keys(small_scalar_dataset, 'service')
    missing = SHARED_POOL_GAUGES - set(diag)
    assert not missing, 'service pool lacks shared gauges %s' % missing


# -- worker-side spans reach the consumer registry ---------------------------


def test_thread_pool_worker_spans_record_inline():
    pool = ThreadPool(2, results_queue_size=10)
    pool.start(SpanningSleepyWorker)
    try:
        for i in range(6):
            pool.ventilate(i, sleep_s=0.01)
        assert sorted(_drain(pool)) == list(range(6))
        decode_s = T.get_registry().counter_value(STAGE_SECONDS,
                                                  stage='decode')
        assert decode_s >= 0.05  # 6 sleeps of ≥10ms, same-process registry
    finally:
        pool.stop()
        pool.join()


def test_process_pool_deltas_ride_markers():
    """The ZMQ process pool's workers run in OTHER processes; their spans
    must reach this process's registry via the delta piggybacked on each
    completion marker."""
    from petastorm_tpu.workers.process_pool import ProcessPool
    pool = ProcessPool(1, results_queue_size=10)
    pool.start(SpanningSleepyWorker)
    try:
        for i in range(5):
            pool.ventilate(i, sleep_s=0.02)
        assert sorted(_drain(pool)) == list(range(5))
        decode_s = T.get_registry().counter_value(STAGE_SECONDS,
                                                  stage='decode')
        assert decode_s >= 0.08, \
            'worker-process spans did not merge (got %r)' % decode_s
    finally:
        pool.stop()
        pool.join()


# -- stall attribution: deliberately slowed sides ----------------------------


def test_slow_consumer_flags_consumer_bound():
    """A consumer sleeping between reads forces producers to block on the
    tiny results queue → producer wait dominates → consumer-bound."""
    pool = ThreadPool(2, results_queue_size=1)
    pool.start(IdentityWorker)
    try:
        for i in range(20):
            pool.ventilate(i)
        seen = 0
        while seen < 20:
            pool.get_results(timeout=_RESULT_TIMEOUT_S)
            seen += 1
            time.sleep(0.03)  # deliberately slow consumer
        producer, consumer = T.get_attributor().totals()
        assert producer > 0.1, producer
        assert T.get_attributor().verdict() == T.CONSUMER_BOUND
    finally:
        pool.stop()
        pool.join()


def test_slow_workers_flag_producer_bound(small_scalar_dataset):
    """A deliberately slowed worker pool starves the consumer: the
    reader's queue_wait clock dominates → producer-bound (input-bound)."""
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.transform import TransformSpec
    with make_batch_reader(small_scalar_dataset,
                           transform_spec=TransformSpec(_slow_identity),
                           workers_count=1, num_epochs=1,
                           shuffle_row_groups=False) as reader:
        for _ in reader:
            pass  # consume as fast as possible
    producer, consumer = T.get_attributor().totals()
    assert consumer > 0.1, consumer
    assert T.get_attributor().verdict() == T.PRODUCER_BOUND
    # the slow stage itself is attributed where it runs: transform
    assert T.get_registry().counter_value(STAGE_SECONDS,
                                          stage='transform') >= 0.2


def _slow_identity(frame):
    time.sleep(0.05)
    return frame


# -- the service pool: deltas must aggregate at the dispatcher ---------------


@pytest.mark.service
def test_service_worker_deltas_aggregate_at_dispatcher():
    """Worker servers run in other processes over tcp://; their stage
    spans piggyback on DONE messages and the dispatcher merges them into
    this process's registry — asserted via the 'decode' seconds their
    SpanningSleepyWorker accrues remotely."""
    from petastorm_tpu.service import ServicePool
    pool = ServicePool(spawn_local_workers=1, heartbeat_interval_s=0.2,
                       connect_timeout_s=60)
    pool.start(SpanningSleepyWorker)
    try:
        for i in range(5):
            pool.ventilate(i, sleep_s=0.02)
        assert sorted(_drain(pool)) == list(range(5))
        decode_s = T.get_registry().counter_value(STAGE_SECONDS,
                                                  stage='decode')
        assert decode_s >= 0.08, \
            'worker-server spans did not aggregate (got %r)' % decode_s
        assert pool.diagnostics['metrics_deltas_merged'] >= 5
    finally:
        pool.stop()
        pool.join()


@pytest.mark.service
def test_service_slow_workers_flag_producer_bound(small_scalar_dataset):
    """Producer-bound detection must hold THROUGH the service pool: remote
    workers slowed by a TransformSpec starve the consumer, and the
    worker-side transform seconds must arrive via dispatcher-merged
    deltas."""
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.service import ServicePool
    from petastorm_tpu.transform import TransformSpec
    pool = ServicePool(spawn_local_workers=1, heartbeat_interval_s=0.2,
                       connect_timeout_s=60)
    with make_batch_reader(small_scalar_dataset, reader_pool_type=pool,
                           transform_spec=TransformSpec(_slow_identity),
                           num_epochs=1, shuffle_row_groups=False) as reader:
        for _ in reader:
            pass
    producer, consumer = T.get_attributor().totals()
    assert consumer > 0.1, consumer
    assert T.get_attributor().verdict() == T.PRODUCER_BOUND
    # fleet-wide aggregation: transform ran on the worker SERVER process
    assert T.get_registry().counter_value(STAGE_SECONDS,
                                          stage='transform') >= 0.2


@pytest.mark.service
def test_service_slow_consumer_flags_consumer_bound(small_scalar_dataset):
    """Consumer-bound detection through the service pool: a slow consumer
    fills the bounded results queue, the dispatcher backlogs completions,
    and its backlog clock (producer wait) must dominate."""
    from petastorm_tpu.reader import make_batch_reader
    from petastorm_tpu.service import ServicePool
    # queue of 2 fits one result+marker pair: the consumer never starves
    # on a marker while completions are backlogged behind it
    pool = ServicePool(spawn_local_workers=2, results_queue_size=2,
                       heartbeat_interval_s=0.2, connect_timeout_s=60)
    with make_batch_reader(small_scalar_dataset, reader_pool_type=pool,
                           num_epochs=2, shuffle_row_groups=False) as reader:
        first = True
        for _ in reader:
            if first:
                # fleet spin-up (registration, worker start) is consumer
                # wait but not contention; scope the verdict to steady
                # state exactly like JaxLoader's first-delivery reset
                T.reset_attributor()
                first = False
            time.sleep(0.05)  # deliberately slow consumer
    producer, consumer = T.get_attributor().totals()
    assert producer > 0.1, producer
    assert T.get_attributor().verdict() == T.CONSUMER_BOUND
