"""Disaggregated decode service tests: dispatcher, worker servers,
ServicePool, and the Reader('service') acceptance path.

Every test spawns real worker-server subprocesses over ``tcp://`` loopback.
There is no pytest-timeout in this environment, so hangs are bounded
internally: every ``get_results`` call carries a timeout, registration
waits carry ``connect_timeout_s``, and fleets are reaped in ``finally``.
"""

import collections
import contextlib
import os
import signal
import subprocess
import sys
import time

import pytest

from petastorm_tpu.service import ServicePool
from petastorm_tpu.service.protocol import free_tcp_port
from petastorm_tpu.workers import EmptyResultError
from tests.stub_workers import ExceptionOnFiveWorker, SleepyIdentityWorker

pytestmark = pytest.mark.service

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Tight-but-safe timing for kill/re-ventilation tests: lapse detection in
# well under a second, generous outer deadlines so slow CI never flakes.
_FAST = dict(heartbeat_interval_s=0.15, liveness_timeout_s=0.75,
             connect_timeout_s=60, no_workers_timeout_s=20)


def _drain(pool, per_result_timeout_s=60):
    out = []
    while True:
        try:
            out.append(pool.get_results(timeout=per_result_timeout_s))
        except EmptyResultError:
            return out


@contextlib.contextmanager
def _external_worker_servers(endpoint, count, heartbeat_interval_s=0.2):
    """Spawn a fleet the way an operator would: the __main__ CLI."""
    # tests/ must be importable too: dill ships this module's transform
    # functions by reference, and pytest imports test files as TOP-LEVEL
    # modules (test_service, not tests.test_service)
    env = dict(os.environ,
               PYTHONPATH=os.pathsep.join(
                   [_REPO_ROOT, os.path.join(_REPO_ROOT, 'tests')]),
               JAX_PLATFORMS='cpu')
    procs = [
        subprocess.Popen(
            [sys.executable, '-m', 'petastorm_tpu.service.worker_server',
             '--endpoint', endpoint,
             '--heartbeat-interval', str(heartbeat_interval_s),
             '--worker-id', str(i),
             '--parent-pid', str(os.getpid())],
            env=env)
        for i in range(count)
    ]
    try:
        yield procs
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def test_sigkill_worker_mid_read_reventilates_exactly_once():
    """The robustness core: hard-kill one worker server while it owns
    in-flight items; the dispatcher's heartbeat sweep must re-ventilate
    them and the full item set must arrive exactly once (a multiset
    mismatch would expose either loss or duplication)."""
    pool = ServicePool(spawn_local_workers=2, **_FAST)
    pool.start(SleepyIdentityWorker)
    try:
        for i in range(40):
            pool.ventilate(i, sleep_s=0.05)
        results = [pool.get_results(timeout=60) for _ in range(5)]
        os.kill(pool._local_procs[0].pid, signal.SIGKILL)
        results.extend(_drain(pool))
        assert sorted(results) == list(range(40))
        diag = pool.diagnostics
        assert diag['items_reventilated'] >= 1
        assert diag['workers_alive'] == 1
        assert diag['items_inflight'] == 0
    finally:
        pool.stop()
        pool.join()


def test_stalled_consumer_quiesces_fleet_without_killing_it():
    """A consumer pause longer than the workers' ack timeout, with the
    results queue full, must NOT lose the fleet: the dispatcher thread
    keeps acking heartbeats while delivery backlogs (regression for the
    blocking-_deliver starvation bug)."""
    pool = ServicePool(spawn_local_workers=2, results_queue_size=4,
                       worker_ack_timeout_s=1.5, **_FAST)
    pool.start(SleepyIdentityWorker)
    try:
        for i in range(30):
            pool.ventilate(i, sleep_s=0.01)
        results = [pool.get_results(timeout=60) for _ in range(2)]
        # stall well past worker_ack_timeout_s with the queue saturated
        time.sleep(4.0)
        assert pool.diagnostics['workers_alive'] == 2
        results.extend(_drain(pool))
        assert sorted(results) == list(range(30))
    finally:
        pool.stop()
        pool.join()


def test_worker_error_propagates_and_pool_cleans_up():
    pool = ServicePool(spawn_local_workers=2, **_FAST)
    pool.start(ExceptionOnFiveWorker)
    try:
        for i in range(10):
            pool.ventilate(i)
        with pytest.raises(ValueError, match='value was 5'):
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                pool.get_results(timeout=60)
        # the error path stops and joins internally: the fleet is reaped
        assert all(p.poll() is not None for p in pool._local_procs) or \
            not pool._local_procs
    finally:
        pool.stop()
        pool.join()


def test_registration_timeout_fails_fast_with_clear_error():
    pool = ServicePool(expected_workers=1, connect_timeout_s=1.5)
    with pytest.raises(RuntimeError, match='registered with the dispatcher'):
        pool.start(SleepyIdentityWorker)


def test_dispatcher_endpoint_resolves_random_port():
    pool = ServicePool(spawn_local_workers=1, **_FAST)
    pool.start(SleepyIdentityWorker)
    try:
        assert pool.dispatcher_endpoint.startswith('tcp://127.0.0.1:')
        assert not pool.dispatcher_endpoint.endswith(':0')
    finally:
        pool.stop()
        pool.join()


def _slow_batch_identity(df):
    # Per-row-group brake so a killed worker server reliably owns
    # in-flight row-groups when the SIGKILL lands.
    time.sleep(0.05)
    return df


@pytest.fixture
def many_rowgroup_scalar_dataset(tmp_path):
    """10 single-row-group files: enough ventilated items that a mid-epoch
    worker kill always leaves undelivered work to re-ventilate."""
    from tests.test_common import create_test_scalar_dataset
    url = 'file://' + str(tmp_path / 'dataset')
    create_test_scalar_dataset(url, num_rows=100, num_files=10)
    return url


def _read_id_multiset(url, reader_pool_type, kill_proc_after_first=None,
                      transform_spec=None):
    """All 'id' values (as a multiset) read through make_batch_reader;
    optionally SIGKILL a worker-server process after the first batch."""
    from petastorm_tpu.reader import make_batch_reader
    ids = collections.Counter()
    with make_batch_reader(url, reader_pool_type=reader_pool_type,
                           num_epochs=1, shuffle_row_groups=False,
                           transform_spec=transform_spec) as reader:
        first = True
        for batch in reader:
            ids.update(int(x) for x in batch.id)
            if first and kill_proc_after_first is not None:
                os.kill(kill_proc_after_first.pid, signal.SIGKILL)
                first = False
    return ids


def test_reader_service_pool_is_drop_in_for_thread_pool(
        many_rowgroup_scalar_dataset, monkeypatch):
    """Acceptance: ``Reader(url, reader_pool_type='service')`` against 2
    localhost worker servers returns the identical multiset of rows as
    ``'thread'`` — including a second job on the SAME long-lived fleet
    (worker servers re-register after a job ends, tf.data-service style)."""
    url = many_rowgroup_scalar_dataset
    expected = _read_id_multiset(url, 'thread')
    assert sum(expected.values()) == 100

    endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
    with _external_worker_servers(endpoint, 2):
        monkeypatch.setenv('PETASTORM_TPU_SERVICE_DISPATCHER', endpoint)
        monkeypatch.setenv('PETASTORM_TPU_SERVICE_WORKERS', '2')
        assert _read_id_multiset(url, 'service') == expected
        # the fleet outlives the first reader: second job, same servers
        assert _read_id_multiset(url, 'service') == expected


def test_reader_survives_worker_server_sigkill_mid_epoch(
        many_rowgroup_scalar_dataset):
    """Acceptance: kill one of two worker servers mid-epoch; re-ventilation
    must deliver every row exactly once (multiset equality vs the thread
    pool proves no loss AND no duplication)."""
    from petastorm_tpu.transform import TransformSpec
    url = many_rowgroup_scalar_dataset
    spec = TransformSpec(_slow_batch_identity)
    expected = _read_id_multiset(url, 'thread', transform_spec=spec)

    endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
    with _external_worker_servers(endpoint, 2) as procs:
        # pool instance (not env) so the kill test runs with tight
        # heartbeat/liveness instead of the production defaults
        pool = ServicePool(endpoint=endpoint, expected_workers=2, **_FAST)
        got = _read_id_multiset(url, pool, kill_proc_after_first=procs[0],
                                transform_spec=spec)
        assert got == expected


def test_service_pool_diagnostics_gauges(many_rowgroup_scalar_dataset):
    """Liveness/ownership gauges surface through Reader.diagnostics with
    the same names the local pools expose (plus service-only extras)."""
    from petastorm_tpu.reader import make_batch_reader
    pool = ServicePool(spawn_local_workers=2, **_FAST)
    with make_batch_reader(many_rowgroup_scalar_dataset,
                           reader_pool_type=pool, num_epochs=1,
                           shuffle_row_groups=False) as reader:
        next(iter(reader))
        diag = reader.diagnostics
        assert diag['workers_alive'] == 2
        assert diag['workers_registered'] == 2
        for gauge in ('items_inflight', 'items_pending', 'items_assigned',
                      'items_reventilated', 'items_ventilated',
                      'items_processed'):
            assert gauge in diag, gauge
