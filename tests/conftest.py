"""Shared test configuration.

Forces JAX onto a virtual 8-device CPU platform *before* jax is imported
anywhere, so sharding/mesh tests exercise real multi-device code paths without
TPU hardware (SURVEY.md §4: multi-node stand-in strategy).
"""

import os

# Force CPU even when the environment preselects a TPU platform: the test
# suite must exercise the virtual 8-device mesh, never the real chip.
os.environ['JAX_PLATFORMS'] = 'cpu'
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (_flags + ' --xla_force_host_platform_device_count=8').strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment's TPU plugin can pre-register itself at interpreter start
# (sitecustomize) and win over the env var; the config update is decisive.
import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Process-pool-parametrized variants each pay a multi-second ZMQ worker
# spawn (spawn-not-fork, full interpreter + pyarrow import per worker), so
# the full dummy/thread/process matrix dominates the suite's wall time. The
# quick profile (-m "not slow") keeps ONE representative process variant per
# behavior family; process-pool-SPECIFIC tests (worker error propagation,
# checkpoint-across-process) are unparametrized, never match '[process',
# and so always stay in the quick profile. The rest of the matrix runs in
# the full suite, mirroring the reference's all-flavors parametrization
# (petastorm/tests/test_end_to_end.py:42-58).
_FAST_PROCESS_KEEP = frozenset([
    'tests/test_end_to_end.py::test_simple_read_all_fields[process]',
    'tests/test_workers_pool.py::test_identity_roundtrip[process-2]',
    'tests/test_ngram.py::TestNGramEndToEnd::test_basic[process]',
])

# pool param id component, wherever it lands in a (possibly stacked)
# parametrize id: '[process]', '[process-2]', '[2-process]'
_PROCESS_ID_RE = __import__('re').compile(r'\[(?:[^\]]*-)?process\b')


def pytest_collection_modifyitems(config, items):
    kept = set()
    for item in items:
        if item.nodeid in _FAST_PROCESS_KEEP:
            kept.add(item.nodeid)
            continue
        if (_PROCESS_ID_RE.search(item.name)
                and not any(m.name == 'slow' for m in item.iter_markers())):
            item.add_marker(pytest.mark.slow)
    # A rename/reparametrize must not silently drop process coverage from
    # the quick profile: a keep entry is STALE when a *process* variant of
    # its test function was collected but none matched the pinned nodeid.
    # Runs that collect no process sibling (single-id selections, partial
    # files) prove nothing either way and stay silent.
    process_funcs = {i.nodeid.split('[', 1)[0] for i in items
                     if _PROCESS_ID_RE.search(i.name)}
    stale = [n for n in _FAST_PROCESS_KEEP - kept
             if n.split('[', 1)[0] in process_funcs]
    assert not stale, 'stale _FAST_PROCESS_KEEP entries: %s' % sorted(stale)


@pytest.fixture(scope='session')
def synthetic_dataset(tmp_path_factory):
    """Session-scoped canonical petastorm_tpu dataset (rich 14-field schema).

    Mirrors the reference's ``synthetic_dataset`` fixture strategy
    (``petastorm/tests/conftest.py:89-98``) without Spark: rows generated with
    :func:`tests.test_common.create_test_dataset`.
    """
    from tests.test_common import create_test_dataset
    path = str(tmp_path_factory.mktemp('synthetic')) + '/dataset'
    url = 'file://' + path
    data = create_test_dataset(url, range(100), num_files=4, rowgroup_size=10)

    # Index it like the reference's fixture does (its test_common.py builds
    # SingleField + FieldNotNull indexes right after materialization).
    from petastorm_tpu.etl.rowgroup_indexers import (
        FieldNotNullIndexer, SingleFieldIndexer,
    )
    from petastorm_tpu.etl.rowgroup_indexing import build_rowgroup_index
    build_rowgroup_index(url, [
        SingleFieldIndexer('id_index', 'id'),
        SingleFieldIndexer('partition_index', 'partition_key'),
        FieldNotNullIndexer('string_arr_not_null', 'string_array_nullable'),
    ])

    class _Dataset:
        pass

    d = _Dataset()
    d.url = url
    d.path = path
    d.data = data
    return d


@pytest.fixture(scope='session')
def scalar_dataset(tmp_path_factory):
    """Plain (non-petastorm) parquet store for make_batch_reader paths."""
    from tests.test_common import create_test_scalar_dataset
    path = str(tmp_path_factory.mktemp('scalar')) + '/dataset'
    url = 'file://' + path
    data = create_test_scalar_dataset(url, num_rows=100, num_files=4)

    class _Dataset:
        pass

    d = _Dataset()
    d.url = url
    d.path = path
    d.data = data
    return d
