"""Shared test configuration.

Forces JAX onto a virtual 8-device CPU platform *before* jax is imported
anywhere, so sharding/mesh tests exercise real multi-device code paths without
TPU hardware (SURVEY.md §4: multi-node stand-in strategy).
"""

import os

# Force CPU even when the environment preselects a TPU platform: the test
# suite must exercise the virtual 8-device mesh, never the real chip.
os.environ['JAX_PLATFORMS'] = 'cpu'
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (_flags + ' --xla_force_host_platform_device_count=8').strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment's TPU plugin can pre-register itself at interpreter start
# (sitecustomize) and win over the env var; the config update is decisive.
import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope='session')
def synthetic_dataset(tmp_path_factory):
    """Session-scoped canonical petastorm_tpu dataset (rich 14-field schema).

    Mirrors the reference's ``synthetic_dataset`` fixture strategy
    (``petastorm/tests/conftest.py:89-98``) without Spark: rows generated with
    :func:`tests.test_common.create_test_dataset`.
    """
    from tests.test_common import create_test_dataset
    path = str(tmp_path_factory.mktemp('synthetic')) + '/dataset'
    url = 'file://' + path
    data = create_test_dataset(url, range(100), num_files=4, rowgroup_size=10)

    # Index it like the reference's fixture does (its test_common.py builds
    # SingleField + FieldNotNull indexes right after materialization).
    from petastorm_tpu.etl.rowgroup_indexers import (
        FieldNotNullIndexer, SingleFieldIndexer,
    )
    from petastorm_tpu.etl.rowgroup_indexing import build_rowgroup_index
    build_rowgroup_index(url, [
        SingleFieldIndexer('id_index', 'id'),
        SingleFieldIndexer('partition_index', 'partition_key'),
        FieldNotNullIndexer('string_arr_not_null', 'string_array_nullable'),
    ])

    class _Dataset:
        pass

    d = _Dataset()
    d.url = url
    d.path = path
    d.data = data
    return d


@pytest.fixture(scope='session')
def scalar_dataset(tmp_path_factory):
    """Plain (non-petastorm) parquet store for make_batch_reader paths."""
    from tests.test_common import create_test_scalar_dataset
    path = str(tmp_path_factory.mktemp('scalar')) + '/dataset'
    url = 'file://' + path
    data = create_test_scalar_dataset(url, num_rows=100, num_files=4)

    class _Dataset:
        pass

    d = _Dataset()
    d.url = url
    d.path = path
    d.data = data
    return d
