"""Fused batch-native decode (ISSUE 9 tentpole, petastorm_tpu/fused.py).

Covers the whole chain: the codecs' ``decode_batch(..., out=)``
destination API (incl. the nulls path's zero-fill + red-zone
no-overrun fixture), the worker's deferral gates, the
``EncodedImageColumn`` carrier, the staging arena's fused fill
(exact-value parity against the pure-Python decode oracle), every
fallback mode the troubleshoot runbook names, the sanitizer interplay
(canaries intact across fused refills), and the ``perf``-marked
zero-per-image-intermediates tracemalloc guard."""

import contextlib
import os
import pickle
import tracemalloc

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu import sanitizer
from petastorm_tpu import telemetry as T
from petastorm_tpu.codecs import (
    CompressedImageCodec, NdarrayCodec, decode_batch_with_nulls,
)
from petastorm_tpu.fused import (
    EncodedImageColumn, SLAB_ALIGN, alloc_column_slab,
)
from petastorm_tpu.jax import make_jax_loader
from petastorm_tpu.jax import staging
from petastorm_tpu.reader import make_batch_reader
from petastorm_tpu.unischema import Unischema, UnischemaField


IMG_SHAPE = (32, 32, 3)


def _png_codec_field(name='image'):
    codec = CompressedImageCodec('png')
    return codec, UnischemaField(name, np.uint8, IMG_SHAPE, codec, False)


def _png_cells(n, seed=0):
    import cv2
    rng = np.random.RandomState(seed)
    cells, images = [], []
    for _ in range(n):
        img = rng.randint(0, 255, IMG_SHAPE, dtype=np.uint8)
        ok, enc = cv2.imencode('.png', cv2.cvtColor(img, cv2.COLOR_RGB2BGR))
        assert ok
        cells.append(enc.tobytes())
        images.append(img)
    return cells, images


@contextlib.contextmanager
def _env(**env):
    saved = {k: os.environ.get(k) for k in env}
    for k, v in env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    T.refresh()
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        T.refresh()


@pytest.fixture(scope='module')
def image_dataset(tmp_path_factory):
    """96 png rows (lossless + decode-path-independent, so every decode
    route must produce bit-identical pixels), 16-row row-groups."""
    from petastorm_tpu.etl.dataset_metadata import write_dataset
    url = 'file://' + str(tmp_path_factory.mktemp('fused')) + '/ds'
    _, field = _png_codec_field()
    schema = Unischema('FusedImages', [
        UnischemaField('id', np.int32, (), None, False),
        field,
    ])
    rng = np.random.RandomState(5)
    rows = [{'id': np.int32(i),
             'image': rng.randint(0, 255, IMG_SHAPE, dtype=np.uint8)}
            for i in range(96)]
    write_dataset(url, schema, rows, rowgroup_size_rows=16, num_files=2)
    return url, rows


# -- alloc_column_slab --------------------------------------------------------


def test_column_slab_is_page_aligned_and_owned():
    slab = alloc_column_slab((7, 32, 32, 3), np.uint8)
    assert slab.shape == (7, 32, 32, 3) and slab.dtype == np.uint8
    assert slab.ctypes.data % SLAB_ALIGN == 0
    assert slab.flags.writeable
    # the backing allocation rides the base chain: the slab owns its
    # memory like any fresh ndarray (no borrowed lifetime)
    root = slab
    while root.base is not None:
        root = root.base
    assert isinstance(root, np.ndarray)
    slab[...] = 1  # writable end to end


# -- decode_batch(out=) -------------------------------------------------------


def test_image_decode_batch_out_matches_no_out():
    codec, field = _png_codec_field()
    cells, images = _png_cells(8, seed=1)
    out = alloc_column_slab((8,) + IMG_SHAPE, np.uint8)
    returned = codec.decode_batch(field, cells, out=out)
    assert returned is out
    np.testing.assert_array_equal(out, np.stack(images))
    np.testing.assert_array_equal(out, codec.decode_batch(field, cells))


def test_image_decode_batch_out_validates_destination():
    codec, field = _png_codec_field()
    cells, _ = _png_cells(4, seed=2)
    with pytest.raises(ValueError, match='does not match'):
        codec.decode_batch(field, cells,
                           out=np.empty((4, 16, 16, 3), np.uint8))
    with pytest.raises(ValueError, match='does not match'):
        codec.decode_batch(field, cells,
                           out=np.empty((4,) + IMG_SHAPE, np.float32))
    wild = UnischemaField('w', np.uint8, (None, None, 3), codec, False)
    with pytest.raises(ValueError, match='fixed-shape'):
        codec.decode_batch(wild, cells,
                           out=np.empty((4,) + IMG_SHAPE, np.uint8))


def test_ndarray_decode_batch_out_matches_no_out():
    codec = NdarrayCodec()
    field = UnischemaField('m', np.float32, (5, 7), codec, False)
    rng = np.random.RandomState(3)
    arrs = [rng.rand(5, 7).astype(np.float32) for _ in range(10)]
    cells = [codec.encode(field, a) for a in arrs]
    out = alloc_column_slab((10, 5, 7), np.float32)
    assert codec.decode_batch(field, cells, out=out) is out
    np.testing.assert_array_equal(out, np.stack(arrs))


def test_out_tail_rejects_broadcastable_shape_mismatch():
    """Review regression: the rejected-tail per-cell assignment must not
    numpy-BROADCAST a smaller cell across its destination row — a (3,)
    cell landing in a (2, 3) row would silently replicate data where the
    no-out path preserved the true shape."""
    from io import BytesIO
    codec = NdarrayCodec()
    field = UnischemaField('m', np.float32, (2, 3), codec, False)
    good = np.arange(6, dtype=np.float32).reshape(2, 3)
    buf = BytesIO()
    np.save(buf, np.arange(3, dtype=np.float32), allow_pickle=False)
    cells = [codec.encode(field, good), buf.getvalue()]
    out = np.empty((2, 2, 3), np.float32)
    with pytest.raises(ValueError, match='decoded to shape'):
        codec.decode_batch(field, cells, out=out)
    # the no-out path still degrades gracefully (true shape preserved)
    rows = codec.decode_batch(field, cells)
    assert rows[1].shape == (3,)


def test_nulls_out_path_zero_fills_inside_red_zones():
    """ISSUE 9 satellite: null positions in the destination slab must be
    ZERO-FILLED (not uninitialized / previous-slot bytes), and a ragged
    tail (out covering fewer rows than the slab) must not overrun — the
    pipesan red-zone fixture proves it byte-exactly."""
    codec, field = _png_codec_field()
    cells, images = _png_cells(4, seed=4)
    ragged = [cells[0], None, cells[1], None, None, cells[2]]
    # guarded slab: poisoned canaries on both sides, garbage in the middle
    slab = sanitizer.allocate_guarded((8,) + IMG_SHAPE, np.uint8)
    slab[...] = 0x77  # stale "previous slot" bytes a lazy path would leak
    out = slab[:6]    # the ragged tail: two slab rows stay out of bounds
    returned = decode_batch_with_nulls(field, ragged, out=out)
    assert returned is out
    np.testing.assert_array_equal(out[0], images[0])
    np.testing.assert_array_equal(out[2], images[1])
    np.testing.assert_array_equal(out[5], images[2])
    for null_row in (1, 3, 4):
        assert not out[null_row].any(), 'null row %d not zeroed' % null_row
    # rows past the destination window were never touched...
    assert (slab[6:] == 0x77).all()
    # ...and neither red zone was (no overrun on the ragged tail)
    assert sanitizer.check_canaries(slab)


def test_all_null_out_path_zero_fills():
    _, field = _png_codec_field()
    out = np.full((3,) + IMG_SHAPE, 0xAB, np.uint8)
    decode_batch_with_nulls(field, [None, None, None], out=out)
    assert not out.any()


# -- EncodedImageColumn -------------------------------------------------------


def test_encoded_column_surface_and_slicing():
    _, field = _png_codec_field()
    cells, images = _png_cells(6, seed=6)
    column = EncodedImageColumn(field, cells)
    assert len(column) == 6
    assert column.shape == (6,) + IMG_SHAPE
    assert column.dtype == np.uint8
    assert column.nbytes == 6 * int(np.prod(IMG_SHAPE))
    head = column[:2]
    assert isinstance(head, EncodedImageColumn) and len(head) == 2
    np.testing.assert_array_equal(head.materialize(), np.stack(images[:2]))
    with pytest.raises(TypeError, match='encoded'):
        column[0]
    np.testing.assert_array_equal(column.materialize(), np.stack(images))


def test_encoded_column_pickles_to_owned_cells():
    _, field = _png_codec_field()
    cells, images = _png_cells(3, seed=7)
    views = [np.frombuffer(c, np.uint8) for c in cells]
    column = EncodedImageColumn(field, views, owner=object())
    clone = pickle.loads(pickle.dumps(column))
    assert clone.owner is None
    np.testing.assert_array_equal(clone.materialize(), np.stack(images))


# -- worker deferral gates ----------------------------------------------------


def test_reader_defers_when_asked(image_dataset):
    url, rows = image_dataset
    with make_batch_reader(url, shuffle_row_groups=False,
                           defer_image_decode=True) as reader:
        columns, _, _ = reader.next_batch_info()
    assert isinstance(columns['image'], EncodedImageColumn)
    # scalar columns decode as always
    assert isinstance(columns['id'], np.ndarray)
    dense = columns['image'].materialize()
    assert dense.shape == (16,) + IMG_SHAPE


def test_reader_does_not_defer_by_default(image_dataset):
    url, _ = image_dataset
    with make_batch_reader(url, shuffle_row_groups=False) as reader:
        batch = next(reader)
    assert isinstance(batch.image, np.ndarray)
    assert batch.image.shape == (16,) + IMG_SHAPE


def test_transform_spec_declines_deferral(image_dataset):
    # a TransformSpec needs pixels at the worker: deferral must not
    # change what the transform sees
    from petastorm_tpu.transform import TransformSpec
    url, _ = image_dataset

    def brighten(frame):
        frame['image'] = [np.minimum(im.astype(np.int32) + 1, 255)
                          .astype(np.uint8) for im in frame['image']]
        return frame

    with make_batch_reader(url, shuffle_row_groups=False,
                           defer_image_decode=True,
                           transform_spec=TransformSpec(brighten)) as reader:
        batch = next(reader)
    assert isinstance(batch.image, np.ndarray)


# -- loader end-to-end: fused vs the pure-Python oracle -----------------------


def _collect(url, **kw):
    with make_jax_loader(url, shuffle_row_groups=False, **kw) as loader:
        batches = [{k: np.asarray(v).copy() for k, v in b.items()}
                   for b in loader]
        diag = loader.diagnostics
    return batches, diag


def _assert_same(batches_a, batches_b):
    assert len(batches_a) == len(batches_b)
    for a, b in zip(batches_a, batches_b):
        assert sorted(a) == sorted(b)
        for name in a:
            np.testing.assert_array_equal(a[name], b[name], err_msg=name)


def test_fused_loader_matches_pure_python_oracle(image_dataset):
    """The acceptance gate: decode fused into staging buffers must be
    value-identical to the legacy path with the native layer OFF — the
    pure-Python cv2 decode oracle (png: lossless + path-independent)."""
    url, rows = image_dataset
    fused_batches, diag = _collect(url, batch_size=24)
    assert diag['fused_decode_mode'] in ('fused-into-slot',
                                         'fused-into-slab')
    assert diag['fused_decode_rows'] == 96
    with _env(PETASTORM_TPU_STAGING='0', PETASTORM_TPU_NATIVE='0'):
        oracle_batches, oracle_diag = _collect(url, batch_size=24)
    assert oracle_diag['fused_decode_mode'] == 'batched'
    _assert_same(fused_batches, oracle_batches)
    # and against the source pixels themselves
    by_id = {}
    for b in fused_batches:
        for i in range(len(b['id'])):
            by_id[int(b['id'][i])] = b['image'][i]
    for row in rows:
        np.testing.assert_array_equal(by_id[int(row['id'])], row['image'])


def test_fused_pad_tail_zero_fills_and_masks(image_dataset):
    url, _ = image_dataset
    batches, diag = _collect(url, batch_size=36, last_batch='pad')
    assert diag['fused_decode_rows'] == 96
    tail = batches[-1]
    mask = tail['valid_mask']
    assert mask[:24].all() and not mask[24:].any()
    assert not tail['image'][24:].any()  # padded rows are zero, not stale
    with _env(PETASTORM_TPU_STAGING='0', PETASTORM_TPU_NATIVE='0'):
        oracle, _ = _collect(url, batch_size=36, last_batch='pad')
    _assert_same(batches, oracle)


def test_shuffled_rows_fall_back_and_match_decoded_path(image_dataset):
    url, _ = image_dataset
    kw = dict(batch_size=24, shuffle_rows=True, seed=3)
    batches, diag = _collect(url, **kw)
    assert diag['fused_decode_mode'] == 'batched'
    with _env(PETASTORM_TPU_STAGING='0', PETASTORM_TPU_NATIVE='0'):
        oracle, _ = _collect(url, **kw)
    # same seed, same buffer discipline: identical shuffled batches
    _assert_same(batches, oracle)


def test_dtype_cast_materializes_and_matches(image_dataset):
    url, _ = image_dataset
    kw = dict(batch_size=24, dtypes={'image': np.float32})
    batches, diag = _collect(url, **kw)
    assert batches[0]['image'].dtype == np.float32
    assert diag['fused_decode_mode'] == 'batched'
    assert diag.get('fused_decode_fallback') == 'dtype-cast'
    with _env(PETASTORM_TPU_STAGING='0', PETASTORM_TPU_NATIVE='0'):
        oracle, _ = _collect(url, **kw)
    _assert_same(batches, oracle)


def test_fused_records_decode_fused_stage(image_dataset):
    url, _ = image_dataset
    T.reset_for_tests()
    try:
        _, diag = _collect(url, batch_size=24)
        assert diag['fused_decode_rows'] > 0
        report = T.pipeline_report()
        assert 'decode_fused' in report['stages']
        from petastorm_tpu.fused import FUSED_BYTES, FUSED_ROWS
        registry = T.get_registry()
        assert registry.counter_value(FUSED_ROWS) == 96
        assert registry.counter_value(FUSED_BYTES) \
            == 96 * int(np.prod(IMG_SHAPE))
    finally:
        T.reset_for_tests()


# -- sanitizer interplay ------------------------------------------------------


class _AcceleratorLeaf:
    """Copies on construction + claims a non-host platform, pinning ring
    mode on the CPU test host (same stand-in as tests/test_staging.py)."""

    def __init__(self, arr):
        self.value = np.array(arr, copy=True)

    def devices(self):
        class _Dev:
            platform = 'tpu'
        return (_Dev(),)

    def block_until_ready(self):
        return self


def _accelerator_put(tree):
    return {name: _AcceleratorLeaf(arr) for name, arr in tree.items()}


def _encoded_parts(bs, n_parts=2, seed=8):
    _, field = _png_codec_field()
    per = bs // n_parts
    parts, images = [], []
    for p in range(n_parts):
        cells, imgs = _png_cells(per, seed=seed + p)
        parts.append({'image': EncodedImageColumn(field, cells)})
        images.extend(imgs)
    return parts, np.stack(images)


def test_fused_ring_mode_under_sanitizer_keeps_canaries_intact():
    """PETASTORM_TPU_SANITIZE=1 over the fused path: slot slabs recycle
    across fused refills with red zones verified each time — the native
    decoders never write past their destination rows."""
    with _env(PETASTORM_TPU_SANITIZE='1'):
        sanitizer.reset_for_tests()
        bs = 8
        eng = staging.StagingEngine(bs, {}, 'drop', _accelerator_put,
                                    num_slots=2)
        held = []
        expected = []
        for i in range(6):
            parts, images = _encoded_parts(bs, seed=20 + i)
            held.append(eng.stage(parts, bs))
            expected.append(images)
        assert eng._host_backed is False      # ring mode engaged
        assert eng.fused_mode == 'fused-into-slot'
        assert eng.fused_rows == 6 * bs
        for batch, images in zip(held, expected):
            np.testing.assert_array_equal(batch['image'].value, images)
        assert sanitizer.violations() == [], sanitizer.violations()
    sanitizer.reset_for_tests()


# -- perf marker: zero per-image intermediates --------------------------------


@pytest.mark.perf
def test_fused_fill_allocates_zero_per_image_intermediates():
    """ISSUE 9 acceptance: decode lands in staging slots with ZERO
    per-image intermediate allocations. After warmup, tracemalloc growth
    attributed to the decode/staging modules stays far below even ONE
    batch of pixels (a per-image Mat/ndarray regression would show ~N
    batches' worth). Same discipline as tests/test_staging.py."""
    from petastorm_tpu.native import get_png_module
    if get_png_module() is None:
        pytest.skip('native png extension unavailable (cv2 fallback '
                    'allocates per-image Mats by design)')
    bs = 16
    eng = staging.StagingEngine(bs, {}, 'drop', _accelerator_put,
                                num_slots=2)
    parts, images = _encoded_parts(bs, seed=40)
    batch_bytes = images.nbytes
    for _ in range(4):
        eng.stage(list(parts), bs)
    assert eng._host_backed is False and eng.fused_rows == 4 * bs
    watched = tuple(os.path.join('petastorm_tpu', tail) for tail in
                    ('fused.py', 'codecs.py', os.path.join('jax',
                                                           'staging.py')))
    n = 40
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    for _ in range(n):
        eng.stage(list(parts), bs)
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    grown = sum(
        max(0, s.size_diff)
        for s in after.compare_to(before, 'filename')
        if s.traceback and s.traceback[0].filename.endswith(watched))
    assert grown < batch_bytes / 2, \
        'fused decode allocated %d bytes over %d steady-state batches ' \
        '(batch is %d bytes)' % (grown, n, batch_bytes)
    np.testing.assert_array_equal(
        eng.stage(list(parts), bs)['image'].value, images)
