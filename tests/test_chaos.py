"""Chaos suite: deterministic fault injection driving the failure-domain
hardening end to end (docs/service.md, "Failure semantics").

Every scenario here was impossible to provoke before the faultpoint
harness existed: poisoned row-groups that exhaust their retry budget and
quarantine instead of crash-looping, a dispatcher replaced without a
goodbye whose fleet re-registers, a lost WORK frame surfacing as a
diagnosable wedge error, a full cache disk degrading to decode-through,
and a seeded chaos soak over a full loader epoch asserting exact
delivery. Timing mirrors tests/test_service.py (tight heartbeats,
generous outer deadlines)."""

import collections
import os
import subprocess
import sys
import time

import pytest

from petastorm_tpu import faults, telemetry
from petastorm_tpu.errors import RowGroupPoisonedError, ServiceWedgedError
from petastorm_tpu.service import ServicePool
from petastorm_tpu.service.protocol import free_tcp_port
from petastorm_tpu.workers import EmptyResultError
from tests.stub_workers import (
    ExceptionOnFiveWorker, ExitOnFiveWorker, SleepyIdentityWorker,
)

pytestmark = pytest.mark.service

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_FAST = dict(heartbeat_interval_s=0.15, liveness_timeout_s=0.75,
             connect_timeout_s=60, no_workers_timeout_s=20)


@pytest.fixture(autouse=True)
def _clean_telemetry_and_faults():
    # plain os.environ, NOT monkeypatch: _arm() writes the var directly
    # (so spawned worker fleets inherit it), and monkeypatch.delenv's
    # undo would RESTORE a var it saw at delete time — leaking an armed
    # spec into every later test module
    telemetry.reset_for_tests()
    yield
    os.environ.pop('PETASTORM_TPU_FAULTS', None)
    faults.refresh_faults()
    assert faults.ARMED is None
    telemetry.reset_for_tests()


def _drain(pool, per_result_timeout_s=60):
    out = []
    while True:
        try:
            out.append(pool.get_results(timeout=per_result_timeout_s))
        except EmptyResultError:
            return out


def _arm(spec):
    os.environ['PETASTORM_TPU_FAULTS'] = spec
    faults.refresh_faults()


# -- retry budget + quarantine ------------------------------------------------


def test_deterministic_error_quarantines_after_exact_budget():
    """A deterministically-erroring item is retried exactly
    ``max_retries`` times in total, then quarantined — visible in
    diagnostics, /health, the anomaly ring and pipeline_report — while
    every other item is delivered exactly once (skip policy)."""
    pool = ServicePool(spawn_local_workers=2, max_retries=2,
                       retry_backoff_s=0.02, poison_policy='skip',
                       **_FAST)
    pool.start(ExceptionOnFiveWorker)
    try:
        for i in range(10):
            pool.ventilate(i)
        results = _drain(pool)
        assert sorted(results) == [i for i in range(10) if i != 5]
        diag = pool.diagnostics
        assert diag['items_poisoned'] == 1
        # budget 2 = one backoff retry, then quarantine on the 2nd fail
        assert diag['items_retried'] == 1
        health = pool._dispatcher.health()
        assert health['items_poisoned'] == 1
        (descriptor,) = health['poisoned']
        assert descriptor['attempts'] == 2
        assert 'value was 5' in descriptor['error']
        assert pool.poisoned_items[0]['attempts'] == 2
        events = telemetry.recent_anomalies()
        poisoned = [e for e in events if e['kind'] == 'row_group_poisoned']
        assert len(poisoned) == 1
        assert poisoned[0]['detail']['attempts'] == 2
        assert 'row_group_poisoned' in \
            telemetry.pipeline_report()['anomalies']['by_kind']
    finally:
        pool.stop()
        pool.join()


def test_worker_killing_item_quarantines_instead_of_crash_looping():
    """THE acceptance scenario: a row-group that SIGKILLs every worker
    that touches it (no exception frame ever comes back). Each death
    re-ventilates and charges the budget; after exactly max_retries
    worker corpses the item quarantines, surviving workers finish the
    epoch, and the loss is reported — the fleet does not crash-loop."""
    pool = ServicePool(spawn_local_workers=4, max_retries=2,
                       retry_backoff_s=0.02, poison_policy='skip',
                       **_FAST)
    pool.start(ExitOnFiveWorker)
    try:
        for i in range(20):
            pool.ventilate(i)
        results = _drain(pool)
        assert sorted(results) == [i for i in range(20) if i != 5]
        diag = pool.diagnostics
        assert diag['items_poisoned'] == 1
        assert diag['items_reventilated'] >= 2  # one per burned worker
        (descriptor,) = pool._dispatcher.health()['poisoned']
        assert descriptor['attempts'] == 2
        assert 'lapsed' in descriptor['reason']
        assert descriptor['error'] is None  # died, never errored
        # exactly max_retries workers were burned, the rest survived
        assert sum(1 for p in pool._local_procs
                   if p.poll() is not None) == 2
    finally:
        pool.stop()
        pool.join()


def test_poison_policy_raise_surfaces_rowgroup_poisoned_error():
    pool = ServicePool(spawn_local_workers=3, max_retries=2,
                       retry_backoff_s=0.02, **_FAST)  # default: raise
    pool.start(ExitOnFiveWorker)
    try:
        for i in range(8):
            pool.ventilate(i)
        with pytest.raises(RowGroupPoisonedError) as info:
            _drain(pool)
        assert info.value.info['attempts'] == 2
        assert "poison_policy='skip'" in str(info.value)
    finally:
        pool.stop()
        pool.join()


def test_ghost_error_from_prior_owner_does_not_cancel_live_assignment():
    """A lapsed worker's late ERROR for an item already reassigned must
    be ignored: cancelling the live assignment would charge a phantom
    attempt and let the item run twice concurrently (review finding)."""
    import threading
    from petastorm_tpu.service.dispatcher import Dispatcher, _WorkerState
    d = Dispatcher('tcp://127.0.0.1:0', b'', lambda e: True,
                   threading.Event(), max_retries=3, retry_backoff_s=0.01)
    item = d.submit(b'payload')
    now = time.monotonic()
    live = _WorkerState(b'B', now)
    d._workers[b'B'] = live
    local_job = d._jobs[0]
    local_job.pending.clear()
    local_job.pending_ids.clear()
    d._inflight[item] = (b'B', b'payload')
    live.inflight.add(item)
    d._fail(b'A', item, ValueError('late ghost'), now)
    assert d._inflight[item][0] == b'B', 'live assignment was cancelled'
    assert item not in d._attempts, 'phantom attempt was charged'
    # the real owner's failure still charges and requeues
    d._fail(b'B', item, ValueError('real'), now)
    assert d._attempts[item] == 1
    assert item not in d._inflight


def test_poison_policy_rejected_for_pools_without_support():
    from petastorm_tpu.reader import _make_pool

    class ContractOnlyPool:
        start = ventilate = get_results = stop = join = lambda self: None
        workers_count = 1
        diagnostics = {}

    with pytest.raises(ValueError, match='poison_policy'):
        _make_pool(ContractOnlyPool(), None, 10, poison_policy='skip')
    with pytest.raises(ValueError, match='poison_policy'):
        _make_pool('thread', 1, 10, poison_policy='skip')


# -- consumer-read deadline (wedge -> diagnosable error) ---------------------


def test_lost_work_frame_raises_wedge_error_with_fleet_view():
    """Drop exactly one WORK frame on the dispatcher->worker wire: the
    item stays assigned to a live, heartbeating worker forever — the
    silent-wedge shape. The read deadline must convert it into
    ServiceWedgedError carrying the live fleet view."""
    _arm('zmq.work:drop:1:times=1')
    pool = ServicePool(spawn_local_workers=1, read_deadline_s=2.0,
                       **_FAST)
    pool.start(SleepyIdentityWorker)
    try:
        for i in range(4):
            pool.ventilate(i, sleep_s=0.01)
        with pytest.raises(ServiceWedgedError) as info:
            _drain(pool)
        assert info.value.fleet['workers'], 'fleet view missing'
        assert 'no progress' in str(info.value)
        assert info.value.fleet['items_assigned'] >= 1
    finally:
        pool.stop()
        pool.join()


# -- dispatcher restart: reconnect + re-registration --------------------------


def _spawn_cli_worker(endpoint, heartbeat_interval_s=0.2):
    env = dict(os.environ,
               PYTHONPATH=os.pathsep.join(
                   [_REPO_ROOT, os.path.join(_REPO_ROOT, 'tests')]),
               JAX_PLATFORMS='cpu')
    env.pop('PETASTORM_TPU_FAULTS', None)  # faults stay client-side here
    return subprocess.Popen(
        [sys.executable, '-m', 'petastorm_tpu.service.worker_server',
         '--endpoint', endpoint,
         '--heartbeat-interval', str(heartbeat_interval_s),
         '--parent-pid', str(os.getpid())],
        env=env)


def _start_pool_with_bind_retry(endpoint, deadline_s=15, **kwargs):
    """The previous dispatcher's ROUTER may linger on the port briefly;
    retry the bind window like a restarting client would."""
    deadline = time.monotonic() + deadline_s
    while True:
        pool = ServicePool(endpoint=endpoint, expected_workers=1, **_FAST)
        try:
            pool.start(SleepyIdentityWorker, **kwargs)
            return pool
        except RuntimeError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.3)


def test_worker_fleet_survives_dispatcher_crash_and_restart():
    """Kill a dispatcher WITHOUT its STOP goodbye (zmq.stop:drop — the
    crash drill), start a new pool on the same endpoint: the standing
    worker process must detect the incarnation change via the
    heartbeat-ack token, abandon the dead job, re-register with backoff
    and serve the new job — same pid, zero manual intervention."""
    endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
    proc = _spawn_cli_worker(endpoint)
    try:
        pool1 = _start_pool_with_bind_retry(endpoint)
        for i in range(6):
            pool1.ventilate(i, sleep_s=0.01)
        assert sorted(_drain(pool1)) == list(range(6))
        # crash the dispatcher: suppress every STOP broadcast, so the
        # worker never hears a goodbye and stays bound to the dead job
        _arm('zmq.stop:drop')
        pool1.stop()
        pool1.join()
        os.environ.pop('PETASTORM_TPU_FAULTS')
        faults.refresh_faults()

        pool2 = _start_pool_with_bind_retry(endpoint)
        try:
            for i in range(10, 16):
                pool2.ventilate(i, sleep_s=0.01)
            assert sorted(_drain(pool2)) == list(range(10, 16))
            assert proc.poll() is None, 'worker process died in restart'
        finally:
            pool2.stop()
            pool2.join()
    finally:
        if proc.poll() is None:
            proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()


# -- decoded-cache degrade-to-decode ------------------------------------------


@pytest.fixture
def scalar_dataset(tmp_path):
    from tests.test_common import create_test_scalar_dataset
    url = 'file://' + str(tmp_path / 'dataset')
    create_test_scalar_dataset(url, num_rows=50, num_files=5)
    return url


def _read_ids(url, **kwargs):
    from petastorm_tpu.reader import make_batch_reader
    ids = collections.Counter()
    with make_batch_reader(url, num_epochs=1, shuffle_row_groups=False,
                           **kwargs) as reader:
        for batch in reader:
            ids.update(int(x) for x in batch.id)
    return ids


def test_cache_disk_full_degrades_to_decode_through(scalar_dataset,
                                                    tmp_path):
    """Every decoded-cache store hits injected ENOSPC: the tier must
    disarm itself ONCE (cache_degraded anomaly + gauge), the epoch must
    deliver the exact row set of an uncached read, and the broken disk
    must not be touched per-row-group afterwards."""
    expected = _read_ids(scalar_dataset)
    _arm('cache.write:oserror:1:errno=28')
    got = _read_ids(scalar_dataset, cache_type='decoded',
                    cache_location=str(tmp_path / 'cache'),
                    cache_size_limit=64 * 2**20)
    assert got == expected
    events = [e for e in telemetry.recent_anomalies()
              if e['kind'] == 'cache_degraded']
    assert len(events) == 1, 'degrade must announce exactly once'
    assert 'ENOSPC' in events[0]['detail']['reason']
    failures = telemetry.get_registry().counters_with_prefix(
        'petastorm_tpu_decoded_cache_disk_failures_total')
    assert sum(failures.values()) == 1, \
        'a degraded tier must stop paying the failing syscall per item'
    report = telemetry.pipeline_report()
    assert report['anomalies']['by_kind'].get('cache_degraded') == 1
    # no entries ever published onto the "full" disk
    arrow_files = [f for _, _, files in os.walk(str(tmp_path / 'cache'))
                   for f in files if f.endswith('.arrow')]
    assert not arrow_files


def test_cache_read_eio_counts_and_serves_decode(scalar_dataset, tmp_path):
    """EIO on entry reads (bad medium under a warm cache): reads decode
    through, failures are counted with op=read, and the tier degrades
    (EIO is a disk-fault errno)."""
    cache_dir = str(tmp_path / 'cache')
    expected = _read_ids(scalar_dataset, cache_type='decoded',
                         cache_location=cache_dir,
                         cache_size_limit=64 * 2**20)  # warm fill
    _arm('cache.read:oserror:1:errno=5:times=1')
    got = _read_ids(scalar_dataset, cache_type='decoded',
                    cache_location=cache_dir,
                    cache_size_limit=64 * 2**20)
    assert got == expected
    failures = telemetry.get_registry().counters_with_prefix(
        'petastorm_tpu_decoded_cache_disk_failures_total')
    assert any('read' in key for key in failures)
    assert [e for e in telemetry.recent_anomalies()
            if e['kind'] == 'cache_degraded']


def test_read_eacces_is_entry_shaped_not_medium_shaped(scalar_dataset,
                                                       tmp_path):
    """One foreign-UID unreadable entry in a shared directory must NOT
    disarm the whole disk tier (review finding): a single read EACCES
    rides the consecutive-failure ramp, the rest of the warm cache
    keeps serving."""
    cache_dir = str(tmp_path / 'cache')
    expected = _read_ids(scalar_dataset, cache_type='decoded',
                         cache_location=cache_dir,
                         cache_size_limit=64 * 2**20)  # warm fill
    _arm('cache.read:oserror:1:errno=13:times=1')  # one EACCES read
    got = _read_ids(scalar_dataset, cache_type='decoded',
                    cache_location=cache_dir,
                    cache_size_limit=64 * 2**20)
    assert got == expected
    assert not [e for e in telemetry.recent_anomalies()
                if e['kind'] == 'cache_degraded'], \
        'one entry-shaped EACCES must not degrade the tier'
    failures = telemetry.get_registry().counters_with_prefix(
        'petastorm_tpu_decoded_cache_disk_failures_total')
    assert sum(failures.values()) == 1


def test_reroot_rearms_degraded_tier_and_clears_gauge(tmp_path):
    """reroot() must re-arm a degraded tier AND reset the degraded gauge
    — stale degraded=1 telemetry after recovery sends operators chasing
    a fault that no longer exists (review finding)."""
    from petastorm_tpu.arrow_worker import ColumnBatch
    from petastorm_tpu.materialized_cache import (
        DECODED_CACHE_DEGRADED, MaterializedRowGroupCache,
    )
    import numpy as np
    cache = MaterializedRowGroupCache(str(tmp_path / 'a'), 64 * 2**20)
    _arm('cache.write:oserror:1:errno=28')
    fill = lambda: ColumnBatch({'x': np.arange(3)}, 3)  # noqa: E731
    cache.get('k1', fill)
    assert cache.degraded
    gauge_key = '%s{pid=%d}' % (DECODED_CACHE_DEGRADED, os.getpid())
    gauges = telemetry.get_registry().gauges_with_prefix(
        DECODED_CACHE_DEGRADED)
    assert gauges and all(v == 1 for v in gauges.values()), gauge_key
    os.environ.pop('PETASTORM_TPU_FAULTS')
    faults.refresh_faults()
    cache.reroot(str(tmp_path / 'b'))
    assert not cache.degraded
    gauges = telemetry.get_registry().gauges_with_prefix(
        DECODED_CACHE_DEGRADED)
    assert all(v == 0 for v in gauges.values())
    cache.get('k1', fill)  # healthy medium: stores again
    assert not cache.degraded


# -- seeded chaos soak over a full loader epoch -------------------------------


@pytest.mark.slow
def test_chaos_soak_loader_epoch_delivers_exact_rows(scalar_dataset):
    """Transient multi-site faults over a full make_jax_loader epoch
    through the service pool: retries absorb every transient, and the
    delivered row set is EXACTLY the dataset — nothing lost, nothing
    duplicated, quarantines reported (none expected: the budget exceeds
    the worst-case fault stacking). ``times=1`` per clause fires each
    fault exactly once per WORKER process regardless of which worker
    drew which item, so injections are guaranteed without depending on
    scheduling — rate-based draws here would be flaky, since the
    per-worker hit sequences vary run to run."""
    import numpy as np
    from petastorm_tpu.jax import make_jax_loader

    # armed in THIS process and inherited by the spawned worker fleet's
    # environment — transient because each clause is one-shot per
    # process, so a retried item passes on a later attempt/worker
    _arm('io.read:error:1:times=1,decode.rowgroup:error:1:times=1')
    try:
        # budget 6 > the 4 one-shot faults even if ONE unlucky item ate
        # every single one of them across both workers
        pool = ServicePool(spawn_local_workers=2, retry_backoff_s=0.02,
                           max_retries=6, poison_policy='skip', **_FAST)
        loader = make_jax_loader(scalar_dataset, batch_size=10,
                                 fields=['id'], num_epochs=1,
                                 last_batch='short',
                                 reader_pool_type=pool,
                                 shuffle_row_groups=False)
        seen = collections.Counter()
        with loader:
            for batch in loader:
                seen.update(int(x) for x in np.asarray(batch['id']))
        quarantined = pool.poisoned_items
        assert not quarantined, \
            'transient-rate faults must never exhaust the budget: %s' \
            % quarantined
        assert sorted(seen.elements()) == list(range(50))
        # the faults fired in the WORKER processes; the evidence here is
        # the dispatcher's retry accounting plus the fleet-aggregated
        # injection counter riding the ERROR frames' metric deltas
        assert pool.diagnostics['items_retried'] >= 1
        injected = telemetry.get_registry().counters_with_prefix(
            faults.FAULTS_INJECTED)
        assert sum(injected.values()) >= 1
    finally:
        os.environ.pop('PETASTORM_TPU_FAULTS', None)
        faults.refresh_faults()
