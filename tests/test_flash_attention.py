"""Flash attention: Pallas kernel (interpret mode) vs the dense oracle."""

import numpy as np
import pytest

# only the interpret-mode KERNEL tests are compile-heavy; the dense-path
# and config tests stay in the quick profile
slow = pytest.mark.slow

import jax
import jax.numpy as jnp

from petastorm_tpu.ops.flash_attention import (
    flash_causal_attention, reference_causal_attention,
)


def _qkv(b=1, s=256, h=2, d=64, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, s, h, d), dtype)
                 for _ in range(3))


@slow
def test_kernel_matches_dense_oracle():
    q, k, v = _qkv()
    want = reference_causal_attention(q, k, v, 1.0 / np.sqrt(64))
    got = flash_causal_attention(q, k, v, force_kernel=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@slow
def test_kernel_gradients_match_dense_oracle():
    from jax.experimental.pallas import tpu as pltpu
    q, k, v = _qkv(s=256)

    def loss_flash(q, k, v):
        return jnp.sum(flash_causal_attention(q, k, v,
                                              force_kernel=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(reference_causal_attention(
            q, k, v, 1.0 / np.sqrt(64)) ** 2)

    # the context must cover the BACKWARD execution too: the VJP kernel
    # runs after flash_causal_attention's own (forward-scoped) context
    with pltpu.force_tpu_interpret_mode():
        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   atol=5e-4, rtol=5e-4)


def test_off_tpu_falls_back_to_exact_dense():
    # without force_kernel, a CPU backend must take the exact dense path
    q, k, v = _qkv(s=64)
    want = reference_causal_attention(q, k, v, 1.0 / np.sqrt(64))
    got = flash_causal_attention(q, k, v)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_transformer_flash_config_runs_and_matches_dense():
    # attn_impl='flash' off-TPU routes through the dense fallback: the
    # config is safe to carry everywhere, identical numerics on CPU
    from petastorm_tpu.models.transformer import (
        TransformerConfig, init_transformer_params, transformer_forward,
    )
    base = dict(vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
                max_seq_len=16, dtype=jnp.float32)
    params = init_transformer_params(
        jax.random.PRNGKey(0), TransformerConfig(**base))
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 32, (2, 16), np.int32))
    dense = transformer_forward(params, tokens, TransformerConfig(**base))
    flash = transformer_forward(
        params, tokens, TransformerConfig(attn_impl='flash', **base))
    # same math, different contraction layouts: allclose, not bit-equal
    np.testing.assert_allclose(np.asarray(dense), np.asarray(flash),
                               atol=1e-5, rtol=1e-5)


def test_invalid_attn_impl_rejected():
    from petastorm_tpu.models.transformer import TransformerConfig
    with pytest.raises(ValueError, match='attn_impl'):
        TransformerConfig(attn_impl='fused')


@slow
def test_bidirectional_kernel_matches_dense_oracle():
    # the fused kernel's non-causal mode (ViT/encoder attention)
    from petastorm_tpu.ops.flash_attention import flash_attention_fused
    from petastorm_tpu.ops.ring_attention import reference_attention
    q, k, v = _qkv(seed=3)
    want = reference_attention(q, k, v, causal=False,
                               scale=1.0 / np.sqrt(64))
    got = flash_attention_fused(q, k, v, causal=False, force_kernel=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@slow
def test_bidirectional_kernel_gradients_match_dense_oracle():
    # the non-causal VJP kernel (what ViT/encoder TRAINING runs under
    # attn_impl='flash') — forward-only coverage would let a backward
    # regression ship silently
    from jax.experimental.pallas import tpu as pltpu
    from petastorm_tpu.ops.flash_attention import flash_attention_fused
    from petastorm_tpu.ops.ring_attention import reference_attention
    q, k, v = _qkv(s=256, seed=4)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention_fused(q, k, v, causal=False,
                                             force_kernel=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=False,
                                           scale=1.0 / np.sqrt(64)) ** 2)

    with pltpu.force_tpu_interpret_mode():
        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                   atol=5e-4, rtol=5e-4)
