"""pipecheck gate + self-tests.

Two halves: (1) the baseline-zero gate — every analyzer pass over the
whole ``petastorm_tpu`` package yields no findings, so a contract
regression (raw env read, typo'd stage, blocking call under a lock,
leaky thread, closure payload) fails tier-1 at commit time; (2) rule
self-tests — the known-bad fixtures under ``tests/data/analysis/``
prove each rule actually fires, so the gate can never rot into a
scanner that silently matches nothing.
"""

import os
import subprocess
import sys

import pytest

from petastorm_tpu.analysis import (
    ALL_RULES, RULE_DESCRIPTIONS, analyze_paths, analyze_source, contracts,
)
from petastorm_tpu.analysis.core import iter_python_files
from petastorm_tpu.analysis.pass_env_knobs import check_docs_coverage

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, 'petastorm_tpu')
FIXTURES = os.path.join(REPO, 'tests', 'data', 'analysis')


def _fixture_findings(name, rule=None):
    path = os.path.join(FIXTURES, name)
    findings = analyze_paths([path], root=REPO, check_docs=False)
    if rule is not None:
        findings = [f for f in findings if f.rule == rule]
    return findings


# -- the gate -----------------------------------------------------------------


def test_package_is_finding_free():
    """The whole package passes every pass — the CI gate in test form."""
    findings = analyze_paths([PACKAGE], root=REPO)
    assert not findings, 'pipecheck findings on the tree:\n%s' \
        % '\n'.join(str(f) for f in findings)


def test_gate_scans_the_real_tree():
    """Guard against a silently-empty scan (wrong path, glob rot)."""
    files = list(iter_python_files([PACKAGE]))
    assert len(files) > 50
    assert any(f.endswith('dispatcher.py') for f in files)


def test_registered_knobs_are_documented():
    findings = check_docs_coverage(os.path.join(REPO, 'docs',
                                                'env_knobs.md'))
    assert not findings, '\n'.join(str(f) for f in findings)


def test_every_rule_has_a_description():
    assert set(ALL_RULES) == set(RULE_DESCRIPTIONS)
    assert len(ALL_RULES) == 6


# -- rule self-tests over the fixtures ---------------------------------------


def test_env_knob_rule_fires():
    findings = _fixture_findings('bad_env_knob.py', 'env-knob')
    lines = [f.line for f in findings]
    assert lines == [8, 11, 14, 17, 20], findings
    assert 'unregistered knob' in findings[-1].message


def test_canonical_name_rule_fires():
    findings = _fixture_findings('bad_canonical_name.py', 'canonical-name')
    assert [f.line for f in findings] == [11, 15, 16], findings
    # the metric finding resolved through a module-level constant
    assert 'petastorm_tpu_reventilated_totl' in findings[2].message


def test_blocking_under_lock_rule_fires():
    findings = _fixture_findings('bad_blocking_under_lock.py',
                                 'blocking-under-lock')
    lines = [f.line for f in findings]
    # 7 hazards in drain(), 1 in acquire_style(); the bounded/unlocked
    # calls in drain_politely() and after release() stay clean
    assert lines == [17, 18, 19, 20, 21, 22, 23, 34], findings


def test_lock_order_rule_fires():
    findings = _fixture_findings('bad_lock_order.py', 'lock-order')
    assert len(findings) == 1, findings
    assert '_IO_LOCK' in findings[0].message
    assert '_STATE_LOCK' in findings[0].message


def test_thread_lifecycle_rule_fires():
    findings = _fixture_findings('bad_thread_lifecycle.py',
                                 'thread-lifecycle')
    assert [f.line for f in findings] == [9, 31], findings


def test_pickle_payload_rule_fires():
    findings = _fixture_findings('bad_pickle_payload.py', 'pickle-payload')
    assert [f.line for f in findings] == [10, 11, 12], findings


def test_suppression_comment_silences_findings():
    assert _fixture_findings('suppressed.py') == []


def test_suppression_is_rule_specific():
    findings = analyze_source(
        "import queue\nimport threading\n_lock = threading.Lock()\n"
        "q = queue.Queue()\n"
        "def f():\n"
        "    with _lock:\n"
        "        q.get()  # pipecheck: disable=lock-order\n")
    assert [f.rule for f in findings] == ['blocking-under-lock']


# -- library/CLI surface ------------------------------------------------------


def test_analyze_source_on_clean_snippet():
    assert analyze_source('x = 1\n') == []


def test_select_narrows_rules():
    source = ("import os\nimport threading\n"
              "_RAW = os.environ.get('PETASTORM_TPU_STAGING')\n"
              "t = threading.Thread(target=print)\nt.start()\n")
    only_env = analyze_source(source, select=['env-knob'])
    assert [f.rule for f in only_env] == ['env-knob']
    both = analyze_source(source)
    assert {f.rule for f in both} == {'env-knob', 'thread-lifecycle'}


def test_findings_are_structured():
    findings = _fixture_findings('bad_lock_order.py')
    record = findings[0].as_dict()
    assert set(record) == {'path', 'line', 'rule', 'message'}
    assert str(findings[0]).startswith(record['path'])


def test_missing_path_raises_not_clean():
    """A scan of nothing must never read as a clean pass (a wrong cwd or
    a renamed package would otherwise turn the CI gate silently green)."""
    with pytest.raises(FileNotFoundError):
        analyze_paths(['no_such_dir_xyz'])


def test_empty_scan_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match='no Python files'):
        analyze_paths([str(tmp_path)])


def test_contracts_import_is_light():
    """telemetry's production import path (analysis.contracts via the
    knob registry) must not drag the ast/tokenize analyzer into every
    reader/worker process."""
    proc = subprocess.run(
        [sys.executable, '-c',
         'import sys; import petastorm_tpu.telemetry.knobs; '
         'bad = [m for m in sys.modules if "analysis.core" in m or '
         '"analysis.pass_" in m or "analysis.findings" in m]; '
         'assert not bad, bad; print("light")'],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert 'light' in proc.stdout


@pytest.mark.parametrize('args,expected_rc', [
    (['petastorm_tpu'], 0),
    (['tests/data/analysis/bad_lock_order.py', '--no-docs-check'], 1),
    (['--list-rules'], 0),
    (['petastorm_tpu', '--select', 'no-such-rule'], 2),
    (['no_such_dir_xyz'], 2),
])
def test_cli_exit_codes(args, expected_rc):
    proc = subprocess.run([sys.executable, '-m', 'petastorm_tpu.analysis']
                          + args, cwd=REPO, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == expected_rc, (proc.stdout, proc.stderr)


def test_cli_json_output():
    proc = subprocess.run(
        [sys.executable, '-m', 'petastorm_tpu.analysis',
         'tests/data/analysis/bad_lock_order.py', '--json',
         '--no-docs-check'],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    import json
    records = [json.loads(line) for line in proc.stdout.splitlines()]
    assert records and records[0]['rule'] == 'lock-order'


# -- contracts stay in sync with the runtime ---------------------------------


def test_contracts_are_the_runtime_sets():
    """telemetry imports the SAME objects the checker verifies against —
    the drift this PR exists to make impossible."""
    from petastorm_tpu import telemetry
    from petastorm_tpu.telemetry import tracing
    assert telemetry.STAGES is contracts.STAGES
    assert tracing.EVENT_NAMES is contracts.EVENT_NAMES
    from petastorm_tpu.telemetry.knobs import KNOWN_KNOBS
    assert KNOWN_KNOBS is contracts.KNOWN_KNOBS
