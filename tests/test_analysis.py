"""pipecheck gate + self-tests.

Two halves: (1) the baseline-zero gate — every analyzer pass over the
whole ``petastorm_tpu`` package yields no findings, so a contract
regression (raw env read, typo'd stage, blocking call under a lock,
leaky thread, closure payload) fails tier-1 at commit time; (2) rule
self-tests — the known-bad fixtures under ``tests/data/analysis/``
prove each rule actually fires, so the gate can never rot into a
scanner that silently matches nothing.
"""

import os
import subprocess
import sys

import pytest

from petastorm_tpu.analysis import (
    ALL_RULES, RULE_DESCRIPTIONS, analyze_paths, analyze_source, contracts,
)
from petastorm_tpu.analysis.core import iter_python_files
from petastorm_tpu.analysis.pass_env_knobs import check_docs_coverage

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, 'petastorm_tpu')
FIXTURES = os.path.join(REPO, 'tests', 'data', 'analysis')


def _fixture_findings(name, rule=None):
    path = os.path.join(FIXTURES, name)
    findings = analyze_paths([path], root=REPO, check_docs=False)
    if rule is not None:
        findings = [f for f in findings if f.rule == rule]
    return findings


# -- the gate -----------------------------------------------------------------


def test_package_is_finding_free():
    """The whole package passes every pass — the CI gate in test form."""
    findings = analyze_paths([PACKAGE], root=REPO)
    assert not findings, 'pipecheck findings on the tree:\n%s' \
        % '\n'.join(str(f) for f in findings)


def test_gate_scans_the_real_tree():
    """Guard against a silently-empty scan (wrong path, glob rot)."""
    files = list(iter_python_files([PACKAGE]))
    assert len(files) > 50
    assert any(f.endswith('dispatcher.py') for f in files)


def test_registered_knobs_are_documented():
    findings = check_docs_coverage(os.path.join(REPO, 'docs',
                                                'env_knobs.md'))
    assert not findings, '\n'.join(str(f) for f in findings)


def test_every_rule_has_a_description():
    assert set(ALL_RULES) == set(RULE_DESCRIPTIONS)
    assert len(ALL_RULES) == 9


# -- rule self-tests over the fixtures ---------------------------------------


def test_env_knob_rule_fires():
    findings = _fixture_findings('bad_env_knob.py', 'env-knob')
    lines = [f.line for f in findings]
    assert lines == [8, 11, 14, 17, 20], findings
    assert 'unregistered knob' in findings[-1].message


def test_canonical_name_rule_fires():
    findings = _fixture_findings('bad_canonical_name.py', 'canonical-name')
    assert [f.line for f in findings] == [11, 15, 16], findings
    # the metric finding resolved through a module-level constant
    assert 'petastorm_tpu_reventilated_totl' in findings[2].message


def test_faultpoint_rule_fires():
    """Every fault_hit() call site must name a registered faultpoint —
    literal or resolved through a module constant; the registered site
    at the fixture's tail stays clean."""
    findings = _fixture_findings('bad_faultpoint.py', 'faultpoint')
    assert [f.line for f in findings] == [9, 11], findings
    assert 'io.reed' in findings[0].message
    assert 'contracts.FAULTPOINTS' in findings[0].message
    assert 'decode.rowgrup' in findings[1].message


def test_blocking_under_lock_rule_fires():
    findings = _fixture_findings('bad_blocking_under_lock.py',
                                 'blocking-under-lock')
    lines = [f.line for f in findings]
    # 7 hazards in drain(), 1 in acquire_style(); the bounded/unlocked
    # calls in drain_politely() and after release() stay clean
    assert lines == [17, 18, 19, 20, 21, 22, 23, 34], findings


def test_lock_order_rule_fires():
    findings = _fixture_findings('bad_lock_order.py', 'lock-order')
    assert len(findings) == 1, findings
    assert '_IO_LOCK' in findings[0].message
    assert '_STATE_LOCK' in findings[0].message


def test_thread_lifecycle_rule_fires():
    findings = _fixture_findings('bad_thread_lifecycle.py',
                                 'thread-lifecycle')
    assert [f.line for f in findings] == [9, 31], findings


def test_pickle_payload_rule_fires():
    findings = _fixture_findings('bad_pickle_payload.py', 'pickle-payload')
    assert [f.line for f in findings] == [10, 11, 12], findings


def test_buffer_escape_rule_fires():
    findings = _fixture_findings('bad_buffer_escape.py', 'buffer-escape')
    # object state, queue, closure, return, astype alias, whole-program
    # propagation through give_back(); the owned/annotated/killed-taint
    # functions at the fixture's tail stay clean
    assert [f.line for f in findings] == [11, 15, 20, 25, 36, 41], findings
    assert 'give_back()' in findings[-1].message


def test_buffer_write_rule_fires():
    findings = _fixture_findings('bad_buffer_escape.py', 'buffer-write')
    assert [f.line for f in findings] == [30, 31, 32], findings
    assert 'copyto' in findings[2].message


def test_owns_annotation_silences_buffer_findings():
    findings = analyze_source(
        "import numpy as np\n"
        "def f(buf):\n"
        "    view = np.frombuffer(buf, dtype=np.uint8)\n"
        "    return view  # pipesan: owns\n")
    assert findings == []


def test_fresh_temporary_views_are_owned_by_construction():
    # frombuffer over a call expression: the anonymous temporary's only
    # reference becomes the array's .base — owned, not borrowed
    assert analyze_source(
        "import numpy as np\n"
        "def f(payload):\n"
        "    return np.frombuffer(bytes(payload), dtype=np.uint8)\n") == []


def test_comprehensions_respect_laundering_and_unpack_is_elementwise():
    """[v.copy() for v in views] (the documented fix) and a literal
    tuple unpack assigning a fresh copy next to a tainted value are both
    clean; a comprehension carrying the raw views still taints."""
    assert analyze_source(
        "import numpy as np\n"
        "def f(frames):\n"
        "    views = [np.frombuffer(b) for b in frames]\n"
        "    return [v.copy() for v in views]\n"
        "def g(frames):\n"
        "    views = [np.frombuffer(b) for b in frames]\n"
        "    return [len(v) for v in views]\n"
        "def h(buf):\n"
        "    view = np.frombuffer(buf, dtype=np.uint8)\n"
        "    size, owned = view.nbytes, view.copy()\n"
        "    return owned\n"
        "def k(buf):\n"
        "    view = np.frombuffer(buf, dtype=np.uint8)\n"
        "    return view.shape[0] * view.itemsize\n") == []
    tainted = analyze_source(
        "import numpy as np\n"
        "def f(frames):\n"
        "    return [np.frombuffer(b) for b in frames]\n")
    assert [f.rule for f in tainted] == ['buffer-escape']


def test_recv_frames_list_mutation_is_not_a_buffer_write():
    """recv_multipart returns a caller-owned LIST; replacing/extending
    its elements mutates the list, not the borrowed frame memory."""
    assert analyze_source(
        "def f(sock, header):\n"
        "    frames = sock.recv_multipart(copy=False)\n"
        "    frames[0] = header\n"
        "    frames += [b'trailer']\n"
        "    return len(frames)\n") == []


def test_owning_methods_launder_taint():
    """view.copy() (and reductions/materializations) OWN their result —
    the canonical fix for an escape finding must itself be clean."""
    assert analyze_source(
        "import numpy as np\n"
        "def f(buf):\n"
        "    view = np.frombuffer(buf, dtype=np.uint8)\n"
        "    return view.copy()\n"
        "def g(buf):\n"
        "    view = np.frombuffer(buf, dtype=np.uint8)\n"
        "    return view.sum()\n") == []


def test_whole_program_lock_order_rule_fires():
    findings = _fixture_findings('bad_lock_order_global', 'lock-order')
    assert len(findings) == 1, findings
    assert 'whole-program' in findings[0].message
    assert '_A_LOCK' in findings[0].message
    assert '_FLUSH_LOCK' in findings[0].message


def test_whole_program_lock_order_resolves_imported_locks(tmp_path):
    """A lock IMPORTED from another module must globalize to its defining
    module, or the two sides of a cross-module inversion never compare
    equal (regression: false negative)."""
    (tmp_path / 'liba.py').write_text(
        "import threading\n"
        "from libb import FLUSH_LOCK\n"
        "A_LOCK = threading.Lock()\n"
        "def one():\n"
        "    with A_LOCK:\n"
        "        with FLUSH_LOCK:\n"
        "            pass\n")
    (tmp_path / 'libb.py').write_text(
        "import threading\n"
        "from liba import A_LOCK\n"
        "FLUSH_LOCK = threading.Lock()\n"
        "def two():\n"
        "    with FLUSH_LOCK:\n"
        "        with A_LOCK:\n"
        "            pass\n")
    findings = analyze_paths([str(tmp_path)], check_docs=False)
    locks = [f for f in findings if f.rule == 'lock-order']
    assert len(locks) == 1, findings
    assert 'A_LOCK' in locks[0].message
    assert 'FLUSH_LOCK' in locks[0].message


def test_whole_program_pass_defers_same_module_inversions():
    """An inversion whose both orders are lexical within one module is
    the per-module scan's report — run_project must not double-report it
    even when a call-graph witness for one order is recorded first."""
    findings = analyze_source(
        "import threading\n"
        "a_lock = threading.Lock()\n"
        "b_lock = threading.Lock()\n"
        "def helper():\n"
        "    with b_lock:\n"
        "        pass\n"
        "def f1():\n"
        "    with a_lock:\n"
        "        helper()\n"
        "def f2():\n"
        "    with a_lock:\n"
        "        with b_lock:\n"
        "            pass\n"
        "def f3():\n"
        "    with b_lock:\n"
        "        with a_lock:\n"
        "            pass\n", select=['lock-order'])
    assert len(findings) == 1, findings


def test_suppression_comment_silences_findings():
    assert _fixture_findings('suppressed.py') == []


def test_suppression_is_rule_specific():
    findings = analyze_source(
        "import queue\nimport threading\n_lock = threading.Lock()\n"
        "q = queue.Queue()\n"
        "def f():\n"
        "    with _lock:\n"
        "        q.get()  # pipecheck: disable=lock-order\n")
    assert [f.rule for f in findings] == ['blocking-under-lock']


# -- library/CLI surface ------------------------------------------------------


def test_analyze_source_on_clean_snippet():
    assert analyze_source('x = 1\n') == []


def test_select_narrows_rules():
    source = ("import os\nimport threading\n"
              "_RAW = os.environ.get('PETASTORM_TPU_STAGING')\n"
              "t = threading.Thread(target=print)\nt.start()\n")
    only_env = analyze_source(source, select=['env-knob'])
    assert [f.rule for f in only_env] == ['env-knob']
    both = analyze_source(source)
    assert {f.rule for f in both} == {'env-knob', 'thread-lifecycle'}


def test_findings_are_structured():
    findings = _fixture_findings('bad_lock_order.py')
    record = findings[0].as_dict()
    assert set(record) == {'path', 'line', 'rule', 'message'}
    assert str(findings[0]).startswith(record['path'])


def test_missing_path_raises_not_clean():
    """A scan of nothing must never read as a clean pass (a wrong cwd or
    a renamed package would otherwise turn the CI gate silently green)."""
    with pytest.raises(FileNotFoundError):
        analyze_paths(['no_such_dir_xyz'])


def test_empty_scan_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match='no Python files'):
        analyze_paths([str(tmp_path)])


def test_contracts_import_is_light():
    """telemetry's production import path (analysis.contracts via the
    knob registry) must not drag the ast/tokenize analyzer into every
    reader/worker process."""
    proc = subprocess.run(
        [sys.executable, '-c',
         'import sys; import petastorm_tpu.telemetry.knobs; '
         'bad = [m for m in sys.modules if "analysis.core" in m or '
         '"analysis.pass_" in m or "analysis.findings" in m]; '
         'assert not bad, bad; print("light")'],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert 'light' in proc.stdout


@pytest.mark.parametrize('args,expected_rc', [
    (['petastorm_tpu'], 0),
    (['tests/data/analysis/bad_lock_order.py', '--no-docs-check'], 1),
    (['--list-rules'], 0),
    (['petastorm_tpu', '--select', 'no-such-rule'], 2),
    (['no_such_dir_xyz'], 2),
])
def test_cli_exit_codes(args, expected_rc):
    proc = subprocess.run([sys.executable, '-m', 'petastorm_tpu.analysis']
                          + args, cwd=REPO, capture_output=True, text=True,
                          timeout=120)
    assert proc.returncode == expected_rc, (proc.stdout, proc.stderr)


def _run_cli(args, **kw):
    return subprocess.run([sys.executable, '-m', 'petastorm_tpu.analysis']
                          + args, cwd=REPO, capture_output=True, text=True,
                          timeout=120, **kw)


def test_cli_baseline_filters_known_findings(tmp_path):
    """--baseline lets a rule land strict-on-new-code: a --json dump of
    the current findings turns the same scan green."""
    fixture = 'tests/data/analysis/bad_buffer_escape.py'
    dump = _run_cli([fixture, '--json', '--no-docs-check'])
    assert dump.returncode == 1
    baseline = tmp_path / 'baseline.jsonl'
    baseline.write_text(dump.stdout)
    clean = _run_cli([fixture, '--baseline', str(baseline),
                      '--fail-on-new', '--no-docs-check'])
    assert clean.returncode == 0, (clean.stdout, clean.stderr)
    assert 'suppressed' in clean.stderr


def test_cli_baseline_still_fails_on_new_findings(tmp_path):
    dump = _run_cli(['tests/data/analysis/bad_lock_order.py', '--json',
                     '--no-docs-check'])
    baseline = tmp_path / 'baseline.jsonl'
    baseline.write_text(dump.stdout)
    mixed = _run_cli(['tests/data/analysis/bad_lock_order.py',
                      'tests/data/analysis/bad_buffer_escape.py',
                      '--baseline', str(baseline), '--no-docs-check'])
    assert mixed.returncode == 1
    # only the NEW findings survive the filter
    assert 'bad_lock_order.py' not in mixed.stdout
    assert 'bad_buffer_escape.py' in mixed.stdout


def test_cli_fail_on_new_requires_a_baseline():
    proc = _run_cli(['petastorm_tpu', '--fail-on-new'])
    assert proc.returncode == 2
    assert '--baseline' in proc.stderr


def test_cli_unusable_baseline_is_an_error(tmp_path):
    """A corrupt baseline must not silently waive every finding."""
    bogus = tmp_path / 'bogus.jsonl'
    bogus.write_text('not json\n')
    proc = _run_cli(['tests/data/analysis/bad_lock_order.py',
                     '--baseline', str(bogus), '--no-docs-check'])
    assert proc.returncode == 2
    assert 'unusable baseline' in proc.stderr


def test_cli_json_output():
    proc = subprocess.run(
        [sys.executable, '-m', 'petastorm_tpu.analysis',
         'tests/data/analysis/bad_lock_order.py', '--json',
         '--no-docs-check'],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    import json
    records = [json.loads(line) for line in proc.stdout.splitlines()]
    assert records and records[0]['rule'] == 'lock-order'


# -- contracts stay in sync with the runtime ---------------------------------


def test_contracts_are_the_runtime_sets():
    """telemetry imports the SAME objects the checker verifies against —
    the drift this PR exists to make impossible."""
    from petastorm_tpu import telemetry
    from petastorm_tpu.telemetry import tracing
    assert telemetry.STAGES is contracts.STAGES
    assert tracing.EVENT_NAMES is contracts.EVENT_NAMES
    from petastorm_tpu.telemetry.knobs import KNOWN_KNOBS
    assert KNOWN_KNOBS is contracts.KNOWN_KNOBS
