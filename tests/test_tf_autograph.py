"""AutoGraph compatibility of the tf.data bridge (reference:
``petastorm/tests/test_tf_autograph.py``): iterating a
``make_petastorm_dataset`` inside a ``@tf.function`` is the
autograph-traced consumption path (a real TF training loop), and the
generator-backed dataset must neither fail the transform nor change
results."""

import logging

import numpy as np
import pytest

from petastorm_tpu.reader import make_batch_reader

tf = pytest.importorskip('tensorflow')

from petastorm_tpu.tf_utils import make_petastorm_dataset  # noqa: E402


def test_dataset_iterated_inside_tf_function(scalar_dataset, caplog):
    @tf.function
    def consume(ds):
        total = tf.zeros((), tf.int64)
        count = tf.zeros((), tf.int64)
        for batch in ds:  # autograph rewrites this loop into tf.while_loop
            total += tf.reduce_sum(tf.cast(batch.id, tf.int64))
            count += tf.cast(tf.shape(batch.id)[0], tf.int64)
        return total, count

    caplog.clear()
    with caplog.at_level(logging.WARNING, logger='tensorflow'):
        with make_batch_reader(scalar_dataset.url, num_epochs=1,
                               schema_fields=['^id$']) as reader:
            dataset = make_petastorm_dataset(reader)
            total, count = consume(dataset)
    assert int(count) == 100
    assert int(total) == sum(row['id'] for row in scalar_dataset.data)
    messages = ' '.join(r.getMessage() for r in caplog.records)
    assert 'AutoGraph could not transform' not in messages, messages
