"""Materialized decoded-row-group cache: Arrow IPC round-trip, zero-copy
mmap hits, fingerprint invalidation, crash safety, end-to-end wiring."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from petastorm_tpu import telemetry as T
from petastorm_tpu.arrow_worker import ColumnBatch
from petastorm_tpu.materialized_cache import (
    MaterializedRowGroupCache, callable_fingerprint, decode_fingerprint,
    ngram_fingerprint, read_entry, schema_fingerprint,
    transform_fingerprint, write_entry,
)
from petastorm_tpu.transform import TransformSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_telemetry():
    T.reset_for_tests()
    yield
    T.reset_for_tests()


def _sample_columns(n=4):
    return {
        'image': np.arange(n * 8 * 8 * 3, dtype=np.uint8).reshape(n, 8, 8, 3),
        'ids': np.arange(n, dtype=np.int64),
        'score': np.linspace(0, 1, n, dtype=np.float32),
        'name': np.array(['row%d' % i for i in range(n)]),
        'ragged': np.array([np.arange(i + 1) for i in range(n)],
                           dtype=object),
    }


def _cache(tmp_path, mem_mb=0, disk_limit=10 ** 8):
    return MaterializedRowGroupCache(str(tmp_path / 'dc'), disk_limit,
                                     mem_limit_bytes=mem_mb * 2 ** 20)


def _fill(columns, calls=None):
    def fill():
        if calls is not None:
            calls.append(1)
        return ColumnBatch(dict(columns), len(columns['ids']))
    return fill


class TestRoundTrip:
    def test_decode_once_then_hit(self, tmp_path):
        cache = _cache(tmp_path)
        cols = _sample_columns()
        calls = []
        first = cache.get('k', _fill(cols, calls))
        second = cache.get('k', _fill(cols, calls))
        assert len(calls) == 1
        assert first.length == second.length == 4
        for name in cols:
            if name == 'ragged':
                for a, b in zip(cols['ragged'], second.columns['ragged']):
                    np.testing.assert_array_equal(a, b)
            else:
                np.testing.assert_array_equal(second.columns[name],
                                              cols[name])

    def test_hit_is_mmap_backed_not_fresh_allocation(self, tmp_path):
        """The acceptance gate's zero-copy claim: numeric/string columns
        of a disk-tier hit alias the IPC file's memory map — their base
        chain ends in a pyarrow Buffer and they own no data."""
        cache = _cache(tmp_path)
        cols = _sample_columns()
        cache.get('k', _fill(cols))
        batch = cache.get('k', _fill(cols))
        for name in ('image', 'ids', 'score', 'name'):
            col = batch.columns[name]
            assert not col.flags['OWNDATA'], name
            base = col
            while getattr(base, 'base', None) is not None and \
                    type(base).__module__.split('.')[0] != 'pyarrow':
                base = base.base
            assert type(base).__module__.split('.')[0] == 'pyarrow', \
                '%s not backed by the IPC buffer: %r' % (name, type(base))
        registry = T.get_registry()
        assert registry.counter_value(
            'petastorm_tpu_decoded_cache_mmap_reads_total') >= 4

    def test_hit_records_no_decode_or_transform_spans(self, tmp_path):
        cache = _cache(tmp_path)
        cols = _sample_columns()
        cache.get('k', _fill(cols))
        registry = T.get_registry()
        base = registry.counter_value('petastorm_tpu_stage_calls_total',
                                      stage='cache_hit_read')
        cache.get('k', _fill(cols))
        assert registry.counter_value('petastorm_tpu_stage_calls_total',
                                      stage='decode') == 0
        assert registry.counter_value('petastorm_tpu_stage_calls_total',
                                      stage='transform') == 0
        assert registry.counter_value('petastorm_tpu_stage_calls_total',
                                      stage='cache_hit_read') == base + 1

    def test_empty_rowgroup_tombstone(self, tmp_path):
        """A filter-emptied row-group (fill returns None) is cached as a
        tombstone: the warm epoch skips the re-read too."""
        cache = _cache(tmp_path)
        calls = []
        assert cache.get('k', lambda: calls.append(1)) is None
        assert cache.get('k', lambda: calls.append(1)) is None
        assert len(calls) == 1

    def test_memory_tier_hit_touches_disk_lru(self, tmp_path):
        """Eviction sorts by the disk entry's atime: a row-group served
        from the memory tier is HOT and must not age toward eviction."""
        cache = _cache(tmp_path, mem_mb=64)
        cols = _sample_columns()
        cache.get('k', _fill(cols))
        entry = cache._entry_path('k')
        os.utime(entry, (1.0, 1.0))  # pretend it is ancient
        cache.get('k', _fill(cols))  # memory-tier hit
        assert os.stat(entry).st_atime > 1.0

    def test_memory_tier_serves_without_disk(self, tmp_path):
        import shutil
        cache = _cache(tmp_path, mem_mb=64)
        cols = _sample_columns()
        cache.get('k', _fill(cols))
        shutil.rmtree(str(tmp_path / 'dc'))  # disk tier gone
        batch = cache.get('k', _fill(cols))
        np.testing.assert_array_equal(batch.columns['image'], cols['image'])
        assert T.get_registry().counter_value(
            'petastorm_tpu_decoded_cache_mem_hits_total') == 1

    def test_disk_tier_lru_eviction_bounds_size(self, tmp_path):
        cache = MaterializedRowGroupCache(str(tmp_path / 'dc'), 200_000,
                                          mem_limit_bytes=0)
        payload = {'x': np.zeros(50_000, dtype=np.uint8)}
        for i in range(10):
            cache.get('k%d' % i, _fill({'x': payload['x'],
                                        'ids': np.arange(1)}))
            time.sleep(0.01)  # distinct atimes for a deterministic LRU
        total = sum(os.path.getsize(os.path.join(root, f))
                    for root, _, files in os.walk(str(tmp_path / 'dc'))
                    for f in files)
        assert total <= 200_000
        assert T.get_registry().counter_value(
            'petastorm_tpu_decoded_cache_evictions_total') > 0

    def test_corrupt_entry_deleted_and_refilled(self, tmp_path):
        cache = _cache(tmp_path)
        cols = _sample_columns()
        cache.get('k', _fill(cols))
        entry = cache._entry_path('k')
        with open(entry, 'wb') as f:
            f.write(b'not an arrow file')
        batch = cache.get('k', _fill(cols))
        np.testing.assert_array_equal(batch.columns['ids'], cols['ids'])
        # refilled with a valid entry, readable again
        assert read_entry(entry)[1] == 4

    def test_truncated_entry_treated_as_miss(self, tmp_path):
        cache = _cache(tmp_path)
        cols = _sample_columns()
        cache.get('k', _fill(cols))
        entry = cache._entry_path('k')
        blob = open(entry, 'rb').read()
        with open(entry, 'wb') as f:
            f.write(blob[:len(blob) // 2])
        calls = []
        batch = cache.get('k', _fill(cols, calls))
        assert len(calls) == 1
        np.testing.assert_array_equal(batch.columns['image'], cols['image'])

    def test_pickles_across_process_boundary(self, tmp_path):
        import pickle
        cache = _cache(tmp_path, mem_mb=16)
        cols = _sample_columns()
        cache.get('k', _fill(cols))
        clone = pickle.loads(pickle.dumps(cache))
        calls = []
        batch = clone.get('k', _fill(cols, calls))
        assert not calls  # served from the shared disk tier
        np.testing.assert_array_equal(batch.columns['ids'], cols['ids'])

    def test_reroot_switches_directory(self, tmp_path):
        cache = _cache(tmp_path, mem_mb=16)
        cols = _sample_columns()
        cache.get('k', _fill(cols))
        cache.reroot(str(tmp_path / 'other'))
        calls = []
        cache.get('k', _fill(cols, calls))
        assert len(calls) == 1  # fresh tier: the old dir's entry is gone
        assert os.path.isdir(str(tmp_path / 'other'))


# -- fingerprints: never serve stale decoded rows ---------------------------


def _transform_a(df):
    return df


def _transform_b(df):
    return df.head(1)


def _closure_transform(k):
    def inner(df):
        return df.head(k)
    return inner


def _transform_with_inner_lambda(df):
    return df.assign(id=df['id'].map(lambda x: x + 0))


def sample_decode_fingerprint():
    """Helper shared with the cross-process determinism test (the child
    imports and prints it; both sides must agree). Deliberately includes
    a NESTED lambda: its code object lands in co_consts, where a naive
    repr-based digest would embed a per-process memory address and
    silently defeat the shared cache."""
    from tests.test_common import TestSchema
    spec = TransformSpec(_transform_with_inner_lambda,
                         removed_fields=['matrix_string'])
    return decode_fingerprint(TestSchema, spec)


class TestFingerprints:
    def test_transform_code_change_misses(self):
        assert transform_fingerprint(TransformSpec(_transform_a)) != \
            transform_fingerprint(TransformSpec(_transform_b))

    def test_transform_closure_change_misses(self):
        assert callable_fingerprint(_closure_transform(2)) != \
            callable_fingerprint(_closure_transform(3))

    def test_transform_schema_edit_change_misses(self):
        base = TransformSpec(_transform_a)
        removed = TransformSpec(_transform_a, removed_fields=['x'])
        selected = TransformSpec(_transform_a, selected_fields=['x'])
        prints = {transform_fingerprint(s) for s in (base, removed,
                                                     selected)}
        assert len(prints) == 3

    def test_identical_spec_same_fingerprint(self):
        a = TransformSpec(_transform_a, removed_fields=['x'])
        b = TransformSpec(_transform_a, removed_fields=['x'])
        assert transform_fingerprint(a) == transform_fingerprint(b)

    def test_none_transform_stable(self):
        assert transform_fingerprint(None) == 'none'

    def test_large_ndarray_closure_change_misses(self):
        """numpy repr truncates big arrays with '…': a repr-based digest
        would collide two different lookup tables and serve the OLD
        transform's cached output — the digest must hash the bytes."""
        base = np.arange(10_000, dtype=np.int64)
        changed = base.copy()
        changed[5_000] += 1

        def closing(table):
            def inner(df):
                return table
            return inner
        assert callable_fingerprint(closing(base)) != \
            callable_fingerprint(closing(changed))
        assert callable_fingerprint(closing(base)) == \
            callable_fingerprint(closing(base.copy()))

    def test_nested_lambda_fingerprint_is_process_stable(self):
        """repr() of a code object carries its memory address; the digest
        must not (checked directly here, and across real processes by
        test_identical_spec_across_processes_hits)."""
        fp = callable_fingerprint(_transform_with_inner_lambda)
        assert '0x' not in fp
        assert fp == callable_fingerprint(_transform_with_inner_lambda)

    def test_ngram_shape_change_misses(self):
        from petastorm_tpu.ngram import NGram
        from tests.test_common import TestSchema

        def gram(length):
            fields = {i: ['id', 'matrix'] for i in range(length)}
            return NGram(fields, delta_threshold=10, timestamp_field='id')
        assert ngram_fingerprint(gram(2)) != ngram_fingerprint(gram(3))
        assert ngram_fingerprint(gram(2)) == ngram_fingerprint(gram(2))
        assert ngram_fingerprint(None) == 'none'
        assert decode_fingerprint(TestSchema, None, gram(2)) != \
            decode_fingerprint(TestSchema, None, gram(3))

    def test_codec_parameter_change_misses(self):
        import pyarrow as pa
        from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec
        from petastorm_tpu.unischema import Unischema, UnischemaField

        def schema(quality):
            return Unischema('S', [
                UnischemaField('id', np.int32, (), ScalarCodec(pa.int32()),
                               False),
                UnischemaField('image', np.uint8, (8, 8, 3),
                               CompressedImageCodec('jpeg', quality=quality),
                               False),
            ])
        assert schema_fingerprint(schema(80)) != schema_fingerprint(
            schema(90))
        assert schema_fingerprint(schema(80)) == schema_fingerprint(
            schema(80))

    def test_column_set_change_misses(self):
        from tests.test_common import TestSchema
        view = TestSchema.create_schema_view(['id', 'matrix'])
        assert schema_fingerprint(TestSchema) != schema_fingerprint(view)

    def test_identical_spec_across_processes_hits(self):
        """The fleet contract: two processes importing the same transform
        derive the SAME key (code-byte hashing is deterministic), so a
        shared directory serves both."""
        out = subprocess.run(
            [sys.executable, '-c',
             'from tests.test_materialized_cache import '
             'sample_decode_fingerprint; print(sample_decode_fingerprint())'],
            cwd=REPO, capture_output=True, text=True, timeout=120,
            env=dict(os.environ, JAX_PLATFORMS='cpu'))
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == sample_decode_fingerprint()


# -- crash safety ------------------------------------------------------------

_CRASH_WRITER = r'''
import numpy as np, sys
from petastorm_tpu.arrow_worker import ColumnBatch
from petastorm_tpu.materialized_cache import MaterializedRowGroupCache
cache = MaterializedRowGroupCache(sys.argv[1], 10**9, mem_limit_bytes=0)
cols = {'x': np.arange(200_000, dtype=np.int64)}
print('ready', flush=True)
i = 0
while True:
    cache.get('key%d' % i, lambda: ColumnBatch(dict(cols), 1))
    i += 1
'''


class TestCrashSafety:
    def test_sigkill_mid_write_never_exposes_partial_entry(self, tmp_path):
        """A writer SIGKILLed in a tight fill loop leaves at most tmp
        files behind: every PUBLISHED entry must open and round-trip
        (os.replace is the commit point), and a fresh cache purges the
        orphan tmps at init."""
        cache_dir = str(tmp_path / 'dc')
        proc = subprocess.Popen(
            [sys.executable, '-c', _CRASH_WRITER, cache_dir],
            cwd=REPO, stdout=subprocess.PIPE, text=True,
            env=dict(os.environ, JAX_PLATFORMS='cpu'))
        try:
            assert proc.stdout.readline().strip() == 'ready'
            time.sleep(0.3)  # let a few dozen writes land
        finally:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
        published = [os.path.join(root, f)
                     for root, _, files in os.walk(cache_dir)
                     for f in files if '.tmp.' not in f]
        assert published, 'writer never published an entry'
        for path in published:
            columns, length, _, _ = read_entry(path)  # raises on partial
            assert length == 1
            np.testing.assert_array_equal(columns['x'],
                                          np.arange(200_000,
                                                    dtype=np.int64))
        # a fresh cache over the same dir purges dead writers' tmp files
        MaterializedRowGroupCache(cache_dir, 10 ** 9)
        leftovers = [f for root, _, files in os.walk(cache_dir)
                     for f in files if '.tmp.' in f]
        assert not leftovers, leftovers


# -- end-to-end through make_reader -----------------------------------------


class TestEndToEnd:
    def _read_all(self, url, tmp_path, **extra):
        from petastorm_tpu.reader import make_reader
        kwargs = dict(reader_pool_type='thread', workers_count=2,
                      shuffle_row_groups=False, cache_type='decoded',
                      cache_location=str(tmp_path / 'dc'),
                      cache_size_limit=10 ** 9)
        kwargs.update(extra)
        with make_reader(url, **kwargs) as reader:
            return {row.id: row for row in reader}

    def test_warm_pass_is_cache_bound_with_zero_decode(
            self, synthetic_dataset, tmp_path):
        """The acceptance gate: epoch 2 serves every row identically from
        the cache, pipeline_report classifies the pass cache-bound, and
        the hit path records zero decode/transform spans."""
        rows1 = self._read_all(synthetic_dataset.url, tmp_path)
        registry = T.get_registry()
        assert registry.counter_value(
            'petastorm_tpu_decoded_cache_misses_total') > 0
        mid = registry.snapshot()
        rows2 = self._read_all(synthetic_dataset.url, tmp_path)
        assert set(rows1) == set(rows2) and len(rows1) == 100
        for i in list(rows1)[:10]:
            np.testing.assert_array_equal(rows1[i].matrix, rows2[i].matrix)
            np.testing.assert_array_equal(rows1[i].image_png,
                                          rows2[i].image_png)
            assert rows1[i].decimal == rows2[i].decimal
        report = T.pipeline_report(baseline=mid)
        cache = report['decoded_cache']
        assert cache['verdict'] == 'cache-bound'
        assert cache['hit_rate'] == 1.0
        assert cache['mmap_reads'] > 0

        def warm_calls(stage):
            key = 'petastorm_tpu_stage_calls_total{stage="%s"}' % stage
            return registry.counter_value(
                'petastorm_tpu_stage_calls_total',
                stage=stage) - mid['counters'].get(key, 0)
        assert warm_calls('decode') == 0
        assert warm_calls('io') == 0
        assert warm_calls('cache_hit_read') > 0

    def test_transform_spec_output_is_cached(self, synthetic_dataset,
                                             tmp_path):
        """Unlike the raw pickle cache (which bypasses transform readers),
        the decoded tier caches POST-transform batches."""
        spec = TransformSpec(_transform_a, removed_fields=['matrix_string'])
        rows1 = self._read_all(synthetic_dataset.url, tmp_path,
                               transform_spec=spec)
        mid = T.get_registry().snapshot()
        rows2 = self._read_all(synthetic_dataset.url, tmp_path,
                               transform_spec=spec)
        assert len(rows1) == len(rows2) == 100
        assert 'matrix_string' not in rows2[0]._fields
        section = T.decoded_cache_section(baseline=mid)
        assert section['hit_rate'] == 1.0

    def test_uncacheable_transform_bypasses_decoded_cache(
            self, synthetic_dataset, tmp_path):
        """TransformSpec(cacheable=False) marks a stochastic transform:
        caching it would replay epoch 1's randomness, so those readers
        decode fresh every pass and never touch the decoded cache."""
        spec = TransformSpec(_transform_a, cacheable=False)
        self._read_all(synthetic_dataset.url, tmp_path,
                       transform_spec=spec)
        self._read_all(synthetic_dataset.url, tmp_path,
                       transform_spec=spec)
        registry = T.get_registry()
        assert registry.counter_value(
            'petastorm_tpu_decoded_cache_hits_total') == 0
        assert registry.counter_value(
            'petastorm_tpu_decoded_cache_misses_total') == 0

    def test_changed_transform_never_serves_stale_rows(
            self, synthetic_dataset, tmp_path):
        self._read_all(synthetic_dataset.url, tmp_path,
                       transform_spec=TransformSpec(_transform_a))
        mid = T.get_registry().snapshot()
        self._read_all(synthetic_dataset.url, tmp_path,
                       transform_spec=TransformSpec(
                           _transform_a, removed_fields=['matrix_string']))
        section = T.decoded_cache_section(baseline=mid)
        assert section['hits'] == 0  # every read missed: new fingerprint

    def test_env_knob_never_breaks_predicate_readers(self,
                                                     synthetic_dataset,
                                                     tmp_path,
                                                     monkeypatch):
        """A fleet-wide PETASTORM_TPU_DECODED_CACHE=1 must not turn a
        previously-working predicate reader into the cache+predicate
        RuntimeError: arbitrary predicates simply stay uncached."""
        from petastorm_tpu.predicates import in_lambda
        from petastorm_tpu.reader import make_reader
        monkeypatch.setenv('PETASTORM_TPU_DECODED_CACHE', '1')
        monkeypatch.setenv('PETASTORM_TPU_DECODED_CACHE_DIR',
                           str(tmp_path / 'fleet'))
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         predicate=in_lambda(['id'],
                                             lambda v: v['id'] < 5),
                         num_epochs=1) as reader:
            rows = list(reader)
        assert rows and all(r.id < 5 for r in rows)
        assert T.get_registry().counter_value(
            'petastorm_tpu_decoded_cache_misses_total') == 0

    def test_env_knob_never_caches_undeclared_transforms(
            self, synthetic_dataset, tmp_path, monkeypatch):
        """The fleet knob must not freeze a transform whose determinism
        nobody declared (it could be random augmentation): under the
        IMPLICIT upgrade only TransformSpec(cacheable=True) participates;
        an explicit cache_type='decoded' keeps the default-cacheable
        behavior (the user configured the cache deliberately)."""
        from petastorm_tpu.reader import make_reader
        monkeypatch.setenv('PETASTORM_TPU_DECODED_CACHE', '1')
        monkeypatch.setenv('PETASTORM_TPU_DECODED_CACHE_DIR',
                           str(tmp_path / 'fleet'))
        registry = T.get_registry()

        def misses():
            return registry.counter_value(
                'petastorm_tpu_decoded_cache_misses_total')

        undeclared = TransformSpec(_transform_a)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         transform_spec=undeclared, num_epochs=1) as r:
            next(r)
        assert misses() == 0  # bypassed: determinism never declared
        declared = TransformSpec(_transform_a, cacheable=True)
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         transform_spec=declared, num_epochs=1) as r:
            next(r)
        assert misses() > 0  # declared deterministic: cached

    def test_env_knob_upgrades_default_readers(self, synthetic_dataset,
                                               tmp_path, monkeypatch):
        monkeypatch.setenv('PETASTORM_TPU_DECODED_CACHE', '1')
        monkeypatch.setenv('PETASTORM_TPU_DECODED_CACHE_DIR',
                           str(tmp_path / 'fleet'))
        from petastorm_tpu.reader import make_reader
        with make_reader(synthetic_dataset.url, reader_pool_type='dummy',
                         num_epochs=1) as reader:
            next(reader)
        assert T.get_registry().counter_value(
            'petastorm_tpu_decoded_cache_misses_total') > 0
        assert os.path.isdir(str(tmp_path / 'fleet'))


class TestServiceReroot:
    def test_worker_server_reroots_cache_to_host_dir(self, tmp_path,
                                                     monkeypatch):
        """A standing fleet's host-local override: the job spec arrives
        with the CLIENT's directory; with the knob set the server re-roots
        it so every job shares this host's tier."""
        from petastorm_tpu.service.worker_server import \
            _reroot_decoded_cache
        cache = MaterializedRowGroupCache(str(tmp_path / 'client'), 10 ** 8)
        host_dir = str(tmp_path / 'host')
        monkeypatch.setenv('PETASTORM_TPU_DECODED_CACHE_DIR', host_dir)
        _reroot_decoded_cache({'cache': cache})
        assert cache.path == host_dir
        assert os.path.isdir(host_dir)

    def test_worker_server_keeps_spec_dir_without_knob(self, tmp_path,
                                                       monkeypatch):
        from petastorm_tpu.service.worker_server import \
            _reroot_decoded_cache
        monkeypatch.delenv('PETASTORM_TPU_DECODED_CACHE_DIR',
                           raising=False)
        cache = MaterializedRowGroupCache(str(tmp_path / 'client'), 10 ** 8)
        _reroot_decoded_cache({'cache': cache})
        assert cache.path == str(tmp_path / 'client')


@pytest.mark.perf
def test_warm_epoch_reads_at_least_as_fast_as_cold(synthetic_dataset,
                                                   tmp_path):
    """Perf guard (loose, order-of-magnitude — see pytest.ini): with the
    decoded cache on, the warm epoch must not read slower than the cold
    epoch that paid io+decode. The 0.8 factor absorbs shared-box noise;
    a real regression (warm path re-decoding) shows up as ~cold/2."""
    from petastorm_tpu.reader import make_batch_reader

    def one_pass():
        with make_batch_reader(synthetic_dataset.url,
                               reader_pool_type='thread', workers_count=2,
                               shuffle_row_groups=False,
                               cache_type='decoded',
                               cache_location=str(tmp_path / 'dc'),
                               cache_size_limit=10 ** 9) as reader:
            seen = 0
            start = time.monotonic()
            for batch in reader:
                seen += len(batch.id)
            return seen / (time.monotonic() - start)

    cold = one_pass()
    warm = max(one_pass() for _ in range(3))
    assert warm >= 0.8 * cold, (cold, warm)


_RACE_READER = '''
import sys
import numpy as np
sys.path.insert(0, sys.argv[1])
from petastorm_tpu.arrow_worker import ColumnBatch
from petastorm_tpu.materialized_cache import MaterializedRowGroupCache

path, direction = sys.argv[2], int(sys.argv[3])
cache = MaterializedRowGroupCache(path, 30_000)  # ~6 entries: heavy churn
order = range(20) if direction else range(19, -1, -1)
for _round in range(3):
    for i in order:
        def fill(i=i):
            return ColumnBatch({'v': np.full(512, i, dtype=np.int64)}, 512)
        batch = cache.get(('race', i), fill)
        v = batch.columns['v']
        assert batch.length == 512 and (np.asarray(v) == i).all(), i
print('OK')
'''


class TestFleetTierSatellites:
    """Satellites of the fleet cache tier: stale placement-marker purge,
    the rate-limited LRU touch behind memory-tier hits, and eviction
    racing a reader in another process."""

    def test_reroot_purges_markers_with_no_entries_behind_them(
            self, tmp_path):
        from petastorm_tpu.service import placement
        host_dir = str(tmp_path / 'host')
        placement.note_fingerprint(host_dir, 'stale-fp')
        cache = _cache(tmp_path)
        cache.reroot(host_dir)
        assert not [n for n in os.listdir(host_dir)
                    if n.startswith('.fp_')]

    def test_reroot_keeps_markers_backed_by_real_entries(self, tmp_path):
        from petastorm_tpu.service import placement
        host_dir = str(tmp_path / 'host')
        warm = MaterializedRowGroupCache(host_dir, 10 ** 8)
        warm.get('k', _fill(_sample_columns()))
        placement.note_fingerprint(host_dir, 'earned-fp')
        cache = _cache(tmp_path)
        cache.reroot(host_dir)
        assert '.fp_earned-fp' in os.listdir(host_dir)

    def test_cleanup_purges_markers_from_kept_directory(self, tmp_path):
        from petastorm_tpu.service import placement
        cache = _cache(tmp_path)
        placement.note_fingerprint(cache.path, 'fp')
        cache.cleanup()  # cleanup_on_exit=False: the directory stays
        assert os.path.isdir(cache.path)
        assert not [n for n in os.listdir(cache.path)
                    if n.startswith('.fp_')]

    def test_mem_tier_hits_touch_disk_entry_rate_limited(self, tmp_path,
                                                         monkeypatch):
        """A hot in-memory loop must not pay one utime syscall per hit —
        the disk LRU only needs coarse freshness."""
        from petastorm_tpu import materialized_cache as MC
        cache = _cache(tmp_path, mem_mb=64)
        cols = _sample_columns()
        cache.get('k', _fill(cols))
        entry = cache._entry_path('k')
        touched = []
        real_utime = os.utime

        def counting_utime(path, *args, **kwargs):
            touched.append(path)
            return real_utime(path, *args, **kwargs)

        monkeypatch.setattr(os, 'utime', counting_utime)
        for _ in range(10):
            cache.get('k', _fill(cols))  # memory-tier hits
        assert touched.count(entry) == 1
        with cache._lock:  # age the record past the interval
            cache._utime_at[entry] -= MC._UTIME_INTERVAL_S + 1
        cache.get('k', _fill(cols))
        assert touched.count(entry) == 2

    def test_eviction_racing_a_reader_in_another_process(self, tmp_path):
        """Two processes hammer one shared directory with a disk limit
        far below the working set: each sees every entry either whole or
        absent (refilled), never torn — values stay exact while the
        other process evicts under its feet."""
        shared = str(tmp_path / 'shared')
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        procs = [subprocess.Popen(
            [sys.executable, '-c', _RACE_READER, REPO, shared,
             str(direction)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)
            for direction in (0, 1)]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append((p.returncode, out.decode(errors='replace')))
        for code, out in outs:
            assert code == 0, out
            assert 'OK' in out, out
