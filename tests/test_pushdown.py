"""Query-shaped reads (ISSUE 12): statistics-driven row-group pruning,
projection pushdown + late materialization, and predicate cacheability.

The load-bearing contract is EXACT PARITY: a pruned + late-materialized
epoch must deliver the identical row multiset as the
decode-everything-then-filter oracle (``PETASTORM_TPU_PUSHDOWN=0``),
across pool types and under sharding — and pruning must be conservative
everywhere (null-bearing columns, missing statistics, faulted footer
reads degrade to unpruned reads, never to a wrong answer).
"""

import os
import tempfile

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu import pushdown
from petastorm_tpu import telemetry as T
from petastorm_tpu.filters import FiltersPredicate
from petastorm_tpu.predicates import in_lambda, in_negate, in_reduce, in_set


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    T.reset_for_tests()
    yield
    T.reset_for_tests()


@pytest.fixture()
def oracle_env(monkeypatch):
    """Flip the whole selective-read fast path off (the comparison
    oracle) for the duration of a ``with``-less block via a callable."""
    def arm(value='0', knob='PETASTORM_TPU_PUSHDOWN'):
        monkeypatch.setenv(knob, value)
    return arm


def _read_ids(url, oracle=False, pool='thread', **kwargs):
    env = dict(os.environ)
    if oracle:
        os.environ['PETASTORM_TPU_PUSHDOWN'] = '0'
    try:
        with make_batch_reader(url, reader_pool_type=pool,
                               shuffle_row_groups=False, **kwargs) as reader:
            return sorted(int(i) for batch in reader for i in batch.id)
    finally:
        os.environ.clear()
        os.environ.update(env)


# ---------------------------------------------------------------------------
# The prover: interval logic per clause/op, against real footer stats
# ---------------------------------------------------------------------------


@pytest.fixture(scope='module')
def two_rowgroup_url(tmp_path_factory):
    """One file, two row-groups with disjoint known ranges:
    rg0 x∈[0,9] (no nulls), rg1 x∈[20,29] (no nulls)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    path = str(tmp_path_factory.mktemp('prover')) + '/ds'
    os.makedirs(path)
    t0 = pa.table({'x': pa.array(range(10), type=pa.int64()),
                   'id': pa.array(range(10), type=pa.int64())})
    t1 = pa.table({'x': pa.array(range(20, 30), type=pa.int64()),
                   'id': pa.array(range(20, 30), type=pa.int64())})
    writer = pq.ParquetWriter(os.path.join(path, 'part0.parquet'), t0.schema)
    writer.write_table(t0)
    writer.write_table(t1)
    writer.close()
    return 'file://' + path


class TestProver:
    @pytest.mark.parametrize('filters,expected_pruned', [
        ([('x', '=', 5)], 1),           # rg1 cannot hold 5
        ([('x', '=', 15)], 2),          # neither range holds 15
        ([('x', '<', 0)], 2),
        ([('x', '<', 1)], 1),
        ([('x', '<=', 0)], 1),
        ([('x', '>', 29)], 2),
        ([('x', '>=', 25)], 1),
        ([('x', '!=', 40)], 0),         # any value ≠ 40
        ([('x', 'in', (11, 15))], 2),
        ([('x', 'in', (5, 15))], 1),
        ([('x', 'not in', (5,))], 0),   # other values survive everywhere
        # OR of clauses: pruned only when EVERY clause proves empty
        ([[('x', '<', 0)], [('x', '>', 29)]], 2),
        ([[('x', '<', 0)], [('x', '=', 25)]], 1),
    ])
    def test_clause_interval_logic(self, two_rowgroup_url, filters,
                                   expected_pruned):
        pred = FiltersPredicate(filters)
        got = _read_ids(two_rowgroup_url, predicate=pred)
        assert got == _read_ids(two_rowgroup_url, oracle=True,
                                predicate=pred)
        assert pushdown.planner_summary()['rowgroups_pruned'] == \
            expected_pruned

    def test_in_set_and_reduce_compositions(self, two_rowgroup_url):
        # in_set prunes by range; in_reduce(all) prunes through any
        # prunable child; in_reduce(any) needs every child prunable
        assert _read_ids(two_rowgroup_url,
                         predicate=in_set([15, 16], 'x')) == []
        assert pushdown.planner_summary()['rowgroups_pruned'] == 2
        T.reset_for_tests()
        pred = in_reduce([in_lambda(['x'], lambda v: True),
                          FiltersPredicate([('x', '>', 15)])], all)
        got = _read_ids(two_rowgroup_url, predicate=pred)
        assert got == list(range(20, 30))
        assert pushdown.planner_summary()['rowgroups_pruned'] == 1
        T.reset_for_tests()
        pred = in_reduce([FiltersPredicate([('x', '=', 15)]),
                          in_set([16], 'x')], any)
        assert _read_ids(two_rowgroup_url, predicate=pred) == []
        assert pushdown.planner_summary()['rowgroups_pruned'] == 2

    def test_arbitrary_predicates_decline(self, two_rowgroup_url):
        for pred in (in_lambda(['x'], lambda v: v['x'] == 25),
                     in_negate(FiltersPredicate([('x', '<', 15)]))):
            T.reset_for_tests()
            got = _read_ids(two_rowgroup_url, predicate=pred)
            assert got == _read_ids(two_rowgroup_url, oracle=True,
                                    predicate=pred)
            summary = pushdown.planner_summary()
            assert summary['rowgroups_pruned'] == 0
            assert summary['declines'] == {'arbitrary-predicate': 1}

    def test_incomparable_types_keep(self, two_rowgroup_url):
        # str bound against int statistics: TypeError is conservative
        pred = FiltersPredicate([('x', 'in', ('zz',))])
        assert _read_ids(two_rowgroup_url, predicate=pred) == []
        assert pushdown.planner_summary()['rowgroups_pruned'] == 0

    def test_counters_and_report_section(self, two_rowgroup_url):
        pred = FiltersPredicate([('x', '<', 5)])
        got = _read_ids(two_rowgroup_url, predicate=pred)
        assert got == list(range(5))
        registry = T.get_registry()
        assert registry.counter_value(pushdown.ROWGROUPS_PRUNED) == 1
        assert registry.counter_value(pushdown.ROWS_PRUNED) == 10
        report = T.pipeline_report()
        section = report['pushdown']
        assert section['rowgroups_pruned'] == 1
        assert section['rows_pruned'] == 10
        assert section['prune_share'] == 0.5
        assert 'pushdown:' in T.format_pipeline_report(report)

    def test_no_section_without_predicates(self, two_rowgroup_url):
        _read_ids(two_rowgroup_url)
        assert 'pushdown' not in T.pipeline_report()

    def test_footer_memoization(self, two_rowgroup_url, monkeypatch):
        pred = FiltersPredicate([('x', '<', 5)])
        calls = []
        real = pushdown.StatsIndex._read_footer

        def counting(self, path):
            calls.append(path)
            return real(self, path)

        monkeypatch.setattr(pushdown.StatsIndex, '_read_footer', counting)
        _read_ids(two_rowgroup_url, predicate=pred)
        assert len(calls) == 1
        # the second reader's plan must hit the process-wide memo
        _read_ids(two_rowgroup_url, predicate=pred)
        assert len(calls) == 1


# ---------------------------------------------------------------------------
# Null safety
# ---------------------------------------------------------------------------


@pytest.fixture(scope='module')
def null_bearing_url(tmp_path_factory):
    """rg0: string x in ['a','c'] WITH a null; rg1: ['m','p'], no nulls.
    String column: nulls survive decode as None (a numeric column's
    nulls become NaN and can never match), so in_set(None) genuinely
    matches rows here."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    path = str(tmp_path_factory.mktemp('nulls')) + '/ds'
    os.makedirs(path)
    t0 = pa.table({'x': pa.array(['a', None, 'c']),
                   'id': pa.array([0, 1, 2], type=pa.int64())})
    t1 = pa.table({'x': pa.array(['m', 'n', 'p']),
                   'id': pa.array([3, 4, 5], type=pa.int64())})
    writer = pq.ParquetWriter(os.path.join(path, 'part0.parquet'), t0.schema)
    writer.write_table(t0)
    writer.write_table(t1)
    writer.close()
    return 'file://' + path


class TestNullSafety:
    def test_in_set_none_not_wrongly_pruned(self, null_bearing_url):
        # REGRESSION (ISSUE 12 satellite): naive min/max logic prunes
        # BOTH row-groups ('zz' is outside both ranges) and silently
        # loses the null row that in_set(None) matches. The null-safe
        # prover must keep rg0 (null_count > 0) and prune only rg1.
        pred = in_set([None, 'zz'], 'x')
        got = _read_ids(null_bearing_url, predicate=pred)
        assert got == [1]
        assert got == _read_ids(null_bearing_url, oracle=True,
                                predicate=pred)
        assert pushdown.planner_summary()['rowgroups_pruned'] == 1

    def test_negative_ops_keep_null_bearing_numeric_groups(
            self, tmp_path):
        # REGRESSION (review finding): numeric nulls decode to NaN, and
        # NaN DOES match '!='/'not in' at worker evaluation — so a
        # [5, null, 5] row-group must NOT be pruned against '!= 5' even
        # though its non-null min==max==5 (pre-fix, the pruned read lost
        # the NaN row the oracle delivers).
        import pyarrow as pa
        import pyarrow.parquet as pq
        path = str(tmp_path / 'numnulls')
        os.makedirs(path)
        t0 = pa.table({'x': pa.array([5, None, 5], type=pa.int64()),
                       'id': pa.array([0, 1, 2], type=pa.int64())})
        t1 = pa.table({'x': pa.array([7, 8, 9], type=pa.int64()),
                       'id': pa.array([3, 4, 5], type=pa.int64())})
        writer = pq.ParquetWriter(os.path.join(path, 'p0.parquet'),
                                  t0.schema)
        writer.write_table(t0)
        writer.write_table(t1)
        writer.close()
        url = 'file://' + path
        for filters in ([('x', '!=', 5)], [('x', 'not in', (5,))]):
            T.reset_for_tests()
            pred = FiltersPredicate(filters)
            got = _read_ids(url, predicate=pred)
            assert got == _read_ids(url, oracle=True, predicate=pred), \
                filters
            assert got == [1, 3, 4, 5], (filters, got)
            # the null-bearing group was kept; the null-free one with
            # lo==hi==5 is still prunable against these ops
            assert pushdown.planner_summary()['rowgroups_pruned'] == 0
        # and WITHOUT nulls the negative ops do prune a lo==hi==value
        # group (the null guard must not blanket-disable them)
        T.reset_for_tests()
        path2 = str(tmp_path / 'nonulls')
        os.makedirs(path2)
        t0 = pa.table({'x': pa.array([5, 5, 5], type=pa.int64()),
                       'id': pa.array([0, 1, 2], type=pa.int64())})
        writer = pq.ParquetWriter(os.path.join(path2, 'p0.parquet'),
                                  t0.schema)
        writer.write_table(t0)
        writer.write_table(t1)
        writer.close()
        pred = FiltersPredicate([('x', '!=', 5)])
        got = _read_ids('file://' + path2, predicate=pred)
        assert got == [3, 4, 5]
        assert pushdown.planner_summary()['rowgroups_pruned'] == 1

    def test_negative_ops_keep_stored_nan_float_groups(self, tmp_path):
        # REGRESSION (review finding): a STORED float NaN is excluded
        # from pyarrow's min/max statistics WITHOUT counting as a null
        # (null_count stays 0), yet NaN != 5.0 is True at worker eval —
        # so float statistics can never prove a '!='/'not in' term
        # empty, even for a "null-free" lo==hi group.
        import pyarrow as pa
        import pyarrow.parquet as pq
        path = str(tmp_path / 'storednan')
        os.makedirs(path)
        t0 = pa.table({'x': pa.array([5.0, float('nan'), 5.0]),
                       'id': pa.array([0, 1, 2], type=pa.int64())})
        pq.write_table(t0, os.path.join(path, 'p0.parquet'))
        url = 'file://' + path
        for filters in ([('x', '!=', 5.0)], [('x', 'not in', (5.0,))]):
            T.reset_for_tests()
            pred = FiltersPredicate(filters)
            got = _read_ids(url, predicate=pred)
            assert got == _read_ids(url, oracle=True, predicate=pred), \
                filters
            assert got == [1], (filters, got)
            assert pushdown.planner_summary()['rowgroups_pruned'] == 0

    def test_dnf_terms_prune_through_nulls(self, null_bearing_url):
        # DNF filters: nulls never match ANY term, so min/max of the
        # non-null values alone decide — the null-bearing rg0 IS
        # prunable against a clause its range excludes
        pred = FiltersPredicate([('x', '>', 'f')])
        got = _read_ids(null_bearing_url, predicate=pred)
        assert got == [3, 4, 5]
        assert got == _read_ids(null_bearing_url, oracle=True,
                                predicate=pred)
        assert pushdown.planner_summary()['rowgroups_pruned'] == 1


# ---------------------------------------------------------------------------
# Exact parity: pruned + late-materialized vs the full-scan oracle
# ---------------------------------------------------------------------------


def _read_rows(url, oracle=False, **kwargs):
    env = dict(os.environ)
    if oracle:
        os.environ['PETASTORM_TPU_PUSHDOWN'] = '0'
    try:
        with make_reader(url, shuffle_row_groups=False, **kwargs) as reader:
            return sorted(
                (row.id, row.image_png.tobytes(), row.matrix.tobytes())
                for row in reader)
    finally:
        os.environ.clear()
        os.environ.update(env)


class TestExactParity:
    @pytest.mark.parametrize('pool', ['thread', 'dummy', 'process',
                                      'service'])
    def test_row_multiset_parity_across_pools(self, synthetic_dataset,
                                              pool):
        pred = FiltersPredicate([[('id', '<', 12)], [('id', '>=', 95)]])
        got = _read_ids(synthetic_dataset.url, pool=pool, predicate=pred,
                        workers_count=2)
        oracle = _read_ids(synthetic_dataset.url, oracle=True, pool=pool,
                           predicate=pred, workers_count=2)
        assert got == oracle == list(range(12)) + list(range(95, 100))
        assert T.get_registry().counter_value(pushdown.ROWGROUPS_PRUNED) > 0

    def test_heavy_column_value_parity(self, synthetic_dataset):
        # pixels and ndarrays decoded late must be byte-identical to the
        # oracle's decode-everything output
        pred = FiltersPredicate([('id', 'in', (3, 31, 47, 99))])
        got = _read_rows(synthetic_dataset.url, predicate=pred)
        oracle = _read_rows(synthetic_dataset.url, oracle=True,
                            predicate=pred)
        assert [g[0] for g in got] == [3, 31, 47, 99]
        assert got == oracle
        registry = T.get_registry()
        assert registry.counter_value(
            'petastorm_tpu_late_materialized_rows_total') == 4
        assert registry.counter_value('petastorm_tpu_stage_calls_total',
                                      stage='late_materialize') > 0

    def test_sharding_parity(self, synthetic_dataset):
        pred = FiltersPredicate([('id', '<', 30)])
        per_shard = []
        for cur in (0, 1):
            got = _read_ids(synthetic_dataset.url, predicate=pred,
                            cur_shard=cur, shard_count=2)
            oracle = _read_ids(synthetic_dataset.url, oracle=True,
                               predicate=pred, cur_shard=cur, shard_count=2)
            # pruning runs AFTER sharding, so each shard's row set is
            # bit-identical to its unpruned self — not just the union
            assert got == oracle
            per_shard.append(got)
        assert sorted(per_shard[0] + per_shard[1]) == list(range(30))

    def test_prune_only_knob_keeps_late_materialization(
            self, synthetic_dataset, monkeypatch):
        monkeypatch.setenv('PETASTORM_TPU_PUSHDOWN_PRUNE', '0')
        pred = FiltersPredicate([('id', 'in', (3, 47))])
        with make_batch_reader(synthetic_dataset.url, shuffle_row_groups=False,
                               predicate=pred) as reader:
            got = sorted(int(i) for b in reader for i in b.id)
        assert got == [3, 47]
        registry = T.get_registry()
        assert registry.counter_value(pushdown.ROWGROUPS_PRUNED) == 0
        assert registry.counter_value(
            'petastorm_tpu_late_materialized_rows_total') == 2

    def test_row_drop_partition_parity(self, synthetic_dataset):
        # shuffle_row_drop_partitions under a predicate: each row-group
        # becomes k items; the late path decides survivors + drop BEFORE
        # the heavy read (an empty partition reads nothing), and the
        # delivered multiset must still match the oracle exactly
        pred = FiltersPredicate([('id', 'in', (3, 31, 47))])
        kwargs = dict(predicate=pred, shuffle_row_drop_partitions=3)
        got = _read_ids(synthetic_dataset.url, **kwargs)
        assert got == _read_ids(synthetic_dataset.url, oracle=True,
                                **kwargs)
        assert got == [3, 31, 47]

    def test_fully_pruned_reader_delivers_empty(self, synthetic_dataset):
        pred = FiltersPredicate([('id', '>', 10 ** 6)])
        for epochs in (1, None):
            with make_batch_reader(synthetic_dataset.url, num_epochs=epochs,
                                   shuffle_row_groups=False,
                                   predicate=pred) as reader:
                assert list(reader) == []

    def test_multi_epoch_parity(self, synthetic_dataset):
        pred = FiltersPredicate([('id', '<', 7)])
        with make_batch_reader(synthetic_dataset.url, num_epochs=3,
                               shuffle_row_groups=False,
                               predicate=pred) as reader:
            got = sorted(int(i) for b in reader for i in b.id)
        assert got == sorted(list(range(7)) * 3)


class TestCheckpointAccounting:
    def test_completed_epoch_reads_complete(self, synthetic_dataset):
        # pruned items are completed-with-zero-rows: a fully consumed
        # epoch's state must say so (without this, resume would rewind
        # to re-read row-groups PROVEN empty, forever)
        pred = FiltersPredicate([('id', '<', 25)])
        with make_batch_reader(synthetic_dataset.url, num_epochs=1,
                               shuffle_row_groups=False,
                               predicate=pred) as reader:
            assert reader._pruned_items
            got = sorted(int(i) for b in reader for i in b.id)
            state = reader.state_dict()
        assert got == list(range(25))
        assert state['epoch'] == 1 and state['consumed_items'] == []

    @pytest.mark.parametrize('save_oracle,restore_oracle',
                             [(False, True), (True, False)])
    def test_resume_across_pushdown_knob_flip(self, synthetic_dataset,
                                              monkeypatch, save_oracle,
                                              restore_oracle):
        # REGRESSION (review finding): the filters= path prunes
        # PRE-shard, so flipping PETASTORM_TPU_PUSHDOWN across a resume
        # changes the item-index space — raw consumed indices would name
        # DIFFERENT row-groups. _localize_state translates through the
        # saved per-index global identities instead; no silent row loss
        # in either flip direction.
        # an OR filter keeping a NON-contiguous piece set: the pruned
        # space's index->piece mapping then genuinely disagrees with the
        # unpruned one (a prefix-keeping filter would map identically
        # and hide the bug)
        filters = [[('id', '<', 10)], [('id', '>=', 30)]]
        expected = set(range(10)) | set(range(30, 100))

        def build(oracle):
            if oracle:
                monkeypatch.setenv('PETASTORM_TPU_PUSHDOWN', '0')
            else:
                monkeypatch.delenv('PETASTORM_TPU_PUSHDOWN', raising=False)
            return make_batch_reader(synthetic_dataset.url, num_epochs=1,
                                     shuffle_row_groups=False,
                                     filters=filters)
        with build(save_oracle) as reader:
            it = iter(reader)
            seen = set(int(i) for i in next(it).id)
            seen |= set(int(i) for i in next(it).id)
            state = reader.state_dict()
        with build(restore_oracle) as reader:
            reader.load_state_dict(state)
            rest = set(int(i) for b in reader for i in b.id)
        assert seen | rest == expected, sorted(expected - (seen | rest))

    def test_mid_epoch_resume_loses_no_rows(self, synthetic_dataset):
        pred = FiltersPredicate([('id', '<', 25)])
        with make_batch_reader(synthetic_dataset.url, num_epochs=1,
                               shuffle_row_groups=False,
                               predicate=pred) as reader:
            first = next(iter(reader))
            state = reader.state_dict()
        seen = set(int(i) for i in first.id)
        with make_batch_reader(synthetic_dataset.url, num_epochs=1,
                               shuffle_row_groups=False,
                               predicate=pred) as reader:
            reader.load_state_dict(state)
            rest = set(int(i) for b in reader for i in b.id)
        assert seen | rest == set(range(25))


# ---------------------------------------------------------------------------
# Degradation: footer faults prune nothing, never lose rows
# ---------------------------------------------------------------------------


class TestFooterFaultDegrade:
    def test_faulted_footer_degrades_to_unpruned(self, synthetic_dataset,
                                                 monkeypatch):
        from petastorm_tpu import faults
        monkeypatch.setenv('PETASTORM_TPU_FAULTS',
                           'io.read:error:1:match=#footer')
        faults.refresh_faults()
        try:
            assert faults.ARMED is not None
            pred = FiltersPredicate([('id', '<', 10)])
            got = _read_ids(synthetic_dataset.url, predicate=pred)
        finally:
            monkeypatch.delenv('PETASTORM_TPU_FAULTS')
            faults.refresh_faults()
        # the answer is RIGHT (degrade, not corrupt) and nothing pruned
        assert got == list(range(10))
        summary = pushdown.planner_summary()
        assert summary['rowgroups_pruned'] == 0
        assert summary['declines'].get('no-statistics', 0) > 0

    def test_statless_dataset_declines(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq
        path = str(tmp_path / 'nostats')
        os.makedirs(path)
        table = pa.table({'id': pa.array(range(20), type=pa.int64())})
        pq.write_table(table, os.path.join(path, 'p0.parquet'),
                       write_statistics=False)
        pred = FiltersPredicate([('id', '<', 5)])
        got = _read_ids('file://' + path, predicate=pred)
        assert got == list(range(5))
        summary = pushdown.planner_summary()
        assert summary['rowgroups_pruned'] == 0
        assert summary['declines'].get('no-statistics', 0) > 0


# ---------------------------------------------------------------------------
# Cacheability satellite: FiltersPredicate readers cache; arbitrary
# predicates stay uncached — counted, not invisible
# ---------------------------------------------------------------------------


class TestPredicateCache:
    def _arm(self, monkeypatch, tmp_path):
        monkeypatch.setenv('PETASTORM_TPU_DECODED_CACHE', '1')
        monkeypatch.setenv('PETASTORM_TPU_DECODED_CACHE_DIR',
                           str(tmp_path / 'decoded'))

    def test_filters_predicate_caches_under_knob(self, synthetic_dataset,
                                                 monkeypatch, tmp_path):
        from petastorm_tpu.materialized_cache import (
            DECODED_CACHE_HITS, DECODED_CACHE_MISSES,
        )
        self._arm(monkeypatch, tmp_path)
        pred = FiltersPredicate([('id', '<', 25)])
        first = _read_ids(synthetic_dataset.url, predicate=pred)
        registry = T.get_registry()
        assert first == list(range(25))
        assert registry.counter_value(DECODED_CACHE_MISSES) > 0
        assert _read_ids(synthetic_dataset.url, predicate=pred) == first
        assert registry.counter_value(DECODED_CACHE_HITS) > 0

    def test_distinct_filters_do_not_collide(self, synthetic_dataset,
                                             monkeypatch, tmp_path):
        self._arm(monkeypatch, tmp_path)
        a = _read_ids(synthetic_dataset.url,
                      predicate=FiltersPredicate([('id', '<', 10)]))
        b = _read_ids(synthetic_dataset.url,
                      predicate=FiltersPredicate([('id', '<', 5)]))
        assert a == list(range(10)) and b == list(range(5))

    def test_arbitrary_predicate_skip_is_counted(self, synthetic_dataset,
                                                 monkeypatch, tmp_path):
        from petastorm_tpu.materialized_cache import DECODED_CACHE_SKIPPED
        self._arm(monkeypatch, tmp_path)
        got = _read_ids(synthetic_dataset.url,
                        predicate=in_lambda(['id'],
                                            lambda v: v['id'] < 5))
        assert got == list(range(5))
        registry = T.get_registry()
        assert registry.counter_value(DECODED_CACHE_SKIPPED,
                                      reason='predicate') == 1

    def test_composed_predicate_downgrades_counted(self, synthetic_dataset,
                                                   monkeypatch, tmp_path):
        # filters= AND predicate= compose to in_reduce: no stable cache
        # identity — under the implicit knob the reader degrades to
        # uncached (counted), it must NOT raise
        from petastorm_tpu.materialized_cache import DECODED_CACHE_SKIPPED
        self._arm(monkeypatch, tmp_path)
        got = _read_ids(synthetic_dataset.url, filters=[('id', '<', 50)],
                        predicate=in_lambda(['id'],
                                            lambda v: v['id'] % 2 == 0))
        assert got == [i for i in range(50) if i % 2 == 0]
        assert T.get_registry().counter_value(DECODED_CACHE_SKIPPED,
                                              reason='predicate') == 1

    def test_explicit_cache_with_filters_predicate_allowed(
            self, synthetic_dataset, tmp_path):
        from petastorm_tpu.materialized_cache import DECODED_CACHE_HITS
        pred = FiltersPredicate([('id', '<', 10)])
        kwargs = dict(cache_type='decoded',
                      cache_location=str(tmp_path / 'explicit'),
                      predicate=pred)
        first = _read_ids(synthetic_dataset.url, **kwargs)
        assert first == list(range(10))
        assert _read_ids(synthetic_dataset.url, **kwargs) == first
        assert T.get_registry().counter_value(DECODED_CACHE_HITS) > 0

    def test_explicit_cache_with_arbitrary_predicate_raises(
            self, synthetic_dataset, tmp_path):
        with pytest.raises(RuntimeError, match='cache'):
            make_batch_reader(synthetic_dataset.url, cache_type='decoded',
                              cache_location=str(tmp_path / 'x'),
                              predicate=in_lambda(['id'],
                                                  lambda v: True))


# ---------------------------------------------------------------------------
# Late materialization internals
# ---------------------------------------------------------------------------


class TestLateMaterialization:
    def test_predicate_columns_not_decoded_twice(self, synthetic_dataset):
        # projection reuse: with id both predicate and output, the heavy
        # read must exclude it (io spans still happen for heavy cols;
        # the reused column arrives by slicing, not re-decode)
        pred = FiltersPredicate([('id', '<', 12)])
        with make_batch_reader(synthetic_dataset.url, shuffle_row_groups=False,
                               predicate=pred,
                               schema_fields=['^id$']) as reader:
            got = sorted(int(i) for b in reader for i in b.id)
        assert got == list(range(12))
        registry = T.get_registry()
        # id-only projection: nothing heavy left, so the late stage (and
        # its counter) must NOT fire at all
        assert registry.counter_value(
            'petastorm_tpu_late_materialized_rows_total') == 0
        assert registry.counter_value('petastorm_tpu_stage_calls_total',
                                      stage='late_materialize') == 0

    def test_deferred_encoded_column_ships_survivors_only(
            self, synthetic_dataset):
        from petastorm_tpu.fused import EncodedImageColumn
        pred = FiltersPredicate([('id', 'in', (3, 7, 47))])
        with make_batch_reader(synthetic_dataset.url, defer_image_decode=True,
                               shuffle_row_groups=False,
                               predicate=pred) as reader:
            batches = []
            while True:
                try:
                    columns, _, _ = reader.next_batch_info()
                except StopIteration:
                    break
                batches.append(columns)
        encoded = [c['image_png'] for c in batches]
        assert all(isinstance(e, EncodedImageColumn) for e in encoded)
        assert sorted(len(e) for e in encoded) == [1, 2]
        # decoded survivors equal the oracle's pixels
        oracle = {row[0]: row[1] for row in _read_rows(
            synthetic_dataset.url, oracle=True, predicate=pred)}
        for columns in batches:
            pixels = columns['image_png'].materialize()
            for k, rid in enumerate(int(i) for i in columns['id']):
                assert pixels[k].tobytes() == oracle[rid]


# ---------------------------------------------------------------------------
# Ventilator always_exclude unit coverage
# ---------------------------------------------------------------------------


class TestVentilatorAlwaysExclude:
    def _run(self, items, **kwargs):
        from petastorm_tpu.workers.ventilator import ConcurrentVentilator
        out = []
        vent = ConcurrentVentilator(lambda **item: out.append(item['i']),
                                    items, **kwargs)
        vent.start()
        while not vent.completed():
            vent.processed_item()
        vent.stop()
        return out, vent

    def test_excluded_every_epoch(self):
        items = [{'i': n} for n in range(4)]
        out, _ = self._run(items, iterations=2, always_exclude={1, 3})
        assert out == [0, 2, 0, 2]

    def test_all_excluded_completes_immediately(self):
        items = [{'i': n} for n in range(3)]
        for iterations in (1, None):
            out, vent = self._run(items, iterations=iterations,
                                  always_exclude={0, 1, 2})
            assert out == [] and vent.completed()

    def test_composes_with_exclude_once(self):
        from petastorm_tpu.workers.ventilator import ConcurrentVentilator
        out = []
        vent = ConcurrentVentilator(lambda **item: out.append(item['i']),
                                    [{'i': n} for n in range(4)],
                                    iterations=2, always_exclude={3})
        vent.exclude_from_next_epoch({0})
        vent.start()
        while not vent.completed():
            vent.processed_item()
        vent.stop()
        # epoch 0 drops 0 (once) and 3 (always); epoch 1 only 3
        assert out == [1, 2, 0, 1, 2]
