"""Pod-aware shard defaults (``petastorm_tpu/parallel/sharding.py``).

The reader-level modulo assignment is covered in test_end_to_end; this
covers the default-resolution rules and the live-backend gate."""

import pytest

from petastorm_tpu.parallel import sharding
from petastorm_tpu.parallel.sharding import default_shard_info


def test_explicit_values_validated():
    assert default_shard_info(2, 4) == (2, 4)
    with pytest.raises(ValueError, match='together'):
        default_shard_info(1, None)
    with pytest.raises(ValueError, match='together'):
        default_shard_info(None, 4)
    with pytest.raises(ValueError, match='must be in'):
        default_shard_info(4, 4)
    with pytest.raises(ValueError, match='must be in'):
        default_shard_info(-1, 4)


def test_single_process_backend_gives_no_sharding():
    # conftest initialized the (single-process) CPU backend: process_count
    # is 1, so reads stay unsharded
    assert default_shard_info(None, None) == (None, None)


def test_multi_process_runtime_defaults_shard(monkeypatch):
    monkeypatch.setattr(sharding, '_jax_process_info', lambda: (3, 8))
    assert default_shard_info(None, None) == (3, 8)
    # explicit values always win over the runtime defaults
    assert default_shard_info(0, 2) == (0, 2)


def test_uninitialized_backend_never_initializes(monkeypatch):
    # the gate must consult the live-backend check, not force one up
    calls = []

    class _Bridge:
        @staticmethod
        def backends_are_initialized():
            calls.append(1)
            return False

    import jax._src.xla_bridge as xb
    monkeypatch.setattr(xb, 'backends_are_initialized',
                        _Bridge.backends_are_initialized)
    assert sharding._jax_process_info() == (None, None)
    assert calls  # the gate was actually consulted
