"""Shard-aware staging engine + autotuner (ISSUE 14).

Exact-parity suite on the forced-8-device CPU mesh (conftest sets
``xla_force_host_platform_device_count=8``): staged-vs-legacy batch
equality on a 4x2 mesh across last-batch policies, multi-epoch replays
and a mid-stream checkpoint resume; the one-dispatch-per-pytree
contract; the structural zero-per-batch-host-allocation guard on the
sharded ring; the sharded row plan's soundness properties; the legacy
path's telemetry (spans + shard-slice bytes); and the staging
autotuner's policy, bounds, decision records and knob discipline.
"""

import contextlib
import os
import tracemalloc

import numpy as np
import pytest

import jax

from petastorm_tpu import codecs
from petastorm_tpu import telemetry as T
from petastorm_tpu.benchmark.dummy_reader import DummyBatchReader
from petastorm_tpu.jax import autotune, staging
from petastorm_tpu.jax.loader import make_jax_loader
from petastorm_tpu.parallel.mesh import DATA_AXIS, make_mesh
from petastorm_tpu.parallel.sharding import local_shard_plan
from petastorm_tpu.telemetry.registry import metric_key
from petastorm_tpu.telemetry.spans import STAGE_SECONDS

N_SHARDS = 4


@pytest.fixture(scope='module')
def mesh():
    """4x2 (data x model) mesh over the virtual 8-CPU-device platform —
    the acceptance gate's shape."""
    return make_mesh(data=N_SHARDS, model=2)


@pytest.fixture(autouse=True)
def _fresh(request):
    staging.refresh_staging()
    autotune.refresh_autotune()
    yield
    codecs.set_image_decoder_threads_override(None)
    autotune._reset_for_tests()   # decision ring + override owner slot
    staging.refresh_staging()
    autotune.refresh_autotune()


@contextlib.contextmanager
def _env(**env):
    saved = {k: os.environ.get(k) for k in env}
    for k, v in env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    T.refresh()
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        T.refresh()


def _read_all(url, mesh, batch_size, last_batch='drop', num_epochs=1,
              **kw):
    """Materialized batches (numpy) of a full mesh read."""
    out = []
    with make_jax_loader(url, batch_size=batch_size, mesh=mesh,
                         data_axes=(DATA_AXIS,), last_batch=last_batch,
                         num_epochs=num_epochs, fields=['^id$', '^float64$'],
                         shuffle_row_groups=False, **kw) as loader:
        for batch in loader:
            for arr in batch.values():
                assert isinstance(arr, jax.Array)
            out.append({k: np.asarray(v) for k, v in batch.items()})
    return out


def _assert_batches_equal(staged, legacy):
    assert len(staged) == len(legacy)
    for sb, lb in zip(staged, legacy):
        assert sorted(sb) == sorted(lb)
        for name in sb:
            np.testing.assert_array_equal(sb[name], lb[name])


# -- the sharded row plan -----------------------------------------------------


def test_local_shard_plan_covers_local_rows(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    sharding = NamedSharding(mesh, PartitionSpec((DATA_AXIS,)))
    plan = local_shard_plan(sharding, 16)
    assert plan is not None
    # every addressable device appears (model-axis replicas included)
    assert len(plan) == 8
    # spans are unit-step, in-bounds, and union-cover [0, 16) exactly
    covered = set()
    for device, lo, hi in plan:
        assert 0 <= lo < hi <= 16
        covered.update(range(lo, hi))
    assert covered == set(range(16))
    # the 4 data shards each own a 4-row block, twice (model replicas)
    blocks = sorted((lo, hi) for _, lo, hi in plan)
    assert blocks == [(i * 4, i * 4 + 4) for i in range(4)
                      for _ in range(2)]


def test_local_shard_plan_declines_uneven_rows(mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    sharding = NamedSharding(mesh, PartitionSpec((DATA_AXIS,)))
    # 10 rows over 4 shards: jax either refuses the indices map or hands
    # back uneven spans the checker must reject — either way the caller
    # must get the always-correct fallback, never a wrong plan
    plan = local_shard_plan(sharding, 10)
    if plan is not None:
        covered = set()
        for _, lo, hi in plan:
            covered.update(range(lo, hi))
        assert covered == set(range(10))


# -- exact parity: staged vs PETASTORM_TPU_STAGING=0 --------------------------


def test_sharded_parity_drop_multi_epoch(scalar_dataset, mesh):
    staged = _read_all(scalar_dataset.url, mesh, 8, num_epochs=2)
    with _env(PETASTORM_TPU_STAGING='0',
              PETASTORM_TPU_STAGING_AUTOTUNE='0'):
        legacy = _read_all(scalar_dataset.url, mesh, 8, num_epochs=2)
    # num_epochs=2 streams 200 rows through ONE staging pass: 25 full
    # batches, nothing dropped
    assert len(staged) == 200 // 8
    _assert_batches_equal(staged, legacy)


def test_sharded_parity_pad_tail_mask(scalar_dataset, mesh):
    # 100 rows, batch 24: tail of 4 zero-pads with a valid_mask
    staged = _read_all(scalar_dataset.url, mesh, 24, last_batch='pad')
    with _env(PETASTORM_TPU_STAGING='0',
              PETASTORM_TPU_STAGING_AUTOTUNE='0'):
        legacy = _read_all(scalar_dataset.url, mesh, 24, last_batch='pad')
    assert all('valid_mask' in b for b in staged)
    tail = staged[-1]
    assert tail['valid_mask'].sum() == 100 % 24
    assert tail['valid_mask'].dtype == bool
    _assert_batches_equal(staged, legacy)


def test_sharded_parity_short_tail(scalar_dataset, mesh):
    # the 4-row short tail still divides over the 4 data shards
    staged = _read_all(scalar_dataset.url, mesh, 24, last_batch='short')
    with _env(PETASTORM_TPU_STAGING='0',
              PETASTORM_TPU_STAGING_AUTOTUNE='0'):
        legacy = _read_all(scalar_dataset.url, mesh, 24,
                           last_batch='short')
    assert staged[-1]['id'].shape[0] == 100 % 24
    _assert_batches_equal(staged, legacy)


def test_sharded_checkpoint_resume_midstream(scalar_dataset, mesh):
    """Mid-stream state_dict on the mesh: a fresh loader restoring it
    delivers every not-yet-delivered row (at-least-once), and the union
    covers the dataset exactly."""
    # 'pad': the resumed stream's row count is not batch-aligned (the
    # checkpoint lands mid-row-group), and a padded tail still divides
    # over the 4 data shards where a 'short' one could not — valid rows
    # are filtered by the mask, so padding never fakes an id
    kw = dict(batch_size=4, mesh=mesh, data_axes=(DATA_AXIS,),
              num_epochs=1, last_batch='pad', fields=['^id$'],
              shuffle_row_groups=False)

    def _valid_ids(batch):
        ids = np.asarray(batch['id'])
        return ids[np.asarray(batch['valid_mask'])].tolist()

    before = set()
    with make_jax_loader(scalar_dataset.url, **kw) as loader:
        it = iter(loader)
        for _ in range(4):
            before.update(_valid_ids(next(it)))
        state = loader.state_dict()
    after = set()
    with make_jax_loader(scalar_dataset.url, **kw) as loader:
        loader.load_state_dict(state)
        for batch in loader:
            after.update(_valid_ids(batch))
    all_ids = set(range(100))
    assert before | after == all_ids
    # the checkpoint was mid-stream: the resume must not replay
    # everything (delivered row-groups stay consumed)
    assert len(after) < 100


def test_sharded_fused_decode_parity(synthetic_dataset, mesh):
    """Deferred image cells decode straight into the shard-slice staging
    buffers (``decode_fused``) and the sharded dispatch ships the result
    — values exactly equal to the fully-materialized legacy path."""
    kw = dict(batch_size=8, mesh=mesh, data_axes=(DATA_AXIS,),
              num_epochs=1, fields=['^id$', '^image_png$'],
              shuffle_row_groups=False)
    with make_jax_loader(synthetic_dataset.url, **kw) as loader:
        staged = [{k: np.asarray(v) for k, v in b.items()}
                  for b in loader]
        fused_rows = loader.diagnostics['fused_decode_rows']
        mode = loader.diagnostics['fused_decode_mode']
    with _env(PETASTORM_TPU_STAGING='0',
              PETASTORM_TPU_STAGING_AUTOTUNE='0'):
        with make_jax_loader(synthetic_dataset.url, **kw) as loader:
            legacy = [{k: np.asarray(v) for k, v in b.items()}
                      for b in loader]
    _assert_batches_equal(staged, legacy)
    # the fused pass really ran (CPU mesh: host-backed fresh assembly)
    assert fused_rows > 0
    assert mode == 'fused-into-slab'


# -- one dispatch covering the whole pytree -----------------------------------


def test_sharded_stage_is_one_device_put_per_batch(mesh, monkeypatch):
    """The staged sharded path ships ALL fields' shard slices in ONE
    batched ``jax.device_put`` call per batch — never one runtime round
    trip per field."""
    calls = []
    real_put = jax.device_put

    def counting_put(x, device=None, **kw):
        calls.append(x)
        return real_put(x, device, **kw)

    monkeypatch.setattr(jax, 'device_put', counting_put)

    def factory(url, **kw):
        return DummyBatchReader(
            fields={'a': ((8,), np.float32), 'b': ((4,), np.int64),
                    'c': ((), np.int32)},
            batch_size=16, num_batches=4)

    with make_jax_loader('dummy://', batch_size=16, mesh=mesh,
                         data_axes=(DATA_AXIS,),
                         reader_factory=factory) as loader:
        batches = list(loader)
    assert len(batches) == 4
    assert len(calls) == 4  # one dispatch per batch, not per field
    # each dispatch carried every field x every addressable device
    assert all(isinstance(c, list) and len(c) == 3 * 8 for c in calls)


def test_sharded_fallback_when_plan_unavailable(mesh, monkeypatch):
    """A sharding the row plan cannot prove sound falls back to the
    per-field ``make_array_from_process_local_data`` build — correct
    batches either way."""
    import petastorm_tpu.parallel.sharding as parallel_sharding
    monkeypatch.setattr(parallel_sharding, 'local_shard_plan',
                        lambda *a, **kw: None)

    def factory(url, **kw):
        return DummyBatchReader(fields={'x': ((8,), np.float32)},
                                batch_size=16, num_batches=3)

    with make_jax_loader('dummy://', batch_size=16, mesh=mesh,
                         data_axes=(DATA_AXIS,),
                         reader_factory=factory) as loader:
        batches = list(loader)
        assert loader._shard_plans == {16: None}
    assert len(batches) == 3
    for batch in batches:
        assert batch['x'].shape == (16, 8)


# -- zero per-batch host allocations on the sharded ring ----------------------


class _ShardLeaf:
    """Per-device shard stand-in that copies on construction (what a
    real transfer does) and claims a non-host platform, pinning ring
    mode on the CPU test host."""

    def __init__(self, arr):
        self.value = np.array(arr, copy=True)

    def devices(self):
        class _Dev:
            platform = 'tpu'
        return (_Dev(),)

    def block_until_ready(self):
        return self


def _sharded_accelerator_put(n_shards):
    """Mimic the sharded dispatch shape: slice each field's local rows
    into per-shard blocks, 'transfer' each (copy), return one leaf per
    field holding its shards."""
    def put(tree):
        out = {}
        for name, arr in tree.items():
            rows = len(arr)
            step = max(1, rows // n_shards)
            shards = [_ShardLeaf(arr[lo:lo + step])
                      for lo in range(0, rows, step)]

            class _Global:
                def __init__(self, shards):
                    self._shards = shards
                    self.value = np.concatenate(
                        [s.value for s in shards])

                def devices(self):
                    class _Dev:
                        platform = 'tpu'
                    return (_Dev(),)

                def block_until_ready(self):
                    return self

            out[name] = _Global(shards)
        return out
    return put


def test_sharded_ring_zero_per_batch_host_allocations():
    """The structural guard on the sharded ring: after warmup, staging N
    more shard-sliced batches allocates no new host buffers — slot slabs
    sized to the LOCAL shard slice are recycled, and tracemalloc growth
    attributed to staging.py stays far below one batch's bytes."""
    bs = 64
    eng = staging.StagingEngine(bs, {'b': np.float32}, 'pad',
                                _sharded_accelerator_put(N_SHARDS),
                                num_slots=2)
    rng = np.random.RandomState(0)
    cols = {'a': rng.rand(bs, 256).astype(np.float32),
            'b': rng.rand(bs, 16)}                      # f64 -> f32 cast
    batch_bytes = cols['a'].nbytes + cols['b'].nbytes
    for _ in range(4):
        eng.stage(dict(cols), bs)
    assert eng._host_backed is False      # ring mode engaged
    slabs_after_warmup = eng.slabs_allocated
    n = 50
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    for _ in range(n):
        eng.stage(dict(cols), bs)
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    grown = sum(
        max(0, s.size_diff)
        for s in after.compare_to(before, 'filename')
        if s.traceback and s.traceback[0].filename.endswith(
            os.path.join('petastorm_tpu', 'jax', 'staging.py')))
    assert eng.slabs_allocated == slabs_after_warmup == 2
    assert grown < batch_bytes / 2, \
        'staging.py allocated %d bytes over %d sharded batches' % (grown, n)


# -- legacy-path telemetry (satellite: sharded dispatch visible) --------------


def test_legacy_sharded_dispatch_records_span_and_bytes(mesh):
    """PETASTORM_TPU_STAGING=0 on a mesh: the
    make_array_from_process_local_data path still lands ``h2d_dispatch``
    spans and counts shard-slice ``petastorm_tpu_h2d_bytes_total``.
    Float32/int32 fields keep host dtype == device dtype, so the
    expected byte count is exact (jax's 32-bit mode would downcast
    64-bit fields AFTER the counted host-side bytes)."""

    def factory(url, **kw):
        return DummyBatchReader(
            fields={'x': ((4,), np.float32), 'y': ((), np.int32)},
            batch_size=8, num_batches=5)

    with _env(PETASTORM_TPU_STAGING='0',
              PETASTORM_TPU_STAGING_AUTOTUNE='0'):
        registry = T.get_registry()
        span_key = metric_key(STAGE_SECONDS, {'stage': 'h2d_dispatch'})
        bytes_before = registry.counter_value(staging.H2D_BYTES)
        span_before = registry.counters_with_prefix(STAGE_SECONDS).get(
            span_key, 0.0)
        with make_jax_loader('dummy://', batch_size=8, mesh=mesh,
                             data_axes=(DATA_AXIS,),
                             reader_factory=factory) as loader:
            assert loader._stager is None   # the legacy path under test
            batches = [{k: np.asarray(v) for k, v in b.items()}
                       for b in loader]
        assert len(batches) == 5
        counted = registry.counter_value(staging.H2D_BYTES) - bytes_before
        # shard-slice bytes: exactly the HOST-side bytes of the batches
        expected = sum(sum(a.nbytes for a in b.values())
                       for b in batches)
        assert counted == expected
        assert registry.counters_with_prefix(STAGE_SECONDS).get(
            span_key, 0.0) > span_before


# -- the staging autotuner ----------------------------------------------------


def _window(ready_share=0.0, verdict='idle', dur_s=1.0):
    return {'dur_s': dur_s, 'verdict': verdict,
            'rates': {metric_key(STAGE_SECONDS,
                                 {'stage': 'h2d_ready'}): ready_share}}


class _FakeLoader:
    def __init__(self, stager):
        self._stager = stager
        self._prefetch = 2
        self._out_queue = None

    def _set_prefetch(self, depth):
        self._prefetch = max(1, int(depth))
        return self._prefetch


def _tuner(num_slots=2):
    eng = staging.StagingEngine(8, {}, 'drop',
                                _sharded_accelerator_put(N_SHARDS),
                                num_slots=num_slots)
    loader = _FakeLoader(eng)
    return autotune.StagingAutotuner(loader, window_s=10.0), loader, eng


def test_autotune_knob_default_and_refresh():
    assert autotune.autotune_enabled()
    with _env(PETASTORM_TPU_STAGING_AUTOTUNE='0'):
        assert not autotune.autotune_enabled()
    assert autotune.autotune_enabled()
    with _env(PETASTORM_TPU_STAGING_AUTOTUNE_MAX_SLOTS='3',
              PETASTORM_TPU_STAGING_AUTOTUNE_MAX_PREFETCH='5',
              PETASTORM_TPU_STAGING_AUTOTUNE_WINDOW_SEC='0.5'):
        assert autotune.autotune_max_slots() == 3
        assert autotune.autotune_max_prefetch() == 5
        assert autotune.autotune_window_sec() == 0.5


def test_autotune_disabled_loader_has_no_tuner(scalar_dataset, mesh):
    with _env(PETASTORM_TPU_STAGING_AUTOTUNE='0'):
        with make_jax_loader(scalar_dataset.url, batch_size=8, mesh=mesh,
                             data_axes=(DATA_AXIS,), num_epochs=1,
                             fields=['^id$'],
                             shuffle_row_groups=False) as loader:
            next(iter(loader))
            assert loader._autotuner is None
            assert not loader.diagnostics['staging_autotune']


def test_autotune_deepens_on_sustained_h2d_starvation():
    """3 consecutive starved windows deepen slots AND prefetch; a
    non-starved window resets the streak; bounds hold."""
    tuner, loader, eng = _tuner()
    assert tuner.observe(_window(ready_share=0.9)) == []
    assert tuner.observe(_window(ready_share=0.9)) == []
    # streak broken: no action on the next two starved windows
    assert tuner.observe(_window(ready_share=0.0)) == []
    assert tuner.observe(_window(ready_share=0.9)) == []
    assert tuner.observe(_window(ready_share=0.9)) == []
    actions = tuner.observe(_window(ready_share=0.9))
    assert [a['action'] for a in actions] == ['deepen_slots',
                                             'deepen_prefetch']
    assert eng.num_slots == 3
    assert loader._prefetch == 3
    assert tuner.decisions == 2


def test_autotune_respects_bounds():
    with _env(PETASTORM_TPU_STAGING_AUTOTUNE_MAX_SLOTS='3',
              PETASTORM_TPU_STAGING_AUTOTUNE_MAX_PREFETCH='3'):
        tuner, loader, eng = _tuner()
        for _ in range(12):
            tuner.observe(_window(ready_share=0.9))
        assert eng.num_slots == 3
        assert loader._prefetch == 3
        # saturated at the bounds: further starvation moves nothing
        total = tuner.decisions
        for _ in range(3):
            tuner.observe(_window(ready_share=0.9))
        assert tuner.decisions == total


def test_autotune_ring_grows_to_learned_depth():
    """A deepened engine actually grows its rings at next use, and
    apply_learned carries the depth into a fresh pass's engine."""
    eng = staging.StagingEngine(8, {'x': np.float32}, 'drop',
                                _sharded_accelerator_put(N_SHARDS),
                                num_slots=2)
    loader = _FakeLoader(eng)
    tuner = autotune.StagingAutotuner(loader, window_s=10.0)
    # f64 -> f32 cast routes the batch through the slot ring (a no-cast
    # full single chunk would take the slot-less direct dispatch)
    cols = {'x': np.arange(32, dtype=np.float64).reshape(8, 4)}
    eng.stage(dict(cols), 8)            # ring exists at depth 2
    assert eng.slabs_allocated == 2
    for _ in range(3):
        tuner.observe(_window(ready_share=0.9))
    eng.stage(dict(cols), 8)            # ring grows lazily at next use
    assert eng.num_slots == 3
    assert eng.slabs_allocated == 3
    fresh = staging.StagingEngine(8, {}, 'drop',
                                  _sharded_accelerator_put(N_SHARDS),
                                  num_slots=2)
    tuner.apply_learned(fresh)
    assert fresh.num_slots == 3


def test_autotune_sheds_and_restores_decode_threads():
    # pin the knob so the policy is testable on any host (incl. 1-core
    # CI boxes whose default width is already the floor)
    with _env(PETASTORM_TPU_IMAGE_DECODER_THREADS='3'):
        tuner, _, _ = _tuner()
        assert codecs.image_decoder_threads() == 3
        for _ in range(3):
            actions = tuner.observe(_window(verdict=T.CONSUMER_BOUND))
        assert [a['action'] for a in actions] == ['shed_decode_threads']
        assert codecs.image_decoder_threads() == 2
        # a second consumer-bound streak sheds further, to the floor of 1
        for _ in range(3):
            tuner.observe(_window(verdict=T.CONSUMER_BOUND))
        assert codecs.image_decoder_threads() == 1
        for _ in range(3):
            tuner.observe(_window(verdict=T.CONSUMER_BOUND))
        assert codecs.image_decoder_threads() == 1   # floor holds
        for _ in range(3):
            actions = tuner.observe(_window(verdict=T.PRODUCER_BOUND))
        assert [a['action'] for a in actions] == ['restore_decode_threads']
        assert codecs.image_decoder_threads() == 2
        for _ in range(3):
            tuner.observe(_window(verdict=T.PRODUCER_BOUND))
        assert codecs.image_decoder_threads() == 3   # back at baseline
        # fully restored: the override is gone, the knob rules again
        tuner.close()
        assert codecs.image_decoder_threads() == 3


def test_autotune_thread_override_is_single_owner():
    """Two live tuners in one process: the thread override is one slot —
    the second tuner neither sheds over the first's setting nor wipes it
    at close, and its restore ceiling is the KNOB's width, never the
    first tuner's live override."""
    with _env(PETASTORM_TPU_IMAGE_DECODER_THREADS='3'):
        first, _, _ = _tuner()
        for _ in range(3):
            first.observe(_window(verdict=T.CONSUMER_BOUND))
        assert codecs.image_decoder_threads() == 2
        # constructed while the override is live: baseline is the knob's 3
        second, _, _ = _tuner()
        assert second._baseline_threads == 3
        # the second tuner cannot move the owned override...
        for _ in range(3):
            assert second.observe(
                _window(verdict=T.CONSUMER_BOUND)) == []
        assert codecs.image_decoder_threads() == 2
        # ...and its close leaves the owner's setting intact
        second.close()
        assert codecs.image_decoder_threads() == 2
        first.close()
        assert codecs.image_decoder_threads() == 3
        # slot free again: a fresh tuner may now shed
        third, _, _ = _tuner()
        for _ in range(3):
            third.observe(_window(verdict=T.CONSUMER_BOUND))
        assert codecs.image_decoder_threads() == 2
        third.close()


def test_autotune_close_clears_thread_override():
    with _env(PETASTORM_TPU_IMAGE_DECODER_THREADS='2'):
        tuner, _, _ = _tuner()
        for _ in range(3):
            tuner.observe(_window(verdict=T.CONSUMER_BOUND))
        assert codecs.image_decoder_threads() == 1
        tuner.close()
        # the override dies with the loader; the knob rules again
        assert codecs.image_decoder_threads() == 2


def test_autotune_decisions_recorded_everywhere():
    """One decision = ring entry + counter + pipeline_report section
    (+ the tuner's own summary)."""
    T.reset_for_tests()
    tuner, _, eng = _tuner()
    for _ in range(3):
        tuner.observe(_window(ready_share=0.9))
    counts = autotune.decision_counts()
    assert counts.get('deepen_slots') == 1
    assert counts.get('deepen_prefetch') == 1
    registry = T.get_registry()
    by_action = registry.counters_with_prefix(autotune.AUTOTUNE_DECISIONS)
    assert sum(by_action.values()) == 2
    section = T.pipeline_report().get('staging_autotune')
    assert section is not None
    assert section['total'] == 2
    assert {e['action'] for e in section['recent']} == {
        'deepen_slots', 'deepen_prefetch'}
    rendered = T.format_pipeline_report(T.pipeline_report())
    assert 'staging autotune: 2 decision(s)' in rendered
    summary = tuner.summary()
    assert summary['slots'] == eng.num_slots == 3
    assert summary['decisions'] == 2


def test_autotune_report_absent_without_decisions():
    T.reset_for_tests()
    autotune._reset_for_tests()
    assert 'staging_autotune' not in T.pipeline_report()


def test_autotune_loader_end_to_end_smoke(scalar_dataset, mesh):
    """A live mesh loader with aggressive windows ticks the loop on its
    staging thread (the ``autotune`` stage lands) without perturbing
    delivered values."""
    with _env(PETASTORM_TPU_STAGING_AUTOTUNE_WINDOW_SEC='0.05'):
        with make_jax_loader(scalar_dataset.url, batch_size=8, mesh=mesh,
                             data_axes=(DATA_AXIS,), num_epochs=2,
                             fields=['^id$'],
                             shuffle_row_groups=False) as loader:
            ids = [np.asarray(b['id']) for b in loader]
            tuner = loader._autotuner
            assert tuner is not None
            diag = loader.diagnostics
            assert diag['staging_autotune']
            assert diag['staging_prefetch'] >= 2
            assert diag['staging_slot_depth'] >= 2
        # num_epochs=2 streams 200 rows through one pass: 25 full batches
        assert len(ids) == 200 // 8
        # the tuner survives across passes (same object) and a direct
        # tick still works after the pass ended
        assert tuner is loader._autotuner
        result = tuner.tick()
        assert result is None or isinstance(result, list)
        # values asserted identical by the parity suite above; here the
        # stream must simply be the dataset exactly twice
        flat = np.concatenate(ids)
        assert sorted(flat.tolist()) == sorted(2 * list(range(100)))
