"""High-availability chaos drills: warm-standby failover, per-job QoS
(weights, priority preemption), and cache-aware placement
(docs/service.md, "High availability" / "Per-job QoS" /
"Cache-aware placement").

Topology mirrors tests/test_daemon.py's SIGKILL drill: the PRIMARY
daemon and its standing workers run as subprocesses (so a SIGKILL is a
real control-plane death), while the standby under test runs in-process
— its anomalies, trace instants, and fault injections land in THIS
process's telemetry where the assertions can see them."""

import collections
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from petastorm_tpu import faults, telemetry
from petastorm_tpu.service.daemon import DaemonClientPool, ServiceDaemon
from petastorm_tpu.service.protocol import free_tcp_port
from petastorm_tpu.service.standby import StandbyDaemon
from petastorm_tpu.workers import EmptyResultError
from tests.stub_workers import IdentityWorker, SleepyIdentityWorker

pytestmark = pytest.mark.service

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# tight-but-safe (the test_daemon.py convention): sub-second detection,
# generous outer deadlines so shared-box scheduling noise cannot flake
_HB = 0.15
_SYNC = 0.2
_LAPSE = 1.2


@pytest.fixture(autouse=True)
def _clean_telemetry_and_faults():
    telemetry.reset_for_tests()
    yield
    os.environ.pop('PETASTORM_TPU_FAULTS', None)
    faults.refresh_faults()
    assert faults.ARMED is None
    telemetry.reset_for_tests()


def _drain(pool, per_result_timeout_s=60):
    out = []
    while True:
        try:
            out.append(pool.get_results(timeout=per_result_timeout_s))
        except EmptyResultError:
            return out


def _client(endpoint, **kwargs):
    kwargs.setdefault('heartbeat_interval_s', _HB)
    return DaemonClientPool(endpoint, **kwargs)


def _await(predicate, deadline_s=30, interval_s=0.05, message='condition'):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval_s)
    raise AssertionError('timed out waiting for %s' % message)


def _subprocess_env():
    return dict(os.environ,
                PYTHONPATH=os.pathsep.join(
                    [_REPO_ROOT, os.path.join(_REPO_ROOT, 'tests')]),
                JAX_PLATFORMS='cpu')


def _spawn_daemon_cli(endpoint, extra=()):
    return subprocess.Popen(
        [sys.executable, '-m', 'petastorm_tpu.service',
         '--endpoint', endpoint, '--no-supervisor',
         '--heartbeat-interval', str(_HB)] + list(extra),
        env=_subprocess_env())


def _spawn_cli_worker(endpoint):
    return subprocess.Popen(
        [sys.executable, '-m', 'petastorm_tpu.service.worker_server',
         '--endpoint', endpoint,
         '--heartbeat-interval', str(_HB),
         '--ack-timeout', '1.5',
         '--parent-pid', str(os.getpid())],
        env=_subprocess_env())


def _reap(procs):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


def _standby(endpoint, **kwargs):
    """Start an in-process standby. Callers must only do this once the
    primary is KNOWN to be bound (a registered client proves it, or
    :func:`_await_primary_up`): a standby pointed at a not-yet-bound
    endpoint lapses during the primary's startup and burns its whole
    promotion window losing the bind race — correct behavior for the
    daemon, a 30-second stall for a test."""
    kwargs.setdefault('sync_interval_s', _SYNC)
    kwargs.setdefault('lapse_s', _LAPSE)
    kwargs.setdefault('heartbeat_interval_s', _HB)
    kwargs.setdefault('supervise', False)
    standby = StandbyDaemon(endpoint, **kwargs)
    standby.start()
    return standby


def _await_primary_up(endpoint, deadline_s=30):
    """Block until the daemon at ``endpoint`` answers a replication
    probe (bound AND serving)."""
    import zmq

    from petastorm_tpu.service import protocol as proto
    context = zmq.Context()
    sock = context.socket(zmq.DEALER)
    sock.setsockopt(zmq.LINGER, 0)
    sock.connect(endpoint)
    try:
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            sock.send_multipart([proto.MSG_STANDBY_SYNC])
            if sock.poll(200):
                return
        raise AssertionError('primary at %s never answered' % endpoint)
    finally:
        sock.close(linger=0)
        context.term()


def _failover_events():
    return [e for e in telemetry.recent_anomalies()
            if e['kind'] == 'dispatcher_failover']


# -- warm-standby promotion ---------------------------------------------------


def test_warm_failover_sigkill_two_priority_jobs_exact():
    """THE HA drill: SIGKILL the primary daemon mid-epoch with two
    unequal-priority jobs registered. The warm standby (which has been
    mirroring the registry) promotes onto the same endpoint within a
    lapse window; both clients re-register against the new incarnation
    and re-submit their unmarkered items; each job's delivered row
    multiset is exact — the failover cost retries, never rows."""
    endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
    primary = _spawn_daemon_cli(endpoint)
    workers = [_spawn_cli_worker(endpoint) for _ in range(3)]
    hi = _client(endpoint, name='job-hi', priority=2,
                 ack_timeout_s=1.5, connect_timeout_s=60)
    lo = _client(endpoint, name='job-lo',
                 ack_timeout_s=1.5, connect_timeout_s=60)
    standby = None
    try:
        hi.start(SleepyIdentityWorker)
        lo.start(SleepyIdentityWorker)
        # both registrations answered: the primary is up — safe to
        # point a standby at it (see _standby)
        standby = _standby(endpoint)
        for i in range(30):
            hi.ventilate(i, sleep_s=0.05)
        for i in range(100, 130):
            lo.ventilate(i, sleep_s=0.05)
        got_hi = [hi.get_results(timeout=60) for _ in range(5)]
        got_lo = [lo.get_results(timeout=60) for _ in range(5)]
        # the standby must hold a WARM snapshot (both jobs) before the
        # kill — otherwise this drill degrades to the cold-promote one
        _await(lambda: standby.health()['snapshot_jobs'] == 2,
               message='warm replication of both jobs')
        t_kill = time.monotonic()
        os.kill(primary.pid, signal.SIGKILL)
        primary.wait()
        assert standby.wait_promoted(30), 'standby must take over'
        blackout_s = time.monotonic() - t_kill
        got_hi.extend(_drain(hi))
        got_lo.extend(_drain(lo))
        assert sorted(got_hi) == list(range(30))
        assert sorted(got_lo) == list(range(100, 130))
        assert standby.role == 'primary'
        # detection is one lapse window; the bind retry adds a little
        assert blackout_s < _LAPSE + 8.0, \
            'promotion took %.1fs — outside the lapse window' % blackout_s
        events = _failover_events()
        assert len(events) == 1, 'exactly one failover announcement'
        assert events[0]['detail']['warm'] is True
        assert events[0]['detail']['snapshot_jobs'] == 2
        health = standby.health()
        assert health['role'] == 'primary'
        assert health['promotions'] == 1
        # QoS params survived the failover through the snapshot
        qos = {q['name']: q for q in health['qos']}
        assert qos['job-hi']['priority'] == 2
        assert qos['job-lo']['priority'] == 0
        assert hi.diagnostics['reregistrations'] >= 1
        assert lo.diagnostics['reregistrations'] >= 1
        assert all(w.poll() is None for w in workers), \
            'standing workers must survive the failover'
    finally:
        for pool in (hi, lo):
            pool.stop()
            pool.join()
        if standby is not None:
            standby.stop()
        _reap([primary] + workers)


def test_reader_completes_through_failover(tmp_path):
    """The same drill through the reader stack: a
    ``make_batch_reader`` job reading through the standing daemon
    delivers the identical row multiset as a thread-pool read even when
    the primary is SIGKILLed mid-read and the standby promotes."""
    from petastorm_tpu.reader import make_batch_reader
    from tests.test_common import create_test_scalar_dataset
    url = 'file://' + str(tmp_path / 'dataset')
    create_test_scalar_dataset(url, num_rows=50, num_files=5)

    def read_ids(pool, kill=None):
        ids = collections.Counter()
        killed = False
        with make_batch_reader(url, reader_pool_type=pool,
                               num_epochs=1,
                               shuffle_row_groups=False) as reader:
            for batch in reader:
                ids.update(int(x) for x in batch.id)
                if kill is not None and not killed \
                        and kill.poll() is None:
                    killed = True
                    os.kill(kill.pid, signal.SIGKILL)
        return ids

    expected = read_ids('thread')
    assert sum(expected.values()) == 50
    endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
    primary = _spawn_daemon_cli(endpoint)
    workers = [_spawn_cli_worker(endpoint) for _ in range(2)]
    _await_primary_up(endpoint)
    standby = _standby(endpoint)
    pool = _client(endpoint, name='reader-ha', ack_timeout_s=1.5,
                   connect_timeout_s=60)
    try:
        assert read_ids(pool, kill=primary) == expected
        assert standby.wait_promoted(30), \
            'the kill mid-read must have promoted the standby'
        assert standby.role == 'primary'
    finally:
        standby.stop()
        _reap([primary] + workers)


def test_standby_death_is_harmless():
    """Losing the MIRROR must cost nothing: a job runs to exact
    completion while the standby watching the primary is SIGKILLed
    mid-replication, and the primary's health stays clean."""
    daemon = ServiceDaemon('tcp://127.0.0.1:0', initial_workers=2,
                           heartbeat_interval_s=_HB,
                           supervisor_tick_s=_HB)
    daemon.start()
    standby_proc = _spawn_daemon_cli(
        daemon.endpoint,
        extra=['--standby', '--standby-sync-interval', str(_SYNC),
               '--standby-lapse', '30'])
    pool = _client(daemon.endpoint, name='survivor')
    try:
        pool.start(SleepyIdentityWorker)
        for i in range(30):
            pool.ventilate(i, sleep_s=0.02)
        # replication is live (the primary answered sync pulls) ...
        _await(lambda: daemon.dispatcher.health()[
            'standby_syncs_served'] >= 2,
            message='standby replication stream')
        # ... and now the mirror dies hard
        os.kill(standby_proc.pid, signal.SIGKILL)
        standby_proc.wait()
        assert sorted(_drain(pool)) == list(range(30))
        health = daemon.health()
        assert health['role'] == 'primary'
        assert health['poisoned'] == []
        assert not _failover_events()
    finally:
        pool.stop()
        pool.join()
        _reap([standby_proc])
        daemon.stop()


def test_replication_drop_cold_promote_still_exact():
    """Chaos seam ``zmq.replicate:drop``: every replication snapshot
    the standby pulls is dropped on receive, so it promotes COLD (no
    registry snapshot). Correctness must not depend on the snapshot:
    the client's job expires on the new incarnation, it re-registers
    and re-submits, and the delivered multiset is still exact."""
    endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
    primary = _spawn_daemon_cli(endpoint)
    workers = [_spawn_cli_worker(endpoint) for _ in range(2)]
    pool = _client(endpoint, name='cold-drill', ack_timeout_s=1.5,
                   connect_timeout_s=60)
    standby = None
    try:
        pool.start(SleepyIdentityWorker)
        for i in range(30):
            pool.ventilate(i, sleep_s=0.05)
        got = [pool.get_results(timeout=60) for _ in range(5)]
        # arm AFTER the subprocesses spawned: the drop must hit the
        # in-process standby's receive side only
        os.environ['PETASTORM_TPU_FAULTS'] = 'zmq.replicate:drop'
        faults.refresh_faults()
        standby = _standby(endpoint, lapse_s=1.0)
        _await(lambda: faults.injection_stats().get(
            'zmq.replicate', {}).get('fired', 0) >= 1,
            message='replication frames to be dropped')
        assert standby.health()['snapshot_jobs'] == 0
        os.kill(primary.pid, signal.SIGKILL)
        primary.wait()
        assert standby.wait_promoted(45), 'cold standby must still promote'
        got.extend(_drain(pool))
        assert sorted(got) == list(range(30))
        assert standby.role == 'primary'
        events = _failover_events()
        assert events, 'cold promotion still announces the failover'
        assert events[-1]['detail']['warm'] is False
        assert pool.diagnostics['reregistrations'] >= 1
    finally:
        pool.stop()
        pool.join()
        if standby is not None:
            standby.stop()
        _reap([primary] + workers)


def test_promote_faultpoint_retries_until_success():
    """Chaos seam ``service.promote``: the first promote attempts fail
    (injected), the standby backs off and retries inside its promotion
    window, and the takeover still lands."""
    daemon = ServiceDaemon('tcp://127.0.0.1:0', initial_workers=1,
                           heartbeat_interval_s=_HB,
                           supervisor_tick_s=_HB)
    daemon.start()
    standby = _standby(daemon.endpoint, sync_interval_s=0.1, lapse_s=0.6)
    try:
        _await(lambda: standby.health()['syncs_ok'] >= 1,
               message='replication before the takeover drill')
        os.environ['PETASTORM_TPU_FAULTS'] = 'service.promote:error:1:times=2'
        faults.refresh_faults()
        daemon.stop()  # frees the endpoint; the standby lapses and takes it
        assert standby.wait_promoted(30), \
            'promotion must survive injected attempt failures'
        stats = faults.injection_stats()
        assert stats['service.promote']['fired'] == 2
        health = standby.health()
        assert health['role'] == 'primary'
        assert health['promotions'] == 1
    finally:
        standby.stop()
        daemon.stop()


# -- per-job QoS --------------------------------------------------------------


def test_priority_preemption_drains_never_strands():
    """A higher-priority job with pending work and no workers preempts
    a lower tier at row-group granularity: the victim worker finishes
    its in-flight items before moving (nothing re-ventilated, nothing
    quarantined, no retry budget charged) and both jobs deliver their
    exact multisets."""
    daemon = ServiceDaemon('tcp://127.0.0.1:0', initial_workers=2,
                           heartbeat_interval_s=_HB,
                           supervisor_tick_s=_HB)
    daemon.start()
    lo = _client(daemon.endpoint, name='batch-lo')
    hi = _client(daemon.endpoint, name='online-hi', priority=3)
    try:
        lo.start(SleepyIdentityWorker)
        for i in range(100):
            lo.ventilate(i, sleep_s=0.05)
        # the whole fleet belongs to the low job before the contender
        _await(lambda: sum(j['workers'] for j in
                           daemon.dispatcher.health()['jobs']) == 2,
               message='fleet bound to the low-priority job')
        hi.start(SleepyIdentityWorker)
        for i in range(1000, 1010):
            hi.ventilate(i, sleep_s=0.01)
        _await(lambda: daemon.dispatcher.health()['preemptions'] >= 1,
               message='a preemption decision')
        assert sorted(_drain(hi)) == list(range(1000, 1010))
        assert sorted(_drain(lo)) == list(range(100))
        stats = daemon.dispatcher.stats()
        assert stats['items_poisoned'] == 0, \
            'preemption must never quarantine'
        assert stats['items_retried'] == 0, \
            'a drained preemption charges no retry budget'
        assert hi.poisoned_items == [] and lo.poisoned_items == []
        assert daemon.dispatcher.health()['preemptions'] >= 1
    finally:
        for pool in (hi, lo):
            pool.stop()
            pool.join()
        daemon.stop()


def test_weighted_fair_share_three_to_one():
    """A weight-3 job targets (and gets) three times the workers of a
    weight-1 co-tenant on a 4-worker fleet, and both still deliver
    exactly."""
    daemon = ServiceDaemon('tcp://127.0.0.1:0', initial_workers=4,
                           heartbeat_interval_s=_HB,
                           supervisor_tick_s=_HB)
    daemon.start()
    heavy = _client(daemon.endpoint, name='heavy', weight=3)
    light = _client(daemon.endpoint, name='light')
    try:
        heavy.start(SleepyIdentityWorker)
        light.start(SleepyIdentityWorker)
        for i in range(200):
            heavy.ventilate(i, sleep_s=0.03)
        for i in range(1000, 1100):
            light.ventilate(i, sleep_s=0.03)

        def shares():
            return {q['name']: q for q in
                    daemon.dispatcher.health()['qos']}

        _await(lambda: shares()['heavy']['worker_share'] == 0.75
               and shares()['light']['worker_share'] == 0.25,
               message='the 3:1 weighted split')
        snap = shares()
        assert snap['heavy']['target_share'] == 0.75
        assert snap['light']['target_share'] == 0.25
        assert sorted(_drain(heavy)) == list(range(200))
        assert sorted(_drain(light)) == list(range(1000, 1100))
    finally:
        for pool in (heavy, light):
            pool.stop()
            pool.join()
        daemon.stop()


# -- cache-aware placement ----------------------------------------------------


def test_placement_binds_second_job_to_warm_host():
    """A second job with the identical decode fingerprint
    (``placement_group``) lands on workers that already ran it, and the
    dispatcher's telemetry counts the warm binding as a hit."""
    daemon = ServiceDaemon('tcp://127.0.0.1:0', initial_workers=2,
                           heartbeat_interval_s=_HB,
                           supervisor_tick_s=_HB)
    daemon.start()
    pools = []
    try:
        first = _client(daemon.endpoint, name='cold-pass')
        pools.append(first)
        first.start(IdentityWorker,
                    worker_args={'placement_group': 'grp-warm'})
        for i in range(10):
            first.ventilate(i)
        assert sorted(_drain(first)) == list(range(10))
        # the first binding of this fingerprint found no warm host
        assert daemon.dispatcher.health()['placement_misses'] >= 1
        pools.remove(first)
        first.stop()
        first.join()
        _await(lambda: daemon.dispatcher.active_jobs() == 0,
               message='first job reclaimed')
        second = _client(daemon.endpoint, name='warm-pass')
        pools.append(second)
        second.start(IdentityWorker,
                     worker_args={'placement_group': 'grp-warm'})
        for i in range(100, 110):
            second.ventilate(i)
        assert sorted(_drain(second)) == list(range(100, 110))
        health = daemon.dispatcher.health()
        assert health['placement_enabled'] is True
        assert health['placement_hits'] >= 1, \
            'the identical fingerprint must bind warm'
    finally:
        for pool in pools:
            pool.stop()
            pool.join()
        daemon.stop()
