"""Unit tests for the schema kernel (parity model: petastorm/tests/test_unischema.py)."""

from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
from petastorm_tpu.unischema import (
    Unischema, UnischemaField, dict_to_encoded_row, insert_explicit_nulls,
    match_unischema_fields,
)


def _schema():
    return Unischema('TestSchema', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('value', np.float64, (), ScalarCodec(pa.float64()), True),
        UnischemaField('image', np.uint8, (16, 32, 3), CompressedImageCodec('png'), False),
        UnischemaField('matrix', np.float32, (None, 4), NdarrayCodec(), False),
    ])


def test_fields_accessible_as_attributes_and_dict():
    s = _schema()
    assert s.id is s.fields['id']
    assert list(s.fields) == ['id', 'value', 'image', 'matrix']
    assert len(s) == 4


def test_duplicate_field_names_raise():
    with pytest.raises(ValueError, match='Duplicate'):
        Unischema('S', [UnischemaField('a', np.int32, ()),
                        UnischemaField('a', np.int64, ())])


def test_field_equality_ignores_codec():
    f1 = UnischemaField('x', np.int32, (), ScalarCodec(pa.int32()), False)
    f2 = UnischemaField('x', np.int32, (), None, False)
    f3 = UnischemaField('x', np.int64, (), None, False)
    assert f1 == f2
    assert hash(f1) == hash(f2)
    assert f1 != f3


def test_field_is_immutable():
    f = UnischemaField('x', np.int32, ())
    with pytest.raises(AttributeError):
        f.name = 'y'


def test_shape_compliance_with_wildcards():
    f = UnischemaField('m', np.float32, (None, 4))
    assert f.is_shape_compliant((7, 4))
    assert not f.is_shape_compliant((7, 5))
    assert not f.is_shape_compliant((7,))


def test_create_schema_view_with_fields_and_regex():
    s = _schema()
    view = s.create_schema_view([s.id, 'im.*'])
    assert set(view.fields) == {'id', 'image'}
    # Order preserved from the parent schema
    assert list(view.fields) == ['id', 'image']


def test_create_schema_view_rejects_foreign_field():
    s = _schema()
    foreign = UnischemaField('other', np.int32, ())
    with pytest.raises(ValueError):
        s.create_schema_view([foreign])


def test_match_unischema_fields_fullmatch_semantics():
    s = _schema()
    # 'i' alone must not prefix-match 'id'/'image' (fullmatch semantics)
    assert match_unischema_fields(s, ['i']) == []
    names = {f.name for f in match_unischema_fields(s, ['i.*'])}
    assert names == {'id', 'image'}


def test_namedtuple_identity_stable():
    s1 = _schema()
    s2 = _schema()
    assert s1.namedtuple is s2.namedtuple
    row = s1.make_namedtuple(id=3)
    assert row.id == 3 and row.value is None


def test_as_arrow_schema_types():
    s = _schema()
    arrow = s.as_arrow_schema()
    assert arrow.field('id').type == pa.int64()
    assert arrow.field('image').type == pa.binary()
    assert arrow.field('value').nullable


def test_json_roundtrip_preserves_everything():
    s = _schema()
    restored = Unischema.from_json_dict(s.to_json_dict())
    assert list(restored.fields) == list(s.fields)
    for name in s.fields:
        assert restored.fields[name] == s.fields[name]
    assert isinstance(restored.image.codec, CompressedImageCodec)
    assert restored.image.codec.image_codec == 'png'
    assert isinstance(restored.matrix.codec, NdarrayCodec)


def test_json_roundtrip_decimal_and_strings():
    s = Unischema('S', [
        UnischemaField('d', Decimal, (), ScalarCodec(pa.string()), False),
        UnischemaField('s', np.str_, (), ScalarCodec(pa.string()), False),
        UnischemaField('b', np.bytes_, (), ScalarCodec(pa.binary()), True),
    ])
    r = Unischema.from_json_dict(s.to_json_dict())
    assert r.d.numpy_dtype is Decimal
    assert r.s.numpy_dtype is np.str_
    assert r.b.numpy_dtype is np.bytes_


def test_from_arrow_schema_inference():
    arrow = pa.schema([
        pa.field('a', pa.int32()),
        pa.field('b', pa.string()),
        pa.field('c', pa.list_(pa.float32())),
        pa.field('nested', pa.list_(pa.list_(pa.int8()))),
    ])
    s = Unischema.from_arrow_schema(arrow)
    assert s.a.numpy_dtype is np.int32 and s.a.shape == ()
    assert s.b.numpy_dtype is np.str_
    assert s.c.shape == (None,) and s.c.numpy_dtype is np.float32
    assert 'nested' not in s.fields  # silently skipped
    with pytest.raises(ValueError):
        Unischema.from_arrow_schema(arrow, omit_unsupported_fields=False)


def test_dict_to_encoded_row_validates_and_encodes():
    s = _schema()
    img = np.random.randint(0, 255, (16, 32, 3), dtype=np.uint8)
    mat = np.random.rand(5, 4).astype(np.float32)
    row = dict_to_encoded_row(s, {'id': 1, 'value': 2.5, 'image': img, 'matrix': mat})
    assert row['id'] == 1
    assert isinstance(row['image'], bytearray)
    assert isinstance(row['matrix'], bytearray)

    with pytest.raises(ValueError, match='not in schema'):
        dict_to_encoded_row(s, {'id': 1, 'bogus': 0})
    with pytest.raises(ValueError, match='not nullable'):
        dict_to_encoded_row(s, {'id': None, 'value': 1.0, 'image': img, 'matrix': mat})
    # nullable field may be None
    row = dict_to_encoded_row(s, {'id': 1, 'value': None, 'image': img, 'matrix': mat})
    assert row['value'] is None


def test_insert_explicit_nulls():
    s = Unischema('S', [
        UnischemaField('req', np.int32, (), None, False),
        UnischemaField('opt', np.int32, (), None, True),
    ])
    d = insert_explicit_nulls(s, {'req': 1})
    assert d['opt'] is None
    with pytest.raises(ValueError):
        insert_explicit_nulls(s, {'opt': 1})
