"""Unit tests for the schema kernel (parity model: petastorm/tests/test_unischema.py)."""

from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.codecs import CompressedImageCodec, NdarrayCodec, ScalarCodec
from petastorm_tpu.unischema import (
    Unischema, UnischemaField, dict_to_encoded_row, insert_explicit_nulls,
    match_unischema_fields,
)


def _schema():
    return Unischema('TestSchema', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('value', np.float64, (), ScalarCodec(pa.float64()), True),
        UnischemaField('image', np.uint8, (16, 32, 3), CompressedImageCodec('png'), False),
        UnischemaField('matrix', np.float32, (None, 4), NdarrayCodec(), False),
    ])


def test_fields_accessible_as_attributes_and_dict():
    s = _schema()
    assert s.id is s.fields['id']
    assert list(s.fields) == ['id', 'value', 'image', 'matrix']
    assert len(s) == 4


def test_duplicate_field_names_raise():
    with pytest.raises(ValueError, match='Duplicate'):
        Unischema('S', [UnischemaField('a', np.int32, ()),
                        UnischemaField('a', np.int64, ())])


def test_field_equality_ignores_codec():
    f1 = UnischemaField('x', np.int32, (), ScalarCodec(pa.int32()), False)
    f2 = UnischemaField('x', np.int32, (), None, False)
    f3 = UnischemaField('x', np.int64, (), None, False)
    assert f1 == f2
    assert hash(f1) == hash(f2)
    assert f1 != f3


def test_field_is_immutable():
    f = UnischemaField('x', np.int32, ())
    with pytest.raises(AttributeError):
        f.name = 'y'


def test_shape_compliance_with_wildcards():
    f = UnischemaField('m', np.float32, (None, 4))
    assert f.is_shape_compliant((7, 4))
    assert not f.is_shape_compliant((7, 5))
    assert not f.is_shape_compliant((7,))


def test_create_schema_view_with_fields_and_regex():
    s = _schema()
    view = s.create_schema_view([s.id, 'im.*'])
    assert set(view.fields) == {'id', 'image'}
    # Order preserved from the parent schema
    assert list(view.fields) == ['id', 'image']


def test_create_schema_view_rejects_foreign_field():
    s = _schema()
    foreign = UnischemaField('other', np.int32, ())
    with pytest.raises(ValueError):
        s.create_schema_view([foreign])


def test_match_unischema_fields_fullmatch_semantics():
    s = _schema()
    # 'i' alone must not prefix-match 'id'/'image' (fullmatch semantics)
    assert match_unischema_fields(s, ['i']) == []
    names = {f.name for f in match_unischema_fields(s, ['i.*'])}
    assert names == {'id', 'image'}


def test_namedtuple_identity_stable():
    s1 = _schema()
    s2 = _schema()
    assert s1.namedtuple is s2.namedtuple
    row = s1.make_namedtuple(id=3)
    assert row.id == 3 and row.value is None


def test_as_arrow_schema_types():
    s = _schema()
    arrow = s.as_arrow_schema()
    assert arrow.field('id').type == pa.int64()
    assert arrow.field('image').type == pa.binary()
    assert arrow.field('value').nullable


def test_json_roundtrip_preserves_everything():
    s = _schema()
    restored = Unischema.from_json_dict(s.to_json_dict())
    assert list(restored.fields) == list(s.fields)
    for name in s.fields:
        assert restored.fields[name] == s.fields[name]
    assert isinstance(restored.image.codec, CompressedImageCodec)
    assert restored.image.codec.image_codec == 'png'
    assert isinstance(restored.matrix.codec, NdarrayCodec)


def test_json_roundtrip_decimal_and_strings():
    s = Unischema('S', [
        UnischemaField('d', Decimal, (), ScalarCodec(pa.string()), False),
        UnischemaField('s', np.str_, (), ScalarCodec(pa.string()), False),
        UnischemaField('b', np.bytes_, (), ScalarCodec(pa.binary()), True),
    ])
    r = Unischema.from_json_dict(s.to_json_dict())
    assert r.d.numpy_dtype is Decimal
    assert r.s.numpy_dtype is np.str_
    assert r.b.numpy_dtype is np.bytes_


def test_from_arrow_schema_inference():
    arrow = pa.schema([
        pa.field('a', pa.int32()),
        pa.field('b', pa.string()),
        pa.field('c', pa.list_(pa.float32())),
        pa.field('nested', pa.list_(pa.list_(pa.int8()))),
    ])
    s = Unischema.from_arrow_schema(arrow)
    assert s.a.numpy_dtype is np.int32 and s.a.shape == ()
    assert s.b.numpy_dtype is np.str_
    assert s.c.shape == (None,) and s.c.numpy_dtype is np.float32
    assert 'nested' not in s.fields  # silently skipped
    with pytest.raises(ValueError):
        Unischema.from_arrow_schema(arrow, omit_unsupported_fields=False)


def test_dict_to_encoded_row_validates_and_encodes():
    s = _schema()
    img = np.random.randint(0, 255, (16, 32, 3), dtype=np.uint8)
    mat = np.random.rand(5, 4).astype(np.float32)
    row = dict_to_encoded_row(s, {'id': 1, 'value': 2.5, 'image': img, 'matrix': mat})
    assert row['id'] == 1
    assert isinstance(row['image'], bytearray)
    assert isinstance(row['matrix'], bytearray)

    with pytest.raises(ValueError, match='not in schema'):
        dict_to_encoded_row(s, {'id': 1, 'bogus': 0})
    with pytest.raises(ValueError, match='not nullable'):
        dict_to_encoded_row(s, {'id': None, 'value': 1.0, 'image': img, 'matrix': mat})
    # nullable field may be None
    row = dict_to_encoded_row(s, {'id': 1, 'value': None, 'image': img, 'matrix': mat})
    assert row['value'] is None


def test_field_name_colliding_with_schema_attribute_rejected():
    # reference: test_field_name_conflict_with_unischema_attribute (:293)
    with pytest.raises(ValueError, match='collides'):
        Unischema('S', [UnischemaField('fields', np.int64, (),
                                       ScalarCodec(pa.int64()), False)])


def test_create_schema_view_no_regex_match_gives_empty_view():
    # reference: test_create_schema_view_no_field_matches_regex (:276)
    view = _schema().create_schema_view(['does_not_exist.*'])
    assert len(view) == 0


def test_create_schema_view_mixed_with_duplicates():
    # regex + explicit field naming the same column yields it once
    # (reference: ..._regex_and_unischema_fields_with_duplicates :266)
    s = _schema()
    view = s.create_schema_view(['id.*', s.id, s.value])
    assert list(view.fields) == ['id', 'value']


def test_create_schema_view_substitutes_own_fields():
    # a stale instance (different codec) is matched by name and replaced by
    # this schema's own field (reference rationale, unischema.py:221-236)
    s = _schema()
    stale = UnischemaField('image', np.uint8, (16, 32, 3),
                           CompressedImageCodec('jpeg', quality=10), False)
    view = s.create_schema_view([stale])
    assert view.image.codec.image_codec == 'png'


def test_namedtuple_more_than_255_fields():
    # the reference ships namedtuple_gt_255_fields.py for py<3.7; document
    # that modern Python needs no shim by exercising 300 fields for real
    fields = [UnischemaField('f%03d' % i, np.int64, (),
                             ScalarCodec(pa.int64()), False)
              for i in range(300)]
    s = Unischema('Wide', fields)
    row = s.make_namedtuple(**{f.name: i for i, f in enumerate(s)})
    assert row.f000 == 0 and row.f299 == 299
    assert len(row) == 300


def test_from_arrow_schema_with_partition_columns():
    # reference: test_arrow_schema_convertion_with_{string,int}_partitions
    arrow = pa.schema([pa.field('v', pa.float64())])
    s = Unischema.from_arrow_schema(arrow, partition_columns=['part'])
    assert s.part.numpy_dtype == np.str_ and s.part.shape == ()


def test_from_arrow_schema_nested_list_skipped_or_raises():
    # reference: test_arrow_schema_arrow_1644_list_of_list (:417) +
    # test_arrow_schema_convertion_fail (:393)
    arrow = pa.schema([pa.field('ok', pa.int32()),
                       pa.field('nested', pa.list_(pa.list_(pa.int32())))])
    s = Unischema.from_arrow_schema(arrow)
    assert list(s.fields) == ['ok']
    with pytest.raises(ValueError, match='[Nn]ested'):
        Unischema.from_arrow_schema(arrow, omit_unsupported_fields=False)


def test_from_arrow_schema_list_of_struct_skipped():
    # reference: test_arrow_schema_arrow_1644_list_of_struct (:404)
    arrow = pa.schema([
        pa.field('ok', pa.int64()),
        pa.field('structs', pa.list_(pa.struct([('a', pa.int32())]))),
    ])
    s = Unischema.from_arrow_schema(arrow)
    assert list(s.fields) == ['ok']


def test_encoded_row_rejects_unknown_and_wrong_shape():
    # reference: test_dict_to_spark_row_field_validation_* (:107-150)
    s = _schema()
    base = {'id': 1, 'value': 2.0,
            'image': np.zeros((16, 32, 3), np.uint8),
            'matrix': np.zeros((5, 4), np.float32)}
    with pytest.raises(ValueError, match='not in schema'):
        dict_to_encoded_row(s, dict(base, bogus=1))
    with pytest.raises(TypeError, match='dict'):
        dict_to_encoded_row(s, [('id', 1)])
    with pytest.raises(ValueError, match='not nullable'):
        dict_to_encoded_row(s, dict(base, id=None))
    # nullable None passes through un-encoded
    assert dict_to_encoded_row(s, dict(base, value=None))['value'] is None
    with pytest.raises(ValueError):
        dict_to_encoded_row(s, dict(base, image=np.zeros((8, 8, 3), np.uint8)))


def test_codecless_multidim_field_rejected_on_encode():
    s = Unischema('S', [UnischemaField('m', np.float32, (2, 2), None, False)])
    with pytest.raises(ValueError, match='codec'):
        dict_to_encoded_row(s, {'m': np.zeros((2, 2), np.float32)})


def test_codecless_1d_field_roundtrips_as_list():
    s = Unischema('S', [UnischemaField('v', np.float32, (None,), None, False)])
    encoded = dict_to_encoded_row(s, {'v': np.arange(3, dtype=np.float32)})
    assert encoded['v'] == [0.0, 1.0, 2.0]


def test_insert_explicit_nulls():
    s = Unischema('S', [
        UnischemaField('req', np.int32, (), None, False),
        UnischemaField('opt', np.int32, (), None, True),
    ])
    d = insert_explicit_nulls(s, {'req': 1})
    assert d['opt'] is None
    with pytest.raises(ValueError):
        insert_explicit_nulls(s, {'opt': 1})
