"""Model/ops/mesh tests on the virtual 8-device CPU platform."""

import numpy as np
import pyarrow as pa
import pytest

import jax
import jax.numpy as jnp
import optax

from petastorm_tpu.parallel.mesh import make_mesh


class TestMesh:
    def test_shape_and_axes(self):
        mesh = make_mesh(data=4, model=2)
        assert mesh.shape == {'data': 4, 'model': 2}

    def test_default_data_size(self):
        mesh = make_mesh(model=2)
        assert mesh.shape['data'] == len(jax.devices()) // 2

    def test_too_many_devices_rejected(self):
        with pytest.raises(ValueError, match='devices'):
            make_mesh(data=16, model=2)


class TestNormalizeOp:
    def _ref(self, x, mean, std, dtype):
        return ((x.astype(np.float32) / 255.0 - mean) / std).astype(dtype)

    def test_pallas_interpret_matches_reference(self):
        from petastorm_tpu.ops import normalize_images
        rng = np.random.RandomState(0)
        x = rng.randint(0, 255, (4, 8, 16, 3), dtype=np.uint8)
        mean = np.array([0.485, 0.456, 0.406], np.float32)
        std = np.array([0.229, 0.224, 0.225], np.float32)
        got = np.asarray(normalize_images(jnp.asarray(x), mean, std,
                                          out_dtype=jnp.float32,
                                          interpret=True))
        np.testing.assert_allclose(got, self._ref(x, mean, std, np.float32),
                                   atol=1e-5)

    def test_fallback_path_matches(self):
        from petastorm_tpu.ops import normalize_images
        rng = np.random.RandomState(1)
        x = rng.randint(0, 255, (2, 4, 4, 3), dtype=np.uint8)
        mean = np.full(3, 0.5, np.float32)
        std = np.full(3, 0.25, np.float32)
        got = np.asarray(normalize_images(jnp.asarray(x), mean, std,
                                          out_dtype=jnp.float32))
        np.testing.assert_allclose(got, self._ref(x, mean, std, np.float32),
                                   atol=1e-5)


class TestTransformer:
    @pytest.mark.slow
    def test_forward_shapes(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_forward,
        )
        config = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=8)
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        tokens = jnp.zeros((2, 8), jnp.int32)
        logits = transformer_forward(params, tokens, config)
        assert logits.shape == (2, 8, 32)
        assert logits.dtype == jnp.float32

    @pytest.mark.slow
    def test_train_step_reduces_loss_on_memorizable_batch(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_train_step,
        )
        config = TransformerConfig(vocab_size=16, d_model=32, n_heads=2,
                                   n_layers=1, d_ff=64, max_seq_len=8,
                                   dtype=jnp.float32)
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        step = transformer_train_step(config, optimizer)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 16, (4, 8), np.int32))
        first = None
        for _ in range(12):
            params, opt_state, loss = step(params, opt_state, tokens)
            first = float(loss) if first is None else first
        assert float(loss) < first

    @pytest.mark.slow
    def test_sharded_train_step_on_mesh(self):
        from jax.sharding import NamedSharding, PartitionSpec
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_train_step,
        )
        config = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=8)
        mesh = make_mesh(data=4, model=2)
        with mesh:
            params = init_transformer_params(jax.random.PRNGKey(0), config,
                                             mesh=mesh)
            # tp layout landed as requested
            assert params['blocks'][0]['qkv'].sharding.spec == \
                PartitionSpec(None, 'model')
            optimizer = optax.adamw(1e-3)
            opt_state = optimizer.init(params)
            step = transformer_train_step(config, optimizer)
            tokens = jax.device_put(
                jnp.zeros((8, 8), jnp.int32),
                NamedSharding(mesh, PartitionSpec('data', None)))
            params2, _, loss = step(params, opt_state, tokens)
        assert np.isfinite(float(loss))
        # params keep their tp sharding across the update
        assert params2['blocks'][0]['qkv'].sharding.spec == \
            PartitionSpec(None, 'model')


class TestAugmentOps:
    def _images(self, b=4, h=8, w=10, c=3):
        rng = np.random.RandomState(0)
        return jnp.asarray(rng.randint(0, 255, (b, h, w, c), np.uint8))

    def test_flip_is_per_image_and_exact(self):
        from petastorm_tpu.ops import random_flip_horizontal
        images = self._images()
        out = np.asarray(random_flip_horizontal(jax.random.PRNGKey(0),
                                                images, p=0.5))
        src = np.asarray(images)
        flipped = rigid = 0
        for i in range(4):
            if np.array_equal(out[i], src[i, :, ::-1]):
                flipped += 1
            elif np.array_equal(out[i], src[i]):
                rigid += 1
        assert flipped + rigid == 4, 'each image either flips or not'
        # p=1 flips everything; p=0 nothing
        all_f = np.asarray(random_flip_horizontal(jax.random.PRNGKey(1),
                                                  images, p=1.0))
        np.testing.assert_array_equal(all_f, src[:, :, ::-1])
        none = np.asarray(random_flip_horizontal(jax.random.PRNGKey(1),
                                                 images, p=0.0))
        np.testing.assert_array_equal(none, src)

    def test_crop_windows_match_source(self):
        from petastorm_tpu.ops import random_crop
        images = self._images()
        out = np.asarray(random_crop(jax.random.PRNGKey(0), images, 5, 6))
        assert out.shape == (4, 5, 6, 3)
        src = np.asarray(images)
        for i in range(4):
            # the crop must appear somewhere in the source image
            found = any(
                np.array_equal(out[i], src[i, y:y + 5, x:x + 6])
                for y in range(4) for x in range(5))
            assert found, 'crop %d is not a window of its source' % i

    def test_crop_too_large_rejected(self):
        from petastorm_tpu.ops import random_crop
        with pytest.raises(ValueError, match='exceeds'):
            random_crop(jax.random.PRNGKey(0), self._images(), 9, 6)

    def test_cutout_zeroes_one_square(self):
        from petastorm_tpu.ops import random_cutout
        images = jnp.ones((3, 8, 8, 3), jnp.uint8) * 7
        out = np.asarray(random_cutout(jax.random.PRNGKey(0), images, 4))
        for i in range(3):
            zeros = (out[i] == 0).all(axis=-1)
            assert zeros.sum() == 16, 'exactly one 4x4 square'
            ys, xs = np.where(zeros)
            assert ys.max() - ys.min() == 3 and xs.max() - xs.min() == 3

    def test_jit_and_determinism(self):
        from petastorm_tpu.ops import (
            random_crop, random_cutout, random_flip_horizontal,
        )
        images = self._images()
        key = jax.random.PRNGKey(9)

        def pipeline(k, im):
            im = random_flip_horizontal(k, im)
            im = random_crop(jax.random.fold_in(k, 1), im, 6, 6)
            return random_cutout(jax.random.fold_in(k, 2), im, 2)

        eager = np.asarray(pipeline(key, images))
        jitted = np.asarray(jax.jit(pipeline)(key, images))
        np.testing.assert_array_equal(eager, jitted)


class TestViT:
    def _config(self, **kw):
        from petastorm_tpu.models.vit import ViTConfig
        base = dict(image_size=16, patch_size=4, n_classes=8, d_model=32,
                    n_heads=2, n_layers=1, d_ff=64, dtype=jnp.float32)
        base.update(kw)
        return ViTConfig(**base)

    def test_patchify_preserves_pixels(self):
        from petastorm_tpu.models.vit import _patchify
        config = self._config()
        images = jnp.asarray(np.arange(2 * 16 * 16 * 3, dtype=np.float32)
                             .reshape(2, 16, 16, 3))
        patches = np.asarray(_patchify(images, config))
        assert patches.shape == (2, 16, 48)
        # patch (row 0, col 1) = pixels [0:4, 4:8]
        want = np.asarray(images)[0, 0:4, 4:8, :].reshape(-1)
        np.testing.assert_array_equal(patches[0, 1], want)

    @pytest.mark.slow
    def test_forward_shapes(self):
        from petastorm_tpu.models.vit import init_vit_params, vit_forward
        config = self._config()
        params = init_vit_params(jax.random.PRNGKey(0), config)
        images = jnp.zeros((2, 16, 16, 3), jnp.float32)
        logits = vit_forward(params, images, config)
        assert logits.shape == (2, 8)
        assert logits.dtype == jnp.float32

    def test_blocks_are_bidirectional_only_for_vit(self):
        # position 0's output must SEE the last position under the ViT's
        # causal=False blocks, and must NOT under the LM's causal default
        from petastorm_tpu.models.transformer import (
            TransformerConfig, _block_forward, init_transformer_params,
        )
        cfg = TransformerConfig(vocab_size=8, d_model=16, n_heads=2,
                                n_layers=1, d_ff=32, max_seq_len=4,
                                dtype=jnp.float32)
        block = init_transformer_params(jax.random.PRNGKey(0),
                                        cfg)['blocks'][0]
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.rand(1, 4, 16).astype(np.float32))
        x2 = x.at[0, -1].add(1.0)
        bi1 = np.asarray(_block_forward(block, x, cfg, causal=False))
        bi2 = np.asarray(_block_forward(block, x2, cfg, causal=False))
        assert not np.allclose(bi1[0, 0], bi2[0, 0])
        ca1 = np.asarray(_block_forward(block, x, cfg, causal=True))
        ca2 = np.asarray(_block_forward(block, x2, cfg, causal=True))
        np.testing.assert_allclose(ca1[0, 0], ca2[0, 0], atol=1e-6)

    def test_flash_bidirectional_matches_dense(self):
        # the fused kernel runs bidirectional too; off-TPU (and below the
        # 128 block) it takes the exact dense fallback — same math as the
        # inline dense path up to scale-application order (x*scale vs
        # x/sqrt differ in the last ulp), so tight allclose, not bitwise
        import dataclasses
        from petastorm_tpu.models.transformer import (
            TransformerConfig, _block_forward, init_transformer_params,
        )
        cfg = TransformerConfig(vocab_size=8, d_model=16, n_heads=2,
                                n_layers=1, d_ff=32, max_seq_len=4,
                                dtype=jnp.float32, attn_impl='flash')
        block = init_transformer_params(jax.random.PRNGKey(0),
                                        cfg)['blocks'][0]
        x = jnp.asarray(np.random.RandomState(0).randn(1, 4, 16),
                        jnp.float32)
        got = _block_forward(block, x, cfg, causal=False)
        dense_cfg = dataclasses.replace(cfg, attn_impl='dense')
        want = _block_forward(block, x, dense_cfg, causal=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.slow
    def test_vit_flash_config_matches_dense(self):
        from petastorm_tpu.models.vit import init_vit_params, vit_forward
        dense_c = self._config()
        flash_c = self._config(attn_impl='flash')
        params = init_vit_params(jax.random.PRNGKey(0), dense_c)
        images = jnp.asarray(
            np.random.RandomState(0).rand(2, dense_c.image_size,
                                          dense_c.image_size, 3),
            jnp.float32)
        want = vit_forward(params, images, dense_c)
        got = vit_forward(params, images, flash_c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)

    def test_bad_patch_size_rejected(self):
        with pytest.raises(ValueError, match='divisible'):
            self._config(image_size=16, patch_size=5)

    @pytest.mark.slow
    def test_train_step_learns_memorizable_batch(self):
        from petastorm_tpu.models.vit import (
            init_vit_params, vit_train_step,
        )
        config = self._config()
        params = init_vit_params(jax.random.PRNGKey(0), config)
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        step = vit_train_step(config, optimizer)
        rng = np.random.RandomState(0)
        images = jnp.asarray(rng.rand(4, 16, 16, 3).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, 8, (4,), np.int32))
        first = None
        for _ in range(15):
            params, opt_state, loss = step(params, opt_state, images,
                                           labels)
            first = float(loss) if first is None else first
        assert float(loss) < first

    @pytest.mark.slow
    def test_sharded_logits_match_unsharded(self):
        # dp×tp mesh: the blocks reuse the LM transformer's Megatron
        # specs; sharded logits must equal the single-device oracle
        from petastorm_tpu.models.vit import init_vit_params, vit_forward
        from petastorm_tpu.parallel.mesh import make_mesh
        config = self._config(n_layers=2)
        mesh = make_mesh(data=2, model=2,
                         devices=jax.devices()[:4])
        rng = np.random.RandomState(1)
        images = jnp.asarray(rng.rand(4, 16, 16, 3).astype(np.float32))
        with mesh:
            params = init_vit_params(jax.random.PRNGKey(0), config,
                                     mesh=mesh)
            got = jax.jit(lambda p, im: vit_forward(p, im, config))(
                params, images)
        host_params = jax.tree_util.tree_map(
            lambda x: jnp.asarray(np.asarray(x)), params)
        want = vit_forward(host_params, images, config)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4, rtol=2e-4)


class TestGQA:
    """Grouped-query attention: training math is exactly MHA with the
    shared K/V heads repeated per query group."""

    @pytest.mark.slow
    def test_gqa_equals_expanded_mha_forward_exactly(self):
        import dataclasses
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_forward,
        )
        c_gqa = TransformerConfig(vocab_size=32, d_model=16, n_heads=4,
                                  n_kv_heads=2, n_layers=2, d_ff=32,
                                  max_seq_len=12, dtype=jnp.float32)
        params = init_transformer_params(jax.random.PRNGKey(3), c_gqa)
        # expand the GQA weights into a full-MHA parameter set: each K/V
        # head's projection columns repeated across its query group
        n, kv = c_gqa.n_heads, 2
        hd = c_gqa.d_model // n
        c_mha = dataclasses.replace(c_gqa, n_kv_heads=None)
        mha_params = jax.tree_util.tree_map(lambda x: x, params)
        for block in mha_params['blocks']:
            qkv = block['qkv']
            q_w = qkv[:, :n * hd]
            k_w = qkv[:, n * hd:(n + kv) * hd]
            v_w = qkv[:, (n + kv) * hd:]

            def expand(w):
                d = w.shape[0]
                return jnp.repeat(w.reshape(d, kv, hd), n // kv,
                                  axis=1).reshape(d, n * hd)

            block['qkv'] = jnp.concatenate(
                [q_w, expand(k_w), expand(v_w)], axis=1)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 32, (2, 12), np.int32))
        got = transformer_forward(params, tokens, c_gqa)
        want = transformer_forward(mha_params, tokens, c_mha)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.slow
    def test_gqa_train_step_learns(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_train_step,
        )
        config = TransformerConfig(vocab_size=16, d_model=32, n_heads=4,
                                   n_kv_heads=1, n_layers=1, d_ff=64,
                                   max_seq_len=8, dtype=jnp.float32)
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        step = transformer_train_step(config, optimizer)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 16, (4, 8), np.int32))
        first = None
        for _ in range(12):
            params, opt_state, loss = step(params, opt_state, tokens)
            first = float(loss) if first is None else first
        assert float(loss) < first

    def test_default_is_full_mha(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params,
        )
        config = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=8)
        assert config.kv_heads == config.n_heads
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        # the classic fused third-split width
        assert params['blocks'][0]['qkv'].shape == (16, 48)

    def test_invalid_kv_heads_rejected(self):
        from petastorm_tpu.models.transformer import TransformerConfig
        with pytest.raises(ValueError, match='multiple'):
            TransformerConfig(n_heads=4, n_kv_heads=3)
        with pytest.raises(ValueError, match='n_kv_heads'):
            TransformerConfig(n_heads=4, n_kv_heads=5)
        with pytest.raises(ValueError, match='n_kv_heads'):
            TransformerConfig(n_heads=4, n_kv_heads=0)


class TestRoPE:
    """Rotary position encoding: table-free positions rotated into q/k."""

    def test_rope_params_have_no_pos_embed(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params,
        )
        config = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=8,
                                   pos_encoding='rope')
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        assert 'pos_embed' not in params
        assert params['blocks'][0]['qkv'].shape == (16, 48)

    @pytest.mark.slow
    def test_rope_train_step_learns(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_train_step,
        )
        config = TransformerConfig(vocab_size=16, d_model=32, n_heads=2,
                                   n_layers=1, d_ff=64, max_seq_len=8,
                                   dtype=jnp.float32, pos_encoding='rope')
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        step = transformer_train_step(config, optimizer)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 16, (4, 8), np.int32))
        first = None
        for _ in range(12):
            params, opt_state, loss = step(params, opt_state, tokens)
            first = float(loss) if first is None else first
        assert float(loss) < first

    def test_rope_scores_depend_on_relative_position_only(self):
        # the defining rope property, tested at the rotation itself:
        # <rot(q, p1), rot(k, p2)> == <rot(q, p1+Δ), rot(k, p2+Δ)> —
        # attention scores see only position DIFFERENCES
        from petastorm_tpu.models.transformer import _rope_rotate
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, 1, 2, 8), jnp.float32)
        k = jnp.asarray(rng.randn(1, 1, 2, 8), jnp.float32)

        def score(p_q, p_k):
            rq = _rope_rotate(q, jnp.asarray([p_q], jnp.int32), 10000.0)
            rk = _rope_rotate(k, jnp.asarray([p_k], jnp.int32), 10000.0)
            return np.asarray(jnp.einsum('bshd,bshd->bsh', rq, rk))

        base = score(3, 7)
        for delta in (1, 11, 100):
            np.testing.assert_allclose(score(3 + delta, 7 + delta), base,
                                       atol=1e-4, rtol=1e-4)
        # and it must NOT be position-blind: an unequal shift changes it
        assert not np.allclose(score(3, 8), base, atol=1e-4)

    def test_rope_validation(self):
        from petastorm_tpu.models.transformer import TransformerConfig
        with pytest.raises(ValueError, match='pos_encoding'):
            TransformerConfig(pos_encoding='alibi')
        with pytest.raises(ValueError, match='even head_dim'):
            TransformerConfig(d_model=12, n_heads=4, pos_encoding='rope')


class TestSwiGLU:
    """Gated FFN variant: silu(x@W_gate) * (x@W_in) @ W_out."""

    def test_swiglu_params_and_validation(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params,
        )
        config = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=8,
                                   ffn='swiglu')
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        block = params['blocks'][0]
        assert block['mlp_gate'].shape == (16, 32)
        assert block['mlp_in'].shape == (16, 32)
        with pytest.raises(ValueError, match='ffn'):
            TransformerConfig(ffn='relu')
        with pytest.raises(ValueError, match='dense blocks only'):
            TransformerConfig(ffn='swiglu', n_experts=4)

    @pytest.mark.slow
    def test_swiglu_ffn_matches_hand_oracle(self):
        # the FFN sublayer against a straight numpy re-derivation
        from petastorm_tpu.models.transformer import (
            TransformerConfig, _block_dense_ffn_half, _rmsnorm,
            init_transformer_params,
        )
        config = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=8,
                                   dtype=jnp.float32, ffn='swiglu')
        params = init_transformer_params(jax.random.PRNGKey(1), config)
        block = params['blocks'][0]
        x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16),
                        jnp.float32)
        got = _block_dense_ffn_half(block, x, config)

        h = np.asarray(_rmsnorm(x, block['ln2']))
        gate = h @ np.asarray(block['mlp_gate'])
        up = h @ np.asarray(block['mlp_in'])
        silu = gate / (1.0 + np.exp(-gate))
        want = np.asarray(x) + (silu * up) @ np.asarray(block['mlp_out'])
        np.testing.assert_allclose(np.asarray(got), want,
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.slow
    def test_swiglu_train_step_learns(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_train_step,
        )
        config = TransformerConfig(vocab_size=16, d_model=32, n_heads=2,
                                   n_layers=1, d_ff=64, max_seq_len=8,
                                   dtype=jnp.float32, ffn='swiglu',
                                   pos_encoding='rope')
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        step = transformer_train_step(config, optimizer)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 16, (4, 8), np.int32))
        first = None
        for _ in range(12):
            params, opt_state, loss = step(params, opt_state, tokens)
            first = float(loss) if first is None else first
        assert float(loss) < first

    @pytest.mark.slow
    def test_swiglu_pipelined_matches_layered(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_pipelined_transformer_params,
            pipelined_transformer_forward, transformer_forward,
        )
        from petastorm_tpu.parallel.mesh import make_named_mesh
        mesh = make_named_mesh({'pipe': 2}, devices=jax.devices()[:2])
        config = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                   n_layers=2, d_ff=32, max_seq_len=8,
                                   dtype=jnp.float32, ffn='swiglu')
        with mesh:
            pipelined = init_pipelined_transformer_params(
                jax.random.PRNGKey(0), config, mesh)
            tokens = jnp.asarray(np.random.RandomState(0)
                                 .randint(0, 32, (4, 8), np.int32))
            got = jax.jit(lambda p, t: pipelined_transformer_forward(
                p, t, config, mesh, n_microbatches=2))(pipelined, tokens)
        stages = pipelined['stages']
        blocks = []
        for s in range(2):
            for l in range(1):
                blocks.append(jax.tree_util.tree_map(
                    lambda leaf: jnp.asarray(leaf[s, l]), stages))
        layered = {name: jnp.asarray(pipelined[name])
                   for name in ('embed', 'pos_embed', 'ln_f', 'lm_head')}
        layered['blocks'] = blocks
        want = transformer_forward(layered, tokens, config)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4, rtol=2e-4)


class TestRematAndAccum:
    """Memory levers: block rematerialization and gradient accumulation —
    both must be pure memory/time trades, never numerics changes."""

    @pytest.mark.slow
    def test_remat_loss_and_grads_match_exactly(self):
        import dataclasses
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_loss,
        )
        config = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                   n_layers=2, d_ff=32, max_seq_len=8,
                                   dtype=jnp.float32)
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 32, (4, 8), np.int32))
        loss, grads = jax.value_and_grad(transformer_loss)(
            params, tokens, config)
        r_config = dataclasses.replace(config, remat=True)
        r_loss, r_grads = jax.value_and_grad(transformer_loss)(
            params, tokens, r_config)
        np.testing.assert_allclose(float(loss), float(r_loss), rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-5),
            grads, r_grads)

    @pytest.mark.slow
    def test_remat_pipelined_forward_matches(self):
        import dataclasses
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_pipelined_transformer_params,
            pipelined_transformer_forward,
        )
        from petastorm_tpu.parallel.mesh import make_named_mesh
        mesh = make_named_mesh({'pipe': 2}, devices=jax.devices()[:2])
        config = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                   n_layers=2, d_ff=32, max_seq_len=8,
                                   dtype=jnp.float32)
        with mesh:
            pipelined = init_pipelined_transformer_params(
                jax.random.PRNGKey(0), config, mesh)
            tokens = jnp.asarray(np.random.RandomState(0)
                                 .randint(0, 32, (4, 8), np.int32))
            plain = jax.jit(lambda p, t: pipelined_transformer_forward(
                p, t, config, mesh, n_microbatches=2))(pipelined, tokens)
            r_config = dataclasses.replace(config, remat=True)
            remat = jax.jit(lambda p, t: pipelined_transformer_forward(
                p, t, r_config, mesh, n_microbatches=2))(pipelined, tokens)
        np.testing.assert_allclose(np.asarray(plain), np.asarray(remat),
                                   atol=1e-6, rtol=1e-5)

    @pytest.mark.slow
    def test_accum_matches_full_batch_update(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_train_step,
        )
        config = TransformerConfig(vocab_size=16, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=8,
                                   dtype=jnp.float32)
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        optimizer = optax.sgd(1e-2)  # stateless update: exact comparison
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 16, (8, 8), np.int32))
        full = transformer_train_step(config, optimizer)
        accum = transformer_train_step(config, optimizer, accum_steps=4)
        p_full, _, l_full = full(params, optimizer.init(params), tokens)
        p_acc, _, l_acc = accum(params, optimizer.init(params), tokens)
        np.testing.assert_allclose(float(l_full), float(l_acc), rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-5),
            p_full, p_acc)

    @pytest.mark.slow
    def test_accum_indivisible_batch_rejected(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_train_step,
        )
        config = TransformerConfig(vocab_size=16, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=8,
                                   dtype=jnp.float32)
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        optimizer = optax.sgd(1e-2)
        step = transformer_train_step(config, optimizer, accum_steps=3)
        tokens = jnp.zeros((4, 8), jnp.int32)
        with pytest.raises(ValueError, match='divisible'):
            step(params, optimizer.init(params), tokens)


class TestChunkedLoss:
    def _setup(self, **kw):
        import dataclasses

        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params,
        )
        base = dict(vocab_size=32, d_model=16, n_heads=2, n_layers=1,
                    d_ff=32, max_seq_len=9, dtype=jnp.float32)
        base.update(kw)
        config = TransformerConfig(**base)
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        return config, params, dataclasses

    @pytest.mark.slow
    @pytest.mark.parametrize('chunk', [4, 3])  # 3 does not divide S-1=8
    def test_chunked_equals_dense_loss_and_grads(self, chunk):
        from petastorm_tpu.models.transformer import transformer_loss
        config, params, dataclasses = self._setup()
        chunked_cfg = dataclasses.replace(config, loss_chunk=chunk)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 32, (4, 9), np.int32))
        dense, dense_grads = jax.value_and_grad(transformer_loss)(
            params, tokens, config)
        ck, ck_grads = jax.value_and_grad(transformer_loss)(
            params, tokens, chunked_cfg)
        np.testing.assert_allclose(float(ck), float(dense), rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4),
            ck_grads, dense_grads)

    @pytest.mark.slow
    def test_chunked_masked_loss_matches(self):
        from petastorm_tpu.models.transformer import (
            transformer_masked_loss,
        )
        config, params, dataclasses = self._setup()
        chunked_cfg = dataclasses.replace(config, loss_chunk=4)
        tokens = jnp.asarray(
            np.random.RandomState(1).randint(0, 32, (4, 9), np.int32))
        lengths = jnp.asarray([3, 9, 6, 1], jnp.int32)
        dense = float(transformer_masked_loss(params, tokens, lengths,
                                              config))
        ck = float(transformer_masked_loss(params, tokens, lengths,
                                           chunked_cfg))
        np.testing.assert_allclose(ck, dense, rtol=1e-5)

    @pytest.mark.slow
    def test_pipelined_step_honors_loss_chunk(self):
        # same weights, pipelined train step with and without loss_chunk:
        # identical loss and updated params (the chunked path is exact)
        import dataclasses

        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_pipelined_transformer_params,
            pipelined_transformer_train_step,
        )
        from petastorm_tpu.parallel.mesh import make_named_mesh
        mesh = make_named_mesh({'pipe': 2}, devices=jax.devices()[:2])
        config = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                   n_layers=2, d_ff=32, max_seq_len=9,
                                   dtype=jnp.float32)
        chunked_cfg = dataclasses.replace(config, loss_chunk=3)
        tokens = jnp.asarray(
            np.random.RandomState(3).randint(0, 32, (4, 9), np.int32))
        results = []
        for cfg in (config, chunked_cfg):
            with mesh:
                params = init_pipelined_transformer_params(
                    jax.random.PRNGKey(0), cfg, mesh)
                opt = optax.adamw(1e-3)
                step = pipelined_transformer_train_step(
                    cfg, opt, mesh, n_microbatches=2)
                p2, _, loss = step(params, opt.init(params), tokens)
            results.append((float(loss), np.asarray(p2['lm_head'])))
        np.testing.assert_allclose(results[1][0], results[0][0], rtol=1e-5)
        np.testing.assert_allclose(results[1][1], results[0][1],
                                   atol=1e-5, rtol=1e-4)

    @pytest.mark.slow
    def test_chunked_moe_loss_matches(self):
        from petastorm_tpu.models.transformer import transformer_loss
        config, params, dataclasses = self._setup(n_experts=4,
                                                  capacity_factor=8.0)
        chunked_cfg = dataclasses.replace(config, loss_chunk=4)
        tokens = jnp.asarray(
            np.random.RandomState(2).randint(0, 32, (4, 9), np.int32))
        dense = float(transformer_loss(params, tokens, config))
        ck = float(transformer_loss(params, tokens, chunked_cfg))
        np.testing.assert_allclose(ck, dense, rtol=1e-5)


class TestMaskedLoss:
    def _setup(self, seq=8):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params,
        )
        config = TransformerConfig(vocab_size=16, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=seq,
                                   dtype=jnp.float32)
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        return config, params

    @pytest.mark.slow
    def test_full_lengths_match_dense_loss(self):
        from petastorm_tpu.models.transformer import (
            transformer_loss, transformer_masked_loss,
        )
        config, params = self._setup()
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 16, (4, 8), np.int32))
        lengths = jnp.full((4,), 8, jnp.int32)
        dense = float(transformer_loss(params, tokens, config))
        masked = float(transformer_masked_loss(params, tokens, lengths,
                                               config))
        np.testing.assert_allclose(masked, dense, rtol=1e-6)
        # truncated-row lengths (> S, the pad_ragged contract) saturate
        over = float(transformer_masked_loss(
            params, tokens, jnp.full((4,), 100, jnp.int32), config))
        np.testing.assert_allclose(over, dense, rtol=1e-6)

    @pytest.mark.slow
    def test_pad_region_values_do_not_change_loss(self):
        # causal attention: real positions never see later (padding)
        # positions, and padded targets are masked out — so the loss must
        # be invariant to whatever values sit in the pad region
        from petastorm_tpu.models.transformer import transformer_masked_loss
        config, params = self._setup()
        rng = np.random.RandomState(1)
        tokens = rng.randint(0, 16, (4, 8), np.int32)
        lengths = jnp.asarray([3, 5, 8, 2], jnp.int32)
        a = float(transformer_masked_loss(params, jnp.asarray(tokens),
                                          lengths, config))
        scrambled = tokens.copy()
        for i, l in enumerate([3, 5, 8, 2]):
            scrambled[i, l:] = rng.randint(0, 16, max(0, 8 - l))
        b = float(transformer_masked_loss(params, jnp.asarray(scrambled),
                                          lengths, config))
        np.testing.assert_allclose(a, b, rtol=1e-6)

    @pytest.mark.slow
    def test_matches_manual_per_row_average(self):
        # the loss equals the hand-computed masked mean over real targets
        from petastorm_tpu.models.transformer import (
            transformer_forward, transformer_masked_loss,
        )
        config, params = self._setup()
        tokens = jnp.asarray(
            np.random.RandomState(2).randint(0, 16, (3, 8), np.int32))
        lengths = np.asarray([4, 8, 1], np.int32)
        logits = transformer_forward(params, tokens[:, :-1], config)
        logp = np.asarray(jax.nn.log_softmax(logits, axis=-1))
        total, count = 0.0, 0
        for i, l in enumerate(lengths):
            for pos in range(7):
                if pos + 1 < l:
                    total -= logp[i, pos, int(tokens[i, pos + 1])]
                    count += 1
        want = total / count
        got = float(transformer_masked_loss(params, tokens,
                                            jnp.asarray(lengths), config))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_moe_config_rejected(self):
        # the Switch aux would include padding positions; dense-only
        from petastorm_tpu.models.transformer import (
            TransformerConfig, transformer_masked_loss,
        )
        config = TransformerConfig(vocab_size=16, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=8,
                                   n_experts=4, dtype=jnp.float32)
        with pytest.raises(NotImplementedError, match='dense configs'):
            transformer_masked_loss(None, jnp.zeros((2, 8), jnp.int32),
                                    jnp.ones((2,), jnp.int32), config)

    @pytest.mark.slow
    def test_masked_train_step_learns(self):
        from petastorm_tpu.models.transformer import (
            transformer_masked_train_step,
        )
        config, params = self._setup()
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        step = transformer_masked_train_step(config, optimizer)
        rng = np.random.RandomState(3)
        tokens = jnp.asarray(rng.randint(0, 16, (4, 8), np.int32))
        lengths = jnp.asarray([5, 8, 6, 3], jnp.int32)
        first = None
        for _ in range(12):
            params, opt_state, loss = step(params, opt_state, tokens,
                                           lengths)
            first = float(loss) if first is None else first
        assert float(loss) < first


class TestMoETransformer:
    @pytest.mark.slow
    def test_moe_train_step_on_data_expert_mesh(self):
        # full expert-parallel train step: experts sharded over 'expert',
        # batch over 'data'; loss finite and expert weights stay sharded
        from jax.sharding import NamedSharding, PartitionSpec
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_train_step,
        )
        from petastorm_tpu.parallel.mesh import make_named_mesh
        config = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                                   n_layers=2, d_ff=32, max_seq_len=8,
                                   n_experts=4)
        mesh = make_named_mesh({'data': 2, 'expert': 4})
        with mesh:
            params = init_transformer_params(jax.random.PRNGKey(0), config,
                                             mesh=mesh)
            assert params['blocks'][0]['moe']['w_in'].sharding.spec[0] == \
                'expert'
            optimizer = optax.adamw(1e-3)
            opt_state = optimizer.init(params)
            step = transformer_train_step(config, optimizer)
            tokens = jax.device_put(
                jnp.zeros((8, 8), jnp.int32),
                NamedSharding(mesh, PartitionSpec('data', None)))
            params2, _, loss = step(params, opt_state, tokens)
        assert np.isfinite(float(loss))
        assert params2['blocks'][0]['moe']['w_in'].sharding.spec[0] == \
            'expert'

    @pytest.mark.slow
    def test_moe_model_learns(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_train_step,
        )
        config = TransformerConfig(vocab_size=16, d_model=32, n_heads=2,
                                   n_layers=1, d_ff=64, max_seq_len=8,
                                   n_experts=2, dtype=jnp.float32)
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        step = transformer_train_step(config, optimizer)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 16, (4, 8), np.int32))
        first = None
        for _ in range(12):
            params, opt_state, loss = step(params, opt_state, tokens)
            first = float(loss) if first is None else first
        assert float(loss) < first

    def test_dense_config_has_no_moe_params(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params,
        )
        config = TransformerConfig(vocab_size=16, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=8)
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        assert 'moe' not in params['blocks'][0]
        assert 'mlp_in' in params['blocks'][0]


class TestSequenceParallelTransformer:
    def _config(self, **kw):
        from petastorm_tpu.models.transformer import TransformerConfig
        base = dict(vocab_size=32, d_model=16, n_heads=2, n_layers=2,
                    d_ff=32, max_seq_len=16, dtype=jnp.float32)
        base.update(kw)
        return TransformerConfig(**base)

    @pytest.mark.parametrize('seq_impl', ['ring', 'ulysses'])
    def test_seq_parallel_logits_match_dense(self, seq_impl):
        # activations stay sequence-sharded through every block and
        # attention runs the chosen collective — the logits must be
        # identical to the unsharded model (sharding is layout, not
        # semantics), for BOTH strategies
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        from petastorm_tpu.models.transformer import (
            init_transformer_params, transformer_forward,
        )
        # ulysses needs heads divisible by the 8-way mesh; ring must keep
        # working with FEWER heads than devices (its distinguishing
        # capability), so only the ulysses case overrides n_heads
        n_heads = 8 if seq_impl == 'ulysses' else 2
        dense_config = self._config(n_heads=n_heads)
        sp_config = self._config(seq_axis='seq', seq_impl=seq_impl,
                                 n_heads=n_heads)
        params = init_transformer_params(jax.random.PRNGKey(0), dense_config)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 32, (2, 16), np.int32))
        want = transformer_forward(params, tokens, dense_config)

        mesh = Mesh(np.asarray(jax.devices()), ('seq',))
        tokens_sharded = jax.device_put(
            tokens, NamedSharding(mesh, PartitionSpec(None, 'seq')))
        with mesh:
            got = jax.jit(lambda p, t: transformer_forward(
                p, t, sp_config, mesh=mesh))(params, tokens_sharded)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-4, rtol=2e-4)

    @pytest.mark.slow
    def test_seq_parallel_train_step_on_data_seq_mesh(self):
        # combined dp x sp: batch sharded over 'data', sequence over 'seq'
        from jax.sharding import NamedSharding, PartitionSpec
        from petastorm_tpu.models.transformer import (
            init_transformer_params, transformer_train_step,
        )
        from petastorm_tpu.parallel.mesh import make_named_mesh
        config = self._config(seq_axis='seq')
        mesh = make_named_mesh({'data': 2, 'seq': 4})
        with mesh:
            params = init_transformer_params(jax.random.PRNGKey(0), config,
                                             mesh=mesh)
            optimizer = optax.adam(1e-2)
            opt_state = optimizer.init(params)
            step = transformer_train_step(config, optimizer, mesh=mesh)
            tokens = jax.device_put(
                jnp.asarray(np.random.RandomState(1)
                            .randint(0, 32, (4, 17), np.int32)),
                NamedSharding(mesh, PartitionSpec('data', None)))
            first = None
            for _ in range(6):
                params, opt_state, loss = step(params, opt_state, tokens)
                first = float(loss) if first is None else first
        assert np.isfinite(float(loss))
        assert float(loss) < first

    def test_layered_seq_parallel_moe_matches_unsharded(self):
        """Layered sp×ep / dp×sp×ep: the MoE forward under auto sharding
        with a seq-sharded sequence must equal the unsharded oracle —
        routing semantics are global (XLA partitions the dispatch; the
        gather-free router keeps the partitioner happy)."""
        import dataclasses

        from jax.sharding import NamedSharding, PartitionSpec
        from petastorm_tpu.models.transformer import (
            init_transformer_params, transformer_forward_with_aux,
        )
        from petastorm_tpu.parallel.mesh import make_named_mesh
        for axes in ({'seq': 4, 'expert': 2},
                     {'data': 2, 'seq': 2, 'expert': 2}):
            mesh = make_named_mesh(dict(axes))
            config = self._config(seq_axis='seq', n_heads=4, n_experts=4,
                                  capacity_factor=8.0)
            with mesh:
                params = init_transformer_params(jax.random.PRNGKey(0),
                                                 config, mesh=mesh)
                tokens = jax.device_put(
                    jnp.asarray(np.random.RandomState(1)
                                .randint(0, 32, (4, 16), np.int32)),
                    NamedSharding(mesh, PartitionSpec(
                        'data' if 'data' in axes else None, None)))
                logits, aux = jax.jit(
                    lambda p, t: transformer_forward_with_aux(
                        p, t, config, mesh))(params, tokens)
            host = jax.tree_util.tree_map(
                lambda leaf: jnp.asarray(np.asarray(leaf)), params)
            want, want_aux = transformer_forward_with_aux(
                host, jnp.asarray(np.asarray(tokens)),
                dataclasses.replace(config, seq_axis=None))
            np.testing.assert_allclose(np.asarray(logits),
                                       np.asarray(want),
                                       atol=2e-4, rtol=2e-4)
            np.testing.assert_allclose(float(aux), float(want_aux),
                                       rtol=1e-4)

    def test_invalid_seq_impl_rejected_at_construction(self):
        # a typo'd strategy must fail at config time, even when seq_axis
        # is unset (it would otherwise silently train dense)
        with pytest.raises(ValueError, match="'ring' or 'ulysses'"):
            self._config(seq_impl='ulises')

    def test_seq_axis_without_mesh_raises(self):
        from petastorm_tpu.models.transformer import (
            init_transformer_params, transformer_forward,
        )
        config = self._config(seq_axis='seq')
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        with pytest.raises(ValueError, match='needs the mesh'):
            transformer_forward(params, jnp.zeros((2, 16), jnp.int32), config)


class TestMnist:
    @pytest.mark.slow
    def test_train_step_learns(self, synthetic_dataset):
        """End-to-end: Parquet images → JaxLoader → CNN step (tiny)."""
        from petastorm_tpu.jax import make_jax_loader
        from petastorm_tpu.models.mnist import MnistCNN, mnist_train_step
        from petastorm_tpu.transform import TransformSpec
        from petastorm_tpu.unischema import UnischemaField

        def to_mnist(frame):
            # use the synthetic 16x32x3 pngs as stand-in digits
            frame['image'] = frame['image_png'].map(
                lambda im: np.asarray(im, np.float32).mean(axis=-1,
                                                           keepdims=True)[:16, :16] / 255.0)
            frame['digit'] = frame['id'] % 10
            return frame[['image', 'digit']]

        spec = TransformSpec(
            to_mnist,
            edit_fields=[UnischemaField('image', np.float32, (16, 16, 1)),
                         UnischemaField('digit', np.int64, ())],
            selected_fields=['image', 'digit'])

        import optax as _optax
        model = MnistCNN()
        with make_jax_loader(synthetic_dataset.url, batch_size=16,
                             fields=['^id$', '^image_png$'],
                             transform_spec=spec,
                             shuffle_row_groups=False) as loader:
            batch = next(iter(loader))
            params = model.init(jax.random.PRNGKey(0), batch['image'])
            optimizer = _optax.sgd(0.05)
            opt_state = optimizer.init(params)
            step = jax.jit(mnist_train_step(model, optimizer))
            p, o, loss = step(params, opt_state, batch['image'],
                              batch['digit'])
        assert np.isfinite(float(loss))


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as g
        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (8, 10)

    @pytest.mark.slow
    def test_dryrun_multichip(self, capsys):
        import __graft_entry__ as g
        g.dryrun_multichip(8)
        out = capsys.readouterr().out
        # every parallelism family must report — a silently dropped
        # section would pass an 'any output' check
        assert 'one train step' in out                    # dp x tp
        assert 'MoE train step' in out                    # dp x ep
        assert 'FULL dp x pp x tp train step' in out      # 3D
        assert 'pipeline matches the sequential oracle' in out
        assert 'ring + Ulysses attention' in out          # sp, both
        # the ingest path (VERDICT r4 #2): loader over the mesh, shard
        # coverage, elastic resume
        assert 'make_jax_loader staged' in out
        assert 'partitions the dataset exactly' in out
        assert 'resumed on 4 shards' in out


class TestAccumEdgeCases:
    def test_accum_steps_below_one_rejected(self):
        from petastorm_tpu.models.transformer import (
            TransformerConfig, transformer_train_step,
        )
        import optax as _optax
        config = TransformerConfig(vocab_size=16, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=8)
        with pytest.raises(ValueError, match='accum_steps'):
            transformer_train_step(config, _optax.sgd(1e-2), accum_steps=0)

    @pytest.mark.slow
    def test_moe_accum_close_to_full_batch(self):
        # MoE: logits-side gradients agree; the Switch aux is the
        # per-microbatch estimator (mean of per-chunk statistics), so the
        # updates are CLOSE, not identical — the documented semantics,
        # matching the pipelined step's microbatching
        from petastorm_tpu.models.transformer import (
            TransformerConfig, init_transformer_params, transformer_train_step,
        )
        config = TransformerConfig(vocab_size=16, d_model=16, n_heads=2,
                                   n_layers=1, d_ff=32, max_seq_len=8,
                                   dtype=jnp.float32, n_experts=2,
                                   capacity_factor=8.0)
        params = init_transformer_params(jax.random.PRNGKey(0), config)
        optimizer = optax.sgd(1e-2)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, 16, (8, 8), np.int32))
        full = transformer_train_step(config, optimizer)
        accum = transformer_train_step(config, optimizer, accum_steps=2)
        _, _, l_full = full(params, optimizer.init(params), tokens)
        _, _, l_acc = accum(params, optimizer.init(params), tokens)
        assert np.isfinite(float(l_acc))
        np.testing.assert_allclose(float(l_full), float(l_acc), rtol=0.1)


class TestViTConfigValidation:
    def test_bad_attn_impl_rejected_eagerly(self):
        from petastorm_tpu.models.vit import ViTConfig
        with pytest.raises(ValueError, match='attn_impl'):
            ViTConfig(image_size=16, patch_size=4, attn_impl='fused')
