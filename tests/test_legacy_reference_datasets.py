"""Read the reference's OWN committed legacy datasets (petastorm
0.4.0-0.7.6, many pickled under Python 2) through ``make_reader``.

This is the strongest possible on-disk interop proof: these files were
written by six historical releases of the actual reference implementation
(mirrors ``petastorm/tests/test_reading_legacy_datasets.py:1-60`` over
``tests/data/legacy/``), not fixtures synthesized here. Skipped wholesale
when the reference checkout is not mounted.
"""

import os
from decimal import Decimal

import numpy as np
import pytest

from petastorm_tpu import make_reader

_LEGACY_ROOT = '/root/reference/petastorm/tests/data/legacy'

pytestmark = pytest.mark.skipif(
    not os.path.isdir(_LEGACY_ROOT),
    reason='reference legacy datasets not mounted')


def _versions():
    if not os.path.isdir(_LEGACY_ROOT):
        return []
    return sorted(v for v in os.listdir(_LEGACY_ROOT)
                  if os.path.isdir(os.path.join(_LEGACY_ROOT, v)))


@pytest.mark.parametrize('version', _versions())
def test_reads_every_legacy_generation(version):
    url = 'file://' + os.path.join(_LEGACY_ROOT, version)
    with make_reader(url, workers_count=1, num_epochs=1,
                     shuffle_row_groups=False) as reader:
        rows = list(reader)
    assert len(rows) == 100
    assert len(rows[0]._fields) > 5
    # decoded codec fields come out typed, not as raw stored bytes
    assert rows[0].matrix.shape == (32, 16, 3)
    assert rows[0].matrix.dtype == np.float32
    png = rows[0].image_png
    assert png.ndim == 3 and png.dtype == np.uint8
    assert isinstance(rows[0].decimal, Decimal)
    ids = sorted(getattr(r, 'id') for r in rows)
    assert ids == list(range(100))


@pytest.mark.parametrize('version', _versions()[:1] + _versions()[-1:])
def test_legacy_column_projection_and_batch_reader(version):
    url = 'file://' + os.path.join(_LEGACY_ROOT, version)
    with make_reader(url, schema_fields=['^id$', '^matrix$'],
                     workers_count=1, num_epochs=1) as reader:
        row = next(reader)
    assert set(row._fields) == {'id', 'matrix'}
    assert row.matrix.shape == (32, 16, 3)
