"""Fixture: raw env reads of the knob namespace + an unregistered knob."""

import os

from petastorm_tpu.telemetry import knobs

# finding: raw os.environ.get outside telemetry/knobs.py
_RAW_GET = os.environ.get('PETASTORM_TPU_STAGING', '')

# finding: raw subscript read
_RAW_SUB = os.environ['PETASTORM_TPU_METRICS']

# finding: raw os.getenv
_RAW_GETENV = os.getenv('PETASTORM_TPU_TRACE')

# finding: membership read
_RAW_IN = 'PETASTORM_TPU_NATIVE' in os.environ

# finding: registry API but an unregistered knob name
_UNREGISTERED = knobs.get_str('PETASTORM_TPU_NOT_A_REAL_KNOB')

# clean: registry API with a registered knob
_OK = knobs.get_str('PETASTORM_TPU_METRICS')
