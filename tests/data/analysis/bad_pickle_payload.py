"""Fixture: closure-y payloads handed to process boundaries."""

import dill


def enqueue_all(pool, rows):
    def local_transform(row):        # nested def: pickles by value, if at all
        return row * 2

    pool.ventilate(local_transform, rows)       # finding: local function
    pool.ventilate(lambda r: r + 1, rows)       # finding: lambda
    _payload = dill.dumps((local_transform, rows))   # finding: local function
    pool.ventilate(process_row, rows)           # clean: module-level


def process_row(row):
    return row
