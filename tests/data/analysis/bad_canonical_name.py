"""Fixture: off-contract stage/event/metric names."""

from petastorm_tpu.telemetry import get_registry, span
from petastorm_tpu.telemetry.tracing import record_instant

# resolved through a module-level constant, like the real call sites
_TYPO_METRIC = 'petastorm_tpu_reventilated_totl'


def record(ctx):
    with span('decod'):          # finding: typo'd stage
        pass
    with span('decode'):         # clean: canonical stage
        pass
    record_instant('reventilated', ctx, 'dispatcher')   # finding: not an event
    get_registry().counter(_TYPO_METRIC).inc()          # finding: via constant
    get_registry().counter('petastorm_tpu_cache_hits_total').inc()  # clean
