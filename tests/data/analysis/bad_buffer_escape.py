"""Fixture: borrowed zero-copy views escaping / written through."""

import queue

import numpy as np


class Holder:
    def keep(self, frames):
        view = np.frombuffer(frames[0], dtype=np.uint8)
        self.stash = view                     # escape: object state (11)

    def enqueue(self, sock, q):
        frames = sock.recv_multipart(copy=False)
        q.put(frames)                         # escape: queue (15)


def capture(buf):
    view = np.frombuffer(buf, dtype=np.uint8)
    return lambda: view.sum()                 # escape: closure (20)


def give_back(buf):
    view = np.frombuffer(buf, dtype=np.uint8)
    return view                               # escape: returned (25)


def scribble(buf):
    view = np.frombuffer(buf, dtype=np.uint8)
    view[0] = 1                               # write-through (30)
    view += 1                                 # write-through (31)
    np.copyto(view, 0)                        # write-through (32)


def cast_alias(arr, dtype):
    return arr.astype(dtype, copy=False)      # escape: alias returned (36)


def indirect(buf):
    view = give_back(buf)                     # give_back() returns borrowed
    return view                               # escape: whole-program (41)


def owned_fresh_temporary(payload):
    # frombuffer over a call expression: the fresh bytes become the
    # array's .base — owned by construction, no finding
    return np.frombuffer(bytes(payload), dtype=np.uint8)


def annotated_transfer(buf):
    view = np.frombuffer(buf, dtype=np.uint8)
    # Documented handoff: fixture for the annotation.  # pipesan: owns
    return view


def killed_taint_is_clean(buf):
    view = np.frombuffer(buf, dtype=np.uint8)
    view = np.array(view, copy=True)          # reassignment kills taint
    return view
