"""Fixture: the ``# pipecheck: disable=<rule>`` comment path."""

import os
import threading


class SuppressedPool:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = None

    def drain(self):
        with self._lock:
            # justified: fixture for the suppression syntax itself
            self._queue.get()  # pipecheck: disable=blocking-under-lock


# suppressed via `all`
_RAW = os.environ.get('PETASTORM_TPU_STAGING')  # pipecheck: disable=all
