"""Fixture: fault_hit() sites naming unregistered faultpoints."""
from petastorm_tpu import faults

_CONSTANT_SITE = 'decode.rowgrup'  # typo'd constant resolves too


def hot_path(piece):
    if faults.ARMED:
        faults.fault_hit('io.reed', key=piece)          # line 9: typo
    if faults.ARMED:
        faults.fault_hit(_CONSTANT_SITE, key=piece)     # line 11: constant
    if faults.ARMED:
        faults.fault_hit('io.read', key=piece)          # registered: clean
