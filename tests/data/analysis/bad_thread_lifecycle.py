"""Fixture: non-daemon threads nobody ever joins."""

import threading


class LeakyPool:
    def start(self):
        # finding: no daemon=True and the class never join()s it
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def _run(self):
        pass


class CleanPool:
    def start(self):
        # clean: joined from stop()
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def stop(self):
        self._thread.join(timeout=5.0)

    def _run(self):
        pass


def fire_and_forget():
    # finding: unbound, undaemonized, unjoined
    threading.Thread(target=print).start()


def scoped_worker():
    # clean: daemonized after construction
    worker = threading.Thread(target=print)
    worker.daemon = True
    worker.start()
