"""Fixture: two locks nested in opposite orders in one module."""

import threading

_STATE_LOCK = threading.Lock()
_IO_LOCK = threading.Lock()


def writer():
    with _STATE_LOCK:
        with _IO_LOCK:       # order: state -> io
            pass


def reader():
    with _IO_LOCK:
        with _STATE_LOCK:    # finding: io -> state inverts writer()'s order
            pass
