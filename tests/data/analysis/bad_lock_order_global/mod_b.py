"""Fixture (whole-program lock-order): the inverse half of mod_a."""

import threading

from mod_a import grab

_FLUSH_LOCK = threading.Lock()


def flush_buffers():
    pass


def drain():
    with _FLUSH_LOCK:
        flush_buffers()


def reverse_path():
    with _FLUSH_LOCK:
        grab()           # grab() acquires mod_a._A_LOCK: B then A — inversion
