"""Fixture (whole-program lock-order): module A holds its lock and calls
into module B, whose call chain acquires B's lock — order A → B. Module B
runs the opposite chain. Neither module shows both orders lexically, so
only the call-graph pass can see the inversion. Never imported: the
circular import between the two fixture modules is parsed, not executed.
"""

import threading

from mod_b import drain

_A_LOCK = threading.Lock()


def path_one():
    with _A_LOCK:
        drain()          # drain() acquires mod_b._FLUSH_LOCK: A then B


def grab():
    with _A_LOCK:
        pass
