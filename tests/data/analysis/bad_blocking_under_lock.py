"""Fixture: indefinitely-blocking calls lexically inside lock bodies."""

import queue
import subprocess
import threading
import time


class Pipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = queue.Queue()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def drain(self, sock, handle):
        with self._lock:
            item = self._queue.get()            # finding: get() sans timeout
            self._queue.put(item)               # finding: put() sans timeout
            frames = sock.recv_multipart()      # finding: ZMQ sans NOBLOCK
            self._thread.join()                 # finding: join() sans timeout
            handle.block_until_ready()          # finding
            subprocess.run(['true'])            # finding
            time.sleep(1.0)                     # finding

    def drain_politely(self, sock):
        with self._lock:
            item = self._queue.get(timeout=0.05)      # clean: bounded
            self._queue.put(item, timeout=0.05)       # clean: bounded
            self._thread.join(0.1)                    # clean: bounded
        self._queue.get()                             # clean: no lock held

    def acquire_style(self):
        self._lock.acquire()
        self._queue.get()                       # finding: between acquire/release
        self._lock.release()
        self._queue.get()                       # clean: released
