"""Standing decode service tests: daemonized dispatcher, job registry,
leases, supervisor self-healing, and the chaos drills of docs/service.md
("Standing service").

Timing mirrors tests/test_service.py: tight heartbeats so failures are
detected in well under a second, generous outer deadlines so slow CI
never flakes, and every ``get_results`` call bounded internally (no
pytest-timeout in this environment)."""

import collections
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from petastorm_tpu import faults, telemetry
from petastorm_tpu.serializers import PickleSerializer
from petastorm_tpu.service import protocol as proto
from petastorm_tpu.service.daemon import DaemonClientPool, ServiceDaemon
from petastorm_tpu.service.protocol import free_tcp_port
from petastorm_tpu.service.supervisor import WorkerSupervisor
from petastorm_tpu.workers import EmptyResultError
from tests.stub_workers import IdentityWorker, SleepyIdentityWorker

pytestmark = pytest.mark.service

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# tight-but-safe: lapse detection well under a second; outer deadlines
# generous so shared-box scheduling noise cannot flake the suite
_HB = 0.15
_TICK = 0.15


@pytest.fixture(autouse=True)
def _clean_telemetry_and_faults():
    telemetry.reset_for_tests()
    yield
    os.environ.pop('PETASTORM_TPU_FAULTS', None)
    faults.refresh_faults()
    assert faults.ARMED is None
    telemetry.reset_for_tests()


def _drain(pool, per_result_timeout_s=60):
    out = []
    while True:
        try:
            out.append(pool.get_results(timeout=per_result_timeout_s))
        except EmptyResultError:
            return out


def _make_daemon(workers=2, **kwargs):
    kwargs.setdefault('heartbeat_interval_s', _HB)
    kwargs.setdefault('supervisor_tick_s', _TICK)
    daemon = ServiceDaemon('tcp://127.0.0.1:0', initial_workers=workers,
                           **kwargs)
    daemon.start()
    return daemon


def _client(endpoint, **kwargs):
    kwargs.setdefault('heartbeat_interval_s', _HB)
    return DaemonClientPool(endpoint, **kwargs)


def _await(predicate, deadline_s=30, interval_s=0.05, message='condition'):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval_s)
    raise AssertionError('timed out waiting for %s' % message)


# -- multi-job registry -------------------------------------------------------


def test_two_jobs_share_one_fleet_exact_delivery():
    """The registry core: two concurrent client jobs on ONE daemonized
    fleet each receive their exact row multiset — no loss, no
    duplication, no cross-job leakage — and the fleet is partitioned
    across them (both jobs hold workers while both run)."""
    daemon = _make_daemon(workers=2)
    a = _client(daemon.endpoint, name='job-a')
    b = _client(daemon.endpoint, name='job-b')
    try:
        a.start(SleepyIdentityWorker)
        b.start(SleepyIdentityWorker)
        for i in range(30):
            a.ventilate(i, sleep_s=0.005)
        for i in range(100, 130):
            b.ventilate(i, sleep_s=0.005)
        # both jobs hold a slice of the fleet while both are live
        _await(lambda: all(
            j['workers'] >= 1
            for j in daemon.dispatcher.health()['jobs']),
            message='fleet partitioned across jobs')
        got_a = sorted(_drain(a))
        got_b = sorted(_drain(b))
        assert got_a == list(range(30))
        assert got_b == list(range(100, 130))
        stats = daemon.dispatcher.stats()
        assert stats['jobs_active'] == 2
        assert stats['jobs_seen'] == 2
    finally:
        for pool in (a, b):
            pool.stop()
            pool.join()
        # clean goodbyes reclaim both jobs without waiting out a lease
        _await(lambda: daemon.dispatcher.active_jobs() == 0,
               message='jobs reclaimed after goodbye')
        daemon.stop()


def test_reader_reads_through_standing_daemon(tmp_path, monkeypatch):
    """Acceptance: ``make_batch_reader(url, reader_pool_type='service')``
    with ``PETASTORM_TPU_SERVICE_DAEMON`` set delivers the identical row
    multiset as a thread-pool read — twice, off one standing daemon (two
    reader lifetimes, zero fleet restarts)."""
    from petastorm_tpu.reader import make_batch_reader
    from tests.test_common import create_test_scalar_dataset
    url = 'file://' + str(tmp_path / 'dataset')
    create_test_scalar_dataset(url, num_rows=50, num_files=5)

    def read_ids(pool_type):
        ids = collections.Counter()
        with make_batch_reader(url, reader_pool_type=pool_type,
                               num_epochs=1,
                               shuffle_row_groups=False) as reader:
            for batch in reader:
                ids.update(int(x) for x in batch.id)
        return ids

    expected = read_ids('thread')
    assert sum(expected.values()) == 50
    daemon = _make_daemon(workers=2)
    try:
        monkeypatch.setenv('PETASTORM_TPU_SERVICE_DAEMON',
                           daemon.endpoint)
        assert read_ids('service') == expected
        assert read_ids('service') == expected  # second reader lifetime
        assert daemon.dispatcher.stats()['jobs_seen'] == 2
    finally:
        monkeypatch.delenv('PETASTORM_TPU_SERVICE_DAEMON', raising=False)
        daemon.stop()


# -- chaos drill (a): worker SIGKILL → supervisor replacement -----------------


def test_worker_sigkill_replaced_within_heartbeat_window():
    """Chaos (a): SIGKILL a supervised worker mid-job. The supervisor
    must respawn the seat within one supervision tick of the death, the
    dispatcher must re-ventilate the dead worker's items, and the job's
    row multiset must arrive exactly once."""
    daemon = _make_daemon(workers=2)
    pool = _client(daemon.endpoint, name='kill-drill')
    try:
        pool.start(SleepyIdentityWorker)
        for i in range(40):
            pool.ventilate(i, sleep_s=0.05)
        results = [pool.get_results(timeout=60) for _ in range(5)]
        victim = daemon.supervisor.status()['slots'][0]['pid']
        os.kill(victim, signal.SIGKILL)
        results.extend(_drain(pool))
        assert sorted(results) == list(range(40))
        status = daemon.supervisor.status()
        assert status['spawned_total'] >= 3, 'no replacement spawn'
        assert daemon.dispatcher.stats()['items_reventilated'] >= 1
        # the replacement actually serves: fleet back at target strength
        _await(lambda: daemon.dispatcher.stats()['workers_alive'] >= 2,
               message='replacement worker registered')
        actions = [d['action'] for d in daemon.supervisor.decisions()]
        assert 'worker_death' in actions and 'worker_spawn' in actions
    finally:
        pool.stop()
        pool.join()
        daemon.stop()


# -- chaos drill (b): crash-looping slot trips the breaker --------------------


def test_breaker_trips_after_exactly_k_deaths_sparing_cotenants():
    """Chaos (b): one worker seat crash-loops (a SIGKILL, then every
    respawn fails via the ``service.spawn`` faultpoint). The breaker
    must trip after EXACTLY ``breaker_deaths`` deaths — announced once
    as a ``worker_flapping`` anomaly — while the co-tenant job on the
    surviving worker keeps its delivery exact and never exhausts a
    retry budget. Disarming the faultpoint lets the backed-off respawn
    close the loop and restore the fleet."""
    daemon = _make_daemon(workers=2)
    pool = _client(daemon.endpoint, name='cotenant')
    try:
        pool.start(SleepyIdentityWorker)
        # stream enough work that delivery spans the whole drill
        for i in range(60):
            pool.ventilate(i, sleep_s=0.02)
        results = [pool.get_results(timeout=60) for _ in range(3)]
        victim_slot = daemon.supervisor.status()['slots'][1]
        os.environ['PETASTORM_TPU_FAULTS'] = \
            'service.spawn:error:1:match=%d' % victim_slot['slot']
        faults.refresh_faults()
        os.kill(victim_slot['pid'], signal.SIGKILL)
        _await(lambda: any(s['breaker_open']
                           for s in daemon.supervisor.status()['slots']),
               message='breaker to open')
        flapping = [e for e in telemetry.recent_anomalies()
                    if e['kind'] == 'worker_flapping']
        assert len(flapping) == 1, 'breaker must announce exactly once'
        assert flapping[0]['detail']['deaths'] == 3  # the default K
        # heal the seam: the next backed-off respawn succeeds
        os.environ.pop('PETASTORM_TPU_FAULTS')
        faults.refresh_faults()
        results.extend(_drain(pool))
        assert sorted(results) == list(range(60)), \
            'co-tenant delivery must stay exact through the crash loop'
        assert pool.poisoned_items == [], \
            'co-tenant retry budgets must survive the crash loop'
        _await(lambda: daemon.dispatcher.stats()['workers_alive'] >= 2,
               message='breaker-closed respawn to restore the fleet')
    finally:
        pool.stop()
        pool.join()
        daemon.stop()


# -- chaos drill (c): silent client → lease reclamation -----------------------


class _RawJobClient:
    """A protocol-level client with NO liveness machinery: registers a
    job, submits items, then can simply go silent — the lease-lapse
    fixture (and the BUSY/expiry probe)."""

    def __init__(self, endpoint):
        import zmq
        self._context = zmq.Context()
        self.sock = self._context.socket(zmq.DEALER)
        self.sock.setsockopt(zmq.LINGER, 0)
        self.sock.connect(endpoint)
        self.job_id = None

    def register(self, worker_class=SleepyIdentityWorker, lease_s=None,
                 timeout_s=15):
        spec = proto.dump_job_spec(worker_class, None, PickleSerializer())
        params = {'name': 'raw', 'credit': 100}
        if lease_s is not None:
            params['lease_s'] = lease_s
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.sock.send_multipart([proto.MSG_REGISTER_JOB, spec,
                                      proto.dump_json_params(params)])
            if not self.sock.poll(500):
                continue
            frames = self.sock.recv_multipart()
            if frames[0] == proto.MSG_JOB_OK:
                self.job_id = int(frames[1])
                return 'ok'
            if frames[0] == proto.MSG_BUSY:
                return proto.load_json_params(frames[1])
        raise AssertionError('no REGISTER_JOB answer within %ss'
                             % timeout_s)

    def submit(self, cid, *args, **kwargs):
        self.sock.send_multipart([proto.MSG_SUBMIT, b'%d' % self.job_id,
                                  b'%d' % cid,
                                  proto.dump_work_item(args, kwargs)])

    def close(self):
        self.sock.close(linger=0)
        self._context.term()


def test_lease_lapse_reclaims_job_without_touching_survivor():
    """Chaos (c): a client registers, submits work, and dies silently
    (no goodbye, no heartbeat). After its lease the daemon must reclaim
    the job — pending purged, in-flight reclaimed, workers returned to
    the pool, ``job_lease_expired`` announced — with zero effect on the
    surviving job's delivery."""
    daemon = _make_daemon(workers=2, lease_s=1.0)
    survivor = _client(daemon.endpoint, name='survivor')
    silent = _RawJobClient(daemon.endpoint)
    try:
        survivor.start(SleepyIdentityWorker)
        assert silent.register(lease_s=1.0) == 'ok'
        for cid in range(10):
            silent.submit(cid, cid, sleep_s=0.05)
        for i in range(40):
            survivor.ventilate(i, sleep_s=0.02)
        _await(lambda: daemon.dispatcher.active_jobs() == 2,
               message='both jobs registered')
        # ... and the silent client now dies without a word
        silent.close()
        _await(lambda: daemon.dispatcher.active_jobs() == 1,
               message='lease to reclaim the silent job')
        expired = [e for e in telemetry.recent_anomalies()
                   if e['kind'] == 'job_lease_expired']
        assert len(expired) == 1
        assert expired[0]['detail']['name'] == 'raw'
        assert daemon.dispatcher.stats()['jobs_expired'] == 1
        got = sorted(_drain(survivor))
        assert got == list(range(40)), \
            'survivor delivery must be untouched by the reclamation'
        # the reclaimed job's workers serve the survivor now
        _await(lambda: daemon.dispatcher.health()['jobs'][0]['workers']
               >= 2, message='orphaned workers rebound to the survivor')
    finally:
        survivor.stop()
        survivor.join()
        daemon.stop()


# -- daemon SIGKILL + restart: client resubmission, worker re-registration ----


def _spawn_daemon_cli(endpoint, extra=()):
    env = dict(os.environ,
               PYTHONPATH=os.pathsep.join(
                   [_REPO_ROOT, os.path.join(_REPO_ROOT, 'tests')]),
               JAX_PLATFORMS='cpu')
    return subprocess.Popen(
        [sys.executable, '-m', 'petastorm_tpu.service',
         '--endpoint', endpoint, '--no-supervisor',
         '--heartbeat-interval', str(_HB)] + list(extra),
        env=env)


def _spawn_cli_worker(endpoint):
    env = dict(os.environ,
               PYTHONPATH=os.pathsep.join(
                   [_REPO_ROOT, os.path.join(_REPO_ROOT, 'tests')]),
               JAX_PLATFORMS='cpu')
    return subprocess.Popen(
        [sys.executable, '-m', 'petastorm_tpu.service.worker_server',
         '--endpoint', endpoint,
         '--heartbeat-interval', str(_HB),
         '--ack-timeout', '1.5',
         '--parent-pid', str(os.getpid())],
        env=env)


def _reap(procs):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


def test_daemon_sigkill_restart_exact_delivery_with_standing_workers():
    """THE standing-service drill: SIGKILL the daemon mid-job with
    standing (externally-started) workers and a live client. On
    restart, the workers detect the incarnation change through the
    PR 11 token and re-register; the client re-registers its job and
    re-submits exactly the unmarkered items. The delivered multiset is
    exact — the daemon's death cost retries, never rows."""
    endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
    daemon_proc = _spawn_daemon_cli(endpoint)
    workers = [_spawn_cli_worker(endpoint) for _ in range(2)]
    pool = _client(endpoint, name='restart-drill', ack_timeout_s=1.5,
                   connect_timeout_s=60)
    try:
        pool.start(SleepyIdentityWorker)
        for i in range(30):
            pool.ventilate(i, sleep_s=0.05)
        results = [pool.get_results(timeout=60) for _ in range(5)]
        os.kill(daemon_proc.pid, signal.SIGKILL)
        daemon_proc.wait()
        # the control plane is DOWN; the standing workers and the
        # client both outlive it
        daemon_proc = _spawn_daemon_cli(endpoint)
        results.extend(_drain(pool))
        assert sorted(results) == list(range(30))
        assert pool.diagnostics['reregistrations'] >= 1
        assert all(w.poll() is None for w in workers), \
            'standing workers must survive both daemon incarnations'
    finally:
        pool.stop()
        pool.join()
        _reap([daemon_proc] + workers)


# -- drain / admission control ------------------------------------------------


def test_drain_refuses_new_jobs_busy_and_finishes_registered_ones():
    daemon = _make_daemon(workers=1)
    pool = _client(daemon.endpoint, name='draining-job')
    probe = _RawJobClient(daemon.endpoint)
    try:
        pool.start(SleepyIdentityWorker)
        for i in range(10):
            pool.ventilate(i, sleep_s=0.01)
        daemon.begin_drain()
        refusal = probe.register(timeout_s=10)
        assert refusal != 'ok' and refusal['reason'] == 'draining'
        # the registered job finishes normally through the drain
        assert sorted(_drain(pool)) == list(range(10))
        assert daemon.health()['draining'] is True
    finally:
        probe.close()
        pool.stop()
        pool.join()
        _await(lambda: daemon.drained, message='drain to empty')
        daemon.stop()


def test_admission_control_refuses_beyond_max_jobs():
    daemon = _make_daemon(workers=1, max_jobs=1)
    first = _client(daemon.endpoint, name='admitted')
    probe = _RawJobClient(daemon.endpoint)
    try:
        first.start(IdentityWorker)
        refusal = probe.register(timeout_s=10)
        assert refusal != 'ok' and refusal['reason'] == 'saturated'
        assert refusal['max_jobs'] == 1
    finally:
        probe.close()
        first.stop()
        first.join()
        daemon.stop()


# -- protocol backward compatibility ------------------------------------------


def test_old_build_worker_serves_new_daemon():
    """Satellite: a pre-standing-service worker build — bare REGISTER
    (no pid frame), bare HEARTBEAT (no summary, no token), DONE with an
    empty metrics frame — must serve a daemon job end to end: the new
    frames are additive, never required."""
    import zmq
    daemon = _make_daemon(workers=0, supervise=False)
    pool = _client(daemon.endpoint, name='old-worker-job')

    stop = threading.Event()
    served = []

    def old_worker():
        context = zmq.Context()
        sock = context.socket(zmq.DEALER)
        sock.setsockopt(zmq.LINGER, 0)
        sock.connect(daemon.endpoint)
        try:
            spec = None
            while spec is None and not stop.is_set():
                sock.send_multipart([proto.MSG_REGISTER])  # v1: no pid
                if sock.poll(200):
                    frames = sock.recv_multipart()
                    if frames[0] == proto.MSG_SPEC:
                        spec = frames[1]
            if spec is None:
                return
            worker_class, worker_args, serializer = \
                proto.load_job_spec(spec)
            buffer = []
            worker = worker_class(0, buffer.append, worker_args)
            worker.initialize()
            sock.send_multipart([proto.MSG_READY])
            last_hb = 0.0
            while not stop.is_set():
                now = time.monotonic()
                if now - last_hb > _HB:
                    last_hb = now
                    sock.send_multipart([proto.MSG_HEARTBEAT])  # v1: bare
                if not sock.poll(50):
                    continue
                frames = sock.recv_multipart()
                if frames[0] == proto.MSG_WORK:
                    del buffer[:]
                    args, kwargs = proto.load_work_item(frames[2])
                    kwargs.pop('_trace_ctx', None)
                    worker.process(*args, **kwargs)
                    served.append(1)
                    sock.send_multipart(
                        [proto.MSG_DONE, frames[1], b'']
                        + [serializer.serialize(v) for v in buffer])
                elif frames[0] == proto.MSG_STOP:
                    break
        finally:
            sock.close(linger=0)
            context.term()

    thread = threading.Thread(target=old_worker, daemon=True)
    thread.start()
    try:
        pool.start(SleepyIdentityWorker)
        for i in range(12):
            pool.ventilate(i, sleep_s=0.005)
        assert sorted(_drain(pool)) == list(range(12))
        assert served, 'the old-build worker never processed anything'
    finally:
        pool.stop()
        pool.join()
        stop.set()
        thread.join(timeout=10)
        daemon.stop()


def test_new_worker_serves_frameless_v1_dispatcher():
    """Satellite: today's worker server against a dispatcher speaking
    only the ORIGINAL frame set (SPEC without token, HEARTBEAT_ACK
    without token, ignoring the new REGISTER pid frame) keeps serving —
    the compatibility promise runs in both directions."""
    import zmq
    endpoint = 'tcp://127.0.0.1:%d' % free_tcp_port()
    spec = proto.dump_job_spec(IdentityWorker, None, PickleSerializer())
    results = {}
    done = threading.Event()

    def v1_dispatcher():
        context = zmq.Context()
        sock = context.socket(zmq.ROUTER)
        sock.bind(endpoint)
        pending = list(range(8))
        inflight = {}
        try:
            deadline = time.monotonic() + 60
            while len(results) < 8 and time.monotonic() < deadline:
                if not sock.poll(50):
                    continue
                frames = sock.recv_multipart()
                identity, msg = frames[0], frames[1]
                if msg == proto.MSG_REGISTER:
                    # v1 reply: NO token frame (and frames[2:] — the new
                    # build's pid frame — deliberately ignored)
                    sock.send_multipart([identity, proto.MSG_SPEC, spec])
                elif msg == proto.MSG_READY or msg == proto.MSG_HEARTBEAT:
                    if msg == proto.MSG_HEARTBEAT:
                        sock.send_multipart(
                            [identity, proto.MSG_HEARTBEAT_ACK])
                    while pending:
                        item = pending.pop(0)
                        inflight[item] = True
                        sock.send_multipart(
                            [identity, proto.MSG_WORK,
                             proto.pack_item_id(item),
                             proto.dump_work_item((item,), {})])
                elif msg == proto.MSG_DONE:
                    item = proto.unpack_item_id(frames[2])
                    payload = frames[3:]
                    if payload and payload[0] == b'':
                        payload = payload[1:]
                    elif payload and proto.load_metrics_delta(payload[0]):
                        payload = payload[1:]
                    results[item] = payload
            for _ in range(3):
                sock.send_multipart([identity, proto.MSG_STOP])
                time.sleep(0.05)
        finally:
            done.set()
            sock.close(linger=0)
            context.term()

    thread = threading.Thread(target=v1_dispatcher, daemon=True)
    thread.start()
    env = dict(os.environ,
               PYTHONPATH=os.pathsep.join(
                   [_REPO_ROOT, os.path.join(_REPO_ROOT, 'tests')]),
               JAX_PLATFORMS='cpu')
    worker = subprocess.Popen(
        [sys.executable, '-m', 'petastorm_tpu.service.worker_server',
         '--endpoint', endpoint, '--heartbeat-interval', str(_HB),
         '--parent-pid', str(os.getpid()), '--once'],
        env=env)
    try:
        assert done.wait(timeout=90), 'v1 dispatcher never finished'
        assert sorted(results) == list(range(8))
    finally:
        thread.join(timeout=10)
        _reap([worker])


def test_completion_from_non_owner_identity_is_dropped():
    """White-box regression for the daemon-restart duplicate: a restarted
    daemon's item-id space collides with its predecessor's, and a stale
    DONE flushed from an old-incarnation worker's socket must NOT
    complete the colliding new item (it carries some OTHER item's rows —
    accepting it is a duplicate plus a loss). Only identities this
    dispatcher actually assigned the item to may complete it."""
    import threading as _threading
    from petastorm_tpu.service.dispatcher import Dispatcher, _WorkerState

    delivered = []
    d = Dispatcher('tcp://127.0.0.1:0', b'spec',
                   lambda entry: delivered.append(entry) or True,
                   _threading.Event())
    now = time.monotonic()
    owner = _WorkerState(b'OWNER', now)
    d._workers[b'OWNER'] = owner
    item = d.submit(b'payload')
    local = d._jobs[0]
    local.pending.clear()
    local.pending_ids.clear()
    d._inflight[item] = (b'OWNER', b'payload')
    owner.inflight.add(item)
    d._item_owners[item] = {b'OWNER'}
    # the stale frame: same item id, an identity never assigned to it
    d._complete(b'STALE-GHOST', item, ('result', [b'wrong-rows']), now)
    assert delivered == [], 'non-owner completion must deliver nothing'
    assert item in d._inflight, 'the live assignment must stand'
    # the real owner's completion flows normally
    d._complete(b'OWNER', item, ('result', [b'rows']), now)
    assert ('result', b'rows') in delivered
    assert ('marker', item) in delivered


def test_lapsed_worker_rebinds_only_to_its_own_job():
    """White-box regression: a lapsed-then-resurfacing worker still RUNS
    the spec of the job it lapsed from — re-admission must restore that
    binding (never least-loaded rebinding, which would hand job B's
    items to job A's decode worker), and a worker whose job is gone must
    be STOPped back through registration instead of idling."""
    import threading as _threading
    from petastorm_tpu.service.dispatcher import Dispatcher

    class _SockStub:
        def __init__(self):
            self.sent = []

        def send_multipart(self, frames, **kwargs):
            self.sent.append(frames)

    d = Dispatcher('tcp://127.0.0.1:0', None, None, _threading.Event(),
                   standing=True)
    sock = _SockStub()
    d._sock = sock
    now = time.monotonic()
    # two registered jobs; a worker registers and binds (job 1, emptier)
    d._handle_register_job(sock, b'client-a', [b'', b'', b'spec-a',
                                               proto.dump_json_params(
                                                   {'key': 'a'})], now)
    d._handle_register_job(sock, b'client-b', [b'', b'', b'spec-b',
                                               proto.dump_json_params(
                                                   {'key': 'b'})], now)
    d._handle(sock, [b'w1', proto.MSG_REGISTER])
    worker = d._workers[b'w1']
    bound_job = worker.job_id
    assert bound_job in d._jobs
    # make the OTHER job the least-loaded one (a naive rebind would pick
    # it), then lapse the worker and let its heartbeat re-admit it
    other = [j for j in d._jobs if j != bound_job][0]
    d._workers[b'w2'] = type(worker)(b'w2', now)
    d._workers[b'w2'].job_id = bound_job
    d._jobs[bound_job].workers.add(b'w2')
    d._deregister(b'w1', 'heartbeat lapsed (test)')
    d._handle(sock, [b'w1', proto.MSG_HEARTBEAT])
    assert d._workers[b'w1'].job_id == bound_job, \
        'resurfaced worker must re-bind to the job whose spec it runs'
    assert b'w1' not in d._jobs[other].workers
    # now the worker's job disappears entirely: re-admission must STOP
    # it back to registration, not leave it idling on a dead spec
    d._remove_job(d._jobs[bound_job], 'test teardown')
    d._deregister(b'w1', 'heartbeat lapsed (test)')
    sock.sent.clear()
    d._handle(sock, [b'w1', proto.MSG_HEARTBEAT])
    assert d._workers[b'w1'].job_id is None
    assert not d._workers[b'w1'].ready
    assert any(frames[1] == proto.MSG_STOP for frames in sock.sent
               if frames[0] == b'w1')


# -- supervisor unit drills (stub processes, no subprocess cost) --------------


class _StubProc:
    def __init__(self, pid):
        self.pid = pid
        self.exit_code = None
        self.signals = []

    def poll(self):
        return self.exit_code

    def send_signal(self, sig):
        self.signals.append(sig)

    def terminate(self):
        self.signals.append(signal.SIGTERM)
        self.exit_code = 0

    def kill(self):
        self.exit_code = -9

    def wait(self, timeout=None):
        return self.exit_code


class _StubDispatcher:
    def __init__(self):
        self.stats_value = {'items_pending': 0, 'items_assigned': 0,
                            'workers_alive': 0}
        self.alive = set()
        self.cordoned = []

    def stats(self):
        return dict(self.stats_value)

    def alive_worker_pids(self):
        return set(self.alive)

    def cordon_worker_by_pid(self, pid):
        self.cordoned.append(pid)
        return True

    def worker_inflight_by_pid(self, pid):
        return 0


def _stub_supervisor(**kwargs):
    dispatcher = _StubDispatcher()
    pids = iter(range(1000, 2000))
    procs = []

    def spawn(worker_id):
        proc = _StubProc(next(pids))
        procs.append(proc)
        return proc

    sup = WorkerSupervisor(dispatcher, 'tcp://stub', spawn=spawn, **kwargs)
    return sup, dispatcher, procs


def test_breaker_opens_after_exactly_k_deaths_and_backs_off():
    """Unit drill of the breaker state machine: deaths below K respawn
    immediately; the K-th death inside the window opens the breaker
    (one ``worker_flapping``), respawns wait out an exponentially
    growing backoff, and a surviving worker closes the breaker."""
    sup, dispatcher, procs = _stub_supervisor(
        initial_workers=1, min_workers=1, max_workers=1,
        breaker_deaths=3, breaker_window_s=120.0)
    sup.start()
    try:
        assert len(procs) == 1
        for expected_spawns in (2, 3):
            procs[-1].exit_code = 13
            sup.tick()
            assert len(procs) == expected_spawns, \
                'deaths under K must respawn immediately'
        # the K-th death: breaker opens, NO immediate respawn
        procs[-1].exit_code = 13
        sup.tick()
        assert len(procs) == 3
        slot = sup.status()['slots'][0]
        assert slot['breaker_open'] is True
        assert slot['breaker_backoff_level'] == 1
        flapping = [e for e in telemetry.recent_anomalies()
                    if e['kind'] == 'worker_flapping']
        assert len(flapping) == 1
        assert flapping[0]['detail']['deaths'] == 3
        # backoff served: the seat respawns again
        sup._slots[0].open_until = 0.0
        sup.tick()
        assert len(procs) == 4
        # a stable worker closes the breaker once the window passes
        sup._slots[0].spawned_at -= 121.0
        dispatcher.alive.add(procs[-1].pid)
        sup.tick()
        slot = sup.status()['slots'][0]
        assert slot['breaker_open'] is False
        assert slot['breaker_backoff_level'] == 0
        actions = [d['action'] for d in sup.decisions()]
        assert 'breaker_open' in actions and 'breaker_close' in actions
    finally:
        sup.stop()


def test_supervisor_scales_up_on_saturation_and_releases_on_idle():
    """Unit drill of the scaling policy: sustained saturation recruits
    one worker per episode up to the ceiling; a sustained idle fleet is
    released two-phase (cordon → wait idle → SIGTERM) down to the
    floor, with every decision logged."""
    sup, dispatcher, procs = _stub_supervisor(
        initial_workers=1, min_workers=1, max_workers=2)
    sup.start()
    try:
        dispatcher.alive.update(p.pid for p in procs)
        dispatcher.stats_value = {'items_pending': 5, 'items_assigned': 1,
                                  'workers_alive': 1}
        for _ in range(3):
            sup.tick()
        assert sup.target == 2
        assert len(procs) == 2, 'saturation must recruit a worker'
        dispatcher.alive.update(p.pid for p in procs)
        # ceiling respected under continued saturation
        for _ in range(5):
            sup.tick()
        assert sup.target == 2
        # idle: released down to the floor, politely
        dispatcher.stats_value = {'items_pending': 0, 'items_assigned': 0,
                                  'workers_alive': 2}
        for _ in range(10):
            sup.tick()
        assert sup.target == 1
        assert dispatcher.cordoned, 'release must cordon before killing'
        sup.tick()  # phase two: cordoned + idle -> SIGTERM
        released = [p for p in procs if signal.SIGTERM in p.signals]
        assert len(released) == 1
        released[0].exit_code = 0
        sup.tick()  # the seat retires with its process
        assert sup.status()['released_total'] == 1
        assert len(sup.status()['slots']) == 1
        actions = [d['action'] for d in sup.decisions()]
        assert 'scale_up_decision' in actions
        assert 'worker_release' in actions
    finally:
        sup.stop()


def test_spawn_faultpoint_is_registered_and_deterministic():
    """The ``service.spawn`` faultpoint feeds the breaker without any
    real process: every spawn in the armed window fails, so the seat's
    deaths are purely injected — the chaos drill the satellite asks
    for."""
    os.environ['PETASTORM_TPU_FAULTS'] = 'service.spawn:error'
    faults.refresh_faults()
    sup, dispatcher, procs = _stub_supervisor(
        initial_workers=1, min_workers=1, max_workers=1,
        breaker_deaths=2, breaker_window_s=60.0)
    sup.start()
    try:
        assert procs == [], 'armed spawn faultpoint must fail the spawn'
        sup.tick()
        assert sup.status()['slots'][0]['breaker_open'] is True
        stats = faults.injection_stats()
        assert stats['service.spawn']['fired'] >= 2
        os.environ.pop('PETASTORM_TPU_FAULTS')
        faults.refresh_faults()
        sup._slots[0].open_until = 0.0
        sup.tick()
        assert len(procs) == 1, 'disarmed seam must spawn again'
    finally:
        sup.stop()
