"""bench.py wedge-proofing contract (VERDICT r3 #1).

Round 3's driver benchmark run was killed by an outer timeout (rc=124)
before bench.py printed its single end-of-run JSON line, losing the whole
round's perf record. The contract under test:

* bench.py prints the CUMULATIVE result JSON after every section, so the
  last complete stdout line is parseable no matter where a kill lands;
* a poisoned/unavailable device platform produces per-section error
  markers (or a probe-pinned CPU fallback), never a hang;
* an exhausted global budget (``BENCH_BUDGET_SECONDS``) skips sections,
  recording them under ``skipped_sections``, and still emits every line;
* every emit ends with a compact HEADLINE line (``"headline": true``)
  hard-capped under 1,500 chars, so the driver's 2,000-char stdout tail
  can always parse the last line (VERDICT r4 #1 — round 4's record was
  lost because the single cumulative line outgrew that tail).
"""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO_ROOT, 'bench.py')


def _run_bench(env_overrides, timeout):
    env = dict(os.environ)
    # the bench subprocesses must see the repo exactly as the driver runs it
    env.pop('PETASTORM_TPU_NATIVE', None)
    env.update(env_overrides)
    out = subprocess.run([sys.executable, BENCH], capture_output=True,
                         text=True, timeout=timeout, env=env,
                         cwd=REPO_ROOT)
    lines = [ln for ln in out.stdout.strip().splitlines()
             if ln.startswith('{')]
    assert lines, 'no JSON lines emitted; stderr tail: %s' % (
        out.stderr[-500:],)
    return out, [json.loads(ln) for ln in lines]


def test_exhausted_budget_still_emits_parseable_lines():
    """Budget 0: every section skips, yet every section emits a cumulative
    parseable line — the driver's last-line parse can never come up empty
    just because time ran out."""
    _, parsed = _run_bench({'BENCH_SMOKE': '1',
                            'BENCH_BUDGET_SECONDS': '0'}, timeout=120)
    assert len(parsed) >= 24  # (full + headline) per section + final pair
    last = parsed[-1]
    assert last['metric'] == 'hello_world_read_rate'
    assert last['unit'] == 'samples/sec'
    assert last.get('headline') is True
    skipped = last['extra']['skipped_sections']
    assert 'hello_row' in skipped and 'lm_train' in skipped
    # the full cumulative dict is the line right before the headline
    full = parsed[-2]
    assert 'headline' not in full
    assert full['extra']['skipped_sections'] == skipped


def test_headline_lines_stay_under_driver_tail_cap():
    """Every headline line must fit the driver's last-line parse: under
    the asserted cap, carrying the metric contract keys, and always the
    LAST line of any emit pair."""
    out, parsed = _run_bench({'BENCH_SMOKE': '1',
                              'BENCH_BUDGET_SECONDS': '0'}, timeout=120)
    raw_lines = [ln for ln in out.stdout.strip().splitlines()
                 if ln.startswith('{')]
    heads = [(ln, obj) for ln, obj in zip(raw_lines, parsed)
             if obj.get('headline')]
    assert heads and heads[-1][1] is parsed[-1]
    for ln, obj in heads:
        assert len(ln) < 1500, len(ln)
        for key in ('metric', 'value', 'unit', 'vs_baseline'):
            assert key in obj
    # full and headline lines strictly alternate: a kill between any two
    # writes leaves either a headline last (ideal) or a full line last
    # (still parseable by drivers with a large-enough tail)
    flags = [bool(obj.get('headline')) for obj in parsed]
    assert flags == [i % 2 == 1 for i in range(len(flags))]


def test_headline_worst_case_length_fits():
    """Static worst case: every headline key populated with wide values
    still fits the cap with generous margin — growth of the key list
    must show up here before it can regress the driver parse."""
    import bench
    worst_extra = {}
    for key in bench._HEADLINE_EXTRA_KEYS:
        if key == 'skipped_sections':
            worst_extra[key] = ['imagenet_python_decode'] * 14
        elif key in ('h2d_link_degraded',):
            worst_extra[key] = True
        elif key == 'probe_platform':
            worst_extra[key] = 'tpu'
        else:
            worst_extra[key] = 12345678.90123
    worst_extra['tpu_wedged_midrun'] = True
    line = json.dumps({'metric': 'hello_world_read_rate',
                       'value': 12345678.90123, 'unit': 'samples/sec',
                       'vs_baseline': 12345.678, 'headline': True,
                       'extra': worst_extra})
    assert len(line) < bench._HEADLINE_MAX_CHARS, len(line)


@pytest.mark.slow
def test_poisoned_platform_full_smoke():
    """BENCH_SMOKE under a poisoned device platform: the host sections
    produce real numbers, the device sections produce error markers
    quickly (no per-section cpu retry when the platform is pinned), and
    the final line carries the metric + north-star keys (VERDICT r3 #1
    'done' criterion)."""
    out, parsed = _run_bench({'BENCH_SMOKE': '1',
                              'BENCH_JAX_PLATFORM': 'poisoned_backend',
                              'BENCH_BUDGET_SECONDS': '220'}, timeout=420)
    head = parsed[-1]
    assert head.get('headline') is True
    assert head['value'] > 0, out.stderr[-500:]
    # no silent truncation: every headline key present in the full dict
    # made it onto the headline line
    import bench
    full_extra = parsed[-2]['extra']
    expected = {k for k in bench._HEADLINE_EXTRA_KEYS if k in full_extra}
    assert expected <= set(head['extra'])
    last = parsed[-2]
    assert last['value'] > 0, out.stderr[-500:]
    assert last['vs_baseline'] > 0
    extra = last['extra']
    # host metrics captured
    assert extra['hello_world_batch_rows_per_sec'] > 0
    assert extra['imagenet_batch_rows_per_sec'] > 0
    assert ('vs_tfdata' in extra or 'tfdata_imagenet_error' in extra
            or 'tfdata' in extra.get('skipped_sections', []))
    # the poisoned platform was recorded, and no device section hung:
    # each either errored, was skipped on budget, or (probe-pinned) fell
    # back — presence of ANY of these markers per section is the proof
    assert extra['forced_platform'] == 'poisoned_backend'
    skipped = extra.get('skipped_sections', [])
    for prefix, sec in [('hello_world_jax', 'jax_hello'),
                        ('imagenet_jax', 'jax_imagenet'),
                        ('imagenet_jax_dummy', 'jax_dummy'),
                        ('vit_train', 'vit_train'),
                        ('lm_train', 'lm_train'),
                        ('lm_train_tuned', 'lm_train_tuned'),
                        ('mfu_parts', 'mfu_breakdown'),
                        ('lm_decode', 'lm_decode'),
                        ('pp_bf16', 'pp_bf16')]:
        assert ('%s_error' % prefix in extra or sec in skipped), (
            prefix, sorted(extra))
    # every intermediate line is itself a complete cumulative report
    for line in parsed:
        assert line['metric'] == 'hello_world_read_rate'


class TestShareMath:
    """The share computations behind jax_framework_share and
    lm_train_mfu_breakdown are pure functions — the TPU sections feed
    them; these tests pin the arithmetic and the clamps."""

    def test_staging_shares_partition_the_real_sec_per_row(self):
        import bench
        # real 300 rows/s, dummy 3000 rows/s, link at 500 MB/s for
        # 150 KB/row batches of 64 (link faster than the dummy path, as
        # physics requires — the dummy run includes the same H2D)
        shares = bench.compute_staging_shares(
            300.0, 3000.0, 500.0, 64 * 150 * 1024, 64)
        assert shares is not None
        total = (shares['jax_h2d_share'] + shares['jax_framework_share']
                 + shares['jax_io_decode_share'])
        assert abs(total - 1.0) < 0.01, shares
        # I/O+decode dominates: dummy is 10x faster than real
        assert shares['jax_io_decode_share'] > 0.8

    def test_staging_shares_clamp_on_overlapping_link(self):
        import bench
        # degraded tunnel: the loader's overlapped H2D (dummy 60 rows/s)
        # beats the raw loop (50 MB/s for 1.5 MB rows => ~33 rows/s) —
        # framework share must clamp to 0, not go negative
        shares = bench.compute_staging_shares(
            50.0, 60.0, 50.0, 64 * 1536 * 1024, 64)
        assert shares['jax_framework_share'] == 0.0
        assert 0.0 <= shares['jax_h2d_share'] <= 1.0
        # the partition property must hold in the clamped regime too:
        # the link term is capped at the dummy path's whole time
        total = (shares['jax_h2d_share'] + shares['jax_framework_share']
                 + shares['jax_io_decode_share'])
        assert abs(total - 1.0) < 0.01, shares

    def test_staging_shares_missing_inputs(self):
        import bench
        assert bench.compute_staging_shares(None, 1.0, 1.0, 1, 64) is None
        assert bench.compute_staging_shares(1.0, 1.0, 0.0, 1, 64) is None

    def test_mfu_breakdown_shares_close_and_split_input_wait(self):
        import bench
        flagship = dict(vocab_size=16384, d_model=1536, n_heads=16,
                        n_layers=10, d_ff=6144)
        # 5 steps/s wall with util 1.05 => compute step ~190.5 ms
        shares = bench.compute_mfu_breakdown(
            5.0, 1.05, 193.0,
            {'attn_measured': 40.0, 'norms_measured': 5.0,
             'loss_head_measured': 25.0},
            flagship=flagship, batch=8, seq=1024)
        assert shares is not None
        # the ideal param-matmul term landed (~78 ms at 193 TF/s)
        assert 0.3 < shares['param_matmul_ideal'] < 0.5, shares
        keyed = ['attn_measured', 'norms_measured', 'loss_head_measured',
                 'param_matmul_ideal', 'other']
        assert abs(sum(shares[k] for k in keyed) - 1.0) < 0.01, shares
        assert abs(shares['input_wait_of_step'] - (1 - 1 / 1.05)) < 1e-3

    def test_mfu_breakdown_partial_parts_no_other(self):
        import bench
        shares = bench.compute_mfu_breakdown(
            5.0, None, None, {'attn_measured': 40.0,
                              'norms_measured': None,
                              'loss_head_measured': None})
        assert set(shares) == {'attn_measured'}
        assert bench.compute_mfu_breakdown(
            None, None, None, {'attn_measured': 1.0}) is None
        assert bench.compute_mfu_breakdown(
            5.0, None, None, {'attn_measured': None}) is None
