"""bench.py wedge-proofing contract (VERDICT r3 #1).

Round 3's driver benchmark run was killed by an outer timeout (rc=124)
before bench.py printed its single end-of-run JSON line, losing the whole
round's perf record. The contract under test:

* bench.py prints the CUMULATIVE result JSON after every section, so the
  last complete stdout line is parseable no matter where a kill lands;
* a poisoned/unavailable device platform produces per-section error
  markers (or a probe-pinned CPU fallback), never a hang;
* an exhausted global budget (``BENCH_BUDGET_SECONDS``) skips sections,
  recording them under ``skipped_sections``, and still emits every line.
"""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO_ROOT, 'bench.py')


def _run_bench(env_overrides, timeout):
    env = dict(os.environ)
    # the bench subprocesses must see the repo exactly as the driver runs it
    env.pop('PETASTORM_TPU_NATIVE', None)
    env.update(env_overrides)
    out = subprocess.run([sys.executable, BENCH], capture_output=True,
                         text=True, timeout=timeout, env=env,
                         cwd=REPO_ROOT)
    lines = [ln for ln in out.stdout.strip().splitlines()
             if ln.startswith('{')]
    assert lines, 'no JSON lines emitted; stderr tail: %s' % (
        out.stderr[-500:],)
    return out, [json.loads(ln) for ln in lines]


def test_exhausted_budget_still_emits_parseable_lines():
    """Budget 0: every section skips, yet every section emits a cumulative
    parseable line — the driver's last-line parse can never come up empty
    just because time ran out."""
    _, parsed = _run_bench({'BENCH_SMOKE': '1',
                            'BENCH_BUDGET_SECONDS': '0'}, timeout=120)
    assert len(parsed) >= 12  # one line per section + the final line
    last = parsed[-1]
    assert last['metric'] == 'hello_world_read_rate'
    assert last['unit'] == 'samples/sec'
    skipped = last['extra']['skipped_sections']
    assert 'hello_row' in skipped and 'lm_train' in skipped


@pytest.mark.slow
def test_poisoned_platform_full_smoke():
    """BENCH_SMOKE under a poisoned device platform: the host sections
    produce real numbers, the device sections produce error markers
    quickly (no per-section cpu retry when the platform is pinned), and
    the final line carries the metric + north-star keys (VERDICT r3 #1
    'done' criterion)."""
    out, parsed = _run_bench({'BENCH_SMOKE': '1',
                              'BENCH_JAX_PLATFORM': 'poisoned_backend',
                              'BENCH_BUDGET_SECONDS': '220'}, timeout=420)
    last = parsed[-1]
    assert last['value'] > 0, out.stderr[-500:]
    assert last['vs_baseline'] > 0
    extra = last['extra']
    # host metrics captured
    assert extra['hello_world_batch_rows_per_sec'] > 0
    assert extra['imagenet_batch_rows_per_sec'] > 0
    assert ('vs_tfdata' in extra or 'tfdata_imagenet_error' in extra
            or 'tfdata' in extra.get('skipped_sections', []))
    # the poisoned platform was recorded, and no device section hung:
    # each either errored, was skipped on budget, or (probe-pinned) fell
    # back — presence of ANY of these markers per section is the proof
    assert extra['forced_platform'] == 'poisoned_backend'
    skipped = extra.get('skipped_sections', [])
    for prefix, sec in [('hello_world_jax', 'jax_hello'),
                        ('imagenet_jax', 'jax_imagenet'),
                        ('lm_train', 'lm_train'),
                        ('lm_decode', 'lm_decode'),
                        ('pp_bf16', 'pp_bf16')]:
        assert ('%s_error' % prefix in extra or sec in skipped), (
            prefix, sorted(extra))
    # every intermediate line is itself a complete cumulative report
    for line in parsed:
        assert line['metric'] == 'hello_world_read_rate'
