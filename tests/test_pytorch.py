"""PyTorch bridge tests (reference: ``tests/test_pytorch_dataloader.py``,
``test_pytorch_utils.py``)."""

from decimal import Decimal

import numpy as np
import pytest
import torch

from petastorm_tpu.pytorch import (
    BatchedDataLoader, DataLoader, _sanitize_pytorch_types,
    decimal_friendly_collate,
)
from petastorm_tpu.reader import make_batch_reader, make_reader


class TestSanitize:
    def test_promotions(self):
        row = {'a': np.arange(3, dtype=np.uint16),
               'b': np.arange(3, dtype=np.uint32),
               'c': np.uint16(7),
               'd': np.arange(3, dtype=np.float32)}
        _sanitize_pytorch_types(row)
        assert row['a'].dtype == np.int32
        assert row['b'].dtype == np.int64
        assert np.asarray(row['c']).dtype == np.int64 or \
            np.asarray(row['c']).dtype == np.int32
        assert row['d'].dtype == np.float32

    def test_string_rejected(self):
        with pytest.raises(TypeError, match='no dense tensor representation'):
            _sanitize_pytorch_types({'s': 'hello'})
        with pytest.raises(TypeError, match='no dense tensor representation'):
            _sanitize_pytorch_types({'s': np.array(['a', 'b'])})

    def test_none_rejected(self):
        with pytest.raises(TypeError, match='None'):
            _sanitize_pytorch_types({'x': None})


class TestCollate:
    def test_decimals_pass_through(self):
        out = decimal_friendly_collate([Decimal('1.5'), Decimal('2.5')])
        assert out == [Decimal('1.5'), Decimal('2.5')]

    def test_empty_dict_input(self):
        # reference: test_decimal_friendly_collate_empty_input (:95)
        assert decimal_friendly_collate([dict()]) == dict()

    def test_decimal_in_tuple(self):
        # reference: ..._has_decimals_in_tuple (:140)
        out = decimal_friendly_collate([(Decimal('1'), np.float32(1.0)),
                                        (Decimal('2'), np.float32(2.0))])
        assert out[0] == [Decimal('1'), Decimal('2')]
        assert torch.is_tensor(out[1])

    @pytest.mark.parametrize('np_dtype', [
        np.float32, np.float64, np.int16, np.int32, np.int64, np.uint8,
    ])
    def test_torch_tensorable_dtypes(self, np_dtype):
        # reference: test_torch_tensorable_types (:101)
        row = {'x': np.arange(4, dtype=np_dtype)}
        _sanitize_pytorch_types(row)
        batch = decimal_friendly_collate([row, row])
        assert torch.is_tensor(batch['x']) and batch['x'].shape == (2, 4)

    def test_dict_with_decimal(self):
        out = decimal_friendly_collate([
            {'d': Decimal('1'), 'x': np.float32(1.0)},
            {'d': Decimal('2'), 'x': np.float32(2.0)},
        ])
        assert out['d'] == [Decimal('1'), Decimal('2')]
        assert torch.is_tensor(out['x']) and out['x'].shape == (2,)


_FIELDS = ['^id$', '^id2$', '^matrix_uint16$']


class TestDataLoader:
    def test_batches(self, synthetic_dataset):
        reader = make_reader(synthetic_dataset.url, schema_fields=_FIELDS,
                             shuffle_row_groups=False, num_epochs=1)
        with DataLoader(reader, batch_size=8) as loader:
            batches = list(loader)
        # 100 rows → 12 full + 1 partial
        assert [len(b['id']) for b in batches] == [8] * 12 + [4]
        assert torch.is_tensor(batches[0]['matrix_uint16'])
        assert batches[0]['matrix_uint16'].shape == (8, 2, 3)
        ids = torch.cat([b['id'] for b in batches])
        assert sorted(ids.tolist()) == list(range(100))

    def test_shuffling_buffer(self, synthetic_dataset):
        reader = make_reader(synthetic_dataset.url, schema_fields=['^id$'],
                             shuffle_row_groups=False, num_epochs=1)
        with DataLoader(reader, batch_size=10,
                        shuffling_queue_capacity=50, seed=1) as loader:
            ids = torch.cat([b['id'] for b in loader]).tolist()
        assert sorted(ids) == list(range(100))
        assert ids != list(range(100))

    def test_reiteration_resets(self, synthetic_dataset):
        reader = make_reader(synthetic_dataset.url, schema_fields=['^id$'],
                             shuffle_row_groups=False, num_epochs=1)
        with DataLoader(reader, batch_size=25) as loader:
            first = [b['id'] for b in loader]
            second = [b['id'] for b in loader]
        assert len(first) == len(second) == 4

    def test_nested_iteration_rejected(self, synthetic_dataset):
        reader = make_reader(synthetic_dataset.url, schema_fields=['^id$'],
                             num_epochs=1)
        with DataLoader(reader, batch_size=10) as loader:
            it = iter(loader)
            next(it)
            with pytest.raises(RuntimeError, match='already being iterated'):
                next(iter(loader))


class TestBatchedDataLoader:
    def test_fixed_batches(self, scalar_dataset):
        reader = make_batch_reader(scalar_dataset.url,
                                   schema_fields=['^id$', '^float64$'],
                                   shuffle_row_groups=False, num_epochs=1)
        with BatchedDataLoader(reader, batch_size=16) as loader:
            batches = list(loader)
        assert [len(b['id']) for b in batches] == [16] * 6 + [4]
        assert torch.is_tensor(batches[0]['float64'])
        ids = torch.cat([b['id'] for b in batches])
        assert sorted(ids.tolist()) == list(range(100))

    def test_shuffled_exactly_once(self, scalar_dataset):
        reader = make_batch_reader(scalar_dataset.url,
                                   schema_fields=['^id$'],
                                   shuffle_row_groups=False, num_epochs=1)
        with BatchedDataLoader(reader, batch_size=10,
                               shuffling_queue_capacity=64, seed=5) as loader:
            ids = torch.cat([b['id'] for b in loader]).tolist()
        assert sorted(ids) == list(range(100))
        assert ids != list(range(100))

    def test_string_field_rejected(self, scalar_dataset):
        reader = make_batch_reader(scalar_dataset.url,
                                   schema_fields=['^id$', '^string$'],
                                   num_epochs=1)
        with BatchedDataLoader(reader, batch_size=10) as loader:
            with pytest.raises(TypeError, match='no dense tensor representation'):
                list(loader)

    def test_keep_fields(self, scalar_dataset):
        reader = make_batch_reader(scalar_dataset.url,
                                   shuffle_row_groups=False, num_epochs=1)
        with BatchedDataLoader(reader, batch_size=10,
                               keep_fields=['id', 'float64']) as loader:
            batch = next(iter(loader))
        assert set(batch) == {'id', 'float64'}

    def test_inmemory_cache_replay(self, scalar_dataset):
        reader = make_batch_reader(scalar_dataset.url,
                                   schema_fields=['^id$'],
                                   shuffle_row_groups=False, num_epochs=1)
        with BatchedDataLoader(reader, batch_size=20,
                               inmemory_cache_all=True) as loader:
            first = torch.cat([b['id'] for b in loader]).tolist()
            # second epoch must come from RAM (reader is exhausted and
            # deliberately NOT reset)
            second = torch.cat([b['id'] for b in loader]).tolist()
            third = torch.cat([b['id'] for b in loader]).tolist()
        assert sorted(first) == list(range(100))
        assert second == first and third == first

    def test_inmemory_cache_reshuffles_epochs(self, scalar_dataset):
        reader = make_batch_reader(scalar_dataset.url,
                                   schema_fields=['^id$'],
                                   shuffle_row_groups=False, num_epochs=1)
        with BatchedDataLoader(reader, batch_size=20,
                               shuffling_queue_capacity=128, seed=0,
                               inmemory_cache_all=True) as loader:
            first = torch.cat([b['id'] for b in loader]).tolist()
            second = torch.cat([b['id'] for b in loader]).tolist()
        assert sorted(first) == sorted(second) == list(range(100))
        assert first != second  # per-epoch reshuffle from the cache

    def test_inmemory_cache_multi_epoch_reader_rejected(self, scalar_dataset):
        # reference: test_mem_cache_reader_num_epochs_error (:214)
        for bad_epochs in (2, None):
            reader = make_batch_reader(scalar_dataset.url,
                                       schema_fields=['^id$'],
                                       num_epochs=bad_epochs)
            try:
                with pytest.raises(ValueError, match='num_epochs=1'):
                    BatchedDataLoader(reader, batch_size=10,
                                      inmemory_cache_all=True)
            finally:
                reader.stop()
                reader.join()

    def test_abandoned_first_epoch_cannot_silently_replay(self,
                                                          scalar_dataset):
        # abandoning the caching pass mid-epoch must NOT leave a truncated
        # cache that later replays as if complete: re-iteration surfaces the
        # reader's reset-mid-epoch error instead
        reader = make_batch_reader(scalar_dataset.url,
                                   schema_fields=['^id$'],
                                   shuffle_row_groups=False, num_epochs=1)
        with BatchedDataLoader(reader, batch_size=10,
                               inmemory_cache_all=True) as loader:
            it = iter(loader)
            next(it)
            it.close()  # explicit abandonment mid-epoch
            with pytest.raises(NotImplementedError, match='middle'):
                list(loader)

    def test_transform_fn(self, scalar_dataset):
        reader = make_batch_reader(scalar_dataset.url,
                                   schema_fields=['^float64$'],
                                   num_epochs=1)

        def to_half(columns):
            return {k: torch.as_tensor(v).to(torch.float16)
                    for k, v in columns.items()}

        with BatchedDataLoader(reader, batch_size=10,
                               transform_fn=to_half) as loader:
            batch = next(iter(loader))
        assert batch['float64'].dtype == torch.float16
