"""Elastic checkpoint resume: N-shard loader state restored on M shards.

The reference has no reader checkpointing at all; this framework's
per-shard states additionally carry shard-independent item identities
(``items_global``), so a pod resize between save and restore merges all
shards' progress (``merge_loader_states``) and re-localizes it under the
new shard layout — at-least-once, nothing lost.
"""

import numpy as np
import pytest

from petastorm_tpu.jax import make_jax_loader
from petastorm_tpu.jax.checkpoint import merge_loader_states
from petastorm_tpu.reader import make_batch_reader

pytestmark = pytest.mark.slow


def _drain_ids(reader):
    ids = []
    for batch in reader:
        ids.extend(np.asarray(batch.id).tolist())
    return ids


def _consume_batches(reader, n):
    ids = []
    for _ in range(n):
        ids.extend(np.asarray(next(reader).id).tolist())
    return ids


class TestMergeLoaderStates:
    def test_merge_requires_items_global(self):
        with pytest.raises(ValueError, match='items_global'):
            merge_loader_states([{'epoch': 0, 'consumed_items': [],
                                  'seed': 0, 'iterations_remaining': 1}])
        with pytest.raises(ValueError, match='no loader states'):
            merge_loader_states([])

    def test_merge_takes_earliest_epoch_and_unions_consumed(self):
        s_behind = {'epoch': 0, 'seed': 7, 'iterations_remaining': 3,
                    'consumed_items': [1],
                    'items_global': [[0, 0], [2, 0], [4, 0]]}
        s_ahead = {'epoch': 1, 'seed': 7, 'iterations_remaining': 2,
                   'consumed_items': [0],
                   'items_global': [[1, 0], [3, 0]]}
        merged = merge_loader_states([s_behind, s_ahead])
        assert merged['epoch'] == 0
        # the behind shard contributes its consumed subset; the ahead
        # shard finished epoch 0 entirely, so ALL its items count
        assert merged['consumed_global'] == [[1, 0], [2, 0], [3, 0]]
        # epoch + remaining is the configured total on both shards (3)
        assert merged['iterations_remaining'] == 3
        assert merged['seed'] == 7

    def test_merge_infinite_epochs(self):
        s = {'epoch': 2, 'seed': 0, 'iterations_remaining': None,
             'consumed_items': [], 'items_global': [[0, 0]]}
        assert merge_loader_states([s, s])['iterations_remaining'] is None

    def test_merge_seed_pick_is_order_independent(self):
        # seed=None shard families carry an independent random uint32 per
        # process (ventilator), and the merge payload arrives in arbitrary
        # dict order — the merged seed must not depend on entry order
        base = {'epoch': 0, 'iterations_remaining': 1,
                'consumed_items': [], 'items_global': [[0, 0]]}
        a, b = dict(base, seed=9), dict(base, seed=2)
        assert (merge_loader_states([a, b])['seed']
                == merge_loader_states([b, a])['seed'])
        # None mixed with ints must not crash the deterministic pick
        c = dict(base, seed=None)
        assert (merge_loader_states([a, c])['seed']
                == merge_loader_states([c, a])['seed'])

    def test_merge_rejects_mixed_sharded_unsharded(self):
        # one entry without shard_count must not bypass the
        # complete-family validation for the rest
        base = {'epoch': 0, 'seed': 0, 'iterations_remaining': 1,
                'consumed_items': [], 'items_global': [[0, 0]]}
        sharded = dict(base, shard_count=2, cur_shard=0)
        legacy = dict(base)
        with pytest.raises(ValueError, match='mix sharded'):
            merge_loader_states([sharded, legacy])
        # while a complete family still validates (and passes)
        family = [dict(base, shard_count=2, cur_shard=0),
                  dict(base, shard_count=2, cur_shard=1)]
        assert merge_loader_states(family)['epoch'] == 0
        # and an incomplete/duplicated family still raises
        with pytest.raises(ValueError, match='complete shard'):
            merge_loader_states([sharded, dict(sharded)])
        # shard_count present but cur_shard missing/null: ValueError (the
        # starts-fresh fallback), never a TypeError from sorting None
        with pytest.raises(ValueError, match='integer cur_shard'):
            merge_loader_states([sharded,
                                 dict(base, shard_count=2)])


class TestReaderRescale:
    def test_two_shards_resume_on_three(self, scalar_dataset):
        # phase 1: two shards each consume part of their epoch
        states, seen_before = [], []
        for shard in range(2):
            with make_batch_reader(scalar_dataset.url,
                                   schema_fields=['^id$'],
                                   cur_shard=shard, shard_count=2,
                                   shuffle_row_groups=True, seed=13,
                                   num_epochs=1) as reader:
                seen_before.extend(_consume_batches(reader, 2))
                states.append(reader.state_dict())
        assert all('items_global' in s for s in states)

        merged = merge_loader_states(states)

        # phase 2: THREE shards resume from the merged global state
        seen_after = []
        for shard in range(3):
            with make_batch_reader(scalar_dataset.url,
                                   schema_fields=['^id$'],
                                   cur_shard=shard, shard_count=3,
                                   shuffle_row_groups=True, seed=13,
                                   num_epochs=1) as reader:
                reader.load_state_dict(merged)
                seen_after.extend(_drain_ids(reader))

        # at-least-once: union covers the dataset, and the resumed pass
        # skipped the globally-consumed row-groups (strictly fewer rows
        # than a fresh epoch)
        assert set(seen_before) | set(seen_after) == set(range(100))
        assert len(seen_after) < 100
        # consumed row-groups are not re-delivered: phase-1 rows reappear
        # only if their row-group was still partially in flight, which
        # cannot exceed one batch per phase-1 shard
        assert len(set(seen_before) & set(seen_after)) == 0

    def test_downscale_to_one_shard(self, scalar_dataset):
        states, seen_before = [], []
        for shard in range(2):
            with make_batch_reader(scalar_dataset.url,
                                   schema_fields=['^id$'],
                                   cur_shard=shard, shard_count=2,
                                   shuffle_row_groups=False,
                                   num_epochs=1) as reader:
                seen_before.extend(_consume_batches(reader, 1))
                states.append(reader.state_dict())
        merged = merge_loader_states(states)
        with make_batch_reader(scalar_dataset.url, schema_fields=['^id$'],
                               shuffle_row_groups=False,
                               num_epochs=1) as reader:
            reader.load_state_dict(merged)
            seen_after = _drain_ids(reader)
        assert set(seen_before) | set(seen_after) == set(range(100))
        assert len(set(seen_before) & set(seen_after)) == 0


class TestCheckpointerElasticRestore:
    def test_restore_merges_on_process_count_mismatch(self, tmp_path,
                                                      scalar_dataset,
                                                      monkeypatch):
        # Save with a payload gathered from TWO (simulated) processes,
        # restore in this ONE-process runtime: restore_loader must take
        # the elastic-merge branch and the resumed single loader must
        # cover everything the two shards had not consumed.
        from petastorm_tpu.jax import TrainCheckpointer
        from petastorm_tpu.jax import checkpoint as ckpt_mod

        states, seen_before = [], []
        for shard in range(2):
            with make_jax_loader(scalar_dataset.url, batch_size=10,
                                 fields=['^id$'], num_epochs=1,
                                 cur_shard=shard, shard_count=2,
                                 shuffle_row_groups=True, seed=3,
                                 last_batch='short') as loader:
                it = iter(loader)
                for _ in range(2):
                    seen_before.extend(np.asarray(next(it)['id']).tolist())
                states.append(loader.state_dict())

        monkeypatch.setattr(
            ckpt_mod, '_gather_per_process',
            lambda state: {'0': states[0], '1': states[1]})
        with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
            ckpt.save(4, {'w': np.zeros(2, np.float32)},
                      loader=_StateOnly(states[0]))

        seen_after = []
        with make_jax_loader(scalar_dataset.url, batch_size=10,
                             fields=['^id$'], num_epochs=1,
                             shuffle_row_groups=True, seed=3,
                             last_batch='short') as loader:
            with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
                assert ckpt.restore_loader(loader) == 4
            for batch in loader:
                seen_after.extend(np.asarray(batch['id']).tolist())

        assert set(seen_before) | set(seen_after) == set(range(100))
        assert len(seen_after) < 100

    def test_pre_elastic_state_still_starts_fresh(self, tmp_path,
                                                  scalar_dataset,
                                                  monkeypatch):
        # a resized payload WITHOUT items_global (old checkpoint): the
        # documented starts-fresh fallback, not a crash
        from petastorm_tpu.jax import TrainCheckpointer
        from petastorm_tpu.jax import checkpoint as ckpt_mod
        legacy = {'version': 1, 'seed': 0, 'epoch': 0,
                  'iterations_remaining': 1, 'consumed_items': []}
        monkeypatch.setattr(ckpt_mod, '_gather_per_process',
                            lambda state: {'0': legacy, '1': legacy})
        with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
            ckpt.save(2, {'w': np.zeros(2, np.float32)},
                      loader=_StateOnly(legacy))
        with make_jax_loader(scalar_dataset.url, batch_size=10,
                             fields=['^id$'], num_epochs=1,
                             last_batch='short') as loader:
            with TrainCheckpointer(str(tmp_path / 'ckpt')) as ckpt:
                assert ckpt.restore_loader(loader) == 2
            seen = []
            for batch in loader:
                seen.extend(np.asarray(batch['id']).tolist())
        assert set(seen) == set(range(100))  # full fresh pass


class _StateOnly:
    """Stands in for a loader at save time (state_dict only)."""

    def __init__(self, state):
        self._state = state

    def state_dict(self):
        return self._state


class TestIdentityAndValidation:
    def test_incomplete_shard_family_rejected(self):
        def s(cur, count):
            return {'epoch': 0, 'seed': 0, 'iterations_remaining': 1,
                    'consumed_items': [], 'items_global': [[0, 0, 1]],
                    'cur_shard': cur, 'shard_count': count}
        with pytest.raises(ValueError, match='complete shard family'):
            merge_loader_states([s(0, 2), s(0, 2)])  # shard 0 twice
        with pytest.raises(ValueError, match='disagree on shard_count'):
            merge_loader_states([s(0, 2), s(1, 3)])

    def test_drop_partition_count_change_re_reads(self, scalar_dataset):
        # identity includes the drop-partition COUNT: a state saved at
        # k=2 must NOT mark k=1 items consumed (the old drop covered only
        # half the piece's rows) — the piece is re-read in full instead
        with make_batch_reader(scalar_dataset.url, schema_fields=['^id$'],
                               shuffle_row_groups=False,
                               shuffle_row_drop_partitions=2,
                               num_epochs=1) as reader:
            _consume_batches(reader, 2)
            state = reader.state_dict()
        assert state['consumed_items']
        merged = merge_loader_states([state])
        with make_batch_reader(scalar_dataset.url, schema_fields=['^id$'],
                               shuffle_row_groups=False,
                               num_epochs=1) as reader:
            reader.load_state_dict(merged)
            seen = _drain_ids(reader)
        assert set(seen) == set(range(100))  # nothing skipped

    def test_malformed_entry_rejected_as_value_error(self):
        # restore_loader's starts-fresh fallback catches ValueError only:
        # a None/non-dict payload entry must surface as that, never as a
        # TypeError that would abort the whole training restore
        good = {'epoch': 0, 'seed': 0, 'iterations_remaining': 1,
                'consumed_items': [], 'items_global': [[0, 0, 1]]}
        with pytest.raises(ValueError, match='malformed'):
            merge_loader_states([good, None])
