"""Codec round-trip tests (parity model: petastorm/tests/test_codec_*.py)."""

from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.codecs import (
    CompressedImageCodec, CompressedNdarrayCodec, NdarrayCodec, ScalarCodec,
    codec_from_json, codec_to_json,
)
from petastorm_tpu.unischema import UnischemaField


def _roundtrip(codec, field, value):
    return codec.decode(field, codec.encode(field, value))


class TestScalarCodec:
    def test_int_roundtrip(self):
        f = UnischemaField('x', np.int32, ())
        c = ScalarCodec(pa.int32())
        assert _roundtrip(c, f, np.int32(42)) == 42
        assert isinstance(_roundtrip(c, f, 42), np.int32)

    def test_float_string_bool(self):
        assert _roundtrip(ScalarCodec(pa.float64()),
                          UnischemaField('x', np.float64, ()), 1.5) == 1.5
        assert _roundtrip(ScalarCodec(pa.string()),
                          UnischemaField('x', np.str_, ()), 'héllo') == 'héllo'
        assert _roundtrip(ScalarCodec(pa.bool_()),
                          UnischemaField('x', np.bool_, ()), True)

    def test_decimal(self):
        f = UnischemaField('x', Decimal, ())
        c = ScalarCodec(pa.string())
        out = _roundtrip(c, f, Decimal('123.4567'))
        assert out == Decimal('123.4567')

    def test_decode_batch_vectorized(self):
        f = UnischemaField('x', np.int16, ())
        c = ScalarCodec(pa.int32())
        out = c.decode_batch(f, [1, 2, 3])
        assert out.dtype == np.int16
        np.testing.assert_array_equal(out, [1, 2, 3])

    def test_accepts_numpy_dtype_param(self):
        c = ScalarCodec(np.int64)
        assert c.arrow_type(None) == pa.int64()


class TestNdarrayCodec:
    @pytest.mark.parametrize('dtype', [np.uint8, np.int64, np.float32, np.float64])
    def test_roundtrip(self, dtype):
        f = UnischemaField('a', dtype, (None, 3))
        c = NdarrayCodec()
        arr = (np.random.rand(7, 3) * 100).astype(dtype)
        np.testing.assert_array_equal(_roundtrip(c, f, arr), arr)

    def test_unicode_array(self):
        f = UnischemaField('a', np.dtype('<U5').type, (None,))
        c = NdarrayCodec()
        arr = np.array(['abc', 'défgh'], dtype='<U5')
        out = c.decode(f, c.encode(UnischemaField('a', arr.dtype.type, (None,)), arr))
        np.testing.assert_array_equal(out, arr)

    def test_shape_mismatch_raises(self):
        f = UnischemaField('a', np.float32, (2, 2))
        with pytest.raises(ValueError, match='shape'):
            NdarrayCodec().encode(f, np.zeros((3, 3), dtype=np.float32))

    def test_dtype_mismatch_raises(self):
        f = UnischemaField('a', np.float32, (2,))
        with pytest.raises(ValueError, match='dtype'):
            NdarrayCodec().encode(f, np.zeros((2,), dtype=np.float64))


class TestCompressedNdarrayCodec:
    def test_roundtrip_compresses(self):
        f = UnischemaField('a', np.float64, (None, None))
        c = CompressedNdarrayCodec()
        arr = np.zeros((100, 100))
        encoded = c.encode(f, arr)
        assert len(encoded) < arr.nbytes / 10  # zeros compress well
        np.testing.assert_array_equal(c.decode(f, encoded), arr)


class TestCompressedImageCodec:
    def test_png_lossless_roundtrip(self):
        f = UnischemaField('im', np.uint8, (12, 10, 3))
        c = CompressedImageCodec('png')
        img = np.random.randint(0, 255, (12, 10, 3), dtype=np.uint8)
        np.testing.assert_array_equal(_roundtrip(c, f, img), img)

    def test_grayscale(self):
        f = UnischemaField('im', np.uint8, (12, 10))
        c = CompressedImageCodec('png')
        img = np.random.randint(0, 255, (12, 10), dtype=np.uint8)
        np.testing.assert_array_equal(_roundtrip(c, f, img), img)

    def test_jpeg_lossy_close(self):
        f = UnischemaField('im', np.uint8, (32, 32, 3))
        c = CompressedImageCodec('jpeg', quality=95)
        img = np.full((32, 32, 3), 128, dtype=np.uint8)
        out = _roundtrip(c, f, img)
        assert out.shape == img.shape
        assert np.abs(out.astype(int) - img.astype(int)).mean() < 5

    def test_channel_order_is_rgb(self):
        # A pure-red RGB image must come back pure red (BGR swap correctness).
        f = UnischemaField('im', np.uint8, (4, 4, 3))
        c = CompressedImageCodec('png')
        img = np.zeros((4, 4, 3), dtype=np.uint8)
        img[:, :, 0] = 255
        out = _roundtrip(c, f, img)
        np.testing.assert_array_equal(out, img)

    def test_uint16_png(self):
        f = UnischemaField('im', np.uint16, (8, 8))
        c = CompressedImageCodec('png')
        img = np.random.randint(0, 2 ** 16 - 1, (8, 8), dtype=np.uint16)
        np.testing.assert_array_equal(_roundtrip(c, f, img), img)

    def test_bad_codec_name(self):
        with pytest.raises(ValueError):
            CompressedImageCodec('gif')

    def test_decode_batch(self):
        f = UnischemaField('im', np.uint8, (6, 6, 3))
        c = CompressedImageCodec('png')
        imgs = [np.random.randint(0, 255, (6, 6, 3), dtype=np.uint8) for _ in range(4)]
        encoded = [c.encode(f, im) for im in imgs]
        out = c.decode_batch(f, encoded)
        for got, want in zip(out, imgs):
            np.testing.assert_array_equal(got, want)


def test_codec_json_roundtrip():
    for codec in [CompressedImageCodec('jpeg', 70), NdarrayCodec(),
                  CompressedNdarrayCodec(), ScalarCodec(pa.int32()), None]:
        d = codec_to_json(codec)
        restored = codec_from_json(d)
        assert type(restored) is type(codec)
    restored = codec_from_json(codec_to_json(ScalarCodec(pa.decimal128(10, 2))))
    assert restored.arrow_type(None) == pa.decimal128(10, 2)


def test_reference_byte_compat_npy():
    """NdarrayCodec bytes must be a plain .npy stream (np.load readable)."""
    f = UnischemaField('a', np.int32, (3,))
    encoded = NdarrayCodec().encode(f, np.array([1, 2, 3], dtype=np.int32))
    assert bytes(encoded[:6]) == b'\x93NUMPY'


class TestBatchedImageDecode:
    def _field(self, shape=(16, 32, 3)):
        from petastorm_tpu.unischema import UnischemaField
        return UnischemaField('im', np.uint8, shape,
                              CompressedImageCodec('png'), False)

    def test_dense_batch_matches_per_cell(self):
        field = self._field()
        codec = field.codec
        rng = np.random.RandomState(0)
        imgs = [rng.randint(0, 255, (16, 32, 3), np.uint8) for _ in range(12)]
        cells = [codec.encode(field, im) for im in imgs]
        batch = codec.decode_batch(field, cells)
        assert isinstance(batch, np.ndarray) and batch.shape == (12, 16, 32, 3)
        for got, im in zip(batch, imgs):
            np.testing.assert_array_equal(got, im)

    def test_jpeg_batch_matches_per_cell(self, monkeypatch):
        # under PETASTORM_TPU_JPEG_FANCY=1 the native batch loop is
        # bit-identical to the per-cell cv2 path (the strict-compat mode);
        # the env-unset DEFAULT auto-calibrates the chroma-upsampling mode
        # per process, so decoded chroma may differ from cv2 within the
        # tolerance tests/test_native.py pins
        from petastorm_tpu.unischema import UnischemaField
        monkeypatch.setenv('PETASTORM_TPU_JPEG_FANCY', '1')
        field = UnischemaField('im', np.uint8, (24, 24, 3),
                               CompressedImageCodec('jpeg', quality=90), False)
        codec = field.codec
        rng = np.random.RandomState(1)
        imgs = [rng.randint(0, 255, (24, 24, 3), np.uint8) for _ in range(8)]
        cells = [codec.encode(field, im) for im in imgs]
        batch = codec.decode_batch(field, cells)
        singles = [codec.decode(field, c) for c in cells]
        for got, single in zip(batch, singles):
            np.testing.assert_array_equal(got, single)

    def test_jpeg_batch_default_close_to_per_cell(self, monkeypatch):
        from petastorm_tpu.unischema import UnischemaField
        monkeypatch.delenv('PETASTORM_TPU_JPEG_FANCY', raising=False)
        field = UnischemaField('im', np.uint8, (24, 24, 3),
                               CompressedImageCodec('jpeg', quality=90), False)
        codec = field.codec
        rng = np.random.RandomState(1)
        imgs = [rng.randint(0, 255, (24, 24, 3), np.uint8) for _ in range(8)]
        cells = [codec.encode(field, im) for im in imgs]
        batch = np.asarray(codec.decode_batch(field, cells)).astype(int)
        singles = np.stack([codec.decode(field, c)
                            for c in cells]).astype(int)
        assert np.abs(batch - singles).mean() < 16.0  # chroma-interp only

    def test_variable_shape_falls_back_to_list(self):
        field = self._field(shape=(None, None, 3))
        codec = field.codec
        rng = np.random.RandomState(2)
        imgs = [rng.randint(0, 255, (8 + i, 8, 3), np.uint8) for i in range(5)]
        batch = codec.decode_batch(field, [codec.encode(field, im) for im in imgs])
        assert isinstance(batch, list)
        assert [b.shape for b in batch] == [(8 + i, 8, 3) for i in range(5)]

    def test_shape_surprise_falls_back(self):
        # a stored cell whose decoded shape differs from the declared fixed
        # shape must come back with its TRUE shape via the fallback path
        field = self._field(shape=(16, 32, 3))
        codec = field.codec
        rng = np.random.RandomState(3)
        ok = rng.randint(0, 255, (16, 32, 3), np.uint8)
        odd = rng.randint(0, 255, (4, 4, 3), np.uint8)
        odd_field = self._field(shape=(4, 4, 3))
        cells = [codec.encode(field, ok) for _ in range(4)]
        cells.append(codec.encode(odd_field, odd))
        batch = codec.decode_batch(field, cells)
        assert isinstance(batch, list)
        assert batch[-1].shape == (4, 4, 3)


class TestBinaryCellViews:
    """Zero-copy arrow binary cell extraction feeding the image decode."""

    def _views(self, arr):
        from petastorm_tpu.arrow_worker import _binary_cell_views
        return _binary_cell_views(arr)

    def test_plain_binary_round_trip(self):
        import pyarrow as pa
        payloads = [b'abc', b'', b'xyzw']
        cells = self._views(pa.chunked_array([pa.array(payloads,
                                                       type=pa.binary())]))
        assert [bytes(c) for c in cells] == payloads
        assert all(c.dtype == np.uint8 for c in cells)

    def test_nulls_preserved(self):
        import pyarrow as pa
        cells = self._views(pa.array([b'abc', None, b'de'], type=pa.binary()))
        assert bytes(cells[0]) == b'abc' and cells[1] is None
        assert bytes(cells[2]) == b'de'

    def test_sliced_array_offsets(self):
        import pyarrow as pa
        arr = pa.array([b'aa', b'bb', b'cc', b'dd'], type=pa.binary())
        cells = self._views(arr.slice(1, 2))
        assert [bytes(c) for c in cells] == [b'bb', b'cc']

    def test_large_binary(self):
        import pyarrow as pa
        arr = pa.array([b'abc', b'defg'], type=pa.large_binary())
        cells = self._views(arr)
        assert [bytes(c) for c in cells] == [b'abc', b'defg']

    def test_non_binary_returns_none(self):
        import pyarrow as pa
        assert self._views(pa.array([1, 2, 3])) is None

    def test_image_decode_from_views(self):
        import pyarrow as pa
        from petastorm_tpu.codecs import CompressedImageCodec
        from petastorm_tpu.unischema import UnischemaField
        field = UnischemaField('im', np.uint8, (8, 6, 3),
                               CompressedImageCodec('png'), False)
        rng = np.random.RandomState(0)
        images = [rng.randint(0, 255, (8, 6, 3), np.uint8) for _ in range(5)]
        encoded = [bytes(field.codec.encode(field, im)) for im in images]
        cells = self._views(pa.array(encoded, type=pa.binary()))
        batch = field.codec.decode_batch(field, cells)
        for got, want in zip(batch, images):
            np.testing.assert_array_equal(got, want)


class TestDirectRgbDecode:
    def _field(self, shape, fmt='png'):
        from petastorm_tpu.codecs import CompressedImageCodec
        from petastorm_tpu.unischema import UnischemaField
        return UnischemaField('im', np.uint8, shape,
                             CompressedImageCodec(fmt), False)

    def test_header_sniff(self):
        import cv2
        from petastorm_tpu.codecs import CompressedImageCodec
        rgb = np.random.RandomState(0).randint(0, 255, (10, 12, 3), np.uint8)
        gray = rgb[:, :, 0]
        sniff = CompressedImageCodec._is_3_channel
        for ext in ('.png', '.jpeg'):
            ok, enc3 = cv2.imencode(ext, rgb)
            ok, enc1 = cv2.imencode(ext, gray)
            assert sniff(np.frombuffer(enc3.tobytes(), np.uint8)), ext
            assert not sniff(np.frombuffer(enc1.tobytes(), np.uint8)), ext
        assert not sniff(np.frombuffer(b'garbage' * 10, np.uint8))

    @pytest.mark.parametrize('fmt', ['png', 'jpeg'])
    def test_batch_matches_single_decode(self, fmt, monkeypatch):
        # the direct-RGB fast path must be bit-identical to decode() —
        # jpeg under strict mode (the env-unset default auto-calibrates
        # the upsampling mode, so chroma may differ within the tolerance
        # test_native.py pins)
        monkeypatch.setenv('PETASTORM_TPU_JPEG_FANCY', '1')
        field = self._field((20, 24, 3), fmt)
        rng = np.random.RandomState(1)
        images = [rng.randint(0, 255, (20, 24, 3), np.uint8)
                  for _ in range(6)]
        cells = [field.codec.encode(field, im) for im in images]
        batch = field.codec.decode_batch(field, cells)
        for got, cell in zip(batch, cells):
            np.testing.assert_array_equal(
                got, field.codec.decode(field, cell))

    def test_grayscale_cell_in_rgb_field_keeps_true_shape(self):
        # a foreign-written grayscale cell must surface with its TRUE shape
        # through the fallback, never silently colorized to 3 channels
        import cv2
        field = self._field((10, 12, 3))
        rgb_field = self._field((10, 12, 3))
        rng = np.random.RandomState(2)
        cells = [rgb_field.codec.encode(
            rgb_field, rng.randint(0, 255, (10, 12, 3), np.uint8))
            for _ in range(4)]
        ok, gray = cv2.imencode('.png',
                                rng.randint(0, 255, (10, 12), np.uint8))
        cells.append(bytearray(gray.tobytes()))
        batch = field.codec.decode_batch(field, cells)
        assert isinstance(batch, list)
        assert batch[-1].shape == (10, 12)

    def test_16bit_png_cell_matches_row_decode(self):
        # 16-bit RGB PNG sniffs as NOT eligible for the fast path; batched
        # and row decode must produce identical values (mod-256 cast)
        import cv2
        field = self._field((6, 8, 3))
        rng = np.random.RandomState(3)
        deep = rng.randint(0, 2 ** 16, (6, 8, 3)).astype(np.uint16)
        ok, enc = cv2.imencode('.png', deep)
        assert ok
        cell = np.frombuffer(enc.tobytes(), np.uint8)
        from petastorm_tpu.codecs import CompressedImageCodec
        assert not CompressedImageCodec._is_3_channel(cell)
        batch = field.codec.decode_batch(field, [cell] * 3)
        single = field.codec.decode(field, cell)
        for got in batch:
            np.testing.assert_array_equal(got, single)

    def test_exif_oriented_jpeg_not_rotated(self, monkeypatch):
        # EXIF Orientation must be IGNORED on the fast path, exactly like
        # decode()'s IMREAD_UNCHANGED (strict mode for the exact compare)
        import cv2
        monkeypatch.setenv('PETASTORM_TPU_JPEG_FANCY', '1')
        field = self._field((10, 10, 3), 'jpeg')
        rng = np.random.RandomState(4)
        img = rng.randint(0, 255, (10, 10, 3), np.uint8)
        ok, enc = cv2.imencode('.jpeg', img)
        raw = enc.tobytes()
        # splice an APP1 Exif segment with Orientation=3 after SOI
        tiff = (b'II*\x00\x08\x00\x00\x00'          # TIFF header, IFD @8
                b'\x01\x00'                          # 1 entry
                b'\x12\x01\x03\x00\x01\x00\x00\x00\x03\x00\x00\x00'
                b'\x00\x00\x00\x00')                 # next IFD = 0
        exif_payload = b'Exif\x00\x00' + tiff
        app1 = b'\xff\xe1' + (len(exif_payload) + 2).to_bytes(2, 'big') \
            + exif_payload
        tagged = np.frombuffer(raw[:2] + app1 + raw[2:], np.uint8)
        from petastorm_tpu.codecs import CompressedImageCodec
        assert CompressedImageCodec._is_3_channel(tagged)
        batch = field.codec.decode_batch(field, [tagged] * 4)
        single = field.codec.decode(field, tagged)
        for got in batch:
            np.testing.assert_array_equal(got, single)
