"""Per-item tracing unit tests: context minting + deterministic sampling,
flight-recorder ring semantics, Chrome trace-event export schema, the
delta-channel piggyback, the shared telemetry.refresh() knob reload, the
producer-bound auto-dump, and the disabled-overhead guard the ISSUE's
acceptance criteria require."""

import json
import time

import pytest

from petastorm_tpu import telemetry as T
from petastorm_tpu.telemetry import tracing
from petastorm_tpu.telemetry.recorder import FlightRecorder
from petastorm_tpu.telemetry import spans
from petastorm_tpu.telemetry.registry import (
    MetricsRegistry, dump_delta_frame, load_delta_frame,
)
from petastorm_tpu.telemetry.tracing import (
    _NOOP_ACTIVATION, activate, attempt, ctx_for, mint,
)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    T.reset_for_tests()
    yield
    T.reset_for_tests()


@pytest.fixture
def traced(monkeypatch):
    monkeypatch.setenv('PETASTORM_TPU_TRACE', '1')
    T.refresh()
    yield
    # monkeypatch restores the env; the autouse fixture re-reads it


# -- context mint + sampling --------------------------------------------------


def test_mint_disabled_by_default():
    assert not tracing.trace_enabled()
    assert mint(0) is None
    assert mint(7, epoch=3, shard=1) is None


def test_mint_and_ctx_for_agree(traced):
    ctx = mint(5, epoch=2, shard=1)
    assert ctx is not None
    assert ctx.item_seq == 5 and ctx.epoch == 2 and ctx.shard == 1
    assert ctx_for(5, 2, 1) == ctx
    # different epoch → different trace id (re-reads of the same item in a
    # later epoch are distinct timeline objects)
    assert ctx_for(5, 3, 1).trace_id != ctx.trace_id
    assert ctx_for(None) is None


def test_sampling_is_deterministic_on_item_seq(traced, monkeypatch):
    monkeypatch.setenv('PETASTORM_TPU_TRACE_SAMPLE', '1/3')
    T.refresh()
    sampled = [i for i in range(9) if mint(i) is not None]
    assert sampled == [0, 3, 6]
    # the consumer re-derives the SAME decision + id without wire state
    for i in range(9):
        a, b = mint(i), ctx_for(i)
        assert (a is None) == (b is None)
        if a is not None:
            assert a == b
    monkeypatch.setenv('PETASTORM_TPU_TRACE_SAMPLE', '4')  # plain-N form
    T.refresh()
    assert [i for i in range(8) if mint(i)] == [0, 4]


def test_refresh_flips_all_knobs_through_one_entry_point(monkeypatch):
    """Satellite: one shared telemetry.refresh() re-reads metrics, trace
    and sampling knobs together."""
    assert not tracing.trace_enabled() and not T.metrics_disabled()
    monkeypatch.setenv('PETASTORM_TPU_TRACE', '1')
    monkeypatch.setenv('PETASTORM_TPU_TRACE_SAMPLE', '1/2')
    monkeypatch.setenv('PETASTORM_TPU_METRICS', '0')
    # not yet visible: knobs are cached
    assert not tracing.trace_enabled() and not T.metrics_disabled()
    T.refresh()
    assert tracing.trace_enabled()
    assert T.metrics_disabled()
    assert tracing.sample_stride() == 2
    monkeypatch.delenv('PETASTORM_TPU_TRACE')
    monkeypatch.delenv('PETASTORM_TPU_TRACE_SAMPLE')
    monkeypatch.delenv('PETASTORM_TPU_METRICS')
    T.refresh()
    assert not tracing.trace_enabled() and not T.metrics_disabled()
    assert tracing.sample_stride() == 1


# -- activation + events ------------------------------------------------------


def test_activation_scopes_context_and_attempt_records(traced):
    ctx = mint(1, epoch=0)
    assert tracing.current_context() is None
    with attempt(ctx, 'worker-9'):
        assert tracing.current_context() == ctx
        assert tracing.current_trace_id() == ctx.trace_id
        with T.span('decode'):
            time.sleep(0.002)
    assert tracing.current_context() is None
    events = T.get_recorder().snapshot()
    by_name = {e['name']: e for e in events}
    assert set(by_name) == {'decode', 'attempt'}
    assert by_name['attempt']['tid'] == 'worker-9'
    assert by_name['attempt']['ph'] == 'X'
    assert by_name['attempt']['dur'] >= 2000  # µs
    # the stage span landed on the SAME trace, same track
    assert by_name['decode']['args']['trace_id'] == ctx.trace_id
    assert by_name['decode']['tid'] == 'worker-9'


def test_untraced_blocks_record_nothing(traced):
    with activate(None):
        with T.span('decode'):
            pass
    with T.span('io'):
        pass
    assert len(T.get_recorder()) == 0


def test_recorder_ring_is_bounded():
    rec = FlightRecorder(capacity=5)
    for i in range(12):
        rec.add({'name': 'e%d' % i, 'ph': 'X'})
    events = rec.snapshot()
    assert len(events) == 5
    assert events[0]['name'] == 'e7' and events[-1]['name'] == 'e11'
    assert rec.drain() == events
    assert len(rec) == 0


# -- export schema ------------------------------------------------------------


def test_chrome_export_schema(traced, tmp_path):
    ctx = mint(4, epoch=1, shard=0)
    with attempt(ctx, 'worker-0'):
        with T.span('io'):
            pass
    tracing.record_instant('done', ctx, 'dispatcher', worker='w')
    path = str(tmp_path / 'trace.json')
    count = T.dump_trace(path)
    assert count == 3
    with open(path) as f:
        doc = json.load(f)
    events = doc['traceEvents']
    meta = [e for e in events if e['ph'] == 'M']
    data = [e for e in events if e['ph'] != 'M']
    assert len(data) == 3
    for e in data:
        # the Chrome trace-event schema fields every viewer needs
        assert isinstance(e['name'], str)
        assert e['ph'] in ('X', 'i')
        assert isinstance(e['pid'], int)
        assert isinstance(e['tid'], int)  # labels interned to int tids
        assert isinstance(e['ts'], (int, float))
        assert e['args']['trace_id'] == ctx.trace_id
        if e['ph'] == 'X':
            assert 'dur' in e
    # one named track per worker/stage via thread_name metadata
    names = {m['args']['name'] for m in meta}
    assert names == {'worker-0', 'dispatcher'}
    tids = {(m['pid'], m['tid']) for m in meta}
    assert {(e['pid'], e['tid']) for e in data} <= tids


def test_slowest_items_ranks_by_attempt_time(traced):
    for seq, sleep_s in ((0, 0.006), (1, 0.001), (2, 0.012)):
        with attempt(mint(seq), 'w'):
            time.sleep(sleep_s)
    ranked = T.slowest_items(n=2)
    assert len(ranked) == 2
    assert ranked[0][0] == ctx_for(2).trace_id
    assert ranked[1][0] == ctx_for(0).trace_id
    assert ranked[0][1] >= ranked[1][1] >= 0.001


# -- the delta-channel piggyback ---------------------------------------------


def test_trace_events_ride_the_delta_frame(traced):
    """Worker-side events drain into the SAME frame the metrics deltas
    use (process-pool markers / service DONE); merging lands them in the
    consumer's recorder."""
    with attempt(mint(3), 'worker-1'):
        with T.span('decode'):
            pass
    frame = dump_delta_frame()
    assert len(T.get_recorder()) == 0, 'dump must drain the worker ring'
    delta = load_delta_frame(frame)
    assert delta is not None
    assert [e['name'] for e in delta['trace_events']] == ['decode',
                                                          'attempt']
    # simulate the consumer process: fresh telemetry state, then merge
    T.reset_for_tests()
    T.merge_worker_delta(delta)
    merged = T.get_recorder().snapshot()
    assert [e['name'] for e in merged] == ['decode', 'attempt']
    # the metrics half merged too
    assert T.get_registry().counter_value(
        'petastorm_tpu_stage_seconds_total', stage='decode') > 0


def test_delta_frame_without_changes_is_empty(traced):
    assert dump_delta_frame() == b''


def test_load_delta_frame_rejects_malformed_trace_events():
    import dill
    bad = dill.dumps({'counters': {'a': 1.0}, 'gauges': {},
                      'histograms': {}, 'trace_events': 'nope'})
    assert load_delta_frame(bad) is None
    good = dill.dumps({'counters': {}, 'gauges': {}, 'histograms': {},
                       'trace_events': [{'name': 'decode', 'ph': 'X'}]})
    assert load_delta_frame(good) is not None


# -- auto-dump ----------------------------------------------------------------


def test_autodump_after_consecutive_producer_bound_windows(
        traced, monkeypatch, tmp_path):
    path = str(tmp_path / 'auto.json')
    monkeypatch.setenv('PETASTORM_TPU_TRACE_DUMP', path)
    monkeypatch.setenv('PETASTORM_TPU_TRACE_AUTODUMP_WINDOWS', '2')
    monkeypatch.setenv('PETASTORM_TPU_METRICS_WINDOW_S', '0.05')
    T.refresh()
    T.reset_attributor()  # pick up the short window
    with attempt(mint(0), 'w'):
        pass
    att = T.get_attributor()
    # note consumer waits CONTINUOUSLY so every closed window is
    # producer-bound (sparse notes would close empty balanced windows
    # in between and break the consecutiveness requirement)
    end = time.monotonic() + 0.25
    while time.monotonic() < end:
        att.note_consumer_wait(0.01)
        time.sleep(0.005)
    assert tracing.maybe_autodump() is True
    with open(path) as f:
        doc = json.load(f)
    assert any(e['name'] == 'attempt' for e in doc['traceEvents'])
    # fires once per process run, not per pull
    assert tracing.maybe_autodump() is False


def test_autodump_idle_without_dump_path(traced):
    assert tracing.maybe_autodump() is False


# -- no-op discipline + overhead guard ---------------------------------------


def test_disabled_tracing_is_noop():
    assert mint(0) is None
    assert activate(None) is _NOOP_ACTIVATION
    assert attempt(None, 'w') is _NOOP_ACTIVATION
    # no trace hook is installed on the span hot path until a context
    # actually activates in this process
    assert spans._trace_hook is None
    with T.span('decode'):
        pass
    assert len(T.get_recorder()) == 0


def test_disabled_trace_overhead_budget():
    """ISSUE acceptance: with PETASTORM_TPU_TRACE unset the per-item cost
    is the PR 3 span discipline — same budget as the existing span guard
    (tests/test_telemetry.py::test_overhead_budget), with the per-item
    mint check far below it. Budgets are loose for shared CI boxes; the
    guard catches an accidental syscall/allocation, not µs noise."""
    n = 20000
    start = time.perf_counter()
    for i in range(n):
        with T.span('decode'):
            pass
    span_per_call = (time.perf_counter() - start) / n

    start = time.perf_counter()
    for i in range(n):
        if mint(i) is not None:  # the ventilator's per-item check
            raise AssertionError
    mint_per_call = (time.perf_counter() - start) / n

    assert span_per_call < 50e-6, span_per_call
    assert mint_per_call < 10e-6, mint_per_call
