"""Faultpoint harness tests: spec grammar, deterministic replay, and the
structural zero-cost-unarmed guarantees (the pattern of test_obs's
zero-thread guard — the disabled case is asserted, not assumed)."""

import ast
import glob
import os

import pytest

from petastorm_tpu import faults, telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def armed(monkeypatch):
    """Arm a spec for the duration of one test; disarm after."""
    def arm(spec):
        monkeypatch.setenv('PETASTORM_TPU_FAULTS', spec)
        faults.refresh_faults()
        return faults.ARMED
    yield arm
    monkeypatch.delenv('PETASTORM_TPU_FAULTS', raising=False)
    faults.refresh_faults()
    assert faults.ARMED is None


# -- spec grammar -------------------------------------------------------------


def test_parse_full_grammar():
    plan = faults.parse_spec(
        'io.read:error:0.05:seed=7,zmq.heartbeat:drop:after=20,'
        'cache.write:oserror:1:errno=28,staging.h2d:delay:ms=1')
    io_clause = plan.by_site['io.read'][0]
    assert (io_clause.mode, io_clause.rate, io_clause.seed) == \
        ('error', 0.05, 7)
    hb = plan.by_site['zmq.heartbeat'][0]
    assert (hb.mode, hb.rate, hb.after) == ('drop', 1.0, 20)
    assert plan.by_site['cache.write'][0].errno == 28
    assert plan.by_site['staging.h2d'][0].delay_ms == 1


@pytest.mark.parametrize('bad', [
    'io.read',                      # no mode
    'io.reed:error',                # unregistered site
    'io.read:explode',              # unknown mode
    'io.read:error:1.5',            # rate out of range
    'io.read:error:1:bogus=3',      # unknown option
    '',                             # empty
])
def test_parse_rejects_malformed(bad):
    with pytest.raises(ValueError):
        faults.parse_spec(bad)


def test_unparseable_env_spec_disarms_not_crashes(monkeypatch):
    monkeypatch.setenv('PETASTORM_TPU_FAULTS', 'io.read:explode')
    faults.refresh_faults()
    assert faults.ARMED is None


# -- determinism --------------------------------------------------------------


def _fire_indices(n=40, site='io.read', key='k'):
    fired = []
    for i in range(n):
        try:
            faults.fault_hit(site, key=key)
        except faults.FaultInjected:
            fired.append(i)
    return fired


def test_seeded_rate_replays_exactly(armed):
    armed('io.read:error:0.3:seed=11')
    first = _fire_indices()
    assert first, 'a 0.3 rate over 40 hits fired nothing'
    armed('io.read:error:0.3:seed=11')  # re-arm resets counters
    assert _fire_indices() == first
    armed('io.read:error:0.3:seed=12')
    assert _fire_indices() != first


def test_after_and_times_windows(armed):
    armed('io.read:error:1:after=3:times=2')
    assert _fire_indices(10) == [3, 4]


def test_match_selects_keys(armed):
    armed('decode.rowgroup:error:1:match=#rg3')
    faults.fault_hit('decode.rowgroup', key='/data/f.parquet#rg2')
    with pytest.raises(faults.FaultInjected):
        faults.fault_hit('decode.rowgroup', key='/data/f.parquet#rg3')
    stats = faults.injection_stats()['decode.rowgroup']
    assert stats == {'hits': 1, 'fired': 1}  # non-matching keys no-op


def test_same_site_clauses_draw_independently(armed):
    """Two clauses on one site (same default seed) must not fire in
    lockstep: the decision digest carries the clause's mode+index salt,
    so 'delay without drop' and 'drop without delay' hits both occur
    (review finding: correlated draws made those unreachable)."""
    armed('zmq.recv:delay:0.5:ms=0,zmq.recv:drop:0.5')
    c_delay, c_drop = faults.ARMED.by_site['zmq.recv']
    pattern = set()
    for i in range(128):
        before = (c_delay.fired, c_drop.fired)
        faults.fault_hit('zmq.recv', key=i)
        pattern.add((c_delay.fired - before[0], c_drop.fired - before[1]))
    assert (1, 0) in pattern and (0, 1) in pattern, pattern


def test_oserror_mode_carries_errno(armed):
    armed('cache.write:oserror:1:errno=28')
    with pytest.raises(faults.FaultInjectedOSError) as info:
        faults.fault_hit('cache.write', key='x')
    assert info.value.errno == 28
    assert isinstance(info.value, OSError)
    assert isinstance(info.value, faults.FaultInjected)


def test_drop_mode_returns_action(armed):
    armed('zmq.heartbeat:drop')
    assert faults.fault_hit('zmq.heartbeat', key=0) == 'drop'


def test_armed_hit_of_unregistered_site_raises(armed):
    armed('io.read:error:1')
    with pytest.raises(ValueError, match='unregistered faultpoint'):
        faults.fault_hit('io.reed', key='x')


def test_injections_counted_per_site(armed):
    telemetry.reset_for_tests()
    armed('io.read:error:1:times=3')
    _fire_indices(5)
    counters = telemetry.get_registry().counters_with_prefix(
        faults.FAULTS_INJECTED)
    assert sum(counters.values()) == 3


def test_telemetry_refresh_arms_and_disarms(monkeypatch):
    monkeypatch.setenv('PETASTORM_TPU_FAULTS', 'io.read:error:1')
    telemetry.refresh()
    assert faults.ARMED is not None
    monkeypatch.delenv('PETASTORM_TPU_FAULTS')
    telemetry.refresh()
    assert faults.ARMED is None


# -- the structural unarmed guarantees ---------------------------------------


def test_unarmed_is_structurally_stateless():
    """With the knob unset there is no plan, no clause state, and a stray
    fault_hit call (sites never make one — see the guard test below)
    returns None without allocating anything."""
    assert 'PETASTORM_TPU_FAULTS' not in os.environ
    faults.refresh_faults()
    assert faults.ARMED is None
    assert faults.fault_hit('io.read', key='x') is None
    assert faults.injection_stats() == {}


def test_every_call_site_is_guarded_by_one_attribute_read():
    """Every ``fault_hit`` call in the package must sit inside an ``if``
    whose test reads ``faults.ARMED`` (or ``ARMED``) — the one-attribute-
    read unarmed guarantee is a SOURCE property, so it is asserted at the
    source level (the pattern of test_obs's zero-thread structural
    guard). Also asserts the scan actually finds the wired sites."""
    def guards(test_node):
        names = set()
        for node in ast.walk(test_node):
            if isinstance(node, ast.Attribute):
                names.add(node.attr)
            elif isinstance(node, ast.Name):
                names.add(node.id)
        return names

    offenders, sites = [], 0
    for path in glob.glob(os.path.join(REPO, 'petastorm_tpu', '**',
                                       '*.py'), recursive=True):
        if os.path.basename(path) == 'faults.py':
            continue  # the harness itself, not a call site
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        # walk with parents: collect every If, then every fault_hit call
        guarded_spans = []
        for node in ast.walk(tree):
            if isinstance(node, ast.If) and 'ARMED' in guards(node.test):
                guarded_spans.append(
                    (node.lineno, max(n.lineno for n in ast.walk(node)
                                      if hasattr(n, 'lineno'))))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = getattr(func, 'attr', getattr(func, 'id', None))
            if name != 'fault_hit':
                continue
            sites += 1
            if not any(lo <= node.lineno <= hi for lo, hi in
                       guarded_spans):
                offenders.append('%s:%d' % (os.path.relpath(path, REPO),
                                            node.lineno))
    assert sites >= 10, 'fault_hit call-site scan went blind'
    assert not offenders, \
        'fault_hit call sites missing the `if faults.ARMED:` guard: %s' \
        % offenders
