"""Seeded schema fuzz: random schemas round-trip write → read exactly.

The unit matrix pins known dtype cases; this sweep composes RANDOM
schemas (scalar dtypes × ndarray dtypes/shapes × codecs × nullability)
and asserts exact value round-trips through the full write path
(``DatasetWriter`` + footer) and both read APIs — the class of
dtype-mapping edge cases a fixed canonical schema cannot enumerate.
"""

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.codecs import (
    CompressedNdarrayCodec, NdarrayCodec, ScalarCodec,
)
from petastorm_tpu.etl.dataset_metadata import write_dataset
from petastorm_tpu.reader import make_batch_reader, make_reader
from petastorm_tpu.unischema import Unischema, UnischemaField

_SCALARS = [
    (np.int8, pa.int8()), (np.int16, pa.int16()), (np.int32, pa.int32()),
    (np.int64, pa.int64()), (np.uint8, pa.uint8()),
    (np.uint16, pa.uint16()), (np.float32, pa.float32()),
    (np.float64, pa.float64()), (np.bool_, pa.bool_()),
    (np.str_, pa.string()),
]
_ND_DTYPES = [np.int16, np.int32, np.uint8, np.uint16, np.float32,
              np.float64]


def _random_schema(rng, trial):
    fields = [UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()),
                             False)]
    for j in range(rng.randint(2, 6)):
        kind = rng.randint(0, 3)
        name = 'f%d' % j
        if kind == 0:  # scalar
            np_t, pa_t = _SCALARS[rng.randint(len(_SCALARS))]
            fields.append(UnischemaField(name, np_t, (),
                                         ScalarCodec(pa_t), False))
        elif kind == 1:  # fixed-shape ndarray
            np_t = _ND_DTYPES[rng.randint(len(_ND_DTYPES))]
            shape = tuple(int(rng.randint(1, 5))
                          for _ in range(rng.randint(1, 4)))
            codec = (CompressedNdarrayCodec() if rng.randint(2)
                     else NdarrayCodec())
            fields.append(UnischemaField(name, np_t, shape, codec, False))
        else:  # variable leading dim, possibly nullable
            np_t = _ND_DTYPES[rng.randint(len(_ND_DTYPES))]
            trailing = tuple(int(rng.randint(1, 4))
                             for _ in range(rng.randint(0, 2)))
            fields.append(UnischemaField(name, np_t, (None,) + trailing,
                                         NdarrayCodec(), bool(rng.randint(2))))
    return Unischema('Fuzz%d' % trial, fields)


def _random_cell(rng, field, i):
    np_t = field.numpy_dtype
    if field.shape == ():
        if np_t is np.str_:
            return '(%d:%s)' % (i, rng.randint(1000))
        if np_t is np.bool_:
            return bool(rng.randint(2))
        if np.issubdtype(np_t, np.floating):
            return np_t(rng.rand())
        info = np.iinfo(np_t)
        return np_t(rng.randint(max(info.min, -1000),
                                min(info.max, 1000)))
    shape = tuple(rng.randint(0, 5) if d is None else d
                  for d in field.shape)
    if field.nullable and rng.randint(3) == 0:
        return None
    if np.issubdtype(np_t, np.floating):
        return rng.rand(*shape).astype(np_t)
    return rng.randint(0, 100, shape).astype(np_t)


@pytest.mark.parametrize('trial', range(6))
def test_random_schema_round_trip(tmp_path, trial):
    rng = np.random.RandomState(1234 + trial)
    schema = _random_schema(rng, trial)
    rows = [dict({f.name: _random_cell(rng, f, i)
                  for f in schema.fields.values()}, id=i)
            for i in range(30)]
    url = 'file://' + str(tmp_path / ('fuzz%d' % trial))
    write_dataset(url, schema, rows, rowgroup_size_rows=7)

    def check(got_by_id):
        assert len(got_by_id) == 30
        for i, want_row in enumerate(rows):
            got = got_by_id[i]
            for f in schema.fields.values():
                want = want_row[f.name]
                value = got[f.name]
                if want is None:
                    assert value is None, (trial, f.name, i)
                elif f.shape == ():
                    # exact, including the dtype: the round-trip is
                    # bit-exact, and a silent float64->float32 narrowing
                    # would survive any tolerance-based comparison
                    if f.numpy_dtype not in (np.str_, np.bool_):
                        assert np.asarray(value).dtype == f.numpy_dtype, \
                            (trial, f.name, np.asarray(value).dtype)
                    assert value == want, (trial, f.name, i)
                else:
                    assert value.dtype == f.numpy_dtype, \
                        (trial, f.name, value.dtype)
                    np.testing.assert_array_equal(value, want,
                                                  err_msg='%s[%d]'
                                                          % (f.name, i))

    with make_reader(url, shuffle_row_groups=False) as reader:
        check({row.id: row._asdict() for row in reader})
    with make_batch_reader(url, shuffle_row_groups=False) as reader:
        by_id = {}
        for batch in reader:
            d = batch._asdict()
            n = len(d['id'])
            for k in range(n):
                by_id[int(d['id'][k])] = {name: col[k]
                                          for name, col in d.items()}
        check(by_id)
