"""Shuffling buffer tests (reference: ``tests/test_shuffling_buffer.py``)."""

import numpy as np
import pytest

from petastorm_tpu.buffers import (
    BatchedNoopShufflingBuffer, BatchedRandomShufflingBuffer,
    NoopShufflingBuffer, RandomShufflingBuffer,
)


class TestNoop:
    def test_fifo(self):
        buf = NoopShufflingBuffer()
        buf.add_many([1, 2, 3])
        assert buf.size == 3
        assert [buf.retrieve() for _ in range(3)] == [1, 2, 3]
        assert not buf.can_retrieve
        buf.finish()
        assert not buf.can_add


class TestRandom:
    def test_holds_until_min_after_retrieve(self):
        buf = RandomShufflingBuffer(10, min_after_retrieve=3, seed=0)
        buf.add_many([1, 2])
        assert not buf.can_retrieve  # below the decorrelation floor
        buf.add_many([3])
        assert buf.can_retrieve

    def test_floor_equal_to_capacity_does_not_deadlock(self):
        buf = RandomShufflingBuffer(3, min_after_retrieve=3, seed=0)
        buf.add_many([1, 2, 3])
        assert not buf.can_add
        assert buf.can_retrieve

    def test_floor_above_capacity_rejected(self):
        with pytest.raises(ValueError, match='capacity'):
            RandomShufflingBuffer(3, min_after_retrieve=4)

    def test_finish_drains_fully(self):
        buf = RandomShufflingBuffer(10, min_after_retrieve=5, seed=0)
        buf.add_many([1, 2, 3])
        buf.finish()
        out = []
        while buf.can_retrieve:
            out.append(buf.retrieve())
        assert sorted(out) == [1, 2, 3]

    def test_capacity_gates_can_add(self):
        buf = RandomShufflingBuffer(3, min_after_retrieve=0, seed=0)
        buf.add_many([1, 2])
        assert buf.can_add
        buf.add_many([3, 4])  # single add may overshoot capacity
        assert not buf.can_add
        with pytest.raises(RuntimeError):
            buf.add_many([5])

    def test_all_items_come_out_exactly_once(self):
        buf = RandomShufflingBuffer(1000, min_after_retrieve=10, seed=1)
        buf.add_many(list(range(500)))
        out = []
        while buf.can_retrieve:
            out.append(buf.retrieve())
        buf.finish()
        while buf.can_retrieve:
            out.append(buf.retrieve())
        assert sorted(out) == list(range(500))

    def test_output_is_shuffled(self):
        buf = RandomShufflingBuffer(1000, min_after_retrieve=0, seed=2)
        buf.add_many(list(range(200)))
        buf.finish()
        out = [buf.retrieve() for _ in range(200)]
        assert out != list(range(200))


def _chunk(start, n):
    return {'id': np.arange(start, start + n),
            'vec': np.arange(start, start + n, dtype=np.float32).reshape(-1, 1)
            * np.ones((1, 4), np.float32)}


class TestBatchedNoop:
    def test_rebatches_preserving_order(self):
        buf = BatchedNoopShufflingBuffer(batch_size=7)
        buf.add_many(_chunk(0, 10))
        buf.add_many(_chunk(10, 10))
        batches = []
        while buf.can_retrieve:
            batches.append(buf.retrieve())
        buf.finish()
        while buf.can_retrieve:
            batches.append(buf.retrieve())
        assert [len(b['id']) for b in batches] == [7, 7, 6]
        np.testing.assert_array_equal(
            np.concatenate([b['id'] for b in batches]), np.arange(20))
        last = batches[-1]
        np.testing.assert_array_equal(last['vec'][:, 0], last['id'])

    def test_empty_chunk_ignored(self):
        buf = BatchedNoopShufflingBuffer(batch_size=2)
        buf.add_many(_chunk(0, 0))
        assert buf.size == 0


class TestBatchedRandom:
    def test_exactly_once_and_row_alignment(self):
        buf = BatchedRandomShufflingBuffer(
            shuffling_buffer_capacity=64, min_after_retrieve=16,
            batch_size=8, extra_capacity=32, seed=0)
        seen = []
        start = 0
        for _ in range(6):
            buf.add_many(_chunk(start, 16))
            start += 16
            while buf.can_retrieve:
                b = buf.retrieve()
                # rows must stay internally consistent across columns
                np.testing.assert_array_equal(b['vec'][:, 2], b['id'])
                seen.extend(b['id'].tolist())
        buf.finish()
        while buf.can_retrieve:
            b = buf.retrieve()
            np.testing.assert_array_equal(b['vec'][:, 2], b['id'])
            seen.extend(b['id'].tolist())
        assert sorted(seen) == list(range(start))

    def test_shuffles_across_chunks(self):
        buf = BatchedRandomShufflingBuffer(
            shuffling_buffer_capacity=100, min_after_retrieve=50,
            batch_size=10, extra_capacity=100, seed=3)
        buf.add_many(_chunk(0, 100))
        first = buf.retrieve()['id']
        assert not np.array_equal(first, np.arange(10))

    def test_chunk_overflow_raises(self):
        buf = BatchedRandomShufflingBuffer(
            shuffling_buffer_capacity=4, min_after_retrieve=0, batch_size=2,
            extra_capacity=0, seed=0)
        with pytest.raises(RuntimeError, match='extra_capacity'):
            buf.add_many(_chunk(0, 10))

    def test_min_after_retrieve_floor(self):
        buf = BatchedRandomShufflingBuffer(
            shuffling_buffer_capacity=100, min_after_retrieve=20,
            batch_size=5, extra_capacity=0, seed=0)
        buf.add_many(_chunk(0, 15))
        assert not buf.can_retrieve
        buf.add_many(_chunk(15, 5))
        assert buf.can_retrieve

    def test_batch_size_above_capacity_rejected(self):
        with pytest.raises(ValueError, match='capacity'):
            BatchedRandomShufflingBuffer(
                shuffling_buffer_capacity=4, min_after_retrieve=0,
                batch_size=8)

    def test_dtype_widening_no_truncation(self):
        buf = BatchedRandomShufflingBuffer(
            shuffling_buffer_capacity=10, min_after_retrieve=0, batch_size=10,
            extra_capacity=10, seed=0)
        buf.add_many({'s': np.array(['abc', 'de'])})
        buf.add_many({'s': np.array(['abcdefghij'])})
        buf.finish()
        out = []
        while buf.can_retrieve:
            out.extend(buf.retrieve()['s'].tolist())
        assert sorted(out) == ['abc', 'abcdefghij', 'de']

    def test_object_dtype_columns(self):
        buf = BatchedRandomShufflingBuffer(
            shuffling_buffer_capacity=10, min_after_retrieve=0, batch_size=4,
            extra_capacity=10, seed=0)
        ragged = np.empty(6, dtype=object)
        for i in range(6):
            ragged[i] = np.arange(i + 1)
        buf.add_many({'id': np.arange(6), 'ragged': ragged})
        buf.finish()
        rows = 0
        while buf.can_retrieve:
            b = buf.retrieve()
            for rid, arr in zip(b['id'], b['ragged']):
                assert len(arr) == rid + 1
            rows += len(b['id'])
        assert rows == 6


class TestRandomizedOpSequences:
    """Long random interleavings of add/retrieve/finish must preserve the
    exactly-once invariant (reference: ``test_shuffling_buffer.py:223`` —
    test_longer_random_sequence_of_queue_ops)."""

    @pytest.mark.parametrize('capacity,min_after', [(20, 10), (64, 1),
                                                    (7, 7)])
    def test_row_buffer_invariants(self, capacity, min_after):
        rng = np.random.RandomState(capacity)
        buf = RandomShufflingBuffer(capacity, min_after_retrieve=min_after,
                                    seed=1)
        fed, got = [], []
        next_item = 0
        for _ in range(2000):
            if buf.can_add and rng.rand() < 0.55:
                chunk = [next_item + i for i in range(int(rng.randint(1, 4)))]
                next_item += len(chunk)
                buf.add_many(chunk)
                fed.extend(chunk)
            elif buf.can_retrieve:
                got.append(buf.retrieve())
            assert buf.size <= capacity + 3  # bounded by capacity + chunk
        buf.finish()
        while buf.can_retrieve:
            got.append(buf.retrieve())
        assert sorted(got) == fed

    @pytest.mark.parametrize('batch_size', [1, 5, 16])
    def test_batched_buffer_invariants(self, batch_size):
        rng = np.random.RandomState(batch_size)
        buf = BatchedRandomShufflingBuffer(
            64, min_after_retrieve=8, batch_size=batch_size,
            extra_capacity=64, seed=2)
        next_row = 0
        fed = 0
        out_ids = []
        for _ in range(500):
            if buf.can_add and rng.rand() < 0.55:
                n = int(rng.randint(1, 20))
                ids = np.arange(next_row, next_row + n)
                buf.add_many({'id': ids, 'sq': ids ** 2})
                next_row += n
                fed += n
            elif buf.can_retrieve:
                batch = buf.retrieve()
                assert len(batch['id']) == batch_size
                # row alignment: columns must stay paired under shuffling
                np.testing.assert_array_equal(batch['sq'],
                                              batch['id'] ** 2)
                out_ids.extend(batch['id'].tolist())
        buf.finish()
        while buf.can_retrieve:
            batch = buf.retrieve()
            np.testing.assert_array_equal(batch['sq'], batch['id'] ** 2)
            out_ids.extend(batch['id'].tolist())
        # exactly-once, in full: finish() + drain must emit every fed row
        assert sorted(out_ids) == list(range(fed))
