"""Shuffle-quality assertions (reference: ``tests/test_end_to_end.py:329-360``
``test_stable_pieces_order``/drop-ratio correlation): decorrelation must
improve monotonically from no-shuffle → row-group shuffle → row-group shuffle
with row-drop partitioning."""

import pytest

from petastorm_tpu.test_util.shuffling_analysis import (
    compute_correlation_distribution, generate_shuffle_analysis_dataset,
)


@pytest.fixture(scope='module')
def shuffle_dataset(tmp_path_factory):
    url = 'file://' + str(tmp_path_factory.mktemp('shuffle')) + '/ds'
    generate_shuffle_analysis_dataset(url, num_rows=1000, rowgroup_size=100)
    return url


def test_unshuffled_is_fully_correlated(shuffle_dataset):
    corr = compute_correlation_distribution(
        shuffle_dataset, num_runs=2, shuffle_row_groups=False,
        reader_pool_type='dummy')
    assert corr > 0.97


def test_rowgroup_shuffle_decorrelates(shuffle_dataset):
    corr = compute_correlation_distribution(
        shuffle_dataset, num_runs=5, shuffle_row_groups=True,
        reader_pool_type='dummy')
    # row order inside each group is still sequential, so correlation drops
    # but cannot vanish with only 10 row-groups
    assert corr < 0.6


def test_row_drop_partitions_improve_decorrelation(shuffle_dataset):
    base = compute_correlation_distribution(
        shuffle_dataset, num_runs=5, shuffle_row_groups=True,
        reader_pool_type='dummy')
    dropped = compute_correlation_distribution(
        shuffle_dataset, num_runs=5, shuffle_row_groups=True,
        shuffle_row_drop_partitions=5, reader_pool_type='dummy')
    # each row-group read 5x keeping 1/5 of rows -> finer-grained
    # interleaving -> measurably better decorrelation (reference asserts the
    # same direction, test_end_to_end.py:350-360)
    assert dropped < base


def test_row_drop_preserves_exactly_once(shuffle_dataset):
    from petastorm_tpu.reader import make_reader
    with make_reader(shuffle_dataset, shuffle_row_groups=True,
                     shuffle_row_drop_partitions=4,
                     reader_pool_type='dummy') as reader:
        ids = sorted(r.id for r in reader)
    assert ids == list(range(1000))
