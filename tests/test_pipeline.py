"""GPipe pipeline over the pipe mesh axis vs the sequential oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from petastorm_tpu.parallel.mesh import PIPE_AXIS
from petastorm_tpu.parallel.pipeline import (
    pipeline_apply, pipeline_supported, reference_pipeline,
    shard_stage_params,
)

# pipeline_apply REQUIRES the modern jax.shard_map + vma machinery (the
# sound replicated-input transpose); on older jax builds the executor
# refuses loudly rather than computing silently wrong input gradients
# through the experimental check_rep=False fallback — so the execution
# tests skip with the reason, and only the capability-independent tests
# (parameter placement, divisibility validation) always run.
requires_vma_shard_map = pytest.mark.skipif(
    not pipeline_supported(),
    reason='this jax lacks jax.shard_map with sound vma tracking '
           '(lax.pcast/pvary); pipeline_apply refuses the silently-'
           'wrong check_rep=False fallback')


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), (PIPE_AXIS,))


def _stage_fn(params, x):
    # a simple but non-commuting stage: affine + gelu (order of stages
    # matters, so a mis-scheduled pipeline cannot accidentally pass)
    return jax.nn.gelu(x @ params['w'] + params['b'])


def _stacked_params(n_stages, d, seed=0):
    rng = np.random.RandomState(seed)
    return {
        'w': jnp.asarray(rng.randn(n_stages, d, d).astype(np.float32)
                         * d ** -0.5),
        'b': jnp.asarray(rng.randn(n_stages, d).astype(np.float32) * 0.1),
    }


@requires_vma_shard_map
@pytest.mark.parametrize('n_stages', [2, 4, 8])
@pytest.mark.parametrize('n_microbatches', [None, 8])
def test_matches_sequential_oracle(n_stages, n_microbatches):
    mesh = _mesh(n_stages)
    params = _stacked_params(n_stages, d=16)
    x = jnp.asarray(np.random.RandomState(1).randn(8, 16).astype(np.float32))
    want = reference_pipeline(_stage_fn, params, x)
    sharded = shard_stage_params(params, mesh)
    with mesh:
        got = pipeline_apply(_stage_fn, sharded, x, mesh,
                             n_microbatches=n_microbatches)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_stage_weights_live_on_their_own_shard():
    mesh = _mesh(4)
    params = shard_stage_params(_stacked_params(4, d=8), mesh)
    assert {s.data.shape for s in params['w'].addressable_shards} \
        == {(1, 8, 8)}


@requires_vma_shard_map
def test_gradients_match_sequential(capsys):
    # parameter AND input gradients: the input cotangent crosses the
    # replicated in_spec boundary, which is exactly where an unsound
    # shard_map transpose (check_rep=False) silently corrupts grads
    # (r2 review finding) — so x's gradient is the load-bearing assert
    mesh = _mesh(4)
    params = _stacked_params(4, d=8, seed=2)
    x = jnp.asarray(np.random.RandomState(3).randn(8, 8).astype(np.float32))

    def pipe_loss(params, x):
        return jnp.sum(pipeline_apply(_stage_fn, params, x, mesh) ** 2)

    def oracle_loss(params, x):
        return jnp.sum(reference_pipeline(_stage_fn, params, x) ** 2)

    sharded = shard_stage_params(params, mesh)
    with mesh:
        pipe_grads, pipe_xgrad = jax.jit(
            jax.grad(pipe_loss, argnums=(0, 1)))(sharded, x)
    oracle_grads, oracle_xgrad = jax.grad(oracle_loss,
                                          argnums=(0, 1))(params, x)
    for name in params:
        np.testing.assert_allclose(np.asarray(pipe_grads[name]),
                                   np.asarray(oracle_grads[name]),
                                   atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(pipe_xgrad),
                               np.asarray(oracle_xgrad),
                               atol=2e-5, rtol=2e-5)


@requires_vma_shard_map
def test_composes_with_upstream_layer_gradients():
    # the real-world shape of the input-grad bug: an upstream (embedding-
    # like) layer feeding the pipeline must train with correct gradients
    mesh = _mesh(4)
    params = _stacked_params(4, d=8, seed=6)
    rng = np.random.RandomState(7)
    w_up = jnp.asarray(rng.randn(8, 8).astype(np.float32) * 0.3)
    x = jnp.asarray(rng.randn(8, 8).astype(np.float32))

    def pipe_loss(w_up, params, x):
        h = jnp.tanh(x @ w_up)
        return jnp.sum(pipeline_apply(_stage_fn, params, h, mesh) ** 2)

    def oracle_loss(w_up, params, x):
        h = jnp.tanh(x @ w_up)
        return jnp.sum(reference_pipeline(_stage_fn, params, h) ** 2)

    sharded = shard_stage_params(params, mesh)
    with mesh:
        got = jax.jit(jax.grad(pipe_loss))(w_up, sharded, x)
    want = jax.grad(oracle_loss)(w_up, params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@requires_vma_shard_map
def test_multilayer_stage_fn():
    # a stage may hold several layers: leading axis is stages, second axis
    # is layers-per-stage
    mesh = _mesh(2)
    rng = np.random.RandomState(4)
    params = {'w': jnp.asarray(rng.randn(2, 3, 8, 8).astype(np.float32)
                               * 8 ** -0.5)}

    def stage(p, x):
        for i in range(p['w'].shape[0]):
            x = jnp.tanh(x @ p['w'][i])
        return x

    x = jnp.asarray(rng.randn(4, 8).astype(np.float32))
    want = reference_pipeline(stage, params, x)
    with mesh:
        got = pipeline_apply(stage, shard_stage_params(params, mesh), x,
                             mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.skipif(pipeline_supported(),
                    reason='modern jax: the executor runs instead of '
                           'refusing')
def test_refuses_loudly_without_vma_shard_map():
    # the version-guard satellite: an old jax must get an actionable
    # RuntimeError naming the requirement — never a bare ImportError
    # mid-trace, and NEVER the silently-wrong check_rep=False fallback
    mesh = _mesh(2)
    params = _stacked_params(2, d=8)
    x = jnp.zeros((8, 8), jnp.float32)
    with pytest.raises(RuntimeError, match='pipeline_apply requires'):
        with mesh:
            pipeline_apply(_stage_fn, shard_stage_params(params, mesh), x,
                           mesh)


def test_rejects_indivisible_microbatches():
    mesh = _mesh(2)
    params = _stacked_params(2, d=8)
    x = jnp.zeros((7, 8))
    with pytest.raises(ValueError, match='not divisible'):
        pipeline_apply(_stage_fn, shard_stage_params(params, mesh), x, mesh,
                       n_microbatches=3)


@requires_vma_shard_map
def test_single_stage_degenerates_to_plain_apply():
    mesh = _mesh(1)
    params = _stacked_params(1, d=8)
    x = jnp.asarray(np.random.RandomState(5).randn(4, 8).astype(np.float32))
    want = reference_pipeline(_stage_fn, params, x)
    with mesh:
        got = pipeline_apply(_stage_fn, shard_stage_params(params, mesh), x,
                             mesh, n_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6, rtol=1e-6)
