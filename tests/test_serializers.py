"""Serializer round-trips (reference: ``tests/test_pickle_serializer.py``,
``test_arrow_table_serializer.py``)."""

import numpy as np
import pyarrow as pa

from petastorm_tpu.arrow_worker import ColumnBatch
from petastorm_tpu.serializers import ArrowTableSerializer, PickleSerializer


def test_pickle_roundtrip_column_batch():
    s = PickleSerializer()
    batch = ColumnBatch({'a': np.arange(5), 'b': np.ones((5, 3), np.float32)},
                        5, item_index=2, epoch=1)
    out = s.deserialize(s.serialize(batch))
    assert out.length == 5
    assert out.item_index == 2 and out.epoch == 1
    np.testing.assert_array_equal(out.columns['a'], batch.columns['a'])
    np.testing.assert_array_equal(out.columns['b'], batch.columns['b'])


def test_arrow_table_roundtrip():
    s = ArrowTableSerializer()
    table = pa.table({'x': pa.array([1, 2, 3], pa.int64()),
                      'y': pa.array(['a', 'b', 'c'])})
    out = s.deserialize(s.serialize(table))
    assert out.equals(table)


def test_pickle_roundtrip_object_columns_and_nulls():
    # ragged/object columns (variable-shape fields) must survive the
    # process-pool boundary intact, Nones included
    s = PickleSerializer()
    ragged = np.empty(3, dtype=object)
    ragged[0] = np.arange(4)
    ragged[1] = None
    ragged[2] = np.ones((2, 2))
    batch = ColumnBatch({'r': ragged}, 3)
    out = s.deserialize(s.serialize(batch))
    np.testing.assert_array_equal(out.columns['r'][0], np.arange(4))
    assert out.columns['r'][1] is None
    np.testing.assert_array_equal(out.columns['r'][2], np.ones((2, 2)))


def test_arrow_roundtrip_binary_and_nulls():
    s = ArrowTableSerializer()
    table = pa.table({
        'blob': pa.array([b'\x00\xff' * 100, None, b''], pa.binary()),
        'f': pa.array([1.5, None, 3.0], pa.float64()),
    })
    out = s.deserialize(s.serialize(table))
    assert out.equals(table)


def test_arrow_roundtrip_preserves_chunking_content():
    s = ArrowTableSerializer()
    chunked = pa.chunked_array([pa.array([1, 2]), pa.array([3, 4, 5])])
    table = pa.table({'x': chunked})
    out = s.deserialize(s.serialize(table))
    assert out.column('x').to_pylist() == [1, 2, 3, 4, 5]
