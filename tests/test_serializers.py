"""Serializer round-trips (reference: ``tests/test_pickle_serializer.py``,
``test_arrow_table_serializer.py``)."""

import numpy as np
import pyarrow as pa

from petastorm_tpu.arrow_worker import ColumnBatch
from petastorm_tpu.serializers import ArrowTableSerializer, PickleSerializer


def test_pickle_roundtrip_column_batch():
    s = PickleSerializer()
    batch = ColumnBatch({'a': np.arange(5), 'b': np.ones((5, 3), np.float32)},
                        5, item_index=2, epoch=1)
    out = s.deserialize(s.serialize(batch))
    assert out.length == 5
    assert out.item_index == 2 and out.epoch == 1
    np.testing.assert_array_equal(out.columns['a'], batch.columns['a'])
    np.testing.assert_array_equal(out.columns['b'], batch.columns['b'])


def test_arrow_table_roundtrip():
    s = ArrowTableSerializer()
    table = pa.table({'x': pa.array([1, 2, 3], pa.int64()),
                      'y': pa.array(['a', 'b', 'c'])})
    out = s.deserialize(s.serialize(table))
    assert out.equals(table)


def test_pickle_roundtrip_object_columns_and_nulls():
    # ragged/object columns (variable-shape fields) must survive the
    # process-pool boundary intact, Nones included
    s = PickleSerializer()
    ragged = np.empty(3, dtype=object)
    ragged[0] = np.arange(4)
    ragged[1] = None
    ragged[2] = np.ones((2, 2))
    batch = ColumnBatch({'r': ragged}, 3)
    out = s.deserialize(s.serialize(batch))
    np.testing.assert_array_equal(out.columns['r'][0], np.arange(4))
    assert out.columns['r'][1] is None
    np.testing.assert_array_equal(out.columns['r'][2], np.ones((2, 2)))


def test_arrow_roundtrip_binary_and_nulls():
    s = ArrowTableSerializer()
    table = pa.table({
        'blob': pa.array([b'\x00\xff' * 100, None, b''], pa.binary()),
        'f': pa.array([1.5, None, 3.0], pa.float64()),
    })
    out = s.deserialize(s.serialize(table))
    assert out.equals(table)


def test_arrow_roundtrip_preserves_chunking_content():
    s = ArrowTableSerializer()
    chunked = pa.chunked_array([pa.array([1, 2]), pa.array([3, 4, 5])])
    table = pa.table({'x': chunked})
    out = s.deserialize(s.serialize(table))
    assert out.column('x').to_pylist() == [1, 2, 3, 4, 5]


# -- pickle-5 out-of-band multipart frames (the process-pool wire) -----------


def test_pickle_frames_ship_ndarrays_out_of_band():
    s = PickleSerializer()
    batch = ColumnBatch({'a': np.arange(1000),
                         'b': np.ones((50, 64), np.float32)}, 1000)
    frames = s.serialize_frames(batch)
    # frame 0 = pickle stream (metadata only), one raw frame per ndarray
    assert len(frames) == 3
    payload_bytes = {f.nbytes for f in map(memoryview, frames[1:])}
    assert payload_bytes == {batch.columns['a'].nbytes,
                             batch.columns['b'].nbytes}
    assert len(frames[0]) < 1000  # arrays did NOT land in the stream
    out = s.deserialize_frames(frames)
    np.testing.assert_array_equal(out.columns['a'], batch.columns['a'])
    np.testing.assert_array_equal(out.columns['b'], batch.columns['b'])


def test_pickle_frames_receive_side_is_zero_copy():
    """Deserializing from received buffers must reconstruct arrays as
    VIEWS over those buffers (what recv_multipart(copy=False) + pickle-5
    out-of-band buys): no host copy between the wire and the consumer."""
    s = PickleSerializer()
    batch = ColumnBatch({'big': np.random.RandomState(0)
                                  .rand(100, 32).astype(np.float32)}, 100)
    # simulate the wire: frames arrive as distinct (read-only) buffers
    wire = [bytes(memoryview(f)) for f in s.serialize_frames(batch)]
    received = [memoryview(f) for f in wire]
    out = s.deserialize_frames(received)
    np.testing.assert_array_equal(out.columns['big'], batch.columns['big'])
    assert any(np.shares_memory(out.columns['big'],
                                np.frombuffer(f, np.uint8))
               for f in wire[1:]), 'deserialized array copied off the wire'


def test_pickle_frames_roundtrip_mixed_and_object_columns():
    # object (ragged) columns cannot go out-of-band; they ride the stream
    # while the dense columns still split out — both must round-trip
    s = PickleSerializer()
    ragged = np.empty(2, dtype=object)
    ragged[0] = np.arange(4)
    ragged[1] = None
    batch = ColumnBatch({'r': ragged, 'd': np.arange(64.0)}, 2)
    out = s.deserialize_frames(s.serialize_frames(batch))
    np.testing.assert_array_equal(out.columns['r'][0], np.arange(4))
    assert out.columns['r'][1] is None
    np.testing.assert_array_equal(out.columns['d'], np.arange(64.0))


def test_default_frames_api_wraps_single_payload():
    s = ArrowTableSerializer()
    table = pa.table({'x': pa.array([1, 2, 3], pa.int64())})
    frames = s.serialize_frames(table)
    assert len(frames) == 1
    assert s.deserialize_frames(frames).equals(table)
    import pytest
    with pytest.raises(ValueError, match='single payload frame'):
        s.deserialize_frames([b'x', b'y'])
