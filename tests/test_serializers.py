"""Serializer round-trips (reference: ``tests/test_pickle_serializer.py``,
``test_arrow_table_serializer.py``)."""

import numpy as np
import pyarrow as pa

from petastorm_tpu.arrow_worker import ColumnBatch
from petastorm_tpu.serializers import ArrowTableSerializer, PickleSerializer


def test_pickle_roundtrip_column_batch():
    s = PickleSerializer()
    batch = ColumnBatch({'a': np.arange(5), 'b': np.ones((5, 3), np.float32)},
                        5, item_index=2, epoch=1)
    out = s.deserialize(s.serialize(batch))
    assert out.length == 5
    assert out.item_index == 2 and out.epoch == 1
    np.testing.assert_array_equal(out.columns['a'], batch.columns['a'])
    np.testing.assert_array_equal(out.columns['b'], batch.columns['b'])


def test_arrow_table_roundtrip():
    s = ArrowTableSerializer()
    table = pa.table({'x': pa.array([1, 2, 3], pa.int64()),
                      'y': pa.array(['a', 'b', 'c'])})
    out = s.deserialize(s.serialize(table))
    assert out.equals(table)
