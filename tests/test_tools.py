"""Benchmark + CLI tool tests (reference: ``tests/test_benchmark.py``,
copy_dataset/metadata_util coverage)."""

import io

import numpy as np
import pytest

from petastorm_tpu.benchmark.throughput import reader_throughput
from petastorm_tpu.etl.metadata_util import print_metadata
from petastorm_tpu.etl.petastorm_generate_metadata import (
    generate_petastorm_metadata,
)
from petastorm_tpu.reader import make_reader
from petastorm_tpu.tools.copy_dataset import copy_dataset


class TestThroughput:
    def test_python_read_method(self, synthetic_dataset):
        result = reader_throughput(synthetic_dataset.url,
                                   field_regex=['^id$'], warmup_cycles=10,
                                   measure_cycles=30, loaders_count=2)
        assert result.samples == 30
        assert result.samples_per_second > 0
        assert result.memory_rss_mb > 0

    def test_batch_read_method(self, scalar_dataset):
        result = reader_throughput(scalar_dataset.url, warmup_cycles=10,
                                   measure_cycles=50, read_method='batch',
                                   loaders_count=2)
        assert result.samples >= 50

    def test_jax_read_method(self, scalar_dataset):
        result = reader_throughput(scalar_dataset.url,
                                   field_regex=['^id$', '^float64$'],
                                   warmup_cycles=8, measure_cycles=32,
                                   read_method='jax', batch_size=8,
                                   loaders_count=2)
        assert result.samples >= 32

    def test_cli_smoke(self, synthetic_dataset, capsys):
        from petastorm_tpu.benchmark.cli import main
        assert main([synthetic_dataset.url, '--field-regex', '^id$',
                     '-w', '5', '-m', '10', '-l', '2']) == 0
        assert 'samples/sec' in capsys.readouterr().out

    def test_write_throughput(self, tmp_path):
        from petastorm_tpu.benchmark.throughput import write_throughput
        url = 'file://' + str(tmp_path / 'wb')
        result = write_throughput(url, rows=24, image_hw=(32, 32),
                                  rowgroup_size_rows=8, workers_count=2)
        assert result.samples == 24
        assert result.samples_per_second > 0
        # the written store must be a real readable dataset
        with make_reader(url, shuffle_row_groups=False) as reader:
            assert sum(1 for _ in reader) == 24

    def test_write_throughput_refuses_nonempty_target(self, tmp_path):
        from petastorm_tpu.benchmark.throughput import write_throughput
        url = 'file://' + str(tmp_path / 'wb_dirty')
        write_throughput(url, rows=8, image_hw=(32, 32),
                         rowgroup_size_rows=8)
        with pytest.raises(ValueError, match='fresh directory'):
            write_throughput(url, rows=8, image_hw=(32, 32))

    def test_cli_write_mode(self, tmp_path, capsys):
        from petastorm_tpu.benchmark.cli import main
        url = 'file://' + str(tmp_path / 'wb_cli')
        assert main([url, '--write', '--write-rows', '12']) == 0
        assert 'samples/sec' in capsys.readouterr().out


class TestDummyReader:
    """Calibration mode: synthetic zero-I/O readers through the same
    measurement paths (reference: ``petastorm/benchmark/dummy_reader.py``)."""

    def test_dummy_batch_reader_serves_schema_shaped_batches(self):
        from petastorm_tpu.benchmark.dummy_reader import DummyBatchReader
        with DummyBatchReader(batch_size=32, num_batches=3) as reader:
            batches = list(reader)
        assert len(batches) == 3
        assert batches[0].test.shape == (32, 64)
        assert batches[0].test.dtype == np.float32
        assert reader.schema.test.name == 'test'
        assert reader.last_row_consumed

    def test_dummy_row_reader_bounded(self):
        from petastorm_tpu.benchmark.dummy_reader import DummyRowReader
        with DummyRowReader(num_rows=10) as reader:
            rows = list(reader)
        assert len(rows) == 10
        assert rows[0].test.shape == (64,)

    def test_dummy_python_mode(self):
        result = reader_throughput(None, warmup_cycles=5, measure_cycles=20,
                                   reader_type='dummy')
        assert result.samples == 20
        assert result.samples_per_second > 0

    def test_dummy_batch_mode(self):
        result = reader_throughput(None, warmup_cycles=10, measure_cycles=50,
                                   read_method='batch', reader_type='dummy')
        assert result.samples >= 50

    def test_dummy_jax_mode_is_framework_upper_bound(self):
        result = reader_throughput(None, warmup_cycles=8, measure_cycles=64,
                                   read_method='jax', batch_size=8,
                                   reader_type='dummy')
        assert result.samples >= 64
        assert result.samples_per_second > 0

    def test_dummy_cycles_distinct_batches(self):
        from petastorm_tpu.benchmark.dummy_reader import DummyBatchReader
        with DummyBatchReader(batch_size=4, num_batches=4,
                              distinct_batches=2) as reader:
            batches = list(reader)
        assert np.array_equal(batches[0].test, batches[2].test)
        assert not np.array_equal(batches[0].test, batches[1].test)

    def test_dummy_spawn_new_process(self):
        # the documented --reader dummy mode has no URL; the clean-RSS
        # subprocess path must tolerate dataset_url=None
        result = reader_throughput(None, warmup_cycles=2, measure_cycles=10,
                                   reader_type='dummy',
                                   spawn_new_process=True)
        assert result.samples == 10

    def test_cli_dummy_mode_needs_no_url(self, capsys):
        from petastorm_tpu.benchmark.cli import main
        assert main(['--reader', 'dummy', '-w', '5', '-m', '10']) == 0
        assert 'samples/sec' in capsys.readouterr().out


class TestCopyDataset:
    def test_full_copy(self, synthetic_dataset, tmp_path):
        target = 'file://' + str(tmp_path / 'copy')
        n = copy_dataset(synthetic_dataset.url, target,
                         field_regex=['^id$', '^id2$', '^matrix_uint16$'])
        assert n == 100
        with make_reader(target, shuffle_row_groups=False) as reader:
            rows = list(reader)
        assert sorted(r.id for r in rows) == list(range(100))
        assert set(rows[0]._fields) == {'id', 'id2', 'matrix_uint16'}
        expected = {r['id']: r for r in synthetic_dataset.data}
        for row in rows[:5]:
            np.testing.assert_array_equal(row.matrix_uint16,
                                          expected[row.id]['matrix_uint16'])

    def test_not_null_filter(self, synthetic_dataset, tmp_path):
        target = 'file://' + str(tmp_path / 'copy_nn')
        n = copy_dataset(synthetic_dataset.url, target,
                         field_regex=['^id$', '^matrix_nullable$'],
                         not_null_fields=['matrix_nullable'])
        # every 3rd row has a null matrix_nullable
        expected = sum(1 for r in synthetic_dataset.data
                       if r['matrix_nullable'] is not None)
        assert n == expected


class TestMetadataTools:
    def test_print_metadata(self, synthetic_dataset):
        out = io.StringIO()
        print_metadata(synthetic_dataset.url, out=out)
        text = out.getvalue()
        assert 'Unischema: TestSchema' in text
        assert 'image_png' in text
        assert 'Row-groups:' in text

    def test_generate_metadata_on_plain_store(self, scalar_dataset):
        from petastorm_tpu.etl.dataset_metadata import (
            ParquetDatasetInfo, get_schema,
        )
        schema = generate_petastorm_metadata(scalar_dataset.url)
        stored = get_schema(ParquetDatasetInfo(scalar_dataset.url))
        assert set(stored.fields) == set(schema.fields)

    def test_generate_metadata_with_class(self, tmp_path):
        from tests.test_common import TestSchema, create_test_dataset
        url = 'file://' + str(tmp_path / 'regen')
        create_test_dataset(url, range(10), num_files=1)
        schema = generate_petastorm_metadata(
            url, unischema_class='tests.test_common.TestSchema')
        assert set(schema.fields) == set(TestSchema.fields)
