"""Benchmark + CLI tool tests (reference: ``tests/test_benchmark.py``,
copy_dataset/metadata_util coverage)."""

import io

import numpy as np
import pytest

from petastorm_tpu.benchmark.throughput import reader_throughput
from petastorm_tpu.etl.metadata_util import print_metadata
from petastorm_tpu.etl.petastorm_generate_metadata import (
    generate_petastorm_metadata,
)
from petastorm_tpu.reader import make_reader
from petastorm_tpu.tools.copy_dataset import copy_dataset


class TestThroughput:
    def test_python_read_method(self, synthetic_dataset):
        result = reader_throughput(synthetic_dataset.url,
                                   field_regex=['^id$'], warmup_cycles=10,
                                   measure_cycles=30, loaders_count=2)
        assert result.samples == 30
        assert result.samples_per_second > 0
        assert result.memory_rss_mb > 0

    def test_batch_read_method(self, scalar_dataset):
        result = reader_throughput(scalar_dataset.url, warmup_cycles=10,
                                   measure_cycles=50, read_method='batch',
                                   loaders_count=2)
        assert result.samples >= 50

    def test_jax_read_method(self, scalar_dataset):
        result = reader_throughput(scalar_dataset.url,
                                   field_regex=['^id$', '^float64$'],
                                   warmup_cycles=8, measure_cycles=32,
                                   read_method='jax', batch_size=8,
                                   loaders_count=2)
        assert result.samples >= 32

    def test_cli_smoke(self, synthetic_dataset, capsys):
        from petastorm_tpu.benchmark.cli import main
        assert main([synthetic_dataset.url, '--field-regex', '^id$',
                     '-w', '5', '-m', '10', '-l', '2']) == 0
        assert 'samples/sec' in capsys.readouterr().out


class TestCopyDataset:
    def test_full_copy(self, synthetic_dataset, tmp_path):
        target = 'file://' + str(tmp_path / 'copy')
        n = copy_dataset(synthetic_dataset.url, target,
                         field_regex=['^id$', '^id2$', '^matrix_uint16$'])
        assert n == 100
        with make_reader(target, shuffle_row_groups=False) as reader:
            rows = list(reader)
        assert sorted(r.id for r in rows) == list(range(100))
        assert set(rows[0]._fields) == {'id', 'id2', 'matrix_uint16'}
        expected = {r['id']: r for r in synthetic_dataset.data}
        for row in rows[:5]:
            np.testing.assert_array_equal(row.matrix_uint16,
                                          expected[row.id]['matrix_uint16'])

    def test_not_null_filter(self, synthetic_dataset, tmp_path):
        target = 'file://' + str(tmp_path / 'copy_nn')
        n = copy_dataset(synthetic_dataset.url, target,
                         field_regex=['^id$', '^matrix_nullable$'],
                         not_null_fields=['matrix_nullable'])
        # every 3rd row has a null matrix_nullable
        expected = sum(1 for r in synthetic_dataset.data
                       if r['matrix_nullable'] is not None)
        assert n == expected


class TestMetadataTools:
    def test_print_metadata(self, synthetic_dataset):
        out = io.StringIO()
        print_metadata(synthetic_dataset.url, out=out)
        text = out.getvalue()
        assert 'Unischema: TestSchema' in text
        assert 'image_png' in text
        assert 'Row-groups:' in text

    def test_generate_metadata_on_plain_store(self, scalar_dataset):
        from petastorm_tpu.etl.dataset_metadata import (
            ParquetDatasetInfo, get_schema,
        )
        schema = generate_petastorm_metadata(scalar_dataset.url)
        stored = get_schema(ParquetDatasetInfo(scalar_dataset.url))
        assert set(stored.fields) == set(schema.fields)

    def test_generate_metadata_with_class(self, tmp_path):
        from tests.test_common import TestSchema, create_test_dataset
        url = 'file://' + str(tmp_path / 'regen')
        create_test_dataset(url, range(10), num_files=1)
        schema = generate_petastorm_metadata(
            url, unischema_class='tests.test_common.TestSchema')
        assert set(schema.fields) == set(TestSchema.fields)
