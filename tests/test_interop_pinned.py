"""Read the CHECKED-IN reference-written dataset (tests/data/reference_written).

The reference pins datasets produced by old petastorm versions
(``tests/data/legacy/``, read by ``test_reading_legacy_datasets.py:1-60``);
this is the same durability guarantee here: the fixture was generated once
by the reference's own ``unischema``/``codecs`` modules (see
``tests/test_interop.py``'s ``reference_written_dataset``), committed as
binary, and must keep decoding byte-for-byte forever — with no dependency
on the reference checkout being mounted.
"""

import os
from decimal import Decimal

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.etl.dataset_metadata import get_schema_from_dataset_url

PINNED_URL = 'file://' + os.path.join(
    os.path.dirname(os.path.abspath(__file__)), 'data', 'reference_written')

N_ROWS = 24  # matches tests/test_interop.py's fixture constants


def _expected_rows():
    """Regenerate the values the fixture was built from (RandomState(42) —
    identical stream on every platform/numpy version for these draws)."""
    rng = np.random.RandomState(42)
    rows = []
    for i in range(N_ROWS):
        rows.append({
            'id': np.int32(i),
            'name': 'row_%d' % i,
            'weight': np.float64(i) / 3.0,
            'vec': rng.rand(8).astype(np.float32),
            'cvec': rng.rand(4).astype(np.float64),
            'img': rng.randint(0, 255, (16, 32, 3), np.uint8),
            'price': Decimal('%d.%02d' % (i, i)),
            'maybe': None if i % 3 == 0 else np.int32(i * 10),
        })
    return {r['id']: r for r in rows}


def test_pinned_schema_loads_via_depickler():
    schema = get_schema_from_dataset_url(PINNED_URL)
    assert set(schema.fields) == {'id', 'name', 'weight', 'vec', 'cvec',
                                  'img', 'price', 'maybe'}
    assert schema.img.shape == (16, 32, 3)
    assert schema.maybe.nullable


@pytest.mark.parametrize('pool', ['dummy', 'thread'])
def test_pinned_rows_decode_exactly(pool):
    expected = _expected_rows()
    with make_reader(PINNED_URL, shuffle_row_groups=False,
                     reader_pool_type=pool) as reader:
        rows = list(reader)
    assert len(rows) == N_ROWS
    for row in rows:
        want = expected[row.id]
        assert row.name == want['name']
        assert row.weight == want['weight']
        np.testing.assert_array_equal(row.vec, want['vec'])
        np.testing.assert_array_equal(row.cvec, want['cvec'])
        np.testing.assert_array_equal(row.img, want['img'])
        assert row.price == want['price']
        if want['maybe'] is None:
            assert row.maybe is None
        else:
            assert row.maybe == want['maybe']


def test_pinned_batch_reader():
    expected = _expected_rows()
    with make_batch_reader(PINNED_URL, shuffle_row_groups=False,
                           schema_fields=['^id$', '^img$']) as reader:
        for batch in reader:
            for i in range(len(batch.id)):
                np.testing.assert_array_equal(
                    batch.img[i], expected[batch.id[i]]['img'])
