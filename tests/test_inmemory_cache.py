"""InMemoryCachedLoader: decode-once epoch replay from device arrays."""

import numpy as np
import pytest

from petastorm_tpu.jax import make_jax_loader


def _ids(batches):
    return np.concatenate([np.asarray(b['id']) for b in batches]).tolist()


def test_replay_serves_same_rows_without_reader(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         last_batch='short',
                         inmemory_cache_all=True) as loader:
        first = _ids(list(loader))
        assert sorted(first) == list(range(100))
        # the single-epoch reader is exhausted; replay must come from cache
        assert loader.reader.last_row_consumed
        second = _ids(list(loader))
        third = _ids(list(loader))
    assert sorted(second) == list(range(100))
    assert sorted(third) == list(range(100))


def test_replay_reshuffles_batch_order(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=5, fields=['^id$'],
                         last_batch='short', seed=7,
                         inmemory_cache_all=True) as loader:
        first = _ids(list(loader))
        second = _ids(list(loader))
        third = _ids(list(loader))
    assert second != first or third != first
    assert second != third


def test_row_shuffle_replay_redraws_batch_membership(scalar_dataset):
    # with shuffle_rows, replay must reshuffle ROW-to-batch composition
    # (not just batch order), mirroring the reference torch loader's
    # fresh-shuffling-buffer replay (petastorm/pytorch.py:344-407)
    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         shuffle_rows=True, seed=3,
                         inmemory_cache_all=True) as loader:
        first = [frozenset(np.asarray(b['id']).tolist()) for b in loader]
        second = [frozenset(np.asarray(b['id']).tolist()) for b in loader]
        third = [frozenset(np.asarray(b['id']).tolist()) for b in loader]
    # every replay is a full epoch...
    assert sorted(x for s in second for x in s) == list(range(100))
    assert sorted(x for s in third for x in s) == list(range(100))
    # ...and batch membership changed, not merely batch order
    assert set(second) != set(first)
    assert set(third) != set(second)


def test_row_shuffle_replay_pads_tail(scalar_dataset):
    # 100 rows, batch 30, pad: replay must re-pad its tail with a mask
    with make_jax_loader(scalar_dataset.url, batch_size=30, fields=['^id$'],
                         shuffle_rows=True, last_batch='pad', seed=5,
                         inmemory_cache_all=True) as loader:
        list(loader)
        replay = list(loader)
    assert len(replay) == 4
    seen = []
    for b in replay:
        mask = np.asarray(b['valid_mask'])
        assert len(mask) == 30
        seen.extend(np.asarray(b['id'])[mask].tolist())
    assert sorted(seen) == list(range(100))
    counts = sorted(int(np.asarray(b['valid_mask']).sum()) for b in replay)
    assert counts == [10, 30, 30, 30]


def test_row_shuffle_replay_of_empty_cache_is_empty(scalar_dataset):
    # zero batches cached (drop + oversize batch): replay must stay empty,
    # not IndexError building the row cache
    with make_jax_loader(scalar_dataset.url, batch_size=512, fields=['^id$'],
                         shuffle_rows=True, last_batch='drop',
                         inmemory_cache_all=True) as loader:
        assert list(loader) == []
        assert list(loader) == []


def test_stopped_iter_steps_raises_not_indexerror(scalar_dataset):
    # a saved iter_steps cursor must not outlive stop(): resuming used to
    # IndexError over the released cache instead of raising 'stopped'
    loader = make_jax_loader(scalar_dataset.url, batch_size=20,
                             fields=['^id$'], inmemory_cache_all=True)
    list(loader.iter_steps(7))
    loader.stop()
    with pytest.raises(RuntimeError, match='stopped'):
        list(loader.iter_steps(1))


def test_live_replay_generator_sees_stop_as_runtimeerror(scalar_dataset):
    # a generator the caller already holds must surface stop() as the
    # 'stopped' RuntimeError, not IndexError/AttributeError over the
    # released cache
    for shuffle in (False, True):
        loader = make_jax_loader(scalar_dataset.url, batch_size=10,
                                 fields=['^id$'], shuffle_rows=shuffle,
                                 inmemory_cache_all=True)
        list(loader)                      # complete the first pass
        g = iter(loader)                  # live replay generator
        next(g)
        loader.stop()
        with pytest.raises(RuntimeError, match='stopped'):
            next(g)


def test_cached_batches_are_same_arrays(scalar_dataset):
    # replay must reuse the staged device arrays (no re-stage, no copy)
    with make_jax_loader(scalar_dataset.url, batch_size=20, fields=['^id$'],
                         last_batch='short',
                         inmemory_cache_all=True) as loader:
        first = list(loader)
        second = list(loader)
    first_ids = {id(b['id']) for b in first}
    second_ids = {id(b['id']) for b in second}
    assert first_ids == second_ids


def test_iter_steps_crosses_epoch_boundaries(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=20, fields=['^id$'],
                         inmemory_cache_all=True) as loader:
        batches = list(loader.iter_steps(12))  # 5 batches/epoch -> 2.4 epochs
    assert len(batches) == 12
    assert all(len(np.asarray(b['id'])) == 20 for b in batches)


def test_abandoned_boundary_iterator_does_not_duplicate_cache(scalar_dataset):
    # consuming exactly all batches WITHOUT running the generator epilogue
    # (zip/islice) used to leave _complete False; the next pass re-read the
    # reader and appended a second copy of the epoch to the cache
    import itertools
    with make_jax_loader(scalar_dataset.url, batch_size=20, fields=['^id$'],
                         inmemory_cache_all=True) as loader:
        head = list(itertools.islice(loader, 5))  # exactly one epoch
        assert len(head) == 5
        replay = list(loader)
    assert len(replay) == 5
    assert sorted(_ids(replay)) == list(range(100))


def test_iterating_after_stop_raises(scalar_dataset):
    loader = make_jax_loader(scalar_dataset.url, batch_size=20,
                             fields=['^id$'], inmemory_cache_all=True)
    list(loader)
    loader.stop()
    with pytest.raises(RuntimeError, match='stopped'):
        iter(loader)


def test_load_state_dict_raises_actionable(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         inmemory_cache_all=True) as loader:
        with pytest.raises(RuntimeError, match='no checkpointable reader'):
            loader.load_state_dict({'epoch': 0})


def test_diagnostics_passthrough(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         inmemory_cache_all=True) as loader:
        assert isinstance(loader.diagnostics, dict)


def test_multi_epoch_reader_rejected(scalar_dataset):
    with pytest.raises(ValueError, match='caches exactly one epoch'):
        make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                        num_epochs=3, inmemory_cache_all=True)


def test_state_dict_raises_actionable(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         inmemory_cache_all=True) as loader:
        with pytest.raises(RuntimeError, match='no checkpointable reader'):
            loader.state_dict()


def test_empty_result_iter_steps_raises(scalar_dataset):
    # batch_size larger than the dataset with 'drop': zero batches cached
    with make_jax_loader(scalar_dataset.url, batch_size=512, fields=['^id$'],
                         last_batch='drop',
                         inmemory_cache_all=True) as loader:
        with pytest.raises(RuntimeError, match='no batches'):
            list(loader.iter_steps(1))
