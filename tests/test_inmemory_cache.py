"""InMemoryCachedLoader: decode-once epoch replay from device arrays."""

import numpy as np
import pytest

from petastorm_tpu.jax import make_jax_loader


def _ids(batches):
    return np.concatenate([np.asarray(b['id']) for b in batches]).tolist()


def test_replay_serves_same_rows_without_reader(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         last_batch='short',
                         inmemory_cache_all=True) as loader:
        first = _ids(list(loader))
        assert sorted(first) == list(range(100))
        # the single-epoch reader is exhausted; replay must come from cache
        assert loader.reader.last_row_consumed
        second = _ids(list(loader))
        third = _ids(list(loader))
    assert sorted(second) == list(range(100))
    assert sorted(third) == list(range(100))


def test_replay_reshuffles_batch_order(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=5, fields=['^id$'],
                         last_batch='short', seed=7,
                         inmemory_cache_all=True) as loader:
        first = _ids(list(loader))
        second = _ids(list(loader))
        third = _ids(list(loader))
    assert second != first or third != first
    assert second != third


def test_cached_batches_are_same_arrays(scalar_dataset):
    # replay must reuse the staged device arrays (no re-stage, no copy)
    with make_jax_loader(scalar_dataset.url, batch_size=20, fields=['^id$'],
                         last_batch='short',
                         inmemory_cache_all=True) as loader:
        first = list(loader)
        second = list(loader)
    first_ids = {id(b['id']) for b in first}
    second_ids = {id(b['id']) for b in second}
    assert first_ids == second_ids


def test_iter_steps_crosses_epoch_boundaries(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=20, fields=['^id$'],
                         inmemory_cache_all=True) as loader:
        batches = list(loader.iter_steps(12))  # 5 batches/epoch -> 2.4 epochs
    assert len(batches) == 12
    assert all(len(np.asarray(b['id'])) == 20 for b in batches)


def test_abandoned_boundary_iterator_does_not_duplicate_cache(scalar_dataset):
    # consuming exactly all batches WITHOUT running the generator epilogue
    # (zip/islice) used to leave _complete False; the next pass re-read the
    # reader and appended a second copy of the epoch to the cache
    import itertools
    with make_jax_loader(scalar_dataset.url, batch_size=20, fields=['^id$'],
                         inmemory_cache_all=True) as loader:
        head = list(itertools.islice(loader, 5))  # exactly one epoch
        assert len(head) == 5
        replay = list(loader)
    assert len(replay) == 5
    assert sorted(_ids(replay)) == list(range(100))


def test_iterating_after_stop_raises(scalar_dataset):
    loader = make_jax_loader(scalar_dataset.url, batch_size=20,
                             fields=['^id$'], inmemory_cache_all=True)
    list(loader)
    loader.stop()
    with pytest.raises(RuntimeError, match='stopped'):
        iter(loader)


def test_load_state_dict_raises_actionable(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         inmemory_cache_all=True) as loader:
        with pytest.raises(RuntimeError, match='no checkpointable reader'):
            loader.load_state_dict({'epoch': 0})


def test_diagnostics_passthrough(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         inmemory_cache_all=True) as loader:
        assert isinstance(loader.diagnostics, dict)


def test_multi_epoch_reader_rejected(scalar_dataset):
    with pytest.raises(ValueError, match='caches exactly one epoch'):
        make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                        num_epochs=3, inmemory_cache_all=True)


def test_state_dict_raises_actionable(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=10, fields=['^id$'],
                         inmemory_cache_all=True) as loader:
        with pytest.raises(RuntimeError, match='no checkpointable reader'):
            loader.state_dict()


def test_empty_result_iter_steps_raises(tmp_path, scalar_dataset):
    # batch_size larger than the dataset with 'drop': zero batches cached
    with make_jax_loader(scalar_dataset.url, batch_size=512, fields=['^id$'],
                         last_batch='drop',
                         inmemory_cache_all=True) as loader:
        with pytest.raises(RuntimeError, match='no batches'):
            list(loader.iter_steps(1))
