"""LocalDiskCache unit tests (reference: ``tests/test_disk_cache.py``)."""

import os
import pickle
import threading

import numpy as np
import pytest

from petastorm_tpu.cache import LocalDiskCache, NullCache


def test_null_cache_always_computes():
    calls = []
    cache = NullCache()
    assert cache.get('k', lambda: calls.append(1) or 42) == 42
    assert cache.get('k', lambda: calls.append(1) or 42) == 42
    assert len(calls) == 2


class TestLocalDiskCache:
    def test_get_or_compute(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        calls = []

        def fill():
            calls.append(1)
            return {'a': np.arange(5)}

        first = cache.get('key1', fill)
        second = cache.get('key1', fill)
        np.testing.assert_array_equal(first['a'], second['a'])
        assert len(calls) == 1  # second call served from disk

    def test_distinct_keys(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        assert cache.get('a', lambda: 1) == 1
        assert cache.get('b', lambda: 2) == 2
        assert cache.get('a', lambda: 99) == 1

    def test_persistence_across_instances(self, tmp_path):
        path = str(tmp_path / 'c')
        LocalDiskCache(path, 10 ** 6).get('k', lambda: 'value')
        fresh = LocalDiskCache(path, 10 ** 6)
        assert fresh.get('k', lambda: 'MISS') == 'value'

    def test_size_limit_evicts_lru(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), size_limit_bytes=50_000)
        payload = np.zeros(10_000, dtype=np.uint8)  # ~10KB pickled
        for i in range(10):
            cache.get('k%d' % i, lambda: payload)
        # total would be ~100KB; eviction must bring it under the cap
        total = sum(os.path.getsize(os.path.join(root, f))
                    for root, _, files in os.walk(str(tmp_path / 'c'))
                    for f in files)
        assert total <= 50_000

    def test_corrupt_entry_recomputed(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        cache.get('k', lambda: 'good')
        entry = cache._entry_path('k')
        with open(entry, 'wb') as f:
            f.write(b'not a pickle')
        assert cache.get('k', lambda: 'recomputed') == 'recomputed'

    def test_weird_keys_are_safe(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        for key in ('a/b/../c', 'x' * 500, 'sp ace\n', "k'\"", ''):
            assert cache.get(key, lambda k=key: 'v:' + str(k)) == 'v:' + key
        # nothing escaped the cache root
        root = os.path.realpath(str(tmp_path / 'c'))
        for dirpath, _, files in os.walk(root):
            assert os.path.realpath(dirpath).startswith(root)

    def test_pickles_across_process_boundary(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        cache.get('k', lambda: 1)
        clone = pickle.loads(pickle.dumps(cache))
        assert clone.get('k', lambda: 'MISS') == 1

    def test_concurrent_get_same_key(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        results = []

        def reader():
            results.append(cache.get('k', lambda: np.arange(100).tolist()))

        threads = [threading.Thread(target=reader) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(r == list(range(100)) for r in results)

    def test_cleanup_flag(self, tmp_path):
        path = str(tmp_path / 'c')
        cache = LocalDiskCache(path, 10 ** 6, cleanup=True)
        cache.get('k', lambda: 1)
        cache.cleanup()
        assert not os.path.exists(path)

    def test_cleanup_default_keeps(self, tmp_path):
        path = str(tmp_path / 'c')
        cache = LocalDiskCache(path, 10 ** 6)
        cache.get('k', lambda: 1)
        cache.cleanup()
        assert os.path.exists(path)

    def test_corrupt_entry_is_deleted_before_refill(self, tmp_path):
        """Other processes must stop re-reading a corrupt entry's bytes:
        the reader that detects corruption deletes the file itself, not
        just its own view of it."""
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        cache.get('k', lambda: 'good')
        entry = cache._entry_path('k')
        with open(entry, 'wb') as f:
            f.write(b'not a pickle')
        removed_during_fill = []

        def fill():
            removed_during_fill.append(not os.path.exists(entry))
            return 'recomputed'

        assert cache.get('k', fill) == 'recomputed'
        assert removed_during_fill == [True]

    def test_truncated_pickle_valueerror_recomputed(self, tmp_path):
        import numpy as np
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        cache.get('k', lambda: np.arange(1000))
        entry = cache._entry_path('k')
        blob = open(entry, 'rb').read()
        with open(entry, 'wb') as f:
            f.write(blob[:len(blob) - 500])  # truncate inside the array
        out = cache.get('k', lambda: 'refilled')
        assert out == 'refilled'

    def test_stale_tmp_files_purged_at_init(self, tmp_path):
        path = str(tmp_path / 'c')
        cache = LocalDiskCache(path, 10 ** 6)
        cache.get('k', lambda: 'v')
        shard = os.path.dirname(cache._entry_path('k'))
        # a crashed writer's orphan (pid 2**22+9999 can't be running:
        # default pid_max) and live-looking garbage from THIS process
        dead = os.path.join(shard, 'orphan.pkl.tmp.%d' % (2 ** 22 + 9999))
        live = os.path.join(shard, 'inflight.pkl.tmp.%d' % os.getpid())
        for p in (dead, live):
            with open(p, 'wb') as f:
                f.write(b'x' * 4096)
        fresh = LocalDiskCache(path, 10 ** 6)
        assert not os.path.exists(dead)   # dead writer: purged
        assert os.path.exists(live)       # live pid: left alone
        # and the running total never counted tmp files
        assert fresh._total == os.path.getsize(cache._entry_path('k'))

    def test_foreign_host_tmp_files_need_age_not_pid(self, tmp_path):
        """On shared storage (multi-host fleet dir) another host's pid
        cannot be liveness-checked here: a FRESH foreign tmp must
        survive the purge (its writer may be mid-rename on its own
        host); only a stale one (writer long dead) is collected."""
        path = str(tmp_path / 'c')
        os.makedirs(os.path.join(path, '00'), exist_ok=True)
        fresh = os.path.join(path, '00', 'e.pkl.tmp.otherhost-12345')
        stale = os.path.join(path, '00', 'f.pkl.tmp.otherhost-12346')
        for p in (fresh, stale):
            with open(p, 'wb') as f:
                f.write(b'x')
        os.utime(stale, (1.0, 1.0))  # hours past the foreign TTL
        LocalDiskCache(path, 10 ** 6)
        assert os.path.exists(fresh)
        assert not os.path.exists(stale)

    def test_eviction_walk_skips_inflight_tmp_files(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), size_limit_bytes=20_000)
        tmp_file = os.path.join(str(tmp_path / 'c'), '00',
                                'big.pkl.tmp.%d' % os.getpid())
        os.makedirs(os.path.dirname(tmp_file), exist_ok=True)
        with open(tmp_file, 'wb') as f:
            f.write(b'x' * 100_000)  # way over the cap, but in-flight
        cache.get('k', lambda: b'y' * 30_000)  # triggers eviction
        assert os.path.exists(tmp_file)  # never "evicted" under a writer
