"""LocalDiskCache unit tests (reference: ``tests/test_disk_cache.py``)."""

import os
import pickle
import threading

import numpy as np
import pytest

from petastorm_tpu.cache import LocalDiskCache, NullCache


def test_null_cache_always_computes():
    calls = []
    cache = NullCache()
    assert cache.get('k', lambda: calls.append(1) or 42) == 42
    assert cache.get('k', lambda: calls.append(1) or 42) == 42
    assert len(calls) == 2


class TestLocalDiskCache:
    def test_get_or_compute(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        calls = []

        def fill():
            calls.append(1)
            return {'a': np.arange(5)}

        first = cache.get('key1', fill)
        second = cache.get('key1', fill)
        np.testing.assert_array_equal(first['a'], second['a'])
        assert len(calls) == 1  # second call served from disk

    def test_distinct_keys(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        assert cache.get('a', lambda: 1) == 1
        assert cache.get('b', lambda: 2) == 2
        assert cache.get('a', lambda: 99) == 1

    def test_persistence_across_instances(self, tmp_path):
        path = str(tmp_path / 'c')
        LocalDiskCache(path, 10 ** 6).get('k', lambda: 'value')
        fresh = LocalDiskCache(path, 10 ** 6)
        assert fresh.get('k', lambda: 'MISS') == 'value'

    def test_size_limit_evicts_lru(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), size_limit_bytes=50_000)
        payload = np.zeros(10_000, dtype=np.uint8)  # ~10KB pickled
        for i in range(10):
            cache.get('k%d' % i, lambda: payload)
        # total would be ~100KB; eviction must bring it under the cap
        total = sum(os.path.getsize(os.path.join(root, f))
                    for root, _, files in os.walk(str(tmp_path / 'c'))
                    for f in files)
        assert total <= 50_000

    def test_corrupt_entry_recomputed(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        cache.get('k', lambda: 'good')
        entry = cache._entry_path('k')
        with open(entry, 'wb') as f:
            f.write(b'not a pickle')
        assert cache.get('k', lambda: 'recomputed') == 'recomputed'

    def test_weird_keys_are_safe(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        for key in ('a/b/../c', 'x' * 500, 'sp ace\n', "k'\"", ''):
            assert cache.get(key, lambda k=key: 'v:' + str(k)) == 'v:' + key
        # nothing escaped the cache root
        root = os.path.realpath(str(tmp_path / 'c'))
        for dirpath, _, files in os.walk(root):
            assert os.path.realpath(dirpath).startswith(root)

    def test_pickles_across_process_boundary(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        cache.get('k', lambda: 1)
        clone = pickle.loads(pickle.dumps(cache))
        assert clone.get('k', lambda: 'MISS') == 1

    def test_concurrent_get_same_key(self, tmp_path):
        cache = LocalDiskCache(str(tmp_path / 'c'), 10 ** 6)
        results = []

        def reader():
            results.append(cache.get('k', lambda: np.arange(100).tolist()))

        threads = [threading.Thread(target=reader) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert all(r == list(range(100)) for r in results)

    def test_cleanup_flag(self, tmp_path):
        path = str(tmp_path / 'c')
        cache = LocalDiskCache(path, 10 ** 6, cleanup=True)
        cache.get('k', lambda: 1)
        cache.cleanup()
        assert not os.path.exists(path)

    def test_cleanup_default_keeps(self, tmp_path):
        path = str(tmp_path / 'c')
        cache = LocalDiskCache(path, 10 ** 6)
        cache.get('k', lambda: 1)
        cache.cleanup()
        assert os.path.exists(path)
