"""Canonical synthetic datasets for the test suite.

Mirrors the reference's fixture strategy (``petastorm/tests/test_common.py``):
a rich multi-codec ``TestSchema`` materialized into a real on-disk dataset,
plus a plain (non-petastorm) scalar parquet store.
"""

from decimal import Decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu.codecs import (
    CompressedImageCodec, CompressedNdarrayCodec, NdarrayCodec, ScalarCodec,
)
from petastorm_tpu.etl.dataset_metadata import write_dataset
from petastorm_tpu.unischema import Unischema, UnischemaField

TestSchema = Unischema('TestSchema', [
    UnischemaField('partition_key', np.str_, (), ScalarCodec(pa.string()), False),
    UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
    UnischemaField('id2', np.int32, (), ScalarCodec(pa.int32()), False),
    UnischemaField('id_float', np.float64, (), ScalarCodec(pa.float64()), False),
    UnischemaField('id_odd', np.bool_, (), ScalarCodec(pa.bool_()), False),
    UnischemaField('python_primitive_uint8', np.uint8, (), ScalarCodec(pa.uint8()), False),
    UnischemaField('image_png', np.uint8, (16, 32, 3), CompressedImageCodec('png'), False),
    UnischemaField('matrix', np.float32, (10, 20, 30), NdarrayCodec(), False),
    UnischemaField('decimal', Decimal, (), ScalarCodec(pa.string()), False),
    UnischemaField('matrix_uint16', np.uint16, (2, 3), NdarrayCodec(), False),
    UnischemaField('matrix_string', np.bytes_, (None, None), NdarrayCodec(), False),
    UnischemaField('empty_matrix_string', np.bytes_, (None,), NdarrayCodec(), False),
    UnischemaField('matrix_nullable', np.uint16, (None, 14), NdarrayCodec(), True),
    UnischemaField('sensor_name', np.str_, (1,), NdarrayCodec(), False),
    UnischemaField('string_array_nullable', np.str_, (None,), NdarrayCodec(), True),
    UnischemaField('compressed', np.float64, (4, 5), CompressedNdarrayCodec(), False),
])


def _row(i, seed=0):
    rng = np.random.RandomState(seed * 100000 + i)
    return {
        'partition_key': 'p_%d' % (i % 5),
        'id': i,
        'id2': i % 2,
        'id_float': float(i),
        'id_odd': bool(i % 2),
        'python_primitive_uint8': i % 255,
        'image_png': rng.randint(0, 255, (16, 32, 3)).astype(np.uint8),
        'matrix': rng.rand(10, 20, 30).astype(np.float32),
        'decimal': Decimal('%d.%d' % (i, i % 100)),
        'matrix_uint16': rng.randint(0, 2 ** 16 - 1, (2, 3)).astype(np.uint16),
        'matrix_string': np.array([[b'a%d' % i, b'bc'], [b'd', b'ef%d' % i]], dtype=np.bytes_),
        'empty_matrix_string': np.array([], dtype=np.bytes_),
        'matrix_nullable': (rng.randint(0, 255, (3, 14)).astype(np.uint16)
                            if i % 3 else None),
        'sensor_name': np.array(['sensor_%d' % i], dtype=np.str_),
        'string_array_nullable': (np.array(['abc', 'x_%d' % i], dtype=np.str_)
                                  if i % 4 else None),
        'compressed': rng.rand(4, 5).astype(np.float64),
    }


def create_test_dataset(url, ids, num_files=4, rowgroup_size=10, partition_by=()):
    """Materialize TestSchema rows for the given ids; returns the row dicts."""
    rows = [_row(i) for i in ids]
    write_dataset(url, TestSchema, rows, rowgroup_size_rows=rowgroup_size,
                  num_files=num_files, partition_by=partition_by)
    return rows


def create_test_scalar_dataset(url, num_rows=100, num_files=4):
    """Plain parquet (no petastorm metadata) for make_batch_reader tests."""
    from petastorm_tpu.fs import get_filesystem_and_path_or_paths
    fs, path = get_filesystem_and_path_or_paths(url)
    fs.makedirs(path, exist_ok=True)
    rows = []
    for i in range(num_rows):
        rows.append({
            'id': i,
            'int_fixed_size_list': list(range(i, i + 3)),
            'datetime': np.datetime64('2019-01-02') + np.timedelta64(i, 'D'),
            'timestamp': np.datetime64('2005-02-25T03:30') + np.timedelta64(i, 'm'),
            'string': 'hello_%d' % i,
            'string2': 'world_%d' % (i % 3),
            'float64': i * 0.66,
        })
    per_file = (num_rows + num_files - 1) // num_files
    for file_idx in range(num_files):
        chunk = rows[file_idx * per_file:(file_idx + 1) * per_file]
        if not chunk:
            continue
        table = pa.table({
            'id': pa.array([r['id'] for r in chunk], pa.int64()),
            'int_fixed_size_list': pa.array([r['int_fixed_size_list'] for r in chunk],
                                            pa.list_(pa.int64())),
            'datetime': pa.array([r['datetime'].astype('datetime64[D]').item() for r in chunk],
                                 pa.date32()),
            'timestamp': pa.array([r['timestamp'].astype('datetime64[us]') for r in chunk],
                                  pa.timestamp('us')),
            'string': pa.array([r['string'] for r in chunk], pa.string()),
            'string2': pa.array([r['string2'] for r in chunk], pa.string()),
            'float64': pa.array([r['float64'] for r in chunk], pa.float64()),
        })
        with fs.open('%s/part-%05d.parquet' % (path, file_idx), 'wb') as f:
            pq.write_table(table, f, row_group_size=13)
    return rows
