"""Ulysses (all-to-all) sequence-parallel attention vs the shared oracle."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # compile-heavy: every test jits on the 8-device mesh

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from petastorm_tpu.ops.ring_attention import (
    reference_attention, ring_attention,
)
from petastorm_tpu.ops.ulysses_attention import ulysses_attention


def _mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ('seq',))


def _qkv(b=2, s=32, h=8, d=16, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, s, h, d).astype(np.float32), dtype)
    return mk(), mk(), mk()


def _shard(mesh, *arrays):
    spec = NamedSharding(mesh, P(None, 'seq', None, None))
    return tuple(jax.device_put(x, spec) for x in arrays)


@pytest.mark.parametrize('n_shards', [2, 4, 8])
@pytest.mark.parametrize('causal', [True, False])
def test_matches_reference(n_shards, causal):
    mesh = _mesh(n_shards)
    q, k, v = _qkv()
    expected = reference_attention(q, k, v, causal=causal)
    qs, ks, vs = _shard(mesh, q, k, v)
    with mesh:
        got = ulysses_attention(qs, ks, vs, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize('dtype', [jnp.float32, jnp.bfloat16])
def test_matches_ring_attention(dtype):
    # the two sequence-parallel strategies must agree with each other, not
    # just with the oracle: same math, different collectives — including in
    # bf16, where both keep f32 softmax probs through the PV product
    mesh = _mesh(4)
    q, k, v = _qkv(seed=3, dtype=dtype)
    qs, ks, vs = _shard(mesh, q, k, v)
    with mesh:
        ring = ring_attention(qs, ks, vs, mesh, causal=True)
        uly = ulysses_attention(qs, ks, vs, mesh, causal=True)
    tol = 2e-5 if dtype == jnp.float32 else 4e-3
    np.testing.assert_allclose(np.asarray(uly, np.float32),
                               np.asarray(ring, np.float32),
                               atol=tol, rtol=tol)


def test_output_stays_sequence_sharded():
    mesh = _mesh(4)
    q, k, v = _qkv()
    qs, ks, vs = _shard(mesh, q, k, v)
    with mesh:
        got = ulysses_attention(qs, ks, vs, mesh)
    assert got.sharding.spec == P(None, 'seq', None, None)
    assert {sh.data.shape for sh in got.addressable_shards} == {(2, 8, 8, 16)}


def test_rejects_indivisible_heads():
    mesh = _mesh(4)
    q, k, v = _qkv(h=6)  # 6 heads over 4 devices
    with pytest.raises(ValueError, match='ring_attention instead'):
        ulysses_attention(q, k, v, mesh)


def test_bfloat16_inputs():
    mesh = _mesh(4)
    q, k, v = _qkv(dtype=jnp.bfloat16)
    expected = reference_attention(q, k, v)
    qs, ks, vs = _shard(mesh, q, k, v)
    with mesh:
        got = ulysses_attention(qs, ks, vs, mesh)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(expected, np.float32),
                               atol=3e-2, rtol=3e-2)


@pytest.mark.parametrize('causal', [True, False])
def test_gradients_match_reference(causal):
    mesh = _mesh(4)
    q, k, v = _qkv(s=16)

    def uly_loss(q, k, v):
        return jnp.sum(ulysses_attention(q, k, v, mesh, causal=causal) ** 2)

    def oracle_loss(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

    qs, ks, vs = _shard(mesh, q, k, v)
    with mesh:
        uly_grads = jax.grad(uly_loss, argnums=(0, 1, 2))(qs, ks, vs)
    oracle_grads = jax.grad(oracle_loss, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(uly_grads, oracle_grads):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=3e-5, rtol=3e-5)


def test_jit_compiles():
    mesh = _mesh(8)
    q, k, v = _qkv(s=64)
    qs, ks, vs = _shard(mesh, q, k, v)
    with mesh:
        got = jax.jit(lambda a, b, c: ulysses_attention(a, b, c, mesh))(
            qs, ks, vs)
    assert got.shape == q.shape
    assert np.isfinite(np.asarray(got)).all()
