"""DataFrame converter, test_util, and examples tests."""

import numpy as np
import pandas as pd
import pytest

from petastorm_tpu.spark import make_dataframe_converter
from petastorm_tpu.test_util import ReaderMock, generate_datapoint
from petastorm_tpu.test_util.shuffling_analysis import (
    compute_correlation_distribution, generate_shuffle_analysis_dataset,
)

from tests.test_common import TestSchema


def _df(n=100):
    return pd.DataFrame({'id': np.arange(n),
                         'value': np.arange(n) * 0.5,
                         'label': np.arange(n) % 3})


class TestDataFrameConverter:
    def test_materialize_and_read(self, tmp_path):
        converter = make_dataframe_converter(
            _df(), 'file://' + str(tmp_path / 'cache'))
        assert len(converter) == 100
        from petastorm_tpu.reader import make_batch_reader
        with make_batch_reader(converter.cache_dir_url) as reader:
            ids = [i for b in reader for i in b.id]
        assert sorted(ids) == list(range(100))
        converter.delete()

    def test_cache_hit_same_content(self, tmp_path):
        parent = 'file://' + str(tmp_path / 'cache')
        c1 = make_dataframe_converter(_df(), parent)
        c2 = make_dataframe_converter(_df(), parent)
        assert c1 is c2
        c3 = make_dataframe_converter(_df(50), parent)
        assert c3 is not c1
        c1.delete()
        c3.delete()

    def test_zero_copy_slices_not_conflated(self, tmp_path):
        import pyarrow as pa
        table = pa.table({'id': list(range(100))})
        parent = 'file://' + str(tmp_path / 'cache_s')
        c1 = make_dataframe_converter(table.slice(0, 50), parent)
        c2 = make_dataframe_converter(table.slice(50, 50), parent)
        assert c1 is not c2
        from petastorm_tpu.reader import make_batch_reader
        with make_batch_reader(c2.cache_dir_url) as reader:
            ids = sorted(i for b in reader for i in b.id)
        assert ids == list(range(50, 100))
        c1.delete()
        c2.delete()

    def test_torch_loader(self, tmp_path):
        pytest.importorskip('torch')
        converter = make_dataframe_converter(
            _df(), 'file://' + str(tmp_path / 'cache_t'))
        with converter.make_torch_dataloader(batch_size=25) as loader:
            sizes = [len(b['id']) for b in loader]
        assert sizes == [25, 25, 25, 25]
        converter.delete()

    def test_tf_dataset(self, tmp_path):
        tf = pytest.importorskip('tensorflow')
        converter = make_dataframe_converter(
            _df(), 'file://' + str(tmp_path / 'cache_tf'))
        with converter.make_tf_dataset(batch_size=20) as dataset:
            n = sum(len(el.id) for el in dataset)
        assert n == 100
        converter.delete()

    def test_jax_loader(self, tmp_path):
        converter = make_dataframe_converter(
            _df(), 'file://' + str(tmp_path / 'cache_j'))
        with converter.make_jax_loader(batch_size=20) as loader:
            n = sum(len(b['id']) for b in loader)
        assert n == 100
        converter.delete()

    def test_delete_removes_files(self, tmp_path):
        import os
        converter = make_dataframe_converter(
            _df(), 'file://' + str(tmp_path / 'cache_d'))
        path = converter.cache_dir_url[len('file://'):]
        assert os.path.exists(path)
        converter.delete()
        assert not os.path.exists(path)

    def test_spark_converter_gated(self):
        from petastorm_tpu.spark import make_spark_converter
        with pytest.raises(ImportError, match='pyspark'):
            make_spark_converter(object())

    def test_dtype_unifies_float_precision(self, tmp_path):
        import pyarrow.parquet as pq
        df = pd.DataFrame({'id': np.arange(10),
                           'x64': np.arange(10) * 0.5,
                           'arr': [np.arange(3, dtype=np.float64)] * 10})
        converter = make_dataframe_converter(
            df, 'file://' + str(tmp_path / 'cache_f32'), dtype='float32')
        root = converter.cache_dir_url[len('file://'):]
        schema = pq.read_table(root).schema
        import pyarrow as pa
        assert schema.field('x64').type == pa.float32()
        assert schema.field('arr').type == pa.list_(pa.float32())
        assert schema.field('id').type == pa.int64()
        converter.delete()

    def test_dtype_invalid_rejected(self, tmp_path):
        with pytest.raises(ValueError, match='float32'):
            make_dataframe_converter(_df(), 'file://' + str(tmp_path / 'c'),
                                     dtype='float16')


class _MapFS:
    """Injectable fsspec stand-in over a {path: size} dict; paths can be
    scheduled to appear after N exists() polls."""

    def __init__(self, sizes, appear_after=None):
        self._sizes = dict(sizes)
        self._appear_after = dict(appear_after or {})

    def exists(self, path):
        waits = self._appear_after.get(path, 0)
        if waits > 0:
            self._appear_after[path] = waits - 1
            return False
        return path in self._sizes

    def size(self, path):
        return self._sizes[path]


class TestConverterOperationalBehaviors:
    """The reference converter's S3-wait / file-size-advisory / precision
    behaviors (``spark_dataset_converter.py:524-640``), testable without
    pyspark via injectable filesystems and duck-typed dataframes."""

    def test_wait_file_available_polls_until_visible(self):
        from petastorm_tpu.spark import wait_file_available
        fs = _MapFS({'/a': 1, '/b': 2}, appear_after={'/b': 3})
        wait_file_available(['/a', '/b'], fs=fs, poll_interval_s=0.001)

    def test_wait_file_available_timeout_names_stragglers(self):
        from petastorm_tpu.spark import wait_file_available
        fs = _MapFS({'/a': 1})
        with pytest.raises(RuntimeError, match='/never'):
            wait_file_available(['/a', '/never'], fs=fs, timeout_s=0.05,
                                poll_interval_s=0.01)

    def test_wait_file_available_empty_list_noop(self):
        from petastorm_tpu.spark import wait_file_available
        wait_file_available([], fs=_MapFS({}))

    def test_median_size_advisory_warns_on_small_files(self, caplog):
        import logging
        from petastorm_tpu.spark import check_dataset_file_median_size
        fs = _MapFS({'/a': 10, '/b': 20, '/c': 30})
        with caplog.at_level(logging.WARNING,
                             logger='petastorm_tpu.spark.spark_dataset_converter'):
            median = check_dataset_file_median_size(['/a', '/b', '/c'], fs=fs)
        assert median == 20
        assert any('median' in r.message for r in caplog.records)

    def test_median_size_advisory_quiet_on_big_files(self, caplog):
        import logging
        from petastorm_tpu.spark import check_dataset_file_median_size
        big = 64 * 1024 * 1024
        fs = _MapFS({'/a': big, '/b': big + 1})
        with caplog.at_level(logging.WARNING):
            median = check_dataset_file_median_size(['/a', '/b'], fs=fs)
        assert median == big + 1  # larger of the tie, like the reference
        assert not any('median' in r.message for r in caplog.records)

    def test_median_size_single_file_skipped(self):
        from petastorm_tpu.spark import check_dataset_file_median_size
        assert check_dataset_file_median_size(['/a'], fs=_MapFS({'/a': 1})) is None


class _FakeType:
    def __init__(self, name, element=None):
        self._name = name
        if element is not None:
            self.elementType = element

    def typeName(self):
        return self._name


class _FakeColumn:
    def __init__(self, name):
        self.name = name
        self.casts = []

    def cast(self, target):
        return ('cast', self.name, target)


class _FakeField:
    def __init__(self, name, data_type):
        self.name = name
        self.dataType = data_type


class _FakeDF:
    """Duck-typed pyspark DataFrame: schema + withColumn/indexing."""

    def __init__(self, fields):
        self.schema = [_FakeField(n, t) for n, t in fields]
        self.replaced = {}

    def __getitem__(self, name):
        return _FakeColumn(name)

    def withColumn(self, name, expr):
        self.replaced[name] = expr
        return self


class TestSparkColumnConversions:
    def test_precision_casts_double_scalars_and_arrays(self):
        from petastorm_tpu.spark import spark_unify_float_precision
        df = _FakeDF([('d', _FakeType('double')),
                      ('f', _FakeType('float')),
                      ('ad', _FakeType('array', _FakeType('double'))),
                      ('i', _FakeType('integer'))])
        out = spark_unify_float_precision(df, 'float32')
        assert out.replaced == {'d': ('cast', 'd', 'float'),
                                'ad': ('cast', 'ad', 'array<float>')}

    def test_precision_float64_direction(self):
        from petastorm_tpu.spark import spark_unify_float_precision
        df = _FakeDF([('f', _FakeType('float'))])
        out = spark_unify_float_precision(df, 'float64')
        assert out.replaced == {'f': ('cast', 'f', 'double')}

    def test_precision_none_is_noop(self):
        from petastorm_tpu.spark import spark_unify_float_precision
        df = _FakeDF([('d', _FakeType('double'))])
        assert spark_unify_float_precision(df, None) is df
        assert df.replaced == {}

    def test_precision_invalid_dtype_rejected(self):
        from petastorm_tpu.spark import spark_unify_float_precision
        with pytest.raises(ValueError, match='float32'):
            spark_unify_float_precision(_FakeDF([]), 'int8')

    def test_vectors_flattened_via_injected_converter(self):
        from petastorm_tpu.spark import spark_vectors_to_arrays
        VectorUDT = type('VectorUDT', (), {'typeName': lambda self: 'vector'})
        df = _FakeDF([('vec', VectorUDT()), ('i', _FakeType('integer'))])
        calls = []

        def fake_vector_to_array(col, dtype):
            calls.append((col.name, dtype))
            return ('array_of', col.name, dtype)

        out = spark_vectors_to_arrays(df, 'float32',
                                      vector_to_array=fake_vector_to_array)
        assert calls == [('vec', 'float32')]
        assert out.replaced == {'vec': ('array_of', 'vec', 'float32')}

    def test_await_and_advise_uses_driver_metadata(self, tmp_path, caplog):
        # the wait list comes from spark's post-commit inputFiles() (the
        # reference's source, :700-703); the wait then covers per-object
        # read-after-write visibility lag for every indexed file
        import logging

        from petastorm_tpu.spark.spark_dataset_converter import (
            _await_and_advise,
        )
        root = tmp_path / 'ds'
        root.mkdir()
        for name in ('part-0.parquet', 'part-1.parquet', 'part-2.parquet'):
            (root / name).write_bytes(b'x' * 100)

        class _FakeRead:
            def parquet(self, url):
                class _DF:
                    @staticmethod
                    def inputFiles():
                        return ['file://%s/%s' % (root, n) for n in
                                ('part-0.parquet', 'part-1.parquet',
                                 'part-2.parquet')]
                return _DF()

        class _FakeSpark:
            read = _FakeRead()

        with caplog.at_level(logging.WARNING):
            _await_and_advise(_FakeSpark(), 'file://' + str(root))
        assert any('median' in r.message for r in caplog.records)

    def test_await_and_advise_missing_file_raises(self, tmp_path):
        from petastorm_tpu.spark.spark_dataset_converter import (
            _await_and_advise,
        )

        class _FakeSpark:
            class read:
                @staticmethod
                def parquet(url):
                    class _DF:
                        @staticmethod
                        def inputFiles():
                            return ['file://%s/gone.parquet' % tmp_path,
                                    'file://%s/gone2.parquet' % tmp_path]
                    return _DF()

        import petastorm_tpu.spark.spark_dataset_converter as mod
        orig = mod.FILE_AVAILABILITY_WAIT_TIMEOUT_S
        mod.FILE_AVAILABILITY_WAIT_TIMEOUT_S = 0.05
        try:
            with pytest.raises(RuntimeError, match='gone'):
                _await_and_advise(_FakeSpark(), 'file://' + str(tmp_path))
        finally:
            mod.FILE_AVAILABILITY_WAIT_TIMEOUT_S = orig

    def test_no_vectors_never_imports_pyspark(self):
        # without vector columns the pyspark import must not even be
        # attempted (this environment has no pyspark to import)
        from petastorm_tpu.spark import spark_vectors_to_arrays
        df = _FakeDF([('i', _FakeType('integer'))])
        assert spark_vectors_to_arrays(df, 'float32') is df


class TestTestUtil:
    def test_generate_datapoint_matches_schema(self):
        rng = np.random.RandomState(0)
        row = generate_datapoint(TestSchema, rng)
        assert set(row) == set(TestSchema.fields)
        assert row['image_png'].shape == (16, 32, 3)
        assert row['matrix'].dtype == np.float32
        # wildcard dims drawn as concrete
        assert row['matrix_nullable'].shape[1] == 14

    def test_reader_mock_rows(self):
        with ReaderMock(TestSchema, seed=1) as reader:
            rows = [next(reader) for _ in range(5)]
        assert all(hasattr(r, 'image_png') for r in rows)
        assert rows[0].image_png.shape == (16, 32, 3)

    def test_reader_mock_batched(self):
        with ReaderMock(TestSchema, seed=1, batched_output=True,
                        batch_size=4) as reader:
            batch = next(reader)
        assert batch.image_png.shape == (4, 16, 32, 3)

    def test_reader_mock_feeds_torch_loader(self):
        from petastorm_tpu.pytorch import DataLoader
        from petastorm_tpu.unischema import Unischema, UnischemaField
        schema = Unischema('S', [
            UnischemaField('x', np.float32, (3,), None, False),
        ])
        with DataLoader(ReaderMock(schema, seed=0), batch_size=4) as loader:
            batch = next(iter(loader))
        assert batch['x'].shape == (4, 3)

    def test_shuffling_analysis(self, tmp_path):
        url = 'file://' + str(tmp_path / 'shuffle_ds')
        generate_shuffle_analysis_dataset(url, num_rows=400,
                                          rowgroup_size=50)
        # single worker: pool completion order must not perturb the baseline
        corr_unshuffled = compute_correlation_distribution(
            url, num_runs=2, shuffle_row_groups=False, workers_count=1)
        corr_shuffled = compute_correlation_distribution(
            url, num_runs=2, shuffle_row_groups=True,
            shuffle_row_drop_partitions=2)
        assert corr_unshuffled > 0.95
        assert corr_shuffled < corr_unshuffled


class TestExamples:
    def test_hello_world_roundtrip(self, tmp_path):
        from examples.hello_world.generate_petastorm_dataset import (
            generate_petastorm_dataset,
        )
        from petastorm_tpu import make_reader
        url = 'file://' + str(tmp_path / 'hello')
        generate_petastorm_dataset(url, num_rows=4)
        with make_reader(url, shuffle_row_groups=False) as reader:
            rows = list(reader)
        assert len(rows) == 4
        assert rows[0].image1.shape == (128, 256, 3)
        assert rows[0].array_4d.shape[1:3] == (128, 30)

    def test_mnist_training_learns(self, tmp_path):
        from examples.mnist.jax_example import (
            generate_synthetic_mnist, train,
        )
        url = 'file://' + str(tmp_path / 'mnist')
        generate_synthetic_mnist(url, num_rows=512)
        loss = train(url, batch_size=64, steps=12)
        assert np.isfinite(loss)

    def test_imagenet_schema_roundtrip(self, tmp_path):
        from examples.imagenet.schema import ImagenetSchema
        from petastorm_tpu import make_reader
        from petastorm_tpu.etl.dataset_metadata import write_dataset
        rng = np.random.RandomState(0)
        rows = [{'noun_id': 'n%08d' % i, 'text': 'thing_%d' % i,
                 'image': rng.randint(0, 255, (32 + i, 48, 3), np.uint8)}
                for i in range(3)]
        url = 'file://' + str(tmp_path / 'imagenet')
        write_dataset(url, ImagenetSchema, rows, rowgroup_size_rows=4)
        with make_reader(url, shuffle_row_groups=False) as reader:
            got = sorted(list(reader), key=lambda r: r.noun_id)
        for row, expected in zip(got, rows):
            np.testing.assert_array_equal(row.image, expected['image'])
