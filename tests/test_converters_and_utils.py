"""DataFrame converter, test_util, and examples tests."""

import numpy as np
import pandas as pd
import pytest

from petastorm_tpu.spark import make_dataframe_converter
from petastorm_tpu.test_util import ReaderMock, generate_datapoint
from petastorm_tpu.test_util.shuffling_analysis import (
    compute_correlation_distribution, generate_shuffle_analysis_dataset,
)

from tests.test_common import TestSchema


def _df(n=100):
    return pd.DataFrame({'id': np.arange(n),
                         'value': np.arange(n) * 0.5,
                         'label': np.arange(n) % 3})


class TestDataFrameConverter:
    def test_materialize_and_read(self, tmp_path):
        converter = make_dataframe_converter(
            _df(), 'file://' + str(tmp_path / 'cache'))
        assert len(converter) == 100
        from petastorm_tpu.reader import make_batch_reader
        with make_batch_reader(converter.cache_dir_url) as reader:
            ids = [i for b in reader for i in b.id]
        assert sorted(ids) == list(range(100))
        converter.delete()

    def test_cache_hit_same_content(self, tmp_path):
        parent = 'file://' + str(tmp_path / 'cache')
        c1 = make_dataframe_converter(_df(), parent)
        c2 = make_dataframe_converter(_df(), parent)
        assert c1 is c2
        c3 = make_dataframe_converter(_df(50), parent)
        assert c3 is not c1
        c1.delete()
        c3.delete()

    def test_zero_copy_slices_not_conflated(self, tmp_path):
        import pyarrow as pa
        table = pa.table({'id': list(range(100))})
        parent = 'file://' + str(tmp_path / 'cache_s')
        c1 = make_dataframe_converter(table.slice(0, 50), parent)
        c2 = make_dataframe_converter(table.slice(50, 50), parent)
        assert c1 is not c2
        from petastorm_tpu.reader import make_batch_reader
        with make_batch_reader(c2.cache_dir_url) as reader:
            ids = sorted(i for b in reader for i in b.id)
        assert ids == list(range(50, 100))
        c1.delete()
        c2.delete()

    def test_torch_loader(self, tmp_path):
        pytest.importorskip('torch')
        converter = make_dataframe_converter(
            _df(), 'file://' + str(tmp_path / 'cache_t'))
        with converter.make_torch_dataloader(batch_size=25) as loader:
            sizes = [len(b['id']) for b in loader]
        assert sizes == [25, 25, 25, 25]
        converter.delete()

    def test_tf_dataset(self, tmp_path):
        tf = pytest.importorskip('tensorflow')
        converter = make_dataframe_converter(
            _df(), 'file://' + str(tmp_path / 'cache_tf'))
        with converter.make_tf_dataset(batch_size=20) as dataset:
            n = sum(len(el.id) for el in dataset)
        assert n == 100
        converter.delete()

    def test_jax_loader(self, tmp_path):
        converter = make_dataframe_converter(
            _df(), 'file://' + str(tmp_path / 'cache_j'))
        with converter.make_jax_loader(batch_size=20) as loader:
            n = sum(len(b['id']) for b in loader)
        assert n == 100
        converter.delete()

    def test_delete_removes_files(self, tmp_path):
        import os
        converter = make_dataframe_converter(
            _df(), 'file://' + str(tmp_path / 'cache_d'))
        path = converter.cache_dir_url[len('file://'):]
        assert os.path.exists(path)
        converter.delete()
        assert not os.path.exists(path)

    def test_spark_converter_gated(self):
        from petastorm_tpu.spark import make_spark_converter
        with pytest.raises(ImportError, match='pyspark'):
            make_spark_converter(object())


class TestTestUtil:
    def test_generate_datapoint_matches_schema(self):
        rng = np.random.RandomState(0)
        row = generate_datapoint(TestSchema, rng)
        assert set(row) == set(TestSchema.fields)
        assert row['image_png'].shape == (16, 32, 3)
        assert row['matrix'].dtype == np.float32
        # wildcard dims drawn as concrete
        assert row['matrix_nullable'].shape[1] == 14

    def test_reader_mock_rows(self):
        with ReaderMock(TestSchema, seed=1) as reader:
            rows = [next(reader) for _ in range(5)]
        assert all(hasattr(r, 'image_png') for r in rows)
        assert rows[0].image_png.shape == (16, 32, 3)

    def test_reader_mock_batched(self):
        with ReaderMock(TestSchema, seed=1, batched_output=True,
                        batch_size=4) as reader:
            batch = next(reader)
        assert batch.image_png.shape == (4, 16, 32, 3)

    def test_reader_mock_feeds_torch_loader(self):
        from petastorm_tpu.pytorch import DataLoader
        from petastorm_tpu.unischema import Unischema, UnischemaField
        schema = Unischema('S', [
            UnischemaField('x', np.float32, (3,), None, False),
        ])
        with DataLoader(ReaderMock(schema, seed=0), batch_size=4) as loader:
            batch = next(iter(loader))
        assert batch['x'].shape == (4, 3)

    def test_shuffling_analysis(self, tmp_path):
        url = 'file://' + str(tmp_path / 'shuffle_ds')
        generate_shuffle_analysis_dataset(url, num_rows=400,
                                          rowgroup_size=50)
        # single worker: pool completion order must not perturb the baseline
        corr_unshuffled = compute_correlation_distribution(
            url, num_runs=2, shuffle_row_groups=False, workers_count=1)
        corr_shuffled = compute_correlation_distribution(
            url, num_runs=2, shuffle_row_groups=True,
            shuffle_row_drop_partitions=2)
        assert corr_unshuffled > 0.95
        assert corr_shuffled < corr_unshuffled


class TestExamples:
    def test_hello_world_roundtrip(self, tmp_path):
        from examples.hello_world.generate_petastorm_dataset import (
            generate_petastorm_dataset,
        )
        from petastorm_tpu import make_reader
        url = 'file://' + str(tmp_path / 'hello')
        generate_petastorm_dataset(url, num_rows=4)
        with make_reader(url, shuffle_row_groups=False) as reader:
            rows = list(reader)
        assert len(rows) == 4
        assert rows[0].image1.shape == (128, 256, 3)
        assert rows[0].array_4d.shape[1:3] == (128, 30)

    def test_mnist_training_learns(self, tmp_path):
        from examples.mnist.jax_example import (
            generate_synthetic_mnist, train,
        )
        url = 'file://' + str(tmp_path / 'mnist')
        generate_synthetic_mnist(url, num_rows=512)
        loss = train(url, batch_size=64, steps=12)
        assert np.isfinite(loss)

    def test_imagenet_schema_roundtrip(self, tmp_path):
        from examples.imagenet.schema import ImagenetSchema
        from petastorm_tpu import make_reader
        from petastorm_tpu.etl.dataset_metadata import write_dataset
        rng = np.random.RandomState(0)
        rows = [{'noun_id': 'n%08d' % i, 'text': 'thing_%d' % i,
                 'image': rng.randint(0, 255, (32 + i, 48, 3), np.uint8)}
                for i in range(3)]
        url = 'file://' + str(tmp_path / 'imagenet')
        write_dataset(url, ImagenetSchema, rows, rowgroup_size_rows=4)
        with make_reader(url, shuffle_row_groups=False) as reader:
            got = sorted(list(reader), key=lambda r: r.noun_id)
        for row, expected in zip(got, rows):
            np.testing.assert_array_equal(row.image, expected['image'])
