"""HDFS HA resolution/failover tests with mocked configuration — no real
HDFS (reference strategy: ``petastorm/hdfs/tests/test_hdfs_namenode.py``)."""

import os
import pickle

import pytest

from petastorm_tpu.hdfs import (
    HAHdfsFilesystem, HdfsConnectError, HdfsConnector, HdfsNamenodeResolver,
    connect_hdfs_url,
)

HC = {
    'fs.defaultFS': 'hdfs://myns/',
    'dfs.ha.namenodes.myns': 'nn1,nn2',
    'dfs.namenode.rpc-address.myns.nn1': 'nn-a.example.com:8020',
    'dfs.namenode.rpc-address.myns.nn2': 'nn-b.example.com:8020',
}


class TestResolver:
    def test_nameservice_resolution(self):
        r = HdfsNamenodeResolver(HC)
        assert r.resolve_hdfs_name_service('myns') == [
            'nn-a.example.com:8020', 'nn-b.example.com:8020']

    def test_unknown_nameservice_returns_none(self):
        assert HdfsNamenodeResolver(HC).resolve_hdfs_name_service('other') is None

    def test_missing_rpc_address_raises(self):
        broken = dict(HC)
        del broken['dfs.namenode.rpc-address.myns.nn2']
        with pytest.raises(HdfsConnectError, match='rpc-address'):
            HdfsNamenodeResolver(broken).resolve_hdfs_name_service('myns')

    def test_default_service(self):
        ns, namenodes = HdfsNamenodeResolver(HC).resolve_default_hdfs_service()
        assert ns == 'myns' and len(namenodes) == 2

    def test_default_service_missing(self):
        with pytest.raises(HdfsConnectError, match='defaultFS'):
            HdfsNamenodeResolver({}).resolve_default_hdfs_service()

    def test_site_xml_parsing(self, tmp_path, monkeypatch):
        conf_dir = tmp_path / 'hadoop' / 'etc' / 'hadoop'
        conf_dir.mkdir(parents=True)
        (conf_dir / 'hdfs-site.xml').write_text(
            '<configuration>'
            '<property><name>dfs.ha.namenodes.x</name><value>a</value></property>'
            '<property><name>dfs.namenode.rpc-address.x.a</name>'
            '<value>h1:9000</value></property>'
            '</configuration>')
        (conf_dir / 'core-site.xml').write_text(
            '<configuration><property><name>fs.defaultFS</name>'
            '<value>hdfs://x/</value></property></configuration>')
        monkeypatch.setenv('HADOOP_HOME', str(tmp_path / 'hadoop'))
        for var in ('HADOOP_PREFIX', 'HADOOP_INSTALL'):
            monkeypatch.delenv(var, raising=False)
        r = HdfsNamenodeResolver()
        assert r.resolve_default_hdfs_service() == ('x', ['h1:9000'])


class _FakeFS:
    def __init__(self, host, port):
        self.host, self.port = host, port


def _connector_fn(fail_hosts):
    def connect(host, port, storage_options):
        if host in fail_hosts:
            raise ConnectionError('refused: %s' % host)
        return _FakeFS(host, port)
    return connect


class TestConnector:
    def test_first_namenode_wins(self):
        fs = HdfsConnector.connect(['a:1', 'b:2'],
                                   connect_fn=_connector_fn(set()))
        assert (fs.host, fs.port) == ('a', 1)

    def test_failover_to_second(self):
        fs = HdfsConnector.connect(['a:1', 'b:2'],
                                   connect_fn=_connector_fn({'a'}))
        assert (fs.host, fs.port) == ('b', 2)

    def test_all_fail_raises(self):
        with pytest.raises(HdfsConnectError, match='any namenode'):
            HdfsConnector.connect(['a:1', 'b:2'],
                                  connect_fn=_connector_fn({'a', 'b'}))

    def test_max_attempts_bounds_candidates(self):
        with pytest.raises(HdfsConnectError):
            HdfsConnector.connect(['a:1', 'b:2', 'c:3'],
                                  connect_fn=_connector_fn({'a', 'b'}))


class _FlakyFS:
    """Filesystem stand-in that starts raising I/O errors after
    ``healthy_calls`` successful method calls (a namenode dying mid-use)."""

    def __init__(self, host, healthy_calls=0, exc=OSError):
        self.host = host
        self._budget = healthy_calls
        self._exc = exc

    def ls(self, path):
        if self._budget <= 0:
            raise self._exc('namenode %s is down' % self.host)
        self._budget -= 1
        return ['%s:%s' % (self.host, path)]


def _flaky_connector(budgets):
    """connect_fn whose fs for each host has a limited healthy-call budget
    (None = always healthy)."""
    def connect(host, port, storage_options):
        budget = budgets.get(host)
        return _FlakyFS(host, float('inf') if budget is None else budget)
    return connect


class TestRuntimeFailover:
    """Established-connection failover (reference:
    ``petastorm/hdfs/namenode.py:146-239``): a live filesystem starts
    raising I/O errors and calls transparently move to the next namenode."""

    def test_midstream_error_fails_over(self):
        fs = HAHdfsFilesystem(['a:1', 'b:2'],
                              connect_fn=_flaky_connector({'a': 2, 'b': None}))
        assert fs.ls('/x') == ['a:/x']
        assert fs.ls('/y') == ['a:/y']
        # namenode a is now dead: the same call must answer from b
        assert fs.ls('/z') == ['b:/z']
        assert 'active=\'b:2\'' in repr(fs)

    def test_rotation_wraps_and_comes_back(self):
        # a dies; after b also dies the rotation returns to a (recovered)
        budgets = {'a': 1, 'b': 1}
        connects = []

        def connect(host, port, storage_options):
            connects.append(host)
            healthy = float('inf') if len(connects) > 3 else budgets[host]
            return _FlakyFS(host, healthy)

        fs = HAHdfsFilesystem(['a:1', 'b:2'], connect_fn=connect)
        assert fs.ls('/1') == ['a:/1']
        assert fs.ls('/2') == ['b:/2']   # a dead -> b
        assert fs.ls('/3') == ['a:/3']   # b dead -> back to a (reconnected)

    def test_file_not_found_is_not_retried(self):
        calls = []

        class _FS:
            def info(self, path):
                calls.append(path)
                raise FileNotFoundError(path)

        fs = HAHdfsFilesystem(['a:1', 'b:2'],
                              connect_fn=lambda *a: _FS())
        with pytest.raises(FileNotFoundError):
            fs.info('/missing')
        assert calls == ['/missing']  # one attempt, no failover

    def test_failover_budget_exhausted_reraises(self):
        fs = HAHdfsFilesystem(['a:1', 'b:2'], max_failovers=2,
                              connect_fn=_flaky_connector({'a': 0, 'b': 0}))
        with pytest.raises(OSError, match='down'):
            fs.ls('/x')

    def test_non_callable_attributes_pass_through(self):
        fs = HAHdfsFilesystem(['a:1'],
                              connect_fn=_flaky_connector({'a': None}))
        assert fs.host == 'a'

    def test_pickle_reconnects(self, monkeypatch):
        # the reference's HAHdfsClient is picklable via __reduce__
        # (namenode.py:231); ours reconnects from the namenode list on
        # unpickle (the custom connect_fn is intentionally not carried)
        monkeypatch.setattr(
            HdfsConnector, '_connect_one',
            staticmethod(_flaky_connector({'a': None, 'b': None})))
        fs = HAHdfsFilesystem(['a:1', 'b:2'])
        clone = pickle.loads(pickle.dumps(fs))
        assert clone.ls('/x') == ['a:/x']
        assert clone._max_failovers == fs._max_failovers

    def test_reader_completes_epoch_across_failover(self, scalar_dataset,
                                                    monkeypatch, tmp_path):
        """The VERDICT-prescribed fault injection: a reader mid-epoch on a
        connected fs that starts raising I/O errors must fail over and
        finish the epoch."""
        import fsspec

        from petastorm_tpu.reader import make_batch_reader

        local = fsspec.filesystem('file')
        root = scalar_dataset.url[len('file://'):]

        class _DyingLocal:
            """Local fs that permanently dies after `budget` open() calls."""

            def __init__(self, budget):
                self._budget = budget

            def __getattr__(self, name):
                attr = getattr(local, name)
                if name == 'open' and callable(attr):
                    def flaky_open(*args, **kwargs):
                        if self._budget <= 0:
                            raise OSError('namenode nn-a lost')
                        self._budget -= 1
                        return attr(*args, **kwargs)
                    return flaky_open
                return attr

        def connect(host, port, storage_options):
            # nn-a survives the metadata reads + first row-group, then dies
            return _DyingLocal(4) if host == 'nn-a' else local

        proxy = HAHdfsFilesystem(['nn-a:8020', 'nn-b:8020'],
                                 connect_fn=connect)
        monkeypatch.setattr(
            'petastorm_tpu.etl.dataset_metadata.'
            'get_filesystem_and_path_or_paths',
            lambda url, storage_options=None, filesystem=None: (proxy, root))

        with make_batch_reader('hdfs://myns' + root,
                               shuffle_row_groups=False) as reader:
            ids = []
            for batch in reader:
                ids.extend(batch.id.tolist())
        assert sorted(ids) == list(range(100))
        # the epoch finished on the standby namenode
        assert proxy._namenodes[proxy._active] == 'nn-b:8020'


class TestConnectUrl:
    def test_nameservice_url(self):
        fs, path = connect_hdfs_url('hdfs://myns/data/set', HC,
                                    connect_fn=_connector_fn({'nn-a.example.com'}))
        assert fs.host == 'nn-b.example.com'
        assert path == '/data/set'

    def test_direct_host_port(self):
        fs, path = connect_hdfs_url('hdfs://host:9000/x', HC,
                                    connect_fn=_connector_fn(set()))
        assert (fs.host, fs.port) == ('host', 9000)

    def test_default_fs(self):
        fs, path = connect_hdfs_url('hdfs:///x', HC,
                                    connect_fn=_connector_fn(set()))
        assert fs.host == 'nn-a.example.com'

    def test_plain_hostname_fallback(self):
        fs, _ = connect_hdfs_url('hdfs://plainhost/x', HC,
                                 connect_fn=_connector_fn(set()))
        assert (fs.host, fs.port) == ('plainhost', 8020)
