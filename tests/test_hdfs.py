"""HDFS HA resolution/failover tests with mocked configuration — no real
HDFS (reference strategy: ``petastorm/hdfs/tests/test_hdfs_namenode.py``)."""

import os

import pytest

from petastorm_tpu.hdfs import (
    HdfsConnectError, HdfsConnector, HdfsNamenodeResolver, connect_hdfs_url,
)

HC = {
    'fs.defaultFS': 'hdfs://myns/',
    'dfs.ha.namenodes.myns': 'nn1,nn2',
    'dfs.namenode.rpc-address.myns.nn1': 'nn-a.example.com:8020',
    'dfs.namenode.rpc-address.myns.nn2': 'nn-b.example.com:8020',
}


class TestResolver:
    def test_nameservice_resolution(self):
        r = HdfsNamenodeResolver(HC)
        assert r.resolve_hdfs_name_service('myns') == [
            'nn-a.example.com:8020', 'nn-b.example.com:8020']

    def test_unknown_nameservice_returns_none(self):
        assert HdfsNamenodeResolver(HC).resolve_hdfs_name_service('other') is None

    def test_missing_rpc_address_raises(self):
        broken = dict(HC)
        del broken['dfs.namenode.rpc-address.myns.nn2']
        with pytest.raises(HdfsConnectError, match='rpc-address'):
            HdfsNamenodeResolver(broken).resolve_hdfs_name_service('myns')

    def test_default_service(self):
        ns, namenodes = HdfsNamenodeResolver(HC).resolve_default_hdfs_service()
        assert ns == 'myns' and len(namenodes) == 2

    def test_default_service_missing(self):
        with pytest.raises(HdfsConnectError, match='defaultFS'):
            HdfsNamenodeResolver({}).resolve_default_hdfs_service()

    def test_site_xml_parsing(self, tmp_path, monkeypatch):
        conf_dir = tmp_path / 'hadoop' / 'etc' / 'hadoop'
        conf_dir.mkdir(parents=True)
        (conf_dir / 'hdfs-site.xml').write_text(
            '<configuration>'
            '<property><name>dfs.ha.namenodes.x</name><value>a</value></property>'
            '<property><name>dfs.namenode.rpc-address.x.a</name>'
            '<value>h1:9000</value></property>'
            '</configuration>')
        (conf_dir / 'core-site.xml').write_text(
            '<configuration><property><name>fs.defaultFS</name>'
            '<value>hdfs://x/</value></property></configuration>')
        monkeypatch.setenv('HADOOP_HOME', str(tmp_path / 'hadoop'))
        for var in ('HADOOP_PREFIX', 'HADOOP_INSTALL'):
            monkeypatch.delenv(var, raising=False)
        r = HdfsNamenodeResolver()
        assert r.resolve_default_hdfs_service() == ('x', ['h1:9000'])


class _FakeFS:
    def __init__(self, host, port):
        self.host, self.port = host, port


def _connector_fn(fail_hosts):
    def connect(host, port, storage_options):
        if host in fail_hosts:
            raise ConnectionError('refused: %s' % host)
        return _FakeFS(host, port)
    return connect


class TestConnector:
    def test_first_namenode_wins(self):
        fs = HdfsConnector.connect(['a:1', 'b:2'],
                                   connect_fn=_connector_fn(set()))
        assert (fs.host, fs.port) == ('a', 1)

    def test_failover_to_second(self):
        fs = HdfsConnector.connect(['a:1', 'b:2'],
                                   connect_fn=_connector_fn({'a'}))
        assert (fs.host, fs.port) == ('b', 2)

    def test_all_fail_raises(self):
        with pytest.raises(HdfsConnectError, match='any namenode'):
            HdfsConnector.connect(['a:1', 'b:2'],
                                  connect_fn=_connector_fn({'a', 'b'}))

    def test_max_attempts_bounds_candidates(self):
        with pytest.raises(HdfsConnectError):
            HdfsConnector.connect(['a:1', 'b:2', 'c:3'],
                                  connect_fn=_connector_fn({'a', 'b'}))


class TestConnectUrl:
    def test_nameservice_url(self):
        fs, path = connect_hdfs_url('hdfs://myns/data/set', HC,
                                    connect_fn=_connector_fn({'nn-a.example.com'}))
        assert fs.host == 'nn-b.example.com'
        assert path == '/data/set'

    def test_direct_host_port(self):
        fs, path = connect_hdfs_url('hdfs://host:9000/x', HC,
                                    connect_fn=_connector_fn(set()))
        assert (fs.host, fs.port) == ('host', 9000)

    def test_default_fs(self):
        fs, path = connect_hdfs_url('hdfs:///x', HC,
                                    connect_fn=_connector_fn(set()))
        assert fs.host == 'nn-a.example.com'

    def test_plain_hostname_fallback(self):
        fs, _ = connect_hdfs_url('hdfs://plainhost/x', HC,
                                 connect_fn=_connector_fn(set()))
        assert (fs.host, fs.port) == ('plainhost', 8020)
