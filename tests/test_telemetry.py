"""Telemetry unit tests: registry semantics, delta merge, exporter
round-trips (JSONL, Prometheus), stall-window classification, and the
overhead guard the ISSUE's satellite tasks require."""

import io
import json
import re
import time

import pytest

from petastorm_tpu import telemetry as T
from petastorm_tpu.telemetry.registry import MetricsRegistry, metric_key
from petastorm_tpu.telemetry.spans import _NOOP_SPAN
from petastorm_tpu.telemetry.stall import classify_window


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    T.reset_for_tests()
    yield
    T.reset_for_tests()


# -- registry ----------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter('items_total')
    c.inc()
    c.inc(2.5)
    assert reg.counter_value('items_total') == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)

    g = reg.gauge('depth')
    g.set(7)
    g.inc()
    g.dec(3)
    assert reg.gauge_value('depth') == 5

    h = reg.histogram('lat', buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    state = h.state()
    assert state['counts'] == [1, 1, 1]  # one per bucket + overflow
    assert state['count'] == 3
    assert state['sum'] == pytest.approx(5.55)


def test_labels_define_identity():
    reg = MetricsRegistry()
    a = reg.counter('x_total', stage='io')
    b = reg.counter('x_total', stage='decode')
    same = reg.counter('x_total', stage='io')
    assert a is same and a is not b
    a.inc()
    assert reg.counter_value('x_total', stage='io') == 1
    assert reg.counter_value('x_total', stage='decode') == 0
    # label order must not split the series
    assert metric_key('x', {'b': 1, 'a': 2}) == metric_key('x', {'a': 2,
                                                                 'b': 1})


def test_histogram_rejects_bad_buckets():
    with pytest.raises(ValueError):
        MetricsRegistry().histogram('h', buckets=(1.0, 1.0))
    with pytest.raises(ValueError):
        MetricsRegistry().histogram('h2', buckets=(2.0, 1.0))


def test_collect_delta_and_merge():
    worker = MetricsRegistry()
    consumer = MetricsRegistry()
    worker.counter('n_total').inc(3)
    worker.gauge('alive').set(1)
    worker.histogram('d', buckets=(0.1, 1.0)).observe(0.5)

    delta = worker.collect_delta()
    consumer.merge_delta(delta)
    assert consumer.counter_value('n_total') == 3
    assert consumer.gauge_value('alive') == 1
    assert consumer._histograms[metric_key('d')].count == 1

    # nothing changed since the flush → no payload to ship
    assert worker.collect_delta() is None

    # increments accumulate, never replace
    worker.counter('n_total').inc(2)
    worker.histogram('d', buckets=(0.1, 1.0)).observe(0.05)
    consumer.merge_delta(worker.collect_delta())
    assert consumer.counter_value('n_total') == 5
    merged = consumer._histograms[metric_key('d')].state()
    assert merged['count'] == 2
    assert merged['counts'] == [1, 1, 0]


def test_merge_worker_delta_feeds_global_attributor():
    worker = MetricsRegistry()
    worker.counter(T.STALL_PRODUCER_WAIT).inc(0.8)
    T.merge_worker_delta(worker.collect_delta())
    producer, consumer = T.get_attributor().totals()
    assert producer == pytest.approx(0.8)
    assert consumer == 0.0
    assert T.get_registry().counter_value(T.STALL_PRODUCER_WAIT) == \
        pytest.approx(0.8)


def test_load_delta_frame_rejects_non_delta_payloads():
    """The service dispatcher relies on this strictness to tell a metrics
    frame from a RESULT frame sent by a pre-telemetry worker build: only
    an exact {counters, gauges, histograms} dict (all dicts, at least one
    non-empty) may be claimed as a delta — anything else must fall
    through as data."""
    import dill
    reg = MetricsRegistry()
    reg.counter('a_total').inc()
    good = dill.dumps(reg.collect_delta())
    assert T.load_delta_frame(good) is not None
    for payload in (
        b'',                                           # "nothing changed"
        b'\x00not-a-pickle',
        dill.dumps([1, 2, 3]),                         # non-dict result
        dill.dumps({'window': {}, 'item_index': 3}),   # ngram result dict
        dill.dumps({'counters': {}, 'gauges': {},
                    'histograms': {}}),                # empty: not a delta
        dill.dumps({'counters': {}, 'gauges': {},
                    'histograms': {}, 'extra': 1}),    # foreign key
        dill.dumps({'counters': [1]}),                 # wrong field type
    ):
        assert T.load_delta_frame(payload) is None, payload[:40]


# -- exporters ---------------------------------------------------------------


def test_jsonl_roundtrip_equals_registry_state():
    reg = MetricsRegistry()
    reg.counter('a_total', stage='io').inc(2)
    reg.gauge('g').set(1.5)
    reg.histogram('h_seconds', buckets=(0.01, 0.1)).observe(0.02)
    buf = io.StringIO()
    T.write_jsonl_snapshot(buf, reg, extra={'run': 'r1'})
    (line,) = buf.getvalue().splitlines()
    parsed = json.loads(line)
    snap = reg.snapshot()
    assert parsed['counters'] == snap['counters']
    assert parsed['gauges'] == snap['gauges']
    assert parsed['histograms'] == snap['histograms']
    assert parsed['run'] == 'r1'
    assert 'ts' in parsed


def test_jsonl_file_append_and_parse(tmp_path):
    reg = MetricsRegistry()
    reg.counter('a_total').inc()
    path = str(tmp_path / 'metrics.jsonl')
    T.write_jsonl_snapshot(path, reg)
    reg.counter('a_total').inc()
    T.write_jsonl_snapshot(path, reg)
    first, second = T.read_jsonl_snapshots(path)
    assert first['counters']['a_total'] == 1
    assert second['counters'] == reg.snapshot()['counters']


def test_prometheus_text_line_by_line():
    reg = MetricsRegistry()
    reg.counter('petastorm_items_total', stage='io').inc(2)
    reg.gauge('petastorm_depth').set(4)
    reg.histogram('petastorm_lat_seconds', buckets=(0.1, 1.0)).observe(0.5)
    reg.histogram('petastorm_lat_seconds', buckets=(0.1, 1.0)).observe(0.05)
    text = T.prometheus_text(reg)
    lines = text.strip().splitlines()

    # exactly one TYPE line per family, with the right type
    assert lines.count('# TYPE petastorm_items_total counter') == 1
    assert lines.count('# TYPE petastorm_depth gauge') == 1
    assert lines.count('# TYPE petastorm_lat_seconds histogram') == 1
    # every non-comment line is "<series> <number>"
    sample_re = re.compile(r'^[A-Za-z_:][\w:]*(\{[^{}]*\})? \S+$')
    for line in lines:
        if not line.startswith('#'):
            assert sample_re.match(line), line

    assert 'petastorm_items_total{stage="io"} 2' in lines
    assert 'petastorm_depth 4' in lines
    # cumulative buckets, ascending le through +Inf, consistent count/sum
    buckets = [ln for ln in lines
               if ln.startswith('petastorm_lat_seconds_bucket')]
    counts = [int(ln.rsplit(' ', 1)[1]) for ln in buckets]
    assert counts == sorted(counts), 'bucket counts must be cumulative'
    assert buckets[-1] == 'petastorm_lat_seconds_bucket{le="+Inf"} 2'
    assert 'petastorm_lat_seconds_count 2' in lines
    assert any(ln.startswith('petastorm_lat_seconds_sum ')
               for ln in lines)


def test_prometheus_label_escaping():
    reg = MetricsRegistry()
    reg.counter('esc_total', path='a"b\\c\nd').inc()
    text = T.prometheus_text(reg)
    assert 'esc_total{path="a\\"b\\\\c\\nd"} 1' in text
    # the raw newline must never split the sample across exposition lines
    esc_lines = [ln for ln in text.splitlines() if 'esc_total' in ln
                 and not ln.startswith('#')]
    assert len(esc_lines) == 1 and esc_lines[0].endswith(' 1')


# -- stall attribution -------------------------------------------------------


def test_classify_window_thresholds():
    # consumer starving → producer-bound; producer blocked → consumer-bound
    assert classify_window(0.0, 0.4, 0.5) == T.PRODUCER_BOUND
    assert classify_window(0.4, 0.0, 0.5) == T.CONSUMER_BOUND
    assert classify_window(0.2, 0.2, 0.5) == T.BALANCED
    # too quiet to call (< 2% of the window)
    assert classify_window(0.0, 0.005, 0.5) == T.BALANCED


def test_attributor_windows_roll_and_classify():
    att = T.StallAttributor(window_s=0.05)
    att.note_consumer_wait(0.04)
    time.sleep(0.12)
    att.note_consumer_wait(0.04)  # closes the first window
    windows = att.windows()
    assert windows, 'expected at least one window'
    assert windows[0]['verdict'] == T.PRODUCER_BOUND
    assert att.verdict() == T.PRODUCER_BOUND
    producer, consumer = att.totals()
    assert producer == 0.0
    assert consumer == pytest.approx(0.08)
    att.reset()
    assert att.windows() == []
    assert att.totals() == (0.0, 0.0)


def test_attributor_ignores_nonpositive_notes():
    att = T.StallAttributor(window_s=0.05)
    att.note_producer_wait(0.0)
    att.note_consumer_wait(-1.0)
    assert att.totals() == (0.0, 0.0)


# -- env gating + overhead guard --------------------------------------------


def test_disabled_spans_are_noops(monkeypatch):
    monkeypatch.setenv('PETASTORM_TPU_METRICS', '0')
    T.refresh_enabled()
    try:
        assert T.metrics_disabled()
        s1 = T.span('decode')
        s2 = T.span('io')
        assert s1 is s2 is _NOOP_SPAN, 'disabled spans must be one no-op'
        with s1:
            pass
        # the note helpers silence too
        T.note_consumer_wait(1.0)
        T.note_producer_wait(1.0)
        assert T.get_registry().snapshot() == {'counters': {}, 'gauges': {},
                                               'histograms': {}}
        assert T.get_attributor().totals() == (0.0, 0.0)
    finally:
        monkeypatch.delenv('PETASTORM_TPU_METRICS')
        T.refresh_enabled()
    assert not T.metrics_disabled()
    assert T.span('decode') is not _NOOP_SPAN


def test_overhead_budget():
    """Counter inc + span enter/exit stay under a per-call budget, enabled
    AND disabled (disabled must be far cheaper). Budgets are deliberately
    loose for shared CI boxes — the guard catches order-of-magnitude
    regressions (an accidental syscall/allocation on the hot path), not
    single-microsecond noise."""
    n = 20000
    counter = T.get_registry().counter('hot_total')
    start = time.perf_counter()
    for _ in range(n):
        counter.inc()
    counter_per_call = (time.perf_counter() - start) / n

    start = time.perf_counter()
    for _ in range(n):
        with T.span('decode'):
            pass
    span_per_call = (time.perf_counter() - start) / n

    assert counter_per_call < 25e-6, counter_per_call
    assert span_per_call < 50e-6, span_per_call


def test_overhead_budget_disabled(monkeypatch):
    monkeypatch.setenv('PETASTORM_TPU_METRICS', 'off')
    T.refresh_enabled()
    try:
        n = 20000
        start = time.perf_counter()
        for _ in range(n):
            with T.span('decode'):
                pass
        per_call = (time.perf_counter() - start) / n
        assert per_call < 10e-6, per_call
    finally:
        monkeypatch.delenv('PETASTORM_TPU_METRICS')
        T.refresh_enabled()
