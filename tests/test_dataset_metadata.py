"""ETL/metadata tests (parity model: petastorm/tests/test_dataset_metadata.py,
test_metadata_read.py)."""

import json
import pickle

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.errors import MetadataError
from petastorm_tpu.etl.dataset_metadata import (
    LEGACY_UNISCHEMA_KEY, ROW_GROUPS_PER_FILE_KEY, UNISCHEMA_KEY,
    DatasetWriter, ParquetDatasetInfo, add_to_dataset_metadata, get_schema,
    get_schema_from_dataset_url, infer_or_load_unischema, load_row_groups,
    materialize_dataset, write_dataset,
)
from petastorm_tpu.unischema import Unischema, UnischemaField
from petastorm_tpu.codecs import ScalarCodec, NdarrayCodec


def _tiny_schema():
    return Unischema('Tiny', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('vec', np.float32, (3,), NdarrayCodec(), False),
    ])


def _tiny_rows(n):
    return [{'id': i, 'vec': np.arange(3, dtype=np.float32) + i} for i in range(n)]


def test_write_dataset_creates_metadata_and_rowgroups(tmp_path):
    url = 'file://' + str(tmp_path / 'ds')
    write_dataset(url, _tiny_schema(), _tiny_rows(25), rowgroup_size_rows=10)
    info = ParquetDatasetInfo(url)
    assert info.common_metadata is not None
    meta = info.common_metadata.metadata
    assert UNISCHEMA_KEY in meta
    assert ROW_GROUPS_PER_FILE_KEY in meta
    pieces = load_row_groups(info)
    assert len(pieces) == 3  # 10 + 10 + 5
    schema = get_schema(info)
    assert list(schema.fields) == ['id', 'vec']


def test_write_dataset_multiple_files(tmp_path):
    url = 'file://' + str(tmp_path / 'ds')
    write_dataset(url, _tiny_schema(), _tiny_rows(40), rowgroup_size_rows=5, num_files=4)
    info = ParquetDatasetInfo(url)
    assert len(info.file_paths) == 4
    assert len(load_row_groups(info)) == 8


def test_parallel_encode_write_matches_serial(tmp_path):
    """workers_count>1 thread-pools the codec encode; the stored dataset is
    row-for-row identical to a serial write (order preserved)."""
    from petastorm_tpu.codecs import CompressedImageCodec
    schema = Unischema('Par', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('vec', np.float32, (4,), NdarrayCodec(), False),
        UnischemaField('img', np.uint8, (16, 16, 3),
                       CompressedImageCodec('png'), False),
    ])
    rng = np.random.RandomState(3)
    rows = [{'id': i, 'vec': rng.rand(4).astype(np.float32),
             'img': rng.randint(0, 255, (16, 16, 3), np.uint8)}
            for i in range(30)]
    serial_url = 'file://' + str(tmp_path / 'serial')
    par_url = 'file://' + str(tmp_path / 'par')
    write_dataset(serial_url, schema, rows, rowgroup_size_rows=7)
    write_dataset(par_url, schema, rows, rowgroup_size_rows=7,
                  workers_count=4)
    import pyarrow.parquet as pq

    def read_all(url):
        info = ParquetDatasetInfo(url)
        return pa.concat_tables(
            [pq.read_table(f) for f in sorted(info.file_paths)])

    serial_table, par_table = read_all(serial_url), read_all(par_url)
    assert serial_table.equals(par_table)
    assert len(load_row_groups(ParquetDatasetInfo(par_url))) == 5  # 7*4+2


def test_parallel_encode_streams_generator_input(tmp_path, monkeypatch):
    """The parallel path must not materialize the whole input: with a
    generator feed, rows PRODUCED may run ahead of rows ENCODED only by
    the documented in-flight window (workers_count + 2 chunks of 64, plus
    the chunk being assembled) — a list(row_dicts) regression would
    produce all 600 before the first encode and fail the bound."""
    import threading as _threading
    import petastorm_tpu.etl.dataset_metadata as dm

    counters = {'produced': 0, 'encoded': 0, 'max_ahead': 0}
    lock = _threading.Lock()
    real_encode = dm.dict_to_encoded_row

    def tracking_encode(schema, row):
        out = real_encode(schema, row)
        with lock:
            counters['encoded'] += 1
            counters['max_ahead'] = max(
                counters['max_ahead'],
                counters['produced'] - counters['encoded'])
        return out

    monkeypatch.setattr(dm, 'dict_to_encoded_row', tracking_encode)

    def rows():
        for i in range(600):
            with lock:
                counters['produced'] += 1
            yield {'id': i, 'vec': np.arange(3, dtype=np.float32) + i}

    url = 'file://' + str(tmp_path / 'ds')
    schema = _tiny_schema()
    with materialize_dataset(url, schema):
        with DatasetWriter(url, schema, rowgroup_size_rows=50,
                           workers_count=4) as w:
            w.write_row_dicts(rows())
    assert len(load_row_groups(ParquetDatasetInfo(url))) == 12
    assert counters['encoded'] == 600
    assert counters['max_ahead'] <= (4 + 2 + 1) * 64, counters


def test_parallel_encode_propagates_errors(tmp_path):
    url = 'file://' + str(tmp_path / 'ds')
    rows = _tiny_rows(10)
    rows[6]['vec'] = np.zeros(5, np.float32)  # wrong shape
    with pytest.raises(ValueError):
        write_dataset(url, _tiny_schema(), rows, workers_count=4)


def test_partitioned_write(tmp_path):
    schema = Unischema('P', [
        UnischemaField('part', np.str_, (), ScalarCodec(pa.string()), False),
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
    ])
    url = 'file://' + str(tmp_path / 'ds')
    rows = [{'part': 'a' if i < 5 else 'b', 'id': i} for i in range(10)]
    write_dataset(url, schema, rows, rowgroup_size_rows=100, partition_by=['part'])
    info = ParquetDatasetInfo(url)
    assert len(info.file_paths) == 2
    assert info.partition_keys == ['part']
    pieces = load_row_groups(info)
    parts = {p.partition_values['part'] for p in pieces}
    assert parts == {'a', 'b'}


def test_load_row_groups_footer_scan_fallback(tmp_path):
    """A dataset without _common_metadata must still enumerate row-groups."""
    url = 'file://' + str(tmp_path / 'ds')
    write_dataset(url, _tiny_schema(), _tiny_rows(20), rowgroup_size_rows=10)
    # Remove the footer file.
    (tmp_path / 'ds' / '_common_metadata').unlink()
    info = ParquetDatasetInfo(url)
    assert info.common_metadata is None
    assert len(load_row_groups(info)) == 2


def test_infer_schema_from_plain_parquet(tmp_path, scalar_dataset):
    info = ParquetDatasetInfo(scalar_dataset.url)
    with pytest.raises(MetadataError):
        get_schema(info)
    schema = infer_or_load_unischema(info)
    assert 'id' in schema.fields
    assert schema.int_fixed_size_list.shape == (None,)


def test_get_schema_from_dataset_url(synthetic_dataset):
    schema = get_schema_from_dataset_url(synthetic_dataset.url)
    assert 'image_png' in schema.fields
    assert schema.image_png.shape == (16, 32, 3)


def test_add_to_dataset_metadata_preserves_existing(tmp_path):
    url = 'file://' + str(tmp_path / 'ds')
    write_dataset(url, _tiny_schema(), _tiny_rows(5))
    info = ParquetDatasetInfo(url)
    add_to_dataset_metadata(info, b'my.custom.key', b'hello')
    info2 = ParquetDatasetInfo(url)
    meta = info2.common_metadata.metadata
    assert meta[b'my.custom.key'] == b'hello'
    assert UNISCHEMA_KEY in meta


def test_materialize_dataset_context_manager(tmp_path):
    url = 'file://' + str(tmp_path / 'ds')
    schema = _tiny_schema()
    with materialize_dataset(url, schema):
        with DatasetWriter(url, schema, rowgroup_size_rows=4) as w:
            w.write_row_dicts(_tiny_rows(9))
    info = ParquetDatasetInfo(url)
    assert len(load_row_groups(info)) == 3
    assert get_schema(info) is not None


def test_materialize_skips_footer_on_body_failure(tmp_path):
    url = 'file://' + str(tmp_path / 'ds')
    with pytest.raises(RuntimeError):
        with materialize_dataset(url, _tiny_schema()):
            raise RuntimeError('write failed')


def test_legacy_pickled_schema_depickling(tmp_path):
    """A footer with a reference-style pickled schema must decode.

    We synthesize the pickle with stand-in classes whose module/qualname match
    the reference's (no petastorm import needed).
    """
    from tests.legacy_pickle_helper import make_reference_style_pickle
    blob = make_reference_style_pickle()
    from petastorm_tpu.etl.legacy import depickle_legacy_unischema
    schema = depickle_legacy_unischema(blob)
    assert list(schema.fields) == ['id', 'image']
    assert schema.id.numpy_dtype is np.int32
    from petastorm_tpu.codecs import CompressedImageCodec, ScalarCodec as TpuScalarCodec
    assert isinstance(schema.image.codec, CompressedImageCodec)
    assert schema.image.codec.image_codec == 'png'
    assert isinstance(schema.id.codec, TpuScalarCodec)
    assert schema.id.codec.arrow_type(None) == pa.int32()


def test_legacy_depickler_refuses_malicious_pickle():
    evil = pickle.dumps(print)  # builtins.print is not allowlisted
    from petastorm_tpu.etl.legacy import depickle_legacy_unischema
    with pytest.raises(pickle.UnpicklingError):
        depickle_legacy_unischema(evil)


def test_read_legacy_footer_keys(tmp_path):
    """Datasets whose footer uses the reference's key names are readable."""
    url = 'file://' + str(tmp_path / 'ds')
    write_dataset(url, _tiny_schema(), _tiny_rows(10), rowgroup_size_rows=5)
    info = ParquetDatasetInfo(url)
    meta = dict(info.common_metadata.metadata)
    counts = meta.pop(ROW_GROUPS_PER_FILE_KEY)
    meta.pop(UNISCHEMA_KEY)
    # Rewrite footer with ONLY legacy-style count key.
    base_schema = info.common_metadata.schema.to_arrow_schema().with_metadata(
        {b'dataset-toolkit.num_row_groups_per_file.v1': counts})
    import pyarrow.parquet as pq
    pq.write_metadata(base_schema, str(tmp_path / 'ds' / '_common_metadata'))
    info = ParquetDatasetInfo(url)
    assert len(load_row_groups(info)) == 2


def test_dataset_info_pickle_resets_lazy_sentinels(tmp_path):
    # Pickle does not preserve identity of the module-level _UNSET sentinel;
    # __setstate__ must re-point the lazy slots so common_metadata re-reads
    # instead of returning a meaningless unpickled sentinel (ADVICE r1).
    import pickle
    url = 'file://' + str(tmp_path / 'ds')
    write_dataset(url, _tiny_schema(), _tiny_rows(6), rowgroup_size_rows=3)
    info = pickle.loads(pickle.dumps(ParquetDatasetInfo(url)))
    meta = info.common_metadata
    assert meta is not None and UNISCHEMA_KEY in dict(meta.metadata)
    assert len(load_row_groups(info)) == 2


def test_auto_compression_per_column(tmp_path):
    # jpeg/npz cells are stored UNCOMPRESSED (snappy would burn CPU for ~0%
    # size win on both write and every read); plain columns stay SNAPPY
    import glob
    import pyarrow.parquet as pq
    from petastorm_tpu.codecs import (
        CompressedImageCodec, CompressedNdarrayCodec, ScalarCodec,
    )
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('C', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('image', np.uint8, (8, 8, 3),
                       CompressedImageCodec('jpeg'), False),
        UnischemaField('blob', np.float32, (4,),
                       CompressedNdarrayCodec(), False),
        UnischemaField('vec', np.float32, (4,), None, False),
    ])
    rng = np.random.RandomState(0)
    url = 'file://' + str(tmp_path / 'ds')
    write_dataset(url, schema, [
        {'id': i, 'image': rng.randint(0, 255, (8, 8, 3), np.uint8),
         'blob': rng.rand(4).astype(np.float32),
         'vec': rng.rand(4).astype(np.float32)} for i in range(6)],
        rowgroup_size_rows=3)
    meta = pq.ParquetFile(
        glob.glob(str(tmp_path / 'ds' / '*.parquet'))[0]).metadata
    comp = {meta.row_group(0).column(i).path_in_schema:
            meta.row_group(0).column(i).compression
            for i in range(meta.row_group(0).num_columns)}
    assert comp['image'] == 'UNCOMPRESSED'
    assert comp['blob'] == 'UNCOMPRESSED'
    assert comp['id'] == 'SNAPPY'
    # list-typed columns are addressed by their parquet leaf path
    assert comp['vec.list.element'] == 'SNAPPY'


def test_explicit_compression_passthrough(tmp_path):
    import glob
    import pyarrow.parquet as pq
    from petastorm_tpu.codecs import ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import DatasetWriter
    from petastorm_tpu.unischema import Unischema, UnischemaField
    schema = Unischema('C', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
    ])
    url = 'file://' + str(tmp_path / 'ds')
    with DatasetWriter(url, schema, rowgroup_size_rows=4,
                       compression='NONE') as writer:
        writer.write_row_dicts([{'id': i} for i in range(4)])
    meta = pq.ParquetFile(
        glob.glob(str(tmp_path / 'ds' / '*.parquet'))[0]).metadata
    assert meta.row_group(0).column(0).compression == 'UNCOMPRESSED'


def test_count_rows_footers_only(synthetic_dataset, scalar_dataset):
    from petastorm_tpu.etl.dataset_metadata import (
        ParquetDatasetInfo, count_rows,
    )
    assert count_rows(synthetic_dataset.url) == 100
    assert count_rows(scalar_dataset.url) == 100
    # accepts a pre-resolved info too
    assert count_rows(ParquetDatasetInfo(synthetic_dataset.url)) == 100
