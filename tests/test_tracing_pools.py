"""Trace-context propagation across the pool flavors — the ISSUE's
acceptance path: the SAME trace id observed worker-side (thread AND
process AND service pools) and consumer-side, dispatcher lifecycle
instants for re-ventilated items, and the end-to-end export of a
``make_jax_loader`` run over the service pool with a worker SIGKILLed
mid-epoch.

Service tests spawn real localhost worker-server subprocesses and are
marked ``service`` like tests/test_service.py (tier-1, tight internal
timeouts)."""

import collections
import json
import os
import signal
import time

import pytest

from petastorm_tpu import telemetry as T
from petastorm_tpu.telemetry import tracing
from petastorm_tpu.workers import EmptyResultError
from petastorm_tpu.workers.thread_pool import ThreadPool
from petastorm_tpu.workers.ventilator import ConcurrentVentilator
from tests.stub_workers import TracingProbeWorker

_RESULT_TIMEOUT_S = 60

# same tight-but-safe timing as tests/test_service.py's kill tests
_FAST = dict(heartbeat_interval_s=0.15, liveness_timeout_s=0.75,
             connect_timeout_s=60, no_workers_timeout_s=20)


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    T.reset_for_tests()
    yield
    T.reset_for_tests()


@pytest.fixture
def traced(monkeypatch):
    monkeypatch.setenv('PETASTORM_TPU_TRACE', '1')
    T.refresh()
    yield


def _drain(pool):
    out = []
    while True:
        try:
            out.append(pool.get_results(timeout=_RESULT_TIMEOUT_S))
        except EmptyResultError:
            return out


def _roundtrip_through(pool, items=6):
    """Ventilate ``items`` probe items; return {item_index: worker-side
    trace id} as published by the workers."""
    ventilator = ConcurrentVentilator(
        pool.ventilate, [{'item_index': i} for i in range(items)],
        iterations=1)
    pool.start(TracingProbeWorker, ventilator=ventilator)
    try:
        results = dict(_drain(pool))
        assert sorted(results) == list(range(items))
        return results
    finally:
        pool.stop()
        pool.join()


def _assert_worker_ids_match_minted(results):
    for item_index, worker_side_id in results.items():
        minted = tracing.ctx_for(item_index, epoch=0)
        assert minted is not None
        assert worker_side_id == minted.trace_id, \
            'item %d: worker saw %r, consumer minted %r' \
            % (item_index, worker_side_id, minted.trace_id)


def test_thread_pool_roundtrip(traced):
    results = _roundtrip_through(ThreadPool(2, results_queue_size=10))
    _assert_worker_ids_match_minted(results)
    events = T.get_recorder().snapshot()
    names = collections.Counter(e['name'] for e in events)
    assert names['ventilate'] == 6
    assert names['attempt'] == 6
    assert names['decode'] == 6
    # worker tracks carry the thread-worker label
    assert any(str(e['tid']).startswith('thread-') for e in events)


def test_thread_pool_sampling_strides(traced, monkeypatch):
    monkeypatch.setenv('PETASTORM_TPU_TRACE_SAMPLE', '1/2')
    T.refresh()
    results = _roundtrip_through(ThreadPool(2, results_queue_size=10))
    assert {i for i, tid in results.items() if tid is not None} == {0, 2, 4}
    events = T.get_recorder().snapshot()
    assert collections.Counter(e['name'] for e in events)['attempt'] == 3


def test_untraced_roundtrip_records_nothing():
    results = _roundtrip_through(ThreadPool(2, results_queue_size=10))
    assert set(results.values()) == {None}
    assert len(T.get_recorder()) == 0


def test_process_pool_roundtrip(traced):
    """Worker-side events cross the ZMQ marker channel: the trace id
    minted here must be ACTIVE inside the spawned decode process, and its
    events must land back in this process's recorder."""
    from petastorm_tpu.workers.process_pool import ProcessPool
    results = _roundtrip_through(ProcessPool(1, results_queue_size=10))
    _assert_worker_ids_match_minted(results)
    events = T.get_recorder().snapshot()
    names = collections.Counter(e['name'] for e in events)
    assert names['attempt'] == 6 and names['decode'] == 6
    worker_pids = {e['pid'] for e in events if e['name'] == 'attempt'}
    assert worker_pids and os.getpid() not in worker_pids, \
        'attempt events must carry the decode PROCESS pid'


@pytest.mark.service
def test_service_pool_roundtrip(traced):
    """The full tcp:// path: context rides the WORK frame, events ride
    the DONE's delta frame, and the dispatcher stamps dispatch/done
    instants keyed by the same trace id."""
    from petastorm_tpu.service import ServicePool
    pool = ServicePool(spawn_local_workers=1, heartbeat_interval_s=0.2,
                       connect_timeout_s=60)
    results = _roundtrip_through(pool)
    _assert_worker_ids_match_minted(results)
    events = T.get_recorder().snapshot()
    names = collections.Counter(e['name'] for e in events)
    assert names['attempt'] == 6 and names['decode'] == 6
    assert names['dispatch'] == 6 and names['done'] == 6
    done_ids = {e['args']['trace_id'] for e in events
                if e['name'] == 'done'}
    attempt_ids = {e['args']['trace_id'] for e in events
                   if e['name'] == 'attempt'}
    assert done_ids == attempt_ids


def _slow_batch_identity(df):
    # per-row-group brake so a killed worker server reliably owns
    # in-flight row-groups when the SIGKILL lands
    time.sleep(0.05)
    return df


@pytest.fixture
def many_rowgroup_scalar_dataset(tmp_path):
    from tests.test_common import create_test_scalar_dataset
    url = 'file://' + str(tmp_path / 'dataset')
    create_test_scalar_dataset(url, num_rows=100, num_files=10)
    return url


@pytest.mark.service
def test_jax_loader_service_trace_with_worker_kill(
        traced, many_rowgroup_scalar_dataset, tmp_path):
    """ISSUE acceptance, end to end: a make_jax_loader run over the
    service pool with one worker SIGKILLed mid-epoch exports a valid
    Chrome trace where (a) per-worker tracks are present, (b) the
    re-ventilated item shows BOTH dispatch attempts and exactly ONE
    completion, and (c) consumer-side queue_wait events share the trace
    ids minted at ventilation."""
    from petastorm_tpu.jax import make_jax_loader
    from petastorm_tpu.service import ServicePool
    from petastorm_tpu.transform import TransformSpec

    pool = ServicePool(spawn_local_workers=2, **_FAST)
    loader = make_jax_loader(
        many_rowgroup_scalar_dataset, batch_size=10, num_epochs=1,
        fields=['^id$', '^float64$'], shuffle_row_groups=False,
        last_batch='short', reader_pool_type=pool,
        transform_spec=TransformSpec(_slow_batch_identity))
    rows = 0
    try:
        first = True
        for batch in loader:
            rows += int(next(iter(batch.values())).shape[0])
            if first:
                os.kill(pool._local_procs[0].pid, signal.SIGKILL)
                first = False
        path = str(tmp_path / 'service_kill.trace.json')
        assert loader.dump_trace(path) > 0
    finally:
        loader.stop()
    assert rows == 100, 'exactly-once delivery must survive the kill'

    with open(path) as f:
        doc = json.load(f)
    events = doc['traceEvents']
    data = [e for e in events if e['ph'] != 'M']
    # (schema) every event well-formed
    for e in data:
        assert isinstance(e['name'], str) and e['ph'] in ('X', 'i')
        assert isinstance(e['pid'], int) and isinstance(e['tid'], int)
        assert isinstance(e['ts'], (int, float))
        assert 'trace_id' in e['args']
    # (tracks) per-worker tracks present: worker-server attempt tracks
    # plus the consumer-side dispatcher/consumer/ventilator tracks
    track_names = {m['args']['name'] for m in events if m['ph'] == 'M'}
    assert any(name.startswith('service-') for name in track_names), \
        track_names
    assert {'dispatcher', 'consumer', 'ventilator'} <= track_names
    # dispatch instants name ≥2 distinct worker servers (both attempts of
    # a re-ventilated item land on different identities)
    dispatch_workers = {e['args']['worker'] for e in data
                        if e['name'] == 'dispatch'}
    assert len(dispatch_workers) >= 2, dispatch_workers

    def ids(name):
        return [e['args']['trace_id'] for e in data if e['name'] == name]

    # (re-ventilation) the killed worker's in-flight items were lapsed
    # back and re-dispatched: both attempts on the timeline, one 'done'
    reventilated = set(ids('reventilate'))
    assert reventilated, 'SIGKILL mid-epoch must re-ventilate something'
    dispatch_counts = collections.Counter(ids('dispatch'))
    done_counts = collections.Counter(ids('done'))
    for trace_id in reventilated:
        assert dispatch_counts[trace_id] >= 2, \
            'both attempts must be on the timeline (%s)' % trace_id
        assert done_counts[trace_id] == 1, \
            'exactly one completion per item (%s)' % trace_id
    # every delivered item completed exactly once
    assert done_counts and set(done_counts.values()) == {1}
    # (consumer side) queue_wait events share ids minted at ventilation
    queue_wait_ids = set(ids('queue_wait'))
    ventilate_ids = set(ids('ventilate'))
    assert queue_wait_ids and queue_wait_ids <= ventilate_ids
    # fleet health satellite: the re-ventilation surfaced as first-class
    # metrics + the pipeline_report service section
    assert T.get_registry().counter_value(
        'petastorm_tpu_service_reventilated_total') >= 1
    report = T.pipeline_report()
    assert report['service']['reventilated'] >= 1
    assert 'service fleet:' in T.format_pipeline_report(report)
