"""Object-store scheme coverage: ``gs://`` and ``s3://`` end to end with no
network (VERDICT r3 #7 — de-risking the GCS north star).

The real backends (gcsfs / s3fs) cannot be exercised in this environment
(zero egress), so each protocol is bound to an in-memory fsspec
implementation for the duration of a test: everything above the fsspec
boundary — URL parsing, scheme dispatch, bucket-in-path semantics,
``storage_options`` plumbing, footer metadata, the batch reader and the JAX
device stage — runs exactly the code a real ``gs://`` dataset would run;
only the bytes transport is faked. Reference scheme dispatch:
``petastorm/fs_utils.py:39-166``.
"""

import numpy as np
import pyarrow as pa
import pytest

import fsspec
from fsspec.implementations.memory import MemoryFileSystem

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import (
    count_rows, get_schema_from_dataset_url, write_dataset,
)
from petastorm_tpu.unischema import Unischema, UnischemaField

SmallSchema = Unischema('SmallSchema', [
    UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
    UnischemaField('vec', np.float32, (4,), NdarrayCodec(), False),
])


def _rows(n):
    rng = np.random.RandomState(0)
    return [{'id': i, 'vec': rng.rand(4).astype(np.float32)}
            for i in range(n)]


def _fake_object_store_class(proto):
    """A MemoryFileSystem bound to ``proto`` with its own store and a
    record of the ``storage_options`` it was constructed with."""

    class _FakeObjectStore(MemoryFileSystem):
        protocol = proto
        store = {}
        pseudo_dirs = ['']
        captured_options = []
        cachable = False  # fresh instance per url_to_fs: options always seen

        def __init__(self, **storage_options):
            type(self).captured_options.append(dict(storage_options))
            super().__init__()

        @classmethod
        def _strip_protocol(cls, path):
            path = str(path)
            if path.startswith(cls.protocol + '://'):
                path = path[len(cls.protocol) + 3:]
            return '/' + path.lstrip('/')

    return _FakeObjectStore


@pytest.fixture(params=['gs', 's3'])
def object_store(request):
    """Bind the param protocol to a fresh fake store; restore after."""
    proto = request.param
    try:
        original = fsspec.get_filesystem_class(proto)
    except (ImportError, ValueError):
        original = None
    cls = _fake_object_store_class(proto)
    fsspec.register_implementation(proto, cls, clobber=True)
    try:
        yield proto, cls
    finally:
        cls.store.clear()
        if original is not None:
            fsspec.register_implementation(proto, original, clobber=True)
        else:
            # no real backend installed (e.g. s3fs absent here): drop the
            # fake binding entirely so later tests get the original
            # missing-backend ImportError, not a silent empty store
            from fsspec.registry import _registry
            _registry.pop(proto, None)


def test_write_read_round_trip(object_store):
    proto, cls = object_store
    url = proto + '://bucket/datasets/small'
    write_dataset(url, SmallSchema, _rows(30), rowgroup_size_rows=10,
                  num_files=2)
    # footer metadata resolves over the scheme
    assert set(get_schema_from_dataset_url(url).fields) == {'id', 'vec'}
    assert count_rows(url) == 30
    with make_batch_reader(url, num_epochs=1) as reader:
        got = sorted(i for b in reader for i in b.id.tolist())
    assert got == list(range(30))


def test_row_reader_and_codec_decode(object_store):
    proto, cls = object_store
    url = proto + '://bucket/rowds'
    rows = _rows(12)
    write_dataset(url, SmallSchema, rows, rowgroup_size_rows=4)
    with make_reader(url, num_epochs=1) as reader:
        by_id = {row.id: row.vec for row in reader}
    assert len(by_id) == 12
    np.testing.assert_array_almost_equal(by_id[3], rows[3]['vec'])


def test_url_list_reads_file_subset(object_store):
    proto, cls = object_store
    url = proto + '://bucket/listed'
    write_dataset(url, SmallSchema, _rows(40), rowgroup_size_rows=10,
                  num_files=4)
    fs = fsspec.filesystem(proto)
    parts = sorted(p.lstrip('/')
                   for p in fs.ls('/bucket/listed', detail=False)
                   if p.endswith('.parquet'))
    assert len(parts) == 4
    urls = ['%s://%s' % (proto, p) for p in parts[:2]]
    with make_batch_reader(urls, num_epochs=1) as reader:
        got = sorted(i for b in reader for i in b.id.tolist())
    assert len(got) == 20  # exactly the two listed files' rows


def test_storage_options_reach_the_filesystem(object_store):
    proto, cls = object_store
    url = proto + '://bucket/opts'
    token = {'token': 'fake-%s-credential' % proto}
    write_dataset(url, SmallSchema, _rows(8), rowgroup_size_rows=4,
                  storage_options=token)
    cls.captured_options.clear()
    with make_batch_reader(url, num_epochs=1,
                           storage_options=token) as reader:
        rows = sum(len(b.id) for b in reader)
    assert rows == 8
    assert any(opts.get('token') == token['token']
               for opts in cls.captured_options), cls.captured_options


def test_jax_loader_over_object_store(object_store):
    proto, cls = object_store
    from petastorm_tpu.jax import make_jax_loader
    url = proto + '://bucket/jaxds'
    write_dataset(url, SmallSchema, _rows(32), rowgroup_size_rows=8)
    with make_jax_loader(url, batch_size=8, num_epochs=1,
                         last_batch='short') as loader:
        batches = list(loader)
    assert sum(b['id'].shape[0] for b in batches) == 32
    assert str(batches[0]['vec'].dtype) == 'float32'
