"""Object-store scheme coverage: ``gs://`` and ``s3://`` end to end with no
network (VERDICT r3 #7 — de-risking the GCS north star).

The real backends (gcsfs / s3fs) cannot be exercised in this environment
(zero egress), so each protocol is bound to an in-memory fsspec
implementation for the duration of a test: everything above the fsspec
boundary — URL parsing, scheme dispatch, bucket-in-path semantics,
``storage_options`` plumbing, footer metadata, the batch reader and the JAX
device stage — runs exactly the code a real ``gs://`` dataset would run;
only the bytes transport is faked. Reference scheme dispatch:
``petastorm/fs_utils.py:39-166``.
"""

import numpy as np
import pyarrow as pa
import pytest

import fsspec
from fsspec.implementations.memory import MemoryFileSystem

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import (
    count_rows, get_schema_from_dataset_url, write_dataset,
)
from petastorm_tpu.unischema import Unischema, UnischemaField

SmallSchema = Unischema('SmallSchema', [
    UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
    UnischemaField('vec', np.float32, (4,), NdarrayCodec(), False),
])


def _rows(n):
    rng = np.random.RandomState(0)
    return [{'id': i, 'vec': rng.rand(4).astype(np.float32)}
            for i in range(n)]


def _fake_object_store_class(proto):
    """A MemoryFileSystem bound to ``proto`` with its own store and a
    record of the ``storage_options`` it was constructed with."""

    class _FakeObjectStore(MemoryFileSystem):
        protocol = proto
        store = {}
        pseudo_dirs = ['']
        captured_options = []
        cachable = False  # fresh instance per url_to_fs: options always seen

        def __init__(self, **storage_options):
            type(self).captured_options.append(dict(storage_options))
            super().__init__()

        @classmethod
        def _strip_protocol(cls, path):
            path = str(path)
            if path.startswith(cls.protocol + '://'):
                path = path[len(cls.protocol) + 3:]
            return '/' + path.lstrip('/')

    return _FakeObjectStore


@pytest.fixture(params=['gs', 's3'])
def object_store(request):
    """Bind the param protocol to a fresh fake store; restore after."""
    proto = request.param
    try:
        original = fsspec.get_filesystem_class(proto)
    except (ImportError, ValueError):
        original = None
    cls = _fake_object_store_class(proto)
    fsspec.register_implementation(proto, cls, clobber=True)
    try:
        yield proto, cls
    finally:
        cls.store.clear()
        if original is not None:
            fsspec.register_implementation(proto, original, clobber=True)
        else:
            # no real backend installed (e.g. s3fs absent here): drop the
            # fake binding entirely so later tests get the original
            # missing-backend ImportError, not a silent empty store
            from fsspec.registry import _registry
            _registry.pop(proto, None)


def test_write_read_round_trip(object_store):
    proto, cls = object_store
    url = proto + '://bucket/datasets/small'
    write_dataset(url, SmallSchema, _rows(30), rowgroup_size_rows=10,
                  num_files=2)
    # footer metadata resolves over the scheme
    assert set(get_schema_from_dataset_url(url).fields) == {'id', 'vec'}
    assert count_rows(url) == 30
    with make_batch_reader(url, num_epochs=1) as reader:
        got = sorted(i for b in reader for i in b.id.tolist())
    assert got == list(range(30))


def test_row_reader_and_codec_decode(object_store):
    proto, cls = object_store
    url = proto + '://bucket/rowds'
    rows = _rows(12)
    write_dataset(url, SmallSchema, rows, rowgroup_size_rows=4)
    with make_reader(url, num_epochs=1) as reader:
        by_id = {row.id: row.vec for row in reader}
    assert len(by_id) == 12
    np.testing.assert_array_almost_equal(by_id[3], rows[3]['vec'])


def test_url_list_reads_file_subset(object_store):
    proto, cls = object_store
    url = proto + '://bucket/listed'
    write_dataset(url, SmallSchema, _rows(40), rowgroup_size_rows=10,
                  num_files=4)
    fs = fsspec.filesystem(proto)
    parts = sorted(p.lstrip('/')
                   for p in fs.ls('/bucket/listed', detail=False)
                   if p.endswith('.parquet'))
    assert len(parts) == 4
    urls = ['%s://%s' % (proto, p) for p in parts[:2]]
    with make_batch_reader(urls, num_epochs=1) as reader:
        got = sorted(i for b in reader for i in b.id.tolist())
    assert len(got) == 20  # exactly the two listed files' rows


def test_storage_options_reach_the_filesystem(object_store):
    proto, cls = object_store
    url = proto + '://bucket/opts'
    token = {'token': 'fake-%s-credential' % proto}
    write_dataset(url, SmallSchema, _rows(8), rowgroup_size_rows=4,
                  storage_options=token)
    cls.captured_options.clear()
    with make_batch_reader(url, num_epochs=1,
                           storage_options=token) as reader:
        rows = sum(len(b.id) for b in reader)
    assert rows == 8
    assert any(opts.get('token') == token['token']
               for opts in cls.captured_options), cls.captured_options


def test_jax_loader_over_object_store(object_store):
    proto, cls = object_store
    from petastorm_tpu.jax import make_jax_loader
    url = proto + '://bucket/jaxds'
    write_dataset(url, SmallSchema, _rows(32), rowgroup_size_rows=8)
    with make_jax_loader(url, batch_size=8, num_epochs=1,
                         last_batch='short') as loader:
        batches = list(loader)
    assert sum(b['id'].shape[0] for b in batches) == 32
    assert str(batches[0]['vec'].dtype) == 'float32'


def _strict_remote_store_class(proto):
    """Fake store that ENFORCES remote semantics (VERDICT r4 #6):

    * every path must keep its bucket — a path that lost it (os.path
      mangling, local-path leakage) raises instead of silently resolving;
    * localizing APIs (``get``/``download``/``open_local``) are forbidden
      — a remote pipeline streams, it never stages to local disk;
    * read opens and seeks are recorded, so a test can assert the data
      really moved through seekable fsspec file objects (the footer-last
      parquet read discipline), not some side channel.
    """
    base = _fake_object_store_class(proto)

    class _StrictRemoteStore(base):
        reads = []
        seeks = []

        @classmethod
        def _strip_protocol(cls, path):
            p = super()._strip_protocol(path)
            if not (p == '/' or p.startswith('/bucket')):
                raise AssertionError(
                    'non-bucket path reached the object store: %r' % (path,))
            return p

        def _forbidden(self, *a, **kw):
            raise AssertionError('localizing API used on a remote store')

        get = get_file = download = open_local = _forbidden

        def _open(self, path, mode='rb', **kw):
            f = super()._open(path, mode=mode, **kw)
            if 'r' in mode:
                cls = type(self)
                cls.reads.append(path)
                orig_seek = f.seek

                def recording_seek(pos, whence=0):
                    cls.seeks.append((path, pos, whence))
                    return orig_seek(pos, whence)

                f.seek = recording_seek
            return f

    return _StrictRemoteStore


@pytest.fixture
def strict_gs_store():
    try:
        original = fsspec.get_filesystem_class('gs')
    except (ImportError, ValueError):
        original = None
    cls = _strict_remote_store_class('gs')
    fsspec.register_implementation('gs', cls, clobber=True)
    try:
        yield cls
    finally:
        cls.store.clear()
        if original is not None:
            fsspec.register_implementation('gs', original, clobber=True)
        else:
            from fsspec.registry import _registry
            _registry.pop('gs', None)


def test_strict_store_rejects_local_paths_and_localizing_apis(
        strict_gs_store):
    cls = strict_gs_store
    fs = fsspec.filesystem('gs')
    with pytest.raises(AssertionError, match='non-bucket'):
        fs.ls('gs://tmp/not-a-bucket-path')
    with pytest.raises(AssertionError, match='localizing'):
        fs.get('gs://bucket/x', '/tmp/x')


def test_e2e_train_loop_from_gs_url(strict_gs_store):
    """The whole product path against remote-semantics storage, zero
    network: write to gs://, read back via a URL LIST + storage_options
    through make_batch_reader/make_jax_loader, and run a real optimizer
    loop on the staged batches. Asserts the bytes moved through seekable
    fsspec reads and that training actually descended."""
    import jax
    import jax.numpy as jnp
    import optax

    from petastorm_tpu.jax import make_jax_loader

    cls = strict_gs_store
    token = {'token': 'fake-gcs-credential'}
    url = 'gs://bucket/train/e2e'
    write_dataset(url, SmallSchema, _rows(64), rowgroup_size_rows=8,
                  num_files=2, storage_options=token)

    # URL-list flavor: read the two part files listed over the scheme
    fs = fsspec.filesystem('gs')
    parts = sorted(p for p in fs.ls('/bucket/train/e2e', detail=False)
                   if p.endswith('.parquet'))
    assert len(parts) == 2
    urls = ['gs://%s' % p.lstrip('/') for p in parts]

    cls.reads.clear()
    cls.seeks.clear()
    w = jnp.zeros((4,), jnp.float32)
    opt = optax.adam(0.2)
    opt_state = opt.init(w)

    @jax.jit
    def train_step(w, opt_state, vec, target):
        def loss_fn(w):
            pred = vec @ w
            return jnp.mean((pred - target) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(w)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(w, updates), opt_state, loss

    losses = []
    for _ in range(4):  # four epochs by re-building over the same urls
        with make_jax_loader(urls, batch_size=8, num_epochs=1,
                             storage_options=token) as loader:
            for batch in loader:
                vec = batch['vec']
                # a learnable target: project vec onto fixed weights
                target = vec @ jnp.asarray([1.0, -2.0, 0.5, 3.0])
                w, opt_state, loss = train_step(w, opt_state, vec, target)
                losses.append(float(loss))
    assert len(losses) == 32  # 64 rows / batch 8, four epochs
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0] / 10, losses  # it really descended

    # the bytes went through seekable remote reads (parquet footer
    # discipline), through THIS store, with the credential visible
    assert any(p.endswith('.parquet') for p in cls.reads), cls.reads
    assert cls.seeks, 'no seek ever recorded: reads were not ranged'
    assert any(opts.get('token') == token['token']
               for opts in cls.captured_options)
