"""Knob registry (telemetry/knobs.py): the one owner of PETASTORM_TPU_*
parsing. Regression coverage for the call sites the env-knob analysis
pass migrated onto it — semantics must match the old per-site parses."""

import pytest

from petastorm_tpu.analysis.contracts import KNOWN_KNOBS
from petastorm_tpu.telemetry import knobs


def test_unregistered_knob_raises():
    with pytest.raises(ValueError, match='Unregistered'):
        knobs.raw('PETASTORM_TPU_NOT_A_REAL_KNOB')
    with pytest.raises(ValueError, match='Unregistered'):
        knobs.set_env('PETASTORM_TPU_NOT_A_REAL_KNOB', '1')


def test_raw_and_get_str(monkeypatch):
    monkeypatch.delenv('PETASTORM_TPU_STAGING', raising=False)
    assert knobs.raw('PETASTORM_TPU_STAGING') is None
    assert knobs.get_str('PETASTORM_TPU_STAGING') == ''
    monkeypatch.setenv('PETASTORM_TPU_STAGING', '  0  ')
    assert knobs.raw('PETASTORM_TPU_STAGING') == '  0  '
    assert knobs.get_str('PETASTORM_TPU_STAGING') == '0'


@pytest.mark.parametrize('value,disabled', [
    ('0', True), ('false', True), ('off', True), ('no', True),
    ('FALSE', True), (' off ', True),
    ('', False), ('1', False), ('anything', False),
])
def test_is_disabled_spellings(monkeypatch, value, disabled):
    monkeypatch.setenv('PETASTORM_TPU_METRICS', value)
    assert knobs.is_disabled('PETASTORM_TPU_METRICS') is disabled


@pytest.mark.parametrize('value,enabled', [
    ('1', True), ('true', True), ('on', True), ('yes', True), ('ON', True),
    ('', False), ('0', False), ('anything', False),
])
def test_is_enabled_spellings(monkeypatch, value, enabled):
    monkeypatch.setenv('PETASTORM_TPU_TRACE', value)
    assert knobs.is_enabled('PETASTORM_TPU_TRACE') is enabled


def test_get_int_fallback_and_floor(monkeypatch):
    name = 'PETASTORM_TPU_STAGING_SLOTS'
    monkeypatch.delenv(name, raising=False)
    assert knobs.get_int(name, 2) == 2
    monkeypatch.setenv(name, '7')
    assert knobs.get_int(name, 2) == 7
    monkeypatch.setenv(name, 'seven')       # unparseable -> default
    assert knobs.get_int(name, 2) == 2
    monkeypatch.setenv(name, '1')
    assert knobs.get_int(name, 2, floor=2) == 2


def test_get_float_fallback(monkeypatch):
    name = 'PETASTORM_TPU_METRICS_WINDOW_S'
    monkeypatch.setenv(name, '0.25')
    assert knobs.get_float(name, 0.5) == 0.25
    monkeypatch.setenv(name, 'fast')
    assert knobs.get_float(name, 0.5) == 0.5


def test_set_env_round_trip(monkeypatch):
    # setenv FIRST so monkeypatch records the true original for teardown
    # (delenv on an already-missing name records nothing, and undo would
    # then RESTORE the set_env write — leaking TRACE=1 into later tests)
    monkeypatch.setenv('PETASTORM_TPU_TRACE', '0')
    knobs.set_env('PETASTORM_TPU_TRACE', '1')
    assert knobs.is_enabled('PETASTORM_TPU_TRACE')


def test_every_registered_knob_is_prefixed():
    assert all(name.startswith(knobs.KNOB_PREFIX) for name in KNOWN_KNOBS)


# -- migrated call sites keep their semantics --------------------------------


def test_native_disabled_semantics(monkeypatch):
    from petastorm_tpu.native import native_disabled
    monkeypatch.delenv('PETASTORM_TPU_NATIVE', raising=False)
    assert native_disabled() is False           # default: on
    monkeypatch.setenv('PETASTORM_TPU_NATIVE', '0')
    assert native_disabled() is True            # live per-call check
    monkeypatch.setenv('PETASTORM_TPU_NATIVE', 'no')
    assert native_disabled() is True            # shared DISABLED_VALUES
    monkeypatch.setenv('PETASTORM_TPU_NATIVE', '1')
    assert native_disabled() is False


def test_staging_knobs_via_refresh(monkeypatch):
    from petastorm_tpu.jax import staging
    monkeypatch.setenv('PETASTORM_TPU_STAGING', '0')
    monkeypatch.setenv('PETASTORM_TPU_STAGING_SLOTS', '5')
    staging.refresh_staging()
    try:
        assert staging.staging_enabled() is False
        assert staging.staging_slots() == 5
        monkeypatch.setenv('PETASTORM_TPU_STAGING', '')
        monkeypatch.setenv('PETASTORM_TPU_STAGING_SLOTS', '1')  # under floor
        staging.refresh_staging()
        assert staging.staging_enabled() is True
        assert staging.staging_slots() == 2
    finally:
        monkeypatch.delenv('PETASTORM_TPU_STAGING', raising=False)
        monkeypatch.delenv('PETASTORM_TPU_STAGING_SLOTS', raising=False)
        staging.refresh_staging()


def test_stall_window_knob(monkeypatch):
    from petastorm_tpu.telemetry.stall import default_window_s
    monkeypatch.delenv('PETASTORM_TPU_METRICS_WINDOW_S', raising=False)
    assert default_window_s() == 0.5
    monkeypatch.setenv('PETASTORM_TPU_METRICS_WINDOW_S', '0.25')
    assert default_window_s() == 0.25
    monkeypatch.setenv('PETASTORM_TPU_METRICS_WINDOW_S', '-1')
    assert default_window_s() == 0.5            # non-positive -> default
    monkeypatch.setenv('PETASTORM_TPU_METRICS_WINDOW_S', 'soon')
    assert default_window_s() == 0.5


def test_autodump_windows_knob(monkeypatch):
    from petastorm_tpu.telemetry.tracing import autodump_windows
    monkeypatch.delenv('PETASTORM_TPU_TRACE_AUTODUMP_WINDOWS', raising=False)
    assert autodump_windows() == 6
    monkeypatch.setenv('PETASTORM_TPU_TRACE_AUTODUMP_WINDOWS', '3')
    assert autodump_windows() == 3
    monkeypatch.setenv('PETASTORM_TPU_TRACE_AUTODUMP_WINDOWS', '0')
    assert autodump_windows() == 1              # floor
    monkeypatch.setenv('PETASTORM_TPU_TRACE_AUTODUMP_WINDOWS', 'many')
    assert autodump_windows() == 6
