"""WeightedSamplingReader tests
(reference: ``tests/test_weighted_sampling_reader.py``)."""

import numpy as np
import pytest

from petastorm_tpu.reader import make_reader
from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader


def _reader(url, **kw):
    kw.setdefault('num_epochs', None)
    kw.setdefault('shuffle_row_groups', False)
    kw.setdefault('reader_pool_type', 'dummy')
    return make_reader(url, **kw)


def test_basic_iteration(synthetic_dataset):
    with _reader(synthetic_dataset.url) as a, _reader(synthetic_dataset.url) as b:
        mix = WeightedSamplingReader([a, b], [0.8, 0.2], seed=0)
        for _ in range(100):
            assert hasattr(next(mix), 'id')


class _SpyReader:
    """Delegating reader wrapper invoking ``on_next`` per drawn row."""

    def __init__(self, reader, on_next):
        self._reader = reader
        self._on_next = on_next

    def __getattr__(self, name):
        return getattr(self._reader, name)

    def __next__(self):
        self._on_next()
        return next(self._reader)


def test_choice_distribution(synthetic_dataset):
    counts = [0, 0]

    def count(bucket):
        return lambda: counts.__setitem__(bucket, counts[bucket] + 1)

    with _reader(synthetic_dataset.url) as a, _reader(synthetic_dataset.url) as b:
        mix = WeightedSamplingReader(
            [_SpyReader(a, count(0)), _SpyReader(b, count(1))],
            [0.75, 0.25], seed=42)
        for _ in range(1000):
            next(mix)
    ratio = counts[0] / 1000.0
    assert 0.70 < ratio < 0.80, counts


def test_schema_mismatch_rejected(synthetic_dataset):
    with _reader(synthetic_dataset.url) as a, \
            _reader(synthetic_dataset.url, schema_fields=['^id$']) as b:
        with pytest.raises(ValueError, match='same output schema'):
            WeightedSamplingReader([a, b], [0.5, 0.5])


def test_bad_probabilities(synthetic_dataset):
    with _reader(synthetic_dataset.url) as a:
        with pytest.raises(ValueError):
            WeightedSamplingReader([a], [0.5, 0.5])
        with pytest.raises(ValueError):
            WeightedSamplingReader([a], [-1.0])
        with pytest.raises(ValueError):
            WeightedSamplingReader([], [])


def test_deterministic_with_seed(synthetic_dataset):
    ids_runs = []
    for _ in range(2):
        with _reader(synthetic_dataset.url) as a, \
                _reader(synthetic_dataset.url) as b:
            mix = WeightedSamplingReader([a, b], [0.5, 0.5], seed=7)
            ids_runs.append([next(mix).id for _ in range(50)])
    assert ids_runs[0] == ids_runs[1]


def test_degenerate_probability_selects_single_reader(synthetic_dataset):
    # reference: test_select_only_one_of_readers (:52)
    marker = {'count': 0}

    def mark():
        marker['count'] += 1

    with _reader(synthetic_dataset.url) as a, \
            _reader(synthetic_dataset.url) as b:
        mix = WeightedSamplingReader([a, _SpyReader(b, mark)],
                                     [1.0, 0.0], seed=1)
        for _ in range(50):
            next(mix)
    assert marker['count'] == 0


def test_tf_dataset_over_mix(synthetic_dataset):
    # reference: test_with_tf_data_api (:172)
    pytest.importorskip('tensorflow')
    from petastorm_tpu.tf_utils import make_petastorm_dataset
    with _reader(synthetic_dataset.url, schema_fields=['^id$']) as a, \
            _reader(synthetic_dataset.url, schema_fields=['^id$']) as b:
        mix = WeightedSamplingReader([a, b], [0.5, 0.5], seed=2)
        dataset = make_petastorm_dataset(mix)
        ids = [int(row.id) for row in dataset.take(20)]
    assert len(ids) == 20 and all(0 <= i < 100 for i in ids)


def test_torch_loader_over_mix(synthetic_dataset):
    # reference: test_with_torch_api (:203)
    pytest.importorskip('torch')
    from petastorm_tpu.pytorch import DataLoader
    with _reader(synthetic_dataset.url, schema_fields=['^id$']) as a, \
            _reader(synthetic_dataset.url, schema_fields=['^id$']) as b:
        mix = WeightedSamplingReader([a, b], [0.3, 0.7], seed=3)
        loader = DataLoader(mix, batch_size=10)
        batch = next(iter(loader))
    assert len(batch['id']) == 10


def test_jax_loader_over_mix(synthetic_dataset, scalar_dataset):
    # the TPU-native consumer over a probabilistic mix: a reader_factory
    # returning a WeightedSamplingReader of BATCHED readers feeds
    # make_jax_loader like any single reader
    from petastorm_tpu.jax import make_jax_loader
    from petastorm_tpu.reader import make_batch_reader

    def factory(unused_url, **kw):
        kw.pop('schema_fields', None)
        kw.pop('num_epochs', None)
        readers = [
            make_batch_reader(synthetic_dataset.url, num_epochs=None,
                              schema_fields=['^id$'], **kw),
            make_batch_reader(scalar_dataset.url, num_epochs=None,
                              schema_fields=['^id$'], **kw),
        ]
        return WeightedSamplingReader(readers, [0.5, 0.5], seed=4)

    with make_jax_loader(synthetic_dataset.url, batch_size=16,
                         reader_factory=factory, num_epochs=None) as loader:
        it = iter(loader)
        ids = np.concatenate([np.asarray(next(it)['id'])
                              for _ in range(4)])
    assert len(ids) == 64
    assert all(0 <= i < 100 for i in ids)


def test_mix_reset_supports_loader_reiteration(synthetic_dataset):
    # the loader's re-iteration contract calls reader.reset(); the mix
    # must delegate it (finite-epoch mixes would crash otherwise)
    from petastorm_tpu.jax import make_jax_loader
    from petastorm_tpu.reader import make_batch_reader

    def factory(unused_url, **kw):
        kw.pop('schema_fields', None)
        kw.pop('num_epochs', None)
        readers = [
            make_batch_reader(synthetic_dataset.url, num_epochs=1,
                              schema_fields=['^id$'],
                              shuffle_row_groups=False, **kw)
            for _ in range(2)
        ]
        return WeightedSamplingReader(readers, [0.5, 0.5], seed=5)

    with make_jax_loader(synthetic_dataset.url, batch_size=20,
                         reader_factory=factory) as loader:
        first = [np.asarray(b['id']) for b in loader]
        second = [np.asarray(b['id']) for b in loader]  # reset + replay
    assert first and second
    assert sum(len(b) for b in second) > 0


def test_mix_checkpoint_resumes_choice_sequence(scalar_dataset):
    # the mix's state = every source's position + the mux RNG cursor: a
    # fresh mix restored from it continues the SAME uniform stream (and
    # so the same source-choice sequence) an uninterrupted run would
    # have produced
    from petastorm_tpu.reader import make_batch_reader

    def build():
        a = make_batch_reader(scalar_dataset.url, schema_fields=['^id$'],
                              num_epochs=None, shuffle_row_groups=False,
                              reader_pool_type='dummy')
        b = make_batch_reader(scalar_dataset.url, schema_fields=['^id$'],
                              num_epochs=None, shuffle_row_groups=False,
                              reader_pool_type='dummy')
        return WeightedSamplingReader([a, b], [0.5, 0.5], seed=42)

    # uninterrupted run: record the raw uniform stream for 12 draws
    rng = np.random.RandomState(42)
    want_stream = [float(rng.random_sample()) for _ in range(12)]

    with build() as mix:
        for _ in range(5):
            next(mix)
        state = mix.state_dict()
    assert state['draws'] == 5 and len(state['readers']) == 2

    with build() as mix2:
        mix2.load_state_dict(state)
        # the restored RNG continues the stream at draw 5 exactly
        got_next = [float(mix2._rng.random_sample()) for _ in range(7)]
    np.testing.assert_allclose(got_next, want_stream[5:], rtol=0, atol=0)


def test_mix_checkpoint_sources_restore(scalar_dataset):
    # sub-reader positions round-trip: rows consumed before the save are
    # not re-delivered after restore (full-rowgroup granularity)
    from petastorm_tpu.reader import make_batch_reader

    def build():
        readers = [make_batch_reader(scalar_dataset.url,
                                     schema_fields=['^id$'], num_epochs=1,
                                     shuffle_row_groups=False,
                                     reader_pool_type='dummy')
                   for _ in range(2)]
        return WeightedSamplingReader(readers, [0.5, 0.5], seed=7)

    seen_before = []
    with build() as mix:
        for _ in range(4):
            seen_before.extend(np.asarray(next(mix).id).tolist())
        state = mix.state_dict()

    seen_after = []
    with build() as mix2:
        mix2.load_state_dict(state)
        try:
            while True:
                seen_after.extend(np.asarray(next(mix2).id).tolist())
        except StopIteration:
            pass
    # each source covers the dataset once; the union must cover it and
    # the resumed pass must be shorter than two fresh epochs
    assert set(seen_before) | set(seen_after) == set(range(100))
    assert len(seen_after) < 200


def test_mix_checkpoint_reader_count_mismatch_rejected(scalar_dataset):
    from petastorm_tpu.reader import make_batch_reader
    with make_batch_reader(scalar_dataset.url, schema_fields=['^id$'],
                           reader_pool_type='dummy') as reader:
        mix = WeightedSamplingReader([reader], [1.0], seed=0)
        with pytest.raises(ValueError, match='reader states'):
            mix.load_state_dict({'version': 1, 'seed': 0, 'draws': 0,
                                 'readers': [{}, {}]})


def test_mix_second_generation_restore_keeps_stream(scalar_dataset):
    # a checkpoint of a RESTORED mix must record the stream it actually
    # runs on (the checkpoint's seed, not this instance's constructor
    # seed), or a second restore replays a different choice sequence
    from petastorm_tpu.reader import make_batch_reader

    def build(seed):
        readers = [make_batch_reader(scalar_dataset.url,
                                     schema_fields=['^id$'],
                                     num_epochs=None,
                                     shuffle_row_groups=False,
                                     reader_pool_type='dummy')
                   for _ in range(2)]
        return WeightedSamplingReader(readers, [0.5, 0.5], seed=seed)

    with build(seed=42) as mix:
        for _ in range(3):
            next(mix)
        s1 = mix.state_dict()

    # restore into a mix constructed with a DIFFERENT seed, advance, save
    with build(seed=None) as mix2:
        mix2.load_state_dict(s1)
        for _ in range(2):
            next(mix2)
        s2 = mix2.state_dict()
    assert s2['seed'] == 42 and s2['draws'] == 5

    # third generation: the restored stream continues seed-42's uniforms
    rng = np.random.RandomState(42)
    rng.random_sample(5)
    want = [float(rng.random_sample()) for _ in range(3)]
    with build(seed=7) as mix3:
        mix3.load_state_dict(s2)
        got = [float(mix3._rng.random_sample()) for _ in range(3)]
    np.testing.assert_allclose(got, want, rtol=0, atol=0)


def test_mix_restore_adopts_rng_state_not_replay(scalar_dataset):
    # O(1) restore (advisor r4): the saved Mersenne-Twister state is
    # adopted directly. Proof without timing: poison the checkpoint's
    # 'seed' — a replay-from-seed implementation would now produce a
    # different stream, while rng_state continues the original exactly.
    from petastorm_tpu.reader import make_batch_reader

    def build(seed):
        readers = [make_batch_reader(scalar_dataset.url,
                                     schema_fields=['^id$'],
                                     num_epochs=None,
                                     shuffle_row_groups=False,
                                     reader_pool_type='dummy')
                   for _ in range(2)]
        return WeightedSamplingReader(readers, [0.5, 0.5], seed=seed)

    rng = np.random.RandomState(42)
    want_stream = [float(rng.random_sample()) for _ in range(12)]

    with build(seed=42) as mix:
        for _ in range(5):
            next(mix)
        state = mix.state_dict()
    assert 'rng_state' in state and state['rng_state'][0] == 'MT19937'
    # JSON round-trip safety: every element is a plain python scalar
    import json
    state_json = json.loads(json.dumps(state))

    poisoned = dict(state_json, seed=999, draws=10**12)
    with build(seed=None) as mix2:
        mix2.load_state_dict(poisoned)  # instant even at draws=10^12
        got = [float(mix2._rng.random_sample()) for _ in range(7)]
        assert mix2._draws == 10**12
    np.testing.assert_allclose(got, want_stream[5:], rtol=0, atol=0)


def test_mix_legacy_checkpoint_without_rng_state_replays(scalar_dataset):
    # checkpoints written before rng_state existed still restore via the
    # bounded-chunk replay of seed+draws
    from petastorm_tpu.reader import make_batch_reader

    def build(seed):
        readers = [make_batch_reader(scalar_dataset.url,
                                     schema_fields=['^id$'],
                                     num_epochs=None,
                                     shuffle_row_groups=False,
                                     reader_pool_type='dummy')
                   for _ in range(2)]
        return WeightedSamplingReader(readers, [0.5, 0.5], seed=seed)

    rng = np.random.RandomState(42)
    want_stream = [float(rng.random_sample()) for _ in range(12)]

    with build(seed=42) as mix:
        for _ in range(5):
            next(mix)
        state = mix.state_dict()
    state.pop('rng_state')

    with build(seed=None) as mix2:
        mix2.load_state_dict(state)
        got = [float(mix2._rng.random_sample()) for _ in range(7)]
    np.testing.assert_allclose(got, want_stream[5:], rtol=0, atol=0)


# -- delivered-draw accounting + deterministic interleave mode ---------------


class _DryReader:
    """Schema-compatible source that is already exhausted."""

    def __init__(self, like):
        self._like = like

    def __getattr__(self, name):
        return getattr(self._like, name)

    def __next__(self):
        raise StopIteration


def test_stop_iteration_does_not_charge_draw(synthetic_dataset):
    # regression: __next__ used to charge _draws BEFORE the source's
    # next(), so the draw that ended the mix (StopIteration) was counted
    # and a checkpoint at mix end replayed a choice sequence shifted by
    # one on restore
    with _reader(synthetic_dataset.url) as a:
        mix = WeightedSamplingReader([a, _DryReader(a)], [0.5, 0.5], seed=3)
        delivered = 0
        try:
            while True:
                next(mix)
                delivered += 1
        except StopIteration:
            pass
        state = mix.state_dict()
    assert state['draws'] == delivered
    # the mux RNG rewound the failed draw: its state equals a reference
    # generator advanced by exactly the DELIVERED draws
    ref = np.random.RandomState(3)
    ref.random_sample(delivered)
    _, ref_keys, ref_pos, _, _ = ref.get_state()
    assert state['rng_state'][1] == [int(k) for k in ref_keys]
    assert state['rng_state'][2] == int(ref_pos)


def test_deterministic_mode_follows_interleave(synthetic_dataset):
    from petastorm_tpu.mixture import InterleaveSchedule
    choices = []

    def record(bucket):
        return lambda: choices.append(bucket)

    with _reader(synthetic_dataset.url) as a, _reader(synthetic_dataset.url) as b:
        mix = WeightedSamplingReader(
            [_SpyReader(a, record(0)), _SpyReader(b, record(1))],
            [3, 1], seed=5, deterministic=True)
        for _ in range(100):
            next(mix)
    assert choices == InterleaveSchedule.order([3, 1], seed=5, start=0,
                                               k=100)


def test_deterministic_mode_checkpoint_roundtrip(scalar_dataset):
    from petastorm_tpu.reader import make_batch_reader

    def build():
        readers = [make_batch_reader(scalar_dataset.url,
                                     schema_fields=['^id$'],
                                     num_epochs=None,
                                     shuffle_row_groups=False,
                                     reader_pool_type='dummy')
                   for _ in range(2)]
        return WeightedSamplingReader(readers, [2, 1], seed=9,
                                      deterministic=True)

    with build() as oracle:
        want = [np.asarray(next(oracle).id).tolist() for _ in range(20)]

    with build() as mix:
        head = [np.asarray(next(mix).id).tolist() for _ in range(7)]
        state = mix.state_dict()
    assert 'interleave' in state

    with build() as mix2:
        mix2.load_state_dict(state)
        tail = [np.asarray(next(mix2).id).tolist() for _ in range(13)]
    assert head + tail == want


def test_deterministic_mode_accepts_legacy_draws_state(scalar_dataset):
    # an RNG-era checkpoint (no 'interleave' leg) restores by replaying
    # the pure schedule to the delivered-draw cursor
    from petastorm_tpu.reader import make_batch_reader

    def build():
        readers = [make_batch_reader(scalar_dataset.url,
                                     schema_fields=['^id$'],
                                     num_epochs=None,
                                     shuffle_row_groups=False,
                                     reader_pool_type='dummy')
                   for _ in range(2)]
        return WeightedSamplingReader(readers, [2, 1], seed=9,
                                      deterministic=True)

    with build() as oracle:
        want = [np.asarray(next(oracle).id).tolist() for _ in range(20)]

    with build() as mix:
        for _ in range(7):
            next(mix)
        state = mix.state_dict()
    del state['interleave']

    with build() as mix2:
        mix2.load_state_dict(state)
        tail = [np.asarray(next(mix2).id).tolist() for _ in range(13)]
    assert tail == want[7:]
