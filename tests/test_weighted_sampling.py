"""WeightedSamplingReader tests
(reference: ``tests/test_weighted_sampling_reader.py``)."""

import numpy as np
import pytest

from petastorm_tpu.reader import make_reader
from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader


def _reader(url, **kw):
    kw.setdefault('num_epochs', None)
    kw.setdefault('shuffle_row_groups', False)
    kw.setdefault('reader_pool_type', 'dummy')
    return make_reader(url, **kw)


def test_basic_iteration(synthetic_dataset):
    with _reader(synthetic_dataset.url) as a, _reader(synthetic_dataset.url) as b:
        mix = WeightedSamplingReader([a, b], [0.8, 0.2], seed=0)
        for _ in range(100):
            assert hasattr(next(mix), 'id')


def test_choice_distribution(synthetic_dataset):
    class _Counting:
        def __init__(self, reader, bucket, counts):
            self._reader = reader
            self._bucket = bucket
            self._counts = counts
            self.schema = reader.schema
            self.batched_output = reader.batched_output
            self.ngram = reader.ngram

        def __next__(self):
            self._counts[self._bucket] += 1
            return next(self._reader)

        def stop(self):
            self._reader.stop()

        def join(self):
            self._reader.join()

    counts = [0, 0]
    with _reader(synthetic_dataset.url) as a, _reader(synthetic_dataset.url) as b:
        mix = WeightedSamplingReader(
            [_Counting(a, 0, counts), _Counting(b, 1, counts)],
            [0.75, 0.25], seed=42)
        for _ in range(1000):
            next(mix)
    ratio = counts[0] / 1000.0
    assert 0.70 < ratio < 0.80, counts


def test_schema_mismatch_rejected(synthetic_dataset):
    with _reader(synthetic_dataset.url) as a, \
            _reader(synthetic_dataset.url, schema_fields=['^id$']) as b:
        with pytest.raises(ValueError, match='same output schema'):
            WeightedSamplingReader([a, b], [0.5, 0.5])


def test_bad_probabilities(synthetic_dataset):
    with _reader(synthetic_dataset.url) as a:
        with pytest.raises(ValueError):
            WeightedSamplingReader([a], [0.5, 0.5])
        with pytest.raises(ValueError):
            WeightedSamplingReader([a], [-1.0])
        with pytest.raises(ValueError):
            WeightedSamplingReader([], [])


def test_deterministic_with_seed(synthetic_dataset):
    ids_runs = []
    for _ in range(2):
        with _reader(synthetic_dataset.url) as a, \
                _reader(synthetic_dataset.url) as b:
            mix = WeightedSamplingReader([a, b], [0.5, 0.5], seed=7)
            ids_runs.append([next(mix).id for _ in range(50)])
    assert ids_runs[0] == ids_runs[1]
