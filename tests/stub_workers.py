"""Stub workers for runtime tests (model: workers_pool/tests/stub_workers.py)."""

import os
import time

from petastorm_tpu.workers.worker_base import WorkerBase


class IdentityWorker(WorkerBase):
    def process(self, *args, **kwargs):
        for a in args:
            self.publish_func(a)
        for v in kwargs.values():
            self.publish_func(v)


class SleepyIdentityWorker(WorkerBase):
    def process(self, value, sleep_s=0.01):
        time.sleep(sleep_s)
        self.publish_func(value)


class ExceptionOnFiveWorker(WorkerBase):
    """Publishes its input unless it equals 5, then raises."""

    def process(self, value):
        if value == 5:
            raise ValueError('value was 5')
        self.publish_func(value)


class ExitOnFiveWorker(WorkerBase):
    """Publishes its input unless it equals 5, then hard-kills its OWN
    process (``os._exit`` — no exception frame, no BYE, no heartbeat
    goodbye): the deterministic worker-killer fixture for poison-
    quarantine tests. A small sleep keeps other items in flight when
    the kill lands."""

    def process(self, value, sleep_s=0.02):
        if value == 5:
            os._exit(13)
        time.sleep(sleep_s)
        self.publish_func(value)


class MultiplyingWorker(WorkerBase):
    """Uses worker args: publishes value * args['factor']."""

    def process(self, value):
        self.publish_func(value * self.args['factor'])


class SpanningSleepyWorker(WorkerBase):
    """Sleeps under a telemetry 'decode' span, then publishes its input —
    the probe for worker-side metric deltas crossing pool result channels
    (process markers / service DONE messages)."""

    def process(self, value, sleep_s=0.02):
        from petastorm_tpu.telemetry import span
        with span('decode'):
            time.sleep(sleep_s)
        self.publish_func(value)


class TracingProbeWorker(WorkerBase):
    """Publishes ``(item_index, trace_id seen worker-side)`` plus a tiny
    'decode' span — the probe asserting a trace context minted at the
    ventilator arrives ACTIVATED inside any pool flavor's worker and that
    the stage span lands on its timeline."""

    def process(self, item_index=None, sleep_s=0.002):
        from petastorm_tpu.telemetry import span
        from petastorm_tpu.telemetry.tracing import current_trace_id
        with span('decode'):
            time.sleep(sleep_s)
        self.publish_func((item_index, current_trace_id()))
