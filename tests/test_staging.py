"""Staging arena + double-buffered async H2D transfer (jax/staging.py).

Covers the ISSUE's arena-correctness satellite: zero per-batch host
allocations in steady state, slot contents never mutated while the
consumer holds the corresponding device batch, exact-value round-trips of
partial/ragged/bucketed batches against the pre-arena path, the knob
discipline, the aliasing-probe safety valve, the ``h2d_overlap_share``
report surface, and the tier-1-safe ``perf``-marked overhead guard."""

import contextlib
import os
import time
import tracemalloc

import numpy as np
import pytest

import jax

from petastorm_tpu import telemetry as T
from petastorm_tpu.benchmark.dummy_reader import DummyBatchReader
from petastorm_tpu.jax import MASK_FIELD, make_jax_loader
from petastorm_tpu.jax import staging


@contextlib.contextmanager
def _staging_env(**env):
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update({k: v for k, v in env.items() if v is not None})
    for k, v in env.items():
        if v is None:
            os.environ.pop(k, None)
    staging.refresh_staging()
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        staging.refresh_staging()


@pytest.fixture(autouse=True)
def _fresh_knobs():
    staging.refresh_staging()
    yield
    staging.refresh_staging()


def _dummy_factory(fields, batch_size=100, num_batches=8):
    def factory(url, **kw):
        return DummyBatchReader(fields=fields, batch_size=batch_size,
                                num_batches=num_batches)
    return factory


@pytest.fixture(scope='module')
def ragged_dataset(tmp_path_factory):
    """Variable-length token rows (same shape family as
    tests/test_jax_loader.py's fixture) for the ragged/bucketed
    round-trips."""
    import pyarrow as pa

    from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.etl.dataset_metadata import (
        DatasetWriter, materialize_dataset,
    )
    from petastorm_tpu.unischema import Unischema, UnischemaField
    url = 'file://' + str(tmp_path_factory.mktemp('staging_ragged')) + '/ds'
    schema = Unischema('Ragged', [
        UnischemaField('id', np.int32, (), ScalarCodec(pa.int32()), False),
        UnischemaField('tokens', np.int32, (None,), NdarrayCodec(), False),
    ])
    rng = np.random.RandomState(0)
    rows = [{
        'id': i,
        'tokens': rng.randint(0, 100, (3 + i % 9,), dtype=np.int32),
    } for i in range(40)]
    with materialize_dataset(url, schema):
        with DatasetWriter(url, schema, rowgroup_size_rows=8) as writer:
            writer.write_row_dicts(rows)
    return url


# -- knobs --------------------------------------------------------------------


def test_knob_defaults_and_refresh():
    with _staging_env(PETASTORM_TPU_STAGING=None,
                      PETASTORM_TPU_STAGING_SLOTS=None):
        assert staging.staging_enabled()
        assert staging.staging_slots() == 2
    with _staging_env(PETASTORM_TPU_STAGING='0',
                      PETASTORM_TPU_STAGING_SLOTS='5'):
        assert not staging.staging_enabled()
        assert staging.staging_slots() == 5
        assert staging.make_stager(8, {}, 'drop', lambda x: x) is None
    # floor of 2 and unparseable values degrade safely
    with _staging_env(PETASTORM_TPU_STAGING_SLOTS='1'):
        assert staging.staging_slots() == 2
    with _staging_env(PETASTORM_TPU_STAGING_SLOTS='bogus'):
        assert staging.staging_slots() == 2


def test_shared_telemetry_refresh_covers_staging_knobs():
    """telemetry.refresh() is the documented one-stop knob re-read; the
    staging knobs must flip through it too, not only through the
    module-private refresh_staging()."""
    assert staging.staging_enabled()
    saved = os.environ.get('PETASTORM_TPU_STAGING')
    os.environ['PETASTORM_TPU_STAGING'] = '0'
    try:
        T.refresh()
        assert not staging.staging_enabled()
    finally:
        if saved is None:
            os.environ.pop('PETASTORM_TPU_STAGING', None)
        else:
            os.environ['PETASTORM_TPU_STAGING'] = saved
        T.refresh()


# -- zero-allocation steady state --------------------------------------------


class _AcceleratorLeaf:
    """Device-array stand-in that copies on construction (what a real
    transfer does) and claims a non-host platform, pinning the engine's
    ring mode on the CPU test host."""

    def __init__(self, arr):
        self.value = np.array(arr, copy=True)

    def devices(self):
        class _Dev:
            platform = 'tpu'
        return (_Dev(),)

    def block_until_ready(self):
        return self


def _accelerator_put(tree):
    return {name: _AcceleratorLeaf(arr) for name, arr in tree.items()}


def test_steady_state_performs_no_per_batch_host_allocations():
    """The acceptance-gate test (ring mode, the accelerator regime):
    after warmup, staging N more batches allocates no new host batch
    buffers — tracemalloc growth attributed to staging.py stays far below
    even ONE batch's bytes (a per-batch allocation regression would show
    ~N batches' worth), and the slot slab count does not move."""
    bs = 64
    eng = staging.StagingEngine(bs, {'b': np.float32}, 'pad',
                                _accelerator_put, num_slots=2)
    rng = np.random.RandomState(0)
    cols = {'a': rng.rand(bs, 256).astype(np.float32),
            'b': rng.rand(bs, 16)}                      # f64 → f32 cast
    batch_bytes = cols['a'].nbytes + cols['b'].nbytes
    for _ in range(4):
        eng.stage(dict(cols), bs)
    assert eng._host_backed is False      # ring mode engaged
    slabs_after_warmup = eng.slabs_allocated
    n = 50
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    for _ in range(n):
        eng.stage(dict(cols), bs)
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    grown = sum(
        max(0, s.size_diff)
        for s in after.compare_to(before, 'filename')
        if s.traceback and s.traceback[0].filename.endswith(
            os.path.join('petastorm_tpu', 'jax', 'staging.py')))
    assert eng.slabs_allocated == slabs_after_warmup == 2
    # bookkeeping (signature tuples, span objects) is KBs; n re-allocated
    # batches would be ~n * batch_bytes (13 MB here)
    assert grown < batch_bytes / 2, \
        'staging.py allocated %d bytes over %d steady-state batches' \
        % (grown, n)


def test_loader_slot_slabs_stop_growing_after_startup():
    # the dtype cast routes every batch through the slot path (a no-cast
    # single-chunk batch would take the even cheaper direct dispatch)
    fields = {'x': ((32,), np.float64)}
    with make_jax_loader('dummy://', batch_size=25,
                         dtypes={'x': np.float32},
                         reader_factory=_dummy_factory(fields,
                                                       num_batches=12)) \
            as loader:
        it = iter(loader)
        for _ in range(4):
            next(it)
        # the first assembled batch allocates one ring (2 slots); the CPU
        # target then retires it for fresh assembly — either way the slab
        # count must never grow with the batch count
        slabs = loader.diagnostics['staging_slots_allocated']
        assert slabs == 2
        for _ in range(20):
            next(it)
        assert loader.diagnostics['staging_slots_allocated'] == slabs
        assert loader.diagnostics['staging_enabled']


def test_single_chunk_uncast_batches_dispatch_direct():
    """A batch that is one chunk view with no cast/pad takes the direct
    (no-slot, no-copy) path: values round-trip and no slot is ever
    allocated."""
    fields = {'x': ((8,), np.float32)}
    with make_jax_loader('dummy://', batch_size=25,
                         reader_factory=_dummy_factory(fields,
                                                       num_batches=4)) \
            as loader:
        batches = list(loader)
    assert len(batches) == 16
    assert loader.diagnostics['staging_slots_allocated'] == 0


# -- slot stability under a live consumer ------------------------------------


class _AsyncLeaf:
    """Device-array stand-in with a DEFERRED transfer: it keeps a VIEW of
    the host buffer and materializes its value only at
    ``block_until_ready`` — exactly an in-flight DMA. If the engine ever
    refilled a slot before awaiting that slot's previous handoff, the
    late materialization would capture the NEXT batch's bytes."""

    def __init__(self, view):
        self._view = view
        self.value = None

    def devices(self):
        class _Dev:
            platform = 'tpu'
        return (_Dev(),)

    def block_until_ready(self):
        if self.value is None:
            self.value = np.array(self._view, copy=True)
        return self


def test_slot_never_refilled_while_its_transfer_is_in_flight():
    """Ring mode: recycling is gated on the slot's PREVIOUS handoff
    completing. The deferred-transfer mock proves the ordering: every
    delivered batch's eventual value matches its source even though the
    two slots are recycled ~4 times over."""
    bs = 16
    eng = staging.StagingEngine(bs, {'v': np.float32}, 'drop',
                                lambda tree: {k: _AsyncLeaf(v)
                                              for k, v in tree.items()},
                                num_slots=2)
    rng = np.random.RandomState(1)
    sources, held = [], []
    for i in range(9):
        cols = {'v': rng.rand(bs, 8) + i}              # f64 → f32 cast
        sources.append(cols['v'].astype(np.float32))
        held.append(eng.stage(cols, bs))
    assert eng._host_backed is False and eng.slabs_allocated == 2
    for src, batch in zip(sources, held):
        np.testing.assert_array_equal(
            batch['v'].block_until_ready().value, src)


def test_loader_holds_all_batches_values_intact(scalar_dataset):
    with make_jax_loader(scalar_dataset.url, batch_size=16,
                         fields=['^id$', '^float64$'],
                         shuffle_row_groups=False) as loader:
        batches = list(loader)           # consumer holds ALL handoffs
        copies = [{k: np.asarray(v).copy() for k, v in b.items()}
                  for b in batches]
    assert len(batches) == 6
    seen_ids = sorted(np.concatenate([c['id'] for c in copies]).tolist())
    assert len(set(seen_ids)) == 96
    # re-read the still-held device arrays: recycling never touched them
    for b, c in zip(batches, copies):
        for name in b:
            np.testing.assert_array_equal(np.asarray(b[name]), c[name])


# -- exact-value round-trips vs the pre-arena path ---------------------------


def _collect(url, enabled, **kw):
    with _staging_env(PETASTORM_TPU_STAGING='1' if enabled else '0'):
        with make_jax_loader(url, shuffle_row_groups=False, **kw) as loader:
            return [{k: np.asarray(v).copy() for k, v in b.items()}
                    for b in loader]


def _assert_same(batches_a, batches_b):
    assert len(batches_a) == len(batches_b)
    for a, b in zip(batches_a, batches_b):
        assert sorted(a) == sorted(b)
        for name in a:
            np.testing.assert_array_equal(a[name], b[name], err_msg=name)
            assert a[name].dtype == b[name].dtype, name


@pytest.mark.parametrize('kw', [
    dict(batch_size=16, last_batch='pad', fields=['^id$', '^float64$']),
    dict(batch_size=16, last_batch='short', fields=['^id$', '^float64$']),
    dict(batch_size=16, last_batch='drop',
         fields=['^id$', '^float64$', '^int32$'],
         dtypes={'float64': np.float32, 'int32': np.int64}),
], ids=['pad-tail', 'short-tail', 'dtype-cast'])
def test_round_trip_matches_pre_arena_path(scalar_dataset, kw):
    arena = _collect(scalar_dataset.url, True, **kw)
    legacy = _collect(scalar_dataset.url, False, **kw)
    _assert_same(arena, legacy)
    if kw['last_batch'] == 'pad':
        assert MASK_FIELD in arena[-1]
        assert not np.asarray(arena[-1][MASK_FIELD])[-1]


def test_ragged_round_trip_matches_pre_arena_path(ragged_dataset):
    kw = dict(batch_size=8, pad_ragged={'tokens': 8}, last_batch='pad')
    _assert_same(_collect(ragged_dataset, True, **kw),
                 _collect(ragged_dataset, False, **kw))


def test_mixed_dtype_parts_promote_like_concatenate():
    """Regression (review finding): a batch spanning chunks of different
    dtypes must PROMOTE like the legacy ``np.concatenate`` — keying the
    slot on the first chunk's dtype would wrap an int64 value into an
    int32 buffer silently."""
    eng = staging.StagingEngine(4, {}, 'drop', jax.device_put, num_slots=2)
    # int16 + int32 promote to int32 (int64 would be re-narrowed by
    # jax's x64-disabled device_put — on the legacy path too)
    out = eng.stage([{'v': np.array([1, 2], np.int16)},
                     {'v': np.array([2 ** 30, 5], np.int32)}], 4)
    arr = np.asarray(out['v'])
    assert arr.dtype == np.int32
    np.testing.assert_array_equal(arr, [1, 2, 2 ** 30, 5])


def test_shape_mismatched_chunk_raises_instead_of_broadcasting():
    """Regression (review finding): np.copyto would BROADCAST a narrower
    chunk into the slot — e.g. a (m, 1) chunk replicated across a
    (m, 16) slot — where the legacy np.concatenate raised. The fill must
    reject the mismatch loudly."""
    eng = staging.StagingEngine(6, {}, 'drop', jax.device_put, num_slots=2)
    ok = np.ones((3, 16), np.float32)
    bad = np.ones((3, 1), np.float32)
    with pytest.raises(ValueError, match='pad_ragged'):
        eng.stage([{'v': ok}, {'v': bad}], 6)


def test_pass_end_releases_slabs_and_in_flight_refs():
    """Regression (review finding): the per-pass stager must drop its
    slot slabs (and the device-array refs they pin) when the pass ends —
    an idle loader between epochs must not hold batches in memory."""
    fields = {'x': ((16,), np.float64)}
    with make_jax_loader('dummy://', batch_size=25,
                         dtypes={'x': np.float32},
                         reader_factory=_dummy_factory(fields,
                                                       num_batches=4)) \
            as loader:
        list(loader)                       # consume the pass to its end
        assert loader._stager is not None
        assert loader._stager._rings == {}
        # replay still works after the release (fresh arena per pass)
        assert len(list(loader)) == 16


def test_bucketed_round_trip_matches_pre_arena_path(ragged_dataset):
    kw = dict(batch_size=4, bucket_boundaries={'tokens': [4, 8, 16]},
              last_batch='short')
    arena = _collect(ragged_dataset, True, **kw)
    legacy = _collect(ragged_dataset, False, **kw)
    _assert_same(arena, legacy)
    # bucketing produced more than one emitted width → more than one ring
    widths = {b['tokens'].shape[1] for b in arena}
    assert len(widths) > 1


# -- host-backed zero-copy safety --------------------------------------------


def test_host_backed_target_retires_the_ring():
    """Regression: XLA:CPU zero-copies suitably-aligned host arrays into
    device handles, so a recycled slot could corrupt a batch the
    consumer still holds (observed nondeterministically — alignment is
    per-allocation luck). On a host-backed target the engine must
    abandon the ring after its first dispatch and assemble every later
    batch into fresh buffers; all delivered values stay intact."""
    bs, w = 8, 16
    eng = staging.StagingEngine(bs, {}, 'drop', jax.device_put,
                                num_slots=2)
    base = np.arange(bs * w, dtype=np.float32).reshape(bs, w)
    # two-part batches force the assembly path (a single ready chunk
    # would take the direct no-copy dispatch)
    held = [eng.stage([{'v': (base + i)[:5]}, {'v': (base + i)[5:]}], bs)
            for i in range(8)]
    assert eng._host_backed is True
    assert eng._rings == {}            # ring retired, never recycled
    assert eng.slabs_allocated == 2    # only the first batch's ring
    for i, b in enumerate(held):
        np.testing.assert_array_equal(np.asarray(b['v']), base + i)


def test_unknown_array_types_default_to_fresh_assembly():
    """A put_fn returning arrays without a ``devices()`` surface counts
    as host-backed: fresh assembly is the always-correct strategy, and
    an always-aliasing runtime stays safe because buffers are never
    reused."""
    class _AliasedLeaf:
        def __init__(self, view):
            self.view = view

        def block_until_ready(self):
            return self

    eng = staging.StagingEngine(4, {}, 'drop',
                                lambda tree: {k: _AliasedLeaf(v)
                                              for k, v in tree.items()},
                                num_slots=2)
    rng = np.random.RandomState(0)
    held, sources = [], []
    for i in range(6):
        cols = {'v': rng.rand(4, 3).astype(np.float32)}
        sources.append(cols['v'].copy())
        held.append(eng.stage([{'v': cols['v'][:2]},
                               {'v': cols['v'][2:]}], 4))
    assert eng._host_backed is True
    # aliased handoffs were never overwritten by later fills
    for src, batch in zip(sources, held):
        np.testing.assert_array_equal(np.asarray(batch['v'].view), src)


# -- report surface -----------------------------------------------------------


def test_pipeline_report_surfaces_h2d_overlap_share():
    T.reset_for_tests()
    try:
        fields = {'x': ((16,), np.float32)}
        # batch 75 over 100-row chunks: chunk-spanning batches take the
        # slot path (stage_fill/h2d_ready), chunk-view batches the direct
        # path (h2d_dispatch only) — the report must cover both
        with make_jax_loader('dummy://', batch_size=75,
                             reader_factory=_dummy_factory(fields)) as loader:
            for _ in loader:
                pass
            report = loader.pipeline_report()
        assert 0.0 <= report['h2d_overlap_share'] <= 1.0
        assert 'h2d overlap' in T.format_pipeline_report(report)
        reg = T.get_registry()
        assert reg.counter_value(staging.H2D_BYTES) > 0
        # host-backed run: fresh assembly (fill) + async dispatch; the
        # ring's h2d_ready gate appears only on accelerator targets
        # (covered by test_ring_mode_records_h2d_ready)
        for stage in ('stage_fill', 'h2d_dispatch'):
            assert stage in report['stages'], report['stages'].keys()
    finally:
        T.reset_for_tests()


def test_ring_mode_records_h2d_ready():
    T.reset_for_tests()
    try:
        eng = staging.StagingEngine(8, {'v': np.float32}, 'drop',
                                    _accelerator_put, num_slots=2)
        rng = np.random.RandomState(0)
        for _ in range(5):
            eng.stage({'v': rng.rand(8, 4)}, 8)
        report = T.pipeline_report()
        for stage in ('stage_fill', 'h2d_dispatch', 'h2d_ready'):
            assert stage in report['stages'], report['stages'].keys()
        assert 0.0 <= report['h2d_overlap_share'] <= 1.0
    finally:
        T.reset_for_tests()


def test_report_omits_overlap_share_without_the_arena():
    T.reset_for_tests()
    try:
        with _staging_env(PETASTORM_TPU_STAGING='0'):
            fields = {'x': ((16,), np.float32)}
            with make_jax_loader('dummy://', batch_size=50,
                                 reader_factory=_dummy_factory(fields)) \
                    as loader:
                for _ in loader:
                    pass
                report = loader.pipeline_report()
        assert 'h2d_overlap_share' not in report
        assert 'h2d' in report['stages']   # the pre-arena umbrella span
    finally:
        T.reset_for_tests()


# -- perf marker: overhead guard ---------------------------------------------


def _rows_per_sec(enabled):
    # f64→f32 cast keeps the measurement on the arena's slot path (the
    # legacy side pays astype allocations — the copies the arena removes)
    fields = {'x': ((64,), np.float64)}
    with _staging_env(PETASTORM_TPU_STAGING='1' if enabled else '0'):
        with make_jax_loader('dummy://', batch_size=100, num_epochs=None,
                             dtypes={'x': np.float32},
                             reader_factory=_dummy_factory(
                                 fields, num_batches=None)) as loader:
            it = iter(loader)
            for _ in range(20):
                next(it)                       # warm
            n = 300
            start = time.monotonic()
            for _ in range(n):
                batch = next(it)
            next(iter(batch.values())).block_until_ready()
            return n * 100 / (time.monotonic() - start)


@pytest.mark.perf
def test_staging_overhead_guard_vs_disabled():
    """Tier-1-safe budget: the arena path must not regress dummy-reader
    rows/sec below 0.35x the pre-arena path (an order-of-magnitude guard,
    deliberately loose for shared-box noise). One retry absorbs a single
    preempted run."""
    for attempt in range(2):
        on, off = _rows_per_sec(True), _rows_per_sec(False)
        if on >= 0.35 * off:
            return
    pytest.fail('staging on: %.0f rows/s vs off: %.0f rows/s '
                '(budget: >= 0.35x)' % (on, off))
