"""Row-group selector + footer index subsystem, end to end.

Parity target: the reference's selector coverage
(``petastorm/tests/test_end_to_end.py:623-729``) and its indexing suite
(``petastorm/etl/rowgroup_indexing.py:37-158``).
"""

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.errors import MetadataError
from petastorm_tpu.etl.dataset_metadata import ParquetDatasetInfo, write_dataset
from petastorm_tpu.etl.rowgroup_indexers import (
    FieldNotNullIndexer, SingleFieldIndexer,
)
from petastorm_tpu.etl.rowgroup_indexing import (
    build_rowgroup_index, get_row_group_indexes,
)
from petastorm_tpu.selectors import (
    IntersectIndexSelector, SingleIndexSelector, UnionIndexSelector,
)
from petastorm_tpu.unischema import Unischema, UnischemaField

BlockySchema = Unischema('BlockySchema', [
    UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
    # category is constant within each 5-row row-group -> selectors are exact
    UnischemaField('category', np.str_, (), ScalarCodec(pa.string()), False),
    UnischemaField('maybe_vec', np.float32, (2,), NdarrayCodec(), True),
])

N_ROWS = 30
ROWGROUP = 5


def _blocky_row(i):
    return {
        'id': i,
        'category': 'cat_%d' % (i // ROWGROUP),
        # an ENTIRE row-group (ids 5..9) is null -> FieldNotNull is exact
        'maybe_vec': None if 5 <= i < 10 else np.float32([i, i + 0.5]),
    }


@pytest.fixture(scope='module')
def indexed_dataset(tmp_path_factory):
    url = 'file://' + str(tmp_path_factory.mktemp('blocky')) + '/ds'
    rows = [_blocky_row(i) for i in range(N_ROWS)]
    write_dataset(url, BlockySchema, rows, rowgroup_size_rows=ROWGROUP,
                  num_files=2)
    build_rowgroup_index(url, [
        SingleFieldIndexer('category_index', 'category'),
        SingleFieldIndexer('id_index', 'id'),
        FieldNotNullIndexer('vec_not_null', 'maybe_vec'),
    ])
    return url, rows


def _read_ids(url, selector, factory=make_reader, **kwargs):
    with factory(url, rowgroup_selector=selector, shuffle_row_groups=False,
                 **kwargs) as reader:
        if getattr(reader, 'batched_output', False):
            out = []
            for batch in reader:
                out.extend(int(v) for v in batch.id)
            return sorted(out)
        return sorted(int(r.id) for r in reader)


class TestIndexBuildAndLoad:
    def test_round_trip(self, indexed_dataset):
        url, _ = indexed_dataset
        indexes = get_row_group_indexes(ParquetDatasetInfo(url))
        assert set(indexes) == {'category_index', 'id_index', 'vec_not_null'}
        cat = indexes['category_index']
        assert sorted(cat.indexed_values) == ['cat_%d' % i for i in range(6)]
        # one row-group per category by construction
        assert all(len(cat.get_row_group_indexes(v)) == 1
                   for v in cat.indexed_values)
        ids = indexes['id_index']
        assert len(ids.indexed_values) == N_ROWS

    def test_not_null_excludes_all_null_group(self, indexed_dataset):
        url, _ = indexed_dataset
        not_null = get_row_group_indexes(ParquetDatasetInfo(url))['vec_not_null']
        all_groups = set(
            get_row_group_indexes(ParquetDatasetInfo(url))['category_index']
            .get_row_group_indexes('cat_1'))
        assert not_null.get_row_group_indexes() & all_groups == set()
        assert len(not_null.get_row_group_indexes()) == N_ROWS // ROWGROUP - 1

    def test_unindexed_field_rejected(self, indexed_dataset):
        url, _ = indexed_dataset
        with pytest.raises(ValueError, match='not in schema'):
            build_rowgroup_index(url, [SingleFieldIndexer('x', 'no_such_field')])

    def test_indexer_merge(self):
        a = SingleFieldIndexer('m', 'f')
        b = SingleFieldIndexer('m', 'f')
        a.build_index([{'f': 'x'}], 0)
        b.build_index([{'f': 'x'}, {'f': 'y'}], 1)
        merged = a + b
        assert merged.get_row_group_indexes('x') == {0, 1}
        assert merged.get_row_group_indexes('y') == {1}
        with pytest.raises(ValueError):
            a + SingleFieldIndexer('m', 'other')


class TestSelectors:
    def test_single_index_selector(self, indexed_dataset):
        url, _ = indexed_dataset
        got = _read_ids(url, SingleIndexSelector('category_index', ['cat_2']))
        assert got == list(range(10, 15))

    def test_single_selector_multiple_values(self, indexed_dataset):
        url, _ = indexed_dataset
        got = _read_ids(url, SingleIndexSelector('category_index',
                                                 ['cat_0', 'cat_5']))
        assert got == list(range(0, 5)) + list(range(25, 30))

    def test_union_selector(self, indexed_dataset):
        url, _ = indexed_dataset
        sel = UnionIndexSelector([
            SingleIndexSelector('category_index', ['cat_3']),
            SingleIndexSelector('id_index', ['7']),
        ])
        assert _read_ids(url, sel) == list(range(5, 10)) + list(range(15, 20))

    def test_intersect_selector(self, indexed_dataset):
        url, _ = indexed_dataset
        sel = IntersectIndexSelector([
            SingleIndexSelector('category_index', ['cat_1', 'cat_4']),
            SingleIndexSelector('id_index', ['21']),
        ])
        # cat_4 is ids 20..24; only that group also contains id 21
        assert _read_ids(url, sel) == list(range(20, 25))

    def test_intersect_empty(self, indexed_dataset):
        url, _ = indexed_dataset
        sel = IntersectIndexSelector([
            SingleIndexSelector('category_index', ['cat_0']),
            SingleIndexSelector('category_index', ['cat_1']),
        ])
        from petastorm_tpu.errors import NoDataAvailableError
        with pytest.raises(NoDataAvailableError):
            _read_ids(url, sel)

    def test_not_null_selector(self, indexed_dataset):
        url, _ = indexed_dataset
        got = _read_ids(url, SingleIndexSelector('vec_not_null', [None]))
        assert got == list(range(0, 5)) + list(range(10, 30))

    def test_batch_reader_selector(self, indexed_dataset):
        url, _ = indexed_dataset
        got = _read_ids(url, SingleIndexSelector('category_index', ['cat_2']),
                        factory=make_batch_reader)
        assert got == list(range(10, 15))

    @pytest.mark.parametrize('pool', ['thread', 'process', 'dummy'])
    def test_selector_over_all_pools(self, indexed_dataset, pool):
        url, _ = indexed_dataset
        got = _read_ids(url, SingleIndexSelector('category_index', ['cat_4']),
                        reader_pool_type=pool)
        assert got == list(range(20, 25))

    def test_missing_index_name(self, indexed_dataset):
        url, _ = indexed_dataset
        with pytest.raises(ValueError, match='no row-group index named'):
            _read_ids(url, SingleIndexSelector('nope', ['x']))

    def test_dataset_without_index(self, synthetic_dataset, tmp_path):
        url = 'file://' + str(tmp_path / 'noindex')
        write_dataset(url, BlockySchema, [_blocky_row(i) for i in range(10)],
                      rowgroup_size_rows=5)
        with pytest.raises(MetadataError, match='no row-group index'):
            _read_ids(url, SingleIndexSelector('category_index', ['cat_0']))


class TestSyntheticDatasetSelectors:
    """Reference-parity: selectors over the canonical indexed fixture
    (``test_end_to_end.py:623-729`` uses its synthetic dataset the same way)."""

    def test_select_by_id_values(self, synthetic_dataset):
        indexes = get_row_group_indexes(ParquetDatasetInfo(synthetic_dataset.url))
        selected = (set(indexes['id_index'].get_row_group_indexes('2'))
                    | set(indexes['id_index'].get_row_group_indexes('18')))
        got = _read_ids(synthetic_dataset.url,
                        SingleIndexSelector('id_index', ['2', '18']),
                        schema_fields=['^id$'])
        assert {2, 18} <= set(got)
        # exactly the rows living in the selected row-groups
        expected = sorted(
            int(v) for v in indexes['id_index'].indexed_values
            if set(indexes['id_index'].get_row_group_indexes(v)) & selected)
        assert got == expected
        assert len(got) < 100

    def test_partition_index_is_coarse(self, synthetic_dataset):
        # partition_key cycles i%5, so every row-group contains every key:
        # selecting one key still reads the full dataset (row-group
        # granularity, matching the reference's selector semantics)
        got = _read_ids(synthetic_dataset.url,
                        SingleIndexSelector('partition_index', ['p_3']),
                        schema_fields=['^id$'])
        assert got == list(range(100))
