"""TransformSpec tests (parity model: petastorm/tests/test_transform.py)."""

import numpy as np
import pytest

from petastorm_tpu.transform import TransformSpec, transform_schema
from petastorm_tpu.unischema import Unischema, UnischemaField


def _schema():
    return Unischema('S', [
        UnischemaField('a', np.int32, ()),
        UnischemaField('b', np.float32, (4,)),
        UnischemaField('c', np.str_, ()),
    ])


def test_removed_fields():
    spec = TransformSpec(removed_fields=['b'])
    out = transform_schema(_schema(), spec)
    assert list(out.fields) == ['a', 'c']


def test_edit_fields_with_tuples_and_fields():
    spec = TransformSpec(edit_fields=[
        ('b', np.float64, (8,), False),
        UnischemaField('d', np.int8, (), None, True),
    ])
    out = transform_schema(_schema(), spec)
    assert out.b.numpy_dtype is np.float64
    assert out.b.shape == (8,)
    assert out.d.nullable


def test_selected_fields_order():
    spec = TransformSpec(selected_fields=['c', 'a'])
    out = transform_schema(_schema(), spec)
    assert list(out.fields) == ['c', 'a']


def test_selected_missing_raises():
    with pytest.raises(ValueError):
        transform_schema(_schema(), TransformSpec(selected_fields=['zzz']))


def test_removed_and_selected_mutually_exclusive():
    with pytest.raises(ValueError):
        TransformSpec(removed_fields=['a'], selected_fields=['b'])


def test_func_is_applied():
    spec = TransformSpec(func=lambda d: {**d, 'a': d['a'] * 2})
    assert spec({'a': 21})['a'] == 42
    assert TransformSpec()( {'a': 1})['a'] == 1
