"""dp×pp×tp pipelined transformer vs the layered (sequential) model."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # compile-heavy: every test jits on the 8-device mesh

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from petastorm_tpu.models.transformer import (
    TransformerConfig, init_pipelined_transformer_params,
    init_transformer_params, pipelined_transformer_forward,
    pipelined_transformer_train_step, transformer_forward,
)
from petastorm_tpu.parallel.mesh import make_named_mesh


def _config(**kw):
    base = dict(vocab_size=32, d_model=16, n_heads=2, n_layers=4, d_ff=32,
                max_seq_len=8, dtype=jnp.float32)
    base.update(kw)
    return TransformerConfig(**base)


def _restack_as_layered(config, pipelined_params):
    """Rebuild the layered params pytree from stacked stages (same values);
    tree_map indexing handles nested MoE block params too."""
    stages = pipelined_params['stages']
    n_stages, per_stage = jax.tree_util.tree_leaves(stages)[0].shape[:2]
    blocks = []
    for s in range(n_stages):
        for l in range(per_stage):
            blocks.append(jax.tree_util.tree_map(
                lambda leaf: np.asarray(leaf[s, l]), stages))
    out = {name: np.asarray(pipelined_params[name])
           for name in ('embed', 'pos_embed', 'ln_f', 'lm_head')
           if name in pipelined_params}  # rope configs carry no pos_embed
    out['blocks'] = blocks
    return out


def _as_jnp(tree):
    return jax.tree_util.tree_map(
        jnp.asarray, tree, is_leaf=lambda x: isinstance(x, np.ndarray))


@pytest.mark.parametrize('mesh_axes, n_layers', [
    ({'data': 2, 'pipe': 2, 'model': 2}, 4),   # full 3D
    ({'data': 2, 'pipe': 4}, 4),               # dp x pp
    ({'pipe': 8}, 8),                          # pure pp
])
def test_logits_match_layered_forward(mesh_axes, n_layers):
    mesh = make_named_mesh(dict(mesh_axes))
    config = _config(n_layers=n_layers)
    with mesh:
        pipelined = init_pipelined_transformer_params(
            jax.random.PRNGKey(0), config, mesh)
        tokens = jax.device_put(
            jnp.asarray(np.random.RandomState(0)
                        .randint(0, 32, (4, 8), np.int32)),
            NamedSharding(mesh, P('data' if 'data' in mesh_axes else None,
                                  None)))
        got = jax.jit(lambda p, t: pipelined_transformer_forward(
            p, t, config, mesh, n_microbatches=4))(pipelined, tokens)
    layered = _restack_as_layered(config, pipelined)
    want = transformer_forward(
        jax.tree_util.tree_map(jnp.asarray, layered,
                               is_leaf=lambda x: isinstance(x, np.ndarray)),
        jnp.asarray(np.asarray(tokens)), config)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def _moe_setup(n_microbatches, mesh_axes=None, batch=4):
    from petastorm_tpu.models.transformer import (
        pipelined_transformer_forward_with_aux,
    )
    axes = dict(mesh_axes or {'pipe': 2, 'expert': 2})
    n_dev = 1
    for v in axes.values():
        n_dev *= v
    mesh = make_named_mesh(axes, devices=jax.devices()[:n_dev])
    # ample capacity: no token drops either per-microbatch or full-batch,
    # so routing (and hence logits) is EXACTLY microbatching-invariant
    config = _config(n_layers=4, n_experts=4, capacity_factor=8.0)
    with mesh:
        pipelined = init_pipelined_transformer_params(
            jax.random.PRNGKey(0), config, mesh)
        tokens = jax.device_put(
            jnp.asarray(np.random.RandomState(0)
                        .randint(0, 32, (batch, 8), np.int32)),
            NamedSharding(mesh, P('data' if 'data' in axes else None,
                                  None)))
        logits, aux = jax.jit(
            lambda p, t: pipelined_transformer_forward_with_aux(
                p, t, config, mesh, n_microbatches=n_microbatches))(
            pipelined, tokens)
    return config, pipelined, tokens, logits, aux


def test_moe_pipelined_logits_and_aux_match_layered():
    # pp×ep at one microbatch: every stage sees the FULL batch, so both
    # logits AND the Switch aux loss must equal the layered oracle exactly
    from petastorm_tpu.models.transformer import transformer_forward_with_aux
    config, pipelined, tokens, logits, aux = _moe_setup(n_microbatches=1)
    layered = _restack_as_layered(config, pipelined)
    want_logits, want_aux = transformer_forward_with_aux(
        _as_jnp(layered), jnp.asarray(np.asarray(tokens)), config)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want_logits),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(float(aux), float(want_aux), rtol=1e-5)
    assert float(aux) > 0.0


def test_moe_pipelined_microbatched_logits_still_exact():
    # with ample capacity, routing decisions are per-token: microbatching
    # must not move the logits; the aux becomes the per-microbatch
    # estimator (close to, not equal to, the full-batch statistic)
    from petastorm_tpu.models.transformer import transformer_forward_with_aux
    config, pipelined, tokens, logits, aux = _moe_setup(n_microbatches=4)
    layered = _restack_as_layered(config, pipelined)
    want_logits, want_aux = transformer_forward_with_aux(
        _as_jnp(layered), jnp.asarray(np.asarray(tokens)), config)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want_logits),
                               atol=2e-4, rtol=2e-4)
    assert np.isfinite(float(aux)) and float(aux) > 0.0
    # per-microbatch load statistics estimate the full-batch aux
    assert abs(float(aux) - float(want_aux)) / float(want_aux) < 0.5


def test_moe_pipelined_dp_pp_ep_matches_layered():
    """The FULL 3D MoE composition (VERDICT r3 #4). This mesh used to
    CHECK-crash XLA's SPMD partitioner on the router's take_along_axis
    gather (spmd_partitioner_util.cc:495 — docs/troubleshoot.md); the
    gather-free one-hot routing in models/moe.py is what makes it
    compile, and this test pins both the compile and the numerics."""
    from petastorm_tpu.models.transformer import transformer_forward_with_aux
    config, pipelined, tokens, logits, aux = _moe_setup(
        n_microbatches=1, mesh_axes={'data': 2, 'pipe': 2, 'expert': 2})
    layered = _restack_as_layered(config, pipelined)
    want_logits, want_aux = transformer_forward_with_aux(
        _as_jnp(layered), jnp.asarray(np.asarray(tokens)), config)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want_logits),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(float(aux), float(want_aux), rtol=1e-5)


def test_moe_pipelined_dp_pp_ep_train_step():
    mesh = make_named_mesh({'data': 2, 'pipe': 2, 'expert': 2})
    config = _config(n_layers=2, n_experts=4, capacity_factor=4.0)
    with mesh:
        params = init_pipelined_transformer_params(jax.random.PRNGKey(3),
                                                   config, mesh)
        optimizer = optax.adam(1e-2)
        step = pipelined_transformer_train_step(config, optimizer, mesh,
                                                n_microbatches=2)
        tokens = jax.device_put(
            jnp.asarray(np.random.RandomState(4)
                        .randint(0, 32, (4, 9), np.int32)),
            NamedSharding(mesh, P('data', None)))
        _, _, loss = step(params, optimizer.init(params), tokens)
    assert np.isfinite(float(loss))


def test_moe_pipelined_train_step_learns():
    mesh = make_named_mesh({'pipe': 2, 'expert': 4})
    config = _config(n_layers=2, n_experts=4, capacity_factor=4.0)
    with mesh:
        params = init_pipelined_transformer_params(jax.random.PRNGKey(1),
                                                   config, mesh)
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        step = pipelined_transformer_train_step(config, optimizer, mesh)
        tokens = jax.device_put(
            jnp.asarray(np.random.RandomState(2)
                        .randint(0, 32, (4, 9), np.int32)),
            NamedSharding(mesh, P(None, None)))
        first = None
        for _ in range(8):
            params, opt_state, loss = step(params, opt_state, tokens)
            first = float(loss) if first is None else first
    assert np.isfinite(float(loss))
    assert float(loss) < first


def test_moe_pipelined_on_dp_pp_mesh_with_replicated_experts():
    # a mesh WITHOUT the expert axis still runs the MoE pipeline (experts
    # replicate, _restrict_spec_to_mesh); this is the dp×pp MoE shape
    from petastorm_tpu.models.transformer import transformer_forward_with_aux
    config, pipelined, tokens, logits, aux = _moe_setup(
        n_microbatches=2, mesh_axes={'data': 2, 'pipe': 2})
    layered = _restack_as_layered(config, pipelined)
    want_logits, _ = transformer_forward_with_aux(
        _as_jnp(layered), jnp.asarray(np.asarray(tokens)), config)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want_logits),
                               atol=2e-4, rtol=2e-4)


def test_moe_expert_sharding_lands_in_stages():
    mesh = make_named_mesh({'pipe': 2, 'expert': 2},
                           devices=jax.devices()[:4])
    config = _config(n_layers=2, n_experts=4)
    with mesh:
        params = init_pipelined_transformer_params(jax.random.PRNGKey(0),
                                                   config, mesh)
    w_in = params['stages']['moe']['w_in']
    # (n_stages, per_stage, E, d_model, d_ff): pipe on stages, experts
    # sharded over the expert axis
    assert w_in.shape == (2, 1, 4, 16, 32)
    spec = tuple(w_in.sharding.spec)
    assert spec[0] == 'pipe'
    assert 'expert' in spec


@pytest.mark.parametrize('seq_impl', ['ring', 'ulysses'])
@pytest.mark.parametrize('mesh_axes', [
    {'pipe': 2, 'seq': 2},
    {'data': 2, 'pipe': 2, 'seq': 2},
])
def test_seq_parallel_pipelined_forward_matches_dense_oracle(seq_impl,
                                                             mesh_axes):
    # pp×sp (and dp×pp×sp): the pipeline shard_map goes manual over pipe
    # AND seq; attention inside each stage runs the ring/Ulysses
    # per-device body. The oracle is the layered model with DENSE
    # attention — sharded-sequence attention must be exact, not merely
    # self-consistent.
    import dataclasses
    n_dev = int(np.prod(list(mesh_axes.values())))
    mesh = make_named_mesh(dict(mesh_axes), devices=jax.devices()[:n_dev])
    config = _config(n_layers=2, seq_axis='seq', seq_impl=seq_impl)
    with mesh:
        pipelined = init_pipelined_transformer_params(
            jax.random.PRNGKey(0), config, mesh)
        tokens = jnp.asarray(np.random.RandomState(0)
                             .randint(0, 32, (4, 8), np.int32))
        got = jax.jit(lambda p, t: pipelined_transformer_forward(
            p, t, config, mesh, n_microbatches=2))(pipelined, tokens)
    layered = _restack_as_layered(config, pipelined)
    oracle_cfg = dataclasses.replace(config, seq_axis=None)
    want = transformer_forward(_as_jnp(layered), tokens, oracle_cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize('seq_impl', ['ring', 'ulysses'])
def test_seq_parallel_pipelined_train_step_matches_oracle(seq_impl):
    # gradients flow through ppermute (pipe) AND the seq collectives:
    # loss and updated params must equal the sequential dense model's
    import dataclasses
    from petastorm_tpu.models.transformer import transformer_train_step
    mesh = make_named_mesh({'pipe': 2, 'seq': 2},
                           devices=jax.devices()[:4])
    config = _config(n_layers=2, seq_axis='seq', seq_impl=seq_impl)
    optimizer = optax.adamw(1e-3)
    with mesh:
        pipelined = init_pipelined_transformer_params(
            jax.random.PRNGKey(0), config, mesh)
        step = pipelined_transformer_train_step(config, optimizer, mesh,
                                                n_microbatches=2)
        tokens = jnp.asarray(np.random.RandomState(0)
                             .randint(0, 32, (4, 9), np.int32))
        p2, _, loss = step(pipelined, optimizer.init(pipelined), tokens)
    layered = _as_jnp(_restack_as_layered(config, pipelined))
    oracle_cfg = dataclasses.replace(config, seq_axis=None)
    oracle_step = transformer_train_step(oracle_cfg, optimizer)
    lp2, _, want_loss = oracle_step(layered, optimizer.init(layered), tokens)
    np.testing.assert_allclose(float(loss), float(want_loss), atol=1e-4)
    np.testing.assert_allclose(np.asarray(p2['lm_head']),
                               np.asarray(lp2['lm_head']),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(p2['embed']),
                               np.asarray(lp2['embed']),
                               atol=2e-4, rtol=2e-4)


def test_seq_parallel_moe_pipelined_matches_layered():
    """pp×sp×ep: Switch routing goes local-per-seq-shard (exact under
    ample capacity) and the aux statistics psum over the seq axis, so at
    one microbatch BOTH logits and aux equal the layered full-sequence
    oracle exactly."""
    from petastorm_tpu.models.transformer import (
        pipelined_transformer_forward_with_aux, transformer_forward_with_aux,
    )
    import dataclasses
    mesh = make_named_mesh({'pipe': 2, 'seq': 2, 'expert': 2})
    config = _config(n_layers=4, seq_axis='seq', n_experts=4,
                     capacity_factor=8.0)
    with mesh:
        pipelined = init_pipelined_transformer_params(jax.random.PRNGKey(0),
                                                      config, mesh)
        tokens = jnp.asarray(np.random.RandomState(0)
                             .randint(0, 32, (4, 8), np.int32))
        logits, aux = jax.jit(
            lambda p, t: pipelined_transformer_forward_with_aux(
                p, t, config, mesh, n_microbatches=1))(pipelined, tokens)
    layered = _restack_as_layered(config, pipelined)
    want_logits, want_aux = transformer_forward_with_aux(
        _as_jnp(layered), tokens,
        dataclasses.replace(config, seq_axis=None))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want_logits),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(float(aux), float(want_aux), rtol=1e-5)


def test_seq_parallel_moe_pipelined_train_step_matches_oracle():
    # gradients flow through ppermute (pipe), the ring-attention seq
    # collectives AND the psum of the routing statistics over 'seq': at
    # one microbatch with ample capacity, loss and updated params must
    # equal the sequential layered model's (a mis-scaled cotangent
    # through the aux psum would show here, not just as a finite loss)
    import dataclasses
    from petastorm_tpu.models.transformer import transformer_train_step
    mesh = make_named_mesh({'pipe': 2, 'seq': 2, 'expert': 2})
    config = _config(n_layers=2, seq_axis='seq', n_experts=4,
                     capacity_factor=8.0)
    optimizer = optax.adamw(1e-3)
    with mesh:
        pipelined = init_pipelined_transformer_params(jax.random.PRNGKey(1),
                                                      config, mesh)
        step = pipelined_transformer_train_step(config, optimizer, mesh,
                                                n_microbatches=1)
        # post-shift seq = 8, divisible by the 2-way seq axis
        tokens = jnp.asarray(np.random.RandomState(2)
                             .randint(0, 32, (4, 9), np.int32))
        p2, _, loss = step(pipelined, optimizer.init(pipelined), tokens)
    layered = _as_jnp(_restack_as_layered(config, pipelined))
    oracle_cfg = dataclasses.replace(config, seq_axis=None)
    oracle_step = transformer_train_step(oracle_cfg, optimizer)
    lp2, _, want_loss = oracle_step(layered, optimizer.init(layered), tokens)
    np.testing.assert_allclose(float(loss), float(want_loss), atol=1e-4)
    np.testing.assert_allclose(np.asarray(p2['lm_head']),
                               np.asarray(lp2['lm_head']),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(p2['embed']),
                               np.asarray(lp2['embed']),
                               atol=2e-4, rtol=2e-4)


def test_stage_and_tp_shardings_land():
    mesh = make_named_mesh({'data': 2, 'pipe': 2, 'model': 2})
    config = _config(n_layers=2)
    with mesh:
        params = init_pipelined_transformer_params(jax.random.PRNGKey(0),
                                                   config, mesh)
    qkv = params['stages']['qkv']
    assert qkv.shape == (2, 1, 16, 48)
    spec = qkv.sharding.spec
    assert spec[0] == 'pipe'
    # the Megatron column split must land on qkv's LAST dim (d_model, 3*d)
    assert tuple(spec)[-1] == 'model'


def test_train_step_learns_3d():
    mesh = make_named_mesh({'data': 2, 'pipe': 2, 'model': 2})
    config = _config(n_layers=2)
    with mesh:
        params = init_pipelined_transformer_params(jax.random.PRNGKey(1),
                                                   config, mesh)
        optimizer = optax.adam(1e-2)
        opt_state = optimizer.init(params)
        step = pipelined_transformer_train_step(config, optimizer, mesh)
        tokens = jax.device_put(
            jnp.asarray(np.random.RandomState(2)
                        .randint(0, 32, (4, 9), np.int32)),
            NamedSharding(mesh, P('data', None)))
        first = None
        for _ in range(8):
            params, opt_state, loss = step(params, opt_state, tokens)
            first = float(loss) if first is None else first
    assert np.isfinite(float(loss))
    assert float(loss) < first


def test_gradients_match_layered():
    # pp grads == layered grads: compare the stacked qkv grad against the
    # layered model's per-block qkv grads
    from petastorm_tpu.models.transformer import transformer_loss
    mesh = make_named_mesh({'pipe': 4}, devices=jax.devices()[:4])
    config = _config(n_layers=4)
    tokens = jnp.asarray(np.random.RandomState(3)
                         .randint(0, 32, (4, 9), np.int32))
    with mesh:
        pipelined = init_pipelined_transformer_params(jax.random.PRNGKey(4),
                                                      config, mesh)

        def pipe_loss(params):
            logits = pipelined_transformer_forward(params, tokens[:, :-1],
                                                   config, mesh)
            targets = tokens[:, 1:]
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(
                logp, targets[..., None], axis=-1)[..., 0].mean()

        pipe_grads = jax.jit(jax.grad(pipe_loss))(pipelined)

    layered = jax.tree_util.tree_map(
        jnp.asarray, _restack_as_layered(config, pipelined),
        is_leaf=lambda x: isinstance(x, np.ndarray))
    layered_grads = jax.grad(
        lambda p: transformer_loss(p, tokens, config))(layered)

    got_qkv = np.asarray(pipe_grads['stages']['qkv']).reshape(4, 16, 48)
    want_qkv = np.stack([np.asarray(b['qkv'])
                         for b in layered_grads['blocks']])
    np.testing.assert_allclose(got_qkv, want_qkv, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(pipe_grads['embed']),
                               np.asarray(layered_grads['embed']),
                               atol=2e-5, rtol=2e-5)


def test_indivisible_layers_rejected():
    mesh = make_named_mesh({'pipe': 8})
    with pytest.raises(ValueError, match='not divisible'):
        init_pipelined_transformer_params(jax.random.PRNGKey(0),
                                          _config(n_layers=6), mesh)





def test_bf16_pipelined_step_on_pipe_mesh():
    # the PRODUCTION dtype through the pipeline: historically XLA:CPU
    # crashed compiling ANY bf16 pipelined step ('Invalid binary
    # instruction opcode copy'); on current jaxlib only the 3-axis
    # dp x pp x tp bf16 combination still does (docs/troubleshoot.md).
    # Keep the working pipe-only bf16 case covered so a regression to the
    # old blanket crash is caught on the CPU mesh.
    mesh = make_named_mesh({'pipe': 2}, devices=jax.devices()[:2])
    config = _config(n_layers=2, dtype=jnp.bfloat16)
    with mesh:
        params = init_pipelined_transformer_params(jax.random.PRNGKey(1),
                                                   config, mesh)
        optimizer = optax.adam(1e-2)
        step = pipelined_transformer_train_step(config, optimizer, mesh,
                                                n_microbatches=2)
        tokens = jax.device_put(
            jnp.asarray(np.random.RandomState(2)
                        .randint(0, 32, (4, 9), np.int32)),
            NamedSharding(mesh, P(None, None)))
        _, _, loss = step(params, optimizer.init(params), tokens)
    assert np.isfinite(float(loss))


@pytest.mark.parametrize('seq_impl', ['ring', 'ulysses'])
def test_rope_seq_parallel_pipelined_matches_dense_oracle(seq_impl):
    # rope + pp×sp: inside the pipeline's manual region each seq shard
    # sees only LOCAL positions, so the rotation must add the shard's
    # global offset (lax.axis_index) — this oracle comparison is exactly
    # the test that catches a local-positions bug.
    import dataclasses
    mesh = make_named_mesh({'pipe': 2, 'seq': 2},
                           devices=jax.devices()[:4])
    config = _config(n_layers=2, seq_axis='seq', seq_impl=seq_impl,
                     pos_encoding='rope')
    with mesh:
        pipelined = init_pipelined_transformer_params(
            jax.random.PRNGKey(0), config, mesh)
        tokens = jnp.asarray(np.random.RandomState(0)
                             .randint(0, 32, (4, 8), np.int32))
        got = jax.jit(lambda p, t: pipelined_transformer_forward(
            p, t, config, mesh, n_microbatches=2))(pipelined, tokens)
    layered = _restack_as_layered(config, pipelined)
    oracle_cfg = dataclasses.replace(config, seq_axis=None)
    want = transformer_forward(_as_jnp(layered), tokens, oracle_cfg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)
