"""Runtime tests across pool flavors (model: workers_pool/tests/test_workers_pool.py,
test_ventilator.py)."""

import contextlib
import threading
import time

import pytest

from petastorm_tpu.service import ServicePool
from petastorm_tpu.workers import EmptyResultError
from petastorm_tpu.workers.dummy_pool import DummyPool
from petastorm_tpu.workers.thread_pool import ThreadPool
from petastorm_tpu.workers.ventilator import ConcurrentVentilator
from tests.stub_workers import (
    ExceptionOnFiveWorker, IdentityWorker, MultiplyingWorker, SleepyIdentityWorker,
)

from petastorm_tpu.workers.process_pool import ProcessPool


def _service_pool():
    # Localhost worker-server fleet over real tcp://: the drop-in contract
    # proof for the disaggregated pool (docs/service.md).
    return ServicePool(spawn_local_workers=2, heartbeat_interval_s=0.25,
                       connect_timeout_s=60, no_workers_timeout_s=20)


POOLS = [
    pytest.param(lambda: ThreadPool(1), id='thread-1'),
    pytest.param(lambda: ThreadPool(4), id='thread-4'),
    pytest.param(lambda: DummyPool(), id='dummy'),
    pytest.param(lambda: ProcessPool(2), id='process-2'),
    pytest.param(_service_pool, id='service-2', marks=pytest.mark.service),
]


# No pytest-timeout in this environment: every get_results in the pool
# matrix carries an internal deadline so a wedged pool FAILS fast instead
# of hanging the quick tier-1 profile (the contract promised by the
# `service` marker note in pytest.ini).
_RESULT_TIMEOUT_S = 60


def _drain(pool):
    out = []
    while True:
        try:
            out.append(pool.get_results(timeout=_RESULT_TIMEOUT_S))
        except EmptyResultError:
            return out


@contextlib.contextmanager
def _stopped_on_exit(pool):
    """stop()/join() even when an assertion fails mid-test: a leaked
    service pool would keep spawned worker-server subprocesses and a bound
    tcp port alive for the rest of the pytest run. Safe after an error
    path that already stopped the pool (join is idempotent)."""
    try:
        yield pool
    finally:
        pool.stop()
        pool.join()


@pytest.mark.parametrize('make_pool', POOLS)
def test_identity_roundtrip(make_pool):
    with _stopped_on_exit(make_pool()) as pool:
        pool.start(IdentityWorker)
        for i in range(20):
            pool.ventilate(i)
        results = sorted(_drain(pool))
        assert results == list(range(20))
        # gauge-name parity across every pool flavor: dashboards and the
        # autotune advice read the same keys whether decode is local or
        # remote
        diag = pool.diagnostics
        assert diag['items_inflight'] == 0
        assert diag['workers_alive'] >= 1


@pytest.mark.parametrize('make_pool', POOLS)
def test_worker_args(make_pool):
    with _stopped_on_exit(make_pool()) as pool:
        pool.start(MultiplyingWorker, worker_args={'factor': 3})
        for i in range(5):
            pool.ventilate(i)
        assert sorted(_drain(pool)) == [0, 3, 6, 9, 12]


@pytest.mark.parametrize('make_pool', POOLS)
def test_exception_propagates_to_consumer(make_pool):
    with _stopped_on_exit(make_pool()) as pool:
        pool.start(ExceptionOnFiveWorker)
        for i in range(10):
            pool.ventilate(i)
        with pytest.raises(ValueError, match='value was 5'):
            while True:
                pool.get_results(timeout=_RESULT_TIMEOUT_S)


@pytest.mark.parametrize('make_pool', POOLS)
def test_empty_pool_raises_empty_result(make_pool):
    with _stopped_on_exit(make_pool()) as pool:
        pool.start(IdentityWorker)
        with pytest.raises(EmptyResultError):
            pool.get_results(timeout=_RESULT_TIMEOUT_S)


@pytest.mark.parametrize('make_pool', POOLS)
def test_with_ventilator_single_epoch(make_pool):
    with _stopped_on_exit(make_pool()) as pool:
        vent = ConcurrentVentilator(pool.ventilate,
                                    [{'value': i} for i in range(30)],
                                    iterations=1, max_ventilation_queue_size=4)
        pool.start(IdentityWorker, ventilator=vent)
        assert sorted(_drain(pool)) == list(range(30))


@pytest.mark.parametrize('make_pool', POOLS)
def test_with_ventilator_multiple_epochs(make_pool):
    with _stopped_on_exit(make_pool()) as pool:
        vent = ConcurrentVentilator(pool.ventilate,
                                    [{'value': i} for i in range(7)],
                                    iterations=3)
        pool.start(IdentityWorker, ventilator=vent)
        results = _drain(pool)
        assert len(results) == 21
        assert sorted(results) == sorted(list(range(7)) * 3)


def test_ventilator_randomizes_order_per_epoch():
    received = []
    vent = ConcurrentVentilator(lambda value: received.append(value),
                                [{'value': i} for i in range(50)], iterations=2,
                                randomize_item_order=True, random_seed=7)
    vent.start()
    while not vent.completed():
        time.sleep(0.01)
        for _ in range(len(received)):
            vent.processed_item()
    epoch1, epoch2 = received[:50], received[50:100]
    assert sorted(epoch1) == list(range(50))
    assert sorted(epoch2) == list(range(50))
    assert epoch1 != list(range(50))  # shuffled
    assert epoch1 != epoch2  # reshuffled between epochs


def test_ventilator_error_completes_instead_of_wedging():
    """A ventilation-thread death must read as 'completed with .error',
    never as still-running: before the fix, the exception died silently
    with completed() stuck False and every consumer polling it hung
    forever (found via the pipecheck work: a leaked PETASTORM_TPU_TRACE=1
    made _trace_ctx injection TypeError a bare-lambda ventilate_fn)."""
    def explode(value):
        raise RuntimeError('boom on %r' % (value,))

    vent = ConcurrentVentilator(explode, [{'value': 1}], iterations=1)
    vent.start()
    deadline = time.monotonic() + 10
    while not vent.completed():
        assert time.monotonic() < deadline, 'ventilator wedged'
        time.sleep(0.01)
    assert isinstance(vent.error, RuntimeError)
    vent.stop()


def test_ventilator_tracing_skips_kwarg_blind_callables(monkeypatch):
    """With tracing on, _trace_ctx is injected only into ventilate_fns
    that can accept it (the pools' **kwargs signatures); a bare user
    callable still receives exactly its own kwargs — tracing is advisory
    and must never break ventilation."""
    from petastorm_tpu import telemetry
    monkeypatch.setenv('PETASTORM_TPU_TRACE', '1')
    telemetry.refresh()
    try:
        received = []
        vent = ConcurrentVentilator(lambda value: received.append(value),
                                    [{'value': i} for i in range(10)],
                                    iterations=1)
        vent.start()
        deadline = time.monotonic() + 10
        while not vent.completed():
            assert time.monotonic() < deadline, 'ventilator wedged'
            time.sleep(0.01)
            for _ in range(len(received)):
                vent.processed_item()
        assert vent.error is None
        assert sorted(received) == list(range(10))

        # a **kwargs ventilate_fn DOES carry the context (the pool shape)
        carried = []
        vent2 = ConcurrentVentilator(lambda **kw: carried.append(kw),
                                     [{'value': i} for i in range(4)],
                                     iterations=1)
        vent2.start()
        deadline = time.monotonic() + 10
        while not vent2.completed():
            assert time.monotonic() < deadline, 'ventilator wedged'
            time.sleep(0.01)
            for _ in range(len(carried)):
                vent2.processed_item()
        from petastorm_tpu.telemetry.tracing import TRACE_CTX_KEY
        assert all(TRACE_CTX_KEY in kw for kw in carried)
    finally:
        monkeypatch.delenv('PETASTORM_TPU_TRACE', raising=False)
        telemetry.refresh()


def test_ventilator_deterministic_given_seed():
    def collect(seed):
        got = []
        vent = ConcurrentVentilator(lambda value: got.append(value),
                                    [{'value': i} for i in range(20)], iterations=1,
                                    randomize_item_order=True, random_seed=seed)
        vent.start()
        while not vent.completed():
            time.sleep(0.005)
            for _ in range(len(got)):
                vent.processed_item()
        return got

    assert collect(3) == collect(3)
    assert collect(3) != collect(4)


def test_ventilator_callable_bound_reread_live():
    # A callable max_ventilation_queue_size is re-read every wait cycle:
    # the reader passes `pool.workers_count + extra`, so a service fleet
    # that grows mid-job raises ventilation parallelism with no restart.
    lock = threading.Lock()
    outstanding = [0]
    high_water = [0]
    bound = [2]

    def tracked(value):
        with lock:
            outstanding[0] += 1
            high_water[0] = max(high_water[0], outstanding[0])

    vent = ConcurrentVentilator(tracked, [{'value': i} for i in range(60)],
                                iterations=1,
                                max_ventilation_queue_size=lambda: bound[0])
    vent.start()
    deadline = time.monotonic() + 10
    grew_at = None
    while not vent.completed() and time.monotonic() < deadline:
        time.sleep(0.002)
        with lock:
            if outstanding[0] > 0:
                outstanding[0] -= 1
                vent.processed_item()
            ventilated_so_far = high_water[0]
        if grew_at is None and ventilated_so_far >= 2:
            bound[0] = 6   # "4 more workers registered"
            grew_at = ventilated_so_far
    assert vent.completed()
    assert grew_at is not None
    assert high_water[0] > 2   # the raised bound took effect mid-run
    assert high_water[0] <= 6


def test_ventilator_backpressure_bounds_in_flight():
    in_flight_high_water = [0]
    lock = threading.Lock()
    outstanding = [0]

    def tracked(value):
        with lock:
            outstanding[0] += 1
            in_flight_high_water[0] = max(in_flight_high_water[0], outstanding[0])

    vent = ConcurrentVentilator(tracked, [{'value': i} for i in range(100)],
                                iterations=1, max_ventilation_queue_size=5)
    vent.start()
    deadline = time.monotonic() + 10
    while not vent.completed() and time.monotonic() < deadline:
        time.sleep(0.002)
        with lock:
            if outstanding[0] > 0:
                outstanding[0] -= 1
                vent.processed_item()
    assert in_flight_high_water[0] <= 5


def test_ventilator_checkpoint_resume():
    first = []
    vent = ConcurrentVentilator(lambda value: first.append(value),
                                [{'value': i} for i in range(10)], iterations=1,
                                randomize_item_order=True, random_seed=11,
                                max_ventilation_queue_size=3)
    vent.start()
    while True:
        state = vent.state_dict()
        if state['cursor'] == len(first) >= 3:
            break
        time.sleep(0.001)
    vent.stop()
    consumed = first[:state['cursor']]

    rest = []
    vent2 = ConcurrentVentilator(lambda value: rest.append(value),
                                 [{'value': i} for i in range(10)], iterations=1,
                                 randomize_item_order=True, random_seed=11)
    vent2.load_state_dict(state)
    vent2.start()
    while not vent2.completed():
        time.sleep(0.005)
        for _ in range(len(rest)):
            vent2.processed_item()
    # Union of pre-checkpoint and post-resume covers each item exactly once.
    assert sorted(consumed + rest) == list(range(10))


def test_ventilator_reset_reruns_epochs():
    got = []
    vent = ConcurrentVentilator(lambda value: got.append(value),
                                [{'value': i} for i in range(5)], iterations=2)
    vent.start()
    while not vent.completed():
        time.sleep(0.005)
        for _ in range(len(got)):
            vent.processed_item()
    assert len(got) == 10
    vent.reset()
    while not vent.completed():
        time.sleep(0.005)
        for _ in range(len(got)):
            vent.processed_item()
    assert len(got) == 20


def test_ventilator_reset_reshuffles_item_order():
    def run_sweep(vent, sink):
        while not vent.completed():
            time.sleep(0.005)
            for _ in range(len(sink)):
                vent.processed_item()

    sweeps = []
    sink = []
    vent = ConcurrentVentilator(lambda value: sink.append(value),
                                [{'value': i} for i in range(32)],
                                iterations=1, randomize_item_order=True,
                                random_seed=5)
    vent.start()
    run_sweep(vent, sink)
    sweeps.append(list(sink))
    for _ in range(2):
        sink.clear()
        vent.reset()
        run_sweep(vent, sink)
        sweeps.append(list(sink))
    for sweep in sweeps:
        assert sorted(sweep) == list(range(32))
    # each reset sweep draws a fresh permutation, not a verbatim replay
    assert sweeps[0] != sweeps[1] and sweeps[1] != sweeps[2]


def test_thread_pool_profiling_with_idle_workers(capsys):
    # 4 workers, ONE item: at least three profiles are guaranteed empty —
    # join() must merge the non-empty one instead of crashing in pstats
    pool = ThreadPool(4, profiling_enabled=True)
    pool.start(IdentityWorker)
    pool.ventilate(7)
    assert pool.get_results() == 7
    pool.stop()
    pool.join()
    assert 'function calls' in capsys.readouterr().out


def test_thread_pool_profiling_no_items_no_crash(capsys):
    # all profiles empty: nothing to print, nothing to crash on
    pool = ThreadPool(2, profiling_enabled=True)
    pool.start(IdentityWorker)
    pool.stop()
    pool.join()
    assert 'function calls' not in capsys.readouterr().out


def test_thread_pool_profiling_prints_stats(capsys):
    # opt-in per-worker cProfile merged and dumped at join
    # (reference: thread_pool.py:48-49,190-198 / SURVEY §5.1)
    pool = ThreadPool(2, profiling_enabled=True)
    pool.start(IdentityWorker)
    for i in range(10):
        pool.ventilate(i)
    got = [pool.get_results() for _ in range(10)]
    pool.stop()
    pool.join()
    assert sorted(got) == list(range(10))
    out = capsys.readouterr().out
    assert 'cumulative' in out and 'function calls' in out


class TestExecInNewProcess:
    """Spawn-not-fork helper (reference:
    ``workers_pool/exec_in_new_process.py:26-48``)."""

    def test_runs_function_in_fresh_interpreter(self, tmp_path):
        from petastorm_tpu.workers.exec_in_new_process import (
            exec_in_new_process,
        )
        out = str(tmp_path / 'out.txt')

        def write_marker(path, value):
            import os
            with open(path, 'w') as f:
                f.write('%s:%d' % (value, os.getpid()))

        proc = exec_in_new_process(write_marker, out, value='hello')
        assert proc.wait(timeout=60) == 0
        value, pid = open(out).read().split(':')
        assert value == 'hello'
        assert int(pid) != __import__('os').getpid()  # genuinely new process

    def test_exit_code_propagates(self):
        from petastorm_tpu.workers.exec_in_new_process import (
            exec_in_new_process,
        )

        def boom():
            raise SystemExit(3)

        assert exec_in_new_process(boom).wait(timeout=60) == 3

    def test_child_forced_onto_cpu_platform(self, tmp_path, monkeypatch):
        # decode workers must never grab the TPU chip the trainer owns —
        # even when the PARENT runs with JAX_PLATFORMS=tpu
        from petastorm_tpu.workers.exec_in_new_process import (
            exec_in_new_process,
        )
        monkeypatch.setenv('JAX_PLATFORMS', 'tpu')
        out = str(tmp_path / 'platform.txt')

        def report(path):
            import os
            with open(path, 'w') as f:
                f.write(os.environ.get('JAX_PLATFORMS', ''))

        proc = exec_in_new_process(report, out)
        assert proc.wait(timeout=60) == 0
        assert open(out).read() == 'cpu'


def test_thread_pool_requires_stop_before_join():
    pool = ThreadPool(1)
    pool.start(IdentityWorker)
    with pytest.raises(RuntimeError):
        pool.join()
    pool.stop()
    pool.join()


def test_thread_pool_stop_mid_stream_does_not_hang():
    pool = ThreadPool(2, results_queue_size=2)
    pool.start(SleepyIdentityWorker)
    for i in range(50):
        pool.ventilate(i, sleep_s=0.001)
    pool.get_results()
    pool.stop()
    pool.join()  # must not deadlock against the full results queue


def test_diagnostics_exposed():
    pool = ThreadPool(1)
    pool.start(IdentityWorker)
    pool.ventilate(1)
    pool.get_results()
    d = pool.diagnostics
    assert d['items_ventilated'] == 1
    pool.stop()
    pool.join()


def test_process_pool_detects_sigkilled_worker():
    # failure detection (SURVEY §5.3): a worker hard-killed mid-stream
    # (OOM-killer shape) must surface as a RuntimeError in get_results,
    # never a silent hang waiting for results that will not come
    import os
    import signal

    pool = ProcessPool(2)
    pool.start(SleepyIdentityWorker)
    try:
        for i in range(50):
            pool.ventilate(i)
        os.kill(pool._processes[0].pid, signal.SIGKILL)
        # the killed worker's in-flight items can never complete, so a
        # drain must end in the dead-worker RuntimeError — anything else
        # (EmptyResultError, timeout) would mean the death went unnoticed
        with pytest.raises(RuntimeError, match='died unexpectedly'):
            for _ in range(60):
                pool.get_results(timeout=30)
    finally:
        pool.stop()
        pool.join()
