"""Switch-MoE layer: routing semantics, expert-parallel sharding parity."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # compile-heavy: every test jits on the 8-device mesh

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from petastorm_tpu.models.moe import (
    MoEConfig, dense_oracle, expert_capacity, init_moe_params, moe_forward,
)
from petastorm_tpu.parallel.mesh import (
    DATA_AXIS, EXPERT_AXIS, make_named_mesh,
)


def _setup(n_experts=4, d_model=16, d_ff=32, dtype=jnp.float32, seed=0,
           batch=4, seq=8, mesh=None, capacity_factor=1.25):
    config = MoEConfig(d_model=d_model, d_ff=d_ff, n_experts=n_experts,
                       capacity_factor=capacity_factor, dtype=dtype)
    params = init_moe_params(jax.random.PRNGKey(seed), config, mesh=mesh)
    x = jnp.asarray(np.random.RandomState(seed + 1)
                    .randn(batch, seq, d_model).astype(np.float32))
    return config, params, x


def test_matches_dense_oracle_with_ample_capacity():
    # capacity ≥ T means nothing drops: output must equal per-token argmax
    # expert MLP, gate-weighted (the loop-based oracle)
    config, params, x = _setup(capacity_factor=float('inf'))
    y, _ = moe_forward(params, x, config, capacity=x.shape[0] * x.shape[1])
    want = dense_oracle(params, x, config)
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-5, rtol=1e-5)


def test_capacity_drop_passes_tokens_through_as_zero():
    # capacity=1: all but the first token per expert emit zeros (caller adds
    # the residual); kept tokens still match the oracle
    config, params, x = _setup()
    y, _ = moe_forward(params, x, config, capacity=1)
    want = dense_oracle(params, x, config)
    got = np.asarray(y).reshape(-1, config.d_model)
    want = want.reshape(-1, config.d_model)
    zero_rows = ~np.abs(got).sum(axis=1).astype(bool)
    assert zero_rows.any(), 'capacity=1 over 32 tokens must drop some'
    kept = ~zero_rows
    assert kept.any()
    np.testing.assert_allclose(got[kept], want[kept], atol=1e-5, rtol=1e-5)


def test_aux_loss_uniform_routing_is_one():
    # with a zero router every expert gets equal probability; the Switch
    # loss E * Σ f_e p_e attains its minimum 1.0 (up to argmax ties making
    # f nonuniform — use probs-only bound: loss >= 1 always)
    config, params, x = _setup()
    params = dict(params, router=jnp.zeros_like(params['router']))
    _, aux = moe_forward(params, x, config)
    assert float(aux) >= 1.0 - 1e-6


def test_aux_loss_penalizes_collapse():
    # a router that sends everything to expert 0 maxes the loss toward E
    config, params, x = _setup()
    # saturate prob on expert 0 via a large constant column
    router = jnp.zeros(params['router'].shape,
                       jnp.float32).at[:, 0].set(10.0 / config.d_model)
    x_pos = jnp.abs(x) + 0.1  # positive activations: logits[:,0] >> others
    _, aux_collapsed = moe_forward(dict(params, router=router), x_pos, config)
    params_uniform = dict(params, router=jnp.zeros_like(params['router']))
    _, aux_uniform = moe_forward(params_uniform, x_pos, config)
    assert float(aux_collapsed) > float(aux_uniform)


def test_expert_capacity_math():
    assert expert_capacity(32, 4, 1.0) == 8
    assert expert_capacity(32, 4, 1.25) == 10
    assert expert_capacity(3, 4, 1.0) == 1


@pytest.mark.parametrize('n_experts', [2, 4, 8])
def test_expert_parallel_matches_unsharded(n_experts):
    # the same forward under an expert-sharded mesh must equal the
    # single-device result: sharding is a layout decision, not semantics
    mesh = make_named_mesh({DATA_AXIS: None, EXPERT_AXIS: n_experts},
                           devices=jax.devices()[:8])
    config, params, x = _setup(n_experts=n_experts)
    y_plain, aux_plain = moe_forward(params, x, config)

    params_sharded = init_moe_params(jax.random.PRNGKey(0), config, mesh=mesh)
    for name in params:
        np.testing.assert_array_equal(np.asarray(params[name]),
                                      np.asarray(params_sharded[name]))
    xs = jax.device_put(x, NamedSharding(mesh, P(DATA_AXIS, None, None)))
    with mesh:
        y_sharded, aux_sharded = jax.jit(
            lambda p, a: moe_forward(p, a, config))(params_sharded, xs)
    np.testing.assert_allclose(np.asarray(y_sharded), np.asarray(y_plain),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(float(aux_sharded), float(aux_plain),
                               rtol=1e-6)


def test_named_mesh_rejects_leftover_devices():
    # 2x2 over 8 devices would silently idle half the pod; must raise
    with pytest.raises(ValueError, match='absorb the remainder'):
        make_named_mesh({DATA_AXIS: 2, EXPERT_AXIS: 2})


def test_expert_params_live_on_expert_shards():
    mesh = make_named_mesh({DATA_AXIS: 2, EXPERT_AXIS: 4})
    config = MoEConfig(d_model=16, d_ff=32, n_experts=4)
    params = init_moe_params(jax.random.PRNGKey(0), config, mesh=mesh)
    assert params['w_in'].sharding.spec == P(EXPERT_AXIS, None, None)
    # each expert shard holds exactly one expert's weights
    assert {s.data.shape for s in params['w_in'].addressable_shards} \
        == {(1, 16, 32)}


def test_grad_flows_and_is_finite():
    config, params, x = _setup()

    def loss(params, x):
        y, aux = moe_forward(params, x, config)
        return jnp.sum(y ** 2) + 0.01 * aux

    grads = jax.jit(jax.grad(loss))(params, x)
    for name, g in grads.items():
        assert np.isfinite(np.asarray(g)).all(), name
    # router must receive gradient through the gate (differentiable path)
    assert np.abs(np.asarray(grads['router'])).sum() > 0


def test_bfloat16_expert_compute_stays_close():
    config32, params, x = _setup()
    config16 = MoEConfig(d_model=16, d_ff=32, n_experts=4,
                         capacity_factor=1.25, dtype=jnp.bfloat16)
    y32, _ = moe_forward(params, x, config32)
    y16, _ = moe_forward(params, x, config16)
    np.testing.assert_allclose(np.asarray(y16, np.float32),
                               np.asarray(y32, np.float32),
                               atol=5e-2, rtol=5e-2)
