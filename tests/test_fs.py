"""Filesystem-layer coverage: object-store-shaped reads over fsspec
``memory://``, URL-list reads, and datasets moved after materialization.

Reference: ``petastorm/tests/test_fs_utils.py`` and the moved-dataset case in
``tests/test_end_to_end.py``.
"""

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import (
    ParquetDatasetInfo, get_schema_from_dataset_url, write_dataset,
)
from petastorm_tpu.fs import (
    get_dataset_path, get_filesystem_and_path_or_paths, normalize_dir_url,
)
from petastorm_tpu.unischema import Unischema, UnischemaField

SmallSchema = Unischema('SmallSchema', [
    UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
    UnischemaField('vec', np.float32, (4,), NdarrayCodec(), False),
])


def _rows(n):
    rng = np.random.RandomState(0)
    return [{'id': i, 'vec': rng.rand(4).astype(np.float32)} for i in range(n)]


class TestUrlHelpers:
    def test_normalize_dir_url(self):
        assert normalize_dir_url('file:///a/b/') == 'file:///a/b'
        with pytest.raises(ValueError):
            normalize_dir_url(123)

    def test_get_dataset_path_object_store_keeps_bucket(self):
        assert get_dataset_path('gs://bucket/dir/ds') == 'bucket/dir/ds'
        assert get_dataset_path('s3://b/key') == 'b/key'
        assert get_dataset_path('file:///x/y') == '/x/y'

    def test_url_list_must_be_homogeneous(self):
        with pytest.raises(ValueError, match='share scheme'):
            get_filesystem_and_path_or_paths(
                ['file:///a/1.parquet', 'memory://a/2.parquet'])

    def test_url_list_resolution(self):
        fs, paths = get_filesystem_and_path_or_paths(
            ['file:///a/1.parquet', 'file:///a/2.parquet'])
        assert len(paths) == 2


class TestMemoryFilesystem:
    """An fsspec object store with no local paths: catches scheme/path
    handling regressions the file:// tests cannot."""

    def test_write_and_read_round_trip(self):
        url = 'memory://interop_ds'
        rows = _rows(20)
        write_dataset(url, SmallSchema, rows, rowgroup_size_rows=5)
        schema = get_schema_from_dataset_url(url)
        assert list(schema.fields) == ['id', 'vec']
        with make_reader(url, shuffle_row_groups=False) as reader:
            got = sorted(reader, key=lambda r: r.id)
        assert [r.id for r in got] == list(range(20))
        np.testing.assert_array_equal(got[3].vec, rows[3]['vec'])

    def test_batch_reader_over_memory(self):
        url = 'memory://interop_batch_ds'
        write_dataset(url, SmallSchema, _rows(30), rowgroup_size_rows=10)
        with make_batch_reader(url) as reader:
            total = sum(len(b.id) for b in reader)
        assert total == 30


class TestUrlListReads:
    @pytest.fixture(scope='class')
    def dataset(self, tmp_path_factory):
        root = str(tmp_path_factory.mktemp('urllist')) + '/ds'
        url = 'file://' + root
        write_dataset(url, SmallSchema, _rows(40), rowgroup_size_rows=10,
                      num_files=4)
        info = ParquetDatasetInfo(url)
        return url, ['file://' + p for p in info.file_paths]

    def test_batch_reader_accepts_file_url_list(self, dataset):
        _, file_urls = dataset
        assert len(file_urls) == 4
        with make_batch_reader(file_urls) as reader:
            ids = sorted(int(i) for b in reader for i in b.id)
        assert ids == list(range(40))

    def test_subset_of_files(self, dataset):
        _, file_urls = dataset
        with make_batch_reader(file_urls[:2]) as reader:
            total = sum(len(b.id) for b in reader)
        assert total == 20


class TestMovedDataset:
    def test_read_after_move(self, tmp_path):
        src = tmp_path / 'original'
        dst = tmp_path / 'relocated'
        write_dataset('file://' + str(src), SmallSchema, _rows(15),
                      rowgroup_size_rows=5)
        src.rename(dst)
        # all metadata must be relative: a moved dataset reads unchanged
        with make_reader('file://' + str(dst),
                         shuffle_row_groups=False) as reader:
            ids = sorted(r.id for r in reader)
        assert ids == list(range(15))
