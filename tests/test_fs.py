"""Filesystem-layer coverage: object-store-shaped reads over fsspec
``memory://``, URL-list reads, and datasets moved after materialization.

Reference: ``petastorm/tests/test_fs_utils.py`` and the moved-dataset case in
``tests/test_end_to_end.py``.
"""

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import (
    ParquetDatasetInfo, get_schema_from_dataset_url, write_dataset,
)
from petastorm_tpu.fs import (
    get_dataset_path, get_filesystem_and_path_or_paths, normalize_dir_url,
)
from petastorm_tpu.unischema import Unischema, UnischemaField

SmallSchema = Unischema('SmallSchema', [
    UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
    UnischemaField('vec', np.float32, (4,), NdarrayCodec(), False),
])


def _rows(n):
    rng = np.random.RandomState(0)
    return [{'id': i, 'vec': rng.rand(4).astype(np.float32)} for i in range(n)]


class TestUrlHelpers:
    def test_normalize_dir_url(self):
        assert normalize_dir_url('file:///a/b/') == 'file:///a/b'
        with pytest.raises(ValueError):
            normalize_dir_url(123)

    def test_get_dataset_path_object_store_keeps_bucket(self):
        assert get_dataset_path('gs://bucket/dir/ds') == 'bucket/dir/ds'
        assert get_dataset_path('s3://b/key') == 'b/key'
        assert get_dataset_path('file:///x/y') == '/x/y'

    def test_url_list_must_be_homogeneous(self):
        with pytest.raises(ValueError, match='share scheme'):
            get_filesystem_and_path_or_paths(
                ['file:///a/1.parquet', 'memory://a/2.parquet'])

    def test_url_list_resolution(self):
        fs, paths = get_filesystem_and_path_or_paths(
            ['file:///a/1.parquet', 'file:///a/2.parquet'])
        assert len(paths) == 2


class TestMemoryFilesystem:
    """An fsspec object store with no local paths: catches scheme/path
    handling regressions the file:// tests cannot."""

    def test_write_and_read_round_trip(self):
        url = 'memory://interop_ds'
        rows = _rows(20)
        write_dataset(url, SmallSchema, rows, rowgroup_size_rows=5)
        schema = get_schema_from_dataset_url(url)
        assert list(schema.fields) == ['id', 'vec']
        with make_reader(url, shuffle_row_groups=False) as reader:
            got = sorted(reader, key=lambda r: r.id)
        assert [r.id for r in got] == list(range(20))
        np.testing.assert_array_equal(got[3].vec, rows[3]['vec'])

    def test_batch_reader_over_memory(self):
        url = 'memory://interop_batch_ds'
        write_dataset(url, SmallSchema, _rows(30), rowgroup_size_rows=10)
        with make_batch_reader(url) as reader:
            total = sum(len(b.id) for b in reader)
        assert total == 30


class TestExplicitFilesystem:
    """``filesystem=`` passthrough: an already-constructed fsspec filesystem
    is used as-is instead of URL-scheme resolution (reference
    ``reader.py:61``'s kwarg; e.g. a pre-authenticated gcsfs instance)."""

    def test_reader_uses_explicit_instance(self):
        import fsspec
        url = 'memory://explicit_fs_ds'
        write_dataset(url, SmallSchema, _rows(20), rowgroup_size_rows=5)
        # skip_instance_cache: fsspec's memory fs is normally a cached
        # singleton, so URL resolution would return the SAME object and a
        # dropped passthrough would be invisible — a distinct instance
        # makes the identity assertions below meaningful
        fs = fsspec.filesystem('memory', skip_instance_cache=True)
        with make_reader(url, shuffle_row_groups=False,
                         filesystem=fs) as reader:
            assert reader.dataset_info.fs is fs
            assert sorted(r.id for r in reader) == list(range(20))
        with make_batch_reader(url, filesystem=fs) as reader:
            assert reader.dataset_info.fs is fs
            assert sum(len(b.id) for b in reader) == 20

    def test_scheme_mismatch_rejected(self):
        import fsspec
        fs = fsspec.filesystem('memory', skip_instance_cache=True)
        with pytest.raises(ValueError, match='does not match'):
            get_filesystem_and_path_or_paths('gs://bucket/ds', filesystem=fs)

    def test_resolver_returns_instance_and_stripped_paths(self):
        import fsspec
        fs = fsspec.filesystem('memory')
        got_fs, path = get_filesystem_and_path_or_paths(
            'memory://some/ds', filesystem=fs)
        assert got_fs is fs
        assert path == fs._strip_protocol('memory://some/ds')
        got_fs, paths = get_filesystem_and_path_or_paths(
            ['memory://a/1.parquet', 'memory://a/2.parquet'], filesystem=fs)
        assert got_fs is fs and len(paths) == 2

    def test_mutually_exclusive_with_storage_options(self):
        import fsspec
        with pytest.raises(ValueError, match='mutually exclusive'):
            get_filesystem_and_path_or_paths(
                'memory://ds', storage_options={'foo': 1},
                filesystem=fsspec.filesystem('memory'))


class TestUrlListReads:
    @pytest.fixture(scope='class')
    def dataset(self, tmp_path_factory):
        root = str(tmp_path_factory.mktemp('urllist')) + '/ds'
        url = 'file://' + root
        write_dataset(url, SmallSchema, _rows(40), rowgroup_size_rows=10,
                      num_files=4)
        info = ParquetDatasetInfo(url)
        return url, ['file://' + p for p in info.file_paths]

    def test_batch_reader_accepts_file_url_list(self, dataset):
        _, file_urls = dataset
        assert len(file_urls) == 4
        with make_batch_reader(file_urls) as reader:
            ids = sorted(int(i) for b in reader for i in b.id)
        assert ids == list(range(40))

    def test_subset_of_files(self, dataset):
        _, file_urls = dataset
        with make_batch_reader(file_urls[:2]) as reader:
            total = sum(len(b.id) for b in reader)
        assert total == 20


class TestMovedDataset:
    def test_read_after_move(self, tmp_path):
        src = tmp_path / 'original'
        dst = tmp_path / 'relocated'
        write_dataset('file://' + str(src), SmallSchema, _rows(15),
                      rowgroup_size_rows=5)
        src.rename(dst)
        # all metadata must be relative: a moved dataset reads unchanged
        with make_reader('file://' + str(dst),
                         shuffle_row_groups=False) as reader:
            ids = sorted(r.id for r in reader)
        assert ids == list(range(15))
