"""Smoke tests running every shipped example end-to-end on tiny data.

Mirrors the reference's per-example ``tests/`` directories (e.g.
``examples/mnist/tests/test_pytorch_mnist.py``,
``examples/hello_world/external_dataset/tests/test_external_hello_world.py``,
``examples/spark_dataset_converter/tests``): each example must actually run,
not just import.
"""

import numpy as np
import pytest


@pytest.fixture(scope='module')
def mnist_url(tmp_path_factory):
    from examples.mnist.jax_example import generate_synthetic_mnist
    url = 'file://' + str(tmp_path_factory.mktemp('mnist_ex')) + '/ds'
    generate_synthetic_mnist(url, num_rows=256)
    return url


@pytest.fixture(scope='module')
def external_url(tmp_path_factory):
    from examples.hello_world.external_dataset.generate_external_dataset \
        import generate_external_dataset
    url = 'file://' + str(tmp_path_factory.mktemp('ext_ex')) + '/ds'
    generate_external_dataset(url, num_rows=60, rows_per_file=20)
    return url


class TestMnistExamples:
    @pytest.mark.slow
    def test_pytorch_example_trains(self, mnist_url):
        from examples.mnist.pytorch_example import train
        loss = train(mnist_url, batch_size=64, epochs=1, log_interval=1000)
        assert np.isfinite(loss)

    def test_pytorch_example_evaluate(self, mnist_url):
        from examples.mnist.pytorch_example import Net, evaluate
        accuracy = evaluate(mnist_url, Net(), batch_size=64)
        assert 0.0 <= accuracy <= 1.0

    @pytest.mark.slow
    def test_tf_example_trains(self, mnist_url):
        from examples.mnist.tf_example import train
        loss = train(mnist_url, batch_size=64, steps_per_epoch=4)
        assert np.isfinite(loss)


class TestExternalDatasetExamples:
    def test_python_hello_world(self, external_url, capsys):
        from examples.hello_world.external_dataset.python_hello_world import (
            python_hello_world,
        )
        python_hello_world(external_url)
        assert 'batch of' in capsys.readouterr().out

    def test_pytorch_hello_world(self, external_url, capsys):
        from examples.hello_world.external_dataset.pytorch_hello_world import (
            pytorch_hello_world,
        )
        pytorch_hello_world(external_url)
        assert 'id batch' in capsys.readouterr().out

    def test_tensorflow_hello_world(self, external_url, capsys):
        from examples.hello_world.external_dataset.tensorflow_hello_world \
            import tensorflow_hello_world
        tensorflow_hello_world(external_url)
        assert 'first batch ids' in capsys.readouterr().out

    def test_read_petastorm_hello_world(self, tmp_path, capsys):
        from examples.hello_world.generate_petastorm_dataset import (
            generate_petastorm_dataset,
        )
        from examples.hello_world import read_petastorm_dataset as consumers
        url = 'file://' + str(tmp_path / 'hello')
        generate_petastorm_dataset(url, num_rows=4)
        consumers.python_hello_world(url)
        consumers.selector_hello_world(url)
        consumers.jax_hello_world(url)
        consumers.torch_hello_world(url)
        consumers.tf_hello_world(url)
        out = capsys.readouterr().out
        assert 'selected ids:' in out
        assert 'jax ids:' in out and 'torch ids:' in out and 'tf id:' in out


class TestConverterExamples:
    def test_pytorch_converter_example(self, tmp_path):
        from examples.dataset_converter.pytorch_converter_example import train
        loss = train(str(tmp_path / 'cache'), batch_size=64, epochs=1)
        assert np.isfinite(loss)

    def test_tensorflow_converter_example(self, tmp_path):
        from examples.dataset_converter.tensorflow_converter_example import (
            train,
        )
        loss = train(str(tmp_path / 'cache'), batch_size=64, steps=4)
        assert np.isfinite(loss)


class TestLmExample:
    def test_packing_preserves_token_stream(self, tmp_path):
        from examples.lm.pretrain_example import (
            EOS, generate_c4_like, packing_transform,
        )
        from petastorm_tpu import make_batch_reader
        url = 'file://' + str(tmp_path / 'c4')
        generate_c4_like(url, num_docs=64)
        with make_batch_reader(url, shuffle_row_groups=False) as reader:
            raw_docs = []
            for batch in reader:
                raw_docs.extend(np.asarray(d) for d in batch.tokens)
        with make_batch_reader(url, shuffle_row_groups=False,
                               transform_spec=packing_transform(32)) as reader:
            packed = np.concatenate([np.asarray(b.tokens) for b in reader])
        assert packed.shape[1] == 32
        # packed rows reproduce the whole document stream (EOS-separated),
        # up to the dropped ragged tail (single row-group: one tail)
        stream = np.concatenate([np.append(d, EOS) for d in raw_docs])
        flat = packed.reshape(-1)
        assert len(flat) == len(stream) // 32 * 32
        assert np.array_equal(flat, stream[:len(flat)])

    @pytest.mark.slow
    def test_pretrain_learns(self, tmp_path):
        from examples.lm.pretrain_example import generate_c4_like, pretrain
        url = 'file://' + str(tmp_path / 'c4')
        generate_c4_like(url, num_docs=128)
        loss = pretrain(url, batch_size=8, steps=6)
        assert np.isfinite(loss)

    @pytest.mark.slow
    def test_modern_recipe_trains_and_decodes(self, tmp_path):
        # the LLaMA-style composition: rope + GQA + swiglu + remat +
        # gradient accumulation + donated state, trained from Parquet,
        # then greedy decode from the grouped KV cache
        from examples.lm.modern_example import modern_pretrain
        from examples.lm.pretrain_example import generate_c4_like
        url = 'file://' + str(tmp_path / 'c4_modern')
        generate_c4_like(url, num_docs=128)
        loss, decoded = modern_pretrain(url, batch_size=8, steps=6,
                                        accum_steps=2, decode_tokens=6)
        assert np.isfinite(loss)
        assert decoded.shape == (2, 14)  # 8 prompt + 6 new

    @pytest.mark.slow
    def test_pretrain_checkpoint_resume(self, tmp_path):
        # interrupt after 8 of 12 steps, rerun: training resumes from the
        # checkpoint (model + data position together), ending with 12 total
        from examples.lm.pretrain_example import generate_c4_like, pretrain
        url = 'file://' + str(tmp_path / 'c4')
        ckpt_dir = str(tmp_path / 'ckpt')
        generate_c4_like(url, num_docs=128)
        pretrain(url, batch_size=8, steps=8, checkpoint_dir=ckpt_dir,
                 checkpoint_every=4)
        loss = pretrain(url, batch_size=8, steps=12, checkpoint_dir=ckpt_dir,
                        checkpoint_every=4)
        assert np.isfinite(loss)
        from petastorm_tpu.jax import TrainCheckpointer
        with TrainCheckpointer(ckpt_dir) as ckpt:
            assert ckpt.latest_step == 12
        # rerunning an already-complete run is a no-op, not a crash
        assert pretrain(url, batch_size=8, steps=12,
                        checkpoint_dir=ckpt_dir) is None

    @pytest.mark.slow
    def test_generate_from_checkpoint(self, tmp_path):
        # the full lifecycle: train with checkpointing, restore in a
        # separate call, decode greedily and with nucleus sampling
        from examples.lm.generate_example import generate_from_checkpoint
        from examples.lm.pretrain_example import generate_c4_like, pretrain
        url = 'file://' + str(tmp_path / 'c4_gen')
        ckpt_dir = str(tmp_path / 'ckpt_gen')
        generate_c4_like(url, num_docs=96)
        pretrain(url, batch_size=8, steps=6, checkpoint_dir=ckpt_dir,
                 checkpoint_every=3)
        greedy = generate_from_checkpoint(ckpt_dir, max_new_tokens=12,
                                          log=lambda *a: None)
        assert greedy.shape == (2, 13)
        assert ((greedy >= 0) & (greedy < 256)).all()
        sampled = generate_from_checkpoint(ckpt_dir, max_new_tokens=12,
                                           temperature=0.9, top_p=0.9,
                                           log=lambda *a: None)
        assert sampled.shape == (2, 13)
        # filters without sampling make no sense and are rejected
        with pytest.raises(ValueError, match='temperature'):
            generate_from_checkpoint(ckpt_dir, top_p=0.9,
                                     log=lambda *a: None)
        # missing checkpoint dir fails actionably WITHOUT creating it
        missing = tmp_path / 'nope'
        with pytest.raises(FileNotFoundError, match='pretrain'):
            generate_from_checkpoint(str(missing), log=lambda *a: None)
        assert not missing.exists(), 'probe must not create the directory'

    @pytest.mark.slow
    def test_variable_length_bucketed_training(self, tmp_path):
        # no-packing path: variable-length docs → length buckets → masked
        # train step; multiple bucket shapes must actually occur
        from examples.lm.pretrain_example import generate_c4_like
        from examples.lm.variable_length_example import (
            train_variable_length,
        )
        url = 'file://' + str(tmp_path / 'c4_var')
        generate_c4_like(url, num_docs=192)
        loss, buckets = train_variable_length(
            url, batch_size=8, steps=10, boundaries=(64, 128, 256, 512),
            d_model=32, n_layers=1, log=lambda *a: None)
        assert np.isfinite(loss)
        assert sum(buckets.values()) == 10
        assert len(buckets) >= 2, 'doc lengths 20-400 must hit >=2 buckets'
        assert set(buckets) <= {64, 128, 256, 512}

    @pytest.mark.slow
    def test_long_context_seq_parallel_pretrain(self, tmp_path):
        # the full long-context path: packed rows → data x seq mesh → ring
        # attention inside the train step (tiny shapes for CI speed)
        from examples.lm.long_context_example import pretrain_long_context
        from examples.lm.pretrain_example import generate_c4_like
        url = 'file://' + str(tmp_path / 'c4_long')
        generate_c4_like(url, num_docs=128)
        loss = pretrain_long_context(url, batch_size=4, steps=4, seq_len=64,
                                     seq_shards=4)
        assert np.isfinite(loss)


class TestImagenetExamples:
    @pytest.mark.slow
    def test_vit_trains_from_parquet(self, tmp_path):
        from examples.imagenet.generate_petastorm_imagenet import (
            generate_petastorm_imagenet,
        )
        from examples.imagenet.vit_example import train_vit
        url = 'file://' + str(tmp_path / 'imagenet')
        generate_petastorm_imagenet(url, num_rows=48)
        loss = train_vit(url, batch_size=8, steps=6, size=32, patch_size=8,
                         n_classes=8, log=lambda *a: None)
        assert np.isfinite(loss)

    def test_generate_and_jax_read(self, tmp_path):
        from examples.imagenet.generate_petastorm_imagenet import (
            generate_petastorm_imagenet,
        )
        from examples.imagenet.jax_example import read_imagenet
        url = 'file://' + str(tmp_path / 'imagenet')
        count = generate_petastorm_imagenet(url, num_rows=24)
        assert count == 24
        images = read_imagenet(url, batch_size=4, batches=2, size=64)
        assert images.shape == (4, 64, 64, 3)

    def test_generate_from_directory(self, tmp_path):
        import cv2
        from examples.imagenet.generate_petastorm_imagenet import (
            generate_petastorm_imagenet,
        )
        from petastorm_tpu import make_reader
        rng = np.random.RandomState(0)
        tree = tmp_path / 'images' / 'n01234567'
        tree.mkdir(parents=True)
        for i in range(3):
            bgr = rng.randint(0, 255, (40, 50, 3), np.uint8)
            cv2.imwrite(str(tree / ('img_%d.png' % i)), bgr)
        url = 'file://' + str(tmp_path / 'ds')
        count = generate_petastorm_imagenet(url,
                                            images_dir=str(tmp_path / 'images'))
        assert count == 3
        with make_reader(url, shuffle_row_groups=False) as reader:
            rows = list(reader)
        assert {r.noun_id for r in rows} == {'n01234567'}
        assert rows[0].image.shape == (40, 50, 3)
