"""PyArrow-style DNF ``filters`` tests (reference parity:
``petastorm/tests/test_end_to_end.py:852-880`` — plus the statistics-based
row-group pruning the reference does not have)."""

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.errors import NoDataAvailableError
from petastorm_tpu.filters import FiltersPredicate, normalize_filters
from petastorm_tpu.predicates import in_lambda


class TestNormalize:
    def test_single_and_group(self):
        assert normalize_filters([('a', '=', 1), ('b', '<', 2)]) == \
            [[('a', '=', 1), ('b', '<', 2)]]

    def test_or_of_ands(self):
        clauses = normalize_filters([[('a', '=', 1)], [('b', 'in', (1, 2))]])
        assert clauses == [[('a', '=', 1)], [('b', 'in', (1, 2))]]

    def test_empty_is_none(self):
        assert normalize_filters(None) is None
        assert normalize_filters([]) is None

    @pytest.mark.parametrize('bad', [
        [('a', 'like', 1)],          # unsupported op
        [('a', '=')],                # not a 3-tuple
        [[('a', '=', 1)], []],       # empty AND clause
        [(1, '=', 1)],               # non-string column
        [('a', '=', 1), [('b', '=', 2)]],   # mixed flat/nested
        [('a', 'in', 'p_2')],        # scalar string for 'in'
        [('a', 'not in', 5)],        # non-iterable for 'not in'
    ])
    def test_invalid_rejected(self, bad):
        with pytest.raises(ValueError):
            normalize_filters(bad)


class TestFiltersPredicate:
    @pytest.mark.parametrize('filters,expected', [
        ([('x', '<', 3)], [True, True, True, False, False]),
        ([('x', '>=', 2), ('y', '!=', 'b')], [False, False, True, False, True]),
        ([('x', 'in', (0, 4))], [True, False, False, False, True]),
        ([('y', 'not in', ('a',))], [False, True, True, True, True]),
        ([[('x', '=', 0)], [('y', '=', 'c')]], [True, False, True, False, True]),
    ])
    def test_row_and_columnar_agree(self, filters, expected):
        pred = FiltersPredicate(filters)
        columns = {'x': np.arange(5), 'y': ['a', 'b', 'c', 'b', 'c']}
        mask = pred.do_include_batch(columns)
        assert mask.tolist() == expected
        rows = [pred.do_include({'x': columns['x'][i], 'y': columns['y'][i]})
                for i in range(5)]
        assert rows == expected

    def test_fields(self):
        pred = FiltersPredicate([[('a', '=', 1)], [('b', '<', 2)]])
        assert pred.get_fields() == {'a', 'b'}

    @pytest.mark.parametrize('filters,expected', [
        ([('x', '<', 2)], [True, True, False]),
        ([('x', '>=', 1)], [False, True, False]),
        ([('x', '!=', 1)], [True, False, False]),
        ([('x', 'in', (0, 1))], [True, True, False]),
        ([('x', 'not in', (0,))], [False, True, False]),
    ])
    def test_nulls_never_match(self, filters, expected):
        # pyarrow DNF semantics: null cells are excluded, never an error
        pred = FiltersPredicate(filters)
        columns = {'x': np.array([0, 1, None], dtype=object)}
        assert pred.do_include_batch(columns).tolist() == expected
        assert [pred.do_include({'x': v}) for v in columns['x']] == expected

    def test_numeric_in_uses_isin(self):
        pred = FiltersPredicate([('x', 'in', (2, 4))])
        mask = pred.do_include_batch({'x': np.arange(6)})
        assert mask.tolist() == [False, False, True, False, True, False]


@pytest.fixture(scope='module')
def partitioned_url(tmp_path_factory):
    from tests.test_common import create_test_dataset
    url = 'file://' + str(tmp_path_factory.mktemp('filters')) + '/ds'
    create_test_dataset(url, range(100), num_files=1, rowgroup_size=10,
                        partition_by=('partition_key',))
    return url


class TestEndToEnd:
    def test_make_reader_partition_filter(self, partitioned_url):
        # reference: test_pyarrow_filters_make_reader (:852)
        with make_reader(partitioned_url,
                         filters=[('partition_key', '=', 'p_2')],
                         shuffle_row_groups=False) as reader:
            rows = list(reader)
        assert rows and {r.partition_key for r in rows} == {'p_2'}
        assert sorted(r.id for r in rows) == [i for i in range(100)
                                              if i % 5 == 2]

    def test_partition_filter_prunes_row_groups(self, partitioned_url):
        with make_reader(partitioned_url, shuffle_row_groups=False) as reader:
            total = len(reader._piece_indices)
        with make_reader(partitioned_url,
                         filters=[('partition_key', '=', 'p_2')],
                         shuffle_row_groups=False) as reader:
            assert 0 < len(reader._piece_indices) < total

    def test_stats_pruning_on_value_column(self, synthetic_dataset):
        # id lives in the files (not partitions): pruning must come from the
        # parquet min/max statistics — the beyond-reference path
        with make_reader(synthetic_dataset.url,
                         shuffle_row_groups=False) as reader:
            total = len(reader._piece_indices)
        with make_reader(synthetic_dataset.url, filters=[('id', '<', 10)],
                         shuffle_row_groups=False) as reader:
            pruned = len(reader._piece_indices)
            rows = list(reader)
        assert sorted(r.id for r in rows) == list(range(10))
        assert pruned < total

    def test_batch_reader_filters(self, scalar_dataset):
        # reference: test_pyarrow_filters_make_batch_reader (:862)
        with make_batch_reader(scalar_dataset.url,
                               filters=[('id', '>=', 90)],
                               shuffle_row_groups=False) as reader:
            ids = np.concatenate([b.id for b in reader])
        assert sorted(ids.tolist()) == list(range(90, 100))

    def test_or_clauses(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url,
                         filters=[[('id', '<', 3)], [('id', '>=', 97)]],
                         schema_fields=['^id$'],
                         shuffle_row_groups=False) as reader:
            ids = sorted(r.id for r in reader)
        assert ids == [0, 1, 2, 97, 98, 99]

    def test_filters_combine_with_predicate(self, synthetic_dataset):
        with make_reader(synthetic_dataset.url, filters=[('id', '<', 50)],
                         predicate=in_lambda(['id'],
                                             lambda v: v['id'] % 2 == 0),
                         schema_fields=['^id$']) as reader:
            ids = sorted(r.id for r in reader)
        assert ids == [i for i in range(50) if i % 2 == 0]

    def test_filters_excluding_everything_raise(self, synthetic_dataset):
        with pytest.raises(NoDataAvailableError):
            make_reader(synthetic_dataset.url, filters=[('id', '>', 10 ** 6)])

    def test_filters_with_local_disk_cache(self, partitioned_url, tmp_path):
        # unlike arbitrary predicates, DNF filters have stable identity and
        # may combine with the cache; different filters must not collide
        def read_ids(filters):
            with make_reader(partitioned_url, filters=filters,
                             schema_fields=['^id$'],
                             cache_type='local-disk',
                             cache_location=str(tmp_path / 'cache'),
                             cache_size_limit=10 ** 8,
                             shuffle_row_groups=False) as reader:
                return sorted(r.id for r in reader)

        first = read_ids([('id', '<', 10)])
        assert first == list(range(10))
        assert read_ids([('id', '<', 10)]) == first          # cache hit
        assert read_ids([('id', '<', 5)]) == list(range(5))  # distinct key

    def test_incomparable_partition_filter_is_conservative(self,
                                                           partitioned_url):
        # string partition vs int bound: pruning keeps everything rather
        # than crashing; the worker's exact evaluation then decides
        with pytest.raises(TypeError):
            # the row-level comparison itself is a genuine type error and
            # surfaces from the worker, not from Reader construction
            with make_reader(partitioned_url,
                             filters=[('partition_key', '<', 5)]) as reader:
                list(reader)

    def test_cache_keys_by_column_set(self, partitioned_url, tmp_path):
        # a cache dir shared by readers with different projections must not
        # serve truncated batches across them
        kwargs = dict(cache_type='local-disk',
                      cache_location=str(tmp_path / 'cache'),
                      cache_size_limit=10 ** 8, shuffle_row_groups=False)
        with make_reader(partitioned_url, schema_fields=['^id$'],
                         **kwargs) as reader:
            assert len(list(reader)) == 100
        with make_reader(partitioned_url, **kwargs) as reader:
            row = next(reader)
        assert row.image_png is not None and row.matrix is not None

    def test_selector_blame_not_filters(self, synthetic_dataset):
        # an empty read caused by the selector must not be blamed on filters
        from petastorm_tpu.selectors import SingleIndexSelector
        with pytest.raises(NoDataAvailableError,
                           match='shard/predicate/selector'):
            make_reader(synthetic_dataset.url, filters=[('id', '>=', 0)],
                        rowgroup_selector=SingleIndexSelector(
                            'id_index', ['no_such_value']))

    def test_in_filter(self, partitioned_url):
        with make_reader(partitioned_url,
                         filters=[('partition_key', 'in', ('p_0', 'p_4'))],
                         schema_fields=['^id$', '^partition_key$'],
                         shuffle_row_groups=False) as reader:
            keys = {r.partition_key for r in reader}
        assert keys == {'p_0', 'p_4'}
