"""Actionable errors for variable-shape fields in the torch/tf bridges."""
import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.codecs import NdarrayCodec, ScalarCodec
from petastorm_tpu.etl.dataset_metadata import write_dataset
from petastorm_tpu.reader import make_batch_reader, make_reader
from petastorm_tpu.unischema import Unischema, UnischemaField


@pytest.fixture(scope='module')
def ragged_url(tmp_path_factory):
    url = 'file://' + str(tmp_path_factory.mktemp('ragged_bridge')) + '/ds'
    schema = Unischema('S', [
        UnischemaField('id', np.int32, (), ScalarCodec(pa.int32()), False),
        UnischemaField('tokens', np.int32, (None,), NdarrayCodec(), False),
    ])
    rng = np.random.RandomState(0)
    write_dataset(url, schema,
                  [{'id': i,
                    'tokens': rng.randint(0, 9, (2 + i % 4,), np.int32)}
                   for i in range(20)], rowgroup_size_rows=5)
    return url


def test_torch_batched_names_ragged_field(ragged_url):
    from petastorm_tpu.pytorch import BatchedDataLoader
    with make_batch_reader(ragged_url) as reader:
        loader = BatchedDataLoader(reader, batch_size=4)
        with pytest.raises(TypeError, match='variable shape.*pad_ragged'):
            next(iter(loader))


def test_torch_row_loader_names_ragged_field(ragged_url):
    from petastorm_tpu.pytorch import DataLoader
    with make_reader(ragged_url) as reader:
        loader = DataLoader(reader, batch_size=4)
        with pytest.raises(TypeError, match="'tokens'.*variable shape"):
            next(iter(loader))


def test_tf_dataset_names_ragged_field(ragged_url):
    pytest.importorskip('tensorflow')
    from petastorm_tpu.tf_utils import make_petastorm_dataset
    with make_batch_reader(ragged_url) as reader:
        dataset = make_petastorm_dataset(reader)
        with pytest.raises(Exception, match='variable shape'):
            next(iter(dataset))


def test_jax_stage_diagnoses_string_field(tmp_path):
    # fixed-width numpy strings are not object dtype but cannot stage;
    # the loader must give the classified diagnosis, not jax's raw error
    from petastorm_tpu.jax import make_jax_loader
    url = 'file://' + str(tmp_path / 'str_ds')
    schema = Unischema('S', [
        UnischemaField('id', np.int64, (), ScalarCodec(pa.int64()), False),
        UnischemaField('name', np.str_, (), ScalarCodec(pa.string()),
                       False),
    ])
    write_dataset(url, schema,
                  [{'id': i, 'name': 'n%d' % i} for i in range(16)],
                  rowgroup_size_rows=8)
    with make_jax_loader(url, batch_size=4) as loader:
        with pytest.raises(TypeError, match='string/decimal'):
            next(iter(loader))
